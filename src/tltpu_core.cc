// tltpu native core: layout algebra + mesh collective schedule synthesis.
//
// Native-equivalent of the reference's C++ compiler-core pieces that remain
// semantic on TPU (cf. /root/reference/src/layout/layout.cc — affine
// Layout/Fragment algebra; /root/reference/src/op/comm.cc — collectives
// synthesized into primitive NoC broadcast steps). Exposed through a plain
// C ABI consumed via ctypes (tilelang_mesh_tpu/layout/native.py), with a
// pure-Python fallback kept in lockstep by parity tests
// (tests/test_native.py).
//
// Build: make -C src  ->  src/libtltpu.so

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Affine layout algebra.
//
// A layout is an affine map from an n-d logical index to a linear offset:
//   offset(i) = sum_d strides[d] * i[d]
// ---------------------------------------------------------------------------

// offset for a single index. Returns -1 on rank mismatch.
int64_t tl_layout_offset(const int64_t* strides, const int64_t* index,
                         int32_t rank) {
  int64_t off = 0;
  for (int32_t d = 0; d < rank; ++d) off += strides[d] * index[d];
  return off;
}

// Row-major strides for a shape.
void tl_layout_row_major(const int64_t* shape, int32_t rank,
                         int64_t* strides_out) {
  int64_t s = 1;
  for (int32_t d = rank - 1; d >= 0; --d) {
    strides_out[d] = s;
    s *= shape[d];
  }
}

// Compose: C = A ∘ B, where B maps an index to an offset in A's *logical*
// row-major space. Both must have matching total sizes for a permutation /
// reshape composition. Concretely: given layout A over shape_a and a
// "view" B described by (shape_b, strides_b into A-logical-space), produce
// strides_c so that offset_C(i) = offset_A(unflatten_a(offset_B(i))).
// Works for permutation-style views where each B stride lands on an exact
// A-logical coordinate.
int32_t tl_layout_compose(const int64_t* shape_a, const int64_t* strides_a,
                          int32_t rank_a, const int64_t* strides_b,
                          int32_t rank_b, int64_t* strides_out) {
  // A-logical row-major strides
  std::vector<int64_t> rm(rank_a);
  tl_layout_row_major(shape_a, rank_a, rm.data());
  for (int32_t d = 0; d < rank_b; ++d) {
    // decompose b-stride into A logical coords, then re-linearize with
    // strides_a
    int64_t rem = strides_b[d];
    int64_t out = 0;
    for (int32_t ad = 0; ad < rank_a; ++ad) {
      int64_t c = rem / rm[ad];
      rem -= c * rm[ad];
      out += c * strides_a[ad];
    }
    if (rem != 0) return -1;  // not decomposable
    strides_out[d] = out;
  }
  return 0;
}

// Inverse of a compact permutation layout: the offset space factors as a
// mixed radix over the dims sorted by descending stride; the inverse maps
// that factorization back to the logical row-major flat index. The layout
// is invertible iff sorting dims by stride yields a compact mixed radix
// (each stride equals the product of all smaller-stride dim sizes).
// shape_out = sizes in stride-descending order; strides_out[d] = row-major
// stride of the corresponding original dim. Returns 0 ok, -1 otherwise.
int32_t tl_layout_inverse(const int64_t* shape, const int64_t* strides,
                          int32_t rank, int64_t* shape_out,
                          int64_t* strides_out) {
  std::vector<int32_t> order(rank);
  for (int32_t d = 0; d < rank; ++d) order[d] = d;
  for (int32_t i = 0; i < rank; ++i)  // stable sort desc by stride
    for (int32_t j = i + 1; j < rank; ++j)
      if (strides[order[j]] > strides[order[i]]) {
        int32_t t = order[i];
        order[i] = order[j];
        order[j] = t;
      }
  int64_t expected = 1;
  for (int32_t k = rank - 1; k >= 0; --k) {
    int32_t d = order[k];
    if (strides[d] != expected) return -1;
    expected *= shape[d];
  }
  std::vector<int64_t> rm(rank);
  tl_layout_row_major(shape, rank, rm.data());
  for (int32_t k = 0; k < rank; ++k) {
    shape_out[k] = shape[order[k]];
    strides_out[k] = rm[order[k]];
  }
  return 0;
}

// ---------------------------------------------------------------------------
// TPU (sublane, lane) tiling math — the packing rules Mosaic applies to
// VMEM tiles; used by the carver/analyzer for true footprint estimates.
// ---------------------------------------------------------------------------

static int64_t cdiv(int64_t a, int64_t b) { return (a + b - 1) / b; }

// Padded VMEM bytes for a logical (rows, cols) tile of dtype_bits.
int64_t tl_vmem_bytes(int64_t rows, int64_t cols, int32_t dtype_bits) {
  int64_t sublane = 8;
  if (dtype_bits == 16) sublane = 16;
  if (dtype_bits == 8) sublane = 32;
  int64_t lane = 128;
  int64_t padded_rows = cdiv(rows, sublane) * sublane;
  int64_t padded_cols = cdiv(cols, lane) * lane;
  return padded_rows * padded_cols * dtype_bits / 8;
}

// ---------------------------------------------------------------------------
// Collective schedule synthesis.
//
// Mirrors the algorithm structure of the reference's AllgatherOp /
// AllreduceOp lowering (comm.cc:479-918): everything decomposes into
// primitive directed broadcasts {src_core, direction, dst_offset_chunks}.
// On TPU these steps become remote-DMA rounds (or document the XLA
// collective the SPMD lowering emits); they also drive hop-count cost
// modeling.
//
// A step is 4 ints: {src_row, src_col, direction(0=h,1=v), dst_chunk}.
// ---------------------------------------------------------------------------

#define DIR_H 0
#define DIR_V 1
#define DIR_ALL 2

// Broadcast from (sr, sc) along direction. 2-D ("all") = one vertical
// broadcast down the source column, then each row's holder broadcasts
// horizontally (cf. comm.cc:196-216). Returns #steps.
int32_t tl_broadcast_schedule(int32_t rows, int32_t cols, int32_t sr,
                              int32_t sc, int32_t dir, int32_t* steps_out) {
  int32_t n = 0;
  auto emit = [&](int32_t r, int32_t c, int32_t d, int32_t chunk) {
    steps_out[n * 4 + 0] = r;
    steps_out[n * 4 + 1] = c;
    steps_out[n * 4 + 2] = d;
    steps_out[n * 4 + 3] = chunk;
    ++n;
  };
  if (dir == DIR_H) {
    if (cols > 1) emit(sr, sc, DIR_H, 0);
  } else if (dir == DIR_V) {
    if (rows > 1) emit(sr, sc, DIR_V, 0);
  } else {
    if (rows > 1) emit(sr, sc, DIR_V, 0);
    for (int32_t r = 0; r < rows; ++r)
      if (cols > 1) emit(r, sc, DIR_H, 0);
  }
  return n;
}

// All-gather along direction: every participant broadcasts its chunk to its
// peers; receiver writes it at the sender's rank offset
// (cf. comm.cc:479-596: "all" = horizontal phase then vertical phase of
// row-bundles). Returns #steps.
int32_t tl_allgather_schedule(int32_t rows, int32_t cols, int32_t dir,
                              int32_t* steps_out) {
  int32_t n = 0;
  auto emit = [&](int32_t r, int32_t c, int32_t d, int32_t chunk) {
    steps_out[n * 4 + 0] = r;
    steps_out[n * 4 + 1] = c;
    steps_out[n * 4 + 2] = d;
    steps_out[n * 4 + 3] = chunk;
    ++n;
  };
  if (dir == DIR_H) {
    for (int32_t r = 0; r < rows; ++r)
      for (int32_t c = 0; c < cols; ++c) emit(r, c, DIR_H, c);
  } else if (dir == DIR_V) {
    for (int32_t c = 0; c < cols; ++c)
      for (int32_t r = 0; r < rows; ++r) emit(r, c, DIR_V, r);
  } else {
    // phase 1: gather within rows (each core ends with its row bundle)
    for (int32_t r = 0; r < rows; ++r)
      for (int32_t c = 0; c < cols; ++c) emit(r, c, DIR_H, c);
    // phase 2: gather row bundles down columns
    for (int32_t c = 0; c < cols; ++c)
      for (int32_t r = 0; r < rows; ++r) emit(r, c, DIR_V, r);
  }
  return n;
}

// All-reduce = local reduce + row allgather + reduce + col allgather +
// reduce (cf. comm.cc:783-918). Emits the gather steps; reduction points
// are implicit after each phase. Returns #steps.
int32_t tl_allreduce_schedule(int32_t rows, int32_t cols, int32_t dir,
                              int32_t* steps_out) {
  if (dir == DIR_H) return tl_allgather_schedule(rows, cols, DIR_H,
                                                 steps_out);
  if (dir == DIR_V) return tl_allgather_schedule(rows, cols, DIR_V,
                                                 steps_out);
  int32_t n = tl_allgather_schedule(rows, cols, DIR_H, steps_out);
  n += tl_allgather_schedule(rows, cols, DIR_V, steps_out + n * 4);
  return n;
}

// Hop-count cost of a schedule on a 2-D torus-less mesh: a horizontal
// broadcast from column c reaches max(c, cols-1-c) hops, etc. Used by the
// analyzer's comm cost model.
int64_t tl_schedule_hops(const int32_t* steps, int32_t n_steps, int32_t rows,
                         int32_t cols) {
  int64_t hops = 0;
  for (int32_t i = 0; i < n_steps; ++i) {
    int32_t r = steps[i * 4], c = steps[i * 4 + 1], d = steps[i * 4 + 2];
    if (d == DIR_H) {
      int32_t right = cols - 1 - c;
      hops += (c > right ? c : right);
    } else {
      int32_t down = rows - 1 - r;
      hops += (r > down ? r : down);
    }
  }
  return hops;
}

// ---------------------------------------------------------------------------
// Blockwise zig-zag ("ZZ") hierarchical layout, the mesh layout the
// reference builds in hierarchical_layout.cc (make_blockwise_zz_layout):
// blocks are laid out in row-major over the mesh but odd rows traverse
// columns in reverse, keeping neighboring blocks on neighboring cores.
// Returns for each (block_row, block_col) the owning linear core id.
// ---------------------------------------------------------------------------
void tl_blockwise_zz_owners(int32_t rows, int32_t cols,
                            int32_t* owners_out) {
  for (int32_t r = 0; r < rows; ++r) {
    for (int32_t c = 0; c < cols; ++c) {
      int32_t cc = (r % 2 == 0) ? c : (cols - 1 - c);
      owners_out[r * cols + c] = r * cols + cc;
    }
  }
}

int32_t tl_native_abi_version() { return 3; }

}  // extern "C"

extern "C" {

// ---------------------------------------------------------------------------
// Liveness-based VMEM packing (native allocator).
//
// Native-equivalent of the reference's storage reuse passes
// (/root/reference/src/transform/storage_rewrite.cc and
// merge_shared_memory_allocations.cc — liveness-interval analysis +
// best-fit packing of shared-memory buffers). Here the scarce arena is
// VMEM: buffers whose [first_use, last_use] statement intervals are
// disjoint may share offsets.
//
// Inputs: per-buffer byte sizes and statement-index live ranges.
// Output: byte offset per buffer; returns the packed arena size in bytes,
// or -1 on bad input. Greedy by (size desc, first_use) with lowest-fit
// placement — the same strategy class the reference uses.
// ---------------------------------------------------------------------------

int64_t tl_vmem_pack(const int64_t* sizes, const int32_t* first_use,
                     const int32_t* last_use, int32_t n, int64_t align,
                     int64_t* offsets_out) {
  if (n < 0 || align <= 0) return -1;
  std::vector<int32_t> order(n);
  for (int32_t i = 0; i < n; ++i) order[i] = i;
  // big buffers first, ties broken by earlier birth
  for (int32_t i = 1; i < n; ++i)
    for (int32_t j = i; j > 0; --j) {
      bool swap = sizes[order[j]] > sizes[order[j - 1]] ||
                  (sizes[order[j]] == sizes[order[j - 1]] &&
                   first_use[order[j]] < first_use[order[j - 1]]);
      if (swap) { int32_t t = order[j]; order[j] = order[j - 1];
                  order[j - 1] = t; } else break;
    }
  std::vector<int64_t> placed_off;
  std::vector<int64_t> placed_end;
  std::vector<int32_t> placed_id;
  int64_t arena = 0;
  for (int32_t oi = 0; oi < n; ++oi) {
    int32_t b = order[oi];
    if (sizes[b] < 0 || last_use[b] < first_use[b]) return -1;
    int64_t sz = ((sizes[b] + align - 1) / align) * align;
    // candidate offsets: 0 and the end of every live-overlapping buffer
    int64_t best = -1;
    for (int64_t cand_i = -1; cand_i < (int64_t)placed_id.size(); ++cand_i) {
      int64_t cand = cand_i < 0 ? 0 : placed_end[cand_i];
      bool ok = true;
      for (size_t p = 0; p < placed_id.size(); ++p) {
        int32_t q = placed_id[p];
        bool live_overlap = !(last_use[q] < first_use[b] ||
                              last_use[b] < first_use[q]);
        bool addr_overlap = cand < placed_end[p] &&
                            placed_off[p] < cand + sz;
        if (live_overlap && addr_overlap) { ok = false; break; }
      }
      if (ok && (best < 0 || cand < best)) best = cand;
    }
    offsets_out[b] = best;
    placed_off.push_back(best);
    placed_end.push_back(best + sz);
    placed_id.push_back(b);
    if (best + sz > arena) arena = best + sz;
  }
  return arena;
}

// ---------------------------------------------------------------------------
// Affine linearization over an encoded expression tree (native
// graph-builder piece; mirror of tilelang_mesh_tpu/ir/expr.py linearize —
// itself the workhorse the reference buries in layout_inference.cc /
// arith analysis). The Python side encodes the tree bottom-up:
//   op[i]: 0=CONST (a[i]=value), 1=VAR (a[i]=var slot),
//          2=ADD, 3=SUB, 4=MUL, 5=FLOORDIV  (a[i], b[i] = child nodes)
// Children must precede parents. Result: coeffs per var slot + constant.
// Returns 1 on success, 0 when the tree is not affine over the slots.
// ---------------------------------------------------------------------------

int32_t tl_affine_linearize(const int32_t* op, const int64_t* a,
                            const int64_t* b, int32_t n_nodes,
                            int32_t n_vars, int64_t* coeffs_out,
                            int64_t* const_out) {
  if (n_nodes <= 0 || n_vars < 0) return 0;
  std::vector<std::vector<int64_t>> C(n_nodes,
                                      std::vector<int64_t>(n_vars, 0));
  std::vector<int64_t> K(n_nodes, 0);
  std::vector<char> ok(n_nodes, 0);
  for (int32_t i = 0; i < n_nodes; ++i) {
    switch (op[i]) {
      case 0: K[i] = a[i]; ok[i] = 1; break;
      case 1:
        if (a[i] < 0 || a[i] >= n_vars) return 0;
        C[i][a[i]] = 1; ok[i] = 1; break;
      case 2: case 3: {
        int64_t x = a[i], y = b[i];
        if (x < 0 || x >= i || y < 0 || y >= i || !ok[x] || !ok[y]) return 0;
        int64_t s = op[i] == 2 ? 1 : -1;
        for (int32_t v = 0; v < n_vars; ++v) C[i][v] = C[x][v] + s * C[y][v];
        K[i] = K[x] + s * K[y]; ok[i] = 1; break;
      }
      case 4: {
        int64_t x = a[i], y = b[i];
        if (x < 0 || x >= i || y < 0 || y >= i || !ok[x] || !ok[y]) return 0;
        bool xc = true, yc = true;
        for (int32_t v = 0; v < n_vars; ++v) {
          if (C[x][v]) xc = false;
          if (C[y][v]) yc = false;
        }
        if (!xc && !yc) return 0;  // non-linear
        if (xc) { int64_t t = x; x = y; y = t; }
        for (int32_t v = 0; v < n_vars; ++v) C[i][v] = C[x][v] * K[y];
        K[i] = K[x] * K[y]; ok[i] = 1; break;
      }
      case 5: {
        int64_t x = a[i], y = b[i];
        if (x < 0 || x >= i || y < 0 || y >= i || !ok[x] || !ok[y]) return 0;
        for (int32_t v = 0; v < n_vars; ++v) if (C[y][v]) return 0;
        int64_t d = K[y];
        if (d == 0) return 0;
        for (int32_t v = 0; v < n_vars; ++v)
          if (C[x][v] % d != 0) return 0;
        if (K[x] % d != 0) return 0;
        for (int32_t v = 0; v < n_vars; ++v) C[i][v] = C[x][v] / d;
        K[i] = K[x] / d; ok[i] = 1; break;
      }
      default: return 0;
    }
  }
  for (int32_t v = 0; v < n_vars; ++v) coeffs_out[v] = C[n_nodes - 1][v];
  *const_out = K[n_nodes - 1];
  return 1;
}

// ---------------------------------------------------------------------------
// Stream-K work partitioner (native scheduler piece; mirror of
// ops/gemm_variants._streamk_segments — the reference's stream-K example
// schedules, examples/gemm_streamk). Splits the flat (tile, k-chunk)
// iteration space evenly over programs, breaking each program's range at
// tile boundaries. Outputs parallel arrays (tile, k0, k_len); returns the
// segment count (call with outputs null to size), or -1 on bad input.
// ---------------------------------------------------------------------------

int32_t tl_streamk_partition(int32_t n_tiles, int32_t k_iters,
                             int32_t n_programs, int32_t* tile_out,
                             int32_t* k0_out, int32_t* klen_out) {
  if (n_tiles <= 0 || k_iters <= 0 || n_programs <= 0) return -1;
  int64_t total = (int64_t)n_tiles * k_iters;
  int64_t per = (total + n_programs - 1) / n_programs;
  int32_t n = 0;
  for (int32_t p = 0; p < n_programs; ++p) {
    int64_t s = (int64_t)p * per;
    int64_t e = s + per < total ? s + per : total;
    while (s < e) {
      int64_t tile = s / k_iters;
      int64_t k0 = s % k_iters;
      int64_t klen = k_iters - k0 < e - s ? k_iters - k0 : e - s;
      if (tile_out) {
        tile_out[n] = (int32_t)tile;
        k0_out[n] = (int32_t)k0;
        klen_out[n] = (int32_t)klen;
      }
      ++n;
      s += klen;
    }
  }
  return n;
}

// ---------------------------------------------------------------------------
// Expression grid evaluation (native pass engine piece; extends the
// tl_affine_linearize node-program format with the non-affine ops the
// planner's modular index maps use). Evaluates a node program at EVERY
// point of an n-d grid in row-major order (last axis fastest — the Pallas
// grid iteration order) — the hot loop of the output-revisit legality
// check (transform/plan.py::_expr_map_revisit_check), which enumerates up
// to 2^16 grid points per output param.
//
// opcodes: 0=const(a) 1=var(slot a, a grid axis) 2=add 3=sub 4=mul
//          5=floordiv 6=floormod 7=min 8=max  (a/b = operand node ids)
// Division follows python floor semantics (negative intermediates, e.g.
// bx - by, round toward -inf). Returns 1 ok, 0 on bad program / div0.
// ---------------------------------------------------------------------------

static inline int64_t tl_floordiv_(int64_t x, int64_t y) {
  int64_t q = x / y;
  if ((x % y != 0) && ((x < 0) != (y < 0))) --q;
  return q;
}

int32_t tl_expr_eval_grid(const int32_t* op, const int64_t* a,
                          const int64_t* b, int32_t n_nodes,
                          const int64_t* extents, int32_t n_axes,
                          int64_t* out) {
  if (n_nodes <= 0 || n_axes <= 0) return 0;
  // validate program shape once
  for (int32_t i = 0; i < n_nodes; ++i) {
    if (op[i] == 0) continue;
    if (op[i] == 1) {
      if (a[i] < 0 || a[i] >= n_axes) return 0;
      continue;
    }
    if (op[i] < 2 || op[i] > 8) return 0;
    if (a[i] < 0 || a[i] >= i || b[i] < 0 || b[i] >= i) return 0;
  }
  int64_t total = 1;
  for (int32_t d = 0; d < n_axes; ++d) {
    if (extents[d] <= 0) return 0;
    total *= extents[d];
  }
  std::vector<int64_t> point(n_axes, 0);
  std::vector<int64_t> val(n_nodes);
  for (int64_t step = 0; step < total; ++step) {
    for (int32_t i = 0; i < n_nodes; ++i) {
      switch (op[i]) {
        case 0: val[i] = a[i]; break;
        case 1: val[i] = point[a[i]]; break;
        case 2:
          if (__builtin_add_overflow(val[a[i]], val[b[i]], &val[i]))
            return 0;
          break;
        case 3:
          if (__builtin_sub_overflow(val[a[i]], val[b[i]], &val[i]))
            return 0;
          break;
        case 4:
          if (__builtin_mul_overflow(val[a[i]], val[b[i]], &val[i]))
            return 0;
          break;
        case 5:
          if (val[b[i]] == 0) return 0;
          if (val[a[i]] == INT64_MIN && val[b[i]] == -1) return 0;
          val[i] = tl_floordiv_(val[a[i]], val[b[i]]);
          break;
        case 6:
          if (val[b[i]] == 0) return 0;
          if (val[a[i]] == INT64_MIN && val[b[i]] == -1) {
            val[i] = 0;  // mod is representable; only the quotient overflows
            break;
          }
          val[i] = val[a[i]] - tl_floordiv_(val[a[i]], val[b[i]]) * val[b[i]];
          break;
        case 7: val[i] = val[a[i]] < val[b[i]] ? val[a[i]] : val[b[i]]; break;
        case 8: val[i] = val[a[i]] > val[b[i]] ? val[a[i]] : val[b[i]]; break;
      }
    }
    out[step] = val[n_nodes - 1];
    // advance row-major point, last axis fastest
    for (int32_t d = n_axes - 1; d >= 0; --d) {
      if (++point[d] < extents[d]) break;
      point[d] = 0;
    }
  }
  return 1;
}

}  // extern "C" (second block)
