"""IR-derived autotune candidates — the PrimFuncNode analog.

Reference: /root/reference/tilelang/carver/roller/node.py:191 (PrimFuncNode
extracts the tunable structure from the kernel's TIR) and
policy/default.py:19 (the policy then emits the candidate space). Here the
traced tile IR is walked directly: the kernel's grid, GEMM tile shapes,
enclosing reduction loops, softmax markers, and output block maps identify
the kernel class and reconstruct the PROBLEM dimensions from the grid/loop
extents times the traced tile sizes — so ``autotune()`` with neither
``configs=`` nor ``template=`` can derive and rank a tuning space for any
kernel the classifier recognizes (GEMM, flash-attention, GEMV,
reduction, elementwise), without a hand-written template.

The factory is traced once at its DEFAULT tile parameters; the derived
template's config keys (block_M/block_N/block_K) are then matched to the
factory's tunable keyword names.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..ir import (Buffer, CopyStmt, CumSumStmt, FillStmt, ForNest, GemmStmt,
                  IfThenElse, KernelNode, PrimFunc, ReduceStmt, Region,
                  SeqStmt, Stmt, as_int)
from ..ir.expr import BinOp, BufferLoad, Call, Cast, Var, affine_decompose
from .arch import TPUArch, auto_arch


def _shape_of(x) -> Optional[Tuple[int, ...]]:
    """Static shape of a Region/Buffer operand, None if dynamic."""
    if isinstance(x, Region):
        return x.static_shape()
    if isinstance(x, Buffer):
        out = []
        for s in x.shape:
            v = as_int(s)
            if v is None:
                return None
            out.append(v)
        return tuple(out)
    return None


def _expr_vars(e, acc: set):
    if isinstance(e, Var):
        acc.add(id(e))
    elif isinstance(e, BinOp):
        _expr_vars(e.a, acc)
        _expr_vars(e.b, acc)
    elif isinstance(e, Call):
        for a in e.args:
            if not isinstance(a, str):
                _expr_vars(a, acc)
    elif isinstance(e, Cast):
        _expr_vars(e.value, acc)
    elif isinstance(e, BufferLoad):
        for i in e.indices:
            if not isinstance(i, slice):
                _expr_vars(i, acc)


def _has_exp_call(e) -> bool:
    if isinstance(e, Call):
        if e.name in ("exp", "exp2", "expf", "exp2f"):
            return True
        return any(not isinstance(a, str) and _has_exp_call(a)
                   for a in e.args)
    if isinstance(e, BinOp):
        return _has_exp_call(e.a) or _has_exp_call(e.b)
    if isinstance(e, Cast):
        return _has_exp_call(e.value)
    return False


@dataclass
class _GemmSite:
    stmt: GemmStmt
    loops: List[Tuple[Any, int, str]]   # (var, extent, kind) enclosing


@dataclass
class KernelStructure:
    """What the walk extracts (the PrimFuncNode payload)."""
    grid: List[Tuple[Any, int]] = field(default_factory=list)
    gemms: List[_GemmSite] = field(default_factory=list)
    copies: List[Tuple[CopyStmt, tuple]] = field(default_factory=list)
    has_exp: bool = False
    n_reduce: int = 0
    causal: bool = False
    global_params: List[Buffer] = field(default_factory=list)

    @property
    def grid_ids(self) -> set:
        return {id(v) for v, _ in self.grid}


def analyze_prim_func(pf) -> KernelStructure:
    """Walk a traced kernel and extract its tunable structure."""
    func: PrimFunc = getattr(pf, "func", pf)
    st = KernelStructure()
    st.global_params = [b for b in func.buffer_params
                       if b.scope == "global"]
    kn = func.kernel_node()
    if kn is None:
        return st
    st.grid = [(v, int(e)) for v, e in zip(kn.grid_vars, kn.extents)]
    kv_loop_ids: set = set()

    def scan(stmts, loops):
        for s in stmts:
            if isinstance(s, SeqStmt):
                scan(s.stmts, loops)
            elif isinstance(s, ForNest):
                exts = [as_int(e) for e in s.extents]
                if s.kind in ("serial", "pipelined") and \
                        all(e is not None for e in exts):
                    inner = loops + [
                        (v, e, s.kind)
                        for v, e in zip(s.loop_vars, exts)]
                    for v in s.loop_vars:
                        kv_loop_ids.add(id(v))
                    scan(s.body.stmts, inner)
                else:
                    scan(s.body.stmts, loops)
            elif isinstance(s, IfThenElse):
                cond_vars: set = set()
                _expr_vars(s.cond, cond_vars)
                if cond_vars & kv_loop_ids and cond_vars & st.grid_ids:
                    # a guard comparing the reduction-loop position to
                    # the grid position: the causal-skip idiom. Known
                    # imprecision: a sliding-window guard matches too —
                    # acceptable, causal only halves the modeled FLOPs
                    # in the RANKING (never affects correctness)
                    st.causal = True
                scan(s.then_body.stmts, loops)
                if s.else_body is not None:
                    scan(s.else_body.stmts, loops)
            elif isinstance(s, GemmStmt):
                st.gemms.append(_GemmSite(s, list(loops)))
            elif isinstance(s, CopyStmt):
                st.copies.append((s, tuple(loops)))
            elif isinstance(s, (ReduceStmt, CumSumStmt)):
                st.n_reduce += 1
            elif isinstance(s, (FillStmt,)):
                if _has_exp_call(s.value):
                    st.has_exp = True
            else:
                v = getattr(s, "value", None)
                if v is not None and not isinstance(v, (Region, Stmt, str)) \
                        and _has_exp_call(v):
                    st.has_exp = True

    scan(kn.body.stmts, [])
    return st


def _out_problem_dim(st: KernelStructure, src_uid: int, tile: int,
                     minor: bool = False) -> int:
    """Problem size along the output dim whose window is `tile` wide:
    find the copy src_uid -> global, decompose that dim's base over the
    grid vars (coeff * grid extent), else the tile itself. ``minor``
    searches dims minor-first so square tiles (bm == bn) still map the
    M and N questions to distinct output dims."""
    for cp, _loops in st.copies:
        src, dst = cp.src, cp.dst
        if not isinstance(src, Region) or not isinstance(dst, Region):
            continue
        if src.buffer.uid != src_uid or dst.buffer.scope != "global":
            continue
        shape = dst.static_shape()
        if shape is None:
            continue
        ext_of = {id(v): e for v, e in st.grid}
        dims = range(len(shape) - 1, -1, -1) if minor else \
            range(len(shape))
        for dim in dims:
            if shape[dim] != tile:
                continue
            b = dst.base[dim]
            if isinstance(b, slice):
                continue
            dec = affine_decompose(b)
            if not dec:
                continue
            coeffs, _const = dec
            for _, (v, c) in coeffs.items():
                if id(v) in ext_of and c == tile:
                    return ext_of[id(v)] * tile
        # this copy didn't resolve the dim — keep scanning the others
        # (e.g. a guarded split epilogue writes through two copies)
    return tile


def _operand_uid(x) -> Optional[int]:
    buf = getattr(x, "buffer", x)
    return getattr(buf, "uid", None)


def _feed_vars(st: KernelStructure, operands) -> set:
    """ids of vars appearing in the global-side window bases that FEED
    the given gemm operands: src bases of global->operand copies, plus
    the operand's own base when it windows a global buffer directly."""
    uids = {_operand_uid(x) for x in operands}
    out: set = set()
    for cp, _loops in st.copies:
        src, dst = cp.src, cp.dst
        if not isinstance(src, Region) or not isinstance(dst, Region):
            continue
        if dst.buffer.uid in uids and src.buffer.scope == "global":
            for b in src.base:
                if not isinstance(b, slice):
                    _expr_vars(b, out)
    for x in operands:
        if isinstance(x, Region) and x.buffer.scope == "global":
            for b in x.base:
                if not isinstance(b, slice):
                    _expr_vars(b, out)
    return out


def _reduction_extent(site: _GemmSite, feed: set) -> int:
    """Product of enclosing loop extents that actually step the gemm's
    input windows. A loop whose var appears in no A/B window base is NOT
    a reduction axis (e.g. an outer multi-step accumulation loop), so
    operands fully staged outside every loop give extent 1."""
    red = 1
    for v, e, _k in site.loops:
        if id(v) in feed:
            red *= e
    return red


def derive_template(pf, arch: Optional[TPUArch] = None):
    """Classify a traced kernel and build the matching carver template
    with problem dims reconstructed from its IR. Raises ValueError when
    the kernel shape is not recognized."""
    from .roller import (ElementwiseTemplate, FlashAttentionTemplate,
                         GEMVTemplate, GeneralReductionTemplate,
                         MatmulTemplate)
    arch = arch or auto_arch()
    st = analyze_prim_func(pf)

    if st.gemms and st.has_exp and len(st.gemms) >= 2:
        # blockwise attention: gemm1 = scores (Q @ K^T), gemm2 = P @ V
        g1 = st.gemms[0].stmt
        a_sh, c_sh = _shape_of(g1.A), _shape_of(g1.C)
        if a_sh is None or c_sh is None:
            raise ValueError("attention operands have dynamic shapes")
        bm, bn = c_sh[-2], c_sh[-1]
        D = a_sh[-1]
        Sq = _out_problem_dim(st, st.gemms[-1].stmt.C.buffer.uid, bm)
        feed = _feed_vars(st, [g1.B])
        Sk = bn * _reduction_extent(st.gemms[0], feed)
        q_grid_used = max(1, Sq // bm)
        bh = 1
        for _v, e in st.grid:
            bh *= e
        bh = max(1, bh // q_grid_used)
        dtype = (st.global_params[0].dtype if st.global_params
                 else "float32")
        return FlashAttentionTemplate(
            seq_q=Sq, seq_k=Sk, head_dim=D, dtype=dtype,
            batch_heads=bh, causal=st.causal, arch=arch)

    if st.gemms:
        g = st.gemms[0].stmt
        a_sh, c_sh = _shape_of(g.A), _shape_of(g.C)
        if a_sh is None or c_sh is None:
            raise ValueError("gemm operands have dynamic shapes")
        bm, bn = c_sh[-2], c_sh[-1]
        bk = a_sh[-1] if a_sh[-2] == bm else a_sh[-2]
        M = _out_problem_dim(st, g.C.buffer.uid, bm)
        N = _out_problem_dim(st, g.C.buffer.uid, bn, minor=True)
        feed = _feed_vars(st, [g.A, g.B])
        K = bk * _reduction_extent(st.gemms[0], feed)
        dtype = (st.global_params[0].dtype if st.global_params
                 else "float32")
        if bm == 1 or M == 1:
            return GEMVTemplate(M=max(M, N), K=K, in_dtype=dtype,
                                arch=arch)
        return MatmulTemplate(M=M, N=N, K=K, in_dtype=dtype, arch=arch)

    # no MXU work: reduction or elementwise over the largest global param
    shapes = [s for s in (_shape_of(b) for b in st.global_params)
              if s is not None]
    if not shapes:
        raise ValueError(
            "cannot derive an autotune space: kernel has no static-shaped "
            "global params (pass configs=[...] or template=)")
    import math
    big = max(shapes, key=lambda s: math.prod(s))
    dtype = st.global_params[0].dtype
    if st.n_reduce:
        return GeneralReductionTemplate(shape=big, dtype=dtype, arch=arch)
    return ElementwiseTemplate(shape=big, dtype=dtype, arch=arch)


def derive_configs(pf, tunable_names, topk: int = 10,
                   arch: Optional[TPUArch] = None) -> List[Dict[str, int]]:
    """Ranked configs for a traced kernel, filtered to the factory's
    tunable keyword names and deduplicated (reference flow: PrimFuncNode
    -> policy.emit_config -> tuner grid)."""
    t = derive_template(pf, arch)
    seen = set()
    out: List[Dict[str, int]] = []
    for h in t.hints(topk * 4):
        cfg = {k: v for k, v in h.config.items() if k in tunable_names}
        if not cfg:
            continue
        key = tuple(sorted(cfg.items()))
        if key in seen:
            continue
        seen.add(key)
        out.append(cfg)
        if len(out) >= topk:
            break
    return out
