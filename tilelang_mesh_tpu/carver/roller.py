"""Tile-config recommendation ("roller").

Reference: /root/reference/tilelang/carver/roller/ (policy/default.py:19
DefaultPolicy, policy/tensorcore.py TensorCorePolicy) + template/ (matmul,
conv, gemv, general_reduce, elementwise, flashattention). Re-founded on
TPU constraints: candidate tiles are multiples of the dtype's
(sublane, lane) packing, bounded by VMEM capacity, and ranked by a
ROOFLINE cost model (predicted total latency = per-tile
max(MXU, VPU, HBM) time x tile count + per-grid-step overhead) against
the arch model — the same role the reference's smem/warp cost policy
plays for CUDA, with the analyzer's roofline (tools/analyzer.py) as the
shared latency vocabulary.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..ir import dtype_bits
from .arch import TPUArch, auto_arch


@dataclass
class Hint:
    config: Dict[str, int]
    score: float          # higher = better (1 / predicted_ms)
    predicted_ms: float = 0.0

    def __repr__(self):
        return (f"Hint({self.config}, score={self.score:.3g}, "
                f"~{self.predicted_ms:.4f} ms)")


@dataclass
class Candidate:
    """One tiling choice, described in roofline vocabulary: total work,
    total HBM traffic, per-tile VMEM footprint, tile count, and the
    fraction of the MXU/VPU the tile shape keeps busy."""
    config: Dict[str, int]
    flops: float            # total useful FLOPs for the whole problem
    hbm_bytes: float        # total HBM traffic
    vpu_elems: float = 0.0  # total elementwise work (VPU) in elements
    vmem_bytes: int = 0     # per-tile VMEM footprint
    n_tiles: int = 1
    utilization: float = 1.0  # MXU shape utilization of one tile


# per-grid-step fixed overhead (dispatch + window bookkeeping); value in
# seconds — small, but it is what separates equal-roofline candidates and
# makes fewer/bigger tiles win, matching measurement. Public: the
# autotuner's cost model (autotuner/cost_model.py) prices configs with
# the SAME constants, so the carver's ranking and the tuner's pruning
# can never disagree about the roofline vocabulary.
TILE_OVERHEAD_S = 1e-6
VPU_ELEMS_PER_S = 0.5e12    # ~VPU elementwise throughput (f32 elems/s)
# legacy private spellings (pre-cost-model callers)
_TILE_OVERHEAD_S = TILE_OVERHEAD_S
_VPU_ELEMS_PER_S = VPU_ELEMS_PER_S


class DefaultPolicy:
    """Roofline-ranked tile policy (reference DefaultPolicy analog).

    Ranks a template's candidates by predicted latency:
      t = max(flops / (peak * util), hbm_bytes / bw, vpu / vpu_rate)
          + n_tiles * overhead
    discarding candidates whose per-tile VMEM exceeds the budget. The
    default budget models Mosaic's scoped-VMEM stack limit, measured on
    v5e at ~0.42x of the arch VMEM figure (a 12.6 MB GEMM tile and a
    7.2 MB flash tile both fault; 6.7 MB runs) — candidates above it
    compile-fail on real chips, so ranking them wastes sweep slots.
    Equal-roofline ties break toward squarer tiles, then a larger minor
    (streaming) dim — the order measurement prefers.
    """

    def __init__(self, arch: Optional[TPUArch] = None,
                 vmem_budget: float = 0.42):
        self.arch = arch or auto_arch()
        self.vmem_budget = vmem_budget

    def predicted_ms(self, c: Candidate) -> float:
        arch = self.arch
        peak = arch.bf16_tflops * 1e12
        t_mxu = c.flops / (peak * max(c.utilization, 1e-3))
        t_hbm = c.hbm_bytes / (arch.hbm_gbps * 1e9)
        t_vpu = c.vpu_elems / _VPU_ELEMS_PER_S
        return (max(t_mxu, t_hbm, t_vpu)
                + c.n_tiles * _TILE_OVERHEAD_S) * 1e3

    def rank(self, candidates: List[Candidate],
             topk: int = 10) -> List[Hint]:
        budget = self.vmem_budget * self.arch.vmem_bytes
        hints = []
        for c in candidates:
            if c.vmem_bytes > budget:
                continue
            ms = self.predicted_ms(c)
            hints.append(Hint(c.config, 1.0 / max(ms, 1e-9), ms))

        def key(h):
            dims = [v for k, v in h.config.items() if k.startswith("block")]
            return (round(h.predicted_ms, 7),
                    -min(dims) if dims else 0,
                    -dims[-1] if dims else 0)
        hints.sort(key=key)
        return hints[:topk]


def _tile_candidates(dim: int, minimum: int, cap: int = 1024) -> List[int]:
    out = []
    t = minimum
    while t <= min(dim, cap):
        if dim % t == 0:
            out.append(t)
        t *= 2
    return out or [min(dim, minimum)]


@dataclass
class MatmulTemplate:
    """GEMM M/N/K tiling (reference carver/template/matmul.py)."""
    M: int
    N: int
    K: int
    in_dtype: str = "bfloat16"
    accum_dtype: str = "float32"
    arch: Optional[TPUArch] = None

    def candidates(self) -> List[Candidate]:
        arch = self.arch or auto_arch()
        sub, lane = arch.min_tile(self.in_dtype)
        ib = dtype_bits(self.in_dtype) // 8
        ab = dtype_bits(self.accum_dtype) // 8
        out = []
        total_flops = 2.0 * self.M * self.N * self.K
        for bm in _tile_candidates(self.M, max(sub, 128), 1024):
            for bn in _tile_candidates(self.N, lane, 1024):
                for bk in _tile_candidates(self.K, max(sub, 128), 2048):
                    # A streams once per N-block, B once per M-block
                    n_m, n_n = self.M // bm, self.N // bn
                    hbm = (self.M * self.K * n_n * ib
                           + self.K * self.N * n_m * ib
                           + self.M * self.N * ab)
                    vmem = 2 * (bm * bk + bk * bn) * ib + bm * bn * ab
                    util = min(bm / arch.mxu_shape[0], 1.0) * \
                        min(bn / arch.mxu_shape[1], 1.0)
                    out.append(Candidate(
                        {"block_M": bm, "block_N": bn, "block_K": bk},
                        total_flops, hbm, 0.0, vmem,
                        n_m * n_n * (self.K // bk), util))
        return out

    def hints(self, topk: int = 10) -> List[Hint]:
        return DefaultPolicy(self.arch).rank(self.candidates(), topk)


@dataclass
class FlashAttentionTemplate:
    seq_q: int
    seq_k: int
    head_dim: int
    dtype: str = "bfloat16"
    batch_heads: int = 1
    causal: bool = False
    arch: Optional[TPUArch] = None

    # Mosaic's scoped-VMEM stack bounds one kernel instance well below
    # the chip's VMEM: the softmax pipeline materializes several f32
    # score-shaped temporaries (logits/exp/p + relayouts), modeled as
    # 6x bm*bn*4, and the measured fault boundary on v5e sits near
    # 0.42x of chip VMEM ((512,512) d=64 runs; (512,512) d=128 faults).
    _SCORE_TEMPS = 6
    _SCOPED_BUDGET = 0.42

    def candidates(self) -> List[Candidate]:
        arch = self.arch or auto_arch()
        ib = dtype_bits(self.dtype) // 8
        D = self.head_dim
        frac = 0.5 if self.causal else 1.0
        total_flops = 4.0 * self.batch_heads * self.seq_q * self.seq_k \
            * D * frac
        out = []
        for bm in _tile_candidates(self.seq_q, 128, 1024):
            for bn in _tile_candidates(self.seq_k, 128, 1024):
                n_q = self.seq_q // bm
                n_k = max(1, int(self.seq_k // bn * frac))
                vmem = (bm * D * ib
                        + 2 * 2 * bn * D * ib
                        + self._SCORE_TEMPS * bm * bn * 4
                        + bm * D * 4
                        + 4 * bm * 4)
                hbm = self.batch_heads * (
                    self.seq_q * D * ib                 # Q once
                    + 2 * self.seq_k * D * ib * n_q * frac  # K,V per q-blk
                    + self.seq_q * D * ib)              # out
                vpu = self.batch_heads * self.seq_q * self.seq_k * frac * 8
                util = min(bm / arch.mxu_shape[0], 1.0) * \
                    min(bn / arch.mxu_shape[1], 1.0)
                out.append(Candidate(
                    {"block_M": bm, "block_N": bn},
                    total_flops, hbm, vpu, vmem,
                    self.batch_heads * n_q * n_k, util))
        return out

    def hints(self, topk: int = 8) -> List[Hint]:
        pol = DefaultPolicy(self.arch, vmem_budget=self._SCOPED_BUDGET)
        return pol.rank(self.candidates(), topk)


@dataclass
class Conv2DTemplate:
    """NHWC conv as implicit GEMM: (N*OH*OW, KH*KW*C) x (KH*KW*C, F)
    (reference carver/template/conv.py). Tiles the GEMM view; the kernel
    realizes it with c2d_im2col windows."""
    N: int
    H: int
    W: int
    C: int
    F: int
    KH: int = 3
    KW: int = 3
    stride: int = 1
    in_dtype: str = "bfloat16"
    accum_dtype: str = "float32"
    arch: Optional[TPUArch] = None

    @property
    def out_hw(self) -> Tuple[int, int]:
        return ((self.H - self.KH) // self.stride + 1,
                (self.W - self.KW) // self.stride + 1)

    def candidates(self) -> List[Candidate]:
        arch = self.arch or auto_arch()
        oh, ow = self.out_hw
        M = self.N * oh * ow
        K = self.KH * self.KW * self.C
        Nn = self.F
        ib = dtype_bits(self.in_dtype) // 8
        ab = dtype_bits(self.accum_dtype) // 8
        total_flops = 2.0 * M * Nn * K
        out = []
        for bm in _tile_candidates(M, 128, 1024):
            for bn in _tile_candidates(Nn, 128, 512):
                for bk in _tile_candidates(K, min(K, 128), 2048):
                    n_m, n_n = M // bm, Nn // bn
                    # im2col reads overlap: each input elem read ~KH*KW
                    # times unless cached; weights stream per m-block
                    hbm = (self.N * self.H * self.W * self.C * ib
                           * self.KH * self.KW / max(self.stride ** 2, 1)
                           + K * Nn * n_m * ib + M * Nn * ab)
                    vmem = 2 * (bm * bk + bk * bn) * ib + bm * bn * ab
                    util = min(bm / arch.mxu_shape[0], 1.0) * \
                        min(bn / arch.mxu_shape[1], 1.0)
                    out.append(Candidate(
                        {"block_M": bm, "block_N": bn, "block_K": bk},
                        total_flops, hbm, 0.0, vmem,
                        n_m * n_n * max(1, K // bk), util))
        return out

    def hints(self, topk: int = 10) -> List[Hint]:
        return DefaultPolicy(self.arch).rank(self.candidates(), topk)


@dataclass
class GEMVTemplate:
    """y = A @ x, memory-bound (reference carver/template/gemv.py). The
    MXU is idle; tiles are ranked purely by HBM streaming efficiency and
    VPU occupancy."""
    M: int
    K: int
    in_dtype: str = "bfloat16"
    arch: Optional[TPUArch] = None

    def candidates(self) -> List[Candidate]:
        arch = self.arch or auto_arch()
        sub, lane = arch.min_tile(self.in_dtype)
        ib = dtype_bits(self.in_dtype) // 8
        out = []
        for bm in _tile_candidates(self.M, sub, 2048):
            for bk in _tile_candidates(self.K, lane, 4096):
                hbm = self.M * self.K * ib + self.K * ib * (self.M // bm) \
                    + self.M * 4
                vmem = 2 * (bm * bk + bk) * ib + bm * 4
                out.append(Candidate(
                    {"block_M": bm, "block_K": bk},
                    2.0 * self.M * self.K, hbm,
                    vpu_elems=1.0 * self.M * self.K,
                    vmem_bytes=vmem,
                    n_tiles=(self.M // bm) * (self.K // bk),
                    utilization=1.0))
        return out

    def hints(self, topk: int = 8) -> List[Hint]:
        return DefaultPolicy(self.arch).rank(self.candidates(), topk)


@dataclass
class ElementwiseTemplate:
    shape: Tuple[int, ...]
    dtype: str = "float32"
    arch: Optional[TPUArch] = None
    ops_per_elem: float = 1.0

    def _rows_cols(self):
        rows = 1
        for s in self.shape[:-1]:
            rows *= s
        return rows, self.shape[-1]

    def candidates(self) -> List[Candidate]:
        arch = self.arch or auto_arch()
        rows, cols = self._rows_cols()
        sub, lane = arch.min_tile(self.dtype)
        b = dtype_bits(self.dtype) // 8
        out = []
        for bm in _tile_candidates(rows, sub, 2048):
            for bn in _tile_candidates(cols, lane, 4096):
                out.append(Candidate(
                    {"block_M": bm, "block_N": bn},
                    0.0, 2.0 * rows * cols * b,
                    vpu_elems=self.ops_per_elem * rows * cols,
                    vmem_bytes=2 * bm * bn * b,
                    n_tiles=(rows // bm) * (cols // bn)))
        return out

    def hints(self, topk: int = 6) -> List[Hint]:
        return DefaultPolicy(self.arch, vmem_budget=0.45).rank(
            self.candidates(), topk)


@dataclass
class GeneralReductionTemplate:
    """Row/column reductions (reference carver/template/general_reduce.py):
    tile the kept axis to VPU sublanes, stream the reduced axis."""
    shape: Tuple[int, ...]
    reduce_dim: int = -1
    dtype: str = "float32"
    arch: Optional[TPUArch] = None

    def candidates(self) -> List[Candidate]:
        arch = self.arch or auto_arch()
        rows = 1
        for s in self.shape[:-1]:
            rows *= s
        cols = self.shape[-1]
        sub, lane = arch.min_tile(self.dtype)
        b = dtype_bits(self.dtype) // 8
        red_last = self.reduce_dim in (-1, len(self.shape) - 1)
        out = []
        for bm in _tile_candidates(rows, sub, 2048):
            for bn in _tile_candidates(cols, lane, 4096):
                kept = rows if red_last else cols
                out.append(Candidate(
                    {"block_M": bm, "block_N": bn},
                    0.0, (rows * cols + kept) * b,
                    vpu_elems=1.0 * rows * cols,
                    vmem_bytes=2 * bm * bn * b + (bm if red_last else bn) * 4,
                    n_tiles=(rows // bm) * (cols // bn)))
        return out

    def hints(self, topk: int = 6) -> List[Hint]:
        return DefaultPolicy(self.arch, vmem_budget=0.45).rank(
            self.candidates(), topk)


def recommend_hints(template, topk: int = 10) -> List[Hint]:
    return template.hints(topk)
