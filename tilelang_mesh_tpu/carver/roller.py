"""Tile-config recommendation ("roller").

Reference: /root/reference/tilelang/carver/roller/ (DefaultPolicy,
TensorCorePolicy) + template/. Re-founded on TPU constraints: candidate
tiles are multiples of the dtype's (sublane, lane) packing, scored by an
arithmetic-intensity model against VMEM capacity — the same role
TensorCorePolicy's smem/warp model plays for CUDA.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .arch import TPUArch, auto_arch
from ..ir import dtype_bits


@dataclass
class Hint:
    config: Dict[str, int]
    score: float

    def __repr__(self):
        return f"Hint({self.config}, score={self.score:.3g})"


def _tile_candidates(dim: int, minimum: int, cap: int = 1024) -> List[int]:
    out = []
    t = minimum
    while t <= min(dim, cap):
        if dim % t == 0:
            out.append(t)
        t *= 2
    return out or [min(dim, minimum)]


@dataclass
class MatmulTemplate:
    """GEMM M/N/K tiling (reference carver/template/matmul.py)."""
    M: int
    N: int
    K: int
    in_dtype: str = "bfloat16"
    accum_dtype: str = "float32"
    arch: Optional[TPUArch] = None

    def hints(self, topk: int = 10) -> List[Hint]:
        arch = self.arch or auto_arch()
        sub, lane = arch.min_tile(self.in_dtype)
        ib = dtype_bits(self.in_dtype) // 8
        ab = dtype_bits(self.accum_dtype) // 8
        cands = []
        for bm in _tile_candidates(self.M, max(sub, 128), 1024):
            for bn in _tile_candidates(self.N, lane, 1024):
                for bk in _tile_candidates(self.K, max(sub, 128), 2048):
                    # VMEM: A tile + B tile (double-buffered by Mosaic) +
                    # f32 accumulator
                    vmem = 2 * (bm * bk + bk * bn) * ib + bm * bn * ab
                    if vmem > 0.9 * arch.vmem_bytes:
                        continue
                    # score: arithmetic intensity x MXU utilization
                    flops = 2 * bm * bn * bk
                    bytes_moved = (bm * bk + bk * bn) * ib
                    intensity = flops / bytes_moved
                    mxu_util = min(bm / arch.mxu_shape[0], 1.0) * \
                        min(bn / arch.mxu_shape[1], 1.0)
                    # prefer larger K tiles (fewer grid steps, less accum
                    # traffic) but cap the benefit
                    k_bonus = min(bk / 512, 1.0)
                    score = intensity * mxu_util * (0.5 + 0.5 * k_bonus)
                    cands.append(Hint(
                        {"block_M": bm, "block_N": bn, "block_K": bk},
                        score))
        cands.sort(key=lambda h: -h.score)
        return cands[:topk]


@dataclass
class FlashAttentionTemplate:
    seq_q: int
    seq_k: int
    head_dim: int
    dtype: str = "bfloat16"
    arch: Optional[TPUArch] = None

    def hints(self, topk: int = 8) -> List[Hint]:
        arch = self.arch or auto_arch()
        ib = dtype_bits(self.dtype) // 8
        cands = []
        for bm in _tile_candidates(self.seq_q, 128, 1024):
            for bn in _tile_candidates(self.seq_k, 128, 1024):
                vmem = (bm * self.head_dim * ib          # Q tile
                        + 2 * 2 * bn * self.head_dim * ib  # K,V double-buf
                        + bm * bn * 4                     # scores f32
                        + bm * self.head_dim * 4          # acc f32
                        + 4 * bm * 4)                     # stats rows
                if vmem > 0.9 * arch.vmem_bytes:
                    continue
                score = min(bm / 256, 1.0) * min(bn / 512, 1.0) + \
                    0.1 * (bm * bn) / (1024 * 1024)
                cands.append(Hint({"block_M": bm, "block_N": bn}, score))
        cands.sort(key=lambda h: -h.score)
        return cands[:topk]


@dataclass
class ElementwiseTemplate:
    shape: Tuple[int, ...]
    dtype: str = "float32"
    arch: Optional[TPUArch] = None

    def hints(self, topk: int = 6) -> List[Hint]:
        arch = self.arch or auto_arch()
        rows = self.shape[-2] if len(self.shape) >= 2 else 1
        cols = self.shape[-1]
        sub, lane = arch.min_tile(self.dtype)
        cands = []
        for bm in _tile_candidates(rows, sub, 2048):
            for bn in _tile_candidates(cols, lane, 4096):
                n = bm * bn * dtype_bits(self.dtype) // 8
                if n > 0.45 * arch.vmem_bytes:
                    continue
                cands.append(Hint({"block_M": bm, "block_N": bn},
                                  float(n)))
        cands.sort(key=lambda h: -h.score)
        return cands[:topk]


@dataclass
class GeneralReductionTemplate(ElementwiseTemplate):
    pass


def recommend_hints(template, topk: int = 10) -> List[Hint]:
    return template.hints(topk)
