"""TPU architecture models for the tile-config recommender.

Reference: /root/reference/tilelang/carver/arch/ (CUDA SM models,
driver/sunmmio_driver.py's per-core SRAM model). The TPU analog captures what
bounds a tile choice: VMEM capacity, MXU shape, dtype-dependent (sublane,
lane) tiling, HBM bandwidth, and ICI links for the mesh tier.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple


@dataclass(frozen=True)
class TPUArch:
    name: str
    mxu_shape: Tuple[int, int] = (128, 128)
    vpu_shape: Tuple[int, int] = (8, 128)
    vmem_bytes: int = 16 * 2 ** 20        # per core
    smem_bytes: int = 1 * 2 ** 20
    hbm_gbps: float = 1200.0              # HBM bandwidth GB/s
    bf16_tflops: float = 200.0            # peak MXU throughput
    ici_gbps_per_link: float = 90.0       # per ICI link, per direction
    ici_links: int = 4
    cores_per_chip: int = 1

    def min_tile(self, dtype: str) -> Tuple[int, int]:
        """Minimum (sublane, lane) tile per dtype (Mosaic packing rules)."""
        from ..ir import dtype_bits
        bits = dtype_bits(dtype)
        sublane = {32: 8, 16: 16, 8: 32}.get(bits, 8)
        return (sublane, 128)

    def fits_vmem(self, *buffers: Tuple[Tuple[int, ...], str],
                  budget: float = 0.9) -> bool:
        return self.buffers_bytes(*buffers) <= budget * self.vmem_bytes

    def buffers_bytes(self, *buffers: Tuple[Tuple[int, ...], str]) -> int:
        """True padded VMEM footprint using the (sublane, lane) packing
        rules (native tl_vmem_bytes when built)."""
        from ..ir import dtype_bits
        from ..layout import python_impl as lpy
        from ..layout import native as lnat
        total = 0
        for shape, dtype in buffers:
            bits = dtype_bits(dtype)
            rows = 1
            for s in shape[:-1]:
                rows *= s
            cols = shape[-1] if shape else 1
            b = lnat.vmem_bytes(rows, cols, bits)
            total += b if b is not None else lpy.vmem_bytes(rows, cols, bits)
        return total


TPU_V4 = TPUArch("tpu_v4", vmem_bytes=16 * 2 ** 20, hbm_gbps=1200.0,
                 bf16_tflops=137.5, cores_per_chip=2)
TPU_V5E = TPUArch("tpu_v5e", vmem_bytes=16 * 2 ** 20, hbm_gbps=819.0,
                  bf16_tflops=197.0)
TPU_V5P = TPUArch("tpu_v5p", vmem_bytes=16 * 2 ** 20, hbm_gbps=2765.0,
                  bf16_tflops=229.0, ici_gbps_per_link=100.0, ici_links=6,
                  cores_per_chip=2)
TPU_V6E = TPUArch("tpu_v6e", vmem_bytes=32 * 2 ** 20, hbm_gbps=1640.0,
                  bf16_tflops=918.0)

_BY_KIND = {"v4": TPU_V4, "v5e": TPU_V5E, "v5 lite": TPU_V5E,
            "v5litepod": TPU_V5E, "v5p": TPU_V5P, "v6e": TPU_V6E,
            "v6 lite": TPU_V6E}


def auto_arch() -> TPUArch:
    try:
        import jax
        kind = jax.devices()[0].device_kind.lower()
        for k, arch in _BY_KIND.items():
            if k in kind:
                return arch
    except Exception:
        pass
    return TPU_V5E


@dataclass(frozen=True)
class TPUMeshArch:
    """A pod-slice mesh: the analog of SunmmioDeviceProperties
    (reference sunmmio_driver.py:7-16 — 4x4 mesh, per-core SRAM banks)."""
    chip: TPUArch
    mesh_config: Tuple[int, int] = (4, 4)

    @property
    def num_chips(self) -> int:
        return self.mesh_config[0] * self.mesh_config[1]

    def bisection_gbps(self) -> float:
        r, c = self.mesh_config
        return min(r, c) * self.chip.ici_gbps_per_link * 2
