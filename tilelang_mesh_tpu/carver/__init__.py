from .arch import (TPUArch, TPU_V4, TPU_V5E, TPU_V5P, TPU_V6E, auto_arch,
                   TPUMeshArch)
from .roller import (MatmulTemplate, FlashAttentionTemplate,
                     ElementwiseTemplate, GeneralReductionTemplate,
                     Conv2DTemplate, GEMVTemplate,
                     DefaultPolicy, Candidate,
                     recommend_hints, Hint)
