"""Tile-IR: expressions, buffers, statements, printer."""

from .expr import (PrimExpr, Var, IntImm, FloatImm, BoolImm, StringImm,
                   BinOp, Call, Cast, BufferLoad, convert, const, as_int,
                   ceildiv,
                   canon_dtype, dtype_bits, dtype_is_float, dtype_is_int,
                   promote_dtypes, linearize, free_vars, for_each_load)
from .buffer import Buffer, Region, to_region
from .stmt import (Stmt, SeqStmt, AllocStmt, AsyncCopyStmt, KernelNode,
                   ForNest, IfThenElse,
                   BufferStoreStmt, EvaluateStmt, CopyStmt, GemmStmt, FillStmt,
                   ReduceStmt, CumSumStmt, AtomicStmt, PrintStmt, AssertStmt,
                   CommStmt, CommBroadcast, CommPut, CommAllGather,
                   CommAllReduce, CommBarrier, CommFence, CommFused,
                   CommChunked, PrimFunc, walk, collect)
from .printer import expr_str, func_str, region_str
