"""Tile-IR expression AST.

TPU-native re-design of the reference's TIR expression surface
(cf. /root/reference/tilelang/language/tir/op.py). We do not embed TVM: the IR
is a small, purpose-built AST that the trace builder records and the Pallas
codegen prints back out as jnp/lax Python source. Integer arithmetic is folded
eagerly so grid extents and block shapes stay concrete Python ints whenever the
user wrote concrete shapes.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

# ---------------------------------------------------------------------------
# dtype handling
# ---------------------------------------------------------------------------

_DTYPE_ALIASES = {
    "float": "float32",
    "fp32": "float32",
    "fp16": "float16",
    "half": "float16",
    "bf16": "bfloat16",
    "int": "int32",
    "bool": "bool",
    "e4m3": "float8_e4m3fn",
    "float8_e4m3": "float8_e4m3fn",
    "e5m2": "float8_e5m2",
    "float8_e5m2": "float8_e5m2",
}

_VALID_DTYPES = {
    "float64", "float32", "float16", "bfloat16",
    "float8_e4m3fn", "float8_e5m2",
    "int64", "int32", "int16", "int8", "uint64", "uint32", "uint16", "uint8",
    "bool",
}


def canon_dtype(dtype: Any) -> str:
    """Canonicalize a dtype spec (str / jnp dtype / np dtype) to a string."""
    if dtype is None:
        return "float32"
    if not isinstance(dtype, str):
        name = getattr(dtype, "__name__", None) or getattr(dtype, "name", None)
        if name is None:
            import numpy as np
            name = np.dtype(dtype).name
        dtype = name
    dtype = _DTYPE_ALIASES.get(dtype, dtype)
    if dtype not in _VALID_DTYPES:
        raise ValueError(f"unsupported dtype: {dtype!r}")
    return dtype


def dtype_bits(dtype: str) -> int:
    dtype = canon_dtype(dtype)
    if dtype == "bool":
        return 8
    for n in (64, 32, 16, 8):
        if dtype.endswith(str(n)) or (n == 8 and dtype.startswith("float8")):
            return n
    raise ValueError(dtype)


def dtype_is_float(dtype: str) -> bool:
    return dtype.startswith("float") or dtype == "bfloat16"


def dtype_is_int(dtype: str) -> bool:
    return dtype.startswith("int") or dtype.startswith("uint")


def promote_dtypes(a: str, b: str) -> str:
    """Numpy-style promotion, simplified for kernel arithmetic."""
    if a == b:
        return a
    fa, fb = dtype_is_float(a), dtype_is_float(b)
    if fa and not fb:
        return a
    if fb and not fa:
        return b
    if fa and fb:
        order = ["float8_e5m2", "float8_e4m3fn", "float16", "bfloat16",
                 "float32", "float64"]
        return order[max(order.index(a), order.index(b))]
    # both int-ish
    return a if dtype_bits(a) >= dtype_bits(b) else b


# ---------------------------------------------------------------------------
# Expression nodes
# ---------------------------------------------------------------------------


class PrimExpr:
    """Base class for all tile-IR expressions."""

    dtype: str = "int32"

    # -- python operator sugar ------------------------------------------------
    def __add__(self, o): return _binop("+", self, o)
    def __radd__(self, o): return _binop("+", o, self)
    def __sub__(self, o): return _binop("-", self, o)
    def __rsub__(self, o): return _binop("-", o, self)
    def __mul__(self, o): return _binop("*", self, o)
    def __rmul__(self, o): return _binop("*", o, self)
    def __floordiv__(self, o): return _binop("//", self, o)
    def __rfloordiv__(self, o): return _binop("//", o, self)
    def __truediv__(self, o): return _binop("/", self, o)
    def __rtruediv__(self, o): return _binop("/", o, self)
    def __mod__(self, o): return _binop("%", self, o)
    def __rmod__(self, o): return _binop("%", o, self)
    def __neg__(self): return _binop("*", self, -1)
    def __lt__(self, o): return _binop("<", self, o)
    def __le__(self, o): return _binop("<=", self, o)
    def __gt__(self, o): return _binop(">", self, o)
    def __ge__(self, o): return _binop(">=", self, o)
    def __pow__(self, o): return Call("pow", [self, convert(o)],
                                      promote_dtypes(self.dtype, convert(o).dtype))

    def __eq__(self, o):  # structural equality is `same_as`; == builds IR
        return _binop("==", self, o)

    def __ne__(self, o):
        return _binop("!=", self, o)

    def __hash__(self):
        return id(self)

    # `&`/`|` follow TVM-script semantics: logical on bools, bitwise on ints
    def __and__(self, o):
        oo = convert(o)
        if self.dtype == "bool" and oo.dtype == "bool":
            return _binop("and", self, oo)
        return Call("bitwise_and", [self, oo],
                    promote_dtypes(self.dtype, oo.dtype))

    def __rand__(self, o): return self.__and__(o)

    def __or__(self, o):
        oo = convert(o)
        if self.dtype == "bool" and oo.dtype == "bool":
            return _binop("or", self, oo)
        return Call("bitwise_or", [self, oo],
                    promote_dtypes(self.dtype, oo.dtype))

    def __ror__(self, o): return self.__or__(o)

    def __xor__(self, o):
        oo = convert(o)
        return Call("bitwise_xor", [self, oo],
                    promote_dtypes(self.dtype, oo.dtype))

    def __rxor__(self, o): return self.__xor__(o)

    def __rshift__(self, o):
        oo = convert(o)
        return Call("shift_right", [self, oo], self.dtype)

    def __rrshift__(self, o):
        oo = convert(o)
        return Call("shift_right", [oo, self], oo.dtype)

    def __lshift__(self, o):
        oo = convert(o)
        return Call("shift_left", [self, oo], self.dtype)

    def __rlshift__(self, o):
        oo = convert(o)
        return Call("shift_left", [oo, self], oo.dtype)

    def __invert__(self):
        if self.dtype == "bool":
            return Call("logical_not", [self], "bool")
        return Call("bitwise_not", [self], self.dtype)

    def __bool__(self):
        raise TypeError(
            "Cannot convert a symbolic tile-IR expression to a Python bool. "
            "Use T.if_then_else(...) / T.Select for data-dependent control "
            "flow inside kernels.")

    def __index__(self):
        raise TypeError(f"symbolic expression {self!r} used where a concrete "
                        "Python int is required")

    def __repr__(self):
        from .printer import expr_str
        return expr_str(self)


class Var(PrimExpr):
    """A scalar variable: loop var, grid var, or dynamic-shape symbol."""

    _counter = [0]

    def __init__(self, name: str, dtype: str = "int32"):
        self.name = name
        self.dtype = canon_dtype(dtype)
        Var._counter[0] += 1
        self.uid = Var._counter[0]
        self._bound = None  # concrete value during lazy_jit re-trace

    def same_as(self, other) -> bool:
        return self is other


class IntImm(PrimExpr):
    def __init__(self, value: int, dtype: str = "int32"):
        self.value = int(value)
        self.dtype = dtype


class FloatImm(PrimExpr):
    def __init__(self, value: float, dtype: str = "float32"):
        self.value = float(value)
        self.dtype = dtype


class BoolImm(PrimExpr):
    def __init__(self, value: bool):
        self.value = bool(value)
        self.dtype = "bool"


class StringImm(PrimExpr):
    def __init__(self, value: str):
        self.value = value
        self.dtype = "handle"


class BinOp(PrimExpr):
    """Binary operation. op in {+,-,*,//,/,%,min,max,<,<=,>,>=,==,!=,and,or}."""

    _CMP = {"<", "<=", ">", ">=", "==", "!=", "and", "or"}

    def __init__(self, op: str, a: PrimExpr, b: PrimExpr):
        self.op = op
        self.a = a
        self.b = b
        if op in self._CMP:
            self.dtype = "bool"
        elif op == "/":
            d = promote_dtypes(a.dtype, b.dtype)
            self.dtype = d if dtype_is_float(d) else "float32"
        else:
            self.dtype = promote_dtypes(a.dtype, b.dtype)


class Call(PrimExpr):
    """Intrinsic call (exp, max, sqrt, ...) printed to the jnp equivalent."""

    def __init__(self, name: str, args: Sequence[Any], dtype: str):
        self.name = name
        self.args = [convert(a) if not isinstance(a, str) else a for a in args]
        self.dtype = dtype


class Cast(PrimExpr):
    def __init__(self, dtype: str, value: PrimExpr):
        self.dtype = canon_dtype(dtype)
        self.value = convert(value)


class BufferLoad(PrimExpr):
    """An element (or region-base) access ``buf[i0, i1, ...]``.

    Indices may contain slices; a BufferLoad with slices denotes a region and
    is only valid as a tile-op operand (T.copy / T.gemm / ...).
    """

    def __init__(self, buffer, indices):
        self.buffer = buffer
        self.indices = tuple(indices)
        self.dtype = buffer.dtype

    @property
    def has_slices(self) -> bool:
        return any(isinstance(i, slice) for i in self.indices)


# ---------------------------------------------------------------------------
# Construction helpers / folding
# ---------------------------------------------------------------------------


def convert(v: Any) -> PrimExpr:
    if isinstance(v, PrimExpr):
        return v
    if isinstance(v, bool):
        return BoolImm(v)
    if isinstance(v, int):
        return IntImm(v)
    if isinstance(v, float):
        return FloatImm(v)
    import numpy as np
    if isinstance(v, np.integer):
        return IntImm(int(v))
    if isinstance(v, np.floating):
        return FloatImm(float(v))
    raise TypeError(f"cannot convert {type(v)} to tile-IR expression")


def _const_val(e: PrimExpr) -> Optional[Union[int, float, bool]]:
    if isinstance(e, (IntImm, FloatImm, BoolImm)):
        return e.value
    if isinstance(e, Var):
        return e._bound
    return None


_FOLD = {
    "+": lambda a, b: a + b,
    "-": lambda a, b: a - b,
    "*": lambda a, b: a * b,
    "//": lambda a, b: a // b,
    "%": lambda a, b: a % b,
    "/": lambda a, b: a / b,
    "min": min,
    "max": max,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "and": lambda a, b: a and b,
    "or": lambda a, b: a or b,
}


def _binop(op: str, a: Any, b: Any) -> PrimExpr:
    a, b = convert(a), convert(b)
    av, bv = _const_val(a), _const_val(b)
    if av is not None and bv is not None:
        r = _FOLD[op](av, bv)
        if isinstance(r, bool):
            return BoolImm(r)
        if isinstance(r, int):
            return IntImm(r)
        return FloatImm(r, promote_dtypes(a.dtype, b.dtype))
    # light algebraic identities keep printed IR and index maps clean
    if op == "+":
        if av == 0:
            return b
        if bv == 0:
            return a
    elif op == "-":
        if bv == 0:
            return a
    elif op == "*":
        if av == 1:
            return b
        if bv == 1:
            return a
        if av == 0 or bv == 0:
            return IntImm(0) if dtype_is_int(promote_dtypes(a.dtype, b.dtype)) \
                else FloatImm(0.0)
    elif op == "//" and bv == 1:
        return a
    return BinOp(op, a, b)


def const(value, dtype=None) -> PrimExpr:
    e = convert(value)
    if dtype is not None and e.dtype != canon_dtype(dtype):
        if isinstance(e, IntImm):
            d = canon_dtype(dtype)
            return FloatImm(float(e.value), d) if dtype_is_float(d) else IntImm(e.value, d)
        return Cast(dtype, e)
    return e


def substitute(e: Any, env: dict) -> Any:
    """Replace Vars (by id or via their lazy_jit binding) with concrete
    values, folding as it rebuilds."""
    if isinstance(e, Var):
        v = env.get(id(e), e._bound)
        return convert(v) if v is not None else e
    if isinstance(e, BinOp):
        return _binop(e.op, substitute(e.a, env), substitute(e.b, env))
    if isinstance(e, Cast):
        return Cast(e.dtype, substitute(e.value, env))
    if isinstance(e, Call):
        return Call(e.name, [a if isinstance(a, str) else
                             substitute(a, env) for a in e.args], e.dtype)
    return e


def as_int(e: Any) -> Optional[int]:
    """Return a concrete Python int if the expression is statically known.

    During a lazy_jit re-trace, dyn Vars carry a concrete binding
    (Var.bind/_bound) and fold like constants — that is what makes
    `T.Kernel(T.ceildiv(M, bm))` with M = T.dynamic(...) compile per
    call-site shape.
    """
    if isinstance(e, int):
        return e
    if isinstance(e, IntImm):
        return e.value
    if isinstance(e, Var) and e._bound is not None:
        return e._bound
    if isinstance(e, BinOp) and _any_bound_var(e):
        se = substitute(e, {})
        if isinstance(se, IntImm):
            return se.value
    return None


def _any_bound_var(e: Any) -> bool:
    """Cheap pre-check so as_int only rebuilds when a binding can fold it."""
    if isinstance(e, Var):
        return e._bound is not None
    if isinstance(e, BinOp):
        return _any_bound_var(e.a) or _any_bound_var(e.b)
    if isinstance(e, Cast):
        return _any_bound_var(e.value)
    if isinstance(e, Call):
        return any(_any_bound_var(a) for a in e.args
                   if not isinstance(a, str))
    return False


def ceildiv(a, b):
    a, b = convert(a), convert(b)
    av, bv = _const_val(a), _const_val(b)
    if av is not None and bv is not None:
        return IntImm(-(-av // bv)).value  # plain python int for grid extents
    return _binop("//", _binop("+", a, _binop("-", b, 1)), b)


# ---------------------------------------------------------------------------
# Affine analysis (the layout-inference workhorse; cf. reference
# src/transform/layout_inference.cc constraint extraction)
# ---------------------------------------------------------------------------


def affine_decompose(expr):
    """Decompose an expression as ``sum(coeff_v * v) + const`` over ALL vars.

    Returns ({id(v): (v, coeff)}, const) or None when not affine with
    integer coefficients. Symbolic cancellation (``i - i`` -> 0) falls out
    of the coefficient arithmetic.
    """
    e = convert(expr)
    if isinstance(e, IntImm):
        return {}, e.value
    if isinstance(e, Var):
        return {id(e): (e, 1)}, 0
    if isinstance(e, BinOp):
        if e.op in ("+", "-"):
            ra, rb = affine_decompose(e.a), affine_decompose(e.b)
            if ra is None or rb is None:
                return None
            ca, ka = ra
            cb, kb = rb
            sign = 1 if e.op == "+" else -1
            out = dict(ca)
            for k, (v, c) in cb.items():
                pv, pc = out.get(k, (v, 0))
                out[k] = (v, pc + sign * c)
            out = {k: vc for k, vc in out.items() if vc[1] != 0}
            return out, ka + sign * kb
        if e.op == "*":
            ra, rb = affine_decompose(e.a), affine_decompose(e.b)
            if ra is None or rb is None:
                return None
            ca, ka = ra
            cb, kb = rb
            if ca and cb:
                return None
            if not ca:
                ca, ka, cb, kb = cb, kb, ca, ka
            return ({k: (v, c * kb) for k, (v, c) in ca.items()}
                    if kb != 0 else {}), ka * kb
        if e.op == "//":
            ra, rb = affine_decompose(e.a), affine_decompose(e.b)
            if ra is None or rb is None:
                return None
            cb, kb = rb
            if cb or kb == 0:
                return None
            ca, ka = ra
            if all(c % kb == 0 for _, c in ca.values()) and ka % kb == 0:
                return {k: (v, c // kb) for k, (v, c) in ca.items()}, ka // kb
            return None
        return None
    return None


def rebuild_affine(coeffs, const) -> PrimExpr:
    """Inverse of affine_decompose: build an expression from terms."""
    out: PrimExpr = IntImm(const)
    for _, (v, c) in sorted(coeffs.items(), key=lambda kv: kv[1][0].uid):
        out = _binop("+", out, _binop("*", v, c))
    return out


_AFFINE_OPS = {"+": 2, "-": 3, "*": 4, "//": 5}
_EVAL_OPS = {"+": 2, "-": 3, "*": 4, "//": 5, "%": 6, "min": 7, "max": 8}


def _encode(expr, slot_of, op_table, cast_transparent):
    """Shared tree -> node-program flattener behind encode_expr (eval
    grammar) and _encode_affine (affine grammar). One walker so the two
    paths cannot diverge; the op table and Cast handling are the only
    degrees of freedom."""
    ops, aa, bb = [], [], []

    def go(e):
        e = convert(e)
        if cast_transparent and isinstance(e, Cast):
            return go(e.value)
        if isinstance(e, IntImm) or (cast_transparent and
                                     isinstance(e, BoolImm)):
            ops.append(0)
            aa.append(int(e.value))
            bb.append(0)
            return len(ops) - 1
        if isinstance(e, Var):
            s = slot_of.get(id(e))
            if s is None:
                return None
            ops.append(1)
            aa.append(s)
            bb.append(0)
            return len(ops) - 1
        if isinstance(e, BinOp) and e.op in op_table:
            x = go(e.a)
            if x is None:
                return None
            y = go(e.b)
            if y is None:
                return None
            ops.append(op_table[e.op])
            aa.append(x)
            bb.append(y)
            return len(ops) - 1
        return None

    return (ops, aa, bb) if go(expr) is not None else None


def encode_expr(expr, slot_of):
    """Flatten an expr tree to the node program tl_expr_eval_grid
    consumes (superset of the affine grammar: adds %, min, max; Casts are
    transparent). Returns (ops, a, b) or None."""
    return _encode(expr, slot_of, _EVAL_OPS, cast_transparent=True)


def _encode_affine(expr, slot_of):
    """Flatten an expr tree to the postfix arrays tl_affine_linearize
    consumes; returns (ops, a, b) or None when a node falls outside the
    affine grammar (same rejections as the python linearize path — Casts
    included, so the native/python None decisions stay identical)."""
    return _encode(expr, slot_of, _AFFINE_OPS, cast_transparent=False)


def linearize(expr: PrimExpr, wrt: Sequence[Var]):
    """Decompose ``expr`` as ``sum(coeff[v] * v) + const`` over vars in `wrt`.

    Returns (coeffs: dict[Var, int], const: int) or None if the expression is
    not affine with integer-constant coefficients over those vars, or mentions
    a var outside `wrt`. Dispatches to the native core's
    tl_affine_linearize when built (src/tltpu_core.cc); the python path
    below is the behavioural reference (parity: tests/test_native.py).
    """
    from ..layout import native as _nat
    if _nat.available():
        slot_of = {id(v): i for i, v in enumerate(wrt)}
        enc = _encode_affine(expr, slot_of)
        if enc is not None:
            r = _nat.affine_linearize(enc[0], enc[1], enc[2], len(wrt))
            if r is None:
                return None
            coeffs, k = r
            return ({v: coeffs[i] for i, v in enumerate(wrt)
                     if coeffs[i] != 0}, k)
        # fall through: encoding rejected the tree exactly where the python
        # path would — but keep python as the single source of truth for
        # the None decision
    wrt_set = set(id(v) for v in wrt)

    def go(e):
        e = convert(e)
        if isinstance(e, IntImm):
            return {}, e.value
        if isinstance(e, Var):
            if id(e) in wrt_set:
                return {id(e): 1}, 0
            return None
        if isinstance(e, BinOp):
            if e.op in ("+", "-"):
                ra, rb = go(e.a), go(e.b)
                if ra is None or rb is None:
                    return None
                ca, ka = ra
                cb, kb = rb
                sign = 1 if e.op == "+" else -1
                out = dict(ca)
                for k, v in cb.items():
                    out[k] = out.get(k, 0) + sign * v
                # prune cancelled vars so (x - x) * y stays linear — keeps
                # parity with the native tl_affine_linearize zero check
                out = {k: v for k, v in out.items() if v != 0}
                return out, ka + sign * kb
            if e.op == "*":
                ra, rb = go(e.a), go(e.b)
                if ra is None or rb is None:
                    return None
                ca, ka = ra
                cb, kb = rb
                if ca and cb:
                    return None  # non-linear
                if not ca:
                    ca, ka, cb, kb = cb, kb, ca, ka
                # now cb empty: multiply by constant kb (prune kb == 0)
                return ({k: v * kb for k, v in ca.items() if v * kb != 0},
                        ka * kb)
            if e.op == "//":
                ra, rb = go(e.a), go(e.b)
                if ra is None or rb is None:
                    return None
                cb, kb = rb
                if cb or kb == 0:
                    return None
                ca, ka = ra
                if all(v % kb == 0 for v in ca.values()) and ka % kb == 0:
                    return {k: v // kb for k, v in ca.items()}, ka // kb
                return None
            return None
        return None

    r = go(expr)
    if r is None:
        return None
    coeffs, k = r
    by_var = {}
    for v in wrt:
        if id(v) in coeffs and coeffs[id(v)] != 0:
            by_var[v] = coeffs[id(v)]
    return by_var, k


def free_vars(expr: Any) -> list:
    """All Vars referenced by an expression tree."""
    out, seen = [], set()

    def go(e):
        if isinstance(e, Var):
            if id(e) not in seen:
                seen.add(id(e))
                out.append(e)
        elif isinstance(e, BinOp):
            go(e.a)
            go(e.b)
        elif isinstance(e, Call):
            for a in e.args:
                if isinstance(e, PrimExpr) or isinstance(a, PrimExpr):
                    go(a) if isinstance(a, PrimExpr) else None
        elif isinstance(e, Cast):
            go(e.value)
        elif isinstance(e, BufferLoad):
            for i in e.indices:
                if isinstance(i, slice):
                    for p in (i.start, i.stop, i.step):
                        if isinstance(p, PrimExpr):
                            go(p)
                else:
                    go(convert(i))
    go(convert(expr) if not isinstance(expr, PrimExpr) else expr)
    return out


def for_each_load(e: Any, fn) -> None:
    """Call fn(load) for every BufferLoad inside expression e, recursing
    into call args, binop operands, casts, and index expressions. The one
    expression walker shared by the codegen-prep passes (transform.mem2reg,
    transform.prefetch_guard) and the emitters in codegen.pallas, so their
    coverage cannot drift."""
    if isinstance(e, BufferLoad):
        fn(e)
        for i in e.indices:
            if not isinstance(i, slice):
                for_each_load(i, fn)
        return
    for a in getattr(e, "args", []) or []:
        if not isinstance(a, str):
            for_each_load(a, fn)
    for at in ("a", "b"):
        sub = getattr(e, at, None)
        if sub is not None:
            for_each_load(sub, fn)
    if isinstance(e, Cast):
        for_each_load(e.value, fn)
