"""Deterministic tile-IR printer.

The printed script is (a) the golden-test surface — the analog of the
reference's ``mod.script()`` structural tests (cf. SURVEY §4 style 1,
testing/python/transform/test_tilelang_transform_*.py) — and (b) the stable
string hashed into the kernel-cache key.
"""

from __future__ import annotations

from .expr import (PrimExpr, Var, IntImm, FloatImm, BoolImm, StringImm, BinOp,
                   Call, Cast, BufferLoad)
from .buffer import Buffer, Region
from . import stmt as S

_PREC = {
    "or": 1, "and": 2,
    "==": 3, "!=": 3, "<": 3, "<=": 3, ">": 3, ">=": 3,
    "+": 4, "-": 4,
    "*": 5, "/": 5, "//": 5, "%": 5,
}


def expr_str(e, prec: int = 0) -> str:
    if isinstance(e, Var):
        return e.name
    if isinstance(e, IntImm):
        return str(e.value)
    if isinstance(e, FloatImm):
        v = repr(e.value)
        return v if e.dtype == "float32" else f"{e.dtype}({v})"
    if isinstance(e, BoolImm):
        return str(e.value)
    if isinstance(e, StringImm):
        return repr(e.value)
    if isinstance(e, BinOp):
        if e.op in ("min", "max"):
            return f"{e.op}({expr_str(e.a)}, {expr_str(e.b)})"
        p = _PREC[e.op]
        s = f"{expr_str(e.a, p)} {e.op} {expr_str(e.b, p + 1)}"
        return f"({s})" if p < prec else s
    if isinstance(e, Call):
        args = ", ".join(a if isinstance(a, str) else expr_str(a)
                         for a in e.args)
        return f"{e.name}({args})"
    if isinstance(e, Cast):
        return f"{e.dtype}({expr_str(e.value)})"
    if isinstance(e, BufferLoad):
        return f"{e.buffer.name}[{_indices_str(e.indices)}]"
    if isinstance(e, (int, float, bool)):
        return str(e)
    return repr(e)


def _indices_str(indices) -> str:
    parts = []
    for i in indices:
        if isinstance(i, slice):
            a = "" if i.start is None else expr_str(i.start)
            b = "" if i.stop is None else expr_str(i.stop)
            parts.append(f"{a}:{b}")
        else:
            parts.append(expr_str(i))
    return ", ".join(parts)


def region_str(r: Region) -> str:
    base = ", ".join(expr_str(b) for b in r.base)
    shape = ", ".join(expr_str(s) if isinstance(s, PrimExpr) else str(s)
                      for s in r.shape)
    return f"{r.buffer.name}[({base}); ({shape})]"


def shape_str(shape) -> str:
    return "(" + ", ".join(
        expr_str(s) if isinstance(s, PrimExpr) else str(s)
        for s in shape) + ")"


_DIR_NAMES = {0: "h", 1: "v", 2: "all"}


class _Printer:
    def __init__(self):
        self.lines = []
        self.indent = 0

    def emit(self, text: str):
        self.lines.append("  " * self.indent + text)

    def stmt(self, s):
        m = getattr(self, "p_" + type(s).__name__, None)
        if m is None:
            self.emit(f"<{type(s).__name__}>")
        else:
            m(s)

    def p_SeqStmt(self, s):
        for c in s.stmts:
            self.stmt(c)

    def p_KernelNode(self, s):
        for p in s.prelude:
            self.stmt(p)
        vars_ = ", ".join(v.name for v in s.grid_vars)
        ext = ", ".join(str(e) for e in s.extents)
        self.emit(f"with Kernel(({ext}), threads={s.threads}) as ({vars_},):")
        self.indent += 1
        self.stmt(s.body)
        self.indent -= 1

    def p_AllocStmt(self, s):
        b = s.buffer
        self.emit(f"{b.name} = alloc({shape_str(b.shape)}, {b.dtype}, "
                  f"scope={b.scope})")

    def p_ForNest(self, s):
        vars_ = ", ".join(v.name for v in s.loop_vars)
        ext = ", ".join(expr_str(e) if isinstance(e, PrimExpr) else str(e)
                        for e in s.extents)
        extra = f", num_stages={s.num_stages}" if s.kind == "pipelined" else ""
        self.emit(f"for ({vars_},) in {s.kind}(({ext}){extra}):")
        self.indent += 1
        self.stmt(s.body)
        self.indent -= 1

    def p_IfThenElse(self, s):
        self.emit(f"if {expr_str(s.cond)}:")
        self.indent += 1
        self.stmt(s.then_body)
        self.indent -= 1
        if s.else_body is not None:
            self.emit("else:")
            self.indent += 1
            self.stmt(s.else_body)
            self.indent -= 1

    def p_BufferStoreStmt(self, s):
        self.emit(f"{s.buffer.name}[{_indices_str(s.indices)}] = "
                  f"{expr_str(s.value)}")

    def p_EvaluateStmt(self, s):
        self.emit(expr_str(s.expr))

    def p_CopyStmt(self, s):
        self.emit(f"copy({region_str(s.src)} -> {region_str(s.dst)})")

    def p_AsyncCopyStmt(self, s):
        self.emit(f"copy_{s.phase}({region_str(s.src)} -> "
                  f"{region_str(s.dst)}, sem={s.sem.name}"
                  f"[{expr_str(s.slot)}])")

    def p_GemmStmt(self, s):
        flags = ""
        if s.trans_A:
            flags += ", trans_A"
        if s.trans_B:
            flags += ", trans_B"
        if s.clear_accum:
            flags += ", clear_accum"
        self.emit(f"gemm({region_str(s.A)}, {region_str(s.B)} -> "
                  f"{region_str(s.C)}{flags})")

    def p_FillStmt(self, s):
        self.emit(f"fill({region_str(s.dst)}, {expr_str(s.value)})")

    def p_ReduceStmt(self, s):
        self.emit(f"reduce_{s.kind}({s.src.name} -> {s.dst.name}, "
                  f"dim={s.dim}, clear={s.clear})")

    def p_CumSumStmt(self, s):
        self.emit(f"cumsum({s.src.name} -> {s.dst.name}, dim={s.dim}, "
                  f"reverse={s.reverse})")

    def p_AtomicStmt(self, s):
        self.emit(f"atomic_{s.op}({region_str(s.dst)}, {expr_str(s.value)})")

    def p_PrintStmt(self, s):
        obj = s.obj.name if isinstance(s.obj, Buffer) else expr_str(s.obj)
        self.emit(f"print({obj}, msg={s.msg!r})")

    def p_AssertStmt(self, s):
        self.emit(f"device_assert({expr_str(s.cond)}, msg={s.msg!r})")

    def p_CommBroadcast(self, s):
        self.emit(f"comm.broadcast({region_str(s.src)} -> {region_str(s.dst)},"
                  f" src_core={s.src_core}, dir={_DIR_NAMES[s.direction]}, "
                  f"size={s.size})")

    def p_CommPut(self, s):
        self.emit(f"comm.put({region_str(s.src)} -> {region_str(s.dst)}, "
                  f"src_core={s.src_core}, dst_core={s.dst_core}, "
                  f"size={s.size})")

    def p_CommAllGather(self, s):
        self.emit(f"comm.all_gather({region_str(s.send)} -> "
                  f"{region_str(s.recv)}, dir={_DIR_NAMES[s.direction]}, "
                  f"size={s.size})")

    def p_CommAllReduce(self, s):
        self.emit(f"comm.all_reduce({region_str(s.buffer)} -> "
                  f"{region_str(s.out)}, op={s.reduce_type}, "
                  f"dir={_DIR_NAMES[s.direction]}, dim={s.dim}, "
                  f"clear={s.clear})")

    def p_CommBarrier(self, s):
        g = "" if s.group is None else f"group={s.group}"
        self.emit(f"comm.barrier({g})")

    def p_CommFence(self, s):
        self.emit("comm.fence()")


def func_str(f) -> str:
    p = _Printer()
    sig = []
    for prm in f.params:
        if isinstance(prm, Buffer):
            extra = ""
            if prm.mesh_meta is not None:
                extra = f", mesh={prm.mesh_meta.describe()}"
            sig.append(f"{prm.name}: Tensor({shape_str(prm.shape)}, "
                       f"{prm.dtype}{extra})")
        else:
            sig.append(f"{prm.name}: {prm.dtype}")
    p.emit(f"def {f.name}({', '.join(sig)}):")
    p.indent += 1
    if f.attrs:
        p.emit(f"# attrs: {dict(sorted(f.attrs.items()))}")
    p.stmt(f.body)
    return "\n".join(p.lines) + "\n"
