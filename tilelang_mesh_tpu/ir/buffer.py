"""Tile-IR buffers and regions.

Scopes map the reference's memory hierarchy onto the TPU's
(cf. /root/reference/tilelang/language/allocate.py):

  global          -> HBM (kernel operand)
  shared          -> VMEM block / scratch (the analog of CUDA smem)
  fragment        -> VMEM scratch, typically an accumulator (register fragments
                     have no TPU analog; Mosaic keeps hot tiles in vregs)
  local           -> VMEM scratch
  local.var       -> SMEM (1,1) scalar
  smem            -> SMEM scratch
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

from .expr import (PrimExpr, BufferLoad, Var, canon_dtype, convert, as_int)

SCOPES = ("global", "shared", "shared.dyn", "fragment", "local", "local.var",
          "smem", "sem")


class Buffer:
    """A typed, shaped memory handle appearing in tile-IR statements."""

    _counter = [0]

    def __init__(self, name: str, shape: Sequence[Any], dtype: str,
                 scope: str = "global"):
        if scope == "shared.dyn":
            scope = "shared"
        if scope not in SCOPES:
            raise ValueError(f"bad scope {scope}")
        self.name = name
        self.shape = tuple(
            s if isinstance(s, Var) else (as_int(s) if as_int(s) is not None
                                          else convert(s))
            for s in (shape if isinstance(shape, (tuple, list)) else (shape,)))
        self.dtype = canon_dtype(dtype)
        self.scope = scope
        Buffer._counter[0] += 1
        self.uid = Buffer._counter[0]
        # filled by the mesh layer for MeshTensor params:
        self.mesh_meta = None

    # -- convenience ---------------------------------------------------------
    @property
    def ndim(self) -> int:
        return len(self.shape)

    def static_shape(self) -> Optional[Tuple[int, ...]]:
        out = []
        for s in self.shape:
            v = as_int(s)
            if v is None:
                return None
            out.append(v)
        return tuple(out)

    def numel(self) -> Optional[int]:
        ss = self.static_shape()
        if ss is None:
            return None
        n = 1
        for s in ss:
            n *= s
        return n

    # -- DSL indexing --------------------------------------------------------
    def _norm_idx(self, idx):
        if not isinstance(idx, tuple):
            idx = (idx,)
        if len(idx) > self.ndim:
            raise IndexError(
                f"{self.name}: {len(idx)} indices for rank-{self.ndim} buffer")
        # pad missing trailing dims with 0: a partial index is a region BASE
        # (reference element-access sugar), the extent comes from the
        # consuming tile op
        if len(idx) < self.ndim:
            idx = idx + (0,) * (self.ndim - len(idx))
        out = []
        for i in idx:
            if isinstance(i, slice):
                out.append(i)
            else:
                out.append(convert(i))
        return tuple(out)

    def __getitem__(self, idx) -> BufferLoad:
        return BufferLoad(self, self._norm_idx(idx))

    def __setitem__(self, idx, value):
        from ..language.builder import current_builder
        b = current_builder()
        if b is None:
            raise RuntimeError(
                f"buffer store to {self.name} outside of a T.prim_func trace")
        from .stmt import BufferStoreStmt
        b.emit(BufferStoreStmt(self, self._norm_idx(idx), convert(value)))

    def __repr__(self):
        return (f"Buffer({self.name}, {self.shape}, {self.dtype}, "
                f"scope={self.scope})")

    def __len__(self):
        v = as_int(self.shape[0])
        if v is None:
            raise TypeError("len() of dynamic buffer dim")
        return v

    # iteration over a buffer is almost always a user error in kernel code
    def __iter__(self):
        raise TypeError("tile-IR buffers are not iterable")


class Region:
    """A rectangular sub-region of a buffer: base indices + extent."""

    def __init__(self, buffer: Buffer, base: Sequence[Any],
                 shape: Sequence[Any]):
        self.buffer = buffer
        self.base = tuple(convert(b) for b in base)
        self.shape = tuple(self._fold(s) for s in shape)

    @staticmethod
    def _fold(s):
        v = as_int(s)
        if v is not None:
            return v
        from .expr import affine_decompose
        e = convert(s)
        dec = affine_decompose(e)
        if dec is not None:
            coeffs, const = dec
            if not coeffs:  # symbolic terms cancelled, e.g. (k+1)*b - k*b
                return const
        return e

    @property
    def dtype(self):
        return self.buffer.dtype

    def static_shape(self):
        out = []
        for s in self.shape:
            v = as_int(s)
            if v is None:
                return None
            out.append(v)
        return tuple(out)

    def numel(self):
        ss = self.static_shape()
        if ss is None:
            return None
        n = 1
        for s in ss:
            n *= s
        return n

    def is_full(self) -> bool:
        bss = self.buffer.static_shape()
        rss = self.static_shape()
        if bss is None or rss is None:
            return False
        return bss == rss and all(as_int(b) == 0 for b in self.base)

    def __repr__(self):
        from .printer import expr_str
        base = ", ".join(expr_str(b) for b in self.base)
        return f"{self.buffer.name}[{base}; {self.shape}]"


def to_region(obj: Any, extent_hint: Optional[Sequence[int]] = None) -> Region:
    """Normalize a tile-op operand to a Region.

    Accepts:
      - Buffer                       -> whole buffer
      - BufferLoad without slices    -> base + extent from hint (reference's
                                        "element access as region base" sugar,
                                        cf. tilelang/utils/language.py
                                        to_buffer_region)
      - BufferLoad with slices       -> explicit slice region
      - Region                       -> itself
    """
    if isinstance(obj, Region):
        return obj
    if isinstance(obj, Buffer):
        return Region(obj, (0,) * obj.ndim, obj.shape)
    if isinstance(obj, BufferLoad):
        buf = obj.buffer
        if obj.has_slices:
            base, shape = [], []
            for d, i in enumerate(obj.indices):
                if isinstance(i, slice):
                    if i.step not in (None, 1):
                        raise ValueError("strided slice regions not supported")
                    start = 0 if i.start is None else i.start
                    stop = buf.shape[d] if i.stop is None else i.stop
                    base.append(start)
                    shape.append(convert(stop) - convert(start))
                else:
                    base.append(i)
                    shape.append(1)
            return Region(buf, base, shape)
        # element-access sugar: base indices, extent from hint clipped to rank
        if extent_hint is None:
            base = list(obj.indices)
            return Region(buf, base, (1,) * buf.ndim)
        hint = list(extent_hint)
        if len(hint) > buf.ndim:
            raise ValueError(
                f"extent hint rank {len(hint)} > buffer rank {buf.ndim}")
        # right-align the hint (leading dims get extent 1), matching the
        # reference's T.copy shape-broadcast behavior
        base = list(obj.indices)
        shape = [1] * (buf.ndim - len(hint)) + hint
        return Region(buf, base, shape)
    raise TypeError(f"cannot interpret {type(obj)} as a buffer region")
