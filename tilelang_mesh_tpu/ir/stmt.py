"""Tile-IR statements and the PrimFunc container.

Each tile operator is its own statement node implementing the reference's
TileOperator protocol surface (cf. /root/reference/src/op/operator.h:55 —
Lower / InferLayout / Clone); here lowering lives in
``tilelang_mesh_tpu.transform`` and ``codegen.pallas`` visitors instead of
virtual methods, which keeps the IR a plain data structure.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

from .buffer import Buffer, Region
from .expr import PrimExpr, Var, convert


class Stmt:
    #: DSL call site ("file", lineno) stamped by the trace builder
    #: (language/builder.py) so static-analysis diagnostics can point at
    #: the offending kernel line; None for IR built outside a trace.
    loc = None


class SeqStmt(Stmt):
    def __init__(self, stmts: Optional[List[Stmt]] = None):
        self.stmts: List[Stmt] = stmts if stmts is not None else []

    def __iter__(self):
        return iter(self.stmts)

    def __len__(self):
        return len(self.stmts)


class AllocStmt(Stmt):
    def __init__(self, buffer: Buffer):
        self.buffer = buffer


class KernelNode(Stmt):
    """The T.Kernel launch frame: grid vars + extents + body.

    Reference: tilelang/language/kernel.py:228 (KernelLaunchFrame). `threads`
    is kept for API parity; on TPU the intra-block parallelism is the VPU/MXU,
    so it only serves as an autotuner hint.
    """

    def __init__(self, grid_vars: List[Var], extents: List[int], threads: Any,
                 body: SeqStmt, prelude: Optional[List[Stmt]] = None):
        self.grid_vars = grid_vars
        self.extents = extents
        self.threads = threads
        self.body = body
        # statements traced before the kernel frame opened (rare)
        self.prelude = prelude or []


class ForNest(Stmt):
    """A (possibly multi-var) loop nest of a single kind.

    kinds: serial | unroll | parallel | pipelined | vectorized | persistent
    """

    def __init__(self, loop_vars: List[Var], extents: List[Any], kind: str,
                 body: SeqStmt, num_stages: int = 0,
                 annotations: Optional[dict] = None):
        self.loop_vars = loop_vars
        self.extents = extents
        self.kind = kind
        self.body = body
        self.num_stages = num_stages
        self.annotations = annotations or {}


class IfThenElse(Stmt):
    def __init__(self, cond: PrimExpr, then_body: SeqStmt,
                 else_body: Optional[SeqStmt] = None):
        self.cond = cond
        self.then_body = then_body
        self.else_body = else_body


class BufferStoreStmt(Stmt):
    def __init__(self, buffer: Buffer, indices: Tuple[Any, ...],
                 value: PrimExpr):
        self.buffer = buffer
        self.indices = indices
        self.value = value


class EvaluateStmt(Stmt):
    def __init__(self, expr: PrimExpr):
        self.expr = expr


# -- tile operators ----------------------------------------------------------


class CopyStmt(Stmt):
    """T.copy — cf. reference src/op/copy.cc. On TPU this lowers to a Pallas
    BlockSpec (pipelined HBM<->VMEM fetch handled by Mosaic) or an explicit
    VMEM assignment / async DMA."""

    def __init__(self, src: Region, dst: Region, coalesced_width=None,
                 disable_cache_hint: bool = False, eviction_policy=None):
        self.src = src
        self.dst = dst
        self.coalesced_width = coalesced_width


class AsyncCopyStmt(Stmt):
    """T.copy_async / T.copy_wait — explicit split-phase DMA with a user
    semaphore slot. The TPU-native form of the reference's warp-specialized
    producer/consumer overlap (src/transform/warp_specialized_rewriter.cc):
    instead of producer warps + mbarriers, the kernel issues the DMA early
    ("start") and blocks on its semaphore right before use ("wait")."""

    def __init__(self, src: Region, dst: Region, sem, slot, phase: str):
        assert phase in ("start", "wait")
        self.src = src
        self.dst = dst
        self.sem = sem          # the T.alloc_semaphore buffer
        self.slot = slot        # index into the semaphore array
        self.phase = phase


class GemmStmt(Stmt):
    """T.gemm — cf. reference src/op/gemm.cc. Lowers to one MXU dot
    (jnp.dot with f32 accumulation) instead of the CUTLASS template zoo."""

    def __init__(self, A: Region, B: Region, C: Region, trans_A: bool = False,
                 trans_B: bool = False, policy=None, clear_accum: bool = False,
                 k_pack: int = 1, wg_wait: int = 0):
        self.A = A
        self.B = B
        self.C = C
        self.trans_A = trans_A
        self.trans_B = trans_B
        self.policy = policy
        self.clear_accum = clear_accum


class FillStmt(Stmt):
    def __init__(self, dst: Region, value: PrimExpr):
        self.dst = dst
        self.value = convert(value)


class ReduceStmt(Stmt):
    """T.reduce_* — cf. reference src/op/reduce.cc. kinds: sum, max, min,
    abssum, absmax, bitand, bitor, bitxor, any, all."""

    def __init__(self, kind: str, src: Buffer, dst: Buffer, dim: int,
                 clear: bool = True):
        self.kind = kind
        self.src = src
        self.dst = dst
        self.dim = dim
        self.clear = clear


class CumSumStmt(Stmt):
    def __init__(self, src: Buffer, dst: Buffer, dim: int, reverse: bool):
        self.src = src
        self.dst = dst
        self.dim = dim
        self.reverse = reverse


class AtomicStmt(Stmt):
    """T.atomic_add and friends. TPU grids are sequential per-core, so an
    'atomic' accumulation into HBM lowers to a read-modify-write via
    input_output_aliasing; cf. reference src/op/atomic_add.cc."""

    def __init__(self, op: str, dst: Region, value: Any):
        self.op = op
        self.dst = dst
        self.value = value


class PrintStmt(Stmt):
    def __init__(self, obj: Any, msg: str = ""):
        self.obj = obj
        self.msg = msg


class AssertStmt(Stmt):
    def __init__(self, cond: PrimExpr, msg: str = ""):
        self.cond = cond
        self.msg = msg


# -- mesh communication operators (cf. reference src/op/comm.cc) -------------


class CommStmt(Stmt):
    """Base for inter-core communication ops (the Mesh extension)."""


class CommBroadcast(CommStmt):
    def __init__(self, src: Region, dst: Region, size: int, dst_offset: int,
                 src_core: int, direction: int):
        self.src = src
        self.dst = dst
        self.size = size
        self.dst_offset = dst_offset
        self.src_core = src_core
        self.direction = direction  # 0=h, 1=v, 2=all


class CommPut(CommStmt):
    def __init__(self, src: Region, dst: Region, size: int, src_core: int,
                 dst_core: int):
        self.src = src
        self.dst = dst
        self.size = size
        self.src_core = src_core
        self.dst_core = dst_core


class CommAllGather(CommStmt):
    def __init__(self, send: Region, recv: Region, direction: int, size: int):
        self.send = send
        self.recv = recv
        self.direction = direction
        self.size = size


class CommAllReduce(CommStmt):
    def __init__(self, buffer: Region, out: Region, reduce_type: str,
                 direction: int, dim: int, clear: bool):
        self.buffer = buffer
        self.out = out
        self.reduce_type = reduce_type
        self.direction = direction
        self.dim = dim
        self.clear = clear


class CommBarrier(CommStmt):
    def __init__(self, group: Optional[List[int]] = None):
        self.group = group


class CommFused(CommStmt):
    """N same-kind / same-axis collectives batched into ONE mesh op over
    their concatenated payloads (transform/comm_opt.py fusion rewrite).

    ``slots[i]`` is the payload slot member ``ops[i]`` reads from:
    byte-identical members share a slot, so each distinct payload crosses
    the wire exactly once and is fanned out to every member destination.
    ``dropped`` holds exact-duplicate ops the rewrite deleted outright;
    they execute as nothing but stay here so pre-optimization accounting
    per record matches the program-level totals. A single-member fused op
    is legal exactly when it carries drops (the dedup survivor)."""

    def __init__(self, ops: List["CommStmt"], slots: List[int],
                 dropped: Optional[List["CommStmt"]] = None):
        assert len(ops) == len(slots) and len(ops) >= 1
        self.ops = list(ops)
        self.slots = list(slots)
        self.dropped = list(dropped or [])

    @property
    def kind(self):
        return type(self.ops[0])

    @property
    def direction(self) -> int:
        return getattr(self.ops[0], "direction", 2)

    @property
    def n_slots(self) -> int:
        return len(set(self.slots))


class CommChunked(CommStmt):
    """A collective split into ``chunks`` equal leading-axis chunks
    issued as independent ops (transform/comm_opt.py overlap rewrite), so
    the ICI transfer of chunk i+1 can overlap the consumer segment's
    compute on chunk i — the double-buffered ring schedule of the
    reference's tile-level comm pipelining."""

    def __init__(self, op: "CommStmt", chunks: int):
        assert chunks >= 2
        self.op = op
        self.chunks = chunks

    @property
    def direction(self) -> int:
        return getattr(self.op, "direction", 2)


class CommFence(CommStmt):
    pass


# ---------------------------------------------------------------------------


class PrimFunc:
    """A traced tile kernel: params + body + attrs."""

    def __init__(self, name: str, params: List[Any], body: SeqStmt,
                 attrs: Optional[dict] = None):
        self.name = name
        self.params = params  # Buffers (tensor args) and Vars (dyn shapes)
        self.body = body
        self.attrs = attrs or {}

    @property
    def buffer_params(self) -> List[Buffer]:
        return [p for p in self.params if isinstance(p, Buffer)]

    @property
    def dyn_params(self) -> List[Var]:
        return [p for p in self.params if isinstance(p, Var)]

    def script(self) -> str:
        from .printer import func_str
        return func_str(self)

    def kernel_node(self) -> Optional[KernelNode]:
        for s in self.body:
            if isinstance(s, KernelNode):
                return s
        return None

    def __repr__(self):
        return self.script()


def walk(stmt: Stmt, fn):
    """Pre-order visit of every statement, including the member ops of
    post-optimizer composites (CommFused/CommChunked) so a checker written
    against the leaf CommStmt types cannot silently skip a rewritten op."""
    fn(stmt)
    children = []
    if isinstance(stmt, SeqStmt):
        children = stmt.stmts
    elif isinstance(stmt, KernelNode):
        children = list(stmt.prelude) + [stmt.body]
    elif isinstance(stmt, ForNest):
        children = [stmt.body]
    elif isinstance(stmt, IfThenElse):
        children = [stmt.then_body] + ([stmt.else_body] if stmt.else_body
                                       else [])
    elif isinstance(stmt, CommFused):
        children = list(stmt.ops)
    elif isinstance(stmt, CommChunked):
        children = [stmt.op]
    for c in children:
        walk(c, fn)


def collect(stmt: Stmt, pred) -> List[Stmt]:
    out = []
    walk(stmt, lambda s: out.append(s) if pred(s) else None)
    return out
