"""Mesh-sharded decode workload + the elastic layout ladder.

This is the mesh-backed consumer of the ``serving/shard.py`` hooks
(ROADMAP item 1's open remainder): a :class:`MeshDecodeWorkload`
dispatches the shape-bucketed decode step through ``shard_map`` over a
2-D host device mesh, deriving its ``in_specs`` from a
:class:`~.shard.ServeShardConfig` layout (``head_parallel`` /
``batch_parallel``) via :func:`~.shard.match_partition_rules` — the
SNIPPETS.md [1]/[2] idioms the rule tables were staged for.

The robustness contract is the product: **losing a mesh slice
mid-decode degrades capacity, never correctness.** Each workload
carries a layout *ladder* (``TL_TPU_SERVE_LAYOUTS``, default
``head_parallel:2x2 -> head_parallel:2x1 -> no_sharding``); when a
sharded step dies with a :class:`DeviceLossError` or a
collective-watchdog timeout, the engine walks one rung down: the
surviving KV slabs are snapshot/checksummed (``kv_cache.KVSnapshot``),
the lost slice is quarantined in the PR 6 backend registry
(``registry().quarantine_device``), the workload rebuilds its mesh +
specs on the next rung, and the KV state migrates byte-conserved into
the new placement. The terminal ``no_sharding`` rung is the PR 8
single-host path through the crash-safe kernel cache, so the ladder
always bottoms out on a layout that needs no mesh at all.

Layout validation happens at workload build, not deep inside XLA: head
and batch-bucket counts must divide the sharded axis size, every axis
name in the config must exist on the concrete mesh, and the mesh must
have enough non-quarantined host devices — violations raise
:class:`~..verify.schedule.MeshVerifyError` naming the offending
dimension.

Observability: sharded steps land in the shared
``kernel.latency{kernel=serve.step}`` histogram like every step; a
sampled *straggler probe* (``TL_TPU_SERVE_SHARD_PROBE_EVERY``) times a
tiny per-device dispatch into per-shard
``serve.shard.latency{shard=x0y1}`` histograms and feeds the
``shard_skew`` gauge, so a slow shard is visible before it is dead.
``serve.shard`` is the fault site on the sharded dispatch (armed
``kind=unreachable`` = a mesh slice dying mid-step; the
``--serve-mesh`` chaos soak kills exactly one).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..env import env
from ..observability import histogram as _hist
from ..observability import meshscope as _meshscope
from ..observability import tracer as _trace
from ..resilience import faults as _faults
from .batcher import FlashDecodeWorkload
from .kv_cache import PagedKVAllocator
from .shard import ServeShardConfig, match_partition_rules

__all__ = ["MeshLayout", "MeshDecodeWorkload", "layout_ladder",
           "parse_layout", "validate_shard_config", "LAYOUT_KINDS"]

LAYOUT_KINDS = ("head_parallel", "batch_parallel", "no_sharding")

# the engine tensor names the partition-rule table is matched against,
# in dispatch argument order (q, kp, vp, table) + the step output
_IN_NAMES = ("step/q", "kv/k_pool", "kv/v_pool", "kv/page_table")
_OUT_NAME = "step/out"


def _verify_error(msg: str):
    from ..verify.schedule import MeshVerifyError
    return MeshVerifyError(msg)


@dataclasses.dataclass(frozen=True)
class MeshLayout:
    """One rung of the elastic layout ladder."""

    kind: str                          # one of LAYOUT_KINDS
    rows: int = 1
    cols: int = 1

    @property
    def name(self) -> str:
        if self.kind == "no_sharding":
            return "no_sharding"
        return f"{self.kind}:{self.rows}x{self.cols}"

    @property
    def sharded(self) -> bool:
        return self.kind != "no_sharding"

    @property
    def devices(self) -> int:
        return self.rows * self.cols if self.sharded else 1

    def shard_config(self) -> ServeShardConfig:
        if self.kind == "head_parallel":
            return ServeShardConfig.head_parallel("x")
        if self.kind == "batch_parallel":
            return ServeShardConfig.batch_parallel("x")
        return ServeShardConfig.no_sharding()


def parse_layout(token: str) -> MeshLayout:
    """``head_parallel:2x2`` / ``batch_parallel:1x4`` / ``no_sharding``
    -> :class:`MeshLayout`. Raises ``ValueError`` on a malformed token
    (a typo'd ladder must not silently serve unsharded)."""
    token = token.strip()
    if not token:
        raise ValueError("empty layout token")
    kind, _, shape = token.partition(":")
    kind = kind.strip()
    if kind not in LAYOUT_KINDS:
        raise ValueError(
            f"unknown serve layout kind {kind!r} (one of {LAYOUT_KINDS})")
    if kind == "no_sharding":
        if shape:
            raise ValueError(
                f"no_sharding takes no mesh shape, got {token!r}")
        return MeshLayout("no_sharding")
    try:
        r, c = (int(x) for x in shape.lower().split("x"))
    except Exception:
        raise ValueError(
            f"layout {token!r}: mesh shape must be RxC (e.g. 2x2)"
        ) from None
    if r < 1 or c < 1:
        raise ValueError(f"layout {token!r}: mesh dims must be >= 1")
    return MeshLayout(kind, r, c)


def layout_ladder(spec: Optional[str] = None) -> List[MeshLayout]:
    """The ordered degradation ladder from ``spec`` (default
    ``TL_TPU_SERVE_LAYOUTS``). A ladder without a terminal
    ``no_sharding`` rung gets one appended: capacity degradation must
    always bottom out on a layout that cannot lose a slice."""
    spec = spec if spec is not None else env.TL_TPU_SERVE_LAYOUTS
    rungs = [parse_layout(t) for t in spec.split(",") if t.strip()]
    if not rungs:
        raise ValueError("TL_TPU_SERVE_LAYOUTS parsed to an empty ladder")
    if rungs[-1].kind != "no_sharding":
        rungs.append(MeshLayout("no_sharding"))
    return rungs


def _spec_axes(spec) -> List[Tuple[int, Tuple[str, ...]]]:
    """(dim index, axis names) per sharded dim of one PartitionSpec."""
    out = []
    for dim, entry in enumerate(tuple(spec)):
        if entry is None:
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        out.append((dim, tuple(str(n) for n in names)))
    return out


def validate_shard_config(cfg: ServeShardConfig, layout: MeshLayout, *,
                          heads: int,
                          batch_buckets: Sequence[int]) -> None:
    """Validate a shard config against the CONCRETE mesh a layout will
    build, at workload-build time: unknown mesh axis names and
    non-divisible head/batch counts raise a named ``MeshVerifyError``
    here instead of letting ``shard_map`` fail deep inside XLA."""
    if not layout.sharded:
        return
    axis_sizes = {"x": layout.rows, "y": layout.cols}

    def shard_factor(spec, dim: int) -> int:
        f = 1
        for d, names in _spec_axes(spec):
            for n in names:
                if n not in axis_sizes:
                    raise _verify_error(
                        f"serve layout {layout.name}: shard config "
                        f"names mesh axis {n!r}, but the "
                        f"{layout.rows}x{layout.cols} mesh has axes "
                        f"{tuple(axis_sizes)}")
                if d == dim:
                    f *= axis_sizes[n]
        return f

    # walk EVERY spec so an unknown axis anywhere is rejected, then
    # check the divisibility that matters per tensor
    for field in ("kv_pool_hrd", "query_bhld", "table_bp", "out_bhld"):
        shard_factor(getattr(cfg, field), -1)
    hf = shard_factor(cfg.kv_pool_hrd, 0)
    if hf > 1 and heads % hf:
        raise _verify_error(
            f"serve layout {layout.name}: {heads} head(s) not divisible "
            f"by the sharded head-axis size {hf}")
    qh = shard_factor(cfg.query_bhld, 1)
    if qh > 1 and heads % qh:
        raise _verify_error(
            f"serve layout {layout.name}: {heads} query head(s) not "
            f"divisible by the sharded head-axis size {qh}")
    bf = max(shard_factor(cfg.query_bhld, 0), shard_factor(cfg.table_bp, 0))
    if bf > 1:
        bad = [b for b in batch_buckets if b % bf]
        if bad:
            raise _verify_error(
                f"serve layout {layout.name}: batch bucket(s) {bad} not "
                f"divisible by the sharded batch-axis size {bf}")


class MeshDecodeWorkload(FlashDecodeWorkload):
    """Flash-decode workload dispatched through ``shard_map`` over a
    2-D host device mesh, with an elastic layout ladder.

    The sharded rungs run the decode math as one SPMD program per
    (batch, pages) bucket: each device holds its head (or batch) shard
    of the H-major pools and computes its slice of the step; the
    ``no_sharding`` terminal rung delegates to the single-host
    ``flash_decode_paged_pool`` path (built through the crash-safe
    kernel cache, exactly the PR 8 engine path). ``warmup()`` AOT
    compiles + dispatches every bucket ON THE CURRENT RUNG; a layout
    change clears the warm set so the next warm-up covers the new
    layout.

    The pools stay host-side numpy (tokens append in place between
    steps), so every sharded step re-feeds them to the compiled SPMD
    executable — the per-step upload is the price of in-place appends,
    and the CPU-mesh smoke measures it honestly.
    """

    elastic = True

    def __init__(self, allocator: PagedKVAllocator, *,
                 layouts: Union[str, Sequence[MeshLayout], None] = None,
                 shard_config: Optional[ServeShardConfig] = None,
                 batch_buckets: Sequence[int] = (1, 2, 4, 8),
                 page_buckets: Sequence[int] = (2, 4),
                 sm_scale: Optional[float] = None):
        super().__init__(allocator, batch_buckets=batch_buckets,
                         page_buckets=page_buckets, sm_scale=sm_scale)
        if isinstance(layouts, str) or layouts is None:
            self.ladder = layout_ladder(layouts)
        else:
            self.ladder = list(layouts)
            if not self.ladder:
                raise ValueError("layout ladder must be non-empty")
            if self.ladder[-1].kind != "no_sharding":
                self.ladder.append(MeshLayout("no_sharding"))
        self._shard_config_override = shard_config
        self._rung = -1
        self.mesh = None
        self._in_specs: Optional[tuple] = None
        self._out_spec = None
        self._fns: Dict[tuple, object] = {}
        self._apply_rung(0)

    # -- layout ladder -------------------------------------------------
    @property
    def layout(self) -> MeshLayout:
        return self.ladder[self._rung]

    def can_degrade(self) -> bool:
        return self._rung + 1 < len(self.ladder)

    def _config_for(self, layout: MeshLayout) -> ServeShardConfig:
        if layout.sharded and self._shard_config_override is not None:
            return self._shard_config_override
        return layout.shard_config()

    def _apply_rung(self, rung: int,
                    exclude: Sequence[str] = ()) -> None:
        layout = self.ladder[rung]
        cfg = self._config_for(layout)
        validate_shard_config(cfg, layout, heads=self.allocator.heads,
                              batch_buckets=self.batch_buckets)
        if layout.sharded:
            from ..parallel.device_mesh import make_host_mesh
            try:
                mesh = make_host_mesh(layout.rows, layout.cols,
                                      exclude=exclude)
            except ValueError as e:
                raise _verify_error(
                    f"serve layout {layout.name}: {e}") from e
            specs = match_partition_rules(cfg.rules(), _IN_NAMES)
            out_spec = match_partition_rules(cfg.rules(), [_OUT_NAME])[0]
        else:
            mesh, specs, out_spec = None, None, None
        self.mesh = mesh
        self._in_specs = tuple(specs) if specs is not None else None
        self._out_spec = out_spec
        self._rung = rung
        self._fns.clear()            # per-layout SPMD programs
        self._warm.clear()            # buckets re-warm per layout

    def degrade(self, exclude: Sequence[str] = ()) -> MeshLayout:
        """Step down the ladder: apply the next rung that can build on
        the surviving (non-excluded) devices. Rungs that cannot build
        are skipped with a traced event; ``no_sharding`` always builds.
        Raises when the ladder is spent."""
        rung = self._rung + 1
        while rung < len(self.ladder):
            try:
                self._apply_rung(rung, exclude=exclude)
                return self.layout
            except Exception as e:  # noqa: BLE001 — rung skipped, traced
                if rung == len(self.ladder) - 1:
                    raise
                _trace.event("serve.layout_skipped", "serving",
                             layout=self.ladder[rung].name,
                             error=f"{type(e).__name__}: {e}")
                rung += 1
        raise _verify_error("serve layout ladder is spent")

    def make_allocator(self) -> PagedKVAllocator:
        """A fresh allocator with this workload's geometry — the
        migration target a reshard restores the KV snapshot into."""
        a = self.allocator
        return PagedKVAllocator(a.n_pages, a.page_size, a.heads,
                                a.head_dim, dtype=str(a.dtype))

    def install_allocator(self, alloc: PagedKVAllocator) -> None:
        """Swap in the migrated allocator (after a successful
        ``restore``; the engine rewrites request page ids)."""
        self.allocator = alloc

    # -- sharded dispatch ----------------------------------------------
    def _dispatch(self, q, table, bb: int, pp: int):
        layout = self.layout
        if not layout.sharded:
            return super()._dispatch(q, table, bb, pp)
        _faults.maybe_fail("serve.shard", layout=layout.name,
                           batch=bb, pages=pp)
        fn = self._fns.get((bb, pp))
        if fn is None:
            fn = self._build_sharded_fn(bb, pp)
            self._fns[(bb, pp)] = fn
        out = fn(np.asarray(q, np.float32), self.allocator.kp,
                 self.allocator.vp, np.asarray(table, np.int32))
        return np.asarray(out)

    def _build_sharded_fn(self, bb: int, pp: int):
        """One jitted ``shard_map`` SPMD program for this bucket on the
        current mesh + specs: every device computes plain decode
        attention over ITS head/batch shard of the pools (table-driven
        page walk, softmax over the full ``pp`` page window — the same
        math ``flash_decode_paged_pool`` runs single-host)."""
        import jax
        import jax.numpy as jnp
        from ..parallel.device_mesh import shard_map_compat

        ps = self.allocator.page_size
        scale = self.sm_scale

        def local_step(q, kp, vp, table):
            # q (b, h, 1, D) / kp, vp (h, rows, D) / table (b, PP) —
            # shapes are the per-device shards under the layout's specs
            b, ppl = table.shape
            idx = (table[:, :, None] * ps
                   + jnp.arange(ps)[None, None, :]).reshape(b, ppl * ps)
            h, _, d = kp.shape
            k = jnp.take(kp, idx.reshape(-1), axis=1
                         ).reshape(h, b, ppl * ps, d)
            v = jnp.take(vp, idx.reshape(-1), axis=1
                         ).reshape(h, b, ppl * ps, d)
            s = jnp.einsum("bhqd,hbsd->bhqs", q, k) * scale
            w = jax.nn.softmax(s, axis=-1)
            return jnp.einsum("bhqs,hbsd->bhqd", w, v)

        spmd = shard_map_compat(local_step, self.mesh,
                                self._in_specs, self._out_spec)
        return jax.jit(spmd)

    # -- straggler probe -----------------------------------------------
    def shard_names(self) -> List[str]:
        if self.mesh is None:
            return []
        return [f"x{i}y{j}"
                for (i, j), _ in np.ndenumerate(self.mesh.devices)]

    def probe_shards(self) -> Optional[float]:
        """Time one tiny dispatch per mesh device into the per-shard
        ``serve.shard.latency{shard=}`` histograms; returns the skew
        ratio (slowest/fastest probe this sweep, >= 1.0) the engine
        publishes as the ``shard_skew`` gauge. A straggling slice shows
        up here while it is still answering — before it is dead."""
        if self.mesh is None:
            return None
        import jax
        payload = np.ones((8, 8), np.float32)
        times = {}
        for (i, j), dev in np.ndenumerate(self.mesh.devices):
            t0 = time.perf_counter()
            jax.device_put(payload, dev).block_until_ready()
            dt = time.perf_counter() - t0
            name = f"x{i}y{j}"
            _hist.observe("serve.shard.latency", dt, shard=name)
            times[name] = dt
        # tl-mesh-scope: the same sweep feeds the per-core EWMA+MAD
        # straggler baseline (a sustained slow shard fires mesh.skew +
        # a flight dump naming the core and its links)
        if _meshscope.mesh_scope_enabled():
            _meshscope.observe_shards(times, probe="serve.shard")
        fastest = min(times.values())
        skew = (max(times.values()) / fastest) if fastest > 0 else 1.0
        return max(skew, 1.0)

    def probe_lost(self, timeout_s: float = 0.25) -> List[str]:
        """Bounded per-device liveness sweep after a sharded-step
        failure: each mesh device gets one tiny dispatch on an
        abandoned-on-timeout daemon thread (a dead device HANGS jax
        calls rather than erroring — same idiom as the PR 6 probes);
        devices that hang or raise are presumed lost. Injected losses
        leave every host device answering, so an empty result is the
        common chaos-soak outcome."""
        if self.mesh is None:
            return []
        import jax

        from ..codegen.backends import _bounded
        payload = np.ones((4,), np.float32)
        dead: List[str] = []
        for dev in self.mesh.devices.flat:
            def _probe(d=dev):
                jax.device_put(payload, d).block_until_ready()
            try:
                _bounded(_probe, f"shard {dev} probe", timeout_s)
            except Exception:  # noqa: BLE001 — hang or raise = lost
                dead.append(str(dev))
        return dead

    # -- accounting ----------------------------------------------------
    def layout_stats(self) -> dict:
        return {
            "layout": self.layout.name,
            "rung": self._rung,
            "ladder": [r.name for r in self.ladder],
            "mesh_devices": ([str(d) for d in self.mesh.devices.flat]
                             if self.mesh is not None else []),
        }
