"""Frame protocol for the process-isolated fleet (fleet-proc).

Every message between the fleet supervisor and a subprocess engine
worker (serving/worker.py) is ONE length-prefixed, checksummed frame::

    MAGIC  b"TLF1"
    u32    payload length          (little-endian)
    u32    crc32(payload)
    payload = u32 header length | header JSON (utf-8) | binary body

The JSON header carries the RPC op and its scalar arguments; the body
carries bulk bytes (KV pages). The crc makes a torn or bit-flipped
frame a *detected* failure (:class:`FrameError`, ``deterministic`` in
the TLError taxonomy) instead of a silent desync, and the length cap
(``TL_TPU_FLEET_MAX_FRAME_MB``) rejects an adversarial/corrupt length
prefix before allocating. The pipe itself (``multiprocessing``
``Connection``) is message-oriented, so one bad frame never shifts the
boundary of the next — the supervisor classifies, ejects the worker,
and keeps serving.

Request and KVSnapshot wire formats live here too, so the fleet's
export/adopt failover and the prefix-tier warm restores cross the
process boundary in exactly the byte-conserved, checksummed shapes the
in-process paths already audit: ``encode_snapshot`` ships an
allocator's pages as raw little-endian bytes under the snapshot's own
sha256 (``KVSnapshot.verify`` re-checks it on the far side), and
``serialize_request``/``deserialize_request`` round-trip a live
request bit-exactly (prompt token ids, sampled tokens, tenant tag,
sampling knobs — the fleet-proc test suite gates on equality).
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Dict, Optional, Tuple

import numpy as np

from ..env import env
from ..resilience.errors import TLError

__all__ = ["MAGIC", "FrameError", "encode_frame", "decode_frame",
           "max_frame_bytes", "encode_snapshot", "decode_snapshot",
           "serialize_request", "deserialize_request"]

MAGIC = b"TLF1"
_PREFIX = struct.Struct("<II")        # payload length, crc32(payload)
_HLEN = struct.Struct("<I")           # header length inside the payload


class FrameError(TLError):
    """A frame failed validation (bad magic, oversized or short length,
    checksum mismatch, unparsable header). Deterministic: resending the
    same bytes cannot help — the supervisor ejects the worker and lets
    the restart probe re-establish the channel."""
    kind = "deterministic"

    def __init__(self, message: str):
        super().__init__(message, site="fleet.ipc")


def max_frame_bytes() -> int:
    return max(1, int(env.TL_TPU_FLEET_MAX_FRAME_MB)) << 20


def encode_frame(header: dict, body: bytes = b"") -> bytes:
    hjson = json.dumps(header, separators=(",", ":")).encode("utf-8")
    payload = _HLEN.pack(len(hjson)) + hjson + bytes(body)
    return MAGIC + _PREFIX.pack(len(payload), zlib.crc32(payload)) \
        + payload


def decode_frame(data: bytes) -> Tuple[dict, bytes]:
    """Validate and split one frame into ``(header, body)``. Raises
    :class:`FrameError` on every way a frame can be wrong; never
    allocates for a length the cap rejects."""
    data = bytes(data)
    head = len(MAGIC) + _PREFIX.size
    if len(data) < head:
        raise FrameError(f"truncated frame: {len(data)} byte(s), "
                         f"need >= {head} for the prefix")
    if data[:len(MAGIC)] != MAGIC:
        raise FrameError(f"bad magic {data[:len(MAGIC)]!r} "
                         f"(want {MAGIC!r})")
    length, crc = _PREFIX.unpack_from(data, len(MAGIC))
    if length > max_frame_bytes():
        raise FrameError(f"oversized length prefix {length} "
                         f"(cap {max_frame_bytes()} bytes)")
    payload = data[head:]
    if len(payload) != length:
        raise FrameError(f"length mismatch: prefix says {length}, "
                         f"payload has {len(payload)} byte(s)")
    if zlib.crc32(payload) != crc:
        raise FrameError("checksum mismatch: frame corrupted in "
                         "transit (torn write or bit flip)")
    if length < _HLEN.size:
        raise FrameError(f"payload too short for a header length "
                         f"({length} byte(s))")
    (hlen,) = _HLEN.unpack_from(payload, 0)
    if _HLEN.size + hlen > length:
        raise FrameError(f"header length {hlen} overruns the payload "
                         f"({length} byte(s))")
    try:
        header = json.loads(payload[_HLEN.size:_HLEN.size + hlen]
                            .decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise FrameError(f"unparsable frame header: {e}") from None
    if not isinstance(header, dict):
        raise FrameError(f"frame header is {type(header).__name__}, "
                         f"not an object")
    return header, payload[_HLEN.size + hlen:]


# -- KVSnapshot wire format ------------------------------------------------
def encode_snapshot(snap) -> bytes:
    """One frame holding a whole :class:`~.kv_cache.KVSnapshot`: the
    header carries geometry + owners + the snapshot's own sha256, the
    body the pages' K then V bytes in sorted page order. The snapshot
    format stays byte-conserved: ``decode_snapshot`` re-verifies the
    sha256 over exactly the bytes that crossed the pipe."""
    pages = sorted(snap.pages)
    chunks = []
    for p in pages:
        k, v = snap.pages[p]
        chunks.append(np.ascontiguousarray(k).tobytes())
        chunks.append(np.ascontiguousarray(v).tobytes())
    header = {
        "kind": "kv_snapshot",
        "page_size": snap.page_size,
        "heads": snap.heads,
        "head_dim": snap.head_dim,
        "dtype": np.dtype(snap.dtype).str,
        "owners": {str(o): list(ps) for o, ps in snap.owners.items()},
        "pages": pages,
        "checksum": snap.checksum,
        "nbytes": snap.nbytes,
    }
    return encode_frame(header, b"".join(chunks))


def decode_snapshot(frame: bytes):
    """Decode + checksum-verify a snapshot frame back into a
    :class:`~.kv_cache.KVSnapshot` (fresh, unconsumed). Raises
    :class:`FrameError` if the page bytes do not hash to the shipped
    checksum — a corrupt restore must never reach an allocator."""
    from .kv_cache import KVSnapshot
    header, body = decode_frame(frame)
    if header.get("kind") != "kv_snapshot":
        raise FrameError(f"not a kv_snapshot frame: "
                         f"kind={header.get('kind')!r}")
    dtype = np.dtype(header["dtype"])
    shape = (int(header["heads"]), int(header["page_size"]),
             int(header["head_dim"]))
    per = int(np.prod(shape)) * dtype.itemsize
    page_ids = [int(p) for p in header["pages"]]
    if len(body) != 2 * per * len(page_ids):
        raise FrameError(
            f"snapshot body has {len(body)} byte(s), geometry wants "
            f"{2 * per * len(page_ids)} for {len(page_ids)} page(s)")
    pages: Dict[int, tuple] = {}
    off = 0
    for p in page_ids:
        k = np.frombuffer(body, dtype, count=per // dtype.itemsize,
                          offset=off).reshape(shape).copy()
        off += per
        v = np.frombuffer(body, dtype, count=per // dtype.itemsize,
                          offset=off).reshape(shape).copy()
        off += per
        pages[p] = (k, v)
    snap = KVSnapshot(
        page_size=int(header["page_size"]), heads=int(header["heads"]),
        head_dim=int(header["head_dim"]), dtype=dtype,
        owners={int(o): [int(p) for p in ps]
                for o, ps in header["owners"].items()},
        pages=pages, checksum=str(header["checksum"]),
        nbytes=int(header["nbytes"]))
    try:
        snap.verify()
    except ValueError as e:
        raise FrameError(f"snapshot failed checksum after transport: "
                         f"{e}") from None
    return snap


# -- Request wire format ---------------------------------------------------
def serialize_request(req, cid: int,
                      now: Optional[float] = None) -> dict:
    """The JSON-safe image of one live request the supervisor ships to
    a worker (submit, adopt). ``cid`` is the supervisor-side
    correlation id; the deadline travels as *remaining* milliseconds so
    it survives a clock domain it cannot compare against."""
    remaining = req.remaining_s(now)
    return {
        "cid": int(cid),
        "context_tokens": req.context_tokens,
        "new_tokens": req.new_tokens,
        "deadline_ms": (None if remaining is None
                        else max(0.0, remaining * 1e3)),
        "seed": req.seed,
        "payload": dict(req.payload),
        "prompt_tokens": [int(t) for t in req.prompt_tokens],
        "temperature": req.temperature,
        "top_p": req.top_p,
        "tenant": req.tenant,
        "steps_done": req.steps_done,
        "retries": req.retries,
        "generated": [int(t) for t in req.generated],
        "trace_id": req.trace_id,
    }


def deserialize_request(d: dict):
    """Rebuild a :class:`~.request.Request` from its wire image (the
    worker side of submit/adopt). Progress fields (``steps_done``,
    ``generated``, ``retries``) are restored so ``adopt()`` replays
    sampled tokens content-derived, exactly as the in-process failover
    does; the origin trace id rides in ``payload`` for post-mortems."""
    from .request import Request
    req = Request(int(d["context_tokens"]), int(d["new_tokens"]),
                  deadline_ms=d.get("deadline_ms"),
                  seed=int(d.get("seed", 0)),
                  payload=dict(d.get("payload") or {}),
                  prompt_tokens=[int(t) for t in d["prompt_tokens"]],
                  temperature=float(d.get("temperature", 0.0)),
                  top_p=float(d.get("top_p", 1.0)),
                  tenant=d.get("tenant"))
    req.steps_done = int(d.get("steps_done", 0))
    req.retries = int(d.get("retries", 0))
    req.generated = [int(t) for t in d.get("generated", [])]
    origin = d.get("trace_id")
    if origin:
        req.payload.setdefault("origin_trace_id", origin)
    return req
