"""Temperature / top-p token sampling for the serving front-end.

The decode step produces an attention output; the workload projects it
onto a logit vector (``DecodeWorkload._logits``) and this module turns
logits into ONE token id. ``temperature=0`` is greedy argmax (the
deterministic default every existing test and soak relies on);
``temperature>0`` scales the logits and samples the softmax, optionally
truncated to the top-p nucleus — the smallest logit set whose
probability mass reaches ``top_p``, renormalized.

Everything is pure and seeded: the engine passes a
``numpy.random.Generator`` derived from ``(request seed, step)``, so a
sampled continuation is reproducible bit-for-bit — which is what makes
the prefix-cache equality tests (restored-prefix decode == cold-prefill
decode, sampled tokens included) possible at all.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["sample_token", "softmax", "top_p_filter"]


def softmax(logits: np.ndarray) -> np.ndarray:
    """Numerically-safe softmax (the online-softmax idiom: subtract the
    max, clamp the normalizer — a fully-underflowed row must yield a
    uniform distribution, never NaN)."""
    x = np.asarray(logits, np.float64)
    x = x - np.max(x)
    e = np.exp(x)
    z = float(e.sum())
    if not np.isfinite(z) or z <= 0.0:
        return np.full(x.shape, 1.0 / x.size)
    return e / z


def top_p_filter(probs: np.ndarray, top_p: float) -> np.ndarray:
    """Zero out everything outside the top-p nucleus and renormalize.
    The nucleus is the smallest probability-sorted set whose cumulative
    mass reaches ``top_p`` (the element crossing the threshold is kept,
    per the standard definition — ``top_p=0`` degenerates to argmax)."""
    if top_p >= 1.0:
        return probs
    order = np.argsort(-probs, kind="stable")
    csum = np.cumsum(probs[order])
    # keep every element up to AND INCLUDING the one crossing top_p
    cut = int(np.searchsorted(csum, max(top_p, 0.0)) + 1)
    keep = order[:max(cut, 1)]
    out = np.zeros_like(probs)
    out[keep] = probs[keep]
    z = float(out.sum())
    return out / z if z > 0 else np.full(probs.shape, 1.0 / probs.size)


def sample_token(logits: np.ndarray, *, temperature: float = 0.0,
                 top_p: float = 1.0,
                 rng: Optional[np.random.Generator] = None) -> int:
    """One token id from a logit vector. ``temperature<=0`` = greedy
    argmax (no rng consumed); otherwise softmax(logits/T) restricted to
    the top-p nucleus, sampled with ``rng``."""
    logits = np.asarray(logits, np.float64).ravel()
    if logits.size == 0:
        raise ValueError("cannot sample from an empty logit vector")
    if not (0.0 < top_p <= 1.0):
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if temperature <= 0.0:
        return int(np.argmax(logits))
    probs = top_p_filter(softmax(logits / temperature), top_p)
    if rng is None:
        rng = np.random.default_rng()
    return int(rng.choice(probs.size, p=probs))
