"""Shape-bucketed continuous batching over the decode op library.

XLA wants static shapes, so the batcher quantizes every dispatch onto a
small grid of precompiled kernels:

- **batch buckets** — a batch of ``n`` live requests pads up to the
  smallest configured bucket ``B >= n`` (padding rows replicate the
  last request's page table; their outputs are discarded), so one
  kernel per bucket serves every batch size;
- **page buckets** — requests are grouped by their *attention window*
  in whole pages (``(context + generated) // page_size``); a window
  larger than the biggest configured bucket attends over the most
  recent ``max_bucket`` pages (a sliding suffix window). Ragged batches
  never share a kernel with the wrong sequence length — the page count
  IS the bucket key.

Two workload families over the ops library (the serving consumers of
``ops/flash_decoding.py`` and ``ops/mla.py``):

- :class:`FlashDecodeWorkload` — in-kernel page walking
  (``flash_decode_paged_pool``) over the allocator's H-major pools; no
  gather pass touches the KV data.
- :class:`MLADecodeWorkload` — latent-attention decode: pages hold
  ``[ckv | kpe]`` rows, gathered to contiguous form at the host level
  (the gather strategy) and fed to ``mla_decode``.

``warmup()`` runs every (batch, pages) bucket once through the
crash-safe kernel cache AND through a real dispatch, so the first
serving request never pays trace/compile latency (the AOT warm store
from ROADMAP item 1). It also consults the **fleet tune cache**
(autotuner/tune_cache.py; docs/autotuning.md) for each bucket: a tuned
kernel config recorded by any fleet member — an offline sweep
(``tools/serve_sweep.py``), another serving process, a merged cache dir
— is adopted with ZERO measurements (the zero-cold-start bucket-config
path), and ``record_bucket_tuning`` is how an offline tuner publishes
one.

Full-lifecycle additions (docs/serving.md "Full-lifecycle serving"):

- **Chunked prefill** — ``ingest()`` fills at most ONE chunk
  (``TL_TPU_SERVE_PREFILL_CHUNK`` tokens) of the prompt's KV
  synchronously; the rest is schedulable work the engine drives via
  ``prefill_chunk()`` between decode steps, so a long prompt can never
  stall decode p99. KV content is a pure function of ``(token id,
  position)`` — the property the prefix cache's content addressing
  rests on.
- **Prefix reuse** — ``ingest()`` first asks the
  :mod:`.prefix_cache` for the longest cached whole-page prefix of the
  prompt and restores it through the allocator's checksummed
  ``restore()`` (PR 9's snapshot machinery); a completed prefill
  publishes its whole-page prefix back.
- **Sampling** — ``sample()`` projects the decode output onto a logit
  vector and draws one token id (temperature/top-p,
  :mod:`.sampling`); the sampled token's KV is what ``append_token``
  writes, so generated continuations are content-consistent too.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..env import env
from ..observability import tracer as _trace
from .kv_cache import PagedKVAllocator
from .request import Request

__all__ = ["DecodeWorkload", "FlashDecodeWorkload", "MLADecodeWorkload"]

BucketKey = Tuple[int, int]          # (batch bucket, window pages)

# bump when the (token, position) -> KV content derivation changes:
# part of the prefix-cache geometry key, so stale fleet entries can
# never restore content a fresh prefill would not have produced
PREFILL_CONTENT_VERSION = 1


class DecodeWorkload:
    """Common bucketing/warm-up/prefill logic; subclasses supply the
    kernel and the (token, position) -> KV content derivation."""

    def __init__(self, allocator: PagedKVAllocator,
                 batch_buckets: Sequence[int] = (1, 2, 4, 8),
                 page_buckets: Sequence[int] = (2, 4),
                 prefix_cache=None):
        if not batch_buckets or not page_buckets:
            raise ValueError("batch_buckets and page_buckets must be "
                             "non-empty")
        self.allocator = allocator
        self.batch_buckets = tuple(sorted(set(int(b)
                                              for b in batch_buckets)))
        self.page_buckets = tuple(sorted(set(int(p)
                                             for p in page_buckets)))
        if self.page_buckets[0] < 1:
            raise ValueError("page buckets must be >= 1")
        self._warm: set = set()
        # (batch, pages) bucket -> tuned kernel config adopted from the
        # fleet tune cache at warmup (None = nothing recorded)
        self._tuned: dict = {}
        # (batch, pages) bucket -> the tuned config's recorded
        # best_latency_ms: the prediction the tl-sol drift detector
        # compares serving-measured step latency against
        self._tuned_pred: dict = {}
        # stand-in sampler vocabulary (serving/sampling.py)
        self.vocab = max(2, env.TL_TPU_SERVE_VOCAB)
        # content-addressed prefix KV cache: None = the env-gated
        # process cache (TL_TPU_SERVE_PREFIX), False = disabled, or an
        # explicit PrefixKVCache instance (tests, benches)
        if prefix_cache is None:
            if env.TL_TPU_SERVE_PREFIX:
                from .prefix_cache import get_prefix_cache
                self.prefix_cache = get_prefix_cache()
            else:
                self.prefix_cache = None
        elif prefix_cache is False:
            self.prefix_cache = None
        else:
            self.prefix_cache = prefix_cache

    # -- bucketing -----------------------------------------------------
    @property
    def max_batch(self) -> int:
        return self.batch_buckets[-1]

    def batch_bucket(self, n: int) -> int:
        """Smallest configured batch bucket holding ``n`` requests."""
        for b in self.batch_buckets:
            if b >= n:
                return b
        return self.batch_buckets[-1]

    def window_pages(self, req: Request) -> int:
        """The request's attention window in whole pages, clamped onto
        the configured page buckets (sliding suffix window above the
        top bucket; the smallest bucket below the bottom one)."""
        total = req.context_tokens + req.steps_done
        full = total // self.allocator.page_size
        for p in reversed(self.page_buckets):
            if full >= p:
                return p
        return self.page_buckets[0]

    def bucket_of(self, req: Request) -> int:
        return self.window_pages(req)

    def pages_needed(self, context_tokens: int,
                     new_tokens: int) -> int:
        """Worst-case page footprint of a request (context + every
        generated token) — what admission checks against capacity."""
        ps = self.allocator.page_size
        return math.ceil((context_tokens + new_tokens) / ps)

    # -- request ingestion / prefill / growth --------------------------
    def ingest(self, req: Request) -> None:
        """Admit the request's KV context: restore the longest cached
        whole-page prefix (a prefix-cache hit converts that many tokens
        of prefill compute into a checksummed page restore), then fill
        at most ONE prefill chunk synchronously — a prompt no longer
        than ``TL_TPU_SERVE_PREFILL_CHUNK`` is fully ingested here
        (exactly the pre-chunking behavior); a longer one leaves
        ``req.needs_prefill`` set and the engine drives the remaining
        chunks between decode steps."""
        ps = self.allocator.page_size
        if req.context_tokens < self.page_buckets[0] * ps:
            raise ValueError(
                f"request #{req.req_id}: context_tokens="
                f"{req.context_tokens} is below the smallest page "
                f"bucket ({self.page_buckets[0]} page(s) x {ps})")
        req.pages = []
        req.tail_tokens = 0
        req.prefill_pos = 0
        if self.prefix_cache is not None:
            ent = self.prefix_cache.lookup(
                self.prefix_geometry(), req.prompt_tokens, ps)
            if ent is not None:
                self._restore_prefix(req, ent)
        self.prefill_chunk(req)

    def _restore_prefix(self, req: Request, ent) -> None:
        """Restore a prefix-cache hit through the allocator's
        checksummed ``restore()`` (undo-logged; byte conservation
        asserted on the written bytes). A corrupt entry is dropped +
        quarantined and the request falls back to cold prefill;
        capacity exhaustion propagates (cold prefill would need the
        same pages)."""
        try:
            mapping = self.allocator.restore(ent.to_snapshot(req.req_id))
        except ValueError as e:
            # checksum/geometry rejection: the entry is poison — drop
            # it so it can never serve anyone, and prefill cold
            self.prefix_cache.drop(ent.key, reason=f"restore rejected: "
                                                   f"{e}")
            return
        req.pages = [mapping[i] for i in range(ent.n_pages)]
        req.prefill_pos = ent.n_tokens
        req.prefix_tokens = ent.n_tokens
        req.tail_tokens = 0
        # bytes_saved counts only VALIDATED restores (the checksum +
        # conservation checks above passed), never lookup hits that
        # failed validation and fell back to cold prefill
        self.prefix_cache.note_restored(ent)
        req.trace.mark("prefix.hit", tokens=ent.n_tokens,
                       pages=ent.n_pages, bytes=ent.nbytes)

    def prefill_chunk(self, req: Request,
                      max_tokens: Optional[int] = None) -> int:
        """Fill up to one chunk of the prompt's KV (allocating pages as
        the fill crosses page boundaries — the ``serve.kv`` fault site
        is visited per page, so mid-prefill KV pressure surfaces here).
        Returns the number of tokens filled; on completion the
        whole-page prefix is published to the prefix cache and the
        request becomes decode-eligible."""
        ps = self.allocator.page_size
        chunk = int(max_tokens if max_tokens is not None
                    else env.TL_TPU_SERVE_PREFILL_CHUNK)
        end = min(req.context_tokens, req.prefill_pos + max(1, chunk))
        start = req.prefill_pos
        while req.prefill_pos < end:
            off = req.prefill_pos % ps
            if off == 0 and len(req.pages) * ps <= req.prefill_pos:
                req.pages.extend(self.allocator.alloc(1, req.req_id))
            n = min(ps - off, end - req.prefill_pos)
            k, v = self._prompt_block(req, req.prefill_pos, n)
            self.allocator.write_span(req.pages[req.prefill_pos // ps],
                                      off, k, v)
            req.prefill_pos += n
        req.tail_tokens = req.prefill_pos % ps
        if not req.needs_prefill:
            self._publish_prefix(req)
        return req.prefill_pos - start

    def prefill_chunks_needed(self, context_tokens: int) -> int:
        """Worst-case chunk units a prompt needs (no prefix hit) — what
        admission folds into deadline feasibility."""
        return math.ceil(int(context_tokens)
                         / max(1, env.TL_TPU_SERVE_PREFILL_CHUNK))

    def _prompt_block(self, req: Request, start: int,
                      n: int) -> Tuple[np.ndarray, np.ndarray]:
        """(H, n, D) K/V blocks for prompt tokens [start, start+n) —
        pure in (token id, position), the prefix-cache contract."""
        al = self.allocator
        k = np.empty((al.heads, n, al.head_dim), al.dtype)
        v = np.empty((al.heads, n, al.head_dim), al.dtype)
        for i in range(n):
            pos = start + i
            ki, vi = self._content_kv(req.prompt_tokens[pos], pos)
            k[:, i, :] = ki
            v[:, i, :] = vi
        return k, v

    def _publish_prefix(self, req: Request) -> None:
        """Insert the prompt's whole-page prefix into the prefix cache
        (copies — the live pages keep mutating as the request decodes).
        Skipped when the cache is off, the prompt has no full page, or
        the full prefix itself came from the cache."""
        if self.prefix_cache is None:
            return
        ps = self.allocator.page_size
        full = req.context_tokens // ps
        if full < 1 or req.prefix_tokens >= full * ps:
            return
        pages = []
        for page in req.pages[:full]:
            r0 = self.allocator.row0(page)
            pages.append((self.allocator.kp[:, r0:r0 + ps, :].copy(),
                          self.allocator.vp[:, r0:r0 + ps, :].copy()))
        try:
            self.prefix_cache.insert(
                self.prefix_geometry(), req.prompt_tokens[:full * ps],
                pages, ps, self.allocator.heads, self.allocator.head_dim,
                self.allocator.dtype)
        except Exception:  # noqa: BLE001 — caching is advisory, never
            pass           # a prefill failure

    def prefix_geometry(self) -> str:
        """The geometry half of the prefix-cache content address: two
        workloads whose pools or content derivations differ must never
        share an entry."""
        al = self.allocator
        return (f"{type(self).__name__}:v{PREFILL_CONTENT_VERSION}"
                f":h{al.heads}:d{al.head_dim}:ps{al.page_size}"
                f":{al.dtype}")

    def append_token(self, req: Request) -> None:
        """Append the just-sampled token's KV in place; allocates a
        fresh page exactly when the tail page is full (the mid-flight
        ``serve.kv`` visit the chaos soak arms)."""
        ps = self.allocator.page_size
        if req.tail_tokens == 0:
            req.pages.extend(self.allocator.alloc(1, req.req_id))
        page = req.pages[-1]
        k, v = self._token_kv(req)
        self.allocator.write_token(page, req.tail_tokens, k, v)
        req.tail_tokens = (req.tail_tokens + 1) % ps

    def replay_tokens(self, req: Request) -> int:
        """Re-derive the KV of every ALREADY-SAMPLED token onto this
        workload's allocator — the decode half of adopting a request
        whose pages died elsewhere (fleet failover, reshard re-warm):
        the prompt KV was just rebuilt by ``ingest``/``prefill_chunk``,
        and because token KV is pure in (token id, position) the
        replayed bytes are bitwise what the lost placement held.
        Returns the number of tokens replayed."""
        ps = self.allocator.page_size
        for i, tok in enumerate(req.generated):
            if req.tail_tokens == 0:
                req.pages.extend(self.allocator.alloc(1, req.req_id))
            k, v = self._content_kv(int(tok), req.context_tokens + i)
            self.allocator.write_token(req.pages[-1], req.tail_tokens,
                                       k, v)
            req.tail_tokens = (req.tail_tokens + 1) % ps
        return len(req.generated)

    # -- sampling ------------------------------------------------------
    def sample(self, req: Request, out) -> int:
        """One token id from a decode step's output: project onto the
        stand-in vocabulary, then temperature/top-p sample with a
        (seed, step)-derived rng — bit-reproducible, so a restored
        prefix decodes the identical continuation."""
        from .sampling import sample_token
        rng = np.random.default_rng((req.seed, 3, req.steps_done))
        return sample_token(self._logits(out),
                            temperature=req.temperature,
                            top_p=req.top_p, rng=rng)

    def _logits(self, out) -> np.ndarray:
        """Deterministic projection of the decode output onto ``vocab``
        logits (the stand-in for an LM head)."""
        flat = np.asarray(out, np.float32).ravel()
        if flat.size >= self.vocab:
            return flat[:self.vocab]
        return np.resize(flat, self.vocab)

    def retire(self, req: Request) -> int:
        """Release every slab the request holds (called on ANY terminal
        transition of an ingested request)."""
        freed = self.allocator.free(req.req_id)
        req.pages = []
        req.tail_tokens = 0
        return freed

    # -- dispatch ------------------------------------------------------
    def run_batch(self, requests: List[Request]) -> List[np.ndarray]:
        """One decode step for every request (all in one page bucket):
        pad to the batch bucket, dispatch the precompiled kernel, slice
        per-request outputs."""
        if not requests:
            return []
        if any(r.needs_prefill for r in requests):
            raise ValueError("batch contains a mid-prefill request "
                             "(scheduler bug)")
        pp = self.bucket_of(requests[0])
        if any(self.bucket_of(r) != pp for r in requests):
            raise ValueError("batch mixes page buckets (scheduler bug)")
        bb = self.batch_bucket(len(requests))
        table = np.zeros((bb, pp), np.int32)
        for i in range(bb):
            r = requests[min(i, len(requests) - 1)]   # pad = replicate
            # suffix window: the most recent pp FULL pages
            total = r.context_tokens + r.steps_done
            full = total // self.allocator.page_size
            full_pages = r.pages[:full]
            table[i, :] = full_pages[-pp:]
        q = np.stack([self._query(requests[min(i, len(requests) - 1)])
                      for i in range(bb)])
        # tl-scope: a traced run tags this dispatch with the bound
        # batch-step context (trace_id/parent_span merge in the tracer),
        # joining the kernel dispatch to the requests it served
        _trace.event("serve.dispatch", "serving",
                     workload=type(self).__name__, batch=bb, pages=pp)
        out = self._dispatch(q, table, bb, pp)
        out = np.asarray(out)
        return [out[i] for i in range(len(requests))]

    # -- fleet tune-cache consumption ----------------------------------
    def _tune_source(self) -> "str | None":
        """Source text identifying the bucket kernel in the fleet tune
        cache — None (no tuned-config consumption) by default."""
        return None

    def _tune_bucket(self, bb: int, pp: int) -> str:
        """Canonical shape-bucket token: the (batch, pages) bucket plus
        the pool geometry that shapes the kernel."""
        al = self.allocator
        return (f"{type(self).__name__}:b{bb}:p{pp}:h{al.heads}"
                f":d{al.head_dim}:ps{al.page_size}:rows{al.kp.shape[1]}")

    def bucket_tune_key(self, bb: int, pp: int) -> "str | None":
        """The tune-cache key of one bucket's kernel, or None when the
        workload exposes no tunable kernel source."""
        src = self._tune_source()
        if src is None:
            return None
        import hashlib

        from ..autotuner.tune_cache import TuneCache
        from ..carver.arch import auto_arch
        return TuneCache.key(hashlib.sha256(src.encode()).hexdigest(),
                             self._tune_bucket(bb, pp),
                             auto_arch().name, {})

    def _consult_tune_cache(self, bb: int, pp: int) -> "dict | None":
        key = self.bucket_tune_key(bb, pp)
        if key is None:
            return None
        try:
            from ..autotuner.tune_cache import TuneCache
            ent = TuneCache().get(key)
        except Exception:   # noqa: BLE001 — tuning is advisory, never
            return None     # a warmup failure
        if isinstance(ent, dict) and isinstance(ent.get("best_config"),
                                                dict):
            cfg = dict(ent["best_config"])
            lat = ent.get("best_latency_ms")
            if isinstance(lat, (int, float)) and lat > 0:
                self._tuned_pred[(bb, pp)] = float(lat)
            _trace.inc("serve.warmup.tuned")
            _trace.event("serve.warmup.tuned", "serving", batch=bb,
                         pages=pp, workload=type(self).__name__,
                         config=str(cfg))
            return cfg
        return None

    def record_bucket_tuning(self, bb: int, pp: int, config: dict,
                             latency_ms: float) -> "str | None":
        """Publish one bucket's tuned config to the fleet tune cache
        (what an offline sweep calls so every serving process
        warm-starts with it). Returns the entry key, or None when the
        workload has no tunable kernel source."""
        key = self.bucket_tune_key(bb, pp)
        if key is None:
            return None
        import hashlib

        from ..autotuner.tune_cache import TuneCache
        from ..carver.arch import auto_arch
        src = self._tune_source()
        TuneCache().record(key, {
            "source_sha": hashlib.sha256(src.encode()).hexdigest(),
            "shape_bucket": self._tune_bucket(bb, pp),
            "arch": auto_arch().name,
            "pass_cfg": {},
            "factory": type(self).__name__,
            "best_config": dict(config),
            "best_latency_ms": float(latency_ms),
            "trials": [{"config": dict(config),
                        "latency_ms": float(latency_ms)}],
            "merges": 0,
        })
        return key

    def tuned_config(self, bb: int, pp: int) -> dict:
        """The bucket's adopted tuned config ({} when none)."""
        return self._tuned.get((bb, pp)) or {}

    def tuned_prediction_ms(self, bb: int, pp: int) -> "float | None":
        """The tuned config's recorded best latency for this bucket —
        the baseline the tl-sol drift detector holds serving-measured
        step latency against (None when the bucket is untuned)."""
        return self._tuned_pred.get((bb, pp))

    # -- AOT warm-up ---------------------------------------------------
    def warmup(self) -> int:
        """Compile AND dispatch every (batch, pages) bucket kernel once,
        routed through the crash-safe kernel cache, so no serving
        request ever pays first-call trace/compile latency. Consults the
        fleet tune cache first, so a bucket some fleet member already
        swept dispatches its TUNED config from the very first request —
        zero cold-start measurements. Returns the number of bucket
        kernels warmed."""
        n = 0
        for bb in self.batch_buckets:
            for pp in self.page_buckets:
                # re-consult on every warmup while the bucket is still
                # untuned: a config published (or `tune_cache merge`d)
                # after the first warmup must be adopted by the next
                # one, not ignored until process restart
                if not self._tuned.get((bb, pp)):
                    self._tuned[(bb, pp)] = self._consult_tune_cache(
                        bb, pp)
                if (bb, pp) in self._warm:
                    continue
                with _trace.span("serve.warmup", "serving", batch=bb,
                                 pages=pp, workload=type(self).__name__):
                    q = np.zeros(self._query_shape(bb), np.float32)
                    table = np.zeros((bb, pp), np.int32)
                    self._dispatch(q, table, bb, pp)
                self._warm.add((bb, pp))
                _trace.inc("serve.warmup.kernels")
                n += 1
        return n

    def forget_kernels(self) -> None:
        """Drop warm-state after a backend failover: the next dispatch
        re-walks the backend chain on the rebuilt kernels."""
        self._warm.clear()

    # -- subclass surface ----------------------------------------------
    def _query_shape(self, bb: int) -> tuple:
        raise NotImplementedError

    def _query(self, req: Request) -> np.ndarray:
        raise NotImplementedError

    def _content_kv(self, token: int, pos: int):
        """One token's ``(k, v)`` pair, each ``(heads, head_dim)`` —
        MUST be pure in (token, pos): prefix-cache content addressing
        and the restored-vs-cold bitwise-equality guarantee both rest
        on this purity."""
        raise NotImplementedError

    def _token_kv(self, req: Request):
        """The just-generated token's KV: content derives from the
        SAMPLED token id at its absolute position, so generated
        continuations stay content-consistent with prefill."""
        pos = req.context_tokens + req.steps_done - 1
        tok = req.generated[-1] if req.generated else \
            int(np.random.default_rng((req.seed, 2,
                                       req.steps_done)).integers(1 << 30))
        return self._content_kv(tok, pos)

    def _dispatch(self, q, table, bb: int, pp: int):
        raise NotImplementedError


class FlashDecodeWorkload(DecodeWorkload):
    """Single-token attention over the paged pool, walked in-kernel
    (``flash_decode_paged_pool``: table-driven DMA offsets, no gather
    pass)."""

    def __init__(self, allocator: PagedKVAllocator, *,
                 batch_buckets: Sequence[int] = (1, 2, 4, 8),
                 page_buckets: Sequence[int] = (2, 4),
                 sm_scale: float = None, prefix_cache=None):
        super().__init__(allocator, batch_buckets, page_buckets,
                         prefix_cache=prefix_cache)
        self.sm_scale = (sm_scale if sm_scale is not None
                         else 1.0 / math.sqrt(allocator.head_dim))

    def _query_shape(self, bb: int) -> tuple:
        return (bb, self.allocator.heads, 1, self.allocator.head_dim)

    def _query(self, req: Request) -> np.ndarray:
        rng = np.random.default_rng((req.seed, 1, req.steps_done))
        return rng.standard_normal(
            (self.allocator.heads, 1, self.allocator.head_dim)
        ).astype(np.float32)

    def _content_kv(self, token: int, pos: int):
        rng = np.random.default_rng((int(token) % (1 << 31), int(pos)))
        shape = (self.allocator.heads, self.allocator.head_dim)
        return (rng.standard_normal(shape).astype(np.float32),
                rng.standard_normal(shape).astype(np.float32))

    def _tune_source(self) -> "str | None":
        import inspect

        from ..ops.flash_decoding import paged_decode_kernel
        try:
            return inspect.getsource(paged_decode_kernel)
        except (OSError, TypeError):
            return None

    def _dispatch(self, q, table, bb: int, pp: int):
        from ..ops.flash_decoding import flash_decode_paged_pool
        # fleet-tuned split factor when a sweep recorded one for this
        # bucket (flash_decode_paged_pool clamps it to a divisor of the
        # page count, so a merged entry can never produce an invalid
        # split)
        ns = self.tuned_config(bb, pp).get("n_split")
        return flash_decode_paged_pool(
            q, self.allocator.kp, self.allocator.vp, table,
            self.allocator.page_size, sm_scale=self.sm_scale,
            n_split=int(ns) if ns else None)


class MLADecodeWorkload(DecodeWorkload):
    """DeepSeek-MLA decode over paged latent rows: each pool row holds
    ``[ckv (dc) | kpe (dr)]`` for one token (one shared latent cache
    for all heads — ``heads`` here is the query-head count the kernel
    scores per tile). Pages gather to contiguous ``(B, S, dc)/(B, S,
    dr)`` on the host (the gather strategy of the paged-decode pair),
    then ``mla_decode`` runs the split-KV latent kernel."""

    def __init__(self, allocator: PagedKVAllocator, *, heads: int,
                 latent_dim: int, rope_dim: int,
                 batch_buckets: Sequence[int] = (1, 2, 4),
                 page_buckets: Sequence[int] = (2, 4),
                 sm_scale: float = None, prefix_cache=None):
        if allocator.heads != 1 or \
                allocator.head_dim != latent_dim + rope_dim:
            raise ValueError(
                "MLA pools are latent-major: construct the allocator "
                "with heads=1, head_dim=latent_dim+rope_dim")
        super().__init__(allocator, batch_buckets, page_buckets,
                         prefix_cache=prefix_cache)
        self.heads = int(heads)
        self.dc = int(latent_dim)
        self.dr = int(rope_dim)
        self.sm_scale = (sm_scale if sm_scale is not None
                         else 1.0 / math.sqrt(self.dc + self.dr))

    def _query_shape(self, bb: int) -> tuple:
        return (bb, self.heads, self.dc + self.dr)

    def _query(self, req: Request) -> np.ndarray:
        rng = np.random.default_rng((req.seed, 1, req.steps_done))
        return rng.standard_normal(
            (self.heads, self.dc + self.dr)).astype(np.float32)

    def _content_kv(self, token: int, pos: int):
        rng = np.random.default_rng((int(token) % (1 << 31), int(pos)))
        shape = (1, self.dc + self.dr)
        return (rng.standard_normal(shape).astype(np.float32),
                np.zeros(shape, np.float32))    # vp unused for MLA

    def _dispatch(self, q, table, bb: int, pp: int):
        from ..ops.mla import mla_decode
        ps = self.allocator.page_size
        # host-level gather: rows (pages) -> contiguous (B, S, dc+dr)
        rows = self.allocator.kp[0]                     # (rows, dc+dr)
        idx = (np.asarray(table)[:, :, None] * ps
               + np.arange(ps)[None, None, :]).reshape(bb, pp * ps)
        seq = rows[idx]                                 # (B, S, dc+dr)
        q = np.asarray(q)
        return mla_decode(q[:, :, :self.dc].copy(),
                          q[:, :, self.dc:].copy(),
                          seq[:, :, :self.dc].copy(),
                          seq[:, :, self.dc:].copy(),
                          sm_scale=self.sm_scale)
