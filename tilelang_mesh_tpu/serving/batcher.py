"""Shape-bucketed continuous batching over the decode op library.

XLA wants static shapes, so the batcher quantizes every dispatch onto a
small grid of precompiled kernels:

- **batch buckets** — a batch of ``n`` live requests pads up to the
  smallest configured bucket ``B >= n`` (padding rows replicate the
  last request's page table; their outputs are discarded), so one
  kernel per bucket serves every batch size;
- **page buckets** — requests are grouped by their *attention window*
  in whole pages (``(context + generated) // page_size``); a window
  larger than the biggest configured bucket attends over the most
  recent ``max_bucket`` pages (a sliding suffix window). Ragged batches
  never share a kernel with the wrong sequence length — the page count
  IS the bucket key.

Two workload families over the ops library (the serving consumers of
``ops/flash_decoding.py`` and ``ops/mla.py``):

- :class:`FlashDecodeWorkload` — in-kernel page walking
  (``flash_decode_paged_pool``) over the allocator's H-major pools; no
  gather pass touches the KV data.
- :class:`MLADecodeWorkload` — latent-attention decode: pages hold
  ``[ckv | kpe]`` rows, gathered to contiguous form at the host level
  (the gather strategy) and fed to ``mla_decode``.

``warmup()`` runs every (batch, pages) bucket once through the
crash-safe kernel cache AND through a real dispatch, so the first
serving request never pays trace/compile latency (the AOT warm store
from ROADMAP item 1). It also consults the **fleet tune cache**
(autotuner/tune_cache.py; docs/autotuning.md) for each bucket: a tuned
kernel config recorded by any fleet member — an offline sweep, another
serving process, a merged cache dir — is adopted with ZERO measurements
(the zero-cold-start bucket-config path), and ``record_bucket_tuning``
is how an offline tuner publishes one.
"""

from __future__ import annotations

import math
from typing import List, Sequence, Tuple

import numpy as np

from ..observability import tracer as _trace
from .kv_cache import PagedKVAllocator
from .request import Request

__all__ = ["DecodeWorkload", "FlashDecodeWorkload", "MLADecodeWorkload"]

BucketKey = Tuple[int, int]          # (batch bucket, window pages)


class DecodeWorkload:
    """Common bucketing/warm-up logic; subclasses supply the kernel."""

    def __init__(self, allocator: PagedKVAllocator,
                 batch_buckets: Sequence[int] = (1, 2, 4, 8),
                 page_buckets: Sequence[int] = (2, 4)):
        if not batch_buckets or not page_buckets:
            raise ValueError("batch_buckets and page_buckets must be "
                             "non-empty")
        self.allocator = allocator
        self.batch_buckets = tuple(sorted(set(int(b)
                                              for b in batch_buckets)))
        self.page_buckets = tuple(sorted(set(int(p)
                                             for p in page_buckets)))
        if self.page_buckets[0] < 1:
            raise ValueError("page buckets must be >= 1")
        self._warm: set = set()
        # (batch, pages) bucket -> tuned kernel config adopted from the
        # fleet tune cache at warmup (None = nothing recorded)
        self._tuned: dict = {}

    # -- bucketing -----------------------------------------------------
    @property
    def max_batch(self) -> int:
        return self.batch_buckets[-1]

    def batch_bucket(self, n: int) -> int:
        """Smallest configured batch bucket holding ``n`` requests."""
        for b in self.batch_buckets:
            if b >= n:
                return b
        return self.batch_buckets[-1]

    def window_pages(self, req: Request) -> int:
        """The request's attention window in whole pages, clamped onto
        the configured page buckets (sliding suffix window above the
        top bucket; the smallest bucket below the bottom one)."""
        total = req.context_tokens + req.steps_done
        full = total // self.allocator.page_size
        for p in reversed(self.page_buckets):
            if full >= p:
                return p
        return self.page_buckets[0]

    def bucket_of(self, req: Request) -> int:
        return self.window_pages(req)

    def pages_needed(self, context_tokens: int,
                     new_tokens: int) -> int:
        """Worst-case page footprint of a request (context + every
        generated token) — what admission checks against capacity."""
        ps = self.allocator.page_size
        return math.ceil((context_tokens + new_tokens) / ps)

    # -- request ingestion / growth ------------------------------------
    def ingest(self, req: Request) -> None:
        """Allocate + fill the request's context pages (deterministic
        content from ``req.seed`` unless the payload carries arrays)."""
        ps = self.allocator.page_size
        if req.context_tokens < self.page_buckets[0] * ps:
            raise ValueError(
                f"request #{req.req_id}: context_tokens="
                f"{req.context_tokens} is below the smallest page "
                f"bucket ({self.page_buckets[0]} page(s) x {ps})")
        n = math.ceil(req.context_tokens / ps)
        pages = self.allocator.alloc(n, req.req_id)
        req.pages = pages
        req.tail_tokens = req.context_tokens % ps
        rng = np.random.default_rng(req.seed)
        for i, page in enumerate(pages):
            k, v = self._context_page(req, rng, i)
            self.allocator.fill_page(page, k, v)

    def append_token(self, req: Request) -> None:
        """Append the just-generated token's KV in place; allocates a
        fresh page exactly when the tail page is full (the mid-flight
        ``serve.kv`` visit the chaos soak arms)."""
        ps = self.allocator.page_size
        if req.tail_tokens == 0:
            req.pages.extend(self.allocator.alloc(1, req.req_id))
        page = req.pages[-1]
        k, v = self._token_kv(req)
        self.allocator.write_token(page, req.tail_tokens, k, v)
        req.tail_tokens = (req.tail_tokens + 1) % ps

    def retire(self, req: Request) -> int:
        """Release every slab the request holds (called on ANY terminal
        transition of an ingested request)."""
        freed = self.allocator.free(req.req_id)
        req.pages = []
        req.tail_tokens = 0
        return freed

    # -- dispatch ------------------------------------------------------
    def run_batch(self, requests: List[Request]) -> List[np.ndarray]:
        """One decode step for every request (all in one page bucket):
        pad to the batch bucket, dispatch the precompiled kernel, slice
        per-request outputs."""
        if not requests:
            return []
        pp = self.bucket_of(requests[0])
        if any(self.bucket_of(r) != pp for r in requests):
            raise ValueError("batch mixes page buckets (scheduler bug)")
        bb = self.batch_bucket(len(requests))
        table = np.zeros((bb, pp), np.int32)
        for i in range(bb):
            r = requests[min(i, len(requests) - 1)]   # pad = replicate
            # suffix window: the most recent pp FULL pages
            total = r.context_tokens + r.steps_done
            full = total // self.allocator.page_size
            full_pages = r.pages[:full]
            table[i, :] = full_pages[-pp:]
        q = np.stack([self._query(requests[min(i, len(requests) - 1)])
                      for i in range(bb)])
        # tl-scope: a traced run tags this dispatch with the bound
        # batch-step context (trace_id/parent_span merge in the tracer),
        # joining the kernel dispatch to the requests it served
        _trace.event("serve.dispatch", "serving",
                     workload=type(self).__name__, batch=bb, pages=pp)
        out = self._dispatch(q, table, bb, pp)
        out = np.asarray(out)
        return [out[i] for i in range(len(requests))]

    # -- fleet tune-cache consumption ----------------------------------
    def _tune_source(self) -> "str | None":
        """Source text identifying the bucket kernel in the fleet tune
        cache — None (no tuned-config consumption) by default."""
        return None

    def _tune_bucket(self, bb: int, pp: int) -> str:
        """Canonical shape-bucket token: the (batch, pages) bucket plus
        the pool geometry that shapes the kernel."""
        al = self.allocator
        return (f"{type(self).__name__}:b{bb}:p{pp}:h{al.heads}"
                f":d{al.head_dim}:ps{al.page_size}:rows{al.kp.shape[1]}")

    def bucket_tune_key(self, bb: int, pp: int) -> "str | None":
        """The tune-cache key of one bucket's kernel, or None when the
        workload exposes no tunable kernel source."""
        src = self._tune_source()
        if src is None:
            return None
        import hashlib

        from ..autotuner.tune_cache import TuneCache
        from ..carver.arch import auto_arch
        return TuneCache.key(hashlib.sha256(src.encode()).hexdigest(),
                             self._tune_bucket(bb, pp),
                             auto_arch().name, {})

    def _consult_tune_cache(self, bb: int, pp: int) -> "dict | None":
        key = self.bucket_tune_key(bb, pp)
        if key is None:
            return None
        try:
            from ..autotuner.tune_cache import TuneCache
            ent = TuneCache().get(key)
        except Exception:   # noqa: BLE001 — tuning is advisory, never
            return None     # a warmup failure
        if isinstance(ent, dict) and isinstance(ent.get("best_config"),
                                                dict):
            cfg = dict(ent["best_config"])
            _trace.inc("serve.warmup.tuned")
            _trace.event("serve.warmup.tuned", "serving", batch=bb,
                         pages=pp, workload=type(self).__name__,
                         config=str(cfg))
            return cfg
        return None

    def record_bucket_tuning(self, bb: int, pp: int, config: dict,
                             latency_ms: float) -> "str | None":
        """Publish one bucket's tuned config to the fleet tune cache
        (what an offline sweep calls so every serving process
        warm-starts with it). Returns the entry key, or None when the
        workload has no tunable kernel source."""
        key = self.bucket_tune_key(bb, pp)
        if key is None:
            return None
        import hashlib

        from ..autotuner.tune_cache import TuneCache
        from ..carver.arch import auto_arch
        src = self._tune_source()
        TuneCache().record(key, {
            "source_sha": hashlib.sha256(src.encode()).hexdigest(),
            "shape_bucket": self._tune_bucket(bb, pp),
            "arch": auto_arch().name,
            "pass_cfg": {},
            "factory": type(self).__name__,
            "best_config": dict(config),
            "best_latency_ms": float(latency_ms),
            "trials": [{"config": dict(config),
                        "latency_ms": float(latency_ms)}],
            "merges": 0,
        })
        return key

    def tuned_config(self, bb: int, pp: int) -> dict:
        """The bucket's adopted tuned config ({} when none)."""
        return self._tuned.get((bb, pp)) or {}

    # -- AOT warm-up ---------------------------------------------------
    def warmup(self) -> int:
        """Compile AND dispatch every (batch, pages) bucket kernel once,
        routed through the crash-safe kernel cache, so no serving
        request ever pays first-call trace/compile latency. Consults the
        fleet tune cache first, so a bucket some fleet member already
        swept dispatches its TUNED config from the very first request —
        zero cold-start measurements. Returns the number of bucket
        kernels warmed."""
        n = 0
        for bb in self.batch_buckets:
            for pp in self.page_buckets:
                # re-consult on every warmup while the bucket is still
                # untuned: a config published (or `tune_cache merge`d)
                # after the first warmup must be adopted by the next
                # one, not ignored until process restart
                if not self._tuned.get((bb, pp)):
                    self._tuned[(bb, pp)] = self._consult_tune_cache(
                        bb, pp)
                if (bb, pp) in self._warm:
                    continue
                with _trace.span("serve.warmup", "serving", batch=bb,
                                 pages=pp, workload=type(self).__name__):
                    q = np.zeros(self._query_shape(bb), np.float32)
                    table = np.zeros((bb, pp), np.int32)
                    self._dispatch(q, table, bb, pp)
                self._warm.add((bb, pp))
                _trace.inc("serve.warmup.kernels")
                n += 1
        return n

    def forget_kernels(self) -> None:
        """Drop warm-state after a backend failover: the next dispatch
        re-walks the backend chain on the rebuilt kernels."""
        self._warm.clear()

    # -- subclass surface ----------------------------------------------
    def _query_shape(self, bb: int) -> tuple:
        raise NotImplementedError

    def _query(self, req: Request) -> np.ndarray:
        raise NotImplementedError

    def _context_page(self, req: Request, rng, index: int):
        raise NotImplementedError

    def _token_kv(self, req: Request):
        raise NotImplementedError

    def _dispatch(self, q, table, bb: int, pp: int):
        raise NotImplementedError


class FlashDecodeWorkload(DecodeWorkload):
    """Single-token attention over the paged pool, walked in-kernel
    (``flash_decode_paged_pool``: table-driven DMA offsets, no gather
    pass)."""

    def __init__(self, allocator: PagedKVAllocator, *,
                 batch_buckets: Sequence[int] = (1, 2, 4, 8),
                 page_buckets: Sequence[int] = (2, 4),
                 sm_scale: float = None):
        super().__init__(allocator, batch_buckets, page_buckets)
        self.sm_scale = (sm_scale if sm_scale is not None
                         else 1.0 / math.sqrt(allocator.head_dim))

    def _query_shape(self, bb: int) -> tuple:
        return (bb, self.allocator.heads, 1, self.allocator.head_dim)

    def _query(self, req: Request) -> np.ndarray:
        rng = np.random.default_rng((req.seed, 1, req.steps_done))
        return rng.standard_normal(
            (self.allocator.heads, 1, self.allocator.head_dim)
        ).astype(np.float32)

    def _context_page(self, req: Request, rng, index: int):
        shape = (self.allocator.heads, self.allocator.page_size,
                 self.allocator.head_dim)
        return (rng.standard_normal(shape).astype(np.float32),
                rng.standard_normal(shape).astype(np.float32))

    def _token_kv(self, req: Request):
        rng = np.random.default_rng((req.seed, 2, req.steps_done))
        shape = (self.allocator.heads, self.allocator.head_dim)
        return (rng.standard_normal(shape).astype(np.float32),
                rng.standard_normal(shape).astype(np.float32))

    def _tune_source(self) -> "str | None":
        import inspect

        from ..ops.flash_decoding import paged_decode_kernel
        try:
            return inspect.getsource(paged_decode_kernel)
        except (OSError, TypeError):
            return None

    def _dispatch(self, q, table, bb: int, pp: int):
        from ..ops.flash_decoding import flash_decode_paged_pool
        # fleet-tuned split factor when a sweep recorded one for this
        # bucket (flash_decode_paged_pool clamps it to a divisor of the
        # page count, so a merged entry can never produce an invalid
        # split)
        ns = self.tuned_config(bb, pp).get("n_split")
        return flash_decode_paged_pool(
            q, self.allocator.kp, self.allocator.vp, table,
            self.allocator.page_size, sm_scale=self.sm_scale,
            n_split=int(ns) if ns else None)


class MLADecodeWorkload(DecodeWorkload):
    """DeepSeek-MLA decode over paged latent rows: each pool row holds
    ``[ckv (dc) | kpe (dr)]`` for one token (one shared latent cache
    for all heads — ``heads`` here is the query-head count the kernel
    scores per tile). Pages gather to contiguous ``(B, S, dc)/(B, S,
    dr)`` on the host (the gather strategy of the paged-decode pair),
    then ``mla_decode`` runs the split-KV latent kernel."""

    def __init__(self, allocator: PagedKVAllocator, *, heads: int,
                 latent_dim: int, rope_dim: int,
                 batch_buckets: Sequence[int] = (1, 2, 4),
                 page_buckets: Sequence[int] = (2, 4),
                 sm_scale: float = None):
        if allocator.heads != 1 or \
                allocator.head_dim != latent_dim + rope_dim:
            raise ValueError(
                "MLA pools are latent-major: construct the allocator "
                "with heads=1, head_dim=latent_dim+rope_dim")
        super().__init__(allocator, batch_buckets, page_buckets)
        self.heads = int(heads)
        self.dc = int(latent_dim)
        self.dr = int(rope_dim)
        self.sm_scale = (sm_scale if sm_scale is not None
                         else 1.0 / math.sqrt(self.dc + self.dr))

    def _query_shape(self, bb: int) -> tuple:
        return (bb, self.heads, self.dc + self.dr)

    def _query(self, req: Request) -> np.ndarray:
        rng = np.random.default_rng((req.seed, 1, req.steps_done))
        return rng.standard_normal(
            (self.heads, self.dc + self.dr)).astype(np.float32)

    def _context_page(self, req: Request, rng, index: int):
        shape = (1, self.allocator.page_size, self.dc + self.dr)
        row = rng.standard_normal(shape).astype(np.float32)
        return row, np.zeros(shape, np.float32)    # vp unused for MLA

    def _token_kv(self, req: Request):
        rng = np.random.default_rng((req.seed, 2, req.steps_done))
        shape = (1, self.dc + self.dr)
        return (rng.standard_normal(shape).astype(np.float32),
                np.zeros(shape, np.float32))

    def _dispatch(self, q, table, bb: int, pp: int):
        from ..ops.mla import mla_decode
        ps = self.allocator.page_size
        # host-level gather: rows (pages) -> contiguous (B, S, dc+dr)
        rows = self.allocator.kp[0]                     # (rows, dc+dr)
        idx = (np.asarray(table)[:, :, None] * ps
               + np.arange(ps)[None, None, :]).reshape(bb, pp * ps)
        seq = rows[idx]                                 # (B, S, dc+dr)
        q = np.asarray(q)
        return mla_decode(q[:, :, :self.dc].copy(),
                          q[:, :, self.dc:].copy(),
                          seq[:, :, :self.dc].copy(),
                          seq[:, :, self.dc:].copy(),
                          sm_scale=self.sm_scale)
