"""Fleet: a supervised multi-engine serving tier (tl-fleet).

One ``ServingEngine`` crash losing every in-flight request is fatal
for fronting real traffic (ROADMAP item 1). The Fleet supervises N
engines — each with its OWN workload + allocator, built by a
``workload_factory`` so restarts get fresh state — and admits requests
through the SLO-aware ``Router`` (serving/router.py). The robustness
core is **zero-loss failover** built on two properties the stack
already guarantees: KV content is pure in (token id, position), so a
request's pages can be re-derived bitwise on any engine, and the
content-addressed prefix cache is shared fleet-wide, so a whole-page
prefix restores *warm* on the adopting engine.

Supervision state machine, per engine slot::

    LIVE --- death / breaker trip ---> EJECTED (backoff scheduled)
     ^                                    |
     |  probe passes: breaker reset,      |  backoff elapsed
     |  backoff reset, fleet.readmit      v
     +------------------------------ HALF_OPEN
                                          |
      probe fails: backoff DOUBLES  ------+--> EJECTED

An engine dies three ways, all handled identically within ONE fleet
step: an exception escaping ``engine.step()``, the fleet-level
watchdog (``TL_TPU_FLEET_STEP_TIMEOUT_MS``) abandoning a hung pump,
or an injected fault at the ``serve.engine`` site (armed around every
pump AND every half-open probe, so chaos can kill a restart too).
Engine-internal step failures — swallowed by the engine's own
``_on_step_failure`` to keep its scheduler moving — feed the per-engine
circuit breaker via the ``step_failures`` delta per pump;
``TL_TPU_FLEET_EJECT_THRESHOLD`` consecutive ones eject the engine the
same way.

Failover: the dead engine's live requests are exported
(``export_inflight`` frees their slabs on the victim), each is marked
``failover`` in its causal chain, re-routed to a healthy peer, and
adopted there (``adopt``: prefix-cache warm restore where a whole-page
prefix exists, cold re-prefill otherwise, generated tokens replayed
content-derived, ``readmit`` mark) — a mid-stream ``TokenStream``
keeps yielding from the new engine, because tokens come off the
request, not the engine. One flight dump per failover
(``engine_failover``) names the victim and the re-routed trace ids.
When no healthy peer exists the request sheds ``failover`` — terminal
beats lost; the all-terminal contract survives a full-fleet outage.

Drive it exactly like one engine: ``submit``/``stream``/``step``/
``run``/``drain`` (deterministic, what tests and the ``--fleet`` chaos
soak use), or ``start()``/``stop()`` to host each engine on its own
daemon pump thread.

``TL_TPU_FLEET_ISOLATION=proc`` (fleet-proc) swaps each slot's
in-process engine for a subprocess worker behind a checksummed frame
protocol (serving/worker.py, serving/ipc.py) — same state machine, but
deaths are real: SIGKILL, non-zero exits, and torn frames classify
through the TLError taxonomy and eject within one fleet step; a
crash-looping slot (> ``TL_TPU_FLEET_MAX_RESTARTS`` deaths within
``TL_TPU_FLEET_RESTART_WINDOW_S``) is quarantined rather than hot-
restarted; ``shutdown(graceful=True)`` / ``install_signal_handler()``
give the SIGTERM drain path. Default ``thread`` keeps today's behavior
byte-for-byte.
"""

from __future__ import annotations

import logging
import math
import signal as _signal
import sys
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional

from ..env import env
from ..observability import flight as _flight
from ..observability import histogram as _hist
from ..observability import tracer as _trace
from ..resilience import faults as _faults
from .engine import ServingEngine, TokenStream, _bounded_step
from .request import Request
from .router import Router

__all__ = ["Fleet", "EngineSlot", "fleet_health", "fleet_slo",
           "registered_fleets"]

logger = logging.getLogger("tilelang_mesh_tpu.serving")

# live fleets, for /healthz + /slo (weak: a fleet dying with its test
# must not haunt the telemetry endpoint)
_FLEETS: "weakref.WeakValueDictionary[str, Fleet]" = \
    weakref.WeakValueDictionary()


def registered_fleets() -> Dict[str, "Fleet"]:
    return dict(_FLEETS)


def fleet_health() -> Dict[str, dict]:
    """Per-fleet health sections for ``/healthz`` (guarded upstream)."""
    return {name: f.health() for name, f in _FLEETS.items()}


def fleet_slo() -> Dict[str, dict]:
    """Per-fleet, per-engine SLO summaries for ``/slo``."""
    return {name: {s.name: f.router.slo_summary(s.name)
                   for s in f.slots}
            for name, f in _FLEETS.items()}


class EngineSlot:
    """One supervised engine position: the slot's name is stable across
    restarts; the engine instance is rebuilt fresh each time."""

    __slots__ = ("index", "name", "engine", "state", "backoff_ms",
                 "restart_due", "restarts", "consecutive_failures",
                 "last_step_failures", "submitted", "shed",
                 "last_tick", "death_times", "quarantined_t", "died_t")

    def __init__(self, index: int, name: str):
        self.index = index
        self.name = name
        self.engine: Optional[ServingEngine] = None
        self.state = "ejected"            # until the first build
        self.backoff_ms = 0.0
        self.restart_due = 0.0
        self.restarts = 0
        self.consecutive_failures = 0
        self.last_step_failures = 0
        self.submitted = 0                # per-slot tallies feeding the
        self.shed = 0                     # router's per-engine SLO
        self.last_tick = 0.0
        self.death_times: List[float] = []   # crash-loop window
        self.quarantined_t = 0.0
        self.died_t = 0.0                    # for kill->readmit latency


class Fleet:
    """Supervised N-engine serving tier; duck-types the single-engine
    surface (``submit``/``stream``/``step``/``run``/``drain``/
    ``cancel``/``requests``/``outcomes``) so accounting audits and
    ``TokenStream`` work unchanged."""

    def __init__(self, workload_factory: Callable[[], object],
                 n_engines: Optional[int] = None, *,
                 router: Optional[Router] = None,
                 engine_kwargs: Optional[dict] = None,
                 restart_base_ms: Optional[float] = None,
                 restart_max_ms: Optional[float] = None,
                 step_timeout_ms: Optional[float] = None,
                 probe_deadline_ms: float = 5000.0,
                 isolation: Optional[str] = None,
                 worker_env: Optional[dict] = None,
                 name: str = "fleet"):
        self.isolation = (isolation if isolation is not None
                          else env.TL_TPU_FLEET_ISOLATION)
        if self.isolation not in ("thread", "proc"):
            raise ValueError(
                f"TL_TPU_FLEET_ISOLATION={self.isolation!r} "
                f"(want 'thread' or 'proc')")
        self.worker_env = dict(worker_env or {})
        self.workload_factory = workload_factory
        self.n_engines = (n_engines if n_engines is not None
                          else env.TL_TPU_FLEET_ENGINES)
        if self.n_engines < 1:
            raise ValueError("a fleet needs at least one engine")
        self.router = router or Router()
        self.engine_kwargs = dict(engine_kwargs or {})
        self.restart_base_ms = (restart_base_ms
                                if restart_base_ms is not None
                                else env.TL_TPU_FLEET_RESTART_BASE_MS)
        self.restart_max_ms = (restart_max_ms
                               if restart_max_ms is not None
                               else env.TL_TPU_FLEET_RESTART_MAX_MS)
        self.step_timeout_ms = (step_timeout_ms
                                if step_timeout_ms is not None
                                else env.TL_TPU_FLEET_STEP_TIMEOUT_MS)
        self.probe_deadline_ms = probe_deadline_ms
        self.name = name
        self.requests: List[Request] = []   # every submission + probes
        self._draining = False
        self._warmed = False
        self._failovers = 0
        self._lock = threading.RLock()
        self._stop_evt = threading.Event()
        self._threads: List[threading.Thread] = []
        self.slots = [EngineSlot(i, f"{name}/e{i}")
                      for i in range(self.n_engines)]
        for slot in self.slots:
            slot.backoff_ms = self.restart_base_ms
            self._build_slot(slot)
        _FLEETS[name] = self

    # -- engine lifecycle ----------------------------------------------
    def _build_slot(self, slot: EngineSlot) -> None:
        # backoff is deliberately NOT touched here: only a PASSED probe
        # resets it to base — a rebuild that fails its probe must keep
        # doubling
        if self.isolation == "proc":
            from .worker import ProcEngine
            slot.engine = ProcEngine(
                self.workload_factory, name=slot.name,
                engine_kwargs=self.engine_kwargs,
                extra_env=self.worker_env,
                step_timeout_ms=self.step_timeout_ms)
        else:
            wl = self.workload_factory()
            slot.engine = ServingEngine(wl, name=slot.name,
                                        **self.engine_kwargs)
        slot.state = "live"
        slot.consecutive_failures = 0
        slot.last_step_failures = 0
        if self._draining:
            slot.engine.drain()

    def warmup(self) -> int:
        """Warm every engine's bucket kernels before traffic; restarted
        engines re-warm inside their half-open probe."""
        with self._lock:
            n = sum(s.engine.warmup() for s in self.slots
                    if s.engine is not None)
            self._warmed = True
            return n

    # -- submission ----------------------------------------------------
    def _live_candidates(self,
                         exclude: Optional[str] = None) -> List[dict]:
        return [{"name": s.name, "queue_depth": s.engine.queue_depth}
                for s in self.slots
                if s.state == "live" and s.engine is not None
                and s.name != exclude]

    def _slot_by_name(self, name: str) -> EngineSlot:
        return next(s for s in self.slots if s.name == name)

    def submit(self, context_tokens: int, new_tokens: int = 1,
               **kwargs) -> Request:
        """Route one request to the healthiest engine (weighted
        least-loaded over breaker-closed LIVE slots) and admit it
        there; ALWAYS returns a request with a recorded transition —
        with zero routable engines it comes back shed ``failover``."""
        with self._lock:
            target = self.router.pick(self._live_candidates())
            if target is None:
                req = Request(context_tokens, new_tokens,
                              deadline_ms=kwargs.get("deadline_ms"),
                              seed=kwargs.get("seed", 0),
                              payload=kwargs.get("payload"),
                              prompt_tokens=kwargs.get("prompt_tokens"),
                              temperature=kwargs.get("temperature", 0.0),
                              top_p=kwargs.get("top_p", 1.0),
                              tenant=kwargs.get("tenant"))
                self.requests.append(req)
                self._finish_shed(req, "failover",
                                  error="no routable engine")
                _trace.inc("fleet.unrouted")
                return req
            slot = self._slot_by_name(target)
            req = slot.engine.submit(context_tokens, new_tokens,
                                     **kwargs)
            self.requests.append(req)
            req.trace.mark("route", engine=slot.name)
            _trace.inc("fleet.dispatch", engine=slot.name)
            slot.submitted += 1
            if req.outcome == "shed":
                slot.shed += 1
            return req

    def stream(self, context_tokens: int, new_tokens: int = 1,
               **kwargs) -> TokenStream:
        """Fleet-hosted streaming: the stream pumps the WHOLE fleet, so
        it keeps yielding after its request fails over to another
        engine (the kill-mid-stream contract)."""
        req = self.submit(context_tokens, new_tokens, **kwargs)
        return TokenStream(self, req)

    def _finish_shed(self, req: Request, reason: str,
                     error: Optional[str] = None) -> None:
        """Terminal shed for a request no engine owns (unroutable
        submission / failover with no healthy peer) — the same
        counters + e2e observation an engine-side shed records, so
        fleet accounting stays exact."""
        req.finish("shed", shed_reason=reason, error=error)
        _trace.inc("serve.shed", reason=reason)
        _trace.inc("serve.tenant", tenant=req.tenant, outcome="shed")
        _trace.event("serve.shed", "serving", req=req.req_id,
                     reason=reason, error=error)
        if req.terminal_t is not None:
            _hist.observe("serve.e2e.latency",
                          req.terminal_t - req.submit_t,
                          outcome=req.outcome)

    # -- supervision / pumping -----------------------------------------
    def step(self) -> bool:
        """One fleet scheduling step: run due half-open probes, then
        pump every LIVE engine once (a dying pump fails over inside
        this same step — the router ejects within one step). False
        when nothing progressed (idle)."""
        with self._lock:
            progressed = False
            now = time.monotonic()
            for slot in self.slots:
                self._maybe_release_quarantine(slot, now)
                if slot.state == "ejected" and slot.engine is None \
                        and now >= slot.restart_due:
                    self._probe(slot)
                    progressed = True
            for slot in self.slots:
                if slot.state == "live":
                    progressed |= self._pump(slot)
            return progressed

    def _maybe_release_quarantine(self, slot: EngineSlot,
                                  now: float) -> None:
        """A quarantined slot re-enters the probe path once the crash
        window has fully aged out (or via ``readmit_slot``)."""
        if slot.state != "quarantined":
            return
        if now - slot.quarantined_t >= env.TL_TPU_FLEET_RESTART_WINDOW_S:
            slot.state = "ejected"
            slot.restart_due = now
            slot.death_times = []

    def _pump(self, slot: EngineSlot) -> bool:
        eng = slot.engine
        base_failures = eng.step_failures
        t0 = time.perf_counter()
        try:
            _faults.maybe_fail("serve.engine", engine=slot.name)
            # a ProcEngine enforces the watchdog inside its own recv
            # loop — wrapping the RPC in _bounded_step would leave a
            # late reply to poison the channel's next round-trip
            if self.step_timeout_ms > 0 \
                    and not getattr(eng, "native_watchdog", False):
                progressed = _bounded_step(
                    eng.step, self.step_timeout_ms / 1e3,
                    f"{slot.name} pump")
            else:
                progressed = eng.step()
        except Exception as e:  # noqa: BLE001 — any escape is a death
            self._fail_engine(slot, e)
            return True
        dt = time.perf_counter() - t0
        if progressed:
            self.router.observe_step(slot.name, dt)
        new_failures = eng.step_failures - base_failures
        if new_failures:
            slot.consecutive_failures += new_failures
            for _ in range(new_failures):
                self.router.record_failure(slot.name)
            if self.router.is_open(slot.name):
                self._fail_engine(slot, RuntimeError(
                    f"{slot.consecutive_failures} consecutive step "
                    f"failure(s)"))
                return True
        elif progressed:
            slot.consecutive_failures = 0
            self.router.note_success(slot.name)
        self._tick_slot(slot)
        return progressed

    def _tick_slot(self, slot: EngineSlot) -> None:
        """Throttled per-engine SLO sample for the router."""
        now = time.monotonic()
        if now - slot.last_tick < 0.05:
            return
        slot.last_tick = now
        out = slot.engine.outcomes()
        self.router.tick(slot.name, submitted=slot.submitted,
                         shed=slot.shed, completed=out["result"],
                         failed=out["failed"], now=now)

    def _fail_engine(self, slot: EngineSlot, exc: Exception) -> None:
        """Eject a dead engine and fail its work over, all inside the
        current fleet step: breaker forced open (no live traffic while
        open), live requests exported + re-routed to healthy peers,
        restart scheduled with the slot's current backoff."""
        eng = slot.engine
        self._failovers += 1
        now = time.monotonic()
        slot.state = "ejected"
        slot.engine = None
        slot.died_t = now
        slot.death_times.append(now)
        self.router.force_open(slot.name)
        err = f"{type(exc).__name__}: {exc}"
        # proc isolation: the death has a PID, an exit signal, and a
        # stderr stream — all of it belongs in the flight dump
        proc_attrs: dict = {}
        if eng is not None and hasattr(eng, "proc"):
            info = getattr(eng, "death_info", None) or {}
            proc_attrs = {
                "pid": info.get("pid", getattr(eng, "pid", None)),
                "exitcode": info.get("exitcode"),
                "signal": info.get("signal"),
                "stderr_tail": (info.get("stderr_tail")
                                or eng._stderr_tail()),
            }
        _trace.inc("fleet.failover", engine=slot.name)
        _trace.event("fleet.failover", "fleet", fleet=self.name,
                     engine=slot.name, error=err,
                     **({"pid": proc_attrs.get("pid"),
                         "signal": proc_attrs.get("signal")}
                        if proc_attrs else {}))
        victims = eng.export_inflight() if eng is not None else []
        redispatched, warm, lost = [], 0, 0
        for r in victims:
            r.trace.mark("failover", frm=slot.name, error=err)
            target = self.router.pick(
                self._live_candidates(exclude=slot.name))
            if target is None:
                # no healthy peer: terminal beats lost
                self._finish_shed(r, "failover", error=err)
                lost += 1
                continue
            dst = self._slot_by_name(target)
            dst.engine.adopt(r, source=slot.name)
            redispatched.append(r.trace_id)
            _trace.inc("fleet.redispatched", frm=slot.name, to=target)
            if not r.is_terminal and r.prefix_tokens > 0:
                warm += 1
                _trace.inc("fleet.failover.warm")
        if lost:
            _trace.inc("fleet.failover.lost", lost)
        # the black box: one dump per failover naming the victim and
        # every re-routed trace id — the post-mortem reconstructs who
        # moved where without replaying the soak
        _flight.dump("engine_failover", fleet=self.name,
                     victim=slot.name, error=err,
                     redispatched_trace_ids=redispatched,
                     warm_restores=warm, shed_unroutable=lost,
                     **proc_attrs)
        # the dead worker process (if any) must not linger: a torn
        # frame ejects a still-alive worker, and its half of the pipe
        # is unrecoverable — the probe builds a fresh one
        if eng is not None and callable(getattr(eng, "close", None)):
            try:
                eng.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                logger.debug("worker close failed", exc_info=True)
        window = env.TL_TPU_FLEET_RESTART_WINDOW_S
        slot.death_times = [t for t in slot.death_times
                            if now - t <= window]
        if len(slot.death_times) > env.TL_TPU_FLEET_MAX_RESTARTS:
            self._quarantine(slot, err, window)
            return
        slot.restart_due = time.monotonic() + slot.backoff_ms / 1e3
        logger.warning(
            "fleet %s: engine %s died (%s); %d request(s) re-dispatched "
            "(%d warm), %d shed, restart in %.0fms", self.name,
            slot.name, err, len(redispatched), warm, lost,
            slot.backoff_ms)

    def _quarantine(self, slot: EngineSlot, err: str,
                    window: float) -> None:
        """Crash-loop containment: a slot that keeps dying inside the
        restart window is PARKED — no hot restart loop burning CPU —
        until the window ages out or an operator calls
        ``readmit_slot``. Its traffic sheds to peers (the breaker is
        already forced open)."""
        slot.state = "quarantined"
        slot.quarantined_t = time.monotonic()
        deaths = len(slot.death_times)
        _trace.inc("fleet.quarantined", engine=slot.name)
        _trace.event("fleet.quarantined", "fleet", fleet=self.name,
                     engine=slot.name, deaths_in_window=deaths,
                     window_s=window, error=err)
        _flight.dump("crash_loop", fleet=self.name, engine=slot.name,
                     deaths_in_window=deaths, window_s=window,
                     max_restarts=env.TL_TPU_FLEET_MAX_RESTARTS,
                     last_error=err)
        logger.error(
            "fleet %s: engine %s QUARANTINED after %d death(s) within "
            "%.0fs (%s)", self.name, slot.name, deaths, window, err)

    def readmit_slot(self, name: str) -> bool:
        """Operator override for a quarantined slot: clear the crash
        window and run the half-open probe NOW. True if the slot came
        back live."""
        with self._lock:
            slot = self._slot_by_name(name)
            if slot.state != "quarantined":
                return slot.state == "live"
            slot.state = "ejected"
            slot.death_times = []
            slot.backoff_ms = self.restart_base_ms
            slot.restart_due = time.monotonic()
            _trace.event("fleet.readmit_manual", "fleet",
                         fleet=self.name, engine=name)
            self._probe(slot)
            return slot.state == "live"

    def _probe(self, slot: EngineSlot) -> None:
        """Half-open: rebuild the engine from the factory, re-warm, and
        serve ONE probe request end to end through the guarded pump
        (the ``serve.engine`` site is armed here too — chaos can kill
        the restart). Pass -> LIVE with the breaker reset and backoff
        back to base; fail -> EJECTED with backoff DOUBLED."""
        slot.state = "half_open"
        _trace.inc("fleet.probe", engine=slot.name)
        req = None
        eng = None
        ok = False
        err = None
        try:
            _faults.maybe_fail("serve.engine", engine=slot.name,
                               probe=True)
            self._build_slot(slot)
            eng, slot.state = slot.engine, "half_open"
            if self._warmed:
                eng.warmup()
            wl = eng.workload
            ctx = wl.page_buckets[0] * wl.allocator.page_size
            req = eng.submit(ctx, 1, deadline_ms=self.probe_deadline_ms,
                             seed=slot.index)
            pumps, bound = 0, eng.pump_bound()
            while not req.is_terminal and pumps < bound:
                _faults.maybe_fail("serve.engine", engine=slot.name,
                                   probe=True)
                if not eng.step():
                    break
                pumps += 1
            ok = req.outcome == "result"
        except Exception as e:  # noqa: BLE001 — a probe death re-ejects
            err = f"{type(e).__name__}: {e}"
        if req is not None:
            # the probe is a real request: it must reach a terminal
            # outcome (all-terminal contract) and it stays in the
            # fleet's accounting either way
            if not req.is_terminal and eng is not None:
                eng.cancel(req)
            self.requests.append(req)
        if ok:
            slot.state = "live"
            slot.backoff_ms = self.restart_base_ms
            slot.restarts += 1
            self.router.reset(slot.name)
            down_ms = (round((time.monotonic() - slot.died_t) * 1e3, 1)
                       if slot.died_t else None)
            _trace.inc("fleet.readmit", engine=slot.name)
            _trace.event("fleet.readmit", "fleet", fleet=self.name,
                         engine=slot.name, restarts=slot.restarts,
                         down_ms=down_ms,
                         pid=getattr(slot.engine, "pid", None))
            logger.info("fleet %s: engine %s re-admitted after probe "
                        "(restart #%d)", self.name, slot.name,
                        slot.restarts)
        else:
            slot.state = "ejected"
            slot.engine = None
            self.router.record_failure(slot.name)
            slot.backoff_ms = min(slot.backoff_ms * 2,
                                  self.restart_max_ms)
            slot.restart_due = time.monotonic() + slot.backoff_ms / 1e3
            _trace.inc("fleet.probe_failed", engine=slot.name)
            _trace.event("fleet.probe_failed", "fleet", fleet=self.name,
                         engine=slot.name, error=err,
                         next_backoff_ms=slot.backoff_ms)

    # -- driving -------------------------------------------------------
    def pump_bound(self) -> int:
        """Finite pump bound over the fleet's outstanding work (same
        discipline as ``ServingEngine.pump_bound``, chunk arithmetic
        from the env since slots may be mid-restart)."""
        chunk = max(1, env.TL_TPU_SERVE_PREFILL_CHUNK)
        total = sum(r.new_tokens + math.ceil(r.context_tokens / chunk)
                    for r in self.requests) or 1
        return 20 * total + 100

    def run(self, max_steps: Optional[int] = None) -> int:
        """Pump ``step()`` until idle; on the (generous, finite) bound
        tripping, every engine's queue is force-retired — the
        all-terminal contract holds even against a scheduler bug."""
        if max_steps is None:
            max_steps = self.pump_bound()
        n = 0
        while n < max_steps:
            if not self.step():
                return n
            n += 1
        with self._lock:
            for slot in self.slots:
                if slot.engine is not None:
                    slot.engine.run(max_steps=0)   # force-retire queue
        logger.error("fleet %s: scheduler bound (%d steps) hit; queues "
                     "force-retired", self.name, max_steps)
        return n

    def await_readmission(self, timeout_s: float = 10.0,
                          sleep_s: float = 0.005) -> bool:
        """Step the fleet until every slot is LIVE again (restart
        backoffs are wall-clock, so a pure step loop may be too fast);
        True when the whole fleet is live within the timeout."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if all(s.state == "live" for s in self.slots):
                return True
            self.step()
            time.sleep(sleep_s)
        return all(s.state == "live" for s in self.slots)

    def drain(self) -> None:
        """Stop admitting fleet-wide; ``run()`` finishes in-flight
        work. Engines restarted after the drain come up draining."""
        with self._lock:
            self._draining = True
            for slot in self.slots:
                if slot.engine is not None:
                    slot.engine.drain()
            _trace.event("fleet.drain", "fleet", fleet=self.name)

    def cancel(self, req: Request) -> bool:
        """Cancel wherever the request lives NOW (it may have failed
        over since submission)."""
        with self._lock:
            for slot in self.slots:
                if slot.engine is not None \
                        and req in slot.engine.requests:
                    return slot.engine.cancel(req)
            return False

    # -- thread hosting ------------------------------------------------
    def start(self) -> None:
        """Host each engine slot on its own daemon pump thread (the
        fleet lock serializes scheduling — the deterministic core is
        unchanged; threads supply liveness, restarts included)."""
        with self._lock:
            if self._threads:
                return
            self._stop_evt.clear()
            for slot in self.slots:
                t = threading.Thread(target=self._host, args=(slot,),
                                     daemon=True,
                                     name=f"tl-{slot.name}")
                t.start()
                self._threads.append(t)

    def _host(self, slot: EngineSlot) -> None:
        while not self._stop_evt.is_set():
            with self._lock:
                self._maybe_release_quarantine(slot, time.monotonic())
                if slot.state == "ejected" and slot.engine is None \
                        and time.monotonic() >= slot.restart_due:
                    self._probe(slot)
                progressed = (self._pump(slot)
                              if slot.state == "live" else False)
            if not progressed:
                self._stop_evt.wait(0.002)

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop_evt.set()
        for t in self._threads:
            t.join(timeout=timeout_s)
        self._threads = []

    # -- lifecycle -----------------------------------------------------
    def shutdown(self, graceful: bool = True,
                 timeout_ms: Optional[float] = None) -> int:
        """Orderly fleet teardown (what the SIGTERM handler runs):
        stop admission (new submissions shed ``draining``), finish
        in-flight work under the ``TL_TPU_FLEET_DRAIN_TIMEOUT_MS``
        deadline, force-retire anything still pending (all-terminal
        beats a hung exit), flush the prefix cache's pending disk
        publications, and tear down worker processes. Returns 0 — the
        exit status the signal handler propagates."""
        timeout_ms = (timeout_ms if timeout_ms is not None
                      else env.TL_TPU_FLEET_DRAIN_TIMEOUT_MS)
        self.drain()
        deadline = time.monotonic() + timeout_ms / 1e3
        if graceful:
            bound = self.pump_bound()
            pumps = 0
            while time.monotonic() < deadline and pumps < bound:
                if not self.step():
                    break
                pumps += 1
        with self._lock:
            for slot in self.slots:
                if slot.engine is not None:
                    slot.engine.run(max_steps=0)   # force-retire
            try:
                from .prefix_cache import get_prefix_cache
                get_prefix_cache().flush()
            except Exception:  # noqa: BLE001 — flush is best-effort
                logger.debug("prefix flush on shutdown failed",
                             exc_info=True)
            for slot in self.slots:
                eng = slot.engine
                if eng is not None \
                        and callable(getattr(eng, "close", None)):
                    try:
                        eng.close(graceful=graceful)
                    except Exception:  # noqa: BLE001
                        logger.debug("worker close failed",
                                     exc_info=True)
                    slot.engine = None
                    slot.state = "ejected"
        self.stop()
        _trace.event("fleet.shutdown", "fleet", fleet=self.name,
                     graceful=graceful)
        logger.info("fleet %s: shutdown complete (graceful=%s)",
                    self.name, graceful)
        return 0

    def install_signal_handler(self,
                               signum: int = _signal.SIGTERM):
        """Install the graceful-drain SIGTERM handler: shed new
        admissions, drain under the deadline, flush, exit 0. Returns
        the previous handler (callers restore it in tests)."""
        prev = _signal.getsignal(signum)

        def _handler(sig, frame):  # noqa: ARG001
            logger.warning("fleet %s: signal %d — graceful shutdown",
                           self.name, sig)
            rc = self.shutdown(graceful=True)
            sys.exit(rc)

        _signal.signal(signum, _handler)
        return prev

    # -- accounting ----------------------------------------------------
    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def failovers(self) -> int:
        return self._failovers

    @property
    def queue_depth(self) -> int:
        return sum(s.engine.queue_depth for s in self.slots
                   if s.engine is not None)

    def outcomes(self) -> Dict[str, int]:
        out = {"result": 0, "shed": 0, "deadline_exceeded": 0,
               "failed": 0, "canceled": 0, "pending": 0}
        for r in self.requests:
            out[r.outcome or "pending"] += 1
        return out

    def leak_check(self) -> Dict[str, dict]:
        """Per-engine allocator leak audit (empty inner dicts = clean);
        ejected slots have no allocator — their pages were freed at
        export."""
        return {s.name: {str(k): v
                         for k, v in
                         s.engine.workload.allocator.leak_check().items()}
                for s in self.slots if s.engine is not None}

    def health(self) -> dict:
        """The fleet section of ``/healthz``: per-slot supervision
        state fused with the router's windowed health."""
        engines = {}
        for s in self.slots:
            h = dict(self.router.health(s.name),
                     state=s.state,
                     queue_depth=(s.engine.queue_depth
                                  if s.engine is not None else 0),
                     restarts=s.restarts,
                     backoff_ms=s.backoff_ms)
            if s.engine is not None \
                    and callable(getattr(s.engine, "proc_health",
                                         None)):
                h.update(s.engine.proc_health())
            engines[s.name] = h
        return {
            "fleet": self.name,
            "isolation": self.isolation,
            "draining": self._draining,
            "failovers": self._failovers,
            "requests": len(self.requests),
            "quarantined": [s.name for s in self.slots
                            if s.state == "quarantined"],
            "engines": engines,
        }

    def stats(self) -> dict:
        return {
            "fleet": self.name,
            "requests": len(self.requests),
            "outcomes": self.outcomes(),
            "failovers": self._failovers,
            "draining": self._draining,
            "engines": {s.name: (s.engine.stats()
                                 if s.engine is not None
                                 else {"state": s.state})
                        for s in self.slots},
            "health": self.health(),
        }
