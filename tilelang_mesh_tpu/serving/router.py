"""SLO-aware request routing for the serving fleet (tl-fleet).

The Router owns the *policy* half of the fleet: per-engine health and
the dispatch decision. The Fleet (serving/fleet.py) owns the process
half — engines, pumps, restarts — and feeds the router its raw
signals. Health is derived from machinery the stack already has, per
engine instead of process-wide:

- **windowed step p99 + burn rate** — one ``SLOEngine``
  (observability/slo.py) per engine, fed synthetic samples built from
  that engine's own submission/shed tallies and its
  ``fleet.step.latency{engine=}`` histogram (an exact-label series, so
  the shared ``kernel.latency{kernel=serve.step}`` estimate admission
  reads stays unpolluted);
- **per-engine circuit breaker** — one ``CircuitBreaker``
  (resilience/retry.py) keyed by the signature ``fleet.<engine>.step``;
  ``TL_TPU_FLEET_EJECT_THRESHOLD`` consecutive step failures open it
  and the engine stops receiving live traffic until the fleet's
  half-open probe passes and resets it.

The dispatch rule is **weighted least-loaded**: among breaker-closed
candidates, prefer engines whose windowed p99 is inside
``TL_TPU_FLEET_P99_BUDGET_MS`` (falling back to
``TL_TPU_SERVE_P99_BUDGET_MS``; engines over budget are a last
resort), then score ``(queue_depth + 1) * p99 / best_p99`` and take
the minimum — a degraded engine keeps serving, but its share drops in
proportion to how much slower it is. Ties break on candidate order,
so routing is deterministic under the chaos soak's fixed seeds.
Every decision is visible: the fleet counts ``fleet.dispatch{engine=}``
per routed request and the analyzer's ``fleet`` view reads the shares
back.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ..env import env
from ..observability import histogram as _hist
from ..observability import slo as _slo
from ..resilience.retry import CircuitBreaker

__all__ = ["Router", "fleet_sig", "fleet_p99_budget_ms",
           "STEP_HIST_NAME"]

# per-engine step-latency histogram (exact label matching keeps it out
# of the shared serve.step admission estimate)
STEP_HIST_NAME = "fleet.step.latency"


def fleet_sig(engine: str) -> str:
    """The per-engine breaker signature."""
    return f"fleet.{engine}.step"


def fleet_p99_budget_ms() -> float:
    b = env.TL_TPU_FLEET_P99_BUDGET_MS
    return b if b > 0 else env.TL_TPU_SERVE_P99_BUDGET_MS


class Router:
    """Pure routing policy over named engines; the Fleet feeds signals
    (``observe_step``/``tick``/``record_failure``) and asks ``pick``."""

    def __init__(self, *, breaker: Optional[CircuitBreaker] = None,
                 p99_budget_ms: Optional[float] = None,
                 eject_threshold: Optional[int] = None,
                 windows: Optional[List[float]] = None,
                 target: Optional[float] = None):
        # a dedicated breaker instance by default: the fleet's eject
        # threshold is its own knob, not TL_TPU_BREAKER_THRESHOLD
        self.breaker = breaker if breaker is not None else CircuitBreaker(
            threshold=(eject_threshold if eject_threshold is not None
                       else env.TL_TPU_FLEET_EJECT_THRESHOLD))
        self._budget_ms = p99_budget_ms
        self._windows = windows
        self._target = target
        self._slos: Dict[str, _slo.SLOEngine] = {}

    # -- per-engine breaker --------------------------------------------
    def sig(self, engine: str) -> str:
        return fleet_sig(engine)

    def is_open(self, engine: str) -> bool:
        return self.breaker.is_open(self.sig(engine))

    def record_failure(self, engine: str) -> bool:
        """One step failure against the engine's breaker; True exactly
        when this failure trips it open."""
        return self.breaker.record_failure(self.sig(engine))

    def force_open(self, engine: str) -> None:
        """Open the engine's breaker NOW (a death is not a countable
        blip — an engine that died mid-step must stop receiving
        traffic within the same fleet step)."""
        s = self.sig(engine)
        while not self.breaker.is_open(s):
            self.breaker.record_failure(s)

    def reset(self, engine: str) -> None:
        """Close the engine's breaker (probe warmup passed)."""
        self.breaker.reset(self.sig(engine))

    def note_success(self, engine: str) -> None:
        """A clean pump: consecutive-failure semantics means the count
        restarts from zero (the stock breaker counts monotonically, so
        the router resets it while it is still below threshold)."""
        if not self.is_open(engine):
            self.breaker.reset(self.sig(engine))

    # -- per-engine SLO signals ----------------------------------------
    def _slo_for(self, engine: str) -> _slo.SLOEngine:
        s = self._slos.get(engine)
        if s is None:
            s = self._slos[engine] = _slo.SLOEngine(
                windows=self._windows, target=self._target)
        return s

    def observe_step(self, engine: str, dt_s: float) -> None:
        _hist.observe(STEP_HIST_NAME, dt_s, engine=engine)

    def tick(self, engine: str, *, submitted: float, shed: float,
             completed: float = 0.0, failed: float = 0.0,
             now: Optional[float] = None) -> None:
        """Append one synthetic SLO sample for the engine (the fleet
        calls this per pump with that engine's own tallies) — the same
        window math as the process-wide ``/slo``, scoped per engine."""
        h = _hist.get_histogram(STEP_HIST_NAME, engine=engine)
        hist = None
        if h is not None and h.count:
            hist = _hist.Histogram(h.bounds)
            hist.merge(h)
        self._slo_for(engine).add({
            "t": time.monotonic() if now is None else now,
            "submitted": float(submitted), "shed": float(shed),
            "completed": float(completed), "failed": float(failed),
            "deadline_exceeded": 0.0, "hist": hist, "ttft_hist": None,
            "prefix_hits": 0.0, "prefix_misses": 0.0})

    def window_stats(self, engine: str) -> dict:
        s = self._slo_for(engine)
        return s.window_stats(s.windows[0])

    def health(self, engine: str) -> dict:
        """One engine's routing-health snapshot (what ``/healthz`` and
        the analyzer surface)."""
        w = self.window_stats(engine)
        return {"engine": engine,
                "breaker_open": self.is_open(engine),
                "p99_ms": w.get("p99_ms"),
                "burn_rate": w.get("burn_rate"),
                "availability": w.get("availability"),
                "window_s": w.get("window_s")}

    def slo_summary(self, engine: str) -> dict:
        return self._slo_for(engine).summary()

    def engines(self) -> List[str]:
        return sorted(self._slos)

    # -- dispatch ------------------------------------------------------
    def pick(self, candidates: List[dict]) -> Optional[str]:
        """Weighted least-loaded choice among candidate views
        (``{"name", "queue_depth"}``, live slots only). Breaker-open
        engines never receive live traffic; within-budget engines beat
        over-budget ones; then ``(queue_depth + 1) * p99/best_p99`` is
        minimized with candidate order as the deterministic
        tie-break. None when nothing is routable."""
        live = [c for c in candidates if not self.is_open(c["name"])]
        if not live:
            return None
        p99 = {c["name"]: (self.window_stats(c["name"]).get("p99_ms")
                           or 0.0)
               for c in live}
        budget = (self._budget_ms if self._budget_ms is not None
                  else fleet_p99_budget_ms())
        if budget > 0:
            within = [c for c in live if p99[c["name"]] <= budget]
            if within:
                live = within
        known = [v for v in p99.values() if v > 0]
        best = min(known) if known else 0.0
        def score(c):
            w = p99[c["name"]] / best if best > 0 and p99[c["name"]] > 0 \
                else 1.0
            return (c.get("queue_depth", 0) + 1) * w
        return min(live, key=score)["name"]
