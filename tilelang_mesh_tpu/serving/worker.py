"""Subprocess engine workers for the process-isolated fleet.

``TL_TPU_FLEET_ISOLATION=proc`` turns every fleet slot into a real OS
process: :func:`worker_main` (the child) hosts one ordinary
``ServingEngine`` behind the checksummed frame protocol
(serving/ipc.py), and :class:`ProcEngine` (the supervisor side)
duck-types the exact engine surface ``serving/fleet.py`` drives —
``submit`` / ``step`` / ``adopt`` / ``export_inflight`` / ``cancel`` /
``drain`` / ``warmup`` / ``outcomes`` / ``step_failures`` — so the
fleet's LIVE→EJECTED→HALF_OPEN→LIVE supervision runs unchanged over
processes it can actually lose.

The zero-loss design point: the supervisor holds a **shadow request**
(a real :class:`Request`) for everything it submitted, synced by
per-step state deltas off the wire. A SIGKILL'd worker can never
answer an ``export_inflight`` RPC — so the shadows, not the worker,
are the source of truth at failover: the fleet exports the shadows,
re-routes them to healthy peers, and the adopting *worker* re-derives
their KV content-addressed (warm from the shared disk prefix tier
where a whole-page prefix was published — the disk tier is the
cross-process transport, so a warm restore survives the death of the
process that wrote it). Sampled tokens ride the shadow, so a
mid-stream ``TokenStream`` keeps yielding across the kill.

Liveness is real-process liveness: every RPC round-trip doubles as a
heartbeat, the recv loop polls the child's aliveness (waitpid via
``Process.is_alive``) so SIGKILL mid-RPC is detected immediately and
classified ``device_loss``; a round-trip past the watchdog
(``TL_TPU_FLEET_STEP_TIMEOUT_MS``) is a ``timeout``; a torn frame is a
deterministic :class:`~.ipc.FrameError`. All three eject the slot
through the same ``_fail_engine`` path as a thread-mode death.

Workers re-record nothing in the supervisor's telemetry — the
supervisor re-records ``serve.*`` accounting itself as deltas apply,
so fleet-wide counters / ``serve.e2e.latency`` audits hold without a
cross-process metrics bus. Worker stderr is redirected to a per-slot
file whose tail lands in the ``engine_failover`` flight dump.
"""

from __future__ import annotations

import logging
import math
import os
import signal as _signal
import sys
import tempfile
import time
from typing import Dict, List, Optional

from ..env import env
from ..observability import histogram as _hist
from ..observability import tracer as _trace
from ..resilience import faults as _faults
from ..resilience.errors import (DeviceLossError, TLError,
                                 TLTimeoutError, classify)
from .ipc import (FrameError, decode_frame, encode_frame,
                  serialize_request)
from .request import Request

__all__ = ["ProcEngine", "worker_main", "default_workload_factory"]

logger = logging.getLogger("tilelang_mesh_tpu.serving")

# generous deadline for the first (hello) frame: the child pays the
# interpreter + package import bill before it can speak
_SPAWN_DEADLINE_S = 120.0
_WARMUP_DEADLINE_S = 300.0


def default_workload_factory(n_pages: int = 64, page_size: int = 8,
                             heads: int = 2, head_dim: int = 64,
                             batch_buckets=(4,), page_buckets=(2, 4)):
    """A module-level (so picklable across the ``spawn`` boundary)
    workload factory: ``functools.partial`` over it parameterizes
    geometry for tests, docs snippets, and the ``--fleet-proc`` soak —
    closures cannot cross ``multiprocessing`` spawn."""
    from .batcher import FlashDecodeWorkload
    from .kv_cache import PagedKVAllocator
    alloc = PagedKVAllocator(n_pages=n_pages, page_size=page_size,
                             heads=heads, head_dim=head_dim)
    return FlashDecodeWorkload(alloc, batch_buckets=tuple(batch_buckets),
                               page_buckets=tuple(page_buckets))


# -- child side ------------------------------------------------------------
def _flush_prefix() -> None:
    """Publish pending prefix-cache disk writes after every scheduling
    quantum: the disk tier is the fleet's cross-process warm-restore
    transport, so a worker's cached prefixes must survive its death
    with at most one step of lag."""
    try:
        from .prefix_cache import get_prefix_cache
        get_prefix_cache().flush()
    except Exception:  # noqa: BLE001 — publication must not kill a step
        logger.debug("worker prefix flush failed", exc_info=True)


class _WorkerLoop:
    """The child's RPC dispatcher: one ``ServingEngine``, a cid → local
    request map, and per-cid sync markers so each reply carries only
    the state that changed."""

    def __init__(self, conn, eng):
        self.conn = conn
        self.eng = eng
        self.reqs: Dict[int, Request] = {}
        self.synced: Dict[int, tuple] = {}

    def _register(self, cid: int, req: Request) -> None:
        self.reqs[cid] = req
        # baseline at the request's CURRENT progress: an adopted
        # request arrives with generated tokens the supervisor already
        # holds — re-shipping them would double the shadow's stream
        self.synced[cid] = (req.steps_done, len(req.generated),
                            req.prefill_pos, req.prefix_tokens,
                            req.outcome, req.first_token_t is not None)

    def deltas(self) -> List[dict]:
        out = []
        for cid in list(self.reqs):
            r = self.reqs[cid]
            mark = (r.steps_done, len(r.generated), r.prefill_pos,
                    r.prefix_tokens, r.outcome,
                    r.first_token_t is not None)
            if mark == self.synced[cid]:
                continue
            prev_gen = self.synced[cid][1]
            out.append({
                "cid": cid,
                "outcome": r.outcome,
                "shed_reason": r.shed_reason,
                "error": r.error,
                "steps_done": r.steps_done,
                "retries": r.retries,
                "generated_tail": [int(t) for t in
                                   r.generated[prev_gen:]],
                "gen_len": len(r.generated),
                "prefill_pos": r.prefill_pos,
                "prefix_tokens": r.prefix_tokens,
                "first_token": r.first_token_t is not None,
            })
            if r.is_terminal:
                del self.reqs[cid]
                del self.synced[cid]
            else:
                self.synced[cid] = mark
        return out

    def handle(self, header: dict) -> dict:
        op = header.get("op")
        eng = self.eng
        if op == "ping":
            return {"ok": True, "pid": os.getpid()}
        if op == "submit":
            d = header["req"]
            try:
                req = eng.submit(
                    int(d["context_tokens"]), int(d["new_tokens"]),
                    deadline_ms=d.get("deadline_ms"),
                    seed=int(d.get("seed", 0)),
                    payload=dict(d.get("payload") or {}),
                    prompt_tokens=[int(t) for t in d["prompt_tokens"]],
                    temperature=float(d.get("temperature", 0.0)),
                    top_p=float(d.get("top_p", 1.0)),
                    tenant=d.get("tenant"))
            except ValueError as e:
                # caller bug (mis-sized prompt, bad bucket): parity
                # with the in-process engine, which raises to the
                # submitter instead of dying
                return {"ok": False, "etype": "ValueError",
                        "error": str(e)}
            # baseline at ZERO, not current state: submit may already
            # have shed / warm-restored, and that transition must ship
            # in this very reply
            self.reqs[int(d["cid"])] = req
            self.synced[int(d["cid"])] = (0, 0, 0, 0, None, False)
            return {"ok": True, "deltas": self.deltas(),
                    "queue_depth": eng.queue_depth}
        if op == "adopt":
            from .ipc import deserialize_request
            req = deserialize_request(header["req"])
            self._register(int(header["req"]["cid"]), req)
            eng.adopt(req, source=header.get("source", ""))
            _flush_prefix()
            return {"ok": True, "deltas": self.deltas(),
                    "queue_depth": eng.queue_depth}
        if op == "step":
            progressed = eng.step()
            _flush_prefix()
            return {"ok": True, "progressed": bool(progressed),
                    "deltas": self.deltas(),
                    "step_failures": eng.step_failures,
                    "queue_depth": eng.queue_depth}
        if op == "force_retire":
            eng.run(max_steps=0)
            return {"ok": True, "deltas": self.deltas(),
                    "queue_depth": eng.queue_depth}
        if op == "cancel":
            req = self.reqs.get(int(header["cid"]))
            ok = eng.cancel(req) if req is not None else False
            return {"ok": bool(ok), "deltas": self.deltas(),
                    "queue_depth": eng.queue_depth}
        if op == "drain":
            eng.drain()
            return {"ok": True}
        if op == "warmup":
            return {"ok": True, "warmed": eng.warmup()}
        if op == "kv":
            alloc = eng.workload.allocator
            return {"ok": True, "in_use": alloc.in_use,
                    "free_pages": alloc.free_pages}
        if op == "leak_check":
            return {"ok": True,
                    "leaks": {str(k): v for k, v in
                              eng.workload.allocator.leak_check()
                              .items()}}
        if op == "stats":
            return {"ok": True, "stats": eng.stats()}
        if op == "snapshot":
            # checksummed KV export of the whole allocator — the
            # byte-conserved snapshot format crossing the boundary as
            # one frame (tests + future disaggregated prefill)
            from .ipc import encode_snapshot
            snap = eng.workload.allocator.snapshot()
            return {"ok": True, "_frame": encode_snapshot(snap)}
        if op == "flush_prefix":
            _flush_prefix()
            return {"ok": True}
        if op == "shutdown":
            if header.get("graceful"):
                eng.drain()
                eng.run()
                _flush_prefix()
            return {"ok": True, "deltas": self.deltas(),
                    "_last": True}
        return {"ok": False, "etype": "ProtocolError",
                "error": f"unknown op {op!r}"}


def worker_main(conn, spec: dict) -> None:
    """Child entry point (``multiprocessing`` spawn target): apply env
    overrides, redirect stderr to the per-slot capture file, build the
    engine from the (picklable) factory, say hello, then serve RPC
    frames until EOF/shutdown. Exits 0 on a clean shutdown, 3 when an
    exception escapes the engine (the supervisor classifies the exit
    code)."""
    for k, v in (spec.get("env") or {}).items():
        os.environ[str(k)] = str(v)
    path = spec.get("stderr_path")
    if path:
        try:
            fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                         0o644)
            os.dup2(fd, 2)
            sys.stderr = os.fdopen(2, "w", buffering=1,
                                   closefd=False)
        except OSError:
            pass
    # the supervisor owns SIGTERM policy; a worker told to terminate
    # exits promptly and lets the shadows carry its work
    _signal.signal(_signal.SIGTERM, lambda *a: sys.exit(0))
    try:
        from .engine import ServingEngine
        wl = spec["factory"]()
        eng = ServingEngine(wl, name=spec.get("name", "worker"),
                            **(spec.get("engine_kwargs") or {}))
    except Exception as e:  # noqa: BLE001 — report the build failure
        try:
            conn.send_bytes(encode_frame(
                {"op": "hello", "ok": False,
                 "error": f"{type(e).__name__}: {e}"}))
        except Exception:  # noqa: BLE001
            pass
        sys.exit(3)
    alloc = wl.allocator
    conn.send_bytes(encode_frame({
        "op": "hello", "ok": True, "pid": os.getpid(),
        "geometry": {"page_size": alloc.page_size,
                     "heads": alloc.heads, "head_dim": alloc.head_dim,
                     "n_pages": alloc.n_pages,
                     "page_buckets": list(wl.page_buckets),
                     "batch_buckets": list(wl.batch_buckets)}}))
    loop = _WorkerLoop(conn, eng)
    while True:
        try:
            data = conn.recv_bytes()
        except (EOFError, OSError):
            sys.exit(0)          # supervisor went away: nothing to serve
        try:
            header, _body = decode_frame(data)
        except FrameError as e:
            # a torn inbound frame: report it and keep the channel —
            # pipes are message-oriented, the next frame realigns
            conn.send_bytes(encode_frame(
                {"op": "error", "etype": "FrameError",
                 "error": str(e)}))
            continue
        try:
            reply = loop.handle(header)
        except SystemExit:
            raise
        except Exception as e:  # noqa: BLE001 — an escaped engine error
            # is a death in thread mode too: report, then die visibly
            try:
                conn.send_bytes(encode_frame(
                    {"op": "error", "etype": type(e).__name__,
                     "error": f"{type(e).__name__}: {e}",
                     "fatal": True}))
            except Exception:  # noqa: BLE001
                pass
            sys.exit(3)
        frame = reply.pop("_frame", None)
        last = reply.pop("_last", False)
        conn.send_bytes(frame if frame is not None
                        else encode_frame(reply))
        if last:
            sys.exit(0)


# -- supervisor side -------------------------------------------------------
class _AllocShim:
    """The allocator face of a remote engine: geometry is local (from
    the hello frame), levels are RPCs, and a dead worker leaks nothing
    into the supervisor — its pages died with it."""

    def __init__(self, proxy: "ProcEngine", geometry: dict):
        self._proxy = proxy
        self.page_size = int(geometry["page_size"])
        self.heads = int(geometry["heads"])
        self.head_dim = int(geometry["head_dim"])
        self.n_pages = int(geometry["n_pages"])

    @property
    def in_use(self) -> int:
        kv = self._proxy._kv_levels()
        return int(kv.get("in_use", 0))

    @property
    def free_pages(self) -> int:
        kv = self._proxy._kv_levels()
        return int(kv.get("free_pages", self.n_pages))

    def leak_check(self) -> dict:
        return self._proxy._leak_check()

    def stats(self) -> dict:
        return {"n_pages": self.n_pages, "page_size": self.page_size,
                "in_use": self.in_use}


class _WorkloadShim:
    """What the fleet reads off ``engine.workload``: bucket geometry
    for probe sizing and the allocator shim for leak audits."""

    def __init__(self, proxy: "ProcEngine", geometry: dict):
        self.page_buckets = tuple(int(p)
                                  for p in geometry["page_buckets"])
        self.batch_buckets = tuple(int(b)
                                   for b in geometry["batch_buckets"])
        self.allocator = _AllocShim(proxy, geometry)

    def prefill_chunks_needed(self, context_tokens: int) -> int:
        chunk = max(1, env.TL_TPU_SERVE_PREFILL_CHUNK)
        return max(1, math.ceil(int(context_tokens) / chunk))


class ProcEngine:
    """Supervisor-side proxy for one subprocess engine worker. Never
    raises from ``submit``/``adopt``/``cancel``/``drain`` — an IPC
    failure there is noted and raised at the next ``step()``, the
    fleet's supervision point, so every death funnels through
    ``_fail_engine`` with the shadows intact."""

    native_watchdog = True   # step RPCs time out in the recv loop;
    #                          the fleet must not double-wrap them

    def __init__(self, factory, *, name: str = "worker",
                 engine_kwargs: Optional[dict] = None,
                 extra_env: Optional[dict] = None,
                 step_timeout_ms: Optional[float] = None,
                 ipc_timeout_ms: Optional[float] = None):
        import multiprocessing as mp
        self.name = name
        self.factory = factory
        self.step_timeout_ms = (step_timeout_ms or 0.0)
        self.ipc_timeout_ms = (ipc_timeout_ms
                               if ipc_timeout_ms is not None
                               else env.TL_TPU_FLEET_IPC_TIMEOUT_MS)
        self.requests: List[Request] = []
        self._by_cid: Dict[int, Request] = {}
        self._cid_of: Dict[int, int] = {}        # req_id -> cid
        self._draining = False
        self._queue_depth = 0
        self._remote_step_failures = 0
        self._pending_death: Optional[Exception] = None
        self._broken = False
        self.death_info: Optional[dict] = None
        self._tmpdir = tempfile.mkdtemp(prefix="tl-fleet-worker-")
        self.stderr_path = os.path.join(self._tmpdir, "stderr.log")
        ctx = mp.get_context("spawn")
        self._conn, child_conn = ctx.Pipe(duplex=True)
        spec = {"name": name, "factory": factory,
                "engine_kwargs": dict(engine_kwargs or {}),
                "env": dict(extra_env or {}),
                "stderr_path": self.stderr_path}
        self.proc = ctx.Process(target=worker_main,
                                args=(child_conn, spec), daemon=True,
                                name=f"tl-{name}")
        self.spawned_t = time.monotonic()
        self.proc.start()
        child_conn.close()
        self.pid = self.proc.pid
        hello, _ = self._recv("hello", _SPAWN_DEADLINE_S * 1e3)
        if not hello.get("ok"):
            err = hello.get("error", "worker build failed")
            self.close()
            raise DeviceLossError(
                f"worker {name} failed to come up: {err}",
                site="fleet.ipc", backend="proc")
        self.pid = int(hello["pid"])
        self.geometry = dict(hello["geometry"])
        self.workload = _WorkloadShim(self, self.geometry)
        self.last_heartbeat = time.monotonic()
        _trace.inc("fleet.worker.spawn", engine=name)
        _trace.event("fleet.worker.spawn", "fleet", engine=name,
                     pid=self.pid)

    # -- transport -----------------------------------------------------
    def _stderr_tail(self, limit: int = 2000) -> str:
        try:
            with open(self.stderr_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - limit))
                return f.read().decode("utf-8", "replace")
        except OSError:
            return ""

    def _death_error(self) -> DeviceLossError:
        self.proc.join(timeout=0.5)   # reap, so exitcode is real
        code = self.proc.exitcode
        sig = -code if (code is not None and code < 0) else None
        if sig is not None:
            try:
                desc = f"signal {sig} ({_signal.Signals(sig).name})"
            except ValueError:
                desc = f"signal {sig}"
        else:
            desc = f"exit code {code}"
        if self.death_info is None:
            self.death_info = {"pid": self.pid, "exitcode": code,
                               "signal": sig,
                               "stderr_tail": self._stderr_tail()}
            _trace.inc("fleet.worker.death", engine=self.name)
            _trace.event("fleet.worker.death", "fleet",
                         engine=self.name, pid=self.pid,
                         exitcode=code, signal=sig)
        return DeviceLossError(
            f"worker {self.name} pid {self.pid} died: {desc}",
            site="fleet.ipc", backend="proc")

    def _armed_mode(self, op: str) -> Optional[str]:
        """Visit the ``fleet.ipc`` fault site once per round-trip;
        ``torn``/``delay``/``kill`` come back as transport damage to
        apply, anything else raises through (an injected classified
        error ejects the slot like an organic one)."""
        try:
            _faults.maybe_fail("fleet.ipc", engine=self.name, op=op)
        except _faults.IPCFaultRequest as f:
            return f.mode
        except _faults.CorruptionRequest:
            return "torn"
        return None

    def _rpc(self, op: str, extra: Optional[dict] = None,
             timeout_ms: Optional[float] = None) -> dict:
        if self._broken:
            raise (self._pending_death
                   or DeviceLossError(f"worker {self.name} channel "
                                      f"is down", site="fleet.ipc",
                                      backend="proc"))
        timeout_ms = (timeout_ms if timeout_ms is not None
                      else self.ipc_timeout_ms)
        t0 = time.monotonic()     # the watchdog covers the WHOLE
        frame = encode_frame({"op": op, **(extra or {})})   # round-trip
        mode = self._armed_mode(op)
        try:
            if mode == "torn":
                # flip one payload byte: the far side's crc catches it
                mid = len(frame) // 2
                frame = frame[:mid] + bytes([frame[mid] ^ 0xFF]) \
                    + frame[mid + 1:]
            elif mode == "delay":
                time.sleep(max((self.step_timeout_ms or 100.0) * 2,
                               50.0) / 1e3)
            elif mode == "kill":
                os.kill(self.pid, _signal.SIGKILL)
                self.proc.join(timeout=2.0)
            self._conn.send_bytes(frame)
            _trace.inc("fleet.ipc.tx", engine=self.name)
            _trace.inc("fleet.ipc.bytes_tx", len(frame),
                       engine=self.name)
            header, _body = self._recv(
                op, timeout_ms - (time.monotonic() - t0) * 1e3)
        except Exception as e:  # noqa: BLE001 — classify + mark broken
            self._broken = True
            # a SIGKILL often lands as EPIPE on the SEND before the
            # recv loop ever polls: convert raw pipe errors on a dead
            # process into the classified death
            if isinstance(e, OSError) and not isinstance(e, TLError) \
                    and not self.proc.is_alive():
                err = self._death_error()
                _trace.inc("fleet.ipc.errors", kind=classify(err),
                           engine=self.name)
                raise err from e
            _trace.inc("fleet.ipc.errors", kind=classify(e),
                       engine=self.name)
            raise
        if header.get("op") == "error":
            err = header.get("error", "worker error")
            self._broken = True
            _trace.inc("fleet.ipc.errors", kind="deterministic",
                       engine=self.name)
            raise FrameError(f"worker {self.name} reported: {err}")
        return header

    def _recv(self, op: str, timeout_ms: float):
        """Blocking receive with the two real liveness signals fused
        in: the watchdog deadline over the round-trip, and waitpid-
        backed death detection so a SIGKILL mid-RPC surfaces NOW, not
        at the deadline."""
        deadline = time.monotonic() + timeout_ms / 1e3
        while True:
            # deadline first: a reply that lands PAST the watchdog is
            # still a watchdog failure (a stalled round-trip must eject
            # deterministically, and the late frame would poison the
            # next RPC's framing if it were accepted)
            if time.monotonic() > deadline:
                raise TLTimeoutError(
                    f"worker {self.name} {op} round-trip exceeded "
                    f"{timeout_ms:g}ms", site="fleet.ipc")
            if self._conn.poll(0.005):
                try:
                    data = self._conn.recv_bytes()
                except (EOFError, OSError):
                    raise self._death_error() from None
                break
            if not self.proc.is_alive():
                # drain anything the worker flushed before dying
                if self._conn.poll(0):
                    continue
                raise self._death_error()
        _trace.inc("fleet.ipc.rx", engine=self.name)
        _trace.inc("fleet.ipc.bytes_rx", len(data), engine=self.name)
        header, body = decode_frame(data)   # FrameError on torn bytes
        self.last_heartbeat = time.monotonic()
        return header, body

    def _note_death(self, exc: Exception) -> None:
        if self._pending_death is None:
            self._pending_death = exc
        self._broken = True

    # -- accounting mirror ---------------------------------------------
    def _record_terminal(self, req: Request) -> None:
        """Re-record the engine-side terminal accounting in the
        supervisor's telemetry: worker counters live in another
        process, but the fleet's audits (counters vs outcomes vs e2e
        histograms) run here."""
        outcome = req.outcome
        if outcome == "result":
            _trace.inc("serve.completed")
        elif outcome == "deadline_exceeded":
            _trace.inc("serve.deadline_exceeded")
            _trace.event("serve.deadline_exceeded", "serving",
                         req=req.req_id, steps_done=req.steps_done)
        elif outcome == "failed":
            _trace.inc("serve.failed")
            _trace.event("serve.request_failed", "serving",
                         req=req.req_id, error=req.error)
        elif outcome == "canceled":
            _trace.inc("serve.canceled")
            _trace.event("serve.canceled", "serving", req=req.req_id,
                         steps_done=req.steps_done,
                         mid_prefill=req.needs_prefill)
        else:
            _trace.inc("serve.shed", reason=req.shed_reason)
            _trace.event("serve.shed", "serving", req=req.req_id,
                         reason=req.shed_reason, error=req.error)
        _trace.inc("serve.tenant", tenant=req.tenant, outcome=outcome)
        if req.terminal_t is not None:
            _hist.observe("serve.e2e.latency",
                          req.terminal_t - req.submit_t,
                          outcome=req.outcome)
        self._cid_of.pop(req.req_id, None)

    def _apply_delta(self, d: dict) -> None:
        req = self._by_cid.get(int(d["cid"]))
        if req is None:
            return
        req.steps_done = int(d["steps_done"])
        req.retries = int(d.get("retries", req.retries))
        tail = [int(t) for t in d.get("generated_tail", [])]
        if tail:
            req.generated.extend(tail)
        req.prefill_pos = int(d.get("prefill_pos", req.prefill_pos))
        req.prefix_tokens = int(d.get("prefix_tokens",
                                      req.prefix_tokens))
        if d.get("first_token") and req.first_token_t is None:
            now = time.monotonic()
            req.first_token_t = now
            _hist.observe("serve.ttft", now - req.submit_t)
            req.trace.mark("first_token",
                           token=(req.generated[0]
                                  if req.generated else None),
                           ttft_ms=round((now - req.submit_t) * 1e3, 3))
        outcome = d.get("outcome")
        if outcome and not req.is_terminal:
            req.finish(outcome, shed_reason=d.get("shed_reason"),
                       error=d.get("error"))
            self._record_terminal(req)
        if req.is_terminal:
            self._by_cid.pop(int(d["cid"]), None)

    def _apply_reply(self, reply: dict) -> None:
        for d in reply.get("deltas", []):
            self._apply_delta(d)
        if "queue_depth" in reply:
            self._queue_depth = int(reply["queue_depth"])
        if "step_failures" in reply:
            self._remote_step_failures = int(reply["step_failures"])

    # -- the engine surface the fleet drives ---------------------------
    def submit(self, context_tokens: int, new_tokens: int = 1,
               **kwargs) -> Request:
        req = Request(context_tokens, new_tokens,
                      deadline_ms=kwargs.get("deadline_ms"),
                      seed=kwargs.get("seed", 0),
                      payload=kwargs.get("payload"),
                      prompt_tokens=kwargs.get("prompt_tokens"),
                      temperature=kwargs.get("temperature", 0.0),
                      top_p=kwargs.get("top_p", 1.0),
                      tenant=kwargs.get("tenant"))
        self.requests.append(req)
        cid = req.req_id
        self._by_cid[cid] = req
        self._cid_of[req.req_id] = cid
        try:
            reply = self._rpc("submit",
                              {"req": serialize_request(req, cid)})
        except Exception as e:  # noqa: BLE001 — death waits for step()
            self._note_death(e)
            return req          # queued shadow: exported at ejection
        if not reply.get("ok") and reply.get("etype") == "ValueError":
            # parity with the in-process engine: a caller bug raises
            # to the submitter and never lingers in accounting
            self.requests.remove(req)
            self._by_cid.pop(cid, None)
            self._cid_of.pop(req.req_id, None)
            raise ValueError(reply.get("error", "invalid request"))
        self._apply_reply(reply)
        if not req.is_terminal:
            req.admit()
            _trace.inc("serve.admitted")
        return req

    def step(self) -> bool:
        if self._pending_death is not None:
            exc, self._pending_death = self._pending_death, None
            raise exc
        timeout = (self.step_timeout_ms
                   if self.step_timeout_ms > 0 else None)
        reply = self._rpc("step", timeout_ms=timeout)
        self._apply_reply(reply)
        return bool(reply.get("progressed"))

    def adopt(self, req: Request, *, source: str = "") -> Request:
        self.requests.append(req)
        cid = req.req_id
        self._by_cid[cid] = req
        self._cid_of[req.req_id] = cid
        try:
            reply = self._rpc("adopt",
                              {"req": serialize_request(req, cid),
                               "source": source})
        except Exception as e:  # noqa: BLE001 — the shadow stays
            self._note_death(e)  # queued; re-exported when this slot
            return req           # is ejected in turn
        self._apply_reply(reply)
        if not req.is_terminal:
            req.trace.mark("readmit", engine=self.name, frm=source,
                           warm=req.prefix_tokens > 0,
                           steps_done=req.steps_done)
            _trace.inc("serve.adopted", engine=self.name)
        return req

    def export_inflight(self) -> List[Request]:
        """The shadows ARE the export: a SIGKILL'd worker cannot answer
        an RPC, so failover reads the supervisor's copies — prompt,
        sampled tokens, deadline, trace identity all intact."""
        exported = []
        for r in [x for x in self.requests if not x.is_terminal]:
            r.prefill_pos = 0
            r.prefix_tokens = 0
            self.requests.remove(r)
            exported.append(r)
        self._by_cid.clear()
        self._cid_of.clear()
        return exported

    def cancel(self, req: Request) -> bool:
        if req.is_terminal:
            return False
        req.cancel_requested = True
        req.trace.mark("cancel", steps_done=req.steps_done,
                       mid_prefill=req.needs_prefill)
        cid = self._cid_of.get(req.req_id)
        if cid is None or self._broken or not self.proc.is_alive():
            req.finish("canceled")
            self._record_terminal(req)
            return True
        try:
            reply = self._rpc("cancel", {"cid": cid})
        except Exception as e:  # noqa: BLE001
            self._note_death(e)
            return True
        self._apply_reply(reply)
        return True

    def drain(self) -> None:
        self._draining = True
        if self._broken:
            return
        try:
            self._rpc("drain")
        except Exception as e:  # noqa: BLE001
            self._note_death(e)

    def warmup(self) -> int:
        if self._broken:
            return 0
        reply = self._rpc("warmup",
                          timeout_ms=_WARMUP_DEADLINE_S * 1e3)
        return int(reply.get("warmed", 0))

    def run(self, max_steps: Optional[int] = None) -> int:
        if max_steps == 0:
            # the fleet's bound-tripped force-retire
            if self._broken or not self.proc.is_alive():
                for r in [x for x in self.requests
                          if not x.is_terminal]:
                    r.finish("failed",
                             error="force-retired: worker down")
                    self._record_terminal(r)
            else:
                try:
                    self._apply_reply(self._rpc("force_retire"))
                except Exception as e:  # noqa: BLE001
                    self._note_death(e)
            return 0
        bound = max_steps if max_steps is not None else self.pump_bound()
        n = 0
        while n < bound:
            if not self.step():
                return n
            n += 1
        return n

    def pump_bound(self) -> int:
        chunk = max(1, env.TL_TPU_SERVE_PREFILL_CHUNK)
        total = sum(r.new_tokens
                    + math.ceil(r.context_tokens / chunk)
                    for r in self.requests) or 1
        return 20 * total + 100

    def pull_snapshot(self):
        """Fetch the worker's whole live KV as one checksummed
        snapshot frame (verified on decode) — the cross-process
        counterpart of ``allocator.snapshot()``."""
        from .ipc import decode_snapshot
        if self._broken:
            raise (self._pending_death or
                   DeviceLossError(f"worker {self.name} is down",
                                   site="fleet.ipc", backend="proc"))
        frame = encode_frame({"op": "snapshot"})
        self._conn.send_bytes(frame)
        _trace.inc("fleet.ipc.tx", engine=self.name)
        _, _ = None, None
        deadline = time.monotonic() + self.ipc_timeout_ms / 1e3
        while not self._conn.poll(0.005):
            if not self.proc.is_alive():
                raise self._death_error()
            if time.monotonic() > deadline:
                raise TLTimeoutError(
                    f"worker {self.name} snapshot round-trip timed "
                    f"out", site="fleet.ipc")
        data = self._conn.recv_bytes()
        _trace.inc("fleet.ipc.rx", engine=self.name)
        _trace.inc("fleet.ipc.bytes_rx", len(data), engine=self.name)
        self.last_heartbeat = time.monotonic()
        return decode_snapshot(data)

    # -- levels / accounting -------------------------------------------
    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def queue_depth(self) -> int:
        return self._queue_depth

    @property
    def step_failures(self) -> int:
        return self._remote_step_failures

    def outcomes(self) -> Dict[str, int]:
        out = {"result": 0, "shed": 0, "deadline_exceeded": 0,
               "failed": 0, "canceled": 0, "pending": 0}
        for r in self.requests:
            out[r.outcome or "pending"] += 1
        return out

    def _kv_levels(self) -> dict:
        if self._broken or not self.proc.is_alive():
            return {"in_use": 0, "free_pages": 0}
        try:
            return self._rpc("kv")
        except Exception as e:  # noqa: BLE001
            self._note_death(e)
            return {"in_use": 0, "free_pages": 0}

    def _leak_check(self) -> dict:
        if self._broken or not self.proc.is_alive():
            return {}
        try:
            return dict(self._rpc("leak_check").get("leaks", {}))
        except Exception as e:  # noqa: BLE001
            self._note_death(e)
            return {}

    def rss_kb(self) -> Optional[int]:
        try:
            with open(f"/proc/{self.pid}/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        return int(line.split()[1])
        except (OSError, ValueError, IndexError):
            return None
        return None

    def proc_health(self) -> dict:
        return {
            "pid": self.pid,
            "alive": self.proc.is_alive(),
            "rss_kb": self.rss_kb(),
            "heartbeat_age_ms": round(
                (time.monotonic() - self.last_heartbeat) * 1e3, 1),
            "uptime_s": round(time.monotonic() - self.spawned_t, 3),
        }

    def stats(self) -> dict:
        out = {"engine": self.name, "isolation": "proc",
               "pid": self.pid, "alive": self.proc.is_alive(),
               "requests": len(self.requests),
               "outcomes": self.outcomes(),
               "queue_depth": self._queue_depth,
               "draining": self._draining}
        if not self._broken and self.proc.is_alive():
            try:
                out["worker"] = self._rpc("stats").get("stats", {})
            except Exception as e:  # noqa: BLE001
                self._note_death(e)
        return out

    def close(self, graceful: bool = False,
              timeout_s: float = 5.0) -> Optional[int]:
        """Tear the worker down; returns its exit code. Graceful sends
        the shutdown RPC (drain + finish + prefix flush, exit 0);
        otherwise (and as escalation) terminate → kill."""
        try:
            if graceful and not self._broken and self.proc.is_alive():
                try:
                    reply = self._rpc("shutdown", {"graceful": True},
                                      timeout_ms=max(
                                          self.ipc_timeout_ms,
                                          env.TL_TPU_FLEET_DRAIN_TIMEOUT_MS))
                    self._apply_reply(reply)
                except Exception:  # noqa: BLE001 — escalate below
                    pass
            if self.proc.is_alive():
                self.proc.join(timeout=timeout_s if graceful else 0.0)
            if self.proc.is_alive():
                self.proc.terminate()
                self.proc.join(timeout=2.0)
            if self.proc.is_alive():
                self.proc.kill()
                self.proc.join(timeout=2.0)
        finally:
            try:
                self._conn.close()
            except OSError:
                pass
        return self.proc.exitcode
