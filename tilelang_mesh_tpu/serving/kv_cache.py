"""Paged KV-cache allocator: a slab freelist over H-major page pools.

The pools are the persistent serving-system layout
``ops/flash_decoding.pages_to_hmajor`` documents: ``(H, n_pages *
page_size, D)`` numpy arrays the in-kernel page walker
(``flash_decode_paged_pool``) DMAs at table-driven offsets. numpy, not
jax: pages are filled in place as tokens arrive, and the PR 7 zero-copy
``to_jax`` path hands the aligned C-contiguous pool to the kernel
without a copy on the host platform.

Accounting contract (the chaos soak gates on it):

- every ``alloc`` names an owner (request id); ``free`` checks the
  pages back in against that owner — freeing a page twice or freeing
  someone else's page raises instead of corrupting the freelist;
- ``leak_check()`` lists owners still holding pages — after every
  request has retired, it must be empty and ``in_use == 0``;
- ``serve.kv`` is the fault site on the alloc path (an injected fault
  there exercises the engine's mid-flight KV-failure handling);
- allocs/frees land in ``serve.kv.alloc_pages`` / ``serve.kv.free_pages``
  counters, so trace artifacts can replay the balance.

Migration contract (the elastic mesh path, docs/serving.md): a live
reshard moves every in-use slab between allocators through a
checksummed :class:`KVSnapshot` — ``snapshot()`` captures the live
pages + owner map with a sha256 over the page bytes, ``restore()``
repacks them into a (possibly smaller) target allocator, re-verifies
the checksum on the bytes it actually wrote, and returns the
old-page -> new-page mapping the engine rewrites request holdings
with. A snapshot restores exactly once (double restore would hand the
same slabs to two allocators) and byte conservation is asserted, not
assumed — the ``--serve-mesh`` chaos soak gates on it.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..observability import tracer as _trace
from ..resilience import faults as _faults
from ..resilience.errors import TLError

__all__ = ["KVCacheExhausted", "KVSnapshot", "PagedKVAllocator", "migrate"]


class KVCacheExhausted(TLError):
    """No free slabs left. Transient at admission time (the request is
    shed, capacity frees as in-flight work retires)."""
    kind = "transient"


def _page_digest(h, page: int, k: np.ndarray, v: np.ndarray) -> int:
    """Feed one page's identity + bytes into a running sha256."""
    h.update(str(page).encode())
    k = np.ascontiguousarray(k)
    v = np.ascontiguousarray(v)
    h.update(k.tobytes())
    h.update(v.tobytes())
    return k.nbytes + v.nbytes


@dataclasses.dataclass
class KVSnapshot:
    """Checksummed capture of every LIVE slab of one allocator — the
    unit of KV migration across a reshard. ``owners`` preserves each
    request's page ORDER (page sequence is token order); ``pages`` maps
    page id -> ``(k, v)`` copies of shape ``(H, page_size, D)``."""

    page_size: int
    heads: int
    head_dim: int
    dtype: np.dtype
    owners: Dict[int, List[int]]
    pages: Dict[int, Tuple[np.ndarray, np.ndarray]]
    checksum: str
    nbytes: int
    consumed: bool = False

    @property
    def n_pages(self) -> int:
        return len(self.pages)

    def verify(self) -> None:
        """Recompute the checksum over the held bytes; raises on a
        corrupted snapshot (bit-rot between snapshot and restore)."""
        h = hashlib.sha256()
        n = 0
        for page in sorted(self.pages):
            k, v = self.pages[page]
            n += _page_digest(h, page, k, v)
        if h.hexdigest() != self.checksum or n != self.nbytes:
            raise ValueError(
                f"KV snapshot corrupted: checksum mismatch over "
                f"{len(self.pages)} page(s) ({n} bytes)")


class PagedKVAllocator:
    """Slab freelist over two H-major page pools (K and V)."""

    def __init__(self, n_pages: int, page_size: int, heads: int,
                 head_dim: int, dtype: str = "float32"):
        if n_pages <= 0 or page_size <= 0:
            raise ValueError("n_pages and page_size must be positive")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self.heads = int(heads)
        self.head_dim = int(head_dim)
        self.dtype = np.dtype(dtype)
        rows = self.n_pages * self.page_size
        # H-major pools (H, rows, D): the layout the in-kernel page walk
        # wants, maintained persistently (not transformed per call)
        self.kp = np.zeros((self.heads, rows, self.head_dim), self.dtype)
        self.vp = np.zeros((self.heads, rows, self.head_dim), self.dtype)
        self._lock = threading.Lock()
        self._free: List[int] = list(range(self.n_pages - 1, -1, -1))
        self._owned: Dict[int, List[int]] = {}    # owner -> page ids
        self.alloc_count = 0
        self.free_count = 0

    # -- capacity ------------------------------------------------------
    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def in_use(self) -> int:
        with self._lock:
            return self.n_pages - len(self._free)

    # -- alloc / free --------------------------------------------------
    def alloc(self, n: int, owner: int) -> List[int]:
        """Check out ``n`` pages for ``owner`` (a request id). Raises
        :class:`KVCacheExhausted` when fewer than ``n`` are free —
        atomically, so a partially satisfied alloc can never leak."""
        _faults.maybe_fail("serve.kv", owner=owner, pages=n)
        with self._lock:
            if len(self._free) < n:
                raise KVCacheExhausted(
                    f"KV cache exhausted: {n} page(s) requested, "
                    f"{len(self._free)}/{self.n_pages} free",
                    site="serve.kv")
            pages = [self._free.pop() for _ in range(n)]
            self._owned.setdefault(owner, []).extend(pages)
            self.alloc_count += n
        _trace.inc("serve.kv.alloc_pages", n)
        return pages

    def free(self, owner: int,
             pages: Optional[List[int]] = None) -> int:
        """Return ``pages`` (default: everything ``owner`` holds) to the
        freelist. Freeing a page the owner does not hold raises — a
        double free would hand one slab to two requests."""
        with self._lock:
            held = self._owned.get(owner, [])
            if pages is None:
                pages = list(held)
            for p in pages:
                if p not in held:
                    raise ValueError(
                        f"request {owner} does not hold page {p} "
                        f"(double free or foreign free)")
            for p in pages:
                held.remove(p)
                self._free.append(p)
            if not held:
                self._owned.pop(owner, None)
            self.free_count += len(pages)
        if pages:
            _trace.inc("serve.kv.free_pages", len(pages))
        return len(pages)

    # -- page filling --------------------------------------------------
    def row0(self, page: int) -> int:
        """First pool row of ``page`` (token t of the page is row0+t)."""
        if not 0 <= page < self.n_pages:
            raise IndexError(f"page {page} out of range")
        return page * self.page_size

    def write_token(self, page: int, offset: int, k: np.ndarray,
                    v: np.ndarray) -> None:
        """Write one token's per-head K/V vectors ``(H, D)`` into
        ``page`` at token ``offset`` — the in-place append a decode
        step performs."""
        if not 0 <= offset < self.page_size:
            raise IndexError(f"token offset {offset} out of page "
                             f"(size {self.page_size})")
        row = self.row0(page) + offset
        self.kp[:, row, :] = k
        self.vp[:, row, :] = v

    def write_span(self, page: int, offset: int, k: np.ndarray,
                   v: np.ndarray) -> None:
        """Write ``n`` consecutive tokens' ``(H, n, D)`` K/V blocks
        into ``page`` starting at token ``offset`` — the bulk write one
        prefill chunk performs (a chunk crossing a page boundary issues
        one span per page)."""
        n = int(k.shape[1])
        if not (0 <= offset and offset + n <= self.page_size):
            raise IndexError(
                f"token span [{offset}, {offset + n}) out of page "
                f"(size {self.page_size})")
        row = self.row0(page) + offset
        self.kp[:, row:row + n, :] = k
        self.vp[:, row:row + n, :] = v

    def fill_page(self, page: int, k: np.ndarray, v: np.ndarray) -> None:
        """Bulk-fill one page from ``(H, page_size, D)`` arrays (context
        ingestion at admission)."""
        r0 = self.row0(page)
        self.kp[:, r0:r0 + self.page_size, :] = k
        self.vp[:, r0:r0 + self.page_size, :] = v

    # -- migration (elastic reshard) -----------------------------------
    def snapshot(self) -> KVSnapshot:
        """Checksummed copy of every live slab + the owner map — what a
        reshard carries across allocators. Free pages are not captured
        (their contents are garbage by contract)."""
        with self._lock:
            owners = {o: list(p) for o, p in self._owned.items() if p}
        h = hashlib.sha256()
        nbytes = 0
        pages: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        for page in sorted(p for held in owners.values() for p in held):
            r0 = page * self.page_size
            k = self.kp[:, r0:r0 + self.page_size, :].copy()
            v = self.vp[:, r0:r0 + self.page_size, :].copy()
            nbytes += _page_digest(h, page, k, v)
            pages[page] = (k, v)
        return KVSnapshot(page_size=self.page_size, heads=self.heads,
                          head_dim=self.head_dim, dtype=self.dtype,
                          owners=owners, pages=pages,
                          checksum=h.hexdigest(), nbytes=nbytes)

    def restore(self, snap: KVSnapshot) -> Dict[int, int]:
        """Repack a snapshot's live slabs into THIS allocator: allocate
        fresh pages per owner (order preserved), write the bytes back,
        re-verify the checksum on what was actually written, and return
        the old-page -> new-page mapping the engine rewrites request
        holdings with. The target may be smaller than the source (a
        reshard onto fewer slices) as long as it has capacity for the
        LIVE pages; a snapshot restores exactly once."""
        if snap.consumed:
            raise ValueError(
                "KV snapshot already restored; restoring it twice would "
                "hand the same slabs to two allocators")
        if (snap.page_size, snap.heads, snap.head_dim) != \
                (self.page_size, self.heads, self.head_dim) or \
                snap.dtype != self.dtype:
            raise ValueError(
                f"KV snapshot geometry (ps={snap.page_size}, "
                f"H={snap.heads}, D={snap.head_dim}, {snap.dtype}) does "
                f"not match this allocator (ps={self.page_size}, "
                f"H={self.heads}, D={self.head_dim}, {self.dtype})")
        snap.verify()
        need = snap.n_pages
        if self.free_pages < need:
            raise KVCacheExhausted(
                f"cannot restore KV snapshot: {need} live page(s), "
                f"{self.free_pages}/{self.n_pages} free in the target",
                site="serve.kv")
        mapping: Dict[int, int] = {}
        restored: List[Tuple[int, int]] = []   # (owner, new page) undo log
        try:
            for owner in sorted(snap.owners):
                for old in snap.owners[owner]:
                    new = self.alloc(1, owner)[0]
                    restored.append((owner, new))
                    k, v = snap.pages[old]
                    self.fill_page(new, k, v)
                    mapping[old] = new
            # byte conservation, asserted on the WRITTEN bytes: re-read
            # the target pages and re-derive the digest under the OLD
            # page ids (the mapping is the identity of the migration,
            # not the bytes)
            h = hashlib.sha256()
            nbytes = 0
            for old in sorted(mapping):
                r0 = mapping[old] * self.page_size
                nbytes += _page_digest(
                    h, old, self.kp[:, r0:r0 + self.page_size, :],
                    self.vp[:, r0:r0 + self.page_size, :])
            if h.hexdigest() != snap.checksum or nbytes != snap.nbytes:
                raise ValueError(
                    f"KV migration corrupted {need} page(s) in flight: "
                    f"restored bytes do not match the snapshot checksum")
        except Exception:
            # a mid-restore failure (injected serve.kv fault, a
            # corrupted write caught by the conservation check) must
            # not leak half the migration into the target
            for owner, new in restored:
                self.free(owner, [new])
            raise
        snap.consumed = True
        _trace.inc("serve.kv.migrated_pages", need)
        _trace.inc("serve.kv.migrated_bytes", nbytes)
        return mapping

    # -- accounting ----------------------------------------------------
    def holdings(self, owner: int) -> List[int]:
        with self._lock:
            return list(self._owned.get(owner, []))

    def leak_check(self) -> Dict[int, List[int]]:
        """owner -> still-held pages. Empty after every request retired,
        or the retirement path leaked slabs."""
        with self._lock:
            return {o: list(p) for o, p in self._owned.items() if p}

    def stats(self) -> dict:
        with self._lock:
            return {
                "n_pages": self.n_pages,
                "page_size": self.page_size,
                "free": len(self._free),
                "in_use": self.n_pages - len(self._free),
                "alloc_count": self.alloc_count,
                "free_count": self.free_count,
                "owners": len(self._owned),
            }


def migrate(src: PagedKVAllocator,
            dst: PagedKVAllocator) -> Tuple[Dict[int, int], int]:
    """Move every live slab from ``src`` to ``dst`` in one audited
    step: snapshot (checksummed), restore (byte-conservation verified),
    then release the source's slabs so BOTH allocators' books balance —
    the global ``serve.kv.alloc_pages``/``free_pages`` counters stay
    replayable across a reshard. Returns ``(old -> new page mapping,
    bytes migrated)``. On a restore failure nothing moves: the source
    keeps its slabs and the exception propagates."""
    snap = src.snapshot()
    mapping = dst.restore(snap)
    for owner in snap.owners:
        src.free(owner)
    return mapping, snap.nbytes
