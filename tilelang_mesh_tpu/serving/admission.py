"""Admission control + load shedding for the serving engine.

Reject-at-admit is the cheapest place to protect the system: a request
that cannot possibly be served (dead backend chain, full queue, no KV
capacity, infeasible deadline) is shed with a named reason BEFORE it
holds any resource. Decisions are wired to the machinery that already
exists instead of new heuristics:

- **circuit breaker** — the engine feeds every deterministic step
  failure into ``global_breaker()`` under the rolled-up signature
  ``serve.step`` (alongside the per-error signature the rest of the
  stack uses); once that circuit opens, admission sheds new arrivals
  until the operator resets it (``breaker_open``).
- **queue depth** — bounded by ``TL_TPU_SERVE_MAX_QUEUE``
  (``queue_full``).
- **p99 pressure** — the PR 3 ``kernel.latency`` histograms: the
  engine records every batch step under ``kernel=serve.step,
  source=serving``; when the observed p99 exceeds
  ``TL_TPU_SERVE_P99_BUDGET_MS`` (opt-in), new arrivals shed
  (``overload``).
- **KV capacity** — the slab freelist must cover the request's
  worst-case page footprint (``kv_exhausted``).
- **deadline feasibility** — a request whose deadline cannot be met
  even at the observed p50 step latency (queue wait included) is shed
  immediately (``deadline_infeasible``) instead of burning a slot and
  expiring later.
- **drain mode** — a draining engine finishes in-flight work and sheds
  every new arrival (``draining``).
- **tenant share** — opt-in fairness cap
  (``TL_TPU_SERVE_TENANT_MAX_SHARE`` < 1.0): a tenant already holding
  that fraction of the queue capacity sheds its new arrivals
  (``tenant_share``) so one hot tenant cannot crowd every slot.

``serve.admit`` is the fault site on this path: an injected fault is
accounted as ``admit_fault`` shedding, never an exception to the
caller — admission itself must not become a crash surface.
"""

from __future__ import annotations

from typing import Optional, Tuple

from ..env import env
from ..observability import histogram as _hist
from ..resilience.retry import global_breaker

__all__ = ["AdmissionController", "SERVE_BREAKER_SIG", "STEP_HIST_KERNEL"]

# the rolled-up breaker signature serving feeds and checks (per-error
# signatures additionally flow through error_signature() as everywhere)
SERVE_BREAKER_SIG = "serve.step"

# the kernel.latency label serving's batch steps record under — the
# PR 3 histogram admission reads its p50/p99 from
STEP_HIST_KERNEL = "serve.step"


def step_histogram() -> Optional["_hist.Histogram"]:
    return _hist.get_histogram("kernel.latency", kernel=STEP_HIST_KERNEL,
                               source="serving")


def observed_step_ms(q: float, default_ms: float = 0.0) -> float:
    """Quantile ``q`` of the recorded serve.step latency, in ms
    (``default_ms`` until anything was recorded — warm-up records one
    dispatch per bucket, so a warmed engine always has an estimate)."""
    h = step_histogram()
    if h is None or h.count == 0:
        return default_ms
    v = h.quantile(q)
    return v * 1e3 if v is not None else default_ms


class AdmissionController:
    """Pure decision logic; the engine owns state transitions."""

    def __init__(self, *, max_queue: Optional[int] = None,
                 p99_budget_ms: Optional[float] = None,
                 grace_ms: Optional[float] = None):
        self.max_queue = (max_queue if max_queue is not None
                          else env.TL_TPU_SERVE_MAX_QUEUE)
        self.p99_budget_ms = (p99_budget_ms if p99_budget_ms is not None
                              else env.TL_TPU_SERVE_P99_BUDGET_MS)
        self.grace_ms = (grace_ms if grace_ms is not None
                         else env.TL_TPU_SERVE_GRACE_MS)
        self.tenant_max_share = env.TL_TPU_SERVE_TENANT_MAX_SHARE

    def decide(self, *, draining: bool, queue_depth: int,
               free_pages: int, pages_needed: int,
               remaining_s: Optional[float],
               steps_requested: int,
               prefill_chunks: int = 0,
               tenant_inflight: int = 0) -> Tuple[bool, Optional[str]]:
        """(admit?, shed reason). Ordered so the cheapest checks run
        first and the reason names the FIRST gate that failed.
        ``tenant_inflight`` is how many queued requests the arriving
        request's tenant already holds."""
        if draining:
            return False, "draining"
        if queue_depth >= self.max_queue:
            return False, "queue_full"
        if self.tenant_max_share < 1.0 and \
                tenant_inflight >= self.tenant_max_share * self.max_queue:
            return False, "tenant_share"
        if global_breaker().is_open(SERVE_BREAKER_SIG):
            return False, "breaker_open"
        if free_pages < pages_needed:
            return False, "kv_exhausted"
        if self.p99_budget_ms > 0:
            p99 = observed_step_ms(0.99)
            if p99 > self.p99_budget_ms:
                return False, "overload"
        if env.TL_TPU_SLO_ADMIT and self._slo_burning():
            return False, "overload"
        if remaining_s is not None:
            # feasibility at the OBSERVED p50: the queue ahead (in
            # batches, optimistically one step each) plus this
            # request's own steps AND its worst-case prefill chunk
            # units (deadline propagation into the chunked-prefill
            # path — a prompt too long for its deadline sheds at the
            # door instead of expiring mid-prefill) must fit in
            # deadline + grace
            p50_s = observed_step_ms(0.50) / 1e3
            need_s = p50_s * (queue_depth + steps_requested
                              + max(0, prefill_chunks - 1))
            if remaining_s + self.grace_ms / 1e3 < need_s or \
                    remaining_s <= 0:
                return False, "deadline_infeasible"
        return True, None

    @staticmethod
    def _slo_burning() -> bool:
        """Opt-in (``TL_TPU_SLO_ADMIT=1``) windowed overload gate: shed
        while the SLO engine's fast-burn window spends error budget
        faster than ``TL_TPU_SLO_BURN_MAX`` — the multi-window sibling
        of the lifetime-p99 gate above (docs/observability.md)."""
        try:
            from ..observability.slo import get_slo
            burn = get_slo().fast_burn_rate()   # cached per SLO tick
            return burn is not None and burn > env.TL_TPU_SLO_BURN_MAX
        except Exception:  # noqa: BLE001 — a broken SLO gate must
            return False   # never shed (fail open, like admit_fault)
