"""Request lifecycle for the serving engine.

The state machine is the product (docs/serving.md):

    queued -> admitted -> batched -> terminal

with exactly five terminal outcomes — ``result`` (the request finished
its decode steps), ``shed`` (admission control rejected it, with a
named reason), ``deadline_exceeded`` (its deadline + grace passed while
queued, in flight, or during a retry), ``failed`` (a deterministic
error retired it), ``canceled`` (the client abandoned it — a closed
stream, an explicit ``engine.cancel()`` — and its KV slabs were freed
mid-request). The engine's contract is that EVERY submitted request
reaches one of the five: no silent drops, no unbounded waits.
``batched`` flips back to ``admitted`` between decode steps — that
re-queueing is what makes the batching *continuous* (a half-finished
request shares its next batch with newly admitted ones). A request
with a prompt longer than one prefill chunk additionally spends time
``admitted`` while its context fills chunk by chunk (the engine
interleaves those chunk units with decode steps).

Every transition is stamped (monotonic clock) into ``timeline`` so the
chaos soak can prove the zero-hang guarantee per request instead of
globally.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Dict, List, Optional

from ..observability import reqtrace as _reqtrace

__all__ = ["Request", "STATES", "OUTCOMES", "SHED_REASONS"]

# non-terminal states, in lifecycle order
STATES = ("queued", "admitted", "batched", "terminal")

# the five terminal outcomes — the whole vocabulary; accounting keys on
# these strings, so they never grow ad hoc
OUTCOMES = ("result", "shed", "deadline_exceeded", "failed", "canceled")

# the admission-control shed vocabulary (admission.py decides, the
# engine records ``serve.shed{reason=}``); ``retry_budget`` is the one
# mid-flight shed: a transient step failure whose deadline headroom
# cannot absorb another attempt; ``tenant_share`` is the per-tenant
# fairness gate (one tenant holding more than its configured share of
# the queue); ``failover`` is the fleet's last resort — an engine died
# and no healthy peer could adopt the request
SHED_REASONS = ("draining", "queue_full", "breaker_open", "kv_exhausted",
                "deadline_infeasible", "overload", "admit_fault",
                "retry_budget", "tenant_share", "failover")

_req_seq = itertools.count(1)


def default_prompt(seed: int, n: int) -> list:
    """Deterministic seed-derived prompt token ids (the stand-in for a
    tokenizer): identical ``(seed, n)`` pairs share a prompt — and
    therefore a prefix-cache content address — by construction."""
    import numpy as np
    rng = np.random.default_rng((int(seed), 0x70))
    return [int(t) for t in rng.integers(0, 1 << 30, size=int(n))]


class Request:
    """One inference request: a paged KV context plus ``new_tokens``
    decode steps to run. ``deadline_ms`` is relative to submission and
    converted to an absolute monotonic stamp at construction so it can
    propagate (retry budgets, step watchdog caps) without re-reading
    clocks ambiguously."""

    __slots__ = ("req_id", "context_tokens", "new_tokens", "deadline",
                 "submit_t", "seed", "state", "outcome", "shed_reason",
                 "error", "result", "steps_done", "retries", "pages",
                 "tail_tokens", "timeline", "terminal_t", "first_batch_t",
                 "payload", "trace", "_step_span", "prompt_tokens",
                 "temperature", "top_p", "generated", "prefill_pos",
                 "prefix_tokens", "cancel_requested", "first_token_t",
                 "tenant")

    def __init__(self, context_tokens: int, new_tokens: int = 1,
                 deadline_ms: Optional[float] = None, seed: int = 0,
                 payload: Optional[Dict[str, Any]] = None,
                 prompt_tokens: Optional[List[int]] = None,
                 temperature: float = 0.0, top_p: float = 1.0,
                 tenant: Optional[str] = None):
        if context_tokens <= 0:
            raise ValueError("context_tokens must be positive")
        if new_tokens <= 0:
            raise ValueError("new_tokens must be positive")
        if not (0.0 < top_p <= 1.0):
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        self.req_id = next(_req_seq)
        self.context_tokens = int(context_tokens)
        self.new_tokens = int(new_tokens)
        self.submit_t = time.monotonic()
        self.deadline = (self.submit_t + deadline_ms / 1e3
                         if deadline_ms is not None else None)
        self.seed = int(seed)
        self.payload = payload or {}
        # fairness label: admission shares and batch round-robin key on
        # it; untagged callers all land in "default" (exactly the old
        # single-tenant behavior)
        self.tenant = str(tenant) if tenant else "default"
        # the prompt as token ids — the content address of the prefix
        # cache and the input of the stand-in KV derivation; defaults
        # to a seed-derived deterministic prompt so every pre-prompt
        # caller keeps its exact behavior
        if prompt_tokens is None:
            prompt_tokens = default_prompt(self.seed, self.context_tokens)
        prompt_tokens = [int(t) for t in prompt_tokens]
        if len(prompt_tokens) != self.context_tokens:
            raise ValueError(
                f"prompt_tokens has {len(prompt_tokens)} token(s) but "
                f"context_tokens={self.context_tokens}")
        self.prompt_tokens = prompt_tokens
        # sampling knobs (serving/sampling.py): temperature 0 = greedy
        self.temperature = float(temperature)
        self.top_p = float(top_p)
        self.generated: List[int] = []   # sampled token ids, in order
        self.state = "queued"
        self.outcome: Optional[str] = None
        self.shed_reason: Optional[str] = None
        self.error: Optional[str] = None
        self.result = None           # last decode step's output (np array)
        self.steps_done = 0
        self.retries = 0
        self.pages: List[int] = []   # allocator page ids owned right now
        self.tail_tokens = 0         # tokens in the (uncommitted) tail page
        self.prefill_pos = 0         # prompt tokens whose KV is filled
        self.prefix_tokens = 0       # of those, restored from the cache
        self.cancel_requested = False
        self.timeline: List[tuple] = [("queued", self.submit_t)]
        self.terminal_t: Optional[float] = None
        self.first_batch_t: Optional[float] = None
        self.first_token_t: Optional[float] = None   # TTFT stamp
        # tl-scope causal chain (observability/reqtrace.py): every
        # lifecycle transition below lands in it, so a terminal
        # request's whole story — submit, admit, every decode step,
        # every requeue/retry, the outcome — is reconstructible even
        # with TL_TPU_TRACE off. The root "submit" span closes at the
        # admission decision; step spans open at batch() and close at
        # requeue()/finish().
        self.trace = _reqtrace.start_trace(
            "request", req=self.req_id, ctx=self.context_tokens,
            steps=self.new_tokens, deadline_ms=deadline_ms)
        self._step_span: Optional[int] = self.trace.span("submit")

    @property
    def trace_id(self) -> str:
        return self.trace.trace_id

    @property
    def needs_prefill(self) -> bool:
        """True while prompt tokens remain to fill — the request sits
        ``admitted`` in the queue as schedulable prefill-chunk work and
        is not yet eligible for a decode batch."""
        return self.prefill_pos < self.context_tokens

    # -- transitions ---------------------------------------------------
    def _stamp(self, state: str) -> None:
        self.state = state
        self.timeline.append((state, time.monotonic()))

    def _close_step(self, **attrs) -> None:
        if self._step_span is not None:
            self.trace.close_span(self._step_span, **attrs)
            self._step_span = None

    def admit(self) -> None:
        self._close_step(outcome="admitted")
        self._stamp("admitted")

    def batch(self) -> None:
        if self.first_batch_t is None:
            self.first_batch_t = time.monotonic()
        self._close_step()    # defensive: a step span must never nest
        self._step_span = self.trace.span("decode.step",
                                          step=self.steps_done + 1)
        self._stamp("batched")

    def requeue(self) -> None:
        """Back to the queue — between decode steps (continuous
        batching) or on a retryable step failure."""
        self._close_step(outcome="requeue")
        self.trace.mark("requeue", steps_done=self.steps_done,
                        retries=self.retries)
        self._stamp("admitted")

    def finish(self, outcome: str, *, shed_reason: Optional[str] = None,
               error: Optional[str] = None) -> None:
        if outcome not in OUTCOMES:
            raise ValueError(f"unknown outcome {outcome!r}")
        if self.is_terminal:
            raise RuntimeError(
                f"request {self.req_id} already terminal "
                f"({self.outcome}); double retirement is a scheduler bug")
        self.outcome = outcome
        self.shed_reason = shed_reason
        self.error = error
        self.terminal_t = time.monotonic()
        self._close_step(outcome=outcome)
        self.trace.finish(outcome, shed_reason=shed_reason, error=error,
                          steps_done=self.steps_done)
        self._stamp("terminal")

    # -- deadline arithmetic -------------------------------------------
    @property
    def is_terminal(self) -> bool:
        return self.outcome is not None

    def remaining_s(self, now: Optional[float] = None) -> Optional[float]:
        """Seconds until the deadline (negative = past it); None when
        the request has no deadline."""
        if self.deadline is None:
            return None
        return self.deadline - (time.monotonic() if now is None else now)

    def expired(self, grace_s: float = 0.0,
                now: Optional[float] = None) -> bool:
        r = self.remaining_s(now)
        return r is not None and r < -grace_s

    def __repr__(self):
        tail = self.outcome or self.state
        return (f"Request(#{self.req_id}, ctx={self.context_tokens}, "
                f"new={self.new_tokens}, steps={self.steps_done}, {tail})")


# process-wide live-gauge snapshot the engines publish into and
# metrics_summary()["serving"] reads (tracer counters are monotonic;
# queue depth / slabs-in-use are levels, so they live here); _META is
# the string-valued sibling (active mesh layout name — a level too,
# just not a number)
_GAUGE_LOCK = threading.Lock()
_GAUGES: Dict[str, float] = {}
_META: Dict[str, str] = {}


def publish_gauges(**values: float) -> None:
    with _GAUGE_LOCK:
        _GAUGES.update(values)


def gauges() -> Dict[str, float]:
    with _GAUGE_LOCK:
        return dict(_GAUGES)


def publish_meta(**values: str) -> None:
    with _GAUGE_LOCK:
        _META.update({k: str(v) for k, v in values.items()})


def serving_meta() -> Dict[str, str]:
    with _GAUGE_LOCK:
        return dict(_META)


def clear_gauges(*names: str) -> None:
    """Drop named level-gauges (e.g. ``shard_skew`` on a reshard: the
    old layout's straggler signal must not outlive its mesh)."""
    with _GAUGE_LOCK:
        for n in names:
            _GAUGES.pop(n, None)


def reset_gauges() -> None:
    with _GAUGE_LOCK:
        _GAUGES.clear()
        _META.clear()
