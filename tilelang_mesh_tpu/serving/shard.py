"""Sharding hooks for serving workloads: regex partition-rule tables.

The serving engine runs single-host today, but its data layout is
designed to shard: the KV pools are head-major precisely so the head
axis can split across a mesh. This module provides the two idioms the
related serving stacks use (SNIPPETS.md [1] ``match_partition_rules``
regex -> PartitionSpec, [2] per-tensor ``ShardConfig`` dataclass),
adapted to the engine's tensor names, so a mesh-backed workload can
derive ``in_specs`` for its pools/queries without hand-writing specs
per bucket.

Rules are ``(regex, PartitionSpec)`` pairs matched IN ORDER against
slash-separated tensor names (first match wins; scalars are never
partitioned); unmatched names raise — a silently replicated KV pool is
a capacity bug, not a default.
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Sequence, Tuple

__all__ = ["ServeShardConfig", "match_partition_rules"]


def _pspec(*axes):
    from jax.sharding import PartitionSpec
    return PartitionSpec(*axes)


def match_partition_rules(rules: Sequence[Tuple[str, object]],
                          names: Sequence[str]) -> List[object]:
    """PartitionSpec per tensor name: first regex match wins (the
    SNIPPETS.md [1] idiom, over a flat name list instead of a pytree —
    the engine's tensors are a fixed small set, not model params)."""
    out = []
    for name in names:
        for rule, spec in rules:
            if re.search(rule, name) is not None:
                out.append(spec)
                break
        else:
            raise ValueError(f"no partition rule matches {name!r}")
    return out


@dataclasses.dataclass(frozen=True)
class ServeShardConfig:
    """Per-tensor sharding layout of a serving workload (the
    SNIPPETS.md [2] ``ShardConfig`` idiom): one PartitionSpec per
    engine tensor, with named constructors for the two layouts that
    matter. Axis names refer to the 2-D device mesh ("x", "y")."""

    kv_pool_hrd: object       # (H, rows, D) K/V page pools
    query_bhld: object        # (B, H, 1, D) step queries
    table_bp: object          # (B, pages) page tables
    out_bhld: object          # (B, H, 1, D) step outputs

    @staticmethod
    def no_sharding() -> "ServeShardConfig":
        """Single-host serving (the default engine layout)."""
        return ServeShardConfig(kv_pool_hrd=_pspec(),
                                query_bhld=_pspec(),
                                table_bp=_pspec(),
                                out_bhld=_pspec())

    @staticmethod
    def head_parallel(axis: str = "x") -> "ServeShardConfig":
        """Split the head axis of pools/queries/outputs over one mesh
        axis — the natural decode sharding (each device walks its own
        heads' pages; the page table replicates)."""
        return ServeShardConfig(kv_pool_hrd=_pspec(axis),
                                query_bhld=_pspec(None, axis),
                                table_bp=_pspec(),
                                out_bhld=_pspec(None, axis))

    @staticmethod
    def batch_parallel(axis: str = "x") -> "ServeShardConfig":
        """Split the batch axis — data-parallel serving replicas with a
        replicated KV pool (small models, large fleets)."""
        return ServeShardConfig(kv_pool_hrd=_pspec(),
                                query_bhld=_pspec(axis),
                                table_bp=_pspec(axis),
                                out_bhld=_pspec(axis))

    def rules(self) -> List[Tuple[str, object]]:
        """This config as a ``match_partition_rules`` table."""
        return [(r"kv/(k|v)_pool", self.kv_pool_hrd),
                (r"step/q(uery)?", self.query_bhld),
                (r"kv/page_table", self.table_bp),
                (r"step/out", self.out_bhld)]
