"""Serving subsystem: continuous batching over the decode op library.

Public surface (docs/serving.md)::

    from tilelang_mesh_tpu.serving import (
        ServingEngine, FlashDecodeWorkload, MLADecodeWorkload,
        PagedKVAllocator, AdmissionController, Request)

    alloc = PagedKVAllocator(n_pages=64, page_size=8, heads=2, head_dim=64)
    eng = ServingEngine(FlashDecodeWorkload(alloc, batch_buckets=(4,),
                                            page_buckets=(2, 4)))
    eng.warmup()                       # AOT: no first-request JIT latency
    r = eng.submit(context_tokens=16, new_tokens=2, deadline_ms=500)
    eng.run()                          # every request reaches a terminal
    assert r.outcome in ("result", "shed", "deadline_exceeded",
                         "failed", "canceled")

Full lifecycle (docs/serving.md "Full-lifecycle serving"): chunked
prefill interleaves with decode inside ``step()``; ``eng.stream(...)``
yields sampled tokens one at a time (closing it cancels);
``serving/prefix_cache.py`` restores shared whole-page prompt prefixes
from checksummed cached pages instead of recomputing them.

Multi-engine serving (docs/serving.md "Fleet serving & failover"):
``Fleet`` supervises N engines behind the SLO-aware ``Router`` —
per-engine breakers, half-open restart probes, and zero-loss failover
that re-dispatches a dead engine's live requests to healthy peers.
With ``TL_TPU_FLEET_ISOLATION=proc`` (docs/serving.md "Process
isolation & crash containment") each slot is a subprocess worker
behind the checksummed frame protocol in ``serving/ipc.py``, and the
same failover survives a real SIGKILL.

``serving_state()`` is the live-gauge snapshot
``metrics_summary()["serving"]`` embeds (queue depth, KV slab levels);
monotonic accounting rides the ``serve.*`` tracer counters.
"""

from .admission import (AdmissionController, SERVE_BREAKER_SIG,  # noqa: F401
                        STEP_HIST_KERNEL)
from .batcher import (DecodeWorkload, FlashDecodeWorkload,  # noqa: F401
                      MLADecodeWorkload)
from .engine import ServingEngine, TokenStream  # noqa: F401
from .fleet import (EngineSlot, Fleet, fleet_health,  # noqa: F401
                    fleet_slo, registered_fleets)
from .ipc import (FrameError, decode_frame, decode_snapshot,  # noqa: F401
                  deserialize_request, encode_frame, encode_snapshot,
                  max_frame_bytes, serialize_request)
from .kv_cache import (KVCacheExhausted, KVSnapshot,  # noqa: F401
                       PagedKVAllocator, migrate)
from .mesh_workload import (LAYOUT_KINDS, MeshDecodeWorkload,  # noqa: F401
                            MeshLayout, layout_ladder, parse_layout,
                            validate_shard_config)
from .prefix_cache import (PrefixEntry, PrefixKVCache,  # noqa: F401
                           get_prefix_cache, reset_prefix_cache)
from .request import (OUTCOMES, Request, SHED_REASONS, STATES,  # noqa: F401
                      default_prompt, gauges as serving_state,
                      publish_meta, reset_gauges, serving_meta)
from .router import Router, fleet_sig, fleet_p99_budget_ms  # noqa: F401
from .sampling import sample_token  # noqa: F401
from .shard import ServeShardConfig, match_partition_rules  # noqa: F401
from .worker import (ProcEngine, default_workload_factory,  # noqa: F401
                     worker_main)

__all__ = [
    "ServingEngine", "TokenStream", "DecodeWorkload",
    "FlashDecodeWorkload",
    "MLADecodeWorkload", "MeshDecodeWorkload", "MeshLayout",
    "layout_ladder", "parse_layout", "validate_shard_config",
    "LAYOUT_KINDS", "PagedKVAllocator", "KVCacheExhausted", "KVSnapshot",
    "migrate", "AdmissionController", "Request", "STATES", "OUTCOMES",
    "SHED_REASONS", "SERVE_BREAKER_SIG", "STEP_HIST_KERNEL",
    "ServeShardConfig", "match_partition_rules", "serving_state",
    "serving_meta", "publish_meta", "reset_gauges", "default_prompt",
    "PrefixEntry", "PrefixKVCache", "get_prefix_cache",
    "reset_prefix_cache", "sample_token",
    "Fleet", "EngineSlot", "Router", "fleet_sig",
    "fleet_p99_budget_ms", "fleet_health", "fleet_slo",
    "registered_fleets",
    "FrameError", "encode_frame", "decode_frame", "max_frame_bytes",
    "encode_snapshot", "decode_snapshot", "serialize_request",
    "deserialize_request", "ProcEngine", "worker_main",
    "default_workload_factory",
]
