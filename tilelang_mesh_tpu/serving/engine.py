"""ServingEngine: continuous batching with a failure-handling contract.

The engine is the first end-to-end consumer of the whole stack: the op
library supplies the decode kernels (via :mod:`.batcher`), the
crash-safe cache warms them ahead of traffic, admission control leans
on the PR 2 circuit breaker and the PR 3 latency histograms, and the
PR 6 backend registry absorbs device loss mid-batch. Its contract —
the product of this module — is:

1. **Every submitted request reaches a terminal outcome** (``result`` /
   ``shed`` / ``deadline_exceeded`` / ``failed``): no silent drops, no
   unbounded waits. Retry budgets are bounded, device-loss re-admission
   is bounded, and expiry sweeps run before every batch.
2. **Deadlines propagate.** A request's deadline caps admission
   feasibility, its retry budget, and the batch step watchdog: a batch
   carrying deadlines is dispatched under a wall-clock bound of the
   tightest remaining deadline plus grace (the serving analog of the
   PR 5 ``TL_TPU_COMM_TIMEOUT_MS`` collective watchdog, which still
   guards the collectives *inside* a mesh-backed step independently).
3. **Graceful degradation.** A batch that dies with a device-loss
   error is quarantined: the serving backend is marked unhealthy in
   the registry (feeding the shared breaker), kernel caches are
   dropped so rebuilds re-walk the ``TL_TPU_BACKENDS`` chain, and
   unexpired requests are re-admitted onto the new tier. ``drain()``
   finishes in-flight work while shedding new arrivals.
4. **Full lifecycle** (docs/serving.md "Full-lifecycle serving"):
   every ``step()`` interleaves a BOUNDED prefill quantum (at most
   ``TL_TPU_SERVE_PREFILL_PER_STEP`` chunk units of
   ``TL_TPU_SERVE_PREFILL_CHUNK`` tokens) with one decode batch, so a
   long prompt costs queue time, never decode p99; decode outputs are
   temperature/top-p sampled into token ids (TTFT recorded in
   ``serve.ttft`` at the first one); ``stream()`` yields tokens as
   they land and closing the stream cancels; ``cancel()`` retires a
   request as ``canceled`` and frees its KV slabs wherever it was in
   the lifecycle — including mid-prefill.

Fault sites: ``serve.admit`` (admission bookkeeping), ``serve.step``
(one batch dispatch), ``serve.kv`` (slab allocation — lives in
:mod:`.kv_cache`). ``verify/chaos.py --serve`` soaks the whole
contract deterministically on CPU.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional

from ..env import env
from ..observability import flight as _flight
from ..observability import histogram as _hist
from ..observability import reqtrace as _reqtrace
from ..observability import slo as _slo
from ..observability import tracer as _trace
from ..resilience import faults as _faults
from ..resilience.errors import TLError, classify, error_signature
from ..resilience.retry import global_breaker
from .admission import (STEP_HIST_KERNEL, SERVE_BREAKER_SIG,
                        AdmissionController)
from .batcher import DecodeWorkload
from .kv_cache import KVCacheExhausted
from .request import (Request, clear_gauges, publish_gauges,
                      publish_meta)

__all__ = ["ServingEngine", "TokenStream"]

logger = logging.getLogger("tilelang_mesh_tpu.serving")


def _bounded_step(fn, budget_s: float, what: str):
    """Dispatch under a wall-clock bound on an abandoned-on-expiry
    daemon thread (a dead device HANGS the call; only abandonment keeps
    the scheduler moving — same idiom as the PR 5 collective watchdog).
    A result that lands late is still returned: per-request expiry
    decides who missed their deadline, so good work is never thrown
    away wholesale."""
    import queue
    import threading
    qq: "queue.Queue" = queue.Queue(maxsize=1)

    def _t():
        try:
            qq.put((True, fn()))
        except BaseException as e:  # noqa: BLE001 — relayed to caller
            qq.put((False, e))

    t = threading.Thread(target=_t, daemon=True,
                         name=f"tl-serve-step-{int(budget_s * 1e3)}ms")
    t.start()
    try:
        ok, val = qq.get(timeout=max(budget_s, 1e-3))
    except queue.Empty:
        from ..resilience.errors import TLTimeoutError
        raise TLTimeoutError(
            f"{what} exceeded its step budget ({budget_s * 1e3:.0f}ms); "
            f"worker {t.name} abandoned", site="serve.step") from None
    if not ok:
        raise val
    return val


class ServingEngine:
    """Synchronous continuous-batching scheduler (deterministic by
    construction: drive it with ``step()``/``run()``; a thread pumping
    ``run()`` makes it a background server)."""

    def __init__(self, workload: DecodeWorkload, *,
                 admission: Optional[AdmissionController] = None,
                 max_batch: Optional[int] = None,
                 grace_ms: Optional[float] = None,
                 step_timeout_ms: Optional[float] = None,
                 retry_max: Optional[int] = None,
                 default_deadline_ms: Optional[float] = None,
                 tenant_weights: Optional[Dict[str, int]] = None,
                 name: str = "serve"):
        self.workload = workload
        self.admission = admission or AdmissionController()
        self.max_batch = min(
            max_batch if max_batch is not None else env.TL_TPU_SERVE_MAX_BATCH,
            workload.max_batch)
        self.grace_ms = (grace_ms if grace_ms is not None
                         else env.TL_TPU_SERVE_GRACE_MS)
        self.step_timeout_ms = (step_timeout_ms if step_timeout_ms is not None
                                else env.TL_TPU_SERVE_STEP_TIMEOUT_MS)
        self.retry_max = (retry_max if retry_max is not None
                          else env.TL_TPU_SERVE_RETRY_MAX)
        self.default_deadline_ms = default_deadline_ms
        # chunked prefill: chunk units processed per step (bounds the
        # prefill work wedged between two decode dispatches)
        self.prefill_per_step = env.TL_TPU_SERVE_PREFILL_PER_STEP
        self.name = name
        # per-tenant batch weights (picks per round-robin round in
        # _form_batch); unlisted tenants weigh 1
        self.tenant_weights = dict(tenant_weights or {})
        self.requests: List[Request] = []    # every submission, in order
        self._queue: List[Request] = []      # admitted, awaiting a batch
        self._draining = False
        self._steps = 0
        self._failovers = 0
        self._step_failures = 0   # every _on_step_failure entry — the
        self._warmed = False      # fleet's per-engine breaker signal
        # elastic mesh serving (serving/mesh_workload.py): the layout
        # ladder the engine walks on a sharded-step device loss /
        # watchdog timeout, bounded by TL_TPU_SERVE_RESHARD_MAX
        self.reshard_max = env.TL_TPU_SERVE_RESHARD_MAX
        self._shard_probe_every = env.TL_TPU_SERVE_SHARD_PROBE_EVERY
        self._reshards = 0
        if getattr(workload, "elastic", False):
            publish_meta(layout=workload.layout.name)
        # tl-scope (docs/observability.md): the engine's own causal
        # trace — batch-step spans live here, linked to every member
        # request's trace — plus the sliding-window SLO engine, and the
        # opt-in telemetry endpoint (TL_TPU_METRICS_PORT)
        # max_spans bounds the never-terminal engine chain (one batch
        # span lands per step, forever): recent history stays, ancient
        # steps evict — the same keep-the-tail policy as the tracer ring
        self.trace = _reqtrace.start_trace("engine", kind="engine",
                                           engine=name, max_spans=1024)
        self._slo = _slo.get_slo()
        try:
            from ..observability import server as _server
            _server.maybe_start()
        except Exception:  # noqa: BLE001 — telemetry must not block serving
            logger.warning("serving engine %s: telemetry endpoint "
                           "failed to start", self.name, exc_info=True)

    # -- submission / admission ----------------------------------------
    def submit(self, context_tokens: int, new_tokens: int = 1,
               deadline_ms: Optional[float] = None, seed: int = 0,
               payload: Optional[dict] = None,
               prompt_tokens: Optional[list] = None,
               temperature: float = 0.0,
               top_p: float = 1.0,
               tenant: Optional[str] = None) -> Request:
        """Admit or shed one request; ALWAYS returns the request with a
        state transition recorded (shed requests come back terminal).
        ``prompt_tokens`` is the prompt's token ids (default: derived
        from ``seed`` — identical seeds share a prefix-cache address);
        ``temperature``/``top_p`` are the sampling knobs (0 = greedy);
        ``tenant`` is the fairness label admission shares and batch
        round-robin key on (None = "default")."""
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        req = Request(context_tokens, new_tokens, deadline_ms=deadline_ms,
                      seed=seed, payload=payload,
                      prompt_tokens=prompt_tokens,
                      temperature=temperature, top_p=top_p,
                      tenant=tenant)
        self.requests.append(req)
        try:
            _faults.maybe_fail("serve.admit", req=req.req_id)
        except Exception as e:  # noqa: BLE001 — admission must not crash
            return self._shed(req, "admit_fault",
                              error=f"{type(e).__name__}: {e}")
        ok, reason = self.admission.decide(
            draining=self._draining,
            queue_depth=len(self._queue),
            free_pages=self.workload.allocator.free_pages,
            pages_needed=self.workload.pages_needed(context_tokens,
                                                    new_tokens),
            remaining_s=req.remaining_s(),
            steps_requested=new_tokens,
            prefill_chunks=self.workload.prefill_chunks_needed(
                context_tokens),
            tenant_inflight=sum(1 for r in self._queue
                                if r.tenant == req.tenant))
        if not ok:
            return self._shed(req, reason)
        try:
            self.workload.ingest(req)
        except ValueError:
            # misconfigured request: a caller bug, not load — it was
            # never accepted, so it must not linger non-terminal in
            # self.requests (the all-terminal contract audits that list)
            self.requests.remove(req)
            raise
        except (TLError, OSError) as e:
            # injected serve.kv fault or organic allocation failure
            # during context ingestion: terminal shed, never a crash
            return self._shed(req, "kv_exhausted",
                              error=f"{type(e).__name__}: {e}")
        req.admit()
        self._queue.append(req)
        _trace.inc("serve.admitted")
        self._gauges()
        return req

    def _shed(self, req: Request, reason: str,
              error: Optional[str] = None) -> Request:
        req.finish("shed", shed_reason=reason, error=error)
        self._retire_slabs(req)
        _trace.inc("serve.shed", reason=reason)
        _trace.inc("serve.tenant", tenant=req.tenant, outcome="shed")
        _trace.event("serve.shed", "serving", req=req.req_id,
                     reason=reason, error=error)
        self._observe_e2e(req)
        return req

    # -- warm-up -------------------------------------------------------
    def warmup(self) -> int:
        """AOT-compile + dispatch every bucket kernel through the
        crash-safe cache BEFORE traffic, and seed the step-latency
        histogram admission reads its estimates from."""
        with _trace.span("serve.warmup_all", "serving", engine=self.name):
            t0 = time.perf_counter()
            n = self.workload.warmup()
            if n:
                # warm dispatches are compile-dominated; seed the step
                # estimate with one extra measured warm dispatch instead
                per = self._measured_warm_step()
                logger.info("serving engine %s: warmed %d bucket "
                            "kernel(s) in %.2fs (warm step ~%.2fms)",
                            self.name, n, time.perf_counter() - t0,
                            per * 1e3)
        self._warmed = True
        return n

    def _measured_warm_step(self) -> float:
        """One post-compile dispatch per smallest bucket, timed, so the
        admission estimates start from a WARM step latency (folding
        compile time in would shed every deadlined request at startup)."""
        import numpy as np
        bb = self.workload.batch_buckets[0]
        pp = self.workload.page_buckets[0]
        q = np.zeros(self.workload._query_shape(bb), np.float32)
        table = np.zeros((bb, pp), np.int32)
        t0 = time.perf_counter()
        self.workload._dispatch(q, table, bb, pp)
        dt = time.perf_counter() - t0
        _hist.observe("kernel.latency", dt, kernel=STEP_HIST_KERNEL,
                      source="serving")
        return dt

    # -- scheduling ----------------------------------------------------
    def _expire_queue(self, now: Optional[float] = None) -> int:
        grace_s = self.grace_ms / 1e3
        expired = [r for r in self._queue if r.expired(grace_s, now)]
        for r in expired:
            self._queue.remove(r)
            self._finish(r, "deadline_exceeded")
        return len(expired)

    def _form_batch(self) -> List[Request]:
        """FIFO head defines the page bucket; same-bucket followers fill
        the batch up to ``max_batch`` — interleaved weighted round-robin
        across tenants (FIFO within a tenant, the head's tenant picked
        first) so one tenant's backlog cannot monopolize every batch
        slot while another waits. With a single tenant this degenerates
        to the original FIFO fill; the head is always served — no
        starvation. Requests still mid-prefill are not decode-eligible
        and are skipped (their chunk units run in the prefill quantum
        instead)."""
        ready = [r for r in self._queue
                 if not r.needs_prefill and not r.cancel_requested]
        if not ready:
            return []
        head_bucket = self.workload.bucket_of(ready[0])
        by_tenant: Dict[str, List[Request]] = {}
        for r in ready:
            if self.workload.bucket_of(r) == head_bucket:
                by_tenant.setdefault(r.tenant, []).append(r)
        order = list(by_tenant)   # first-seen order: head's tenant first
        batch: List[Request] = []
        while len(batch) < self.max_batch and \
                any(by_tenant[t] for t in order):
            for t in order:
                take = max(1, int(self.tenant_weights.get(t, 1)))
                while take > 0 and by_tenant[t] \
                        and len(batch) < self.max_batch:
                    batch.append(by_tenant[t].pop(0))
                    take -= 1
                if len(batch) >= self.max_batch:
                    break
        for r in batch:
            self._queue.remove(r)
            r.batch()
        return batch

    def _cancel_sweep(self) -> int:
        """Retire queued requests whose cancellation was requested:
        terminal ``canceled``, KV slabs freed — the batcher never sees
        them again."""
        victims = [r for r in self._queue if r.cancel_requested]
        for r in victims:
            self._queue.remove(r)
            self._finish(r, "canceled")
        return len(victims)

    def _prefill_quantum(self) -> bool:
        """Run at most ``prefill_per_step`` prefill chunk units — the
        bounded wedge of prompt work between two decode dispatches. The
        FIFO-first mid-prefill request is re-picked per unit, so the
        queue head may consume several units in one step (it finishes
        — and becomes decode-eligible — sooner) and the whole budget
        is spent whenever work exists. ``prefill_per_step<=0`` is
        unthrottled: every pending chunk runs this step. Returns True
        when any chunk ran."""
        budget = (self.prefill_per_step if self.prefill_per_step > 0
                  else float("inf"))
        units = 0
        while units < budget:
            r = next((x for x in self._queue
                      if x.needs_prefill and not x.cancel_requested),
                     None)
            if r is None:
                break
            sid = r.trace.span("prefill.chunk", pos=r.prefill_pos)
            t0 = time.perf_counter()
            try:
                n = self.workload.prefill_chunk(r)
            except Exception as e:  # noqa: BLE001 — classified below
                r.trace.close_span(sid, error=f"{type(e).__name__}: {e}")
                self._queue.remove(r)
                if isinstance(e, (TLError, OSError)):
                    # injected serve.kv fault or organic KV pressure
                    # mid-prefill: terminal shed, slabs freed
                    self._finish(r, "shed", shed_reason="kv_exhausted",
                                 error=f"{type(e).__name__}: {e}")
                else:
                    self._finish(r, "failed",
                                 error=f"{type(e).__name__}: {e}")
                continue
            dt = time.perf_counter() - t0
            r.trace.close_span(sid, tokens=n,
                               done=not r.needs_prefill)
            _hist.observe("serve.prefill.latency", dt)
            _trace.inc("serve.prefill.chunks")
            _trace.inc("serve.prefill.tokens", n)
            units += 1
        return units > 0

    def _step_budget_s(self, batch: List[Request]) -> Optional[float]:
        """Deadline propagation into the step watchdog: the tightest
        remaining deadline (plus grace) caps the dispatch, as does the
        static ``TL_TPU_SERVE_STEP_TIMEOUT_MS`` when set."""
        budgets = []
        if self.step_timeout_ms > 0:
            budgets.append(self.step_timeout_ms / 1e3)
        rem = [r.remaining_s() for r in batch
               if r.remaining_s() is not None]
        if rem:
            budgets.append(max(min(rem), 0.0) + self.grace_ms / 1e3)
        return min(budgets) if budgets else None

    def step(self) -> bool:
        """Run one scheduling step — a bounded prefill quantum plus one
        decode batch; False when the queue is idle (no prefill ran and
        no batch formed)."""
        self._expire_queue()
        self._cancel_sweep()
        prefilled = self._prefill_quantum()
        batch = self._form_batch()
        if not batch:
            self._gauges()
            if prefilled:
                self._slo_tick()
            return prefilled
        now = time.monotonic()
        for r in batch:
            if r.first_batch_t is not None and len(r.timeline) <= 3:
                _hist.observe("serve.queue.wait", now - r.submit_t)
        budget = self._step_budget_s(batch)
        # tl-scope: the batch step is one span in the ENGINE's causal
        # trace, linked to every member request's trace_id; binding its
        # context around the dispatch tags every span/event recorded
        # underneath (kernel dispatches, collectives, faults) with
        # trace_id/parent_span — the connected arrow chain in the
        # Chrome trace
        member_ids = [r.trace_id for r in batch]
        batch_no = self._steps + 1
        step_sid = self.trace.span("serve.batch", batch=batch_no,
                                   size=len(batch), links=member_ids)
        t0 = time.perf_counter()
        try:
            with _trace.span("serve.batch", "serving", engine=self.name,
                             batch=batch_no, size=len(batch),
                             links=member_ids), \
                    _reqtrace.bind(self.trace.trace_id, step_sid):
                _faults.maybe_fail("serve.step", batch=len(batch))
                if budget is not None:
                    outs = _bounded_step(
                        lambda: self.workload.run_batch(batch), budget,
                        f"{self.name} batch of {len(batch)}")
                else:
                    outs = self.workload.run_batch(batch)
        except Exception as e:  # noqa: BLE001 — classified below
            self.trace.close_span(step_sid,
                                  error=f"{type(e).__name__}: {e}")
            self._on_step_failure(batch, e)
            self._gauges()
            self._slo_tick()
            return True
        dt = time.perf_counter() - t0
        self.trace.close_span(step_sid)
        self._steps += 1
        _trace.inc("serve.batches")
        _trace.inc("serve.steps", len(batch))
        _hist.observe("kernel.latency", dt, kernel=STEP_HIST_KERNEL,
                      source="serving")
        self._maybe_probe_shards()
        self._sol_tick(batch, dt)
        self._retire_or_requeue(batch, outs)
        self._gauges()
        self._slo_tick()
        return True

    def _sol_tick(self, batch, dt: float) -> None:
        """tl-sol drift tick: hold this step's measured latency against
        the batch bucket's tuned-config prediction (the fleet tune
        cache's ``best_latency_ms`` the workload adopted at warmup). A
        sustained drift fires ``sol.drift``, dumps a flight black box
        naming the kernel/config, and enqueues the bucket on the retune
        queue served at ``/prof`` (observability/sol.py)."""
        try:
            wl = self.workload
            pred_fn = getattr(wl, "tuned_prediction_ms", None)
            if pred_fn is None:
                return
            bb = wl.batch_bucket(len(batch))
            pp = max(wl.bucket_of(r) for r in batch)
            pred = pred_fn(bb, pp)
            if pred is None:
                return
            from ..observability import sol as _sol
            _sol.observe_bucket(
                kernel=type(wl).__name__, bucket=f"b{bb}:p{pp}",
                measured_ms=dt * 1e3, predicted_ms=pred,
                config=wl.tuned_config(bb, pp), engine=self.name)
        except Exception:  # noqa: BLE001 — drift math must not kill a step
            logger.warning("serving engine %s: sol tick failed",
                           self.name, exc_info=True)

    def _slo_tick(self) -> None:
        """Feed the sliding-window SLO engine (throttled) and fire ONE
        flight-recorder dump per breach episode (docs/observability.md)."""
        try:
            if self._slo.tick():
                breach = self._slo.check_breach()
                if breach is not None:
                    _trace.event("slo.breach", "serving",
                                 engine=self.name,
                                 reasons=breach["breach_reasons"])
                    _flight.dump("slo_breach", engine=self.name,
                                 reasons=breach["breach_reasons"])
        except Exception:  # noqa: BLE001 — SLO math must not kill a step
            logger.warning("serving engine %s: SLO tick failed",
                           self.name, exc_info=True)

    def _maybe_probe_shards(self) -> None:
        """Sampled straggler probe on sharded layouts: per-shard probe
        latencies land in ``serve.shard.latency{shard=}`` and the skew
        ratio in the ``shard_skew`` gauge — a slow shard is visible
        before it is dead (docs/serving.md)."""
        wl = self.workload
        if (self._shard_probe_every <= 0
                or not getattr(wl, "elastic", False)
                or not wl.layout.sharded
                or self._steps % self._shard_probe_every):
            return
        try:
            skew = wl.probe_shards()
        except Exception as e:  # noqa: BLE001 — a probe must not kill a step
            logger.warning("serving engine %s: shard probe failed: %s",
                           self.name, e)
            return
        if skew is not None:
            publish_gauges(shard_skew=skew)

    def pump_bound(self) -> int:
        """The finite pump bound ``run()``/``TokenStream`` share: 20x
        the total outstanding work (decode steps + worst-case prefill
        chunk units) plus slack. Recomputed per call — submissions
        arriving mid-pump extend it; a scheduler bug still cannot pump
        forever."""
        total = sum(r.new_tokens
                    + self.workload.prefill_chunks_needed(
                        r.context_tokens)
                    for r in self.requests) or 1
        return 20 * total + 100

    def run(self, max_steps: Optional[int] = None) -> int:
        """Pump ``step()`` until idle; returns steps executed. The
        default bound is generous but FINITE — the no-unbounded-waits
        contract holds even against a scheduler bug."""
        if max_steps is None:
            max_steps = self.pump_bound()
        n = 0
        while n < max_steps:
            if not self.step():
                return n
            n += 1
        # the bound tripping means requests would otherwise wait forever:
        # retire everything still queued as failed, honoring the contract
        for r in list(self._queue):
            self._queue.remove(r)
            self._finish(r, "failed",
                         error=f"scheduler exceeded {max_steps} steps")
        logger.error("serving engine %s: scheduler bound (%d steps) hit; "
                     "queue force-retired", self.name, max_steps)
        self._gauges()
        return n

    def drain(self) -> None:
        """Stop admitting; ``run()`` finishes the in-flight work."""
        self._draining = True
        _trace.event("serve.drain", "serving", engine=self.name,
                     queued=len(self._queue))

    # -- cancellation / streaming --------------------------------------
    def cancel(self, req: Request) -> bool:
        """Cancel one request: queued (incl. mid-prefill) requests
        retire ``canceled`` immediately with their KV slabs freed; a
        request currently inside a batch dispatch is flagged and
        retired when the step returns (its in-flight work is not
        interruptible, its slabs still free the same step). False when
        the request is already terminal."""
        if req.is_terminal:
            return False
        req.cancel_requested = True
        req.trace.mark("cancel", steps_done=req.steps_done,
                       mid_prefill=req.needs_prefill)
        if req in self._queue:
            self._queue.remove(req)
            self._finish(req, "canceled")
            self._gauges()
        return True

    def stream(self, context_tokens: int, new_tokens: int = 1,
               deadline_ms: Optional[float] = None, seed: int = 0,
               payload: Optional[dict] = None,
               prompt_tokens: Optional[list] = None,
               temperature: float = 0.0,
               top_p: float = 1.0,
               tenant: Optional[str] = None) -> "TokenStream":
        """The streaming front-end: submit + an iterator yielding one
        event dict per sampled token (``{"token", "index", "req",
        "trace_id"}``) as decode steps land. The iterator pumps
        ``step()`` itself, so a plain ``for`` loop serves the request
        end to end; closing it early (``break``, ``.close()``)
        CANCELS the request and frees its KV slabs — the
        client-disconnect contract."""
        req = self.submit(context_tokens, new_tokens,
                          deadline_ms=deadline_ms, seed=seed,
                          payload=payload, prompt_tokens=prompt_tokens,
                          temperature=temperature, top_p=top_p,
                          tenant=tenant)
        return TokenStream(self, req)

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    # -- retirement ----------------------------------------------------
    def _retire_or_requeue(self, batch: List[Request], outs) -> None:
        now = time.monotonic()
        for r, out in zip(batch, outs):
            r.steps_done += 1
            r.result = out
            # real sampling (serving/sampling.py): the decode output
            # becomes ONE token id — what stream() yields and what the
            # appended KV content derives from
            try:
                tok = self.workload.sample(r, out)
            except Exception as e:  # noqa: BLE001 — a sampler bug fails
                self._finish(r, "failed",        # the request, never
                             error=f"{type(e).__name__}: {e}")  # a hang
                continue
            r.generated.append(tok)
            if r.first_token_t is None:
                # TTFT: submit -> first sampled token, the latency a
                # streaming client actually feels
                r.first_token_t = now
                _hist.observe("serve.ttft", now - r.submit_t)
                r.trace.mark("first_token", token=tok,
                             ttft_ms=round((now - r.submit_t) * 1e3, 3))
            if r.cancel_requested:
                # canceled while in flight: the step's work is done but
                # the client is gone — retire now, free the slabs
                self._finish(r, "canceled")
                continue
            if r.steps_done >= r.new_tokens:
                self._finish(r, "result")
                continue
            try:
                self.workload.append_token(r)
            except (KVCacheExhausted, TLError, OSError) as e:
                # mid-flight KV pressure (or an injected serve.kv
                # fault): the request cannot grow its context — shed
                # it terminally rather than serve corrupt attention
                self._finish(r, "shed", shed_reason="kv_exhausted",
                             error=f"{type(e).__name__}: {e}")
                continue
            r.requeue()
            self._queue.append(r)

    def _finish(self, req: Request, outcome: str, *,
                shed_reason: Optional[str] = None,
                error: Optional[str] = None) -> None:
        req.finish(outcome, shed_reason=shed_reason, error=error)
        self._retire_slabs(req)
        if outcome == "result":
            _trace.inc("serve.completed")
        elif outcome == "deadline_exceeded":
            _trace.inc("serve.deadline_exceeded")
            _trace.event("serve.deadline_exceeded", "serving",
                         req=req.req_id, steps_done=req.steps_done)
        elif outcome == "failed":
            _trace.inc("serve.failed")
            _trace.event("serve.request_failed", "serving",
                         req=req.req_id, error=error)
        elif outcome == "canceled":
            _trace.inc("serve.canceled")
            _trace.event("serve.canceled", "serving", req=req.req_id,
                         steps_done=req.steps_done,
                         mid_prefill=req.needs_prefill)
        else:
            _trace.inc("serve.shed", reason=shed_reason)
            _trace.event("serve.shed", "serving", req=req.req_id,
                         reason=shed_reason, error=error)
        _trace.inc("serve.tenant", tenant=req.tenant, outcome=outcome)
        self._observe_e2e(req)

    def _retire_slabs(self, req: Request) -> None:
        """Leak-checked slab release on EVERY terminal transition."""
        if req.pages:
            self.workload.retire(req)

    def _observe_e2e(self, req: Request) -> None:
        if req.terminal_t is not None:
            _hist.observe("serve.e2e.latency",
                          req.terminal_t - req.submit_t,
                          outcome=req.outcome)

    # -- failure handling ----------------------------------------------
    def _on_step_failure(self, batch: List[Request], exc: Exception) -> None:
        kind = classify(exc)
        self._step_failures += 1
        _trace.inc("serve.step_failures", kind=kind)
        _trace.event("serve.step_failure", "serving", kind=kind,
                     batch=[r.req_id for r in batch],
                     error=f"{type(exc).__name__}: {exc}")
        # the black box: a step failure dumps the flight ring with the
        # victim batch's member trace ids, so the post-mortem names
        # exactly which requests were in flight when the step died
        _flight.dump("step_failure", engine=self.name, kind=kind,
                     batch=[r.req_id for r in batch],
                     batch_trace_ids=[r.trace_id for r in batch],
                     error=f"{type(exc).__name__}: {exc}")
        resharded = False
        if kind == "device_loss" or (
                kind == "timeout"
                and getattr(exc, "site", None) != "serve.step"):
            # elastic mesh workloads degrade one layout rung instead of
            # condemning the whole backend tier: losing a slice costs
            # capacity, never correctness (docs/serving.md). A
            # deadline-derived step-budget timeout (site=serve.step)
            # says nothing about mesh health — one tight-deadlined
            # request must not halve serving capacity — so only
            # collective-watchdog / mesh-dispatch timeouts walk the
            # ladder.
            resharded = self._maybe_reshard(exc)
            if resharded:
                # the reshard lands in every surviving member's causal
                # chain: a request that lived through a slice loss says
                # so in its own trace
                for r in batch:
                    if not r.is_terminal:
                        r.trace.mark("reshard",
                                     layout=self.workload.layout.name)
        if kind == "device_loss" and not resharded:
            self._quarantine_and_failover(exc)
        if kind == "deterministic":
            # feed the shared breaker under both the per-error signature
            # (the stack-wide convention) and the rolled-up serve.step
            # signature admission checks
            breaker = global_breaker()
            breaker.record_failure(error_signature(exc))
            breaker.record_failure(SERVE_BREAKER_SIG)
            for r in batch:
                self._finish(r, "failed",
                             error=f"{type(exc).__name__}: {exc}")
            return
        # transient / timeout / device_loss: retry within budget
        grace_s = self.grace_ms / 1e3
        for r in batch:
            if r.is_terminal:
                # retired during the reshard re-warm (the fresh
                # placement could not hold it) — already accounted
                continue
            if r.expired(grace_s):
                self._finish(r, "deadline_exceeded")
            elif r.retries < self.retry_max:
                r.retries += 1
                _trace.inc("serve.retries")
                r.requeue()
                # retries go to the queue FRONT: their deadline budget
                # is already partly spent
                self._queue.insert(0, r)
            elif r.deadline is not None:
                self._finish(r, "shed", shed_reason="retry_budget",
                             error=f"{type(exc).__name__}: {exc}")
            else:
                self._finish(r, "failed",
                             error=f"retry budget exhausted: "
                                   f"{type(exc).__name__}: {exc}")

    def _maybe_reshard(self, exc: Exception) -> bool:
        """Walk the elastic layout ladder one rung down after a sharded
        step died (device loss / watchdog timeout): quarantine the lost
        slice in the PR 6 backend registry, rebuild the workload's mesh
        + specs on the next rung, migrate the KV state byte-conserved
        into a fresh placement, AOT re-warm the bucket kernels, and let
        the caller's retry path re-admit the batch's unexpired
        requests. Returns False (-> ordinary failure handling) when the
        workload is not elastic, already unsharded, the ladder or the
        reshard budget is spent, or the migration failed."""
        wl = self.workload
        if not getattr(wl, "elastic", False) or not wl.layout.sharded \
                or not wl.can_degrade():
            return False
        if self._reshards >= self.reshard_max:
            logger.error(
                "serving engine %s: reshard budget (%d) spent; falling "
                "through to ordinary failure handling", self.name,
                self.reshard_max)
            return False
        frm = wl.layout.name
        # 1. quarantine the lost slice: the error's device when it
        # names one, plus every mesh device failing a bounded liveness
        # probe (an injected loss leaves all host devices answering, so
        # this set may be empty — the rung walk is the degradation)
        from ..codegen.backends import registry
        lost = []
        dev = getattr(exc, "device", None)
        if dev is not None:
            lost.append(str(dev))
        try:
            lost.extend(d for d in wl.probe_lost() if d not in lost)
        except Exception:  # noqa: BLE001 — probe is best-effort
            pass
        reg = registry()
        for d in lost:
            reg.quarantine_device(d, exc)
        # every slice quarantined by an EARLIER reshard stays excluded
        # too — a known-dead device must never re-enter a layout
        exclude = sorted(set(lost) | set(reg.quarantined_devices()))
        # 2. migrate the surviving KV slabs into a fresh placement
        # FIRST, checksummed + byte-conservation-verified. When the
        # migration itself fails (the bytes cannot be carried over),
        # the reshard no longer gives up (ROADMAP 1(d)): the fresh
        # placement is installed anyway and every live request is
        # RE-WARMED from the prefix cache — a whole-page prefix
        # restores warm (``prefix_cache.hit`` lands on the reshard
        # path), the rest cold re-prefills, and already-sampled tokens
        # replay content-derived
        from .kv_cache import migrate
        new_alloc = wl.make_allocator()
        rewarmed = None
        try:
            mapping, nbytes = migrate(wl.allocator, new_alloc)
        except Exception as e:  # noqa: BLE001 — migration must not crash
            logger.warning(
                "serving engine %s: KV migration off %s failed "
                "(%s: %s); re-warming live requests from the prefix "
                "cache on a fresh placement", self.name, frm,
                type(e).__name__, e)
            mapping, nbytes = {}, 0
            wl.install_allocator(new_alloc)
            rewarmed = self._rewarm_requests()
        else:
            wl.install_allocator(new_alloc)
            for r in self.requests:
                if not r.is_terminal and r.pages:
                    r.pages = [mapping[p] for p in r.pages]
        # 3. next rung (skips rungs that cannot build on the survivors);
        # on failure the engine stays on the OLD layout with its KV
        # migrated in place — byte-identical state, books balanced
        try:
            to = wl.degrade(exclude=exclude)
        except Exception as e:  # noqa: BLE001 — ladder spent / unbuildable
            logger.error(
                "serving engine %s: layout ladder walk from %s failed "
                "(%s: %s); falling through to ordinary failure "
                "handling", self.name, frm, type(e).__name__, e)
            return False
        # 4. AOT re-warm every bucket on the new rung before traffic;
        # a warm-up failure must not crash the step (buckets compile
        # lazily on first dispatch, and if the rung is truly dead the
        # next step failure walks the ladder again)
        try:
            with _trace.span("serve.rewarm", "serving", engine=self.name,
                             layout=to.name):
                wl.warmup()
        except Exception as e:  # noqa: BLE001 — warm-up is best-effort
            logger.warning(
                "serving engine %s: re-warm on %s failed (%s: %s); "
                "buckets will compile lazily", self.name, to.name,
                type(e).__name__, e)
        self._reshards += 1
        _trace.inc("serve.reshard", frm=frm, to=to.name)
        _trace.event("serve.reshard", "serving", engine=self.name,
                     frm=frm, to=to.name, pages=len(mapping),
                     bytes=nbytes, lost=sorted(lost),
                     rewarmed=rewarmed,
                     error=f"{type(exc).__name__}: {exc}")
        publish_meta(layout=to.name)
        # the old layout's straggler signal dies with its mesh; the
        # next probe on the new rung (if sharded) repopulates it
        clear_gauges("shard_skew")
        logger.warning(
            "serving engine %s: mesh slice loss mid-decode (%s: %s); "
            "resharded %s -> %s, %d KV page(s) (%d bytes) migrated, "
            "%d device(s) quarantined", self.name, type(exc).__name__,
            exc, frm, to.name, len(mapping), nbytes, len(lost))
        return True

    def _rewarm_requests(self) -> Dict[str, int]:
        """Rebuild every live request's KV on the just-installed fresh
        allocator when a reshard migration could not carry the bytes
        over: ``ingest`` consults the prefix cache first (a whole-page
        prefix restores warm — that lookup is where ``prefix_cache.hit``
        lands on the reshard path), cold re-prefill otherwise; already-
        sampled tokens replay content-derived. A request the fresh
        placement cannot hold sheds ``kv_exhausted``. Returns warm/cold
        counts for the reshard event."""
        out = {"warm": 0, "cold": 0}
        for r in list(self.requests):
            if r.is_terminal or not (r.pages or r.prefill_pos):
                continue
            r.pages = []          # the old placement died with its
            r.tail_tokens = 0     # allocator; nothing left to free
            r.prefill_pos = 0
            r.prefix_tokens = 0
            try:
                self.workload.ingest(r)
                if r.generated:
                    # mid-decode: the request must be fully prefilled
                    # before its continuation can replay
                    while r.needs_prefill:
                        self.workload.prefill_chunk(r)
                    self.workload.replay_tokens(r)
            except (TLError, OSError) as e:
                if r in self._queue:
                    self._queue.remove(r)
                self._finish(r, "shed", shed_reason="kv_exhausted",
                             error=f"{type(e).__name__}: {e}")
                continue
            source = "prefix" if r.prefix_tokens > 0 else "cold"
            out["warm" if source == "prefix" else "cold"] += 1
            _trace.inc("serve.reshard.rewarm", source=source)
            r.trace.mark("rewarm", source=source,
                         prefix_tokens=r.prefix_tokens,
                         replayed=len(r.generated))
        return out

    # -- fleet hooks (serving/fleet.py) --------------------------------
    def export_inflight(self) -> List[Request]:
        """Remove and return every live (non-terminal) request,
        releasing its KV slabs on THIS engine so a healthy peer can
        rebuild them — the donor half of the fleet's zero-loss
        failover. Terminal requests stay: their accounting is final."""
        exported = []
        for r in [x for x in self.requests if not x.is_terminal]:
            if r in self._queue:
                self._queue.remove(r)
            self._retire_slabs(r)
            r.prefill_pos = 0
            r.prefix_tokens = 0
            self.requests.remove(r)
            exported.append(r)
        self._gauges()
        return exported

    def adopt(self, req: Request, *, source: str = "") -> Request:
        """Adopt a request exported from a dead peer (the recipient
        half of zero-loss failover): re-ingest its context on THIS
        workload — prefix-cache warm restore where a whole-page prefix
        exists, cold re-prefill otherwise — replay already-sampled
        tokens, and queue it. The request keeps its identity: req_id,
        causal trace, deadline, steps_done, generated tokens. Skips
        admission (it was admitted once; shedding an adopted request
        on load would break the zero-loss contract) but KV exhaustion
        still sheds terminally — terminal beats lost."""
        self.requests.append(req)
        try:
            self.workload.ingest(req)
            if req.generated:
                while req.needs_prefill:
                    self.workload.prefill_chunk(req)
                self.workload.replay_tokens(req)
        except (TLError, OSError) as e:
            return self._shed(req, "kv_exhausted",
                              error=f"{type(e).__name__}: {e}")
        req.trace.mark("readmit", engine=self.name, frm=source,
                       warm=req.prefix_tokens > 0,
                       steps_done=req.steps_done)
        self._queue.append(req)
        _trace.inc("serve.adopted", engine=self.name)
        self._gauges()
        return req

    def _quarantine_and_failover(self, exc: Exception) -> None:
        """Device loss mid-batch: mark the serving tier unhealthy in the
        PR 6 registry, drop every kernel cache tier so rebuilds re-walk
        the ``TL_TPU_BACKENDS`` chain, and count the failover. (The
        kernel layer already failed over internally when its chain had
        a healthy next entry; reaching here means the error surfaced to
        the scheduler, so the batch is quarantined and its unexpired
        requests re-admitted by the retry path.)"""
        from ..codegen.backends import registry
        self._failovers += 1
        _trace.inc("serve.failover")
        reg = registry()
        chain = reg.chain()
        used = self._backends_used()
        cand = [b.name for b in chain if b.name in used]
        # blame the tier actually serving: builds walk the chain
        # head->tail picking the first healthy entry, so the serving
        # tier is the first USED entry not already marked unhealthy —
        # an earlier tier that died in a previous failover must not
        # soak up the blame for a later tier's death
        frm = next((n for n in cand
                    if reg.health(n).healthy is not False),
                   cand[-1] if cand else chain[0].name)
        nxt = reg.next_healthy(chain, frm)
        if nxt is not None:
            reg.mark_unhealthy(frm, exc)
            reg.note_failover(frm=frm, to=nxt.name,
                              kernel=f"{self.name}.step",
                              during="serving", error=exc)
        logger.warning(
            "serving engine %s: device loss mid-batch (%s: %s); "
            "quarantining the batch and rebuilding kernels on the "
            "next healthy tier", self.name, type(exc).__name__, exc)
        # drop every tier that could pin the dead backend's callables
        import tilelang_mesh_tpu as tilelang
        tilelang.clear_cache()
        from ..jit import clear_factory_caches
        clear_factory_caches()
        self.workload.forget_kernels()

    @staticmethod
    def _backends_used() -> set:
        raw = _trace.get_tracer().counters_raw()
        return {dict(labels).get("backend")
                for (name, labels), _ in raw.items()
                if name == "backend.build"} - {None}

    # -- accounting ----------------------------------------------------
    def _gauges(self) -> None:
        alloc = self.workload.allocator
        publish_gauges(queue_depth=len(self._queue),
                       kv_pages_in_use=alloc.in_use,
                       kv_pages_free=alloc.free_pages,
                       draining=float(self._draining))

    def outcomes(self) -> Dict[str, int]:
        out = {"result": 0, "shed": 0, "deadline_exceeded": 0,
               "failed": 0, "canceled": 0, "pending": 0}
        for r in self.requests:
            out[r.outcome or "pending"] += 1
        return out

    @property
    def reshards(self) -> int:
        return self._reshards

    @property
    def step_failures(self) -> int:
        """Step failures handled INTERNALLY (``_on_step_failure``
        swallows the exception to keep the scheduler moving) — the
        fleet supervisor reads the delta per pump to feed its
        per-engine breaker."""
        return self._step_failures

    def stats(self) -> dict:
        alloc = self.workload.allocator
        out = {
            "engine": self.name,
            "requests": len(self.requests),
            "outcomes": self.outcomes(),
            "queue_depth": len(self._queue),
            "steps": self._steps,
            "failovers": self._failovers,
            "reshards": self._reshards,
            "draining": self._draining,
            "kv": alloc.stats(),
            "kv_leaks": {str(k): v
                         for k, v in alloc.leak_check().items()},
        }
        if getattr(self.workload, "elastic", False):
            out["mesh"] = self.workload.layout_stats()
        return out


class TokenStream:
    """Token-at-a-time iterator over one request (the ``stream()``
    front-end): yields an event dict per sampled token, pumping the
    host's synchronous ``step()`` underneath. Closing the iterator
    before the request retires cancels it — the generator-``close()``
    analog of a dropped client connection.

    The host is anything with the pump protocol — ``step()``,
    ``cancel(req)``, ``pump_bound()``: a single ``ServingEngine`` or a
    whole ``Fleet``. Tokens are read off ``req.generated``, never off
    a particular engine's queue, so a fleet-hosted stream survives
    failover: when the request is re-dispatched to another engine
    mid-stream, the next pump decodes it THERE and the stream keeps
    yielding — the client never learns an engine died."""

    def __init__(self, engine, request: Request):
        self.engine = engine     # the pump host (engine OR fleet)
        self.request = request

    def cancel(self) -> bool:
        return self.engine.cancel(self.request)

    def __iter__(self):
        eng, req = self.engine, self.request
        delivered = 0

        def pending():
            return req.generated[delivered:]

        # same finite-bound discipline as run(), over the WHOLE host's
        # work: the stream pumps every request's steps, so a bound
        # scaled only to this request would spuriously cancel a
        # healthy stream queued behind a long-running neighbor.
        # Recomputed per pump — submissions arriving mid-stream extend
        # it, a scheduler bug still cannot pump forever.
        try:
            pumps = 0
            while not req.is_terminal and pumps < eng.pump_bound():
                progressed = eng.step()
                pumps += 1
                for tok in pending():
                    delivered += 1
                    yield {"token": int(tok), "index": delivered,
                           "req": req.req_id, "trace_id": req.trace_id}
                if not progressed and not req.is_terminal:
                    break      # idle queue with a live request: a
                # scheduler bug — the finally clause cancels it so the
                # contract (every request terminal) still holds
            for tok in pending():
                delivered += 1
                yield {"token": int(tok), "index": delivered,
                       "req": req.req_id, "trace_id": req.trace_id}
        finally:
            if not req.is_terminal:
                eng.cancel(req)
