"""Content-addressed prefix KV cache: prefill shared prompts once.

A fleet serving millions of users sees the same system prompt in front
of thousands of requests; recomputing its KV context per request is the
largest untapped throughput lever in the stack. This module caches the
KV pages of **whole-page token prefixes**, content-addressed on a
sha256 of (pool geometry, token ids), so a prefix hit converts
O(prompt) prefill compute into an O(pages) checksummed restore:

- **Entries are KVSnapshot-format pages** (PR 9's migration unit): an
  entry's pages + checksum reconstruct a :class:`~.kv_cache.KVSnapshot`
  with synthetic page ids ``0..n-1``, so a hit restores through the
  allocator's existing ``restore()`` — checksum verified, byte
  conservation asserted on the written bytes, undo-logged on mid-restore
  failure. The cache adds NO second restore path to audit.
- **Two tiers**: an in-process LRU (shared by every engine in the
  process) over a disk tier committed with the crash-safe kernel
  cache's atomic tmp+rename discipline, so a prefix prefilled by one
  process warm-starts every other fleet member pointed at the same
  ``TL_TPU_SERVE_PREFIX_DIR``. Disk serialization is DEFERRED to an
  entry's first reuse (a memory hit): single-use prompts — most
  traffic — never pay the base64+JSON write on the serving path, while
  a genuinely shared prefix reaches the fleet tier on its second
  in-process request (``flush()`` force-publishes, for offline
  seeders).
- **Corruption quarantines, never serves**: disk reads visit the
  ``cache.disk.read`` fault site and verify the entry checksum; a torn,
  corrupt, or injected-fault entry moves to ``.quarantine/`` (counted,
  logged) and reads as a miss — the damage stays inspectable, the
  request falls back to cold prefill.
- **Bounded by a page budget** (``TL_TPU_SERVE_PREFIX_PAGES``):
  least-recently-used entries evict — memory entry and its disk file
  together — counted in ``prefix_cache.evicted``.

Counters: ``prefix_cache.{hit,miss,bytes_saved,evicted,insert,
quarantined,write_errors}`` — surfaced in ``metrics_summary()
["serving"]["prefix_cache"]``, the ``/slo`` window stats, and
``analyzer serve``.
"""

from __future__ import annotations

import base64
import hashlib
import json
import logging
import os
import threading
from collections import OrderedDict
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..env import env
from ..observability import tracer as _trace
from ..resilience import faults as _faults
from .kv_cache import KVSnapshot, _page_digest

__all__ = ["PREFIX_SCHEMA", "PrefixEntry", "PrefixKVCache",
           "get_prefix_cache", "reset_prefix_cache"]

logger = logging.getLogger("tilelang_mesh_tpu.serving")

PREFIX_SCHEMA = 1
QUARANTINE_DIR = ".quarantine"


def _entry_checksum(pages: List[Tuple[np.ndarray, np.ndarray]]):
    """KVSnapshot-format digest over synthetic page ids ``0..n-1`` —
    the SAME bytes ``KVSnapshot.verify`` and ``restore()`` recompute,
    so one checksum covers the entry on disk, in memory, and on the
    pages actually written into an allocator."""
    h = hashlib.sha256()
    nbytes = 0
    for i, (k, v) in enumerate(pages):
        nbytes += _page_digest(h, i, k, v)
    return h.hexdigest(), nbytes


class PrefixEntry:
    """The cached KV pages of one whole-page token prefix."""

    __slots__ = ("key", "n_tokens", "page_size", "heads", "head_dim",
                 "dtype", "pages", "checksum", "nbytes")

    def __init__(self, key: str, n_tokens: int, page_size: int,
                 heads: int, head_dim: int, dtype: np.dtype,
                 pages: List[Tuple[np.ndarray, np.ndarray]],
                 checksum: Optional[str] = None,
                 nbytes: Optional[int] = None):
        self.key = key
        self.n_tokens = int(n_tokens)
        self.page_size = int(page_size)
        self.heads = int(heads)
        self.head_dim = int(head_dim)
        self.dtype = np.dtype(dtype)
        self.pages = pages
        if checksum is None:
            checksum, nbytes = _entry_checksum(pages)
        self.checksum = checksum
        self.nbytes = int(nbytes)

    @property
    def n_pages(self) -> int:
        return len(self.pages)

    def to_snapshot(self, owner: int) -> KVSnapshot:
        """A fresh one-shot KVSnapshot over synthetic page ids, owned
        entirely by ``owner`` — ``allocator.restore()`` verifies the
        checksum, allocates, writes, and re-verifies byte conservation;
        the returned mapping's values (in id order 0..n-1) ARE the
        request's page list in token order."""
        return KVSnapshot(
            page_size=self.page_size, heads=self.heads,
            head_dim=self.head_dim, dtype=self.dtype,
            owners={owner: list(range(self.n_pages))},
            pages={i: self.pages[i] for i in range(self.n_pages)},
            checksum=self.checksum, nbytes=self.nbytes)

    def to_json(self) -> str:
        def b64(a: np.ndarray) -> str:
            return base64.b64encode(
                np.ascontiguousarray(a).tobytes()).decode()
        return json.dumps({
            "schema": PREFIX_SCHEMA, "key": self.key,
            "n_tokens": self.n_tokens, "page_size": self.page_size,
            "heads": self.heads, "head_dim": self.head_dim,
            "dtype": str(self.dtype), "checksum": self.checksum,
            "nbytes": self.nbytes,
            "pages": [{"k": b64(k), "v": b64(v)} for k, v in self.pages],
        })

    @classmethod
    def from_json(cls, text: str) -> "PrefixEntry":
        doc = json.loads(text)
        if doc.get("schema") != PREFIX_SCHEMA:
            raise ValueError(f"unknown prefix-cache schema "
                             f"{doc.get('schema')!r}")
        dt = np.dtype(doc["dtype"])
        shape = (doc["heads"], doc["page_size"], doc["head_dim"])

        def arr(b: str) -> np.ndarray:
            a = np.frombuffer(base64.b64decode(b), dtype=dt)
            return a.reshape(shape).copy()

        ent = cls(doc["key"], doc["n_tokens"], doc["page_size"],
                  doc["heads"], doc["head_dim"], dt,
                  [(arr(p["k"]), arr(p["v"])) for p in doc["pages"]],
                  checksum=doc["checksum"], nbytes=doc["nbytes"])
        # content-address integrity: the held bytes must hash to the
        # stored checksum or the entry is corrupt (quarantined by the
        # caller)
        got, gb = _entry_checksum(ent.pages)
        if got != ent.checksum or gb != ent.nbytes:
            raise ValueError("prefix-cache entry checksum mismatch")
        return ent


class PrefixKVCache:
    """LRU memory tier over an atomic-commit disk tier, bounded by a
    total page budget."""

    def __init__(self, root: Optional[Path] = None,
                 page_budget: Optional[int] = None):
        self._explicit_root = Path(root) if root is not None else None
        self._budget = page_budget
        self._lock = threading.Lock()
        self._mem: "OrderedDict[str, PrefixEntry]" = OrderedDict()
        # keys inserted but not yet serialized to the disk tier: the
        # base64+JSON+atomic-write cost is paid on an entry's FIRST
        # REUSE (a memory hit), so single-use prompts — most traffic —
        # never pay disk serialization on the serving path (measured
        # ~36% of serve_smoke throughput when paid unconditionally)
        self._pending_disk: set = set()
        self.hits = 0
        self.misses = 0
        self.inserts = 0
        self.evictions = 0
        self.quarantined = 0
        self.write_errors = 0
        self.bytes_saved = 0

    # -- configuration -------------------------------------------------
    @property
    def root(self) -> Path:
        if self._explicit_root is not None:
            self._explicit_root.mkdir(parents=True, exist_ok=True)
            return self._explicit_root
        return env.prefix_cache_dir()

    @property
    def page_budget(self) -> int:
        return self._budget if self._budget is not None \
            else max(1, env.TL_TPU_SERVE_PREFIX_PAGES)

    def _count(self, attr: str, n: int = 1) -> None:
        """Counter bump under the cache lock: the cache is shared by
        every engine in the process, and the stats feed gates
        (serve_prefill_smoke's hit count) that must not lose
        concurrent read-modify-write updates."""
        with self._lock:
            setattr(self, attr, getattr(self, attr) + n)

    # -- keying --------------------------------------------------------
    @staticmethod
    def key(geometry: str, tokens) -> str:
        """Content address of one token prefix under one pool geometry
        (two workloads with different pool shapes must never share an
        entry, whatever their token ids)."""
        h = hashlib.sha256()
        h.update(geometry.encode())
        h.update(np.asarray(tokens, np.int64).tobytes())
        return h.hexdigest()

    @staticmethod
    def prefix_keys(geometry: str, tokens, page_size: int):
        """Content addresses of EVERY whole-page prefix of ``tokens``,
        shortest first, in one incremental hashing pass (each prefix's
        byte stream is a prefix of the next one's, so one running
        sha256 plus a ``copy()`` per page boundary yields the same
        digests ``key()`` would — O(tokens) total instead of
        O(pages x tokens))."""
        toks = np.asarray(list(tokens), np.int64)
        ps = int(page_size)
        h = hashlib.sha256()
        h.update(geometry.encode())
        out = []
        for n_pages in range(1, len(toks) // ps + 1):
            h.update(toks[(n_pages - 1) * ps:n_pages * ps].tobytes())
            out.append((n_pages, h.copy().hexdigest()))
        return out

    # -- lookup --------------------------------------------------------
    def lookup(self, geometry: str, tokens, page_size: int
               ) -> Optional[PrefixEntry]:
        """The LONGEST cached whole-page prefix of ``tokens``, or None.
        One miss is counted per failed lookup (not per probed length);
        a hit counts once. ``bytes_saved`` is NOT counted here — the
        restore path calls :meth:`note_restored` once the entry's
        pages actually landed in an allocator, so the savings metric
        can never be satisfied by an entry that failed validation."""
        for n_pages, key in reversed(
                self.prefix_keys(geometry, tokens, page_size)):
            ent = self._get(key)
            if ent is not None:
                self._count("hits")
                _trace.inc("prefix_cache.hit")
                return ent
        self._count("misses")
        _trace.inc("prefix_cache.miss")
        return None

    def note_restored(self, ent: PrefixEntry) -> None:
        """Account one SUCCESSFUL restore of ``ent`` (checksum + byte
        conservation already verified by the allocator)."""
        self._count("bytes_saved", ent.nbytes)
        _trace.inc("prefix_cache.bytes_saved", ent.nbytes)

    def _get(self, key: str) -> Optional[PrefixEntry]:
        with self._lock:
            ent = self._mem.get(key)
            pending = ent is not None and key in self._pending_disk
            if pending:
                self._pending_disk.discard(key)
            if ent is not None:
                self._mem.move_to_end(key)      # LRU touch
        if pending:
            # first reuse proves the prefix is shared: NOW it earns
            # its place in the fleet disk tier (deferred publication)
            self._disk_store(ent)
        if ent is not None:
            return ent
        ent = self._disk_load(key)
        if ent is not None:
            with self._lock:
                self._mem[key] = ent
                self._mem.move_to_end(key)
            # a disk promotion grows the memory tier exactly like an
            # insert: the page budget bounds BOTH paths
            self._evict_over_budget()
        return ent

    # -- insert / evict ------------------------------------------------
    def insert(self, geometry: str, tokens,
               pages: List[Tuple[np.ndarray, np.ndarray]],
               page_size: int, heads: int, head_dim: int,
               dtype) -> Optional[PrefixEntry]:
        """Cache the whole-page prefix ``tokens`` (length must be
        ``len(pages) * page_size``) backed by ``pages`` COPIES. A key
        already present is not re-written (content addressing: same key
        = same bytes). The entry lands in the MEMORY tier immediately;
        disk serialization is deferred to its first reuse (``_get``) —
        single-use prompts never pay the write on the serving path.
        ``flush()`` forces pending entries out (fleet seeding)."""
        toks = list(tokens)
        if not pages or len(toks) != len(pages) * int(page_size):
            raise ValueError(
                f"prefix insert must be whole-page: {len(toks)} tokens "
                f"vs {len(pages)} page(s) x {page_size}")
        key = self.key(geometry, toks)
        with self._lock:
            if key in self._mem:
                self._mem.move_to_end(key)
                return self._mem[key]
        ent = PrefixEntry(key, len(toks), page_size, heads, head_dim,
                          np.dtype(dtype), pages)
        with self._lock:
            self._mem[key] = ent
            self._pending_disk.add(key)
        self._count("inserts")
        _trace.inc("prefix_cache.insert")
        self._evict_over_budget()
        return ent

    def flush(self) -> int:
        """Serialize every pending entry to the disk tier now (an
        offline seeder populating a fleet dir calls this; the serving
        path relies on first-reuse publication instead). Returns the
        number of entries written."""
        with self._lock:
            keys = list(self._pending_disk)
            self._pending_disk.clear()
            ents = [self._mem[k] for k in keys if k in self._mem]
        for ent in ents:
            self._disk_store(ent)
        return len(ents)

    def drop(self, key: str, reason: str = "corrupt") -> None:
        """Remove an entry that failed at RESTORE time (the allocator's
        checksum/geometry rejection): the memory entry dies, the disk
        file quarantines, and the key reads as a miss until a clean
        prefill re-inserts it."""
        with self._lock:
            self._mem.pop(key, None)
        path = self.root / f"{key}.json"
        if path.is_file():
            self._quarantine(path, reason)
        else:
            self._count("quarantined")
            _trace.inc("prefix_cache.quarantined")
            _trace.event("prefix_cache.quarantine", "serving",
                         entry=key, reason=reason)

    def _evict_over_budget(self) -> None:
        """LRU eviction down to the page budget; a memory entry and its
        disk file leave together (the budget bounds the WHOLE tier)."""
        while True:
            with self._lock:
                total = sum(e.n_pages for e in self._mem.values())
                if total <= self.page_budget or len(self._mem) <= 1:
                    return
                key, ent = self._mem.popitem(last=False)
                pending = key in self._pending_disk
                self._pending_disk.discard(key)
            if not pending:     # a never-published entry has no file
                try:
                    (self.root / f"{key}.json").unlink(missing_ok=True)
                except OSError:
                    pass
            self._count("evictions")
            _trace.inc("prefix_cache.evicted")
            _trace.event("prefix_cache.evicted", "serving", key=key,
                         pages=ent.n_pages)

    # -- disk tier -----------------------------------------------------
    def _disk_store(self, ent: PrefixEntry) -> None:
        try:
            from ..cache.kernel_cache import atomic_write
            _faults.maybe_fail("cache.disk.write",
                               key=f"prefix:{ent.key}")
            atomic_write(self.root / f"{ent.key}.json", ent.to_json())
        except Exception as e:  # noqa: BLE001 — write failures degrade
            # to a process-local entry, never a serving failure
            self._count("write_errors")
            _trace.inc("prefix_cache.write_errors")
            logger.warning("prefix cache: disk write of %s failed "
                           "(%s: %s)", ent.key[:12], type(e).__name__, e)

    def _disk_load(self, key: str) -> Optional[PrefixEntry]:
        path = self.root / f"{key}.json"
        if not path.is_file():
            return None
        try:
            _faults.maybe_fail("cache.disk.read", key=f"prefix:{key}")
            return PrefixEntry.from_json(path.read_text())
        except Exception as e:  # noqa: BLE001 — corruption quarantines
            self._quarantine(path, f"{type(e).__name__}: {e}")
            return None

    def _quarantine(self, path: Path, reason: str) -> None:
        """Move a corrupt/unreadable entry aside — the evidence stays
        inspectable, the key reads as a miss, and the next completed
        prefill re-inserts a clean entry (the kernel cache's
        never-rebuild-in-place discipline)."""
        qdir = self.root / QUARANTINE_DIR
        try:
            qdir.mkdir(parents=True, exist_ok=True)
            dst = qdir / path.name
            n = 0
            while dst.exists():
                n += 1
                dst = qdir / f"{path.name}.{n}"
            os.replace(path, dst)
        except OSError:
            try:
                path.unlink(missing_ok=True)
            except OSError:
                pass
        self._count("quarantined")
        _trace.inc("prefix_cache.quarantined")
        _trace.event("prefix_cache.quarantine", "serving",
                     entry=path.name, reason=reason)
        logger.warning("prefix cache: quarantined corrupt entry %s "
                       "(%s)", path.name, reason)

    # -- accounting ----------------------------------------------------
    def stats(self) -> Dict[str, int]:
        with self._lock:
            entries = len(self._mem)
            pages = sum(e.n_pages for e in self._mem.values())
        return {"entries": entries, "pages": pages,
                "page_budget": self.page_budget, "hits": self.hits,
                "misses": self.misses, "inserts": self.inserts,
                "evictions": self.evictions,
                "quarantined": self.quarantined,
                "write_errors": self.write_errors,
                "bytes_saved": self.bytes_saved}

    def clear(self, disk: bool = False) -> None:
        with self._lock:
            self._mem.clear()
            self._pending_disk.clear()
        if disk:
            for p in self.root.glob("*.json"):
                try:
                    p.unlink()
                except OSError:
                    pass


_CACHE: Optional[PrefixKVCache] = None
_CACHE_LOCK = threading.Lock()


def get_prefix_cache() -> PrefixKVCache:
    """The process-wide cache every workload in this process shares
    (the in-memory tier is the fast path; the disk tier is the fleet
    tier)."""
    global _CACHE
    with _CACHE_LOCK:
        if _CACHE is None:
            _CACHE = PrefixKVCache()
        return _CACHE


def reset_prefix_cache() -> None:
    global _CACHE
    with _CACHE_LOCK:
        _CACHE = None
