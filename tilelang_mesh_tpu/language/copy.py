"""T.copy / T.fill / T.clear — tile data movement.

Reference: /root/reference/tilelang/language/copy.py (T.copy:13) and
src/op/copy.cc (instruction selection over cp.async/LDSM/TMA). On TPU, copy
instruction selection happens in the transform pipeline instead: a copy whose
source indices are affine in grid vars becomes a Pallas BlockSpec (Mosaic
auto-DMA, multi-buffered); others lower to VMEM assignments or explicit
async DMA.
"""

from __future__ import annotations

from typing import Any, Optional

from ..ir import (Buffer, BufferLoad, CopyStmt, FillStmt, Region, to_region,
                  convert)
from .builder import require_builder


def _extent_hint(obj) -> Optional[tuple]:
    if isinstance(obj, Buffer):
        return tuple(obj.shape)
    if isinstance(obj, BufferLoad) and not obj.has_slices:
        return None
    if isinstance(obj, BufferLoad):
        return tuple(to_region(obj).shape)
    if isinstance(obj, Region):
        return tuple(obj.shape)
    return None


def copy(src: Any, dst: Any, coalesced_width: Optional[int] = None,
         disable_cache_hint: bool = False, eviction_policy=None):
    """Copy a rectangular region between buffers (any scopes).

    Shapes follow the reference's broadcast rule: an element-access base
    (``A[i, j]``) takes its extent from the other side.
    """
    b = require_builder()
    src_hint = _extent_hint(src)
    dst_hint = _extent_hint(dst)
    src_r = to_region(src, extent_hint=dst_hint)
    dst_r = to_region(dst, extent_hint=src_hint or tuple(src_r.shape))
    # validate extents where static
    ss, ds = src_r.static_shape(), dst_r.static_shape()
    if ss is not None and ds is not None:
        # right-aligned broadcast compare (leading 1s allowed)
        a, c = list(ss), list(ds)
        while len(a) < len(c):
            a.insert(0, 1)
        while len(c) < len(a):
            c.insert(0, 1)
        for x, y in zip(a, c):
            if x != y and x != 1 and y != 1:
                raise ValueError(
                    f"T.copy extent mismatch: src {ss} vs dst {ds}")
    b.emit(CopyStmt(src_r, dst_r, coalesced_width))


def fill(dst: Any, value):
    b = require_builder()
    b.emit(FillStmt(to_region(dst), convert(value)))


def clear(dst: Any):
    fill(dst, 0)


def c2d_im2col(img: Buffer, col: Buffer, nhw_step, c_step, kernel, stride,
               dilation, pad):
    raise NotImplementedError(
        "T.c2d_im2col is a TMA-hardware gather (reference src/op/copy.cc "
        "Conv2DIm2ColOp); TPUs have no im2col engine and a gather wastes "
        "HBM bandwidth. Express conv as K*K shifted-window GEMMs instead — "
        "every tap is a contiguous/strided VMEM slice feeding the MXU; see "
        "examples/convolution/example_convolution.py")
