"""T.copy / T.fill / T.clear — tile data movement.

Reference: /root/reference/tilelang/language/copy.py (T.copy:13) and
src/op/copy.cc (instruction selection over cp.async/LDSM/TMA). On TPU, copy
instruction selection happens in the transform pipeline instead: a copy whose
source indices are affine in grid vars becomes a Pallas BlockSpec (Mosaic
auto-DMA, multi-buffered); others lower to VMEM assignments or explicit
async DMA.
"""

from __future__ import annotations

from typing import Any, Optional

from ..ir import (Buffer, BufferLoad, CopyStmt, FillStmt, Region, to_region,
                  convert)
from .builder import require_builder


def _extent_hint(obj) -> Optional[tuple]:
    if isinstance(obj, Buffer):
        return tuple(obj.shape)
    if isinstance(obj, BufferLoad) and not obj.has_slices:
        return None
    if isinstance(obj, BufferLoad):
        return tuple(to_region(obj).shape)
    if isinstance(obj, Region):
        return tuple(obj.shape)
    return None


def copy(src: Any, dst: Any, coalesced_width: Optional[int] = None,
         disable_cache_hint: bool = False, eviction_policy=None):
    """Copy a rectangular region between buffers (any scopes).

    Shapes follow the reference's broadcast rule: an element-access base
    (``A[i, j]``) takes its extent from the other side.
    """
    b = require_builder()
    src_hint = _extent_hint(src)
    dst_hint = _extent_hint(dst)
    src_r = to_region(src, extent_hint=dst_hint)
    dst_r = to_region(dst, extent_hint=src_hint or tuple(src_r.shape))
    _validate_extents(src_r, dst_r, "T.copy")
    b.emit(CopyStmt(src_r, dst_r, coalesced_width))


def _validate_extents(src_r, dst_r, what: str):
    """Right-aligned broadcast compare of static extents (leading 1s ok)."""
    ss, ds = src_r.static_shape(), dst_r.static_shape()
    if ss is None or ds is None:
        return
    a, c = list(ss), list(ds)
    while len(a) < len(c):
        a.insert(0, 1)
    while len(c) < len(a):
        c.insert(0, 1)
    for x, y in zip(a, c):
        if x != y and x != 1 and y != 1:
            raise ValueError(f"{what} extent mismatch: src {ss} vs dst {ds}")


def _async_stmt(src, dst, sem, slot, phase):
    from ..ir import AsyncCopyStmt, Buffer as _Buf
    b = require_builder()

    def fit(hint, obj):
        # drop leading unit extents so a sliced-region hint can describe a
        # lower-rank element-base operand (A_s[0, 0:M, 0:K] -> A[i, j])
        if hint is None or not isinstance(obj, (Buffer, BufferLoad)):
            return hint
        rank = obj.ndim if isinstance(obj, Buffer) else obj.buffer.ndim
        h = list(hint)
        while len(h) > rank and h[0] == 1:
            h.pop(0)
        return tuple(h)

    src_hint = _extent_hint(src)
    dst_hint = _extent_hint(dst)
    src_r = to_region(src, extent_hint=fit(dst_hint, src))
    dst_r = to_region(dst, extent_hint=fit(src_hint, dst) or
                      tuple(src_r.shape))
    if not isinstance(sem, _Buf) or sem.scope != "sem":
        raise ValueError("sem must come from T.alloc_semaphore(n)")
    if src_r.buffer.dtype != dst_r.buffer.dtype:
        raise ValueError("T.copy_async cannot convert dtypes; stage through "
                         "VMEM and cast")
    _validate_extents(src_r, dst_r, f"T.copy_{phase}")
    b.emit(AsyncCopyStmt(src_r, dst_r, sem, convert(slot), phase))


def copy_async(src: Any, dst: Any, sem, slot=0):
    """Start an async DMA; completion is signalled on sem[slot].

    The split-phase form of T.copy: issue early, overlap compute, then
    T.copy_wait before use — the TPU-native expression of the reference's
    warp-specialized producer/consumer (warp_specialized_rewriter.cc)."""
    _async_stmt(src, dst, sem, slot, "start")


def copy_wait(src: Any, dst: Any, sem, slot=0):
    """Block until the DMA issued with the same (shape, sem[slot]) lands.

    src/dst restate the copy being awaited (their indices may differ from
    the issuing iteration; shapes and the semaphore slot must match)."""
    _async_stmt(src, dst, sem, slot, "wait")


def fill(dst: Any, value):
    b = require_builder()
    b.emit(FillStmt(to_region(dst), convert(value)))


def clear(dst: Any):
    fill(dst, 0)


def c2d_im2col(img: Buffer, col: Buffer, nhw_step, c_step, kernel, stride,
               dilation, pad):
    raise NotImplementedError(
        "T.c2d_im2col is a TMA-hardware gather (reference src/op/copy.cc "
        "Conv2DIm2ColOp); TPUs have no im2col engine and a gather wastes "
        "HBM bandwidth. Express conv as K*K shifted-window GEMMs instead — "
        "every tap is a contiguous/strided VMEM slice feeding the MXU; see "
        "examples/convolution/example_convolution.py")
