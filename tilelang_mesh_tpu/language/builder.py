"""Trace builder: executes a user kernel function against buffer proxies and
records tile-IR.

TPU-native re-design of the reference's DSL v2 builder
(/root/reference/tilelang/language/v2/builder.py:178). The reference rewrites
the Python AST and replays it against a TVM IRBuilder; we instead run the
function directly — loops and frames are context managers / generators that
push and pop builder frames. This covers the tile-DSL subset (data-dependent
Python `if` over traced values is rejected with a clear error; use
T.if_then_else / T.Select).
"""

from __future__ import annotations

import functools
import inspect
import os
import sys
import threading
from typing import Any, Callable, List, Optional

from ..ir import (Buffer, PrimFunc, SeqStmt, Stmt, AllocStmt, Var, convert)

_STATE = threading.local()

# DSL-machinery directories skipped when attributing an emitted statement
# to its user call site: the first frame OUTSIDE these is the kernel body
# line a diagnostic should point at (ops/ and user modules both count as
# kernel source).
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_DSL_DIRS = (os.path.join(_PKG_DIR, "language") + os.sep,
             os.path.join(_PKG_DIR, "ir") + os.sep)


def _source_loc(max_depth: int = 32):
    """("file", lineno) of the innermost non-DSL frame, or None.

    Captured on every Builder.emit so static-analysis diagnostics
    (analysis/diagnostics.py) can name the offending kernel line. Tracing
    runs once per kernel shape, so the small frame walk is off every hot
    path."""
    try:
        f = sys._getframe(2)
    except ValueError:          # pragma: no cover - interpreter limits
        return None
    depth = 0
    while f is not None and depth < max_depth:
        fname = f.f_code.co_filename
        if not fname.startswith("<") and \
                not any(fname.startswith(d) for d in _DSL_DIRS):
            return fname, f.f_lineno
        f = f.f_back
        depth += 1
    return None


def _stack() -> List["Builder"]:
    if not hasattr(_STATE, "stack"):
        _STATE.stack = []
    return _STATE.stack


def current_builder() -> Optional["Builder"]:
    st = _stack()
    return st[-1] if st else None


def require_builder() -> "Builder":
    b = current_builder()
    if b is None:
        raise RuntimeError("this T.* construct is only valid inside a "
                           "@T.prim_func body")
    return b


class Builder:
    """Collects statements into nested frames while the user function runs."""

    def __init__(self, name: str):
        self.name = name
        self.frames: List[SeqStmt] = [SeqStmt()]
        self.params: List[Any] = []
        self.attrs: dict = {}
        self._name_counts: dict = {}

    # -- frame management ----------------------------------------------------
    def push_frame(self) -> SeqStmt:
        f = SeqStmt()
        self.frames.append(f)
        return f

    def pop_frame(self) -> SeqStmt:
        return self.frames.pop()

    def emit(self, stmt: Stmt):
        if stmt.loc is None:
            stmt.loc = _source_loc()
        self.frames[-1].stmts.append(stmt)

    # -- naming --------------------------------------------------------------
    def fresh_name(self, base: str) -> str:
        n = self._name_counts.get(base, 0)
        self._name_counts[base] = n + 1
        return base if n == 0 else f"{base}_{n}"

    def alloc_buffer(self, shape, dtype, scope, name: str) -> Buffer:
        buf = Buffer(self.fresh_name(name), shape, dtype, scope)
        self.emit(AllocStmt(buf))
        return buf

    # -- finish --------------------------------------------------------------
    def finish(self) -> PrimFunc:
        assert len(self.frames) == 1, "unbalanced builder frames"
        return PrimFunc(self.name, self.params, self.frames[0], self.attrs)


class PrimFuncObj:
    """The object returned by @T.prim_func: holds the traced IR plus the
    original callable for re-elaboration (lazy_jit / dynamic shapes)."""

    def __init__(self, func: PrimFunc, source_fn: Callable,
                 annots: List[tuple]):
        self.func = func
        self.source_fn = source_fn
        self.annots = annots  # [(param_name, annot_obj)]

    @property
    def name(self):
        return self.func.name

    def script(self) -> str:
        return self.func.script()

    @property
    def params(self):
        return self.func.params

    @property
    def attrs(self):
        return self.func.attrs

    def __repr__(self):
        return f"PrimFuncObj({self.func.name})"

    def __call__(self, *args, **kwargs):
        # Convenience: compile on first call with the default target.
        from .. import compile as _compile
        if not hasattr(self, "_default_kernel"):
            self._default_kernel = _compile(self)
        return self._default_kernel(*args, **kwargs)


def _param_annotations(fn: Callable) -> List[tuple]:
    sig = inspect.signature(fn)
    # `from __future__ import annotations` stringifies annotations; evaluate
    # them against the function's globals + closure cells
    env = None
    out = []
    for name, p in sig.parameters.items():
        annot = p.annotation
        if annot is inspect.Parameter.empty:
            raise TypeError(
                f"@T.prim_func parameter {name!r} needs a T.Tensor/"
                f"T.MeshTensor/T.dyn annotation")
        if isinstance(annot, str):
            if env is None:
                env = dict(fn.__globals__)
                free = fn.__code__.co_freevars
                cells = fn.__closure__ or ()
                for fv, cell in zip(free, cells):
                    try:
                        env[fv] = cell.cell_contents
                    except ValueError:
                        pass
            try:
                annot = eval(annot, env)  # noqa: S307 - trusted kernel code
            except NameError as e:
                raise TypeError(
                    f"cannot evaluate stringified annotation {annot!r} for "
                    f"parameter {name!r} ({e}); avoid `from __future__ "
                    "import annotations` in kernel modules or annotate with "
                    "names visible in the function's closure") from e
        out.append((name, annot))
    return out


# observers called with every PrimFuncObj the builder produces — the
# offline linter (tools/lint.py) hooks here to collect the kernels a
# module traces while importing / seeding factories, without needing the
# module to export them
_TRACE_CALLBACKS: List[Callable] = []


def add_trace_callback(cb: Callable) -> Callable:
    _TRACE_CALLBACKS.append(cb)
    return cb


def remove_trace_callback(cb: Callable) -> None:
    try:
        _TRACE_CALLBACKS.remove(cb)
    except ValueError:
        pass


def trace_prim_func(fn: Callable, name: Optional[str] = None) -> PrimFuncObj:
    """Run `fn` against proxies built from its annotations; return the IR."""
    annots = _param_annotations(fn)
    b = Builder(name or fn.__name__)
    _stack().append(b)
    try:
        args = []
        for pname, annot in annots:
            proxy = _make_param(b, pname, annot)
            args.append(proxy)
        fn(*args)
    finally:
        _stack().pop()
    obj = PrimFuncObj(b.finish(), fn, annots)
    for cb in list(_TRACE_CALLBACKS):
        cb(obj)
    return obj


def _make_param(b: Builder, pname: str, annot) -> Any:
    """Instantiate a parameter proxy from its annotation object."""
    make = getattr(annot, "__tl_make_param__", None)
    if make is None:
        raise TypeError(
            f"annotation for parameter {pname!r} is {annot!r}, which is not a "
            "tile-language annotation (T.Tensor(...), T.MeshTensor(...), "
            "T.dyn(...))")
    proxy = make(pname, b)
    b.params.append(proxy if isinstance(proxy, (Buffer, Var)) else proxy)
    return proxy


def prim_func(fn: Optional[Callable] = None, *, private: bool = False):
    """Decorator: trace the function body into tile-IR.

    Mirrors the reference's ``T.prim_func``
    (/root/reference/tilelang/language/v2/builder.py:843). The traced IR is
    built eagerly at decoration time when all annotation shapes are concrete.
    """

    def wrap(f: Callable) -> PrimFuncObj:
        return trace_prim_func(f)

    if fn is not None:
        return wrap(fn)
    return wrap


def macro(fn: Callable) -> Callable:
    """A reusable DSL fragment: calling it inside a prim_func inlines its
    statements (reference: builder.py:718 Macro). With a trace-based builder
    a macro is just a Python function — provided for API parity."""

    @functools.wraps(fn)
    def inner(*args, **kwargs):
        require_builder()
        return fn(*args, **kwargs)

    return inner
