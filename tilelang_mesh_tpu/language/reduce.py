"""T.reduce_* / T.cumsum — tile reductions on the VPU.

Reference: /root/reference/tilelang/language/reduce.py + src/op/reduce.cc.
The GPU implementation synthesizes intra-warp shuffle trees; on TPU a tile
reduction is a single jnp.sum/max/... over the VMEM tile.
"""

from __future__ import annotations

from typing import Any

from ..ir import Buffer, CumSumStmt, ReduceStmt
from .builder import require_builder

_KINDS = ("sum", "max", "min", "abssum", "absmax", "bitand", "bitor",
          "bitxor", "any", "all")


def _reduce(kind: str, buffer: Buffer, out: Buffer, dim: int = -1,
            clear: bool = True):
    b = require_builder()
    assert kind in _KINDS, kind
    if dim < 0:
        dim += buffer.ndim
    if not 0 <= dim < buffer.ndim:
        raise ValueError(f"reduce dim {dim} out of range for rank "
                         f"{buffer.ndim}")
    b.emit(ReduceStmt(kind, buffer, out, dim, clear))


def reduce(buffer: Buffer, out: Buffer, reduce_type: str, dim: int = -1,
           clear: bool = True):
    _reduce(reduce_type, buffer, out, dim, clear)


def reduce_sum(buffer, out, dim: int = -1, clear: bool = True):
    _reduce("sum", buffer, out, dim, clear)


def reduce_max(buffer, out, dim: int = -1, clear: bool = True):
    _reduce("max", buffer, out, dim, clear)


def reduce_min(buffer, out, dim: int = -1, clear: bool = True):
    _reduce("min", buffer, out, dim, clear)


def reduce_abssum(buffer, out, dim: int = -1, clear: bool = True):
    _reduce("abssum", buffer, out, dim, clear)


def reduce_absmax(buffer, out, dim: int = -1, clear: bool = True):
    _reduce("absmax", buffer, out, dim, clear)


def reduce_bitand(buffer, out, dim: int = -1, clear: bool = True):
    _reduce("bitand", buffer, out, dim, clear)


def reduce_bitor(buffer, out, dim: int = -1, clear: bool = True):
    _reduce("bitor", buffer, out, dim, clear)


def reduce_bitxor(buffer, out, dim: int = -1, clear: bool = True):
    _reduce("bitxor", buffer, out, dim, clear)


def cumsum(src: Buffer, dst: Buffer = None, dim: int = -1,
           reverse: bool = False):
    b = require_builder()
    dst = dst if dst is not None else src
    if dim < 0:
        dim += src.ndim
    b.emit(CumSumStmt(src, dst, dim, reverse))


def finalize_reducer(reducer: Buffer):
    """Reference src/op/finalize_reducer.cc — combines per-thread partials.
    TPU fragments are whole tiles, so there is nothing to finalize."""
    require_builder()


def warp_reduce_sum(value):
    raise NotImplementedError("warp shuffles have no TPU analog; reduce over "
                              "a fragment buffer with T.reduce_sum")
