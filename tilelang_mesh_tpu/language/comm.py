"""T.comm.* — inter-core mesh communication DSL.

Behavioral equivalent of /root/reference/tilelang/language/comm.py (same
signatures, same shape/mesh validation, same direction and reduce-type
vocabulary). The ops record CommStmt nodes; the SPMD lowering
(parallel/lowering.py) turns them into XLA collectives over the ICI mesh —
``psum`` / ``all_gather`` / ``ppermute`` inside ``shard_map`` — instead of
the reference's compiler-synthesized NoC broadcast schedules
(src/op/comm.cc). The schedule synthesis itself is kept (parallel/
collectives.py, native-backed) for the Pallas ring-collective path and for
golden parity tests.
"""

from __future__ import annotations

from typing import Iterable, Literal, Optional, Tuple

from ..ir import (Buffer, CommAllGather, CommAllReduce, CommBarrier,
                  CommBroadcast, CommFence, CommPut, Region, to_region, Call,
                  dtype_bits)
from ..observability import tracer as _trace
from ..parallel.device_mesh import (get_device_mesh_config, core_tuple_to_id,
                                    core_id_to_tuple)
from .builder import require_builder

DIRECTION_MAP = {"horizontal": 0, "h": 0, "vertical": 1, "v": 1, "all": 2,
                 "a": 2}
REDUCE_TYPE_LIST = ("sum", "abssum", "max", "min", "absmax", "bitand",
                    "bitor", "bitxor")


def get_target_mesh_shape() -> dict:
    nrow, ncol = get_device_mesh_config()
    return {"x": nrow, "y": ncol}


def CoreId(core_id):
    """Linear core id for an int or (row, col) tuple."""
    mesh = get_target_mesh_shape()
    if isinstance(core_id, tuple):
        return core_tuple_to_id(core_id)
    if isinstance(core_id, int):
        assert 0 <= core_id < mesh["x"] * mesh["y"], \
            f"Core ID {core_id} out of bounds for mesh shape {mesh}"
        return core_id
    raise ValueError("core_id must be either a tuple[int, int] or an int.")


def current_core():
    """The executing core's linear id (a traced expression)."""
    return Call("current_core", [], "int32")


def _check_shapes_bcast(src: Buffer, dst: Buffer, opname: str):
    assert src.dtype == dst.dtype, (
        f"Source and destination buffer dtypes must match for {opname}. "
        f"Got {src.dtype} vs {dst.dtype}.")
    if len(src.shape) != len(dst.shape):
        raise ValueError(f"Source and destination buffer must have the same "
                         f"number of dimensions for {opname}.")
    for a, b in zip(src.shape, dst.shape):
        assert a == b or a == 1 or b == 1, (
            f"Source/destination shapes must be compatible for {opname}: "
            f"{src.shape} vs {dst.shape}")


def _check_core(core: Tuple[int, int], what: str):
    mesh = get_target_mesh_shape()
    assert isinstance(core, tuple) and len(core) == 2, \
        f"{what} must be a tuple of (row, col)."
    assert 0 <= core[0] < mesh["x"], \
        f"{what} row {core[0]} out of bounds for mesh shape {mesh}."
    assert 0 <= core[1] < mesh["y"], \
        f"{what} col {core[1]} out of bounds for mesh shape {mesh}."


_EMIT_SEQ = [0]


def _record_emit(op: str, payload_buf: Optional[Buffer],
                 direction: Optional[str] = None) -> dict:
    """Trace-time accounting of a T.comm.* emission: op kind, direction
    and the payload buffer's bytes. The *wire* cost (hops x chunk) is
    accounted where the schedule is known, in parallel/lowering.py; this
    records what the DSL asked for, so untraced-at-lowering programs
    (e.g. plain golden traces) still show up in metrics_summary().

    Returns the emission metadata dict; the emit helpers attach it to
    the CommStmt as ``emit_meta``. The collective optimizer
    (transform/comm_opt.py) folds the recorded payload bytes into its
    payload-identity slot keys, so two ops can only share a wire slot
    when the frontend also agreed on their size."""
    nbytes = 0
    if payload_buf is not None:
        n = payload_buf.numel()
        if n is not None:
            nbytes = n * dtype_bits(payload_buf.dtype) // 8
    _EMIT_SEQ[0] += 1
    _trace.inc("comm.emitted", op=op)
    _trace.event("comm.emit", "comm", op=op, direction=direction,
                 payload_bytes=nbytes)
    return {"op": op, "direction": direction, "payload_bytes": nbytes,
            "seq": _EMIT_SEQ[0]}


def _emit_comm(builder, stmt, meta: dict):
    """Emit a CommStmt carrying its emission metadata."""
    stmt.emit_meta = meta
    builder.emit(stmt)


def _check_size(size: int, buf: Buffer, what: str = "size"):
    n = buf.numel()
    assert isinstance(size, int) and size >= -1, \
        f"{what} must be an integer >= -1."
    if n is not None:
        assert size <= n, f"{what} {size} exceeds source buffer size {n}."


def broadcast(src: Buffer, dst: Buffer, src_core: Tuple[int, int],
              direction: Literal["horizontal", "h", "vertical", "v", "all",
                                 "a"] = "all",
              size: int = -1):
    """Broadcast `src` on `src_core` into `dst` on every core along
    `direction`."""
    b = require_builder()
    _check_shapes_bcast(src, dst, "broadcast")
    _check_core(src_core, "src_core")
    _check_size(size, src)
    assert direction.lower() in DIRECTION_MAP, \
        f"Invalid direction string: {direction}"
    meta = _record_emit("broadcast", src, direction.lower())
    _emit_comm(b, CommBroadcast(to_region(src), to_region(dst), size, 0,
                                core_tuple_to_id(src_core),
                                DIRECTION_MAP[direction.lower()]), meta)


def put(src: Buffer, dst: Buffer, src_core: Tuple[int, int],
        dst_core: Tuple[int, int], size: int = -1):
    """Point-to-point: send `src` from src_core into `dst` on dst_core."""
    b = require_builder()
    _check_shapes_bcast(src, dst, "put")
    _check_core(src_core, "src_core")
    _check_core(dst_core, "dst_core")
    _check_size(size, src)
    meta = _record_emit("put", src)
    _emit_comm(b, CommPut(to_region(src), to_region(dst), size,
                          core_tuple_to_id(src_core),
                          core_tuple_to_id(dst_core)), meta)


def all_gather(send_buffer: Buffer, recv_buffer: Buffer,
               direction: Literal["horizontal", "h", "vertical", "v", "all",
                                  "a"] = "all",
               size: int = -1):
    """Gather every participating core's send_buffer into
    recv_buffer[core, ...]."""
    b = require_builder()
    assert direction.lower() in DIRECTION_MAP, \
        f"Invalid direction string: {direction}"
    assert send_buffer.dtype == recv_buffer.dtype, (
        f"Source and destination buffer dtypes must match for all_gather. "
        f"Got {send_buffer.dtype} vs {recv_buffer.dtype}.")
    mesh = get_target_mesh_shape()
    d = direction.lower()
    if d in ("horizontal", "h"):
        recv_num = mesh["y"]
    elif d in ("vertical", "v"):
        recv_num = mesh["x"]
    else:
        recv_num = mesh["x"] * mesh["y"]
    expected = [recv_num] + [int(s) for s in send_buffer.shape]
    got = [int(s) for s in recv_buffer.shape]
    assert got == expected, (
        f"Receive buffer shape must be {expected} to hold gathered data from "
        f"{recv_num} cores, but got {got}.")
    _check_size(size, send_buffer)
    meta = _record_emit("all_gather", send_buffer, d)
    _emit_comm(b, CommAllGather(to_region(send_buffer),
                                to_region(recv_buffer),
                                DIRECTION_MAP[d], size), meta)


def all_reduce(buffer: Buffer, out: Buffer, reduce_type: str,
               direction: Literal["horizontal", "h", "vertical", "v", "all",
                                  "a"],
               dim: int = -1, clear: bool = True):
    """Local reduce over `dim`, then mesh-wide reduce along `direction`.

    Output shape: buffer.shape without `dim` (or with `dim` kept as 1).
    clear=False accumulates into the existing contents of `out`.
    """
    b = require_builder()
    assert isinstance(dim, int) and -1 <= dim < len(buffer.shape), \
        f"dim {dim} out of bounds for buffer with {len(buffer.shape)} " \
        "dimensions."
    if dim == -1:
        dim = len(buffer.shape) - 1
    shape = [int(s) for s in buffer.shape]
    expected = [shape[:dim] + shape[dim + 1:],
                shape[:dim] + [1] + shape[dim + 1:]]
    got = [int(s) for s in out.shape]
    if got not in expected:
        exp_s = " or ".join(map(str, expected))
        raise ValueError(
            f"Invalid reduce output shape, buffer shape is {shape}, dim is "
            f"{dim}, output shape is {got}, expected shapes are {exp_s}")
    reduce_type = reduce_type.lower()
    assert reduce_type in REDUCE_TYPE_LIST, (
        f"Reduction op must be one of {REDUCE_TYPE_LIST}, but got "
        f"{reduce_type}.")
    assert direction.lower() in DIRECTION_MAP, \
        f"Invalid direction string: {direction}"
    assert clear in (True, False), "clear must be a boolean value."
    meta = _record_emit("all_reduce", out, direction.lower())
    _emit_comm(b, CommAllReduce(to_region(buffer), to_region(out),
                                reduce_type,
                                DIRECTION_MAP[direction.lower()], dim,
                                clear), meta)


def barrier(group: Optional[Iterable[Tuple[int, int]]] = None):
    """Synchronize a group of cores (all cores when group is None)."""
    b = require_builder()
    ids = None if group is None else [core_tuple_to_id(c) for c in group]
    _record_emit("barrier", None)
    b.emit(CommBarrier(ids))


def fence():
    """Order communication against subsequent memory operations."""
    b = require_builder()
    _record_emit("fence", None)
    b.emit(CommFence())
