"""T.print / T.device_assert — in-kernel debugging.

Reference: /root/reference/tilelang/language/print.py. Lowered to
pl.debug_print / jax checkify-style predicated traps.
"""

from __future__ import annotations

from ..ir import AssertStmt, Buffer, PrintStmt, convert
from .builder import require_builder


def print(obj, msg: str = ""):  # noqa: A001 - mirrors reference name
    b = require_builder()
    if not isinstance(obj, Buffer):
        obj = convert(obj)
    b.emit(PrintStmt(obj, msg))


def device_assert(cond, msg: str = ""):
    b = require_builder()
    b.emit(AssertStmt(convert(cond), msg))
