"""T.gemm — tile matrix multiply on the MXU.

Reference: /root/reference/tilelang/language/gemm.py + src/op/gemm.cc
(GemmInst selection MMA/WGMMA/TCGEN5MMA and warp partitioning). On TPU there
is exactly one instruction that matters — the 128x128 systolic MXU — so the
op lowers to ``jnp.dot(..., preferred_element_type=f32)`` on VMEM tiles and
the whole warp-policy machinery degenerates to an API-compatible hint object.
"""

from __future__ import annotations

from enum import IntEnum
from typing import Any, Optional

from ..ir import GemmStmt, to_region
from .builder import require_builder


class GemmWarpPolicy(IntEnum):
    """API-parity stub of the reference's warp-partition policy
    (tilelang/language/gemm.py:18-163); harmless on TPU."""
    Square = 0
    FullRow = 1
    FullCol = 2

    @classmethod
    def from_warp_partition(cls, m_warp: int, n_warp: int) -> "GemmWarpPolicy":
        if m_warp == n_warp:
            return cls.Square
        return cls.FullRow if m_warp > n_warp else cls.FullCol


def gemm(A: Any, B: Any, C: Any, transpose_A: bool = False,
         transpose_B: bool = False, policy: GemmWarpPolicy = GemmWarpPolicy.Square,
         clear_accum: bool = False, k_pack: int = 1, wg_wait: int = 0):
    """C += op(A) @ op(B)  (C zeroed first when clear_accum).

    A: (M, K) or (K, M) if transpose_A; B: (K, N) or (N, K) if transpose_B;
    C: (M, N) accumulator fragment.
    """
    b = require_builder()
    A_r, B_r, C_r = to_region(A), to_region(B), to_region(C)
    # static shape validation when available
    a_s, b_s, c_s = A_r.static_shape(), B_r.static_shape(), C_r.static_shape()
    if a_s and b_s and c_s and len(a_s) == 2 and len(b_s) == 2:
        M, K = (a_s[1], a_s[0]) if transpose_A else a_s
        Kb, N = (b_s[1], b_s[0]) if transpose_B else b_s
        if K != Kb:
            raise ValueError(f"T.gemm K mismatch: {K} vs {Kb} "
                             f"(A={a_s} tA={transpose_A}, B={b_s} "
                             f"tB={transpose_B})")
        if (M, N) != tuple(c_s):
            raise ValueError(f"T.gemm output shape {c_s} != ({M}, {N})")
    b.emit(GemmStmt(A_r, B_r, C_r, transpose_A, transpose_B, policy,
                    clear_accum, k_pack, wg_wait))


def gemm_sp(A_sparse, E, B, C, transpose_A: bool = False,
            transpose_B: bool = False,
            policy: GemmWarpPolicy = GemmWarpPolicy.Square,
            clear_accum: bool = False, k_pack: int = 1, wg_wait: int = 0,
            **kwargs):
    """C += decompress(A_sparse, E) @ op(B) — 2:4 structured-sparse GEMM.

    Reference: src/op/gemm_sp.cc lowers to mma.sp with CUTLASS-packed
    metadata. TPUs have no sparse-MXU instruction, so this expands to a
    VPU decompress (compare-select against the int8 slot metadata of
    utils/sparse.py compress) into a VMEM scratch tile followed by a dense
    MXU T.gemm — the HBM saving on the sparse operand is kept, the FLOPs
    are dense.

    A_sparse: (M, K//2) VMEM tile of kept values; E: (M, K//2) int8 slot
    indices (0..3 within each K-group of 4); B: (K, N); C: (M, N) fragment.
    """
    if kwargs:
        # Reject unknown options instead of silently discarding them —
        # a misspelled reference kwarg must not pass (round-1 advisor
        # finding). k_pack/wg_wait are accepted for API parity; they tune
        # MMA packing / warpgroup waits, which Mosaic owns on TPU.
        raise TypeError(f"gemm_sp got unexpected kwargs: {sorted(kwargs)}")
    if transpose_A:
        raise NotImplementedError(
            "gemm_sp with transpose_A: store A_sparse row-major (the "
            "decompress scratch is row-major)")
    from .allocate import alloc_shared
    from .loop import Parallel
    from .math_ops import if_then_else

    A_r, E_r = to_region(A_sparse), to_region(E)
    a_s, e_s = A_r.static_shape(), E_r.static_shape()
    if a_s is None or len(a_s) != 2:
        raise ValueError("gemm_sp needs a static 2-D A_sparse tile")
    if e_s != a_s:
        raise ValueError(
            f"gemm_sp metadata shape {e_s} must match values {a_s}")
    M, half = a_s
    if half % 2:
        raise ValueError("A_sparse second dim must be even (pairs per "
                         "4-group)")
    K = half * 2
    if not (A_r.is_full() and E_r.is_full()):
        raise ValueError("gemm_sp operands must be whole tiles (pass the "
                         "buffers, not slices)")
    Ab, Eb = A_r.buffer, E_r.buffer
    dense = alloc_shared((M, K), Ab.dtype)
    for i, g, p in Parallel(M, K // 4, 4):
        dense[i, g * 4 + p] = (
            if_then_else(Eb[i, g * 2] == p, Ab[i, g * 2], 0.0) +
            if_then_else(Eb[i, g * 2 + 1] == p, Ab[i, g * 2 + 1], 0.0))
    gemm(dense, B, C, transpose_A=False, transpose_B=transpose_B,
         policy=policy, clear_accum=clear_accum)
