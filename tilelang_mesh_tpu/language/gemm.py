"""T.gemm — tile matrix multiply on the MXU.

Reference: /root/reference/tilelang/language/gemm.py + src/op/gemm.cc
(GemmInst selection MMA/WGMMA/TCGEN5MMA and warp partitioning). On TPU there
is exactly one instruction that matters — the 128x128 systolic MXU — so the
op lowers to ``jnp.dot(..., preferred_element_type=f32)`` on VMEM tiles and
the whole warp-policy machinery degenerates to an API-compatible hint object.
"""

from __future__ import annotations

from enum import IntEnum
from typing import Any, Optional

from ..ir import GemmStmt, to_region
from .builder import require_builder


class GemmWarpPolicy(IntEnum):
    """API-parity stub of the reference's warp-partition policy
    (tilelang/language/gemm.py:18-163); harmless on TPU."""
    Square = 0
    FullRow = 1
    FullCol = 2

    @classmethod
    def from_warp_partition(cls, m_warp: int, n_warp: int) -> "GemmWarpPolicy":
        if m_warp == n_warp:
            return cls.Square
        return cls.FullRow if m_warp > n_warp else cls.FullCol


def gemm(A: Any, B: Any, C: Any, transpose_A: bool = False,
         transpose_B: bool = False, policy: GemmWarpPolicy = GemmWarpPolicy.Square,
         clear_accum: bool = False, k_pack: int = 1, wg_wait: int = 0):
    """C += op(A) @ op(B)  (C zeroed first when clear_accum).

    A: (M, K) or (K, M) if transpose_A; B: (K, N) or (N, K) if transpose_B;
    C: (M, N) accumulator fragment.
    """
    b = require_builder()
    A_r, B_r, C_r = to_region(A), to_region(B), to_region(C)
    # static shape validation when available
    a_s, b_s, c_s = A_r.static_shape(), B_r.static_shape(), C_r.static_shape()
    if a_s and b_s and c_s and len(a_s) == 2 and len(b_s) == 2:
        M, K = (a_s[1], a_s[0]) if transpose_A else a_s
        Kb, N = (b_s[1], b_s[0]) if transpose_B else b_s
        if K != Kb:
            raise ValueError(f"T.gemm K mismatch: {K} vs {Kb} "
                             f"(A={a_s} tA={transpose_A}, B={b_s} "
                             f"tB={transpose_B})")
        if (M, N) != tuple(c_s):
            raise ValueError(f"T.gemm output shape {c_s} != ({M}, {N})")
    b.emit(GemmStmt(A_r, B_r, C_r, transpose_A, transpose_B, policy,
                    clear_accum, k_pack, wg_wait))


def gemm_sp(A_sparse, E, B, C, **kwargs):
    raise NotImplementedError(
        "2:4 structured-sparse GEMM has no MXU instruction on TPU; "
        "densify the operand or use a blocksparse schedule "
        "(ops.blocksparse)")
