"""tilelang_mesh_tpu.language — the `T` namespace.

The full DSL surface, mirroring /root/reference/tilelang/language/__init__.py
re-founded on TPU semantics. Typical use::

    import tilelang_mesh_tpu.language as T

    @T.prim_func
    def kernel(A: T.Tensor((M, K), "bfloat16"), ...):
        with T.Kernel(T.ceildiv(N, bn), T.ceildiv(M, bm)) as (bx, by):
            ...
"""

# builder / prim_func
from .builder import prim_func, macro, Builder, PrimFuncObj, current_builder

# annotations (kernel params)
from .annot import (Tensor, StridedTensor, MeshTensor, MeshTensorAnnot,
                    TensorAnnot, dyn, dynamic, symbolic)
from ..parallel.sharding import MeshShardingPolicy, MeshReplicationType

# kernel frame
from .kernel import Kernel

# allocation
from .allocate import (alloc_shared, alloc_fragment, alloc_local, alloc_var,
                       alloc_reducer, alloc_semaphore, alloc_barrier,
                       alloc_tmem, alloc_descriptor)

# data movement
from .copy import copy, copy_async, copy_wait, fill, clear, c2d_im2col

# compute
from .gemm import gemm, gemm_sp, GemmWarpPolicy

# loops
from .loop import Parallel, Pipelined, Persistent, serial, unroll, vectorized

# reductions
from .reduce import (reduce, reduce_sum, reduce_max, reduce_min,
                     reduce_abssum, reduce_absmax, reduce_bitand,
                     reduce_bitor, reduce_bitxor, cumsum, finalize_reducer)

# atomics
from .atomic import (atomic_add, atomic_max, atomic_min, atomic_addx2,
                     atomic_addx4)

# math intrinsics
from .math_ops import (exp, exp2, exp10, log, log2, log10, log1p, sqrt, rsqrt,
                       sin, cos, tan, sinh, cosh, tanh, asin, acos, atan,
                       atan2, erf, floor, ceil, round, trunc, sigmoid, abs,
                       max, min, pow, fmod, max_value, min_value, infinity,
                       if_then_else, Select, clamp, cast, reinterpret,
                       shift_right, shift_left, bitwise_and, bitwise_or,
                       bitwise_xor,
                       ceildiv, floordiv, floormod, truncdiv, truncmod,
                       __exp, __exp2, __exp10, __log, __log2, __log10, __sin,
                       __cos, __tan, __pow)

# predicated blocks
from .ifelse import If, Else

# debug
from .debug import print, device_assert  # noqa: A004

# annotations / hints
from .annotations import (use_swizzle, annotate_layout, annotate_safe_value,
                          annotate_l2_hit_ratio, annotate_restricted_layout,
                          set_max_nreg, no_set_max_nreg,
                          disable_warp_group_reg_alloc, sync_threads,
                          fence_proxy_async)

# communication (mesh extension)
from . import comm
from .comm import CoreId, current_core

# expression-level helpers re-exported at T.*
from ..ir import Var, const, convert as _convert

int8 = "int8"
int16 = "int16"
int32 = "int32"
int64 = "int64"
uint8 = "uint8"
uint16 = "uint16"
uint32 = "uint32"
uint64 = "uint64"
float16 = "float16"
bfloat16 = "bfloat16"
float32 = "float32"
float64 = "float64"
float8_e4m3 = "float8_e4m3fn"
float8_e5m2 = "float8_e5m2"
bool_ = "bool"


def thread_binding(*args, **kwargs):
    raise NotImplementedError(
        "T.thread_binding is CUDA-specific; TPU kernels express parallelism "
        "with T.Parallel (VPU lanes) and the T.Kernel grid (cores)")
