"""Kernel annotations: T.use_swizzle, T.annotate_layout, etc.

Reference: /root/reference/tilelang/language/annotations.py. On TPU these are
scheduling hints recorded into the enclosing kernel's annotation dict; the
Mosaic compiler owns physical layout, so most are advisory (swizzle -> grid
rasterization hint consumed by the codegen's grid-order choice; layout
annotations -> checked against the layout engine).
"""

from __future__ import annotations

from typing import Any, Dict

from .builder import require_builder


def _annotate(key: str, value):
    b = require_builder()
    b.attrs.setdefault("kernel_annotations", {})[key] = value


def use_swizzle(panel_size: int = 10, order: str = "row", enable: bool = True):
    """L2-locality rasterization hint (reference: rasterization2DColumn).
    TPU grids iterate sequentially per core; the codegen uses this to choose
    a panel-major grid order when beneficial."""
    _annotate("swizzle", {"panel_size": panel_size, "order": order,
                          "enable": enable})


def annotate_layout(layout_map: Dict[Any, Any]):
    _annotate("layout_map", layout_map)


def annotate_safe_value(buffer, value):
    _annotate("safe_value", (buffer, value))


def annotate_l2_hit_ratio(buffer, ratio: float):
    # No L2 persisting-cache on TPU; retained for API parity.
    _annotate("l2_hit_ratio", (getattr(buffer, "name", buffer), ratio))


def annotate_restricted_layout(*args, **kwargs):
    pass


def no_set_max_nreg(*args, **kwargs):
    pass


def set_max_nreg(*args, **kwargs):
    pass


def disable_warp_group_reg_alloc(*args, **kwargs):
    pass


def sync_threads():
    """__syncthreads analog: a no-op on TPU (single instruction stream per
    core; DMA ordering is handled by semaphores the compiler inserts)."""
    require_builder()


def fence_proxy_async(*a, **k):
    require_builder()
