"""Kernel annotations: T.use_swizzle, T.annotate_layout, etc.

Reference: /root/reference/tilelang/language/annotations.py. On TPU these are
scheduling hints recorded into the enclosing kernel's annotation dict; the
Mosaic compiler owns physical layout, so most are advisory (swizzle -> grid
rasterization hint consumed by the codegen's grid-order choice; layout
annotations -> checked against the layout engine).
"""

from __future__ import annotations

import logging
from typing import Any, Dict

from .builder import require_builder

logger = logging.getLogger("tilelang_mesh_tpu")
_warned: set = set()


def _no_tpu_effect(what: str, why: str):
    """API-parity hint accepted for source compatibility but with no TPU
    effect: validate that it is called inside a kernel and warn ONCE per
    process so silent-accept cannot hide a user error (cf. the loud
    _gpu_only allocs in language/allocate.py)."""
    def f(*args, **kwargs):
        require_builder()   # misuse outside a kernel still errors
        if what not in _warned:
            _warned.add(what)
            logger.warning("T.%s has no effect on TPU: %s", what, why)
    f.__name__ = what
    f.__doc__ = f"Reference API-parity no-op on TPU: {why}"
    return f


def _annotate(key: str, value):
    b = require_builder()
    b.attrs.setdefault("kernel_annotations", {})[key] = value


def use_swizzle(panel_size: int = 10, order: str = "row", enable: bool = True):
    """L2-locality rasterization hint (reference: rasterization2DColumn).
    TPU grids iterate sequentially per core; the codegen uses this to choose
    a panel-major grid order when beneficial."""
    _annotate("swizzle", {"panel_size": panel_size, "order": order,
                          "enable": enable})


def annotate_layout(layout_map: Dict[Any, Any]):
    _annotate("layout_map", layout_map)


def annotate_safe_value(buffer, value):
    _annotate("safe_value", (buffer, value))


def annotate_l2_hit_ratio(buffer, ratio: float):
    # No L2 persisting-cache on TPU; retained for API parity.
    _annotate("l2_hit_ratio", (getattr(buffer, "name", buffer), ratio))


annotate_restricted_layout = _no_tpu_effect(
    "annotate_restricted_layout",
    "Mosaic owns physical layout; restricted-layout constraints are "
    "GPU-fragment concepts")
no_set_max_nreg = _no_tpu_effect(
    "no_set_max_nreg", "there is no per-thread register file to cap on "
    "the TPU's vector cores")
set_max_nreg = _no_tpu_effect(
    "set_max_nreg", "there is no per-thread register file to cap on the "
    "TPU's vector cores")
disable_warp_group_reg_alloc = _no_tpu_effect(
    "disable_warp_group_reg_alloc",
    "warpgroup register reallocation is a Hopper construct; TPU has no "
    "warps")


def sync_threads():
    """__syncthreads analog: a no-op on TPU (single instruction stream per
    core; DMA ordering is handled by semaphores the compiler inserts)."""
    require_builder()


def fence_proxy_async(*a, **k):
    require_builder()
