"""T.Kernel — the grid launch frame.

Reference: /root/reference/tilelang/language/kernel.py:228. On GPU this frame
binds blockIdx; on TPU the frame's vars become Pallas grid dimensions
(sequential per-core iteration, auto-pipelined by Mosaic). The first var
(`bx`) is the fastest-varying, matching CUDA blockIdx.x — the pass pipeline
reverses the order when building the Pallas grid so `bx` lands innermost.
"""

from __future__ import annotations

from typing import Any

from ..ir import KernelNode, SeqStmt, Var, as_int
from .builder import require_builder


class KernelFrame:
    def __init__(self, *extents, threads: Any = None, prelude=None):
        if len(extents) == 1 and isinstance(extents[0], (tuple, list)):
            extents = tuple(extents[0])
        self.extents = []
        for e in extents:
            v = as_int(e)
            if v is None:
                raise ValueError(
                    "T.Kernel grid extents must be static ints on TPU "
                    f"(got {e!r}); use lazy_jit for per-shape specialization")
            self.extents.append(v)
        self.threads = threads
        self.grid_vars = []

    def __enter__(self):
        b = require_builder()
        names = ["bx", "by", "bz"]
        self.grid_vars = [
            Var(b.fresh_name(names[i] if i < 3 else f"b{i}"))
            for i in range(len(self.extents))
        ]
        # capture statements traced before the frame (rare; kept as prelude)
        self._prelude = b.frames[-1].stmts
        b.frames[-1].stmts = []
        self._outer_holder = b.frames[-1]
        b.push_frame()
        if len(self.grid_vars) == 1:
            return self.grid_vars[0]
        return tuple(self.grid_vars)

    def __exit__(self, exc_type, exc, tb):
        b = require_builder()
        body = b.pop_frame()
        if exc_type is not None:
            return False
        node = KernelNode(self.grid_vars, self.extents, self.threads, body,
                          prelude=self._prelude)
        b.emit(node)
        return False


def Kernel(*extents, threads: Any = None, prelude=None) -> KernelFrame:
    return KernelFrame(*extents, threads=threads, prelude=prelude)


def get_thread_binding(dim: int = 0):
    raise NotImplementedError(
        "explicit thread bindings have no TPU analog; use T.Parallel and let "
        "the compiler vectorize over VPU lanes")
