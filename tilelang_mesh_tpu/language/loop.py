"""Loop constructs: T.Parallel, T.Pipelined, T.serial, T.unroll,
T.vectorized, T.Persistent.

Reference: /root/reference/tilelang/language/loop.py. TPU lowering:
  Parallel   -> vectorized VPU/MXU ops over the whole tile (no thread binding)
  Pipelined  -> an extra (innermost) Pallas grid dimension; Mosaic's pipeline
                machinery provides the multi-stage HBM->VMEM double buffering
                that inject_pipeline.cc builds by hand on GPU
  serial     -> lax.fori_loop (or unrolled Python loop when small)
  unroll     -> unrolled at trace time by the codegen
"""

from __future__ import annotations

from typing import Any, List

from ..ir import ForNest, Var, as_int, convert
from .builder import require_builder


class _LoopBuilder:
    def __init__(self, extents, kind: str, num_stages: int = 0,
                 annotations=None):
        self.extents = list(extents)
        self.kind = kind
        self.num_stages = num_stages
        self.annotations = annotations or {}

    def __iter__(self):
        b = require_builder()
        base = {"parallel": "i", "pipelined": "ko", "serial": "k",
                "unroll": "u", "vectorized": "v", "persistent": "p"}
        names = ("i", "j", "k", "l", "m", "n")
        if len(self.extents) == 1:
            vs = [Var(b.fresh_name(base.get(self.kind, "i")))]
        else:
            vs = [Var(b.fresh_name(names[i] if i < len(names) else f"i{i}"))
                  for i in range(len(self.extents))]
        b.push_frame()
        try:
            yield vs[0] if len(vs) == 1 else tuple(vs)
        finally:
            body = b.pop_frame()
            exts = [as_int(e) if as_int(e) is not None else convert(e)
                    for e in self.extents]
            b.emit(ForNest(vs, exts, self.kind, body, self.num_stages,
                           self.annotations))


def Parallel(*extents, coalesced_width=None) -> _LoopBuilder:
    """Elementwise loop nest mapped to full-tile vector ops."""
    return _LoopBuilder(extents, "parallel",
                        annotations={"coalesced_width": coalesced_width})


def Pipelined(extent, num_stages: int = 0, order=None, stage=None,
              sync=None, group=None) -> _LoopBuilder:
    """Software-pipelined reduction loop (num_stages is an overlap hint; the
    Mosaic pipeline chooses actual buffering)."""
    return _LoopBuilder([extent], "pipelined", num_stages=num_stages,
                        annotations={"order": order, "stage": stage})


def serial(*args, annotations=None) -> _LoopBuilder:
    start, stop = (0, args[0]) if len(args) == 1 else args[:2]
    if as_int(start) not in (0, None) :
        raise NotImplementedError("non-zero loop start not supported yet")
    return _LoopBuilder([stop], "serial", annotations=annotations)


def unroll(*args) -> _LoopBuilder:
    start, stop = (0, args[0]) if len(args) == 1 else args[:2]
    return _LoopBuilder([stop], "unroll")


def vectorized(*args) -> _LoopBuilder:
    start, stop = (0, args[0]) if len(args) == 1 else args[:2]
    return _LoopBuilder([stop], "vectorized")


def Persistent(*extents) -> _LoopBuilder:
    """Persistent-kernel loop (reference loop.py:35). TPU cores already run a
    persistent sequential grid, so this is a serial loop annotation."""
    return _LoopBuilder(extents, "persistent")
