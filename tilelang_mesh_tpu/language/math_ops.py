"""Scalar/elementwise intrinsics available inside kernel expressions.

Reference: /root/reference/tilelang/language/math_intrinsics.py and
fastmath.py. Each intrinsic records a Call node; the codegen maps names to
jnp/lax equivalents (see codegen/pallas.py _CALL_IMPL). On TPU there is no
--use_fast_math split: XLA picks VPU transcendental approximations itself,
so the __exp-style fastmath variants alias the exact ones.
"""

from __future__ import annotations

from typing import Any

from ..ir import Call, Cast, PrimExpr, convert, promote_dtypes


def _unary(name):
    def f(x):
        x = convert(x)
        dt = x.dtype if x.dtype.startswith("float") or x.dtype == "bfloat16" \
            else "float32"
        return Call(name, [x], dt)
    f.__name__ = name
    return f


def _binary(name):
    def f(a, b):
        a, b = convert(a), convert(b)
        return Call(name, [a, b], promote_dtypes(a.dtype, b.dtype))
    f.__name__ = name
    return f


exp = _unary("exp")
exp2 = _unary("exp2")
exp10 = _unary("exp10")
log = _unary("log")
log2 = _unary("log2")
log10 = _unary("log10")
log1p = _unary("log1p")
sqrt = _unary("sqrt")
rsqrt = _unary("rsqrt")
sin = _unary("sin")
cos = _unary("cos")
tan = _unary("tan")
sinh = _unary("sinh")
cosh = _unary("cosh")
tanh = _unary("tanh")
asin = _unary("asin")
acos = _unary("acos")
atan = _unary("atan")
erf = _unary("erf")
floor = _unary("floor")
ceil = _unary("ceil")
round = _unary("round")
trunc = _unary("trunc")
sigmoid = _unary("sigmoid")

atan2 = _binary("atan2")
pow = _binary("pow")
fmod = _binary("fmod")

# fastmath aliases (reference fastmath.py __exp etc.)
__exp = exp
__exp2 = exp2
__exp10 = exp10
__log = log
__log2 = log2
__log10 = log10
__sin = sin
__cos = cos
__tan = tan
__pow = pow


def abs(x):
    x = convert(x)
    return Call("abs", [x], x.dtype)


def shift_right(x, n):
    x, n = convert(x), convert(n)
    return Call("shift_right", [x, n], x.dtype)


def shift_left(x, n):
    x, n = convert(x), convert(n)
    return Call("shift_left", [x, n], x.dtype)


def bitwise_and(a, b):
    a, b = convert(a), convert(b)
    return Call("bitwise_and", [a, b], promote_dtypes(a.dtype, b.dtype))


def bitwise_or(a, b):
    a, b = convert(a), convert(b)
    return Call("bitwise_or", [a, b], promote_dtypes(a.dtype, b.dtype))


def bitwise_xor(a, b):
    a, b = convert(a), convert(b)
    return Call("bitwise_xor", [a, b], promote_dtypes(a.dtype, b.dtype))


def max(a, b, *rest):
    from ..ir.expr import _binop
    r = _binop("max", a, b)
    for x in rest:
        r = _binop("max", r, x)
    return r


def min(a, b, *rest):
    from ..ir.expr import _binop
    r = _binop("min", a, b)
    for x in rest:
        r = _binop("min", r, x)
    return r


def max_value(dtype: str):
    return Call("max_value", [str(dtype)], dtype if isinstance(dtype, str)
                else "float32")


def min_value(dtype: str):
    return Call("min_value", [str(dtype)], dtype if isinstance(dtype, str)
                else "float32")


def infinity(dtype: str = "float32"):
    return Call("max_value", [str(dtype)], dtype)


def if_then_else(cond, a, b):
    cond, a, b = convert(cond), convert(a), convert(b)
    return Call("where", [cond, a, b], promote_dtypes(a.dtype, b.dtype))


Select = if_then_else


def clamp(x, lo, hi):
    return min(max(x, lo), hi)


def Cast_(dtype, value):
    return Cast(dtype, convert(value))


def cast(value, dtype):
    return Cast(dtype, convert(value))


def reinterpret(dtype, value):
    value = convert(value)
    return Call("bitcast", [value, str(dtype)], str(dtype))


def ceildiv(a, b):
    from ..ir import ceildiv as _cd
    return _cd(a, b)


def floordiv(a, b):
    from ..ir.expr import _binop
    return _binop("//", a, b)


def floormod(a, b):
    from ..ir.expr import _binop
    return _binop("%", a, b)


def truncdiv(a, b):
    from ..ir.expr import _binop
    return _binop("//", a, b)


def truncmod(a, b):
    from ..ir.expr import _binop
    return _binop("%", a, b)
