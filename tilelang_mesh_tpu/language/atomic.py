"""T.atomic_* — reference tilelang/language/atomic.py + src/op/atomic_add.cc.

TPU grid steps run sequentially on a core and cross-core accumulation goes
through collectives, so 'atomics' lower to plain read-modify-write on the
destination tile (correct under Pallas' sequential grid semantics)."""

from __future__ import annotations

from typing import Any

from ..ir import AtomicStmt, to_region, convert, Buffer, BufferLoad, Region
from .builder import require_builder


def _emit(op: str, dst: Any, value: Any):
    b = require_builder()
    hint = None
    if isinstance(value, (Buffer, Region)) or (
            isinstance(value, BufferLoad) and value.has_slices):
        value = to_region(value)
        hint = tuple(value.shape)
        dst_r = to_region(dst, extent_hint=hint)
    else:
        value = convert(value)
        dst_r = to_region(dst, extent_hint=(1,))
    b.emit(AtomicStmt(op, dst_r, value))


def atomic_add(dst, value, memory_order=None, scope=None):
    _emit("add", dst, value)


def atomic_max(dst, value, memory_order=None, scope=None):
    _emit("max", dst, value)


def atomic_min(dst, value, memory_order=None, scope=None):
    _emit("min", dst, value)


def atomic_addx2(dst, value):
    _emit("add", dst, value)


def atomic_addx4(dst, value):
    _emit("add", dst, value)
