"""Buffer allocation DSL: T.alloc_shared / alloc_fragment / alloc_local /
alloc_var / alloc_reducer.

Reference: /root/reference/tilelang/language/allocate.py:37-282. TPU mapping:
shared -> VMEM tile, fragment -> VMEM accumulator (Mosaic registers hot tiles
into vregs itself), var -> SMEM scalar. Barrier/tmem/descriptor allocs are
GPU-specific (mbarrier/TMA/tcgen05) and have no TPU analog — they raise with
guidance.
"""

from __future__ import annotations

from ..ir import Buffer
from .builder import require_builder


def alloc_shared(shape, dtype, scope: str = "shared") -> Buffer:
    b = require_builder()
    return b.alloc_buffer(shape, dtype, "shared", "shared")


def alloc_fragment(shape, dtype, scope: str = "fragment") -> Buffer:
    b = require_builder()
    return b.alloc_buffer(shape, dtype, "fragment", "frag")


def alloc_local(shape, dtype) -> Buffer:
    b = require_builder()
    return b.alloc_buffer(shape, dtype, "local", "local")


def alloc_var(dtype, init=None) -> Buffer:
    """A mutable scalar; lowers to an SMEM (1,1) cell."""
    b = require_builder()
    buf = b.alloc_buffer((1,), dtype, "local.var", "var")
    if init is not None:
        buf[0] = init
    return buf


def alloc_reducer(shape, dtype, op: str = "sum", replication=None) -> Buffer:
    """Reducer buffer (reference allocate.py alloc_reducer). On TPU a reducer
    is just a fragment accumulator; the finalize step is a no-op."""
    b = require_builder()
    buf = b.alloc_buffer(shape, dtype, "fragment", "reducer")
    buf.reducer_op = op
    return buf


def _gpu_only(what: str, hint: str):
    def f(*a, **k):
        raise NotImplementedError(
            f"T.{what} is a GPU-specific construct with no TPU analog; {hint}")
    return f


def alloc_semaphore(n: int = 1) -> Buffer:
    """An array of n DMA semaphores for split-phase T.copy_async /
    T.copy_wait (the TPU analog of the reference's T.alloc_barrier +
    warp-specialized producer/consumer, tilelang/language/allocate.py
    alloc_barrier)."""
    b = require_builder()
    return b.alloc_buffer((int(n),), "int32", "sem", "sem")


alloc_barrier = _gpu_only(
    "alloc_barrier", "mbarriers do not exist on TPU; allocate DMA "
    "semaphores with T.alloc_semaphore(n) and pair T.copy_async/"
    "T.copy_wait for producer/consumer overlap")
alloc_tmem = _gpu_only(
    "alloc_tmem", "tcgen05 tensor memory does not exist on TPU; accumulate in "
    "a T.alloc_fragment buffer")
alloc_descriptor = _gpu_only(
    "alloc_descriptor", "TMA descriptors do not exist on TPU; T.copy lowers "
    "to Mosaic DMA directly")
