"""`with T.If(cond):` — predicated statement blocks.

The reference rewrites native Python `if` via its AST pass; with a
trace-based builder the explicit frame is the equivalent. Lowers to
`@pl.when` (predicated execution on TPU).
"""

from __future__ import annotations

from ..ir import IfThenElse, convert
from .builder import require_builder


class _IfFrame:
    def __init__(self, cond):
        self.cond = convert(cond)

    def __enter__(self):
        b = require_builder()
        b.push_frame()
        return self

    def __exit__(self, exc_type, exc, tb):
        b = require_builder()
        body = b.pop_frame()
        if exc_type is None:
            b.emit(IfThenElse(self.cond, body))
        return False


class _ElseFrame:
    def __enter__(self):
        b = require_builder()
        stmts = b.frames[-1].stmts
        if not stmts or not isinstance(stmts[-1], IfThenElse) or \
                stmts[-1].else_body is not None:
            raise RuntimeError("T.Else() must directly follow a T.If block")
        self._if = stmts[-1]
        b.push_frame()
        return self

    def __exit__(self, exc_type, exc, tb):
        b = require_builder()
        body = b.pop_frame()
        if exc_type is None:
            self._if.else_body = body
        return False


def If(cond) -> _IfFrame:  # noqa: N802 - mirrors reference naming style
    return _IfFrame(cond)


def Else() -> _ElseFrame:  # noqa: N802
    return _ElseFrame()
