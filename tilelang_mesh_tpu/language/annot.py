"""Kernel-parameter annotations: T.Tensor, T.StridedTensor, T.MeshTensor,
T.dyn / T.dynamic / T.symbolic.

Reference surface: /root/reference/tilelang/language/v2/annot.py. Annotations
are plain objects evaluated at function-definition time; ``@T.prim_func`` asks
each one to materialize a parameter proxy via ``__tl_make_param__``.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

from ..ir import Buffer, Var, canon_dtype
from ..parallel.sharding import (MeshShardingPolicy, MeshReplicationType,
                                 MeshTensorMeta)


class _AnnotBase:
    def __tl_make_param__(self, name: str, builder):
        raise NotImplementedError


class TensorAnnot(_AnnotBase):
    """Annotation instance for one tensor parameter."""

    def __init__(self, shape, dtype="float32", scope: str = "global",
                 strides=None):
        if not isinstance(shape, (tuple, list)):
            shape = (shape,)
        self.shape = tuple(shape)
        self.dtype = canon_dtype(dtype)
        self.scope = scope
        self.strides = strides

    def __tl_make_param__(self, name: str, builder) -> Buffer:
        return Buffer(name, self.shape, self.dtype, self.scope)

    def get_key(self) -> tuple:
        return ("tensor", self.shape, self.dtype, self.scope)

    def __repr__(self):
        return f"Tensor({self.shape}, {self.dtype})"


class _TensorFactory:
    """``T.Tensor((M, K), dtype)`` and ``T.Tensor[...]`` both produce
    TensorAnnot instances (the subscript form serves lazy_jit signatures)."""

    def __call__(self, shape, dtype="float32", strides=None):
        return TensorAnnot(shape, dtype, strides=strides)

    def __getitem__(self, params):
        if not isinstance(params, tuple):
            params = (params,)
        if params and isinstance(params[-1], str):
            return TensorAnnot(params[:-1], params[-1])
        return TensorAnnot(params, "float32")


class _StridedTensorFactory(_TensorFactory):
    def __call__(self, shape, dtype="float32", strides=None):
        return TensorAnnot(shape, dtype, strides=strides)


class MeshTensorAnnot(_AnnotBase):
    """A distributed tensor parameter sharded over the 2-D core mesh.

    The traced kernel sees the *local shard* buffer (A.shape == sharded
    shape), exactly like the reference (annot.py:659-720); the global shape
    and policy ride along as mesh_meta so the SPMD lowering can build
    PartitionSpecs and validate collectives.
    """

    def __init__(self, shape, sharding_policy: MeshShardingPolicy,
                 device_mesh_config: Tuple[int, int], dtype="float32"):
        if not isinstance(shape, (tuple, list)):
            shape = (shape,)
        self.global_shape = tuple(shape)
        self.policy = sharding_policy
        self.mesh_config = tuple(device_mesh_config)
        self.dtype = canon_dtype(dtype)
        nrows, ncols = self.mesh_config
        self.sharded_shape = sharding_policy.sharded_shape(
            self.global_shape, nrows, ncols)

    def __tl_make_param__(self, name: str, builder) -> Buffer:
        buf = Buffer(name, self.sharded_shape, self.dtype, "global")
        buf.mesh_meta = MeshTensorMeta(self.global_shape, self.policy,
                                       self.mesh_config)
        builder.attrs.setdefault("mesh_config", self.mesh_config)
        return buf

    def get_key(self) -> tuple:
        return ("mesh_tensor", self.global_shape, repr(self.policy),
                self.mesh_config, self.dtype)


def MeshTensor(shape, sharding_policy, device_mesh_config, dtype="float32"):
    return MeshTensorAnnot(shape, sharding_policy, device_mesh_config, dtype)


class DynAnnot(_AnnotBase):
    """A dynamic (symbolic) scalar parameter — lazy_jit specializes on the
    concrete value per call site (cf. SURVEY §7 'dynamic shapes')."""

    def __init__(self, dtype="int32", name: Optional[str] = None):
        self.dtype = canon_dtype(dtype)
        self.name = name

    def __tl_make_param__(self, name: str, builder) -> Var:
        return Var(self.name or name, self.dtype)


class _DynFactory:
    def __call__(self, dtype="int32", name=None):
        return DynAnnot(dtype, name)

    def __getitem__(self, params):
        if isinstance(params, str):
            return DynAnnot("int32", params)
        return DynAnnot()


Tensor = _TensorFactory()
StridedTensor = _StridedTensorFactory()
dyn = _DynFactory()


def dynamic(name: str, dtype: str = "int32") -> Var:
    """``T.dynamic("m")`` — a symbolic dimension usable in shapes."""
    return Var(name, dtype)


symbolic = dynamic
