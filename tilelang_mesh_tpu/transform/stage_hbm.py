"""Auto-staging of HBM-resident ("any"-mode) param accesses through DMA.

The planner's block-affine matcher (transform/plan.py) drops a param to
HBM residency when its accesses cannot ride a BlockSpec (non-block-affine
offsets, serial-loop-dependent windows, conflicting patterns, or a
VMEM-budget demotion). Copies against such params already lower to
explicit ``rt.dma`` with dynamic ``.at[pl.ds(...)]`` windows — but compute
reads (``T.gemm`` operands), elementwise loads/stores inside
``T.Parallel`` nests, and scalar loads used to be codegen errors.

This pass rewrites those accesses to go through synthesized VMEM staging
buffers fed/flushed by DMA copies:

    T.gemm(A[f(k), 0], Bs, C)   ->   copy(A[f(k), 0] -> stage); gemm(stage, ...)
    s[i, j] = A[g(k) + i, j]    ->   copy(A[g(k), 0] -> stage); s[i, j] = stage[i, j]
    O[h(k) + i, j] = e          ->   stage[i, j] = e; copy(stage -> O[h(k), 0])

making "buffer stayed in HBM" reachable only for genuinely unlowerable
programs. It is the TPU analog of the reference's DMA-staging fallback in
layout inference (/root/reference/src/transform/layout_inference.cc:306-939
backtracks to shared-memory staging where a fragment layout cannot be
proven; here the fallback target is a VMEM window moved by explicit DMA).

Runs inside plan_kernel, after residency finalization and before scratch
packing, so staged buffers take part in liveness-packed VMEM accounting
and the extracted codegen-prep passes (mem2reg disqualifies DMA partners,
pad1 keeps their logical shape) see them like any other scratch.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..ir import (AssertStmt, AsyncCopyStmt, AtomicStmt, Buffer, BufferLoad,
                  BufferStoreStmt, CopyStmt, CumSumStmt, FillStmt, ForNest,
                  GemmStmt, IfThenElse, PrintStmt, Region, SeqStmt, Stmt,
                  as_int, convert, for_each_load)
from ..ir.expr import BinOp, Call, Cast, Var
from ..ir.printer import expr_str

# attribute names that denote a WRITTEN Region on a statement: plain dst
# plus the comm destinations (all_gather recv, all_reduce out). One
# constant shared by the hazard scan and the cache-invalidation walk so
# the two analyses cannot disagree.
_WRITE_REGION_ATTRS = ("dst", "recv", "out")


class _Stager:
    def __init__(self, any_uids: set):
        self.any_uids = any_uids
        self.new_allocs: List[Buffer] = []
        # uids whose staging is declined inside the current T.Parallel
        # nest: a read-after-store of the same any-param would see the
        # stale pre-nest copy (stores flush only post-nest), so those
        # buffers keep the loud codegen error instead
        self._declined: set = set()
        self._n = 0

    # -- staging-buffer factory ---------------------------------------------
    def _fresh(self, base_name: str, shape, dtype) -> Buffer:
        self._n += 1
        b = Buffer(f"stage_{base_name}_{self._n}", shape, dtype, "shared")
        self.new_allocs.append(b)
        return b

    # -- index decomposition -------------------------------------------------
    @staticmethod
    def _split_par(idx, par_ids: Dict[int, int]):
        """idx -> (par_var | None, remainder_expr). The par var must appear
        as a bare additive term (coefficient 1); otherwise None is returned
        for the whole decomposition (unstageable)."""
        terms: List[Tuple[int, object]] = []  # (sign, expr)

        def flat(e, sign):
            if isinstance(e, BinOp) and e.op == "+":
                flat(e.a, sign)
                flat(e.b, sign)
            elif isinstance(e, BinOp) and e.op == "-":
                flat(e.a, sign)
                flat(e.b, -sign)
            else:
                terms.append((sign, e))

        flat(convert(idx), 1)
        par_term = None
        rest: List[Tuple[int, object]] = []
        for sign, t in terms:
            if isinstance(t, Var) and id(t) in par_ids:
                if par_term is not None or sign != 1:
                    return None  # twice, or negated
                par_term = t
            else:
                # a par var buried in a non-trivial term (i*2, i//4, ...)
                if any(id(v) in par_ids for v in _free_vars(t)):
                    return None
                rest.append((sign, t))
        if not rest:
            rem = convert(0)
        else:
            rem = None
            for sign, t in rest:
                if rem is None:
                    rem = t if sign == 1 else BinOp("-", convert(0), t)
                else:
                    rem = BinOp("+" if sign == 1 else "-", rem, t)
        return par_term, rem

    # -- read staging --------------------------------------------------------
    def stage_region_read(self, region: Region, pre: List[Stmt],
                          cache: Dict[str, Buffer]) -> Optional[Region]:
        """Copy an HBM region into a fresh VMEM buffer; return the staged
        full-region replacement (or None if the shape is dynamic)."""
        shape = region.static_shape()
        if shape is None:
            return None
        key = (f"r{region.buffer.uid}:"
               f"{[expr_str(b) for b in region.base]}:{shape}")
        staged = cache.get(key)
        if staged is None:
            staged = self._fresh(region.buffer.name, shape,
                                 region.buffer.dtype)
            pre.append(CopyStmt(region,
                                Region(staged, (0,) * len(shape), shape)))
            cache[key] = staged
        return Region(staged, (0,) * len(shape), shape)

    def stage_load(self, load: BufferLoad, par_ids: Dict[int, int],
                   pre: List[Stmt], cache: Dict[str, Buffer]):
        """Rewrite an elementwise load of an any-param: DMA the par-window
        into a staged buffer, return the staged load (or None)."""
        buf = load.buffer
        dec = []
        for idx in load.indices:
            if isinstance(idx, slice):
                return None
            d = self._split_par(idx, par_ids)
            if d is None:
                return None
            dec.append(d)
        used = [id(pv) for pv, _ in dec if pv is not None]
        if len(used) != len(set(used)):
            return None  # same par var in two dims
        shape = tuple(par_ids[id(pv)] if pv is not None else 1
                      for pv, _ in dec)
        base = tuple(rem for _, rem in dec)
        key = (f"l{buf.uid}:{[expr_str(b) for b in base]}:{shape}")
        staged = cache.get(key)
        if staged is None:
            staged = self._fresh(buf.name, shape, buf.dtype)
            pre.append(CopyStmt(Region(buf, base, shape),
                                Region(staged, (0,) * len(shape), shape)))
            cache[key] = staged
        new_idx = tuple(pv if pv is not None else 0 for pv, _ in dec)
        return BufferLoad(staged, new_idx)

    # -- expression rewriting ------------------------------------------------
    def rewrite_expr(self, e, par_ids, pre, cache):
        """Replace loads of any-params inside an expression tree."""
        if isinstance(e, BufferLoad):
            idx = tuple(i if isinstance(i, slice)
                        else self.rewrite_expr(i, par_ids, pre, cache)
                        for i in e.indices)
            if self._is_any(e.buffer):
                staged = self.stage_load(BufferLoad(e.buffer, idx),
                                         par_ids, pre, cache)
                if staged is not None:
                    return staged
                return BufferLoad(e.buffer, idx)  # codegen reports clearly
            if any(x is not y for x, y in zip(idx, e.indices)):
                return BufferLoad(e.buffer, idx)
            return e
        if isinstance(e, BinOp):
            a = self.rewrite_expr(e.a, par_ids, pre, cache)
            b = self.rewrite_expr(e.b, par_ids, pre, cache)
            if a is not e.a or b is not e.b:
                return BinOp(e.op, a, b)
            return e
        if isinstance(e, Call):
            args = [a if isinstance(a, str)
                    else self.rewrite_expr(a, par_ids, pre, cache)
                    for a in e.args]
            if any(x is not y for x, y in zip(args, e.args)):
                return Call(e.name, args, e.dtype)
            return e
        if isinstance(e, Cast):
            v = self.rewrite_expr(e.value, par_ids, pre, cache)
            if v is not e.value:
                return Cast(e.dtype, v)
            return e
        return e

    def _region_base_rewrite(self, region: Region, par_ids, pre, cache):
        base = tuple(b if isinstance(b, slice)
                     else self.rewrite_expr(b, par_ids, pre, cache)
                     for b in region.base)
        if any(x is not y for x, y in zip(base, region.base)):
            return Region(region.buffer, base, region.shape)
        return region

    def _is_any(self, region_or_buf) -> bool:
        buf = getattr(region_or_buf, "buffer", region_or_buf)
        return (buf.scope == "global" and buf.uid in self.any_uids
                and buf.uid not in self._declined)

    # -- read-after-store hazard scan ---------------------------------------
    def _par_hazard_uids(self, stmts: List[Stmt],
                         par_ids: Dict[int, int]) -> set:
        """Any-param uids read AFTER being stored inside one T.Parallel
        body, where the read window may overlap a stored window. Staged
        reads are hoisted pre-nest and staged stores flush post-nest, so
        such a read would silently see the stale pre-nest window; staging
        is declined for those buffers.

        Window-granular: a read of a window provably DISJOINT from every
        prior store of the same buffer (affine bases differing by a
        constant >= the extent along some dim) is not a hazard, so
        store-block-k / read-block-k±1 nests keep staging."""
        from ..ir.expr import affine_decompose

        written: Dict[int, list] = {}   # uid -> [window | None(=unknown)]
        hazard: set = set()

        def raw_any(buf) -> bool:
            return buf.scope == "global" and buf.uid in self.any_uids

        def win_of_indices(indices):
            """Elementwise access -> per-dim (sym_terms, const, extent);
            a par var with coeff 1 spans its extent, other vars join the
            symbolic base. None = unknown window."""
            dims = []
            for idx in indices:
                if isinstance(idx, slice):
                    return None
                dec = affine_decompose(idx)
                if dec is None:
                    return None
                coeffs, const = dec
                ext = 1
                sym = []
                for _, (v, c) in coeffs.items():
                    if id(v) in par_ids:
                        if c != 1 or ext != 1:
                            return None
                        ext = par_ids[id(v)]
                    else:
                        sym.append((v.uid, c))
                dims.append((tuple(sorted(sym)), const, ext))
            return dims

        def win_of_region(r: Region):
            shape = r.static_shape()
            if shape is None:
                return None
            dims = []
            for b, s in zip(r.base, shape):
                if isinstance(b, slice):
                    return None
                dec = affine_decompose(b)
                if dec is None:
                    return None
                coeffs, const = dec
                sym = []
                for _, (v, c) in coeffs.items():
                    if id(v) in par_ids:
                        return None   # per-lane dynamic window
                    sym.append((v.uid, c))
                dims.append((tuple(sorted(sym)), const, s))
            return dims

        def disjoint(w1, w2) -> bool:
            if w1 is None or w2 is None or len(w1) != len(w2):
                return False
            for (s1, c1, e1), (s2, c2, e2) in zip(w1, w2):
                if s1 == s2 and (c1 + e1 <= c2 or c2 + e2 <= c1):
                    return True
            return False

        def read(uid, win):
            for sw in written.get(uid, ()):
                if not disjoint(win, sw):
                    hazard.add(uid)
                    return

        def write(uid, win):
            written.setdefault(uid, []).append(win)

        def expr_reads(e):
            def on_load(ld):
                if raw_any(ld.buffer):
                    read(ld.buffer.uid, win_of_indices(ld.indices))
            for_each_load(e, on_load)

        def reg_read(r):
            if not isinstance(r, Region):
                return
            for b in r.base:
                if not isinstance(b, slice):
                    expr_reads(b)
            if raw_any(r.buffer):
                read(r.buffer.uid, win_of_region(r))

        def reg_write(r):
            if not isinstance(r, Region):
                return
            for b in r.base:
                if not isinstance(b, slice):
                    expr_reads(b)
            if raw_any(r.buffer):
                write(r.buffer.uid, win_of_region(r))

        def scan(s):
            if isinstance(s, BufferStoreStmt):
                expr_reads(s.value)
                for i in s.indices:
                    if not isinstance(i, slice):
                        expr_reads(i)
                if raw_any(s.buffer):
                    write(s.buffer.uid, win_of_indices(s.indices))
            elif isinstance(s, FillStmt):
                expr_reads(s.value)
                reg_write(s.dst)
            elif isinstance(s, CopyStmt):
                reg_read(s.src)
                reg_write(s.dst)
            elif isinstance(s, AtomicStmt):
                if isinstance(s.value, Region):
                    reg_read(s.value)
                else:
                    expr_reads(s.value)
                reg_read(s.dst)   # rmw
                reg_write(s.dst)
            elif isinstance(s, GemmStmt):
                reg_read(s.A)
                reg_read(s.B)
                reg_read(s.C)     # accumulator rmw
                reg_write(s.C)
            elif isinstance(s, IfThenElse):
                expr_reads(s.cond)
                for b in (s.then_body, s.else_body):
                    if b is not None:
                        for c in b.stmts:
                            scan(c)
            elif isinstance(s, ForNest):
                for e in s.extents:
                    expr_reads(e)
                for c in s.body.stmts:
                    scan(c)
            elif isinstance(s, SeqStmt):
                for c in s.stmts:
                    scan(c)
            else:
                # unknown statement kinds: any Region attr whose name
                # suggests a destination is a write, the rest are reads;
                # expression attrs are reads
                for at, v in vars(s).items():
                    if isinstance(v, Region) and raw_any(v.buffer):
                        if at in _WRITE_REGION_ATTRS:
                            reg_write(v)
                        else:
                            reg_read(v)
                    elif at in ("value", "cond") and not isinstance(
                            v, (Region, Stmt, str, type(None))):
                        expr_reads(v)

        for s in stmts:
            scan(s)
        return hazard

    # -- statement rewriting -------------------------------------------------
    def _writes_any_param(self, s: Stmt) -> bool:
        """Does this statement (or a child) write an any-mode param? Such
        a write makes previously staged windows of it stale."""
        from ..ir import walk
        hit = [False]

        def chk(x):
            # 'dst' plus the comm destinations (all_gather recv,
            # all_reduce out) — any of them overwrites an any-param
            for at in _WRITE_REGION_ATTRS:
                r = getattr(x, at, None)
                if isinstance(r, Region) and self._is_any(r):
                    hit[0] = True
            if isinstance(x, BufferStoreStmt) and self._is_any(x.buffer):
                hit[0] = True
        walk(s, chk)
        return hit[0]

    def rewrite_stmts(self, stmts: List[Stmt],
                      par_ids: Dict[int, int]) -> List[Stmt]:
        out: List[Stmt] = []
        # one read-window dedup cache per statement LIST: adjacent
        # statements reading the same HBM window share one staged buffer
        # and one DMA; invalidated by any write to an any-mode param
        cache: Dict[str, Buffer] = {}
        for s in stmts:
            # decide BEFORE rewriting: the rewrite replaces any-param
            # writes with staged-buffer stores (flushes hoisted outside
            # s), which would hide the write from the scan
            invalidate = self._writes_any_param(s)
            out.extend(self.rewrite_stmt(s, par_ids, cache))
            if invalidate:
                cache.clear()
        return out

    def rewrite_stmt(self, s: Stmt, par_ids: Dict[int, int],
                     cache: Optional[Dict[str, Buffer]] = None) -> List[Stmt]:
        pre: List[Stmt] = []
        post: List[Stmt] = []
        if cache is None:
            cache = {}

        if isinstance(s, SeqStmt):
            s.stmts = self.rewrite_stmts(list(s.stmts), par_ids)
            return [s]
        if isinstance(s, IfThenElse):
            s.cond = self.rewrite_expr(s.cond, par_ids, pre, cache)
            s.then_body.stmts = self.rewrite_stmts(
                list(s.then_body.stmts), par_ids)
            if s.else_body is not None:
                s.else_body.stmts = self.rewrite_stmts(
                    list(s.else_body.stmts), par_ids)
            return pre + [s]
        if isinstance(s, ForNest):
            if s.kind in ("parallel", "vectorized"):
                # a nest with a non-static extent cannot be staged: its
                # loop vars would leak into hoisted window bases as
                # unbound remainders — decline (guarded mode stages
                # nothing and keeps the loud codegen errors)
                dyn = any(as_int(e) is None for e in s.extents)
                inner = dict(par_ids)
                if not dyn:
                    for v, e in zip(s.loop_vars, s.extents):
                        inner[id(v)] = as_int(e)
                body_pre, body_post = [], []
                declined = (set() if dyn else
                            self._par_hazard_uids(list(s.body.stmts),
                                                  inner))
                saved = self._declined
                self._declined = saved | declined
                try:
                    s.body.stmts = self._rewrite_par_body(
                        list(s.body.stmts), inner, body_pre, body_post,
                        guarded=dyn)
                finally:
                    self._declined = saved
                # window copies are loop-invariant w.r.t. the nest: hoist
                return body_pre + [s] + body_post
            s.body.stmts = self.rewrite_stmts(list(s.body.stmts), par_ids)
            return [s]
        if isinstance(s, GemmStmt):
            if self._is_any(s.A):
                r = self.stage_region_read(
                    self._region_base_rewrite(s.A, par_ids, pre, cache),
                    pre, cache)
                if r is not None:
                    s.A = r
            if self._is_any(s.B):
                r = self.stage_region_read(
                    self._region_base_rewrite(s.B, par_ids, pre, cache),
                    pre, cache)
                if r is not None:
                    s.B = r
            return pre + [s]
        if isinstance(s, CopyStmt):
            # DMA handles any-mode endpoints; only index expressions that
            # themselves load from any-params need staging
            s.src = self._region_base_rewrite(s.src, par_ids, pre, cache)
            s.dst = self._region_base_rewrite(s.dst, par_ids, pre, cache)
            return pre + [s]
        if isinstance(s, FillStmt):
            s.value = self.rewrite_expr(s.value, par_ids, pre, cache)
            if self._is_any(s.dst):
                shape = s.dst.static_shape()
                if shape is not None:
                    dst = self._region_base_rewrite(s.dst, par_ids, pre,
                                                    cache)
                    staged = self._fresh(dst.buffer.name, shape,
                                         dst.buffer.dtype)
                    full = Region(staged, (0,) * len(shape), shape)
                    post.append(CopyStmt(full, dst))
                    s.dst = full
            return pre + [s] + post
        if isinstance(s, AtomicStmt):
            # destination semantics are handled by the inout-block path /
            # codegen error; the VALUE region can still be staged
            if isinstance(s.value, Region) and self._is_any(s.value):
                r = self.stage_region_read(
                    self._region_base_rewrite(s.value, par_ids, pre, cache),
                    pre, cache)
                if r is not None:
                    s.value = r
            elif not isinstance(s.value, Region):
                s.value = self.rewrite_expr(s.value, par_ids, pre, cache)
            return pre + [s]
        if isinstance(s, BufferStoreStmt):
            s.value = self.rewrite_expr(s.value, par_ids, pre, cache)
            s.indices = tuple(
                i if isinstance(i, slice)
                else self.rewrite_expr(i, par_ids, pre, cache)
                for i in s.indices)
            # scalar store to an any-param (no par nest): stage the element
            if self._is_any(s.buffer) and not par_ids and \
                    not any(isinstance(i, slice) for i in s.indices):
                shape = tuple(1 for _ in s.indices)
                staged = self._fresh(s.buffer.name, shape, s.buffer.dtype)
                post.append(CopyStmt(
                    Region(staged, (0,) * len(shape), shape),
                    Region(s.buffer, s.indices, shape)))
                return pre + [BufferStoreStmt(
                    staged, (0,) * len(shape), s.value)] + post
            return pre + [s]
        if isinstance(s, (PrintStmt, AssertStmt, CumSumStmt,
                          AsyncCopyStmt)):
            return [s]
        return [s]

    def _rewrite_par_body(self, stmts: List[Stmt], par_ids: Dict[int, int],
                          nest_pre: List[Stmt], nest_post: List[Stmt],
                          guarded: bool = False) -> List[Stmt]:
        """Rewrite a T.Parallel body: loads become staged-window loads
        (copies hoisted before the nest); stores to any-params become
        staged-window stores flushed after the nest.

        ``guarded``: inside an IfThenElse the hoisted window copy could be
        out-of-bounds (loads) and the unconditional post-nest flush would
        clobber destination blocks whose guard was false (stores) — so no
        staging happens there; guarded HBM accesses keep the loud codegen
        error."""
        cache: Dict[str, Buffer] = {}
        store_cache: Dict[str, Buffer] = {}
        out: List[Stmt] = []
        for s in stmts:
            if isinstance(s, BufferStoreStmt):
                if not guarded:
                    s.value = self.rewrite_expr(s.value, par_ids, nest_pre,
                                                cache)
                    s.indices = tuple(
                        i if isinstance(i, slice)
                        else self.rewrite_expr(i, par_ids, nest_pre, cache)
                        for i in s.indices)
                    if self._is_any(s.buffer):
                        ns = self._stage_par_store(s, par_ids, nest_post,
                                                   store_cache)
                        if ns is not None:
                            out.append(ns)
                            continue
                out.append(s)
            elif isinstance(s, IfThenElse):
                if not guarded:
                    s.cond = self.rewrite_expr(s.cond, par_ids, nest_pre,
                                               cache)
                s.then_body.stmts = self._rewrite_par_body(
                    list(s.then_body.stmts), par_ids, nest_pre, nest_post,
                    guarded=True)
                if s.else_body is not None:
                    s.else_body.stmts = self._rewrite_par_body(
                        list(s.else_body.stmts), par_ids, nest_pre,
                        nest_post, guarded=True)
                out.append(s)
            elif guarded:
                out.append(s)
            else:
                out.extend(self.rewrite_stmt(s, par_ids))
        return out

    def _stage_par_store(self, s: BufferStoreStmt, par_ids: Dict[int, int],
                         nest_post: List[Stmt],
                         store_cache: Dict[str, Buffer]):
        dec = []
        for idx in s.indices:
            if isinstance(idx, slice):
                return None
            d = self._split_par(idx, par_ids)
            if d is None:
                return None
            dec.append(d)
        used = [id(pv) for pv, _ in dec if pv is not None]
        if len(used) != len(set(used)):
            return None
        shape = tuple(par_ids[id(pv)] if pv is not None else 1
                      for pv, _ in dec)
        base = tuple(rem for _, rem in dec)
        key = (f"s{s.buffer.uid}:{[expr_str(b) for b in base]}:{shape}")
        staged = store_cache.get(key)
        if staged is None:
            staged = self._fresh(s.buffer.name, shape, s.buffer.dtype)
            nest_post.append(CopyStmt(
                Region(staged, (0,) * len(shape), shape),
                Region(s.buffer, base, shape)))
            store_cache[key] = staged
        new_idx = tuple(pv if pv is not None else 0 for pv, _ in dec)
        return BufferStoreStmt(staged, new_idx, s.value)


def _free_vars(e):
    from ..ir import free_vars
    return free_vars(e)


def _copy_tree(s: Stmt) -> Stmt:
    """Shallow-copy every Stmt node of a statement tree (expressions,
    regions, and buffers stay shared — the rewriter replaces them, never
    mutates them). plan_kernel's phase lists alias the traced function's
    body, which must survive re-planning (lazy_jit re-elaborates, tests
    plan twice), so staging may only mutate plan-local copies."""
    import copy as _copy
    c = _copy.copy(s)
    if isinstance(c, SeqStmt):
        c.stmts = [_copy_tree(x) for x in c.stmts]
        return c
    for at in ("body", "then_body", "else_body"):
        sub = getattr(c, at, None)
        if isinstance(sub, SeqStmt):
            new = _copy.copy(sub)
            new.stmts = [_copy_tree(x) for x in sub.stmts]
            setattr(c, at, new)
    return c


def stage_hbm_accesses(params, init_stmts, main_stmts, epi_stmts):
    """Entry point: rewrite the three phase statement lists so every
    stageable access of an any-mode param goes through DMA-fed VMEM
    staging. The lists are updated in place with rewritten COPIES of the
    statement trees; returns the list of staging buffers created."""
    any_uids = {p.buffer.uid for p in params if p.mode == "any"}
    if not any_uids:
        return []
    st = _Stager(any_uids)
    init_stmts[:] = st.rewrite_stmts([_copy_tree(s) for s in init_stmts], {})
    main_stmts[:] = st.rewrite_stmts([_copy_tree(s) for s in main_stmts], {})
    epi_stmts[:] = st.rewrite_stmts([_copy_tree(s) for s in epi_stmts], {})
    return st.new_allocs
