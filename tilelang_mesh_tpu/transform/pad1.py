"""Column-layout decision for 1-D VMEM fragments.

A bare (M,) vector lives on the 128-wide lane axis, so broadcasting it over
the rows of a (M, N) tile costs a lane->sublane relayout on every use — the
dominant cost in online-softmax stats. Storing the fragment as a (M, 1)
column makes the row broadcast free; this is the codegen pipeline's analog
of the reference's Fragment layout inference
(/root/reference/src/layout/layout.cc).

Exclusions: buffers that take part in a DMA keep their logical shape, since
rt.dma windows both endpoints with .at[] and never applies the pad column —
that covers both HBM-resident partners of a sync T.copy and BOTH endpoints
of any split-phase AsyncCopyStmt, even VMEM-to-VMEM ones (round-2 advisor
finding).
"""

from __future__ import annotations

from ..ir import AsyncCopyStmt, CopyStmt, as_int, walk


def decide_pad1(plan) -> set:
    """Return the set of scratch-buffer uids to store as (M, 1) columns."""
    padded = set()
    for b in plan.scratch:
        if b.scope in ("local.var", "smem", "sem"):
            continue
        if len(b.shape) == 1 and as_int(b.shape[0]) is not None:
            padded.add(b.uid)
    if not padded:
        return padded
    any_bufs = {p.buffer.uid for p in plan.params if p.mode == "any"}

    def chk(s):
        if isinstance(s, AsyncCopyStmt):
            padded.discard(s.src.buffer.uid)
            padded.discard(s.dst.buffer.uid)
        elif isinstance(s, CopyStmt):
            su, du = s.src.buffer.uid, s.dst.buffer.uid
            if su in any_bufs:
                padded.discard(du)
            if du in any_bufs:
                padded.discard(su)
    for stmts in (plan.init_stmts, plan.main_stmts, plan.epi_stmts):
        for s in stmts:
            walk(s, chk)
    return padded
