"""Fragment SSA promotion (mem2reg) — this pipeline's analog of the
reference's StorageRewrite (/root/reference/src/transform/storage_rewrite.cc).

Decides which VMEM scratch fragments can live as Python locals (SSA values)
in the generated Pallas source instead of memref-backed scratch. A scratch
fragment qualifies when its whole life is: fully overwritten first, then
read/accumulated, all within ONE phase and one control-scope chain. Such a
buffer never needs VMEM backing — Mosaic then sees an SSA value chain
instead of memref round-trips between every statement (the difference is
~1.5x on attention-class kernels).

Loop-carried state (read-before-def in the pipelined main phase, or live
across init/main/epi) stays in scratch, as do buffers with partial stores,
DMA/atomic/semaphore uses, traced (runtime) indices, or conditional defs
that escape their scope.

Kept separate from the printer (codegen/pallas.py) the way the reference
keeps analysis passes out of codegen_cuda.cc.
"""

from __future__ import annotations

from typing import Dict

from ..ir import (AllocStmt, AssertStmt, AsyncCopyStmt, AtomicStmt, Buffer,
                  BufferStoreStmt, CommStmt, CopyStmt, CumSumStmt,
                  EvaluateStmt, FillStmt, ForNest, GemmStmt, IfThenElse,
                  PrintStmt, ReduceStmt, Region, SeqStmt, Var, as_int,
                  for_each_load, free_vars)


def plan_locals(plan) -> set:
    """Return the set of scratch-buffer uids that are safe to promote to
    SSA locals in the generated kernel source."""
    cand = {b.uid for b in plan.scratch
            if b.scope not in ("local.var", "smem", "sem")}
    if not cand:
        return set()
    # DMA partners (HBM-resident params) need .at refs
    any_bufs = {p.buffer.uid for p in plan.params if p.mode == "any"}
    recs: Dict[int, list] = {}   # uid -> [(kind, phase, scope, seq)]
    disq = set()
    seq = [0]
    # traced ints: lax.fori loop vars plus grid vars (pl.program_id) —
    # plain slicing of a Python value can't take a traced start index
    # (pl.ds is ref-only)
    traced_ids: set = {id(a.var) for a in plan.grid}

    def idx_traced(indices) -> bool:
        for i in indices:
            if isinstance(i, slice):
                continue
            if any(id(v) in traced_ids for v in free_vars(i)):
                return True
            # Loads from refs (e.g. an SMEM scalar sm[0]) are always
            # traced values even though they carry no free Vars —
            # a Python slice of a promoted local can't take them.
            loads = [0]
            for_each_load(i, lambda ld: loads.__setitem__(0, 1))
            if loads[0]:
                return True
        return False

    def rec(uid, kind, phase, scope):
        if uid in cand:
            recs.setdefault(uid, []).append((kind, phase, tuple(scope),
                                             seq[0]))
        seq[0] += 1

    def expr_uses(e, phase, scope):
        def on_load(ld):
            rec(ld.buffer.uid, "use", phase, scope)
            if idx_traced(ld.indices):
                disq.add(ld.buffer.uid)
        for_each_load(e, on_load)

    def region_rec(r: Region, kind, phase, scope):
        full = r.is_full() if hasattr(r, "is_full") else False
        if idx_traced(r.base):
            disq.add(r.buffer.uid)
        if kind in ("def", "rmw") and not full:
            disq.add(r.buffer.uid)
            rec(r.buffer.uid, "use", phase, scope)
        else:
            rec(r.buffer.uid, kind, phase, scope)
        for b in r.base:
            if not isinstance(b, slice):
                expr_uses(b, phase, scope)

    scope_n = [0]

    def child(scope):
        scope_n[0] += 1
        return scope + [scope_n[0]]

    def scan(s, phase, scope, par_nest):
        if isinstance(s, AllocStmt) or isinstance(s, EvaluateStmt):
            return
        if isinstance(s, SeqStmt):
            for c in s.stmts:
                scan(c, phase, scope, par_nest)
        elif isinstance(s, CopyStmt):
            if s.src.buffer.uid in any_bufs or \
                    s.dst.buffer.uid in any_bufs:
                # lowers to rt.dma, which needs .at[] on a real ref
                disq.add(s.src.buffer.uid)
                disq.add(s.dst.buffer.uid)
            region_rec(s.src, "use", phase, scope)
            region_rec(s.dst, "def", phase, scope)
        elif isinstance(s, AsyncCopyStmt):
            disq.add(s.src.buffer.uid)
            disq.add(s.dst.buffer.uid)
            disq.add(s.sem.uid)
        elif isinstance(s, GemmStmt):
            region_rec(s.A, "use", phase, scope)
            region_rec(s.B, "use", phase, scope)
            region_rec(s.C, "def" if s.clear_accum else "rmw",
                       phase, scope)
        elif isinstance(s, FillStmt):
            region_rec(s.dst, "def", phase, scope)
            expr_uses(s.value, phase, scope)
        elif isinstance(s, ReduceStmt):
            rec(s.src.uid, "use", phase, scope)
            rec(s.dst.uid, "def" if s.clear else "rmw", phase, scope)
        elif isinstance(s, CumSumStmt):
            rec(s.src.uid, "use", phase, scope)
            rec(s.dst.uid, "def", phase, scope)
        elif isinstance(s, AtomicStmt):
            disq.add(s.dst.buffer.uid)
            if isinstance(s.value, Region):
                region_rec(s.value, "use", phase, scope)
            else:
                expr_uses(s.value, phase, scope)
        elif isinstance(s, PrintStmt):
            if isinstance(s.obj, Buffer):
                rec(s.obj.uid, "use", phase, scope)
            else:
                expr_uses(s.obj, phase, scope)
        elif isinstance(s, AssertStmt):
            expr_uses(s.cond, phase, scope)
        elif isinstance(s, IfThenElse):
            expr_uses(s.cond, phase, scope)
            sc = child(scope)
            for c in s.then_body.stmts:
                scan(c, phase, sc, par_nest)
            if s.else_body is not None:
                sc2 = child(scope)
                for c in s.else_body.stmts:
                    scan(c, phase, sc2, par_nest)
        elif isinstance(s, ForNest):
            for e in s.extents:
                expr_uses(e, phase, scope)
            if s.kind in ("parallel", "vectorized"):
                nest = par_nest + list(zip(s.loop_vars,
                                           [as_int(e) for e in s.extents]))
                for c in s.body.stmts:
                    scan(c, phase, scope, nest)
            elif s.kind == "unroll" or (as_int(s.extents[0]) is not None
                                        and as_int(s.extents[0]) <= 4):
                for c in s.body.stmts:
                    scan(c, phase, scope, par_nest)
            else:  # lax.fori_loop body = its own function scope
                sc = child(scope)
                for v in s.loop_vars:
                    traced_ids.add(id(v))
                for c in s.body.stmts:
                    scan(c, phase, sc, par_nest)
        elif isinstance(s, BufferStoreStmt):
            expr_uses(s.value, phase, scope)
            for i in s.indices:
                if not isinstance(i, slice):
                    expr_uses(i, phase, scope)
            uid = s.buffer.uid
            if uid in cand:
                if idx_traced(s.indices):
                    disq.add(uid)
                # full def iff indices are exactly the par nest vars,
                # one per dim, covering each dim
                shape = [as_int(x) for x in s.buffer.shape]
                ext_of = {id(v): e for v, e in par_nest}
                full = len(s.indices) == len(shape) and \
                    None not in shape
                used = set()
                if full:
                    for idx, dim in zip(s.indices, shape):
                        if not (isinstance(idx, Var) and
                                id(idx) in ext_of and
                                ext_of[id(idx)] == dim and
                                id(idx) not in used):
                            full = False
                            break
                        used.add(id(idx))
                if full:
                    rec(uid, "def", phase, scope)
                else:
                    disq.add(uid)
                    rec(uid, "use", phase, scope)
        elif isinstance(s, CommStmt):
            # every Region-valued operand (src/dst, send/recv, buffer/out)
            # needs a real ref for comm lowering — never SSA-promote it
            for r in vars(s).values():
                if isinstance(r, Region):
                    disq.add(r.buffer.uid)

    for phase, stmts in (("init", plan.init_stmts),
                         ("main", plan.main_stmts),
                         ("epi", plan.epi_stmts)):
        for s in stmts:
            scan(s, phase, [0], [])

    out = set()
    for uid in cand:
        if uid in disq or uid in any_bufs:
            continue
        rs = recs.get(uid)
        if not rs:
            continue
        phases = {p for _, p, _, _ in rs}
        if len(phases) != 1:
            continue
        rs = sorted(rs, key=lambda r: r[3])
        if rs[0][0] != "def":
            continue
        # defs and rmws REBIND the Python name, so they must all sit in
        # one scope (a rebind inside a pl.when / fori body function
        # neither escapes nor sees the outer binding); plain reads may
        # be in any descendant scope (closure capture).
        bind_scopes = {sc for k, _, sc, _ in rs if k in ("def", "rmw")}
        if len(bind_scopes) != 1:
            continue
        s0 = next(iter(bind_scopes))
        if any(sc[:len(s0)] != s0 for _, _, sc, _ in rs):
            continue
        out.add(uid)
    return out
