"""Kernel planning: the TPU re-founding of LayoutInference + PipelinePlanning.

The reference infers per-buffer thread layouts and injects a software
pipeline (src/transform/layout_inference.cc, pipeline_planning.cc,
inject_pipeline.cc). On TPU both jobs collapse into one decision: **which
global-memory accesses can ride the Pallas grid/BlockSpec pipeline** (Mosaic
then auto-double-buffers HBM->VMEM exactly where the GPU build hand-rotates
smem versions), and which fall back to explicit in-kernel DMA.

The plan computed here drives codegen/pallas.py:
  - grid = reversed(T.Kernel vars) + the grid-mapped T.Pipelined var
  - every global buffer access that is block-affine in those axes becomes a
    BlockSpec (block shape + index map in block units)
  - on-chip buffers fed by exactly one such copy are aliased to the block ref
  - statements are split into init (first pipeline step), main, and epilogue
    (last step) phases
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..ir import (AllocStmt, AsyncCopyStmt, AtomicStmt, Buffer, BufferLoad,
                  BufferStoreStmt,
                  CommStmt, CopyStmt, CumSumStmt, FillStmt, ForNest, GemmStmt,
                  IfThenElse, KernelNode, PrimFunc, Region, ReduceStmt,
                  SeqStmt, Stmt, as_int, collect, linearize, free_vars)


class PlanError(Exception):
    pass


@dataclass
class BlockDim:
    """One dimension of a BlockSpec: size (None = squeezed unit dim),
    index-map terms in block units, and an optional post-division applied
    to the whole index expression (GQA-style `head // group` maps; only
    legal on squeezed unit dims).

    ``expr`` carries a non-linear block-index expression over grid vars
    (modular rasterization maps like ``(bx % W)`` or swizzles mixing
    ``//`` and ``%``) when the affine (terms, const) form cannot express
    the map; the reference's symbolic simplifier handles these in
    src/transform/simplify.cc. When set, terms/const are unused."""
    size: Optional[int]
    terms: Tuple[Tuple[int, int], ...]  # ((grid_axis, coeff_blocks), ...)
    const: int
    post_div: int = 1
    expr: Any = None                    # block-index expr over grid vars

    def key(self):
        from ..ir.printer import expr_str
        e = expr_str(self.expr) if self.expr is not None else None
        return (self.size, self.terms, self.const, self.post_div, e)

    def grid_axes_used(self, grid: "List[GridAxis]") -> set:
        """Grid axis indices this dim's index map depends on."""
        used = {a for a, _ in self.terms}
        if self.expr is not None:
            by_id = {id(a.var): i for i, a in enumerate(grid)}
            for v in free_vars(self.expr):
                if id(v) in by_id:
                    used.add(by_id[id(v)])
        return used


@dataclass
class ParamPlan:
    buffer: Buffer
    role: str = "in"          # in | out | inout
    mode: str = "block"       # block | any | smem
    block_dims: Optional[List[BlockDim]] = None
    alias: Optional[Buffer] = None   # on-chip buffer aliased to this block
    # set when the chosen residency only works in interpret mode (e.g.
    # unaligned lane windows Mosaic cannot express); codegen turns it
    # into a clear error on the real-TPU path
    tpu_note: Optional[str] = None
    # atomic destination: codegen seeds the out window from the aliased
    # input at each block's first visit (accumulate-into-existing)
    atomic: bool = False
    # grid axes (indices) across which this output's block is revisited —
    # filled by _demote_revisited_axes; codegen's seed predicate uses it
    revisit_axes: List[int] = field(default_factory=list)

    def block_key(self):
        return None if self.block_dims is None else tuple(
            d.key() for d in self.block_dims)


@dataclass
class GridAxis:
    var: Any
    extent: int
    kind: str  # parallel | arbitrary


@dataclass
class KernelPlan:
    func: PrimFunc
    grid: List[GridAxis]
    params: List[ParamPlan]                  # in func.buffer_params order
    scratch: List[Buffer]
    init_stmts: List[Stmt]
    main_stmts: List[Stmt]
    epi_stmts: List[Stmt]
    pipeline_axis: Optional[int]             # grid axis index of ko, or None
    aliased_copies: List[CopyStmt] = field(default_factory=list)
    annotations: Dict[str, Any] = field(default_factory=dict)
    # liveness-packed VMEM accounting (native tl_vmem_pack / python mirror):
    # arena bytes if disjoint-lifetime scratch shared storage, and the
    # per-buffer offsets — advisory (Mosaic owns real allocation), surfaced
    # through describe()/Analyzer for budget checks
    vmem_arena: int = 0
    vmem_offsets: Dict[int, int] = field(default_factory=dict)

    def param_for(self, buf: Buffer) -> Optional[ParamPlan]:
        for p in self.params:
            if p.buffer is buf:
                return p
        return None

    @property
    def inputs(self) -> List[ParamPlan]:
        return [p for p in self.params if p.role in ("in", "inout")]

    @property
    def outputs(self) -> List[ParamPlan]:
        return [p for p in self.params if p.role in ("out", "inout")]

    def describe(self) -> str:
        """Stable text form for golden tests (analog of pass-output
        mod.script() comparisons)."""
        lines = [f"plan({self.func.name}):"]
        g = ", ".join(f"{a.var.name}:{a.extent}:{a.kind}" for a in self.grid)
        lines.append(f"  grid = [{g}]")
        for p in self.params:
            if p.mode == "block":
                dims = []
                for d in p.block_dims:
                    if d.expr is not None:
                        from ..ir.printer import expr_str
                        t = expr_str(d.expr)
                    else:
                        t = " + ".join(
                            (f"{self.grid[a].var.name}" if c == 1
                             else f"{self.grid[a].var.name}*{c}")
                            for a, c in d.terms) or "0"
                        if d.const:
                            t += f" + {d.const}"
                        if d.post_div != 1:
                            t = f"({t})//{d.post_div}"
                    dims.append(f"{d.size}@({t})")
                desc = f"block[{', '.join(dims)}]"
                if p.alias is not None:
                    desc += f" alias={p.alias.name}"
            elif p.mode == "smem":
                desc = "smem(full)"
            else:
                desc = "any(hbm)"
            lines.append(f"  {p.role:5s} {p.buffer.name}: {desc}")
        for b in self.scratch:
            off = self.vmem_offsets.get(b.uid)
            at = f" @{off}" if off is not None else ""
            lines.append(f"  scratch {b.name}: {tuple(b.shape)} {b.dtype} "
                         f"[{b.scope}]{at}")
        if self.vmem_arena:
            lines.append(f"  vmem arena: {self.vmem_arena} bytes "
                         "(liveness-packed)")
        lines.append(f"  phases: init={len(self.init_stmts)} "
                     f"main={len(self.main_stmts)} epi={len(self.epi_stmts)}")
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------


def _div_exact(e, k: int):
    """Structurally divide expression e by integer k (e == result * k),
    or None. Handles +, -, *, and % (since (k*a) % (k*b) == k*(a % b) for
    non-negative operands — grid indices are). The reach this gives the
    planner over pure ``linearize`` is exactly modular index maps:
    ``(bx % W) * bs`` or swizzled ``((bx // g) * g + ...) * bs`` bases."""
    from ..ir.expr import IntImm, Var as _Var, _binop
    if k == 1:
        return e
    e = convert_expr(e)
    if isinstance(e, IntImm):
        return IntImm(e.value // k) if e.value % k == 0 else None
    if isinstance(e, _Var):
        return None
    from ..ir.expr import BinOp
    if isinstance(e, BinOp):
        if e.op in ("+", "-"):
            a, b = _div_exact(e.a, k), _div_exact(e.b, k)
            if a is None or b is None:
                return None
            return _binop(e.op, a, b)
        if e.op == "*":
            for num, other in ((e.a, e.b), (e.b, e.a)):
                iv = as_int(num)
                if iv is not None and iv % k == 0:
                    q = iv // k
                    return other if q == 1 else _binop("*", other, q)
            a = _div_exact(e.a, k)
            if a is not None:
                return _binop("*", a, e.b)
            b = _div_exact(e.b, k)
            if b is not None:
                return _binop("*", e.a, b)
            return None
        if e.op == "%":
            a, b = _div_exact(e.a, k), _div_exact(e.b, k)
            if a is None or b is None:
                return None
            return _binop("%", a, b)
    return None


def convert_expr(e):
    from ..ir.expr import convert
    return convert(e)


def _grid_only_expr(e, axes: List[GridAxis]) -> bool:
    """True when e references only grid vars (no loads, no other vars)."""
    from ..ir import for_each_load
    grid_ids = {id(a.var) for a in axes}
    if any(id(v) not in grid_ids for v in free_vars(e)):
        return False
    n = [0]
    for_each_load(convert_expr(e), lambda ld: n.__setitem__(0, 1))
    return not n[0]


def _region_block_dims(region: Region, axes: List[GridAxis],
                       squeeze_to_rank: Optional[int]) -> Optional[List[BlockDim]]:
    """Try to express a region as a BlockSpec over the grid axes."""
    shape = region.static_shape()
    if shape is None:
        return None
    axis_vars = [a.var for a in axes]
    var_to_axis = {id(a.var): i for i, a in enumerate(axes)}
    dims: List[BlockDim] = []
    rank = len(region.base)
    n_squeeze = rank - (squeeze_to_rank or rank)
    for d, (base, size) in enumerate(zip(region.base, shape)):
        post_div = 1
        lin = linearize(base, axis_vars)
        if lin is None:
            # GQA-style `expr // const` on a unit dim that will be squeezed
            from ..ir.expr import BinOp, IntImm
            if (isinstance(base, BinOp) and base.op == "//"
                    and isinstance(base.b, IntImm) and size == 1
                    and d < n_squeeze):
                lin = linearize(base.a, axis_vars)
                post_div = base.b.value
            if lin is None and size > 0 and _grid_only_expr(base, axes):
                # modular / swizzled map: base = f(grid) * size with f
                # non-affine (e.g. (bx % W) * bs) — carry f as the dim's
                # block-index expression
                f = _div_exact(base, size)
                if f is not None:
                    blk = size
                    if d < n_squeeze and size == 1:
                        blk = None
                    dims.append(BlockDim(blk, (), 0, 1, expr=f))
                    continue
            if lin is None:
                return None
        coeffs, const = lin
        if size <= 0:
            return None
        # every coefficient and the constant must be whole blocks
        terms = []
        ok = True
        for v, c in coeffs.items():
            if c % size != 0:
                ok = False
                break
            terms.append((var_to_axis[id(v)], c // size))
        if not ok or const % size != 0:
            return None
        terms.sort()
        blk = size
        if d < n_squeeze and size == 1:
            blk = None  # squeeze leading unit dims to match on-chip rank
        if post_div != 1 and blk is not None:
            return None  # divided maps only legal on squeezed unit dims
        dims.append(BlockDim(blk, tuple(terms), const // size, post_div))
    return dims


def _merge_param(plans: Dict[int, ParamPlan], buf: Buffer, role: str,
                 dims: Optional[List[BlockDim]], alias: Optional[Buffer]):
    p = plans[buf.uid]
    # role lattice: _unused -> in/out; in + out -> inout
    if p.role == "_unused":
        p.role = role
    elif p.role != role:
        p.role = "inout"
    if p.mode == "any":
        return
    if dims is None:
        p.mode = "any"
        p.block_dims = None
        p.alias = None
        return
    key = tuple(d.key() for d in dims)
    if p.block_dims is None:
        p.block_dims = dims
        p.alias = alias
    elif p.block_key() != key:
        # conflicting access patterns -> keep whole array in HBM
        p.mode = "any"
        p.block_dims = None
        p.alias = None
    elif p.alias is None and alias is not None:
        p.alias = alias


_SMEM_PARAM_LIMIT = 16 * 1024  # bytes of SMEM a single param may claim


def _min_tile_illegal(p: ParamPlan) -> bool:
    """Would this block mapping violate Mosaic's (8, 128) trailing-dims
    rule (squeezed unit dims count as extent 1)?"""
    shape = [as_int(s) for s in p.buffer.shape]
    if not shape or any(s is None for s in shape):
        return False
    nd = len(shape)
    for pos, min_mult in ((1, 128), (2, 8)):
        if nd < pos:
            continue
        bd = p.block_dims[nd - pos]
        blk = bd.size if bd.size is not None else 1
        if blk != shape[nd - pos] and blk % min_mult:
            return True
    return False


def _region_used_bufs(stmts: List[Stmt]) -> set:
    """uids of global buffers accessed as regions (copies/gemms/...) —
    as opposed to pure scalar element loads."""
    used = set()

    def chk(s):
        for attr in ("src", "dst", "A", "B", "C"):
            r = getattr(s, attr, None)
            if isinstance(r, Region) and r.buffer.scope == "global":
                used.add(r.buffer.uid)
    from ..ir import walk
    for s in stmts:
        walk(s, chk)
    return used


def _smem_promote(p: ParamPlan, region_used: set) -> bool:
    """Small read-only params whose every access is a scalar element load
    (sparsity masks, stream-K partition tables, varlen row maps) live
    whole in SMEM: Mosaic reads scalars from SMEM with arbitrary dynamic
    indices, where a (1,1,..) VMEM block would break the min-tile rule.
    The analog of the reference's scalar kernel arguments / jax flash's
    scalar-prefetch segment ids."""
    buf = p.buffer
    if p.role != "in":
        return False
    if buf.uid in region_used:
        return False
    if p.mode == "block":
        if p.block_dims is None or not _min_tile_illegal(p):
            return False  # a legal block mapping beats SMEM residency
    elif p.mode != "any":
        return False
    # mode "any" + no region use means every access is a scalar element
    # load (e.g. under a serial loop) — HBM cannot serve those at all
    shape = [as_int(s) for s in buf.shape]
    if any(s is None for s in shape):
        return False
    from ..ir.expr import dtype_bits
    nbytes = max(1, dtype_bits(buf.dtype) // 8)
    for s in shape:
        nbytes *= s
    if nbytes > _SMEM_PARAM_LIMIT:
        return False
    p.mode = "smem"
    p.block_dims = None
    p.alias = None
    return True


def _widen_min_tile(p: ParamPlan) -> None:
    """Mosaic requires a block's last-two dims (squeezed unit dims count
    as extent 1) to be divisible by (8, 128) respectively or equal to the
    full array extent. Widen a violating trailing dim to the whole axis:
    its index-map component becomes 0 and every in-kernel access keeps
    its original (possibly grid-var) index, which the accessor emits as a
    dynamic start. For outputs this relies on the widened axis being
    swept by grid vars, whose kinds are demoted to "arbitrary" by
    _demote_revisited_axes so Mosaic keeps the block resident across the
    revisit sequence. (The reference solves the analogous problem by
    backtracking over layouts in layout_inference.cc:928-939; on TPU the
    legal-layout set is the Mosaic tiling rule, so widening is exact.)"""
    shape = [as_int(s) for s in p.buffer.shape]
    if not shape or any(s is None for s in shape):
        return
    nd = len(shape)
    changed = False
    for pos, min_mult in ((1, 128), (2, 8)):  # (minor, second-minor)
        if nd < pos:
            continue
        i = nd - pos
        bd = p.block_dims[i]
        blk = bd.size if bd.size is not None else 1
        if blk == shape[i] or blk % min_mult == 0:
            continue
        if pos == 1 and (bd.terms or bd.expr is not None
                         or (bd.const * blk) % 128):
            # Widening the lane (minor) dim would keep the original index
            # as a dynamic/unaligned start, and Mosaic only accepts lane
            # offsets it can prove are multiples of 128 (DMA windows
            # included). Keep the block mapping — interpret mode executes
            # it — and give the real-TPU path a clear error instead of a
            # Mosaic crash. (Small scalar-read params get SMEM residency
            # before this check and never reach here.)
            p.tpu_note = (
                f"param '{p.buffer.name}': a {blk}-wide block on the "
                f"minor (lane) axis of shape {tuple(shape)} is not "
                f"Mosaic-legal (lane offsets must be 128-aligned); use a "
                f"minor block size that is a multiple of 128 or covers "
                f"the whole axis")
            return
        p.block_dims[i] = BlockDim(shape[i], (), 0, 1)
        changed = True
    if changed:
        # a widened block no longer matches the on-chip copy partner:
        # keep the explicit copy instead of BlockSpec aliasing
        p.alias = None


def _eval_expr(e, env: Dict[int, int]) -> Optional[int]:
    """Evaluate an integer IR expression under a var assignment."""
    from ..ir.expr import BinOp, BoolImm, Cast, IntImm
    from ..ir.expr import Var as _Var
    e = convert_expr(e)
    if isinstance(e, IntImm):
        return e.value
    if isinstance(e, BoolImm):
        return int(e.value)
    if isinstance(e, _Var):
        return env.get(id(e))
    if isinstance(e, Cast):
        return _eval_expr(e.value, env)
    if isinstance(e, BinOp):
        a, b = _eval_expr(e.a, env), _eval_expr(e.b, env)
        if a is None or b is None:
            return None
        try:
            return {"+": lambda: a + b, "-": lambda: a - b,
                    "*": lambda: a * b, "//": lambda: a // b,
                    "%": lambda: a % b,
                    "min": lambda: min(a, b),
                    "max": lambda: max(a, b)}[e.op]()
        except (KeyError, ZeroDivisionError):
            return None
    return None


_REVISIT_ENUM_CAP = 1 << 16


def _expr_map_revisit_check(grid: List[GridAxis], p: ParamPlan) -> None:
    """Output-revisit legality for non-affine (expr) index maps, where the
    per-axis omitted-suffix analysis does not apply: a map like
    ``(bx % 2)`` uses the axis but NON-INJECTIVELY, revisiting block 0 at
    bx = 0 and bx = 2 — non-consecutive steps, which Pallas handles by
    flushing and refetching an unwritten output block (silent corruption
    on real TPUs). Enumerate the grid (row-major, last axis fastest — the
    Pallas iteration order) and require every distinct block tuple's
    visits to be one contiguous run; demote every contributing axis to
    'arbitrary' when revisits exist at all."""
    extents = [a.extent for a in grid]
    total = 1
    for e in extents:
        total *= e
    if total > _REVISIT_ENUM_CAP:
        p.tpu_note = (
            f"output '{p.buffer.name}': a non-affine block index map over "
            f"a grid of {total} steps cannot be verified for consecutive "
            f"revisits; use an affine index map or a smaller grid")
        return
    env_vars = [a.var for a in grid]
    slot_of = {id(v): i for i, v in enumerate(env_vars)}
    import itertools
    points = list(itertools.product(*[range(e) for e in extents]))

    # per-dim block-index value arrays over the whole grid; expr dims go
    # through the native expression engine (tl_expr_eval_grid, python
    # mirror as fallback) — the hot loop of this check
    dim_vals: List[List[int]] = []
    for d in p.block_dims:
        if d.expr is not None:
            from ..ir.expr import encode_expr
            from ..layout import native as lnat
            from ..layout import python_impl as lpy
            enc = encode_expr(d.expr, slot_of)
            vals = None
            if enc is not None:
                vals = lnat.expr_eval_grid(enc[0], enc[1], enc[2], extents)
                if vals is None:
                    vals = lpy.expr_eval_grid(enc[0], enc[1], enc[2],
                                              extents)
            if vals is None:  # unencodable: per-point interpreter
                vals = []
                for point in points:
                    env = {id(v): x for v, x in zip(env_vars, point)}
                    ev = _eval_expr(d.expr, env)
                    if ev is None:
                        p.tpu_note = (
                            f"output '{p.buffer.name}': its block index "
                            f"map could not be evaluated for revisit "
                            f"legality")
                        return
                    vals.append(ev)
        else:
            vals = [sum(pt[a] * c for a, c in d.terms) + d.const
                    for pt in points]
            if d.post_div != 1:
                vals = [v // d.post_div for v in vals]
        dim_vals.append(vals)

    keys: Dict[tuple, tuple] = {}   # grid point -> block tuple
    seen: Dict[tuple, int] = {}     # block tuple -> last step seen
    bad = False
    for step, point in enumerate(points):
        key = tuple(dv[step] for dv in dim_vals)
        keys[point] = key
        if key in seen:
            if seen[key] != step - 1:
                bad = True
        seen[key] = step
    # an axis revisits the output if stepping it ALONE can leave the
    # block unchanged (covers both omission and non-injective maps) ...
    revisit = set()
    for point, key in keys.items():
        for i in range(len(extents)):
            if i in revisit or point[i] == 0:
                continue
            prev = point[:i] + (point[i] - 1,) + point[i + 1:]
            if keys[prev] == key:
                revisit.add(i)
    # ... and a CONSECUTIVE-step revisit that changes several axes at once
    # (e.g. (bx + by) % 4 revisiting across a row boundary) must demote
    # every axis that steps between the two visits, or Mosaic's parallel
    # dimension semantics could reorder the two writes apart
    prev_point, prev_key = None, None
    for point, key in keys.items():   # insertion order == grid order
        if prev_key is not None and key == prev_key:
            for i in range(len(extents)):
                if point[i] != prev_point[i]:
                    revisit.add(i)
        prev_point, prev_key = point, key
    if revisit:
        p.revisit_axes = sorted(revisit | set(p.revisit_axes))
        for i in p.revisit_axes:
            if grid[i].kind == "parallel":
                grid[i].kind = "arbitrary"
    if bad:
        p.tpu_note = (
            f"output '{p.buffer.name}': its non-affine block index map "
            f"revisits a block on non-consecutive grid steps; Pallas "
            f"requires output revisits to be consecutive — restructure "
            f"the index map (e.g. make the modular axis innermost)")


def _demote_revisited_axes(grid: List[GridAxis],
                           params: List[ParamPlan]) -> None:
    """Any grid axis absent from some block-mode output's index map
    revisits that output's block across its steps; Mosaic only keeps the
    block resident (and flushes once) for non-parallel dims, so demote
    those axes to "arbitrary".

    Pallas additionally requires output revisits to be CONSECUTIVE grid
    steps: the omitted axes must form the innermost suffix of the grid,
    or the block is flushed and refetched from an unwritten buffer
    between revisits — silently wrong results on real TPUs (interpret
    mode masks it). Kernels that violate this get a tpu_note so the
    real-TPU path fails loudly with reordering guidance."""
    for p in params:
        if p.role not in ("out", "inout") or p.mode != "block" \
                or p.block_dims is None:
            continue
        if any(d.expr is not None for d in p.block_dims):
            # non-affine maps need the enumeration-based check: the
            # suffix analysis below assumes axis-in-terms == injective
            _expr_map_revisit_check(grid, p)
            continue
        used = set()
        for d in p.block_dims:
            used |= d.grid_axes_used(grid)
        omitted = [i for i, ax in enumerate(grid)
                   if i not in used and ax.extent > 1]
        p.revisit_axes = omitted
        for i in omitted:
            if grid[i].kind == "parallel":
                grid[i].kind = "arbitrary"
        # consecutive == the omitted axes are the innermost suffix of the
        # axes that actually step (extent-1 axes contribute one step and
        # can sit anywhere)
        stepping = [i for i, ax in enumerate(grid) if ax.extent > 1]
        if omitted and omitted != stepping[len(stepping) - len(omitted):]:
            names = ", ".join(grid[i].var.name for i in omitted)
            p.tpu_note = (
                f"output '{p.buffer.name}': its block is revisited "
                f"across non-innermost grid axes ({names}); Pallas "
                f"requires output revisits to be consecutive grid steps "
                f"— reorder T.Kernel axes so the axes absent from this "
                f"output's index come first (innermost)")


_DEFAULT_VMEM_BUDGET = 15 * 2 ** 20  # ~0.9 of the 16 MiB per-core VMEM


def _copy_only_uids(stmts: List[Stmt], params: List["ParamPlan"]) -> set:
    """Global params whose every access is a CopyStmt/AsyncCopyStmt region
    endpoint — the ones that can be demoted to HBM residency with a plain
    DMA lowering (no staging rewrite needed)."""
    from ..ir import for_each_load, walk
    bad = set()

    def expr_bad(e):
        def on(ld):
            if ld.buffer.scope == "global":
                bad.add(ld.buffer.uid)
        for_each_load(e, on)

    def chk(x):
        if isinstance(x, (CopyStmt, AsyncCopyStmt)):
            for r in (x.src, x.dst):
                for b in r.base:
                    if not isinstance(b, slice):
                        expr_bad(b)
            return
        if isinstance(x, GemmStmt):
            for r in (x.A, x.B, x.C):
                if r.buffer.scope == "global":
                    bad.add(r.buffer.uid)
            return
        if isinstance(x, FillStmt):
            if x.dst.buffer.scope == "global":
                bad.add(x.dst.buffer.uid)
            expr_bad(x.value)
            return
        if isinstance(x, AtomicStmt):
            bad.add(x.dst.buffer.uid)
            if isinstance(x.value, Region):
                if x.value.buffer.scope == "global":
                    bad.add(x.value.buffer.uid)
            else:
                expr_bad(x.value)
            return
        if isinstance(x, BufferStoreStmt):
            if x.buffer.scope == "global":
                bad.add(x.buffer.uid)
            expr_bad(x.value)
            for i in x.indices:
                if not isinstance(i, slice):
                    expr_bad(i)
            return
        if isinstance(x, IfThenElse):
            expr_bad(x.cond)
            return
        if isinstance(x, ForNest):
            for e in x.extents:
                expr_bad(e)
            return
        if isinstance(x, CommStmt):
            # comm lowering is planned against the param's residency;
            # never demote a collective operand behind its back. Walk every
            # Region-valued attribute (src/dst, all_gather's send/recv,
            # all_reduce's buffer/out, and any future variant).
            for r in vars(x).values():
                if isinstance(r, Region) and r.buffer.scope == "global":
                    bad.add(r.buffer.uid)
            return
        for at in ("cond", "obj", "value"):
            v = getattr(x, at, None)
            if v is not None and not isinstance(v, (Region, Buffer, Stmt,
                                                    str)):
                expr_bad(v)

    for s in stmts:
        walk(s, chk)
    return {p.buffer.uid for p in params} - bad


def _block_param_bytes(p: "ParamPlan", grid: List["GridAxis"]) -> int:
    """Padded VMEM footprint of one BlockSpec window, doubled when the
    block streams across a stepping grid axis (Mosaic double-buffers the
    pipeline)."""
    from ..ir import dtype_bits
    from ..layout import native as lnat
    from ..layout import python_impl as lpy
    sizes = [d.size for d in p.block_dims if d.size is not None] or [1]
    rows = 1
    for s in sizes[:-1]:
        rows *= s
    cols = sizes[-1]
    bits = dtype_bits(p.buffer.dtype)
    b = lnat.vmem_bytes(rows, cols, bits)
    if b is None:
        b = lpy.vmem_bytes(rows, cols, bits)
    used = set()
    for d in p.block_dims:
        used |= d.grid_axes_used(grid)
    streamed = any(grid[a].extent > 1 for a in used)
    return b * (2 if streamed else 1)


def _vmem_backoff(grid: List["GridAxis"], params: List["ParamPlan"],
                  allocs: List[Buffer], stmts: List[Stmt],
                  pass_cfg: dict) -> None:
    """Backtrack over residency choices when the planned VMEM footprint
    (BlockSpec windows + scratch) exceeds the budget: demote the largest
    copy-only block params to HBM residency (their copies become explicit
    DMA) until the plan fits. The TPU realization of the reference's
    layout-inference backtracking (layout_inference.cc:928-939), where the
    search is over fragment layouts; here the only degree of freedom is
    which windows ride the BlockSpec pipeline."""
    budget = pass_cfg.get("tl.tpu.vmem_budget_bytes") \
        or pass_cfg.get("tl.tpu.vmem_limit_bytes") \
        or _DEFAULT_VMEM_BUDGET
    budget = int(budget)

    def estimate() -> int:
        aliased = {p.alias.uid for p in params if p.alias is not None}
        scratch = [b for b in allocs if b.uid not in aliased]
        arena, _ = _pack_scratch(scratch, stmts)
        blocks = sum(_block_param_bytes(p, grid) for p in params
                     if p.mode == "block" and p.block_dims)
        return arena + blocks

    if estimate() <= budget:
        return
    copy_only = _copy_only_uids(stmts, params)
    while estimate() > budget:
        cands = [p for p in params
                 if p.mode == "block" and p.block_dims and not p.atomic
                 and p.buffer.uid in copy_only]
        if not cands:
            return  # nothing safely demotable; Mosaic reports the overflow
        victim = max(cands, key=lambda p: _block_param_bytes(p, grid))
        victim.mode = "any"
        victim.block_dims = None
        victim.alias = None
        victim.tpu_note = None


def _writers(stmts_root: Stmt) -> Dict[int, int]:
    """buffer uid -> number of statements that write it."""
    counts: Dict[int, int] = {}

    def bump(buf):
        counts[buf.uid] = counts.get(buf.uid, 0) + 1

    def visit(s):
        if isinstance(s, (CopyStmt, AsyncCopyStmt)):
            bump(s.dst.buffer)
        elif isinstance(s, (FillStmt,)):
            bump(s.dst.buffer)
        elif isinstance(s, GemmStmt):
            bump(s.C.buffer)
        elif isinstance(s, BufferStoreStmt):
            bump(s.buffer)
        elif isinstance(s, ReduceStmt):
            bump(s.dst)
        elif isinstance(s, CumSumStmt):
            bump(s.dst)
        elif isinstance(s, AtomicStmt):
            bump(s.dst.buffer)

    from ..ir import walk
    walk(stmts_root, visit)
    return counts


def plan_kernel(func: PrimFunc, pass_cfg: Optional[dict] = None) -> KernelPlan:
    kn = func.kernel_node()
    if kn is None:
        raise PlanError(
            f"{func.name}: kernel has no T.Kernel frame; every tile kernel "
            "must open `with T.Kernel(...)`")
    pass_cfg = pass_cfg or {}

    # ---- grid ------------------------------------------------------------
    grid: List[GridAxis] = [
        GridAxis(v, e, "parallel")
        for v, e in zip(reversed(kn.grid_vars), reversed(kn.extents))
    ]

    top = list(kn.body.stmts)
    pipelined = [s for s in top
                 if isinstance(s, ForNest) and s.kind == "pipelined"]
    mapped_loop: Optional[ForNest] = None
    if len(pipelined) == 1 and not any(
            isinstance(s, CommStmt) for s in top):
        lp = pipelined[0]
        ext = as_int(lp.extents[0])
        if ext is not None and len(lp.loop_vars) == 1 \
                and lp.num_stages != 1:
            # num_stages semantics on TPU: grid-mapping hands the loop to
            # Mosaic's pipeline (double-buffered streams — the hardware's
            # fixed depth; >=2 means "let Mosaic pipeline"). An EXPLICIT
            # num_stages=1 opts out: the loop stays in-kernel (serial
            # fori + DMA staging), single-buffering the streams to halve
            # their VMEM footprint. Cf. reference inject_pipeline.cc,
            # where num_stages sizes the smem version ring.
            mapped_loop = lp
    pipeline_axis = None
    if mapped_loop is not None:
        grid.append(GridAxis(mapped_loop.loop_vars[0],
                             as_int(mapped_loop.extents[0]), "arbitrary"))
        pipeline_axis = len(grid) - 1

    # ---- phase split ------------------------------------------------------
    if mapped_loop is not None:
        idx = top.index(mapped_loop)
        init_stmts = [s for s in top[:idx] if not isinstance(s, AllocStmt)]
        main_stmts = list(mapped_loop.body.stmts)
        epi_stmts = [s for s in top[idx + 1:] if not isinstance(s, AllocStmt)]
    else:
        init_stmts, epi_stmts = [], []
        main_stmts = [s for s in top if not isinstance(s, AllocStmt)]

    # ---- buffer classification -------------------------------------------
    allocs = [s.buffer for s in collect(func.body,
                                        lambda s: isinstance(s, AllocStmt))]
    global_params = [b for b in func.buffer_params]
    plans: Dict[int, ParamPlan] = {
        b.uid: ParamPlan(b, role="_unused", mode="block", block_dims=None)
        for b in global_params
    }
    writer_counts = _writers(func.body)

    aliased_copies: List[CopyStmt] = []
    vector_elem_bufs: set = set()   # globals loaded with Parallel-var indices

    def loop_ctx_axes(extra_vars) -> List[GridAxis]:
        # axes visible to an access: the grid plus (for elementwise accesses)
        # the enclosing parallel loop vars, appended as pseudo-axes
        return grid + list(extra_vars)

    def consider_copy(stmt: CopyStmt, in_mapped_loop: bool,
                      serial_vars: list):
        src, dst = stmt.src, stmt.dst
        _visit_region_base(src, serial_vars, [])
        _visit_region_base(dst, serial_vars, [])
        sg = src.buffer.scope == "global"
        dg = dst.buffer.scope == "global"
        if sg and not dg:
            if serial_vars:
                _merge_param(plans, src.buffer, "in", None, None)
                return
            dims = _region_block_dims(src, grid, dst.buffer.ndim)
            alias = None
            if dims is not None and writer_counts.get(dst.buffer.uid, 0) == 1 \
                    and dst.is_full():
                alias = dst.buffer
                aliased_copies.append(stmt)
            _merge_param(plans, src.buffer, "in", dims, alias)
        elif dg and not sg:
            if serial_vars:
                _merge_param(plans, dst.buffer, "out", None, None)
                return
            dims = _region_block_dims(dst, grid, src.buffer.ndim)
            _merge_param(plans, dst.buffer, "out", dims, None)
        elif sg and dg:
            _merge_param(plans, src.buffer, "in", None, None)
            _merge_param(plans, dst.buffer, "out", None, None)

    def _visit_region_base(region: Region, serial_vars, par_vars):
        # global loads used as indices (e.g. SMEM-promoted lookup tables
        # in a gather-style copy base) are elementwise reads too
        for b in region.base:
            if not isinstance(b, slice):
                visit_expr_globals(b, serial_vars, par_vars)

    def consider_region_read(region: Region, serial_vars: list,
                             par_vars: list = ()):
        _visit_region_base(region, serial_vars, list(par_vars))
        if region.buffer.scope == "global":
            if serial_vars:
                _merge_param(plans, region.buffer, "in", None, None)
            else:
                dims = _region_block_dims(region, grid, None)
                _merge_param(plans, region.buffer, "in", dims, None)

    def consider_region_write(region: Region, serial_vars: list,
                              par_vars: list = ()):
        _visit_region_base(region, serial_vars, list(par_vars))
        if region.buffer.scope == "global":
            if serial_vars:
                _merge_param(plans, region.buffer, "out", None, None)
            else:
                dims = _region_block_dims(region, grid, None)
                _merge_param(plans, region.buffer, "out", dims, None)

    def visit_expr_globals(e, serial_vars: list, par_vars: list):
        # global BufferLoads inside expressions (elementwise access)
        def go(x):
            if isinstance(x, BufferLoad):
                if x.buffer.scope == "global":
                    _elementwise_access(x, "in", serial_vars, par_vars)
                for i in x.indices:
                    if not isinstance(i, slice):
                        go(i)
            else:
                from ..ir.expr import BinOp, Call, Cast
                if isinstance(x, BinOp):
                    go(x.a)
                    go(x.b)
                elif isinstance(x, Call):
                    for a in x.args:
                        if not isinstance(a, str):
                            go(a)
                elif isinstance(x, Cast):
                    go(x.value)
        go(e)

    def _elementwise_access(load_or_store, role: str, serial_vars: list,
                            par_vars: list):
        buf = load_or_store.buffer
        indices = load_or_store.indices
        # a load whose index depends on a Parallel var vectorizes onto VPU
        # lanes — SMEM residency can only serve SCALAR reads, so remember
        # these for the _smem_promote veto (staging serves them instead)
        par_ids_ = {id(v) for v, _ in par_vars}
        for idx in indices:
            if not isinstance(idx, slice) and \
                    any(id(v) in par_ids_ for v in free_vars(idx)):
                vector_elem_bufs.add(buf.uid)
                break
        if serial_vars:
            _merge_param(plans, buf, role, None, None)
            return
        # index = grid-affine * block + parallel-loop var; block size from
        # the loop extent of that var
        par_var_ext = {id(v): e for v, e in par_vars}
        dims: List[BlockDim] = []
        axis_vars = [a.var for a in grid]
        var_to_axis = {id(a.var): i for i, a in enumerate(grid)}
        for idx in indices:
            if isinstance(idx, slice):
                return _merge_param(plans, buf, role, None, None)
            lin = linearize(idx, axis_vars + [v for v, _ in par_vars])
            if lin is None:
                return _merge_param(plans, buf, role, None, None)
            coeffs, const = lin
            pvars = [(v, c) for v, c in coeffs.items() if id(v) in par_var_ext]
            gvars = [(v, c) for v, c in coeffs.items()
                     if id(v) in var_to_axis]
            if len(pvars) > 1:
                return _merge_param(plans, buf, role, None, None)
            if pvars:
                v, c = pvars[0]
                if c != 1:
                    return _merge_param(plans, buf, role, None, None)
                size = par_var_ext[id(v)]
            else:
                size = 1
            terms, ok = [], True
            for v, c in gvars:
                if c % size != 0:
                    ok = False
                    break
                terms.append((var_to_axis[id(v)], c // size))
            if not ok or const % size != 0:
                return _merge_param(plans, buf, role, None, None)
            terms.sort()
            dims.append(BlockDim(size, tuple(terms), const // size))
        _merge_param(plans, buf, role, dims, None)

    def visit(stmts: Sequence[Stmt], serial_vars: list, par_vars: list):
        for s in stmts:
            if isinstance(s, AllocStmt):
                continue
            if isinstance(s, CopyStmt):
                consider_copy(s, False, serial_vars)
            elif isinstance(s, AsyncCopyStmt):
                # split-phase DMA is explicit by design: never BlockSpec-map
                # or alias its global operands
                if s.src.buffer.scope == "global":
                    _merge_param(plans, s.src.buffer, "in", None, None)
                if s.dst.buffer.scope == "global":
                    _merge_param(plans, s.dst.buffer, "out", None, None)
            elif isinstance(s, GemmStmt):
                consider_region_read(s.A, serial_vars)
                consider_region_read(s.B, serial_vars)
                if s.C.buffer.scope == "global":
                    raise PlanError("T.gemm accumulator must be an on-chip "
                                    "fragment buffer")
            elif isinstance(s, FillStmt):
                consider_region_write(s.dst, serial_vars)
            elif isinstance(s, AtomicStmt):
                if s.dst.buffer.scope == "global":
                    # a global atomic destination is an accumulate into
                    # the tensor's EXISTING contents (reference
                    # src/op/atomic_add.cc semantics): map it as an inout
                    # block so the original data is fetched via aliasing
                    # and the out window seeded at each block's first
                    # visit (codegen _emit_atomic_seeds)
                    _visit_region_base(s.dst, serial_vars, list(par_vars))
                    if serial_vars:
                        _merge_param(plans, s.dst.buffer, "inout", None,
                                     None)
                    elif par_vars:
                        _elementwise_access(
                            BufferLoad(s.dst.buffer, tuple(s.dst.base)),
                            "inout", serial_vars, par_vars)
                    else:
                        vr = s.value.buffer.ndim \
                            if isinstance(s.value, Region) else None
                        dims = _region_block_dims(s.dst, grid, vr)
                        _merge_param(plans, s.dst.buffer, "inout", dims,
                                     None)
                    plans[s.dst.buffer.uid].atomic = True
                if isinstance(s.value, Region):
                    consider_region_read(s.value, serial_vars)
                else:
                    visit_expr_globals(s.value, serial_vars, par_vars)
            elif isinstance(s, BufferStoreStmt):
                if s.buffer.scope == "global":
                    _elementwise_access(s, "out", serial_vars, par_vars)
                visit_expr_globals(s.value, serial_vars, par_vars)
            elif isinstance(s, ReduceStmt):
                if s.src.scope == "global" or s.dst.scope == "global":
                    raise PlanError("T.reduce_* operates on on-chip buffers")
            elif isinstance(s, ForNest):
                if s.kind == "parallel":
                    visit(s.body.stmts, serial_vars,
                          par_vars + list(zip(s.loop_vars,
                                              [as_int(e) for e in s.extents])))
                else:
                    visit(s.body.stmts,
                          serial_vars + list(s.loop_vars), par_vars)
            elif isinstance(s, IfThenElse):
                visit_expr_globals(s.cond, serial_vars, par_vars)
                visit(s.then_body.stmts, serial_vars, par_vars)
                if s.else_body:
                    visit(s.else_body.stmts, serial_vars, par_vars)
            elif isinstance(s, SeqStmt):
                visit(s.stmts, serial_vars, par_vars)

    visit(init_stmts, [], [])
    visit(main_stmts, [], [])
    visit(epi_stmts, [], [])

    # ---- finalize ---------------------------------------------------------
    region_used_bufs = _region_used_bufs(init_stmts + main_stmts + epi_stmts)
    # SMEM can only serve scalar reads: vector-loaded params must not be
    # promoted (DMA staging serves them)
    region_used_bufs |= vector_elem_bufs
    params: List[ParamPlan] = []
    for b in global_params:
        p = plans[b.uid]
        if p.role == "_unused":
            # keep unused params as pass-through inputs (ANY, zero-cost)
            p.role = "in"
            p.mode = "any"
            p.block_dims = None
            params.append(p)
            continue
        if p.mode == "block" and p.block_dims is None:
            p.mode = "any"
        if p.mode in ("block", "any"):
            if not _smem_promote(p, region_used_bufs) \
                    and p.mode == "block":
                _widen_min_tile(p)
        params.append(p)

    # auto-stage unservable HBM accesses through DMA windows FIRST, so the
    # budget backoff's estimate sees the staging buffers it adds; backoff
    # then only demotes copy-only params, which need no staging of their own
    from .stage_hbm import stage_hbm_accesses
    allocs = allocs + stage_hbm_accesses(params, init_stmts, main_stmts,
                                         epi_stmts)
    _vmem_backoff(grid, params, allocs,
                  init_stmts + main_stmts + epi_stmts, pass_cfg)
    _demote_revisited_axes(grid, params)

    aliased_bufs = {p.alias.uid for p in params if p.alias is not None}
    # keep aliased_copies consistent with the params' final alias state:
    # widening/SMEM promotion may have cleared an alias after its copy
    # was recorded, and that copy must now really execute
    aliased_copies = [c for c in aliased_copies
                      if c.dst.buffer.uid in aliased_bufs]
    scratch = [b for b in allocs if b.uid not in aliased_bufs]

    vmem_arena, vmem_offsets = _pack_scratch(
        scratch, init_stmts + main_stmts + epi_stmts,
        main_range=((len(init_stmts), len(init_stmts) + len(main_stmts))
                    if pipeline_axis is not None else None))

    return KernelPlan(
        func=func, grid=grid, params=params, scratch=scratch,
        init_stmts=init_stmts, main_stmts=main_stmts, epi_stmts=epi_stmts,
        pipeline_axis=pipeline_axis,
        aliased_copies=aliased_copies,
        annotations=dict(func.attrs.get("kernel_annotations", {})),
        vmem_arena=vmem_arena, vmem_offsets=vmem_offsets,
    )


def _pack_scratch(scratch: List[Buffer], stmts: List[Stmt],
                  main_range=None):
    """Statement-granular liveness + best-fit packing of scratch VMEM
    (native allocator src/tltpu_core.cc tl_vmem_pack; the reference does
    this in storage_rewrite.cc / merge_shared_memory_allocations.cc).

    main_range=(lo, hi) marks the half-open statement range of a pipelined
    main phase: those statements re-execute once per grid step along the
    pipeline axis, so any buffer referenced there is loop-carried — its
    live interval is widened to the whole phase (a value written in one
    iteration may be read in the next, which statement-granular intervals
    cannot see; round-1 advisor finding)."""
    from ..ir import walk
    from ..layout import native as lnat
    from ..layout import python_impl as lpy

    uids: Dict[int, int] = {}
    for b in scratch:
        # contiguous slot indices: enumerate positions would leave holes
        # (and walk off the first/last arrays) when a semaphore sits
        # mid-list — e.g. the tile-opt dbuf rewrite allocates its
        # rotated semaphore right after the slotted stream buffer
        if b.scope != "sem" and b.uid not in uids:
            uids[b.uid] = len(uids)
    if not uids:
        return 0, {}
    n = len(uids)
    first = [None] * n
    last = [0] * n

    def see(buf, t):
        i = uids.get(getattr(buf, "uid", None))
        if i is None:
            return
        if first[i] is None:
            first[i] = t
        last[i] = t

    for t, top in enumerate(stmts):
        def visit(s, t=t):
            for attr in ("src", "dst", "A", "B", "C", "value", "sem"):
                r = getattr(s, attr, None)
                if isinstance(r, Region):
                    see(r.buffer, t)
                elif isinstance(r, Buffer):
                    see(r, t)
                elif isinstance(r, BufferLoad):
                    see(r.buffer, t)
            if isinstance(s, BufferStoreStmt):
                see(s.buffer, t)
            for e in getattr(s, "exprs", []) or []:
                if isinstance(e, BufferLoad):
                    see(e.buffer, t)

        walk(top, visit)
        # expressions inside loads nested in values
        def deep(e, t=t):
            if isinstance(e, BufferLoad):
                see(e.buffer, t)
                for i in e.indices:
                    if not isinstance(i, slice):
                        deep(i)
            else:
                from ..ir.expr import BinOp, Call, Cast
                if isinstance(e, BinOp):
                    deep(e.a)
                    deep(e.b)
                elif isinstance(e, Call):
                    for x in e.args:
                        if not isinstance(x, str):
                            deep(x)
                elif isinstance(e, Cast):
                    deep(e.value)

        def vals(s, t=t):
            v = getattr(s, "value", None)
            if v is not None and not isinstance(v, (Region, Buffer)):
                deep(v)
        walk(top, vals)

    if main_range is not None:
        lo, hi = main_range
        for i in range(n):
            if first[i] is not None and first[i] < hi and last[i] >= lo:
                first[i] = min(first[i], lo)
                last[i] = max(last[i], hi - 1)

    sizes, fu, lu, idx_of = [], [], [], []
    rev = {i: uid for uid, i in uids.items()}
    for i in range(n):
        b = next(bb for bb in scratch if bb.uid == rev[i])
        shape = [as_int(x) for x in b.shape]
        if any(x is None for x in shape):
            return 0, {}
        from ..ir import dtype_bits
        bits = dtype_bits(b.dtype)
        # true (sublane, lane)-padded footprint: the tiling applies to the
        # trailing 2-D slice; leading dims multiply (the same rule
        # tests/test_native.py asserts for Fragment.vmem_bytes)
        rows = shape[-2] if len(shape) >= 2 else 1
        cols = shape[-1] if shape else 1
        tile = lnat.vmem_bytes(rows, cols, bits)
        if tile is None:
            tile = lpy.vmem_bytes(rows, cols, bits)
        lead = 1
        for x in shape[:-2]:
            lead *= x
        sz = tile * lead
        sizes.append(sz)
        fu.append(first[i] if first[i] is not None else 0)
        lu.append(max(last[i], fu[-1]))
        idx_of.append(rev[i])
    packed = lnat.vmem_pack(sizes, fu, lu)
    if packed is None:
        packed = lpy.vmem_pack(sizes, fu, lu)
    if packed is None:
        return 0, {}
    arena, offsets = packed
    return arena, {idx_of[i]: offsets[i] for i in range(n)}


# ---------------------------------------------------------------------------
# compile-time cost features (autotuner/cost_model.py; docs/autotuning.md)
# ---------------------------------------------------------------------------

#: bump when the feature dict's keys or semantics change — the cost
#: model refuses to mix samples across feature schemas, and stale
#: journal/tune-cache features are skipped instead of misfit
#: (v2: + vmem_occupancy — the post-tile-opt resident footprint, so the
#: model prices the OPTIMIZED kernel: narrowing/repack shrink it)
FEATURES_VERSION = 2


def plan_features(func: PrimFunc, plan: KernelPlan) -> dict:
    """Arch-independent cost features of one planned kernel, derived
    entirely from the traced IR and this plan — nothing executes.

    These are the raw quantities the autotuner's analytic cost model
    (autotuner/cost_model.py) combines with a ``carver/arch.py`` machine
    model at predict time: total MXU FLOPs and global<->VMEM traffic
    with loop/grid multiplicity (the roofline numerators), the
    liveness-packed scratch arena plus resident BlockSpec windows (the
    TL005 interval model's footprint, post tile-opt repack since the
    plan is built AFTER the rewrites), grid step count, and block shape
    descriptors. ``engine/lower.py`` attaches the dict to
    ``CompiledArtifact.attrs["features"]`` (adding the tile-opt dbuf
    chain count), so features ride the crash-safe artifact cache and are
    available without re-planning.
    """
    from ..ir import dtype_bits
    grid_steps = 1
    for a in plan.grid:
        grid_steps *= max(1, a.extent)
    flops = [0]
    copy_bytes = [0]
    vpu = [0]
    kn = func.kernel_node()
    # walk multiplicity starts from the KERNEL grid (T.Kernel vars), not
    # plan.grid: a pipelined loop that plan promoted onto the dispatch
    # grid still appears as a ForNest in the body and multiplies there —
    # basing the walk on plan.grid would double-count it
    kn_mult = 1
    if kn is not None:
        for e in kn.extents:
            kn_mult *= max(1, int(e))

    def visit(s, mult):
        if isinstance(s, ForNest):
            exts = [as_int(e) or 1 for e in s.extents]
            prod = 1
            for e in exts:
                prod *= e
            if s.kind == "parallel":
                vpu[0] += prod * mult
            else:
                mult *= prod
            for c in s.body.stmts:
                visit(c, mult)
        elif isinstance(s, SeqStmt):
            for c in s.stmts:
                visit(c, mult)
        elif isinstance(s, KernelNode):
            for c in s.body.stmts:
                visit(c, mult)
        elif isinstance(s, IfThenElse):
            for c in s.then_body.stmts:
                visit(c, mult)
            if s.else_body is not None:
                for c in s.else_body.stmts:
                    visit(c, mult)
        elif isinstance(s, GemmStmt):
            a_sh = s.A.static_shape()
            c_sh = s.C.static_shape()
            if a_sh and c_sh:
                k = a_sh[0] if s.trans_A else a_sh[-1]
                flops[0] += 2 * c_sh[-2] * c_sh[-1] * k * mult
        elif isinstance(s, (CopyStmt, AsyncCopyStmt)):
            src, dst = s.src, s.dst
            if isinstance(src, Region) and isinstance(dst, Region) and \
                    (src.buffer.scope == "global"
                     or dst.buffer.scope == "global"):
                n = src.numel() or dst.numel() or 0
                copy_bytes[0] += n * dtype_bits(src.dtype) // 8 * mult
        elif isinstance(s, (ReduceStmt, CumSumStmt)):
            r = getattr(s, "src", None)
            if isinstance(r, Region):
                vpu[0] += (r.numel() or 0) * mult

    if kn is not None:
        for s in kn.body.stmts:
            visit(s, kn_mult)

    # BlockSpec streaming: each block-mode param's window is fetched
    # (or written back) once per grid step; smem-promoted params stage
    # fully once. The max() with the explicit-copy count covers both
    # idioms — elementwise kernels move data through BlockSpecs with no
    # CopyStmt, staged GEMMs through copies the params alias.
    block_resident = 0
    stream_bytes = 0
    best_block: Tuple[int, Tuple[int, ...]] = (0, ())
    for p in plan.params:
        if p.mode == "block" and p.block_dims:
            b = _block_param_bytes(p, plan.grid)
            block_resident += b
            stream_bytes += b * grid_steps
            sizes = tuple(d.size for d in p.block_dims
                          if d.size is not None)
            if b > best_block[0]:
                best_block = (b, sizes)
        elif p.mode == "smem":
            shape = p.buffer.static_shape()
            if shape:
                n = 1
                for d in shape:
                    n *= d
                stream_bytes += n * dtype_bits(p.buffer.dtype) // 8
    hbm_bytes = max(copy_bytes[0], stream_bytes)

    # resident occupancy: per-buffer scratch bytes (Mosaic allocates each
    # scratch buffer separately — the liveness-packed arena is the
    # *if-shared* lower bound, not the allocation) + BlockSpec windows,
    # as a fraction of the TL005 budget. The plan is built AFTER tile-opt
    # ran, so a narrowed or repacked kernel genuinely shrinks this — the
    # PR 11/12 remainder: the cost model prices the optimized kernel.
    scratch_bytes = 0
    for b in plan.scratch:
        sh = b.static_shape()
        if sh:
            n = max(1, dtype_bits(b.dtype) // 8)
            for d in sh:
                n *= d
            scratch_bytes += n

    sizes = best_block[1] or (1,)
    rows = 1
    for d in sizes[:-1]:
        rows *= d
    cols = sizes[-1]
    skew = max(rows, cols) / max(1, min(rows, cols))
    return {
        "version": FEATURES_VERSION,
        "flops": int(flops[0]),
        "hbm_bytes": int(hbm_bytes),
        "vpu_elems": int(vpu[0]),
        "grid_steps": int(grid_steps),
        "vmem_arena": int(plan.vmem_arena),
        "vmem_block_bytes": int(block_resident),
        "vmem_occupancy": round(
            (scratch_bytes + block_resident) / _DEFAULT_VMEM_BUDGET, 6),
        "n_scratch": len(plan.scratch),
        "n_params": len(plan.params),
        "pipelined": 1 if plan.pipeline_axis is not None else 0,
        "block_rows": int(rows),
        "block_cols": int(cols),
        "block_skew": float(round(skew, 4)),
        "dbuf_chains": 0,          # engine/lower.py fills from tile-opt
    }
