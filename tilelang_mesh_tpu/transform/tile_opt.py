"""Proof-carrying tile-IR optimization passes (tile-opt).

PR 10 built a dataflow/affine proof engine (``analysis/dataflow.py``,
``analysis/regions.py``) whose proofs only produced diagnostics.  This
pass suite promotes those proofs into rewrites: it runs in
``engine/lower.py`` between the semantic checks and planning, reusing
the lint analysis VERBATIM as its legality oracle — every rewrite fires
only on what the affine model can *prove*, exactly the
proof-carrying-tile-rewrite discipline of the xDSL custom-lowering and
CUDA-Tile evaluation work (PAPERS.md).  Five rewrites, individually
selectable through ``TL_TPU_TILE_OPT`` (docs/tile_opt.md):

``dse``
    Dead-store elimination.  The TL006 proof (a scratch buffer with
    writes but no reaching read, or an alloc with no use at all) turns
    from an info diagnostic into a deletion: the stores, the alloc, and
    — to fixpoint — anything that only fed the deleted stores are
    removed, shrinking both the VMEM arena and the executed store
    count.  The auto-fixed TL006 findings are consumed (they surface in
    the ``tile_opt[...]`` accounting instead of the lint block).

``narrow``
    Value-range-driven dtype narrowing — the TL007/TL008 dual-track
    interpretation (``analysis/numerics.py``) run in the INVERSE
    direction: instead of checking that a value fits its declared
    dtype, the pass finds scratch buffers whose proven sound interval
    AND accumulated rounding-error bound fit a *thinner* dtype
    (f32 -> bf16 scratch, i32 -> i16 index/position buffers) and
    rewrites the alloc plus every touch.  Loads present the original
    dtype through an exact widening cast (compute precision is
    unchanged; only the storage rounds), so the interpreter's
    store-side error model prices the rewrite exactly.  The proof is
    triple-checked: after the cheap envelope pre-gate, a cancellation
    screen refuses any candidate whose storage rounding (an ABSOLUTE
    error the relative TL008 model cannot see through a subtraction of
    nearly-equal values) could blow the error budget, and the
    candidate narrowed kernel is re-interpreted end to end with any
    candidate implicated in a new finding (or a lost output-finiteness
    proof) dropped.  Each narrowing is golden-recorded with its proof
    (interval, error bound, bytes) and guarded by the
    ``TL_TPU_SELFCHECK`` differential first-call check.  Opt-in: not
    part of the default set (``TL_TPU_TILE_OPT=narrow,...`` or
    ``=auto`` or ``=all``), so default plan_descs stay byte-stable.

``repack``
    VMEM arena re-packing.  The TL005 interval model already proves
    which scratch lifetimes never overlap; this rewrite *realizes* that
    packing at the IR level by aliasing same-shape/same-dtype buffers
    with provably disjoint top-level live ranges onto one shared arena
    slot — Mosaic then allocates one buffer where it allocated N, so
    bigger tiles fit the real VMEM budget (the advisory
    liveness-packed arena becomes the physical footprint).  The slot
    gate also admits byte-compatible slots: a buffer whose dtype
    widens EXACTLY into a same-shape slot's dtype (bf16 into f32)
    shares it through a cast view — loads re-narrow, stored values
    round eagerly — which is bit-exact, and is how a buffer thinned by
    ``narrow`` becomes newly packable (the passes compose).

``dbuf``
    Proof-gated automatic double-buffering.  A synchronous ``T.copy``
    HBM->VMEM feeding compute inside a serial loop — the pattern the
    planner must lower as a blocking per-iteration DMA — gets the
    second slot and the rotated semaphore automatically: the rewrite
    re-shapes the destination to ``(2,) + shape``, prefetches iteration
    ko+1 into slot ``(ko+1) % 2`` while compute consumes slot
    ``ko % 2``, and is gated by the same region-overlap machinery TL002
    uses (the stream buffer single-writer / loop-local, the source
    never written in the loop), so the in-flight window provably never
    collides with compute.

``fuse``
    Affine loop fusion.  Adjacent ``T.Parallel`` nests with identical
    iteration spaces merge into one elementwise region when the TL001
    affine collision machinery proves no cross-region dependency:
    every shared written buffer is accessed with per-dimension affine
    forms that are IDENTICAL across the two nests (iteration i only
    talks to iteration i) and injective over the extent>1 vars.  One
    region means one vectorized sweep — shared loads (the dequant
    ``Bp_s[i, j]`` nibble source) are read once instead of twice.
    Fusion is also INTERLEAVED: a nest separated from its partner by
    unrelated statements still fuses when every intervening statement
    provably touches a disjoint buffer set (the TL001 access
    enumeration as the overlap oracle), hoisting the nest across them.

``TL_TPU_TILE_OPT=auto`` replaces the fixed canonical order with a
cost-model pass scheduler (``engine/lower.py``): the legal rewrite
subsets for the kernel are enumerated, each candidate is priced via
``autotuner/cost_model.analytic_terms`` on re-derived
``plan_features``, and the min-predicted-latency set (VMEM footprint
as the tie-break) is chosen; the decision — candidates, predicted ms,
chosen set, predicted-gap-closed — is recorded in
``attrs["tile_opt"]["sched"]`` and the SoL record.

Every decision is deterministic (program order, no dict-order
dependence; two lowerings are byte-identical), golden-recorded in a
``tile_opt[...]`` plan_desc block (nothing is emitted when no rewrite
fires, so existing goldens stay byte-stable), accounted in
``attrs["tile_opt"]`` + ``opt.*`` counters +
``metrics_summary()["tile_opt"]``, guarded by the PR 5 differential
selfcheck (``TL_TPU_SELFCHECK=1`` compares the optimized kernel's first
call against the ``TL_TPU_TILE_OPT=0`` lowering), and part of the
kernel-cache key.  ``TL_TPU_TILE_OPT=0`` restores the pre-pass
plan_desc byte-identically.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..ir import (AllocStmt, AssertStmt, AsyncCopyStmt, AtomicStmt, Buffer,
                  BufferLoad, BufferStoreStmt, CommStmt, CopyStmt, CumSumStmt,
                  EvaluateStmt, FillStmt, ForNest, GemmStmt, IfThenElse,
                  KernelNode, PrimFunc, PrintStmt, Region, ReduceStmt,
                  SeqStmt, Stmt, as_int, dtype_bits)
from ..ir.expr import BinOp, Call, Cast, Var, convert

# rewrites in canonical order (execution, plan_desc and attrs all use
# it; the composition tests assert dse -> narrow -> repack -> dbuf ->
# fuse is the one deterministic pipeline — narrow runs before repack so
# a thinned buffer can land in a widening-compatible slot)
MODES = ("dse", "narrow", "repack", "dbuf", "fuse")

#: the default-on subset ("1"/"on"/unset): narrow is opt-in — it changes
#: stored precision (within the proven error budget), so it only runs
#: when asked for by name, by "all", or by the =auto scheduler.  This
#: keeps every pre-narrow golden byte-stable under the default knob.
DEFAULT_MODES = ("dse", "repack", "dbuf", "fuse")


def tile_opt_modes(pass_cfg: Optional[dict] = None) -> Tuple[str, ...]:
    """Active rewrite set: ``tl.tpu.tile_opt`` pass config when present,
    else the ``TL_TPU_TILE_OPT`` env var.  "1"/"on" enables the default
    set (everything but ``narrow``), "all" enables every rewrite,
    "auto" defers the choice to the cost-model pass scheduler in
    engine/lower.py (returned as the ``("auto",)`` sentinel), "0"/"off"
    disables the pass (restoring pre-pass plan_descs byte-identically),
    and a comma list selects a subset.  A typo'd token raises instead
    of silently disabling the optimizer (the same contract as
    TL_TPU_COMM_OPT / TL_TPU_LINT)."""
    raw: Any = None
    if pass_cfg:
        raw = pass_cfg.get("tl.tpu.tile_opt")
    if raw is None:
        from ..env import env
        raw = env.TL_TPU_TILE_OPT
    s = str(raw).strip().lower()
    if s == "auto":
        return ("auto",)
    if s in ("1", "on", "true", "yes", ""):
        return DEFAULT_MODES
    from .pass_config import parse_mode_set
    return parse_mode_set(raw, MODES, "TL_TPU_TILE_OPT")


@dataclass
class TileOptResult:
    """Outcome of one tile-opt run over a kernel."""

    modes: Tuple[str, ...] = ()
    rewrites: List[str] = field(default_factory=list)
    #: unified dead-code accounting — the SAME record shape comm_opt's
    #: dce emits ({op, buffer, bytes}), so ``analyzer trace`` shows one
    #: "eliminated" table across both optimizers
    eliminated: List[dict] = field(default_factory=list)
    dse_stores: int = 0
    dse_allocs: int = 0
    dse_bytes: int = 0
    narrow_buffers: int = 0
    narrow_bytes: int = 0
    #: per-narrowing proof record: buffer, from/to dtype, sound
    #: interval, accumulated error bound, bytes saved, verify rounds
    narrow_proofs: List[dict] = field(default_factory=list)
    repack_pre_bytes: int = 0
    repack_post_bytes: int = 0
    repack_buffers: int = 0
    repack_compat: int = 0
    repack_slots: int = 0
    dbuf_chains: int = 0
    fuse_regions: int = 0
    fuse_interleaved: int = 0
    #: the =auto cost-model scheduler's decision record (engine/lower),
    #: None under any fixed mode set
    sched: Optional[dict] = None

    def attrs_record(self) -> dict:
        """JSON-safe accounting for CompiledArtifact.attrs['tile_opt']."""
        rec = {
            "modes": list(self.modes),
            "rewrites": list(self.rewrites),
            "eliminated": [dict(e) for e in self.eliminated],
            "dse": {"stores": self.dse_stores, "allocs": self.dse_allocs,
                    "bytes": self.dse_bytes},
            "narrow": {"buffers": self.narrow_buffers,
                       "bytes": self.narrow_bytes,
                       "proofs": [dict(p) for p in self.narrow_proofs]},
            "repack": {"pre_bytes": self.repack_pre_bytes,
                       "post_bytes": self.repack_post_bytes,
                       "buffers": self.repack_buffers,
                       "compat": self.repack_compat,
                       "slots": self.repack_slots},
            "dbuf": {"chains": self.dbuf_chains},
            "fuse": {"regions": self.fuse_regions,
                     "interleaved": self.fuse_interleaved},
        }
        if self.sched is not None:
            rec["sched"] = dict(self.sched)
        return rec

    def desc_block(self) -> List[str]:
        """The ``tile_opt[...]`` lines appended to plan_desc — only when
        a rewrite actually fired, so unoptimized kernels (and
        TL_TPU_TILE_OPT=0) keep the exact pre-pass text."""
        if not self.rewrites:
            return []
        head = (f"  tile_opt[{','.join(self.modes)}]: "
                f"{len(self.rewrites)} rewrite(s)")
        if self.repack_buffers:
            # the repacked footprint, surfaced next to the TL005 budget
            # accounting the lint block carries
            head += (f", scratch {self.repack_pre_bytes}B -> "
                     f"{self.repack_post_bytes}B")
        if self.narrow_buffers:
            head += f", narrowed {self.narrow_bytes}B VMEM"
        if self.sched is not None:
            head += (f"; auto: predicted "
                     f"{self.sched['predicted_ms']:.4g}ms (gap closed "
                     f"{self.sched['gap_closed_ms']:.4g}ms)")
        return [head] + [f"    * {r}" for r in self.rewrites]


# ---------------------------------------------------------------------------
# shared rewrite machinery: functional stmt/expr reconstruction.  The
# traced PrimFunc is shared state (lint CLI collections, selfcheck
# re-lowers with the pass off), so the passes never mutate a statement
# in place — containers are rebuilt, unchanged subtrees are reused.
# ---------------------------------------------------------------------------

#: uid -> (replacement buffer, optional leading index expr, optional
#: cast dtype).  A None lead is a plain buffer substitution (repack); a
#: non-None lead prepends a slot index to every access (dbuf's rotated
#: second slot).  A non-None cast is the ORIGINAL dtype the
#: surrounding code expects: every elementwise load is wrapped
#: ``Cast(cast, load)`` so consumers see the original type (narrow's
#: widen-on-read; compat repack's re-narrow-on-read), and — only when
#: ``cast`` is strictly narrower than the replacement buffer's dtype
#: (the compat-repack case) — stored values are rounded through
#: ``Cast(cast, value)`` before landing, keeping every uncasted
#: observation path (a copy source) bit-exact with the original.
BufSub = Dict[int, Tuple[Buffer, Optional[Any], Optional[str]]]


def _rw_expr(e, vm: dict, bs: BufSub):
    if isinstance(e, Var):
        return vm.get(id(e), e)
    if isinstance(e, BufferLoad):
        idx = [i if isinstance(i, slice) else _rw_expr(i, vm, bs)
               for i in e.indices]
        sub = bs.get(e.buffer.uid)
        changed = any(a is not b for a, b in zip(idx, e.indices))
        if sub is None and not changed:
            return e
        buf, lead, cast = sub if sub is not None else (e.buffer, None, None)
        if lead is not None:
            idx = [lead] + idx
        load = BufferLoad(buf, tuple(idx))
        return Cast(cast, load) if cast is not None else load
    if isinstance(e, BinOp):
        a, b = _rw_expr(e.a, vm, bs), _rw_expr(e.b, vm, bs)
        if a is e.a and b is e.b:
            return e
        return BinOp(e.op, convert(a), convert(b))
    if isinstance(e, Call):
        args = [a if isinstance(a, str) else _rw_expr(a, vm, bs)
                for a in e.args]
        if all(a is b for a, b in zip(args, e.args)):
            return e
        return Call(e.name, args, e.dtype)
    if isinstance(e, Cast):
        v = _rw_expr(e.value, vm, bs)
        return e if v is e.value else Cast(e.dtype, v)
    return e


def _rw_region(r: Region, vm: dict, bs: BufSub) -> Region:
    base = [_rw_expr(b, vm, bs) for b in r.base]
    sub = bs.get(r.buffer.uid)
    if sub is None and all(a is b for a, b in zip(base, r.base)):
        return r
    buf, lead, _cast = sub if sub is not None else (r.buffer, None, None)
    shape = list(r.shape)
    if lead is not None:
        base = [lead] + base
        shape = [1] + shape
    return Region(buf, tuple(base), tuple(shape))


def _rw_buf(b: Buffer, bs: BufSub, allow_cast: bool = False) -> Buffer:
    sub = bs.get(b.uid)
    if sub is None:
        return b
    buf, lead, cast = sub
    if lead is not None:
        raise AssertionError(
            f"buffer {b.name} used as a whole-buffer operand cannot take "
            f"a slot index (tile-opt pass bug: dbuf must bail on it)")
    if cast is not None and _exact_widens(cast, buf.dtype) \
            and not allow_cast:
        # a compat-repack cast view cannot present through a
        # whole-buffer operand — _compat_castable must have refused
        # (reduce/cumsum SRC is the one exception: the caller passes
        # allow_cast because reading exactly-representable values at
        # the wider slot dtype is bit-identical to the upcast)
        raise AssertionError(
            f"buffer {b.name} with a narrowing cast view used as a "
            f"whole-buffer operand (tile-opt pass bug: compat repack "
            f"must bail on it)")
    return buf


def _keep_loc(new: Stmt, old: Stmt) -> Stmt:
    if old.loc is not None:
        new.loc = old.loc
    return new


def _rw_stmt(s: Stmt, vm: dict, bs: BufSub) -> Stmt:
    """Rebuild one statement under a var/buffer substitution; returns
    the ORIGINAL object when nothing inside it changed."""
    if isinstance(s, SeqStmt):
        kids = [_rw_stmt(c, vm, bs) for c in s.stmts]
        if all(a is b for a, b in zip(kids, s.stmts)):
            return s
        return _keep_loc(SeqStmt(kids), s)
    if isinstance(s, KernelNode):
        pre = [_rw_stmt(c, vm, bs) for c in s.prelude]
        body = _rw_stmt(s.body, vm, bs)
        if body is s.body and all(a is b for a, b in zip(pre, s.prelude)):
            return s
        return _keep_loc(KernelNode(s.grid_vars, s.extents, s.threads,
                                    body, prelude=pre), s)
    if isinstance(s, ForNest):
        exts = [e if isinstance(e, int) else _rw_expr(e, vm, bs)
                for e in s.extents]
        body = _rw_stmt(s.body, vm, bs)
        if body is s.body and all(a is b for a, b in zip(exts, s.extents)):
            return s
        return _keep_loc(ForNest(s.loop_vars, exts, s.kind, body,
                                 s.num_stages, dict(s.annotations)), s)
    if isinstance(s, IfThenElse):
        cond = _rw_expr(s.cond, vm, bs)
        then = _rw_stmt(s.then_body, vm, bs)
        els = _rw_stmt(s.else_body, vm, bs) if s.else_body is not None \
            else None
        if cond is s.cond and then is s.then_body and els is s.else_body:
            return s
        return _keep_loc(IfThenElse(cond, then, els), s)
    if isinstance(s, AllocStmt):
        # narrow swaps the alloc in place (same name/shape/scope,
        # thinner dtype).  repack drops its allocs BEFORE rewriting and
        # dbuf subs carry a lead, so only narrow reaches this branch.
        sub = bs.get(s.buffer.uid)
        if sub is not None and sub[1] is None and sub[0] is not s.buffer:
            return _keep_loc(AllocStmt(sub[0]), s)
        return s
    if isinstance(s, CopyStmt):
        src, dst = _rw_region(s.src, vm, bs), _rw_region(s.dst, vm, bs)
        if src is s.src and dst is s.dst:
            return s
        return _keep_loc(CopyStmt(src, dst, s.coalesced_width), s)
    if isinstance(s, AsyncCopyStmt):
        src, dst = _rw_region(s.src, vm, bs), _rw_region(s.dst, vm, bs)
        slot = _rw_expr(s.slot, vm, bs)
        sem = _rw_buf(s.sem, bs)
        if src is s.src and dst is s.dst and slot is s.slot \
                and sem is s.sem:
            return s
        return _keep_loc(AsyncCopyStmt(src, dst, sem, slot, s.phase), s)
    if isinstance(s, GemmStmt):
        A, B, C = (_rw_region(r, vm, bs) for r in (s.A, s.B, s.C))
        if A is s.A and B is s.B and C is s.C:
            return s
        return _keep_loc(GemmStmt(A, B, C, s.trans_A, s.trans_B, s.policy,
                                  s.clear_accum), s)
    if isinstance(s, FillStmt):
        dst = _rw_region(s.dst, vm, bs)
        val = _rw_expr(s.value, vm, bs)
        sub = bs.get(s.dst.buffer.uid)
        if sub is not None and sub[2] is not None \
                and _exact_widens(sub[2], sub[0].dtype):
            # compat repack: round the fill value at the original
            # (narrower) dtype before it lands in the wider slot
            val = Cast(sub[2], convert(val))
        if dst is s.dst and val is s.value:
            return s
        return _keep_loc(FillStmt(dst, val), s)
    if isinstance(s, ReduceStmt):
        # the SRC may carry a compat cast view: reading the slot's
        # exactly-representable values at its wider dtype is
        # bit-identical to upcast-before-reduce, so the cast is simply
        # dropped (codegen accumulates at the dst dtype regardless)
        src, dst = _rw_buf(s.src, bs, allow_cast=True), _rw_buf(s.dst, bs)
        if src is s.src and dst is s.dst:
            return s
        return _keep_loc(ReduceStmt(s.kind, src, dst, s.dim, s.clear), s)
    if isinstance(s, CumSumStmt):
        src, dst = _rw_buf(s.src, bs, allow_cast=True), _rw_buf(s.dst, bs)
        if src is s.src and dst is s.dst:
            return s
        return _keep_loc(CumSumStmt(src, dst, s.dim, s.reverse), s)
    if isinstance(s, AtomicStmt):
        dst = _rw_region(s.dst, vm, bs)
        val = _rw_region(s.value, vm, bs) if isinstance(s.value, Region) \
            else _rw_expr(s.value, vm, bs)
        if dst is s.dst and val is s.value:
            return s
        return _keep_loc(AtomicStmt(s.op, dst, val), s)
    if isinstance(s, BufferStoreStmt):
        idx = [i if isinstance(i, slice) else _rw_expr(i, vm, bs)
               for i in s.indices]
        val = _rw_expr(s.value, vm, bs)
        sub = bs.get(s.buffer.uid)
        if sub is None and val is s.value and \
                all(a is b for a, b in zip(idx, s.indices)):
            return s
        buf, lead, cast = sub if sub is not None else (s.buffer, None, None)
        if lead is not None:
            idx = [lead] + idx
        if cast is not None and _exact_widens(cast, buf.dtype):
            # compat repack: round the stored value at the original
            # (narrower) dtype so every observation path — including an
            # uncasted copy source — stays bit-exact with the original
            val = Cast(cast, convert(val))
        return _keep_loc(BufferStoreStmt(buf, tuple(idx), val), s)
    if isinstance(s, EvaluateStmt):
        e = _rw_expr(s.expr, vm, bs)
        return s if e is s.expr else _keep_loc(EvaluateStmt(e), s)
    if isinstance(s, AssertStmt):
        c = _rw_expr(s.cond, vm, bs)
        return s if c is s.cond else _keep_loc(AssertStmt(c, s.msg), s)
    if isinstance(s, PrintStmt):
        obj = s.obj
        if isinstance(obj, Buffer):
            obj = _rw_buf(obj, bs)
        elif isinstance(obj, Region):
            obj = _rw_region(obj, vm, bs)
        elif obj is not None and not isinstance(obj, str):
            obj = _rw_expr(obj, vm, bs)
        return s if obj is s.obj else _keep_loc(PrintStmt(obj, s.msg), s)
    # CommStmt and friends: tile-opt never runs on mesh programs
    # (lower_mesh branches before it); leave them untouched if ever seen
    return s


def _drop_stmts(stmts, drop: set) -> List[Stmt]:
    """Rebuild a statement list without the dropped statements, pruning
    loops whose bodies emptied and Ifs whose arms both emptied (their
    condition/extent reads are pure)."""
    out: List[Stmt] = []
    for s in stmts:
        if id(s) in drop:
            continue
        if isinstance(s, SeqStmt):
            kids = _drop_stmts(s.stmts, drop)
            if kids != list(s.stmts):
                if not kids:
                    continue
                s = _keep_loc(SeqStmt(kids), s)
        elif isinstance(s, KernelNode):
            pre = _drop_stmts(s.prelude, drop)
            body = _drop_stmts(s.body.stmts, drop)
            if pre != list(s.prelude) or body != list(s.body.stmts):
                s = _keep_loc(KernelNode(s.grid_vars, s.extents, s.threads,
                                         SeqStmt(body), prelude=pre), s)
        elif isinstance(s, ForNest):
            body = _drop_stmts(s.body.stmts, drop)
            if not body:
                continue
            if body != list(s.body.stmts):
                s = _keep_loc(ForNest(s.loop_vars, s.extents, s.kind,
                                      SeqStmt(body), s.num_stages,
                                      dict(s.annotations)), s)
        elif isinstance(s, IfThenElse):
            then = _drop_stmts(s.then_body.stmts, drop)
            els = _drop_stmts(s.else_body.stmts, drop) \
                if s.else_body is not None else None
            if not then and not els:
                continue
            if then != list(s.then_body.stmts) or \
                    (s.else_body is not None and
                     els != list(s.else_body.stmts)):
                s = _keep_loc(IfThenElse(
                    s.cond, SeqStmt(then),
                    SeqStmt(els) if els else None), s)
        out.append(s)
    return out


def _buf_bytes(b: Buffer) -> int:
    """Padded VMEM footprint of one scratch buffer — the same
    (sublane, lane)-tile rule transform/plan._pack_scratch charges."""
    ss = b.static_shape()
    if not ss:
        return 0
    from ..layout import native as lnat
    from ..layout import python_impl as lpy
    rows = ss[-2] if len(ss) >= 2 else 1
    cols = ss[-1] if ss else 1
    bits = dtype_bits(b.dtype)
    tile = lnat.vmem_bytes(rows, cols, bits)
    if tile is None:
        tile = lpy.vmem_bytes(rows, cols, bits)
    lead = 1
    for x in ss[:-2]:
        lead *= x
    return tile * lead


# ---------------------------------------------------------------------------
# dse — dead-store / dead-alloc elimination (TL006's proof, applied)
# ---------------------------------------------------------------------------

#: statements that only exist to produce their written buffers — safe to
#: delete when every written buffer is dead (their reads are pure)
_PURE_WRITERS = (CopyStmt, FillStmt, GemmStmt, ReduceStmt, CumSumStmt,
                 BufferStoreStmt, AtomicStmt)


def _dse_dead_allocs(body) -> Dict[int, AllocStmt]:
    """TL006's exact dead set: on-chip allocs never read (dead stores)
    or never touched at all (unused allocs).  Async-copy destinations
    are excluded — deleting half a split-phase DMA pair would leave a
    wait on a never-armed slot.  Split out as its own helper so the
    mutation tests can corrupt it and assert the selfcheck catches the
    miscompile."""
    from ..analysis.dataflow import iter_stmts, stmt_accesses
    allocs: Dict[int, AllocStmt] = {}
    reads: set = set()
    async_touched: set = set()
    for s, _c in iter_stmts(body):
        if isinstance(s, AllocStmt):
            allocs.setdefault(s.buffer.uid, s)
            continue
        if isinstance(s, AsyncCopyStmt):
            async_touched.add(s.src.buffer.uid)
            async_touched.add(s.dst.buffer.uid)
        for acc in stmt_accesses(s):
            if acc.kind == "read":
                reads.add(acc.buffer.uid)
    return {uid: a for uid, a in allocs.items()
            if a.buffer.scope not in ("global", "sem")
            and uid not in reads and uid not in async_touched}


def _dse(body: SeqStmt, res: TileOptResult) -> SeqStmt:
    """Delete TL006-proven dead stores and unused allocs, to fixpoint
    (removing the stores into a dead buffer can strand the buffer that
    only fed them — a dead chain, same fixpoint comm_opt's dce runs)."""
    from ..analysis.dataflow import iter_stmts, stmt_accesses
    for _round in range(16):
        dead = _dse_dead_allocs(body)
        if not dead:
            break
        drop: set = set()
        stores: Dict[int, List[Stmt]] = {uid: [] for uid in dead}
        for s, _c in iter_stmts(body):
            if not isinstance(s, _PURE_WRITERS):
                continue
            ws = [a for a in stmt_accesses(s) if a.kind == "write"]
            if ws and all(a.buffer.uid in dead for a in ws):
                drop.add(id(s))
                for a in ws:
                    stores[a.buffer.uid].append(s)
        for uid, astmt in sorted(dead.items()):
            b = astmt.buffer
            drop.add(id(astmt))
            nbytes = _buf_bytes(b)
            nstores = len(stores.get(uid, []))
            res.dse_bytes += nbytes
            res.dse_allocs += 1
            res.dse_stores += nstores
            op = (type(stores[uid][0]).__name__ if stores.get(uid)
                  else "AllocStmt")
            res.eliminated.append(
                {"op": op, "buffer": b.name, "bytes": nbytes})
            if nstores:
                res.rewrites.append(
                    f"dse: removed dead scratch '{b.name}' "
                    f"({nstores} store(s), {nbytes}B VMEM)")
            else:
                res.rewrites.append(
                    f"dse: removed unused alloc '{b.name}' "
                    f"({nbytes}B VMEM)")
        body = SeqStmt(_drop_stmts(body.stmts, drop))
    return body


# ---------------------------------------------------------------------------
# narrow — value-range-driven dtype narrowing (TL007/TL008 inverted)
# ---------------------------------------------------------------------------

#: the narrowing directions the pass considers.  f32 scratch holding a
#: proven-small, error-budgeted value thins to bf16 (same exponent
#: range, so the TL007 range check is about the ERROR budget); i32
#: index/position scratch with a proven sub-16-bit sound interval thins
#: to i16 (exact — the range proof is the whole story).
_NARROW_TARGETS = {"float32": "bfloat16", "int32": "int16"}


def _exact_widens(narrow_dt: str, wide_dt: str) -> bool:
    """True when every value of ``narrow_dt`` is exactly representable
    in ``wide_dt`` (the narrow -> wide -> narrow round trip is
    lossless) — the legality rule behind both the compat-repack slot
    gate and the cast views the BufSub machinery installs."""
    if narrow_dt == wide_dt:
        return False
    from ..analysis.absint import (dtype_eps, dtype_max, int_range,
                                   is_float, is_int)
    if is_float(narrow_dt) and is_float(wide_dt):
        return (dtype_eps(wide_dt) <= dtype_eps(narrow_dt)
                and dtype_max(wide_dt) >= dtype_max(narrow_dt))
    if is_int(narrow_dt) and is_int(wide_dt):
        nlo, nhi = int_range(narrow_dt)
        wlo, whi = int_range(wide_dt)
        return wlo <= nlo and whi >= nhi
    return False


def _interp_body(func: PrimFunc, body: SeqStmt, pass_cfg):
    """Fresh dual-track interpretation of the CURRENT (possibly already
    rewritten) body — run_tile_opt rewrites functionally, so the
    memoized analysis/numerics.analyze cache keyed on the original
    PrimFunc cannot be reused here."""
    from ..analysis.numerics import Interp
    f2 = PrimFunc(func.name, func.params, body, dict(func.attrs))
    return Interp(f2, pass_cfg).run()


def _narrow_fits(env, old_dt: str, new_dt: str, err_thr: float) -> bool:
    """The cheap envelope pre-gate: does the buffer's proven write
    envelope (sound interval + accumulated relative error) fit the
    thinner dtype?  Floats need the whole store chain to stay inside
    the TL008 error budget even after the extra per-store rounding;
    ints need the exact sound interval inside the thinner range.
    Module-level so the mutation tests can corrupt it and assert the
    differential selfcheck catches the miscompile."""
    from ..analysis.absint import dtype_eps, dtype_max, int_range, is_float
    if env is None or not env.sound_bounded():
        return False
    if is_float(old_dt):
        if not env.finite:
            return False
        fmax = dtype_max(new_dt)
        if env.shi > fmax or env.slo < -fmax:
            return False
        return env.err + dtype_eps(new_dt) <= err_thr
    lo, hi = int_range(new_dt)
    return env.slo >= lo and env.shi <= hi


def _cancel_screen(body: SeqStmt, base, cands, err_thr: float) -> set:
    """The cancellation screen — the narrow pass's third gate.

    TL008 tracks RELATIVE rounding error and carries
    ``max(err_a, err_b)`` through add/sub, which is sound only while
    magnitudes do not cancel.  Storing a large-magnitude buffer at a
    thinner dtype plants an ABSOLUTE error of up to
    ``maxmag * eps(new)``; a downstream subtraction of nearly-equal
    values then shrinks the value without shrinking that error — a
    blow-up invisible to both the envelope pre-gate and the
    re-interpretation (``x + 16384`` staged through bf16 rounds to a
    multiple of 128; ``- 16384`` afterwards returns garbage).

    The screen walks every store-side expression with plain interval
    arithmetic (buffer loads read the proven write envelopes, taint
    tracks which candidates feed which buffers, two forward passes
    catch simple staging chains) and refuses any candidate feeding an
    add/sub whose proven result magnitude is small enough for the
    candidate's storage error alone to blow the error budget:
    ``maxmag(result) < maxmag(cand) * eps(new) / err_thr``.  This is
    heuristic hardening, not a completeness proof — the differential
    selfcheck stays the runtime backstop.  Module-level so the
    mutation tests can corrupt it.  Returns the blocked buffer uids."""
    from ..analysis.absint import (AbsVal, av_add, av_mul, av_sub,
                                   dtype_eps, mk)
    from ..analysis.dataflow import iter_stmts

    env_mag: Dict[int, float] = {}
    for _a, b, new_dt, env in cands:
        env_mag[b.uid] = max(abs(env.slo), abs(env.shi)) \
            * dtype_eps(new_dt) / err_thr
    if not env_mag:
        return set()
    unknown = AbsVal()
    blocked: set = set()
    taint: Dict[int, frozenset] = {}

    def ev(e) -> Tuple[AbsVal, frozenset]:
        if isinstance(e, (int, float)):
            v = float(e)
            return mk(v, v, v, v, True), frozenset()
        val = getattr(e, "value", None)
        if isinstance(e, Cast):
            return ev(val)
        if val is not None and isinstance(val, (int, float)) \
                and not isinstance(e, BufferLoad):
            v = float(val)
            return mk(v, v, v, v, True), frozenset()
        if isinstance(e, BufferLoad):
            uid = e.buffer.uid
            u = taint.get(uid, frozenset())
            if uid in env_mag:
                u = u | frozenset((uid,))
            env = base.envelopes.get(uid)
            return (env if env is not None else unknown), u
        if isinstance(e, BinOp):
            va, ua = ev(e.a)
            vb, ub = ev(e.b)
            u = ua | ub
            fn = {"+": av_add, "-": av_sub, "*": av_mul}.get(e.op)
            if fn is None:
                return unknown, u
            r = fn(va, vb)
            if e.op in ("+", "-") and u and r.sound_bounded():
                rmax = max(abs(r.slo), abs(r.shi))
                for uid in u:
                    if rmax < env_mag[uid]:
                        blocked.add(uid)
            return r, u
        if isinstance(e, Call):
            u = frozenset()
            for a in e.args:
                if not isinstance(a, (str, slice)):
                    u = u | ev(a)[1]
            return unknown, u
        return unknown, frozenset()

    for _round in range(2):      # second pass: simple staging back-edges
        for s, _c in iter_stmts(body):
            if isinstance(s, BufferStoreStmt):
                _v, u = ev(s.value)
                if u:
                    taint[s.buffer.uid] = taint.get(
                        s.buffer.uid, frozenset()) | u
            elif isinstance(s, CopyStmt):
                u = taint.get(s.src.buffer.uid)
                if u:
                    taint[s.dst.buffer.uid] = taint.get(
                        s.dst.buffer.uid, frozenset()) | u
    return blocked


def _narrow_blockers(body: SeqStmt) -> set:
    """Buffer uids the narrow rewrite must refuse on STRUCTURAL grounds
    regardless of any value proof: DMA endpoints (the TPU DMA engine
    cannot convert dtypes), gemm accumulators (the MXU accumulates at
    the C dtype), reduce/cumsum destinations (the n*eps(dst) error
    model is priced at the destination dtype), atomics, collectives,
    prints, int gemm operands, and anything read before its first
    write (garbage VMEM has no envelope)."""
    from ..analysis.dataflow import iter_stmts, stmt_accesses
    from ..analysis.absint import is_int
    bad: set = set()
    first_read: set = set()
    touched: set = set()
    for s, _c in iter_stmts(body):
        if isinstance(s, AllocStmt):
            continue
        if isinstance(s, (AsyncCopyStmt, CommStmt, AtomicStmt, PrintStmt)):
            for acc in stmt_accesses(s):
                bad.add(acc.buffer.uid)
            if isinstance(s, AsyncCopyStmt):
                bad.add(s.sem.uid)
            continue
        if isinstance(s, CopyStmt):
            # DMA legs (a global peer) cannot convert dtypes; pure
            # on-chip copies go through an astype and stay legal
            if s.src.buffer.scope == "global":
                bad.add(s.dst.buffer.uid)
            if s.dst.buffer.scope == "global":
                bad.add(s.src.buffer.uid)
        elif isinstance(s, GemmStmt):
            bad.add(s.C.buffer.uid)
            # bf16 gemm operands ride the MXU natively (the either-f32
            # HIGHEST precision rule keeps the wide side exact); int
            # operands do not — refuse int narrowing there
            for r in (s.A, s.B):
                if is_int(r.buffer.dtype):
                    bad.add(r.buffer.uid)
        elif isinstance(s, (ReduceStmt, CumSumStmt)):
            # src is legal: codegen upcasts a narrower src to the dst
            # dtype before accumulating (matching the interpreter's
            # n*eps(dst) model); the dst dtype IS the accumulator
            bad.add(s.dst.uid)
        # first-touch discipline: a buffer read before any write is
        # uninitialized garbage — both lowerings would read DIFFERENT
        # garbage, so narrowing it is unverifiable
        for acc in stmt_accesses(s):
            uid = acc.buffer.uid
            if uid not in touched and acc.kind == "read":
                first_read.add(uid)
            touched.add(uid)
    return bad | first_read


def _narrow_candidates(func: PrimFunc, body: SeqStmt, pass_cfg):
    """(interp result, [(alloc stmt, buffer, target dtype, envelope)])
    for every scratch alloc that passes the structural refusals AND the
    envelope pre-gate, in program order.  Shared by the rewrite and the
    lint CLI's TL008 --fix hint."""
    from ..analysis.dataflow import iter_stmts
    from ..analysis.numerics import num_err_threshold
    try:
        base = _interp_body(func, body, pass_cfg)
    except Exception:   # noqa: BLE001 — no proof, no rewrite
        return None, []
    err_thr = num_err_threshold(pass_cfg)
    blocked = _narrow_blockers(body)
    cands = []
    seen: set = set()
    for s, _c in iter_stmts(body):
        if not isinstance(s, AllocStmt) or s.buffer.uid in seen:
            continue
        seen.add(s.buffer.uid)
        b = s.buffer
        new_dt = _NARROW_TARGETS.get(b.dtype)
        if new_dt is None or b.scope in ("global", "sem", "smem") \
                or b.static_shape() is None or b.uid in blocked:
            continue
        env = base.envelopes.get(b.uid)
        if not _narrow_fits(env, b.dtype, new_dt, err_thr):
            continue
        cands.append((s, b, new_dt, env))
    if cands:
        cancel = _cancel_screen(body, base, cands, err_thr)
        cands = [c for c in cands if c[1].uid not in cancel]
    return base, cands


def narrow_candidates(func: PrimFunc, pass_cfg: Optional[dict] = None
                      ) -> List[str]:
    """Buffer names the narrow rewrite would provably thin (envelope
    pre-gate only, no re-verification) — the lint CLI consults this to
    print the TL008 -> TL_TPU_TILE_OPT=narrow --fix hint."""
    body = func.body if isinstance(func.body, SeqStmt) \
        else SeqStmt(list(func.body))
    _base, cands = _narrow_candidates(func, body, pass_cfg)
    return [b.name for _s, b, _dt, _env in cands]


def _narrow_verify(func: PrimFunc, cand_body: SeqStmt, base, pass_cfg,
                   cand_names: set):
    """Re-interpretation verification: run the dual-track interpreter
    over the candidate NARROWED body and demand it is proof-clean vs
    the baseline — no new (rule, buffer) finding, no lost
    output-finiteness proof.  Returns the set of candidate buffer
    names implicated in a regression ('*' = unattributable, the caller
    drops everything), an empty set when clean, or None when the
    interpretation itself failed.  Module-level so the mutation tests
    can corrupt it."""
    try:
        ver = _interp_body(func, cand_body, pass_cfg)
    except Exception:   # noqa: BLE001
        return None
    base_keys = {(d.rule, d.buffer) for d in base.findings}
    bad: set = set()
    for d in ver.findings:
        if (d.rule, d.buffer) in base_keys:
            continue
        bad.add(d.buffer if d.buffer in cand_names else "*")
    for name, proven in base.outputs.items():
        if proven and not ver.outputs.get(name, False):
            bad.add("*")
    return bad


def _narrow(func: PrimFunc, body: SeqStmt, res: TileOptResult,
            pass_cfg) -> SeqStmt:
    """Thin provably-small scratch buffers to a narrower dtype.

    Loads present the original dtype through an exact widening cast —
    compute precision is untouched, only the STORAGE rounds (each
    store charges one eps(new dtype), exactly what the envelope
    pre-gate budgeted).  After the pre-gate, the candidate narrowed
    body is re-interpreted end to end; any candidate implicated in a
    new finding is dropped and verification repeats until the set is
    clean (or empty)."""
    from ..analysis.numerics import num_err_threshold
    base, cands = _narrow_candidates(func, body, pass_cfg)
    if not cands:
        return body
    err_thr = num_err_threshold(pass_cfg)
    rounds = 0
    cand_body, buf_sub = body, {}
    while cands:
        rounds += 1
        buf_sub = {}
        for _astmt, b, new_dt, _env in cands:
            nb = Buffer(b.name, b.static_shape(), new_dt, b.scope)
            buf_sub[b.uid] = (nb, None, b.dtype)
        cand_body = _rw_stmt(body, {}, buf_sub)
        bad = _narrow_verify(func, cand_body, base, pass_cfg,
                             {b.name for _a, b, _d, _e in cands})
        if bad is None or "*" in bad:
            return body
        if not bad:
            break
        cands = [c for c in cands if c[1].name not in bad]
    if not cands:
        return body
    for _astmt, b, new_dt, env in cands:
        nb = buf_sub[b.uid][0]
        saved = _buf_bytes(b) - _buf_bytes(nb)
        res.narrow_buffers += 1
        res.narrow_bytes += saved
        res.narrow_proofs.append({
            "buffer": b.name, "from": b.dtype, "to": new_dt,
            "interval": [env.slo, env.shi], "err": env.err,
            "bytes_saved": saved, "verify_rounds": rounds})
        res.rewrites.append(
            f"narrow: '{b.name}' {b.dtype} -> {new_dt} (sound interval "
            f"[{env.slo:.4g}, {env.shi:.4g}], err bound {env.err:.3g} + "
            f"eps({new_dt}) <= {err_thr:g}, re-verified by dual-track "
            f"interpretation; {saved}B VMEM saved)")
    return cand_body


# ---------------------------------------------------------------------------
# repack — realize the TL005 interval packing at the IR level
# ---------------------------------------------------------------------------


def _kernel_node(body) -> Optional[KernelNode]:
    for s in body.stmts:
        if isinstance(s, KernelNode):
            return s
    return None


def _compat_castable(body) -> set:
    """Buffer uids that must NOT be given a cast view into a wider
    slot.  A compat-placed buffer is observed through loads (castable)
    and written through stores/fills (the rewriter eagerly rounds the
    value back to the original dtype, so the slot only ever holds
    exactly-representable values).  Everything else — copy
    destinations (region writes land raw src values in the slot,
    breaking the eager-rounding invariant), DMA legs (the DMA engine
    cannot convert), and whole-buffer / region operands of gemm,
    reduce, cumsum, atomics, async copies, collectives and prints —
    has no place to hang the cast, so the buffer keeps its own slot."""
    from ..analysis.dataflow import iter_stmts, stmt_accesses
    bad: set = set()
    for s, _c in iter_stmts(body):
        if isinstance(s, (AllocStmt, BufferStoreStmt, FillStmt,
                          EvaluateStmt, AssertStmt)):
            continue
        if isinstance(s, CopyStmt):
            bad.add(s.dst.buffer.uid)
            if s.src.buffer.scope == "global":
                bad.add(s.dst.buffer.uid)
            if s.dst.buffer.scope == "global":
                bad.add(s.src.buffer.uid)
            continue
        if isinstance(s, (ReduceStmt, CumSumStmt)):
            # the SRC is castable: the slot holds exactly-representable
            # values, and codegen accumulates at the dst dtype either
            # way — reading them at the slot's wider dtype is
            # bit-identical to the upcast-before-reduce the narrow
            # lowering emits.  The dst is a raw accumulator write.
            bad.add(s.dst.uid)
            continue
        for acc in stmt_accesses(s):
            bad.add(acc.buffer.uid)
    return bad


def _repack(body: SeqStmt, res: TileOptResult) -> SeqStmt:
    """Alias same-shape/dtype/scope scratch buffers with provably
    disjoint top-level live intervals onto one shared slot.

    Liveness is measured at top-level-statement granularity of the
    kernel body — the same interval model TL005's arena packing uses.
    A buffer is slot-shareable only when its FIRST access is an
    unconditional write (no branch guard, every enclosing loop extent
    statically >= 1): a guarded first write is the grid-carried-init
    idiom, whose value must survive from one grid step into the next —
    re-using its slot between steps would corrupt it, so such buffers
    are left alone."""
    from ..analysis.dataflow import iter_stmts, stmt_accesses
    kn = _kernel_node(body)
    if kn is None:
        return body
    top = list(kn.body.stmts)

    info: Dict[int, dict] = {}
    # accesses OUTSIDE the kernel body — the KernelNode prelude and any
    # sibling top statements — are invisible to the top-level interval
    # model below; buffers they touch are disqualified outright (a
    # prelude read of grid-carried scratch must never lose its slot)
    outside = [s for s in body.stmts if s is not kn] + list(kn.prelude)
    for s, _c in iter_stmts(outside):
        for acc in stmt_accesses(s):
            info[acc.buffer.uid] = {"first": -1, "last": -1,
                                    "first_write": False, "bad": True}
    for ti, child in enumerate(top):
        for s, c in iter_stmts([child]):
            if isinstance(s, AllocStmt):
                continue
            bad = isinstance(s, AsyncCopyStmt)
            for acc in stmt_accesses(s):
                b = acc.buffer
                d = info.get(b.uid)
                if d is None:
                    uncond = (not c.guards) and all(
                        as_int(e) is not None and as_int(e) >= 1
                        for ln in c.loops for e in ln.extents)
                    d = info[b.uid] = {
                        "first": ti, "last": ti,
                        "first_write": acc.kind == "write" and uncond,
                    }
                d["last"] = ti
                if bad:
                    d["bad"] = True

    # allocs in program order (anywhere in the func body)
    alloc_stmts: List[AllocStmt] = []
    seen_allocs: set = set()
    for s, _c in iter_stmts(body):
        if isinstance(s, AllocStmt) and s.buffer.uid not in seen_allocs:
            seen_allocs.add(s.buffer.uid)
            alloc_stmts.append(s)

    res.repack_pre_bytes = sum(
        _buf_bytes(a.buffer) for a in alloc_stmts
        if a.buffer.scope not in ("global", "sem"))

    cands = []
    for a in alloc_stmts:
        b = a.buffer
        if b.scope in ("global", "sem") or b.static_shape() is None:
            continue
        d = info.get(b.uid)
        if d is None or d.get("bad") or not d["first_write"]:
            continue
        cands.append((d["first"], b.uid, a, d))
    cands.sort(key=lambda t: (t[0], t[1]))

    no_cast = _compat_castable(body)
    slots: List[dict] = []       # {"rep": Buffer, "last": int}
    buf_sub: BufSub = {}
    drop: set = set()
    saved = 0
    for _first, _uid, astmt, d in cands:
        b = astmt.buffer
        placed = False
        for slot in slots:
            rep = slot["rep"]
            if rep.static_shape() != b.static_shape() \
                    or rep.scope != b.scope or slot["last"] >= d["first"]:
                continue
            if rep.dtype == b.dtype:
                buf_sub[b.uid] = (rep, None, None)
                res.rewrites.append(
                    f"repack: '{b.name}' shares the VMEM slot of "
                    f"'{rep.name}' (disjoint lifetimes, "
                    f"{_buf_bytes(b)}B saved)")
            elif _exact_widens(b.dtype, rep.dtype) \
                    and b.uid not in no_cast:
                # compat slot: b's dtype exactly widens into rep's, so
                # b lives in rep's slot behind a cast view — loads
                # present b.dtype, stores eagerly round back to it
                buf_sub[b.uid] = (rep, None, b.dtype)
                res.repack_compat += 1
                res.rewrites.append(
                    f"repack: '{b.name}' ({b.dtype}) shares the "
                    f"compatible {rep.dtype} slot of '{rep.name}' "
                    f"(exact-widening cast view, disjoint lifetimes, "
                    f"{_buf_bytes(b)}B saved)")
            else:
                continue
            drop.add(id(astmt))
            slot["last"] = d["last"]
            saved += _buf_bytes(b)
            res.repack_buffers += 1
            placed = True
            break
        if not placed:
            slots.append({"rep": b, "last": d["last"]})

    if not buf_sub:
        res.repack_pre_bytes = 0
        return body
    res.repack_slots = len(slots)
    res.repack_post_bytes = res.repack_pre_bytes - saved
    body = SeqStmt(_drop_stmts(body.stmts, drop))
    return _rw_stmt(body, {}, buf_sub)


# ---------------------------------------------------------------------------
# dbuf — proof-gated automatic double-buffering of serial-loop streams
# ---------------------------------------------------------------------------


def _dbuf(body: SeqStmt, res: TileOptResult) -> SeqStmt:
    from ..analysis.dataflow import iter_stmts, stmt_accesses

    # whole-function facts: every write/read of every buffer, and the
    # buffers used as whole-buffer operands (ReduceStmt/CumSumStmt take
    # a Buffer, which cannot carry a slot index)
    writes: Dict[int, List[Stmt]] = {}
    reads: Dict[int, List[Stmt]] = {}
    whole_ops: set = set()
    for s, _c in iter_stmts(body):
        if isinstance(s, (ReduceStmt, CumSumStmt)):
            whole_ops.add(s.src.uid)
            whole_ops.add(s.dst.uid)
        if isinstance(s, AllocStmt):
            continue
        for acc in stmt_accesses(s):
            (writes if acc.kind == "write" else reads).setdefault(
                acc.buffer.uid, []).append(s)

    drop_allocs: set = set()

    def try_loop(loop: ForNest) -> Optional[Tuple[List[Stmt], ForNest]]:
        if loop.kind != "serial" or len(loop.loop_vars) != 1:
            return None
        n = as_int(loop.extents[0])
        if n is None or n < 2:
            return None
        ko = loop.loop_vars[0]
        children = list(loop.body.stmts)
        owner: Dict[int, int] = {}
        for idx, child in enumerate(children):
            for st, _ in iter_stmts([child]):
                owner[id(st)] = idx
        body_writes: set = set()
        for child in children:
            for st, _ in iter_stmts([child]):
                for acc in stmt_accesses(st):
                    if acc.kind == "write":
                        body_writes.add(acc.buffer.uid)

        new_allocs: List[Stmt] = []
        buf_sub: BufSub = {}
        copy_repl: Dict[int, List[Stmt]] = {}
        for ci, s in enumerate(children):
            if not isinstance(s, CopyStmt):
                continue
            dstb = s.dst.buffer
            if dstb.uid in buf_sub:
                continue
            if s.src.buffer.scope != "global" \
                    or dstb.scope in ("global", "sem") \
                    or not s.dst.is_full() \
                    or dstb.static_shape() is None \
                    or dstb.uid in whole_ops:
                continue
            # the full-region copy must be the FIRST access of the
            # stream buffer: every other touch (the in-place transforms
            # and the consumers) lives inside THIS loop body after it.
            # The full refill kills loop-carried state, so re-slotting
            # each iteration onto ko % 2 cannot change what any read
            # observes — the proof the TL002 window machinery encodes.
            others = [w for w in writes.get(dstb.uid, []) if w is not s] \
                + reads.get(dstb.uid, [])
            if not reads.get(dstb.uid) or \
                    any(owner.get(id(o), -1) <= ci for o in others):
                continue
            # the in-flight prefetch reads src(ko+1): nothing in the
            # loop may write the DMA source (TL002's clobber hazard) —
            # NOR any buffer the source's base indices read (a
            # gather-style `A[idx[0], 0]` source whose index scratch is
            # updated in the loop would prefetch ko+1's tile through
            # ko's stale index value). stmt_accesses enumerates both:
            # the src region read and every load inside its bases.
            if any(a.kind == "read" and a.buffer.uid in body_writes
                   for a in stmt_accesses(s)):
                continue
            shape = dstb.static_shape()
            dst2 = Buffer(f"{dstb.name}_db", (2,) + shape, dstb.dtype,
                          dstb.scope)
            sem = Buffer(f"{dstb.name}_dbsem", (2,), "int32", "sem")
            new_allocs.extend([AllocStmt(dst2), AllocStmt(sem)])
            lead = ko % 2
            nxt = (ko + 1) % 2
            zeros = (0,) * len(shape)
            slot_cur = Region(dst2, (lead,) + zeros, (1,) + shape)
            slot_nxt = Region(dst2, (nxt,) + zeros, (1,) + shape)
            src_next = _rw_region(s.src, {id(ko): ko + 1}, {})
            prologue = IfThenElse(
                ko == 0,
                SeqStmt([_keep_loc(AsyncCopyStmt(s.src, slot_cur, sem,
                                                 lead, "start"), s)]))
            prefetch = IfThenElse(
                ko + 1 < n,
                SeqStmt([_keep_loc(AsyncCopyStmt(src_next, slot_nxt, sem,
                                                 nxt, "start"), s)]))
            wait = _keep_loc(AsyncCopyStmt(s.src, slot_cur, sem, lead,
                                           "wait"), s)
            copy_repl[ci] = [_keep_loc(prologue, s),
                             _keep_loc(prefetch, s), wait]
            buf_sub[dstb.uid] = (dst2, lead, None)
            res.dbuf_chains += 1
            res.rewrites.append(
                f"dbuf: double-buffered '{dstb.name}' "
                f"({_fmt_shape(shape)} {dstb.dtype}) HBM stream in serial "
                f"loop {ko.name} — prefetch ko+1 overlaps compute on ko "
                f"(2 slots, rotated semaphore)")
        if not buf_sub:
            return None
        # the original allocs of the re-slotted buffers die with them
        for s, _c in iter_stmts(body):
            if isinstance(s, AllocStmt) and s.buffer.uid in buf_sub:
                drop_allocs.add(id(s))
        new_children: List[Stmt] = []
        for ci, child in enumerate(children):
            if ci in copy_repl:
                new_children.extend(copy_repl[ci])
            else:
                new_children.append(_rw_stmt(child, {}, buf_sub))
        return new_allocs, _keep_loc(
            ForNest(loop.loop_vars, loop.extents, loop.kind,
                    SeqStmt(new_children), loop.num_stages,
                    dict(loop.annotations)), loop)

    def rebuild(stmts) -> List[Stmt]:
        out: List[Stmt] = []
        for s in stmts:
            if isinstance(s, ForNest):
                hit = try_loop(s)
                if hit is not None:
                    allocs, newl = hit
                    out.extend(allocs)
                    out.append(newl)
                    continue
                nb = rebuild(s.body.stmts)
                if nb != list(s.body.stmts):
                    s = _keep_loc(ForNest(s.loop_vars, s.extents, s.kind,
                                          SeqStmt(nb), s.num_stages,
                                          dict(s.annotations)), s)
            elif isinstance(s, KernelNode):
                nb = rebuild(s.body.stmts)
                if nb != list(s.body.stmts):
                    s = _keep_loc(KernelNode(s.grid_vars, s.extents,
                                             s.threads, SeqStmt(nb),
                                             prelude=list(s.prelude)), s)
            elif isinstance(s, IfThenElse):
                then = rebuild(s.then_body.stmts)
                els = rebuild(s.else_body.stmts) \
                    if s.else_body is not None else None
                if then != list(s.then_body.stmts) or \
                        (s.else_body is not None and
                         els != list(s.else_body.stmts)):
                    s = _keep_loc(IfThenElse(
                        s.cond, SeqStmt(then),
                        SeqStmt(els) if els is not None else None), s)
            elif isinstance(s, SeqStmt):
                nb = rebuild(s.stmts)
                if nb != list(s.stmts):
                    s = _keep_loc(SeqStmt(nb), s)
            out.append(s)
        return out

    new_body = SeqStmt(rebuild(body.stmts))
    if drop_allocs:
        new_body = SeqStmt(_drop_stmts(new_body.stmts, drop_allocs))
    return new_body


def _fmt_shape(shape) -> str:
    return "(" + ", ".join(str(s) for s in shape) + ")"


# ---------------------------------------------------------------------------
# fuse — affine fusion of adjacent identical-space T.Parallel regions
# ---------------------------------------------------------------------------


def _positional_forms(indices, loop_vars) -> Optional[list]:
    """Per-dimension affine forms with loop-var coefficients keyed by
    POSITION in ``loop_vars`` (so forms from two different nests compare
    directly), or None when any dimension is unanalyzable."""
    from ..analysis.regions import access_affine
    forms = access_affine(indices, loop_vars)
    if forms is None:
        return None
    pos_of = {id(v): i for i, v in enumerate(loop_vars)}
    out = []
    for coeffs, ambient, const in forms:
        vec = [0] * len(loop_vars)
        for vid, c in coeffs.items():
            vec[pos_of[vid]] = c
        out.append((tuple(vec), ambient, const))
    return out


def _forms_injective(forms, exts) -> bool:
    """Sufficient injectivity proof over the iteration box: every
    extent>1 var owns at least one dimension alone (single-var affine
    dim with non-zero coefficient) — two iterations differing in that
    var provably differ in that dimension."""
    for pos, ext in enumerate(exts):
        if ext is None or ext <= 1:
            continue
        owned = any(
            vec[pos] != 0 and all(c == 0 for i, c in enumerate(vec)
                                  if i != pos)
            for vec, _amb, _k in forms)
        if not owned:
            return False
    return True


def _fusable(n1: ForNest, n2: ForNest) -> bool:
    from ..analysis.dataflow import stmt_accesses
    if n1.kind != "parallel" or n2.kind != "parallel":
        return False
    if len(n1.loop_vars) != len(n2.loop_vars):
        return False
    e1 = [as_int(e) for e in n1.extents]
    e2 = [as_int(e) for e in n2.extents]
    if e1 != e2 or any(e is None or e < 1 for e in e1):
        return False
    # only simple elementwise bodies (no nested control flow — guards
    # would weaken the iteration-space identity the proof relies on)
    for nest in (n1, n2):
        for st in nest.body.stmts:
            if not isinstance(st, (BufferStoreStmt, EvaluateStmt)):
                return False
    acc1 = [a for st in n1.body.stmts for a in stmt_accesses(st)]
    acc2 = [a for st in n2.body.stmts for a in stmt_accesses(st)]
    touched1 = {a.buffer.uid for a in acc1}
    touched2 = {a.buffer.uid for a in acc2}
    written = {a.buffer.uid for a in acc1 + acc2 if a.kind == "write"}
    shared = (touched1 & touched2) & written
    if not shared:
        return True
    # TL001's machinery as the dependency oracle: on every shared
    # written buffer, all cross-nest access pairs must be affine with
    # IDENTICAL positional forms (iteration i talks only to iteration
    # i), and every write must be injective over the extent>1 vars
    # (no two iterations alias one element).
    for uid in sorted(shared):
        f1 = [(_positional_forms(a.indices, n1.loop_vars), a.kind)
              for a in acc1 if a.buffer.uid == uid]
        f2 = [(_positional_forms(a.indices, n2.loop_vars), a.kind)
              for a in acc2 if a.buffer.uid == uid]
        for forms, _k in f1 + f2:
            if forms is None:
                return False
        for forms1, k1 in f1:
            for forms2, k2 in f2:
                if k1 != "write" and k2 != "write":
                    continue
                if forms1 != forms2:
                    return False
        for forms, k in f1 + f2:
            if k == "write" and not _forms_injective(forms, e1):
                return False
    return True


def _fuse_pair(n1: ForNest, n2: ForNest) -> ForNest:
    vm = {id(v2): v1 for v1, v2 in zip(n1.loop_vars, n2.loop_vars)}
    body2 = [_rw_stmt(st, vm, {}) for st in n2.body.stmts]
    return _keep_loc(ForNest(
        n1.loop_vars, n1.extents, "parallel",
        SeqStmt(list(n1.body.stmts) + body2),
        0, {**n2.annotations, **n1.annotations}), n1)


#: how many already-emitted siblings the interleaved-fusion scan looks
#: back across when the immediately preceding statement is not a
#: fusable nest.  Bounded so the disjointness proof obligation (and the
#: hoisting distance) stays small and reviewable.
_FUSE_LOOKBACK = 8


def _hoist_disjoint(stmt: Stmt, nest: ForNest) -> bool:
    """May ``nest`` legally hop over ``stmt`` (so it can fuse with an
    earlier sibling)?  Only when ``stmt``'s whole subtree is free of
    ordering-sensitive effects (DMA, collectives, prints, asserts,
    atomics) and shares NO buffer — accessed or allocated — with the
    nest's body: TL001's access model proves the two command streams
    commute.  Module-level so the mutation sweep can corrupt it."""
    from ..analysis.dataflow import iter_stmts, stmt_accesses
    nest_uids = {a.buffer.uid for st in nest.body.stmts
                 for a in stmt_accesses(st)}
    for s, _c in iter_stmts([stmt]):
        if isinstance(s, (AsyncCopyStmt, CommStmt, PrintStmt,
                          AssertStmt, AtomicStmt)):
            return False
        if isinstance(s, AllocStmt):
            if s.buffer.uid in nest_uids:
                return False
            continue
        for a in stmt_accesses(s):
            if a.buffer.uid in nest_uids:
                return False
    return True


def _fuse(body: SeqStmt, res: TileOptResult) -> SeqStmt:
    def rebuild(stmts) -> List[Stmt]:
        kids: List[Stmt] = []
        for s in stmts:
            if isinstance(s, KernelNode):
                nb = rebuild(s.body.stmts)
                if nb != list(s.body.stmts):
                    s = _keep_loc(KernelNode(s.grid_vars, s.extents,
                                             s.threads, SeqStmt(nb),
                                             prelude=list(s.prelude)), s)
            elif isinstance(s, ForNest):
                nb = rebuild(s.body.stmts)
                if nb != list(s.body.stmts):
                    s = _keep_loc(ForNest(s.loop_vars, s.extents, s.kind,
                                          SeqStmt(nb), s.num_stages,
                                          dict(s.annotations)), s)
            elif isinstance(s, IfThenElse):
                then = rebuild(s.then_body.stmts)
                els = rebuild(s.else_body.stmts) \
                    if s.else_body is not None else None
                if then != list(s.then_body.stmts) or \
                        (s.else_body is not None and
                         els != list(s.else_body.stmts)):
                    s = _keep_loc(IfThenElse(
                        s.cond, SeqStmt(then),
                        SeqStmt(els) if els is not None else None), s)
            elif isinstance(s, SeqStmt):
                nb = rebuild(s.stmts)
                if nb != list(s.stmts):
                    s = _keep_loc(SeqStmt(nb), s)
            out_merge(kids, s)
        return kids

    def out_merge(kids: List[Stmt], s: Stmt) -> None:
        if isinstance(s, ForNest):
            # scan back over already-emitted siblings: the adjacent
            # case (back == 1) is PR 11's original fusion; deeper hits
            # are interleaved fusion, legal only while every hopped
            # statement is proven disjoint from this nest
            for back in range(1, min(len(kids), _FUSE_LOOKBACK) + 1):
                cand = kids[-back]
                if isinstance(cand, ForNest) and _fusable(cand, s):
                    exts = [as_int(e) for e in cand.extents]
                    kids[-back] = _fuse_pair(cand, s)
                    res.fuse_regions += 1
                    if back > 1:
                        res.fuse_interleaved += 1
                        res.rewrites.append(
                            f"fuse: merged T.Parallel{_fmt_shape(exts)} "
                            f"regions interleaved across {back - 1} "
                            f"disjoint statement(s) (all hopped "
                            f"statements touch provably unrelated "
                            f"buffers; one vectorized sweep)")
                    else:
                        res.rewrites.append(
                            f"fuse: merged adjacent "
                            f"T.Parallel{_fmt_shape(exts)} regions (no "
                            f"cross-region dependency; one vectorized "
                            f"sweep)")
                    return
                if not _hoist_disjoint(cand, s):
                    break
        kids.append(s)

    return SeqStmt(rebuild(body.stmts))


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def run_tile_opt(func: PrimFunc, pass_cfg: Optional[dict] = None,
                 findings: Optional[list] = None, *,
                 modes_override: Optional[tuple] = None,
                 _metrics: bool = True):
    """Run the enabled rewrites over one kernel.

    Returns ``(func, result, findings)``: the (possibly rebuilt)
    PrimFunc, the :class:`TileOptResult` accounting, and the lint
    findings with auto-fixed TL006 entries consumed (they are reported
    through the ``tile_opt[...]`` block instead — the finding is fixed,
    not worth a second warning).

    ``modes_override`` lets the ``auto`` scheduler in engine/lower run
    candidate subsets without round-tripping through pass-config
    strings; candidate probes pass ``_metrics=False`` so only the one
    authoritative lowering lands in the tracer counters."""
    from ..observability import tracer as _trace
    findings = list(findings or [])
    modes = tuple(modes_override) if modes_override is not None \
        else tile_opt_modes(pass_cfg)
    res = TileOptResult(modes=modes)
    if not modes or "auto" in modes:
        # "auto" is resolved by engine/lower's cost-model scheduler,
        # which re-enters with an explicit modes_override
        return func, res, findings

    body = func.body if isinstance(func.body, SeqStmt) \
        else SeqStmt(list(func.body))
    new_body = body
    if "dse" in modes:
        new_body = _dse(new_body, res)
    if "narrow" in modes:
        new_body = _narrow(func, new_body, res, pass_cfg)
    if "repack" in modes:
        new_body = _repack(new_body, res)
    if "dbuf" in modes:
        new_body = _dbuf(new_body, res)
    if "fuse" in modes:
        new_body = _fuse(new_body, res)

    if not res.rewrites:
        return func, res, findings

    if _metrics:
        _trace.inc("opt.kernels")
        for mode, n in (("dse", res.dse_allocs),
                        ("narrow", res.narrow_buffers),
                        ("repack", res.repack_buffers),
                        ("dbuf", res.dbuf_chains),
                        ("fuse", res.fuse_regions)):
            if n:
                _trace.inc("opt.rewrites", n, mode=mode)
        if res.dse_stores:
            _trace.inc("opt.dse.stores", res.dse_stores)
        if res.dse_allocs:
            _trace.inc("opt.dse.allocs", res.dse_allocs)
        if res.dse_bytes:
            _trace.inc("opt.dse.bytes", res.dse_bytes)
        if res.narrow_buffers:
            _trace.inc("opt.narrow.bytes_saved", res.narrow_bytes)
        if res.repack_buffers:
            _trace.inc("opt.repack.bytes_saved",
                       res.repack_pre_bytes - res.repack_post_bytes)
        if res.repack_compat:
            _trace.inc("opt.repack.compat", res.repack_compat)
        if res.dbuf_chains:
            _trace.inc("opt.dbuf.chains", res.dbuf_chains)
        if res.fuse_regions:
            _trace.inc("opt.fuse.regions", res.fuse_regions)
        if res.fuse_interleaved:
            _trace.inc("opt.fuse.interleaved", res.fuse_interleaved)

    new_func = PrimFunc(func.name, func.params, new_body,
                        dict(func.attrs))
    fixed = {e["buffer"] for e in res.eliminated}
    findings = [d for d in findings
                if not (d.rule == "TL006" and d.buffer in fixed)]
    if not _metrics:
        return new_func, res, findings
    for e in res.eliminated:
        # bytes here are padded VMEM footprint; comm_opt's dce rows
        # carry ICI wire bytes — the shared counter is labelled by
        # source so the two units are never summed into one scalar
        _trace.inc("opt.eliminated.bytes", e["bytes"], source="tile_opt")
        _trace.event("opt.eliminated", "lower", source="tile_opt",
                     kernel=func.name, **e)
    return new_func, res, findings
