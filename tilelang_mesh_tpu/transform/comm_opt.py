"""Cost-model-driven mesh collective optimizer.

Runs between segmentation and codegen in ``parallel/lowering.lower_mesh``,
rewriting the segment list the way the reference's comm IR passes rewrite
its NoC schedules (src/op/comm.cc): the compiler — not program order —
decides what crosses the ICI and when. Three rewrites, individually
selectable through ``TL_TPU_COMM_OPT`` (see docs/mesh_comm_opt.md):

``fuse``
    Adjacent collectives of the same kind on the same mesh axis are
    batched into one :class:`~..ir.CommFused` op over their concatenated
    payloads (one XLA collective, one synchronization, one per-hop setup
    cost instead of N).  Byte-identical members share a payload *slot* —
    each distinct payload crosses the wire once and fans out to every
    member destination — and fully identical idempotent duplicates are
    dropped outright.

``dce``
    A payload-bearing collective whose written buffers are never read by
    a later segment and never reach a kernel output is deleted; compute
    segments left adjacent by the deletion are merged back into one
    Pallas kernel.

``overlap``
    A large ``all_gather``/``all_reduce`` feeding a later compute segment
    is split into K equal leading-axis chunks (:class:`~..ir.CommChunked`)
    issued as independent collectives, so the ICI transfer of chunk i+1
    can overlap the consumer's compute on chunk i — the double-buffered
    ring schedule, chosen only when the cost model says the wire time is
    worth pipelining (wire bytes >= ``tl.tpu.comm_chunk_bytes``).

Every decision is deterministic (program order + canonical keys that
include the collective's kind, mesh axis/direction, and operand
identity — never dict iteration order) and is recorded both in
``CompiledArtifact.plan_desc`` (golden-testable) and in the artifact's
``attrs["comm_opt"]`` accounting consumed by ``analyzer trace`` and
``metrics_summary()``.

The optimizer does not check its own work: the rewritten schedule is
independently re-verified before codegen by ``verify/schedule.py``
(deadlock freedom, slot agreement, overlap races, aliasing, wire-byte
conservation — ``TL_TPU_VERIFY``, default on), and at runtime the
``TL_TPU_SELFCHECK=1`` differential check diffs the optimized program's
first call against the ``TL_TPU_COMM_OPT=0`` schedule.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence, Set, Tuple

from ..ir import (CommAllGather, CommAllReduce, CommBroadcast, CommChunked,
                  CommFused, CommPut, CommStmt, Region)

# rewrites in canonical order (plan_desc / attrs always print this order)
MODES = ("fuse", "dce", "overlap")

# reduce types the fused/chunked all_reduce paths can realize with one
# jax psum/pmax/pmin over a concatenated or split payload; the bit ops
# take the gather+local-combine path and are left unrewritten. Public:
# the schedule verifier (verify/schedule.py) keys its "is this op
# chunkable at all" rule on the same vocabulary, so the two can never
# disagree about which collectives the overlap rewrite may touch.
PSUMMABLE = ("sum", "abssum", "max", "absmax", "min")
_PSUMMABLE = PSUMMABLE   # pre-verifier spelling, kept for callers


def comm_opt_modes(pass_cfg: Optional[dict] = None) -> Tuple[str, ...]:
    """Active rewrite set: ``tl.tpu.comm_opt`` pass config when present,
    else the ``TL_TPU_COMM_OPT`` env var.  "1"/"on"/"all" enables every
    rewrite, "0"/"off" disables the pass, and a comma list selects a
    subset (e.g. ``fuse,dce`` for debugging the overlap rewrite)."""
    raw: Any = None
    if pass_cfg:
        raw = pass_cfg.get("tl.tpu.comm_opt")
    if raw is None:
        from ..env import env
        raw = env.TL_TPU_COMM_OPT
    from .pass_config import parse_mode_set
    return parse_mode_set(raw, MODES, "TL_TPU_COMM_OPT")


@dataclass
class CommOptResult:
    """Outcome of one optimizer run over a segment list."""
    segments: List[Tuple[str, Any]]
    modes: Tuple[str, ...]
    pre_wire_bytes: int = 0
    post_wire_bytes: int = 0
    pre_hops: int = 0
    post_hops: int = 0
    rewrites: List[str] = field(default_factory=list)
    #: dce accounting in the SAME {op, buffer, bytes} record shape the
    #: tile-opt dse pass emits (transform/tile_opt.py), so ``analyzer
    #: trace`` renders one unified "eliminated" table for both
    eliminated: List[dict] = field(default_factory=list)

    @property
    def hops_saved(self) -> int:
        return max(0, self.pre_hops - self.post_hops)

    def attrs_record(self) -> dict:
        """JSON-safe accounting for CompiledArtifact.attrs['comm_opt']."""
        return {
            "modes": list(self.modes),
            "pre_wire_bytes": self.pre_wire_bytes,
            "post_wire_bytes": self.post_wire_bytes,
            "pre_hops": self.pre_hops,
            "post_hops": self.post_hops,
            "hops_saved": self.hops_saved,
            "rewrites": list(self.rewrites),
            "eliminated": [dict(e) for e in self.eliminated],
        }


# ---------------------------------------------------------------------------
# canonical keys — deterministic, and always including the collective's
# kind and mesh direction/axis so grouping can never depend on dict
# iteration order
# ---------------------------------------------------------------------------


def _region_key(r: Region) -> tuple:
    return (r.buffer.uid, tuple(str(b) for b in r.base),
            tuple(str(s) for s in r.shape))


def _fuse_key(c: CommStmt) -> Optional[tuple]:
    """Grouping key for the fusion rewrite: ops with equal keys are
    batchable into one mesh collective. None = never fused."""
    if isinstance(c, CommBroadcast):
        return ("broadcast", c.direction, c.src_core, c.src.dtype)
    if isinstance(c, CommAllGather):
        return ("all_gather", c.direction, c.send.dtype)
    if isinstance(c, CommAllReduce) and c.reduce_type in _PSUMMABLE:
        return ("all_reduce", c.direction, c.reduce_type, c.buffer.dtype)
    return None


def _slot_key(c: CommStmt) -> tuple:
    """Payload identity inside a fused group: members with equal slot
    keys move byte-identical data and share one wire transfer. The
    payload bytes the DSL recorded at emission (``emit_meta``,
    language/comm.py) fold into the key as defense in depth — two ops
    can only share a slot when the frontend also agrees on their size."""
    meta = getattr(c, "emit_meta", None)
    nbytes = meta.get("payload_bytes") if meta else None
    if isinstance(c, CommBroadcast):
        return ("broadcast", _region_key(c.src), c.size, nbytes)
    if isinstance(c, CommAllGather):
        return ("all_gather", _region_key(c.send), c.size, nbytes)
    # all_reduce payload = the locally-reduced buffer
    return ("all_reduce", _region_key(c.buffer), c.reduce_type, c.dim,
            nbytes)


def _dup_key(c: CommStmt) -> Optional[tuple]:
    """Full identity of an IDEMPOTENT collective (payload + destination
    + semantics): a later op with the same key recomputes exactly the
    same destination bytes and can be dropped.  Non-idempotent ops
    (all_reduce clear=False accumulates into dst) return None."""
    if isinstance(c, CommBroadcast):
        return ("broadcast", _slot_key(c), _region_key(c.dst),
                c.dst_offset, c.src_core, c.direction)
    if isinstance(c, CommAllGather):
        return ("all_gather", _slot_key(c), _region_key(c.recv),
                c.direction)
    if isinstance(c, CommAllReduce) and c.clear:
        return ("all_reduce", _slot_key(c), _region_key(c.out),
                c.direction, c.clear)
    return None


def _rw_uids(c: CommStmt) -> Tuple[Set[int], Set[int]]:
    """(read uids, written uids) of one collective."""
    from ..parallel.lowering import _comm_buffers
    r, w = _comm_buffers(c)
    return ({x.buffer.uid for x in r}, {x.buffer.uid for x in w})


def _payload_bearing(c: CommStmt) -> bool:
    return isinstance(c, (CommBroadcast, CommPut, CommAllGather,
                          CommAllReduce))


# ---------------------------------------------------------------------------
# the three rewrites
# ---------------------------------------------------------------------------


def _eliminate_dead(segments, seg_rw, global_out_uids, desc_fn, rewrites,
                    cost_fn=None, eliminated=None):
    """Drop collectives whose results never reach a later read or a
    kernel output, then merge the compute segments left adjacent."""
    n = len(segments)
    keep = [True] * n
    for i, (kind, payload) in enumerate(segments):
        if kind != "comm" or not _payload_bearing(payload):
            continue
        _, writes = _rw_uids(payload)
        if not writes:
            continue
        live = False
        for w in sorted(writes):
            if w in global_out_uids:
                live = True
                break
            if any(w in seg_rw[j][0] for j in range(i + 1, n)):
                live = True
                break
        if not live:
            keep[i] = False
            rewrites.append(f"dce: dropped dead {desc_fn(payload)}")
            if eliminated is not None:
                from ..parallel.lowering import _comm_buffers
                _r, wregs = _comm_buffers(payload)
                hops, per_hop = cost_fn(payload) if cost_fn else (0, 0)
                eliminated.append({
                    "op": type(payload).__name__,
                    "buffer": ",".join(sorted(
                        x.buffer.name for x in wregs)),
                    "bytes": hops * per_hop,
                })
    out: List[Tuple[str, Any]] = []
    for i, seg in enumerate(segments):
        if not keep[i]:
            continue
        if (seg[0] == "compute" and out and out[-1][0] == "compute"):
            # the collective between them is gone: one kernel again
            out[-1] = ("compute", list(out[-1][1]) + list(seg[1]))
            rewrites.append("dce: merged adjacent compute segments")
            continue
        out.append(seg)
    return out


def _fuse_run(run: List[CommStmt], desc_fn, rewrites) -> List[CommStmt]:
    """Fuse one maximal run of adjacent payload-bearing collectives.
    Scans in program order, batching while the fuse key holds and the
    members stay data-independent; byte-identical idempotent duplicates
    are dropped, identical payloads to distinct destinations share a
    payload slot."""
    out: List[CommStmt] = []
    i = 0
    while i < len(run):
        head = run[i]
        key = _fuse_key(head)
        if key is None:
            out.append(head)
            i += 1
            continue
        members: List[CommStmt] = [head]
        slots: List[int] = [0]
        slot_keys: List[tuple] = [_slot_key(head)]
        dup_keys = {_dup_key(head)} - {None}
        dropped: List[CommStmt] = []
        reads0, writes0 = _rw_uids(head)
        grp_reads, grp_writes = set(reads0), set(writes0)
        j = i + 1
        while j < len(run) and _fuse_key(run[j]) == key:
            cand = run[j]
            dk = _dup_key(cand)
            if dk is not None and dk in dup_keys:
                dropped.append(cand)
                rewrites.append(
                    f"fuse: dropped duplicate {desc_fn(cand)}")
                j += 1
                continue
            creads, cwrites = _rw_uids(cand)
            # batching reorders members into ONE simultaneous op: a
            # member may not read what an earlier member writes, nor
            # overwrite anything the group already touches
            if (creads & grp_writes) or (cwrites & grp_writes) \
                    or (cwrites & grp_reads):
                break
            sk = _slot_key(cand)
            slots.append(slot_keys.index(sk) if sk in slot_keys
                         else len(slot_keys))
            if sk not in slot_keys:
                slot_keys.append(sk)
            members.append(cand)
            if dk is not None:
                dup_keys.add(dk)
            grp_reads |= creads
            grp_writes |= cwrites
            j += 1
        if len(members) >= 2 or dropped:
            # a single survivor still becomes a (1-member) fused op when
            # duplicates were dropped, so its record can carry the
            # pre-optimization wire bytes of the ops it replaced
            fused = CommFused(members, slots, dropped=dropped)
            out.append(fused)
            if len(members) >= 2:
                shared = len(members) - len(set(slots))
                rewrites.append(
                    f"fuse: {len(members)}x {desc_fn(members[0])} -> 1 "
                    f"batched op"
                    + (f" ({shared} shared payload slot"
                       f"{'s' if shared > 1 else ''})" if shared else ""))
        else:
            out.append(members[0])
        i = j
    return out


def _fuse_collectives(segments, desc_fn, rewrites):
    """Batch adjacent same-key collectives across the whole segment
    list. Barriers, fences and compute segments bound the runs."""
    out: List[Tuple[str, Any]] = []
    run: List[CommStmt] = []

    def flush():
        for op in _fuse_run(run, desc_fn, rewrites):
            out.append(("comm", op))
        run.clear()

    for kind, payload in segments:
        if kind == "comm" and _payload_bearing(payload):
            run.append(payload)
            continue
        flush()
        out.append((kind, payload))
    flush()
    return out


def _chunk_candidates(c: CommStmt):
    """(chunk-axis extent, written uid) when the overlap rewrite knows
    how to split this collective, else None."""
    if isinstance(c, CommAllGather):
        shape = c.send.static_shape()
        if shape:
            return shape[0], c.recv.buffer.uid
    elif isinstance(c, CommAllReduce) and c.reduce_type in _PSUMMABLE:
        shape = c.out.static_shape()
        if shape:
            return shape[0], c.out.buffer.uid
    return None


def _overlap_chunks(segments, cost_fn, desc_fn, pass_cfg, rewrites):
    """Split large collectives that feed a later compute segment into K
    pipelined chunks (double-buffered ring-style schedule)."""
    from ..env import env
    min_bytes = int(pass_cfg.get("tl.tpu.comm_chunk_bytes",
                                 env.TL_TPU_COMM_CHUNK_BYTES))
    want_k = int(pass_cfg.get("tl.tpu.comm_chunks", env.TL_TPU_COMM_CHUNKS))
    if want_k < 2:
        return segments
    out = list(segments)
    for i, (kind, payload) in enumerate(out):
        if kind != "comm":
            continue
        cand = _chunk_candidates(payload)
        if cand is None:
            continue
        extent, out_uid = cand
        hops, per_hop = cost_fn(payload)
        if hops * per_hop < min_bytes:
            continue
        # a consumer compute segment must read the result before anything
        # else overwrites it — otherwise there is nothing to overlap with
        consumer = None
        for j in range(i + 1, len(out)):
            jkind, jpayload = out[j]
            if jkind == "compute":
                from ..parallel.lowering import _buffer_reads_writes
                reads, writes = _buffer_reads_writes(jpayload)
                if out_uid in reads:
                    consumer = j
                    break
                if out_uid in writes:
                    break
            else:
                creads, cwrites = _rw_uids(jpayload)
                if out_uid in creads or out_uid in cwrites:
                    break
        if consumer is None:
            continue
        k = next((kk for kk in range(min(want_k, extent), 1, -1)
                  if extent % kk == 0), None)
        if k is None:
            continue
        out[i] = ("comm", CommChunked(payload, k))
        rewrites.append(
            f"overlap: {desc_fn(payload)} -> {k} pipelined chunks "
            f"({hops * per_hop}B wire over segment [{consumer}]'s "
            f"compute)")
    return out


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def optimize_collectives(segments: Sequence[Tuple[str, Any]],
                         seg_rw: Sequence[Tuple[set, set]],
                         global_out_uids: Set[int],
                         nrow: int, ncol: int,
                         modes: Sequence[str],
                         pass_cfg: Optional[dict] = None) -> CommOptResult:
    """Run the enabled rewrites over a lower_mesh segment list.

    ``seg_rw`` is the caller's per-segment (reads, writes) liveness for
    the INPUT segments (the dce rewrite consumes it); ``global_out_uids``
    are the kernel's global param buffers (collective results reaching
    them are always live)."""
    from ..parallel.lowering import _comm_desc, comm_cost
    pass_cfg = pass_cfg or {}
    modes = tuple(m for m in MODES if m in modes)

    def cost_fn(c):
        return comm_cost(c, nrow, ncol)

    def desc_fn(c):
        return _comm_desc(c, nrow, ncol)

    def wire(segs) -> Tuple[int, int]:
        total, hops_total = 0, 0
        for kind, payload in segs:
            if kind != "comm":
                continue
            hops, per_hop = cost_fn(payload)
            if per_hop:
                total += hops * per_hop
                hops_total += hops
        return total, hops_total

    from ..parallel.lowering import segments_rw as seg_rw_of

    res = CommOptResult(segments=list(segments), modes=modes)
    res.pre_wire_bytes, res.pre_hops = wire(segments)
    segs = list(segments)
    if "dce" in modes:
        # to fixpoint: dropping a dead collective can strand the reads
        # that kept an EARLIER collective alive (a dead chain), so
        # liveness is recomputed until a pass deletes nothing
        rw = seg_rw
        while True:
            dropped_before = sum(1 for r in res.rewrites
                                 if r.startswith("dce: dropped"))
            segs = _eliminate_dead(segs, rw, global_out_uids,
                                   desc_fn, res.rewrites,
                                   cost_fn=cost_fn,
                                   eliminated=res.eliminated)
            if sum(1 for r in res.rewrites
                   if r.startswith("dce: dropped")) == dropped_before:
                break
            rw = seg_rw_of(segs)
    if "fuse" in modes:
        segs = _fuse_collectives(segs, desc_fn, res.rewrites)
    if "overlap" in modes:
        segs = _overlap_chunks(segs, cost_fn, desc_fn, pass_cfg,
                               res.rewrites)
    res.segments = segs
    res.post_wire_bytes, res.post_hops = wire(segs)
    return res
