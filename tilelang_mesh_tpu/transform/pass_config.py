"""Per-compile pass configuration.

Reference: /root/reference/tilelang/transform/pass_config.py (PassConfigKey,
~30 tl.* keys threaded through PassContext). TPU-relevant keys are live; the
GPU-only ones are accepted-and-ignored so reference-style call sites port
without edits.
"""

from __future__ import annotations

import contextlib
import threading
from enum import Enum
from typing import Any, Dict, Optional


class PassConfigKey(str, Enum):
    # live on TPU
    TL_SIMPLIFY = "tl.Simplify"
    TL_DYNAMIC_ALIGNMENT = "tl.dynamic_alignment"
    TL_DISABLE_DYNAMIC_TAIL_SPLIT = "tl.disable_dynamic_tail_split"
    TL_DISABLE_SAFE_MEMORY_ACCESS = "tl.disable_safe_memory_legalize"
    TL_DEBUG_MERGE_SHARED_MEMORY_ALLOCATIONS = \
        "tl.debug_merge_shared_memory_allocations"
    TL_ENABLE_FAST_MATH = "tl.enable_fast_math"
    TL_DISABLE_FAST_MATH = "tl.disable_fast_math"
    TL_LAYOUT_VISUAL = "tl.layout_visual"
    # TPU-specific
    TL_TPU_DIMENSION_SEMANTICS = "tl.tpu.dimension_semantics"
    TL_TPU_VMEM_LIMIT_BYTES = "tl.tpu.vmem_limit_bytes"
    TL_TPU_INTERPRET = "tl.tpu.interpret"
    TL_TPU_COST_ESTIMATE = "tl.tpu.cost_estimate"
    TL_TPU_ALLOW_INPUT_FUSION = "tl.tpu.allow_input_fusion"
    # mesh collective optimizer (transform/comm_opt.py): rewrite set
    # ("1"/"0"/comma list of fuse,dce,overlap — overrides
    # TL_TPU_COMM_OPT), overlap chunking threshold and chunk count
    TL_TPU_COMM_OPT = "tl.tpu.comm_opt"
    TL_TPU_COMM_CHUNK_BYTES = "tl.tpu.comm_chunk_bytes"
    TL_TPU_COMM_CHUNKS = "tl.tpu.comm_chunks"
    # tile-IR optimizer (transform/tile_opt.py): rewrite set ("1"/"0"/
    # comma list of dse,repack,dbuf,fuse — overrides TL_TPU_TILE_OPT)
    TL_TPU_TILE_OPT = "tl.tpu.tile_opt"
    # mesh schedule verifier (verify/schedule.py): "1"/"on" (default),
    # "0"/"off", or "strict" — overrides TL_TPU_VERIFY
    TL_TPU_VERIFY = "tl.tpu.verify"
    # tl-num numerical-safety analysis (analysis/numerics.py): nominal
    # |input| magnitude assumption of the warning track / finiteness
    # proofs, and the TL008 accumulated-relative-error threshold
    TL_TPU_NUM_ASSUME_ABS = "tl.tpu.num_assume_abs"
    TL_TPU_NUM_ERR_THRESHOLD = "tl.tpu.num_err_threshold"
    # accepted for API parity, no TPU effect
    TL_DISABLE_TMA_LOWER = "tl.disable_tma_lower"
    TL_DISABLE_WARP_SPECIALIZED = "tl.disable_warp_specialized"
    TL_CONFIG_INDEX_BITWIDTH = "tl.config_index_bitwidth"
    TL_DISABLE_VECTORIZE_256 = "tl.disable_vectorize_256"
    TL_ENABLE_AGGRESSIVE_SHARED_MEMORY_MERGE = \
        "tl.enable_aggressive_shared_memory_merge"
    TL_ENABLE_PTXAS_VERBOSE_OUTPUT = "tl.enable_ptxas_verbose_output"


def parse_mode_set(raw, valid, knob: str):
    """The ONE rewrite-set knob grammar shared by TL_TPU_COMM_OPT and
    TL_TPU_TILE_OPT (comm_opt_modes / tile_opt_modes delegate here):
    "1"/"on"/"all" = every mode, "0"/"off" = none, or a comma (or +)
    subset of ``valid``. A typo'd token raises instead of silently
    disabling an optimizer."""
    raw = str(raw).strip().lower()
    if raw in ("1", "on", "true", "all", "yes", ""):
        return tuple(valid)
    if raw in ("0", "off", "false", "none", "no"):
        return ()
    picked = {m.strip() for m in raw.replace("+", ",").split(",")
              if m.strip()}
    unknown = picked - set(valid)
    if unknown:
        raise ValueError(
            f"unknown {knob} mode(s) {sorted(unknown)}; valid "
            f"tokens are {list(valid)}, or 1/0 for all/none")
    return tuple(m for m in valid if m in picked)


_STATE = threading.local()


def _stack():
    if not hasattr(_STATE, "stack"):
        _STATE.stack = [{}]
    return _STATE.stack


def current_pass_config() -> Dict[str, Any]:
    merged: Dict[str, Any] = {}
    for d in _stack():
        merged.update(d)
    return merged


@contextlib.contextmanager
def pass_config(cfg: Optional[Dict[Any, Any]] = None, **kwargs):
    d = {}
    for k, v in {**(cfg or {}), **kwargs}.items():
        d[k.value if isinstance(k, PassConfigKey) else str(k)] = v
    _stack().append(d)
    try:
        yield
    finally:
        _stack().pop()
