"""Transform passes over tile-IR.

The reference's 56-file C++ pass pipeline (src/transform/) collapses on TPU:
Mosaic/XLA own vectorization, memory planning, and synchronization. What
remains semantic — block-mapping inference, pipeline planning, phase
splitting — lives in plan.py; mesh SPMD splitting in parallel/lowering.py.
"""

from .pass_config import PassConfigKey, pass_config, current_pass_config
from .plan import plan_kernel, KernelPlan, PlanError
from .comm_opt import (CommOptResult, comm_opt_modes, optimize_collectives)
from .tile_opt import TileOptResult, run_tile_opt, tile_opt_modes
