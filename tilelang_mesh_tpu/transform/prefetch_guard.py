"""Conditional prefetch redirection analysis.

The trick jax's flash-attention kernel hand-codes in its kv_index_map,
derived here automatically: a block param whose every main-phase read sits
under an IfThenElse over grid vars gets, for index dims driven by the
pipeline axis, ``where(cond, idx, 0)`` — on skipped grid steps the Pallas
pipeline re-requests a block it would fetch anyway instead of streaming one
nobody reads (causal attention skips ~half the KV stream this way).

Pure inputs only: an inout param is aliased into both in_specs and
out_specs, and redirecting only its input index_map would write block-0
data back over untouched blocks on skipped steps (round-2 advisor finding).

Analysis lives here, printing lives in codegen/pallas.py — matching the
reference's pass/codegen separation (layout_inference.cc vs
codegen_cuda.cc).
"""

from __future__ import annotations

from typing import Any, Dict

from ..ir import (AtomicStmt, Buffer, BufferStoreStmt, GemmStmt, IfThenElse,
                  PrintStmt, ReduceStmt, Region, Stmt, for_each_load,
                  free_vars, walk)


def param_guards(plan) -> Dict[int, Any]:
    """Return uid -> guard condition expr for block params whose main-phase
    reads are all under one grid-var IfThenElse involving the pipeline
    axis."""
    pa = plan.pipeline_axis
    if pa is None:
        return {}
    grid_ids = {id(a.var) for a in plan.grid}
    pa_var = plan.grid[pa].var

    def reads_of(stmts):
        seen = set()

        def chk(x):
            for attr in ("src", "A", "B"):
                r = getattr(x, attr, None)
                if isinstance(r, Region):
                    seen.add(r.buffer.uid)
            # read-modify-write targets are reads too
            if isinstance(x, GemmStmt) and not x.clear_accum:
                seen.add(x.C.buffer.uid)
            if isinstance(x, ReduceStmt) and not x.clear:
                seen.add(x.dst.uid)
            if isinstance(x, AtomicStmt):
                seen.add(x.dst.buffer.uid)
            if isinstance(x, PrintStmt) and isinstance(x.obj, Buffer):
                seen.add(x.obj.uid)
            if isinstance(x, IfThenElse):
                for_each_load(x.cond, lambda ld: seen.add(ld.buffer.uid))
            for at in ("value", "cond", "obj"):
                v = getattr(x, at, None)
                if v is not None and not isinstance(
                        v, (Region, Buffer, Stmt, str)):
                    for_each_load(v, lambda ld: seen.add(ld.buffer.uid))
            if isinstance(x, BufferStoreStmt):
                for i in x.indices:
                    if not isinstance(i, slice):
                        for_each_load(i, lambda ld: seen.add(ld.buffer.uid))
        for s in stmts:
            walk(s, chk)
        return seen

    guarded: Dict[int, Any] = {}
    unguarded = set()
    unguarded |= reads_of(plan.init_stmts)
    unguarded |= reads_of(plan.epi_stmts)
    for s in plan.main_stmts:
        if isinstance(s, IfThenElse) and s.else_body is None and \
                all(id(v) in grid_ids for v in free_vars(s.cond)) and \
                any(v is pa_var for v in free_vars(s.cond)):
            for uid in reads_of(s.then_body.stmts):
                if uid in guarded and guarded[uid] is not s.cond:
                    unguarded.add(uid)
                guarded[uid] = s.cond
        else:
            unguarded |= reads_of([s])
    param_uids = {p.buffer.uid for p in plan.params
                  if p.mode == "block" and p.role == "in"}
    return {uid: c for uid, c in guarded.items()
            if uid not in unguarded and uid in param_uids}
