"""Seeded chaos-verify driver (the CI ``chaos-verify`` job).

Arms deterministic corruption faults on the PR-4 collective interpret
paths (``comm.chunk`` / ``comm.fused``), runs comm-opt-rewritten mesh
programs on the 2x2 CPU mesh with the differential selfcheck on, and
asserts the guardrails actually caught the corruption:

- every corrupted program must trigger selfcheck divergence AND degrade
  to the ``TL_TPU_COMM_OPT=0`` schedule,
- every degraded program's outputs must match the clean reference,
- a clean control run must pass selfcheck with zero divergence.

Exit code 0 = all corruption caught (the guardrails work); 1 = a
corruption slipped through (a real miscompile would too). The JSONL
trace and a JSON report land in ``--out`` for CI artifact upload;
``analyzer verify <out>/chaos_trace.jsonl`` prints the summary.

``--device-loss`` switches to the second chaos mode (the PR-6 failover
tier): a seeded RNG kills the "device" at a random config index of a
``bench.py --hermetic`` sweep (a one-shot ``device.dispatch``
unreachable fault inside that config's child) and asserts the sweep
still completes — rc=0, EVERY CPU-safe config producing a record, and
the victim's record carrying ``backend.failover`` accounting. Exit 1
means a dying worker can still zero a bench round.

``--serve`` is the third chaos mode (the ISSUE 8 serving core): a
seeded request storm through the continuous-batching engine with
``serve.admit``/``serve.step``/``serve.kv`` faults armed, the device
killed once mid-batch, a tight-deadline arrival stall, and a drain
wave — asserting every request reaches a terminal outcome, zero KV
slabs leak, and the shed/deadline accounting matches the histograms
(docs/serving.md).

``--serve-mesh`` is the fourth chaos mode (elastic mesh serving,
docs/serving.md): the same storm through a ``MeshDecodeWorkload``
sharded over the 2x2 host device mesh, with a mesh slice killed
mid-step (``serve.shard`` armed ``kind=unreachable``). Exit 0 requires
100% terminal outcomes, at least one recorded reshard down the layout
ladder, zero leaked KV slabs, KV byte-conservation across the
migration, and counter/histogram accounting agreement.

``--fleet`` is the fleet chaos mode (tl-fleet, docs/serving.md "Fleet
serving & failover"): a seeded multi-tenant storm through a supervised
3-engine ``Fleet`` with streaming clients opened before one engine is
killed mid-stream (``serve.engine`` armed ``kind=unreachable``). Exit 0
requires zero lost requests, 100% terminal outcomes, at least one
warm prefix-cache restore on the failover path, the victim re-admitted
(half-open probe) and serving live traffic again, every pre-kill
stream yielding its full token budget, zero KV leaks across engines,
an atomic ``engine_failover`` flight dump naming the victim + re-routed
trace ids, and the per-engine fleet step p99 within budget.

``--seeds 7,13,42`` runs the selected mode once per seed (artifacts
land in ``<out>/seed<N>`` when more than one); the exit code is the
worst of the runs. Without ``--seeds`` the single ``--seed`` (default
7) runs exactly as before.

Usage::

    JAX_PLATFORMS=cpu python -m tilelang_mesh_tpu.verify.chaos \
        --out chaos_report
    python -m tilelang_mesh_tpu.verify.chaos --device-loss \
        --out chaos_device_loss --seed 7
    JAX_PLATFORMS=cpu python -m tilelang_mesh_tpu.verify.chaos \
        --serve --requests 500 --out chaos_serve --seed 7
    JAX_PLATFORMS=cpu python -m tilelang_mesh_tpu.verify.chaos \
        --serve-mesh --seeds 7,13,42 --out chaos_serve_mesh
"""

# NOTE: no `from __future__ import annotations` here — the T.prim_func
# tracer evaluates parameter annotations, and stringified annotations
# cannot see the factory's closure.
import argparse
import json
import os
import sys
from pathlib import Path
from typing import Optional

MESH = (2, 2)
SHAPE = (8, 128)


def _programs():
    """(name, program factory, pass config, fault site) per scenario —
    one exercising the chunked interpret path, one the fused path."""
    import tilelang_mesh_tpu.language as T
    from tilelang_mesh_tpu.parallel import mesh_config
    nrow, ncol = MESH

    def _global(shape=None, name="float32"):
        shape = shape or (nrow * ncol * SHAPE[0], SHAPE[1])
        return T.MeshTensor(shape, T.MeshShardingPolicy(cross_mesh_dim=0),
                            MESH, name)

    def chunked():
        with mesh_config(*MESH):
            @T.prim_func
            def chaos_chunked(A: _global(),
                              B: _global((nrow * ncol, ncol, SHAPE[0],
                                          SHAPE[1]))):
                with T.Kernel(1) as bx:
                    send = T.alloc_shared(SHAPE, "float32")
                    recv = T.alloc_shared((ncol, *SHAPE), "float32")
                    T.copy(A, send)
                    T.comm.all_gather(send, recv, "h")
                    T.copy(recv, B[0, 0, 0])
            return chaos_chunked

    def fused():
        with mesh_config(*MESH):
            @T.prim_func
            def chaos_fused(A: _global(),
                            B: _global((nrow * ncol * SHAPE[0], 1)),
                            C: _global((nrow * ncol * SHAPE[0], 1))):
                with T.Kernel(1) as bx:
                    x = T.alloc_fragment(SHAPE, "float32")
                    y = T.alloc_fragment(SHAPE, "float32")
                    o1 = T.alloc_fragment((SHAPE[0], 1), "float32")
                    o2 = T.alloc_fragment((SHAPE[0], 1), "float32")
                    T.copy(A, x)
                    T.copy(A, y)
                    T.comm.all_reduce(x, o1, "sum", "h", dim=1)
                    T.comm.all_reduce(y, o2, "sum", "h", dim=1)
                    T.copy(o1, B)
                    T.copy(o2, C)
            return chaos_fused

    chunk_cfg = {"tl.tpu.comm_chunk_bytes": 1024}
    return [("chunked_allgather", chunked, chunk_cfg, "comm.chunk"),
            ("fused_allreduce", fused, {}, "comm.fused")]


def _run_one(name, prog, cfg, site, seed, report):
    import numpy as np
    import tilelang_mesh_tpu as tilelang
    from tilelang_mesh_tpu import observability as obs
    from tilelang_mesh_tpu.parallel import mesh_config  # noqa: F401
    from tilelang_mesh_tpu.resilience import inject
    from tilelang_mesh_tpu.transform import pass_config

    nrow, ncol = MESH
    target = f"cpu-mesh[{nrow}x{ncol}]"
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((nrow * ncol * SHAPE[0], SHAPE[1])
                            ).astype(np.float32)

    def compiled():
        with pass_config(cfg):
            return tilelang.compile(prog(), target=target)

    def as_tuple(r):
        return r if isinstance(r, tuple) else (r,)

    # the trustworthy reference
    with pass_config({**cfg, "tl.tpu.comm_opt": "0"}):
        ref = tilelang.compile(prog(), target=target)
    want = as_tuple(ref(a))

    # clean control: selfcheck must pass
    tilelang.clear_cache()
    before = obs.metrics_summary()["verify"]
    got = as_tuple(compiled()(a))
    after = obs.metrics_summary()["verify"]
    clean_ok = (after["selfcheck_ok"] > before["selfcheck_ok"]
                and after["selfcheck_divergence"]
                == before["selfcheck_divergence"])

    # corrupted run: selfcheck must diverge AND fall back
    tilelang.clear_cache()
    with inject(site, kind="corrupt", seed=seed):
        k = compiled()
        got_corrupt = as_tuple(k(a))
    after2 = obs.metrics_summary()["verify"]
    caught = (after2["selfcheck_divergence"]
              > after["selfcheck_divergence"]
              and after2["degraded_schedules"]
              > after["degraded_schedules"])
    numerically_safe = all(
        np.allclose(np.asarray(g), np.asarray(w), rtol=1e-5, atol=1e-6)
        for g, w in zip(got_corrupt, want)) and all(
        np.allclose(np.asarray(g), np.asarray(w), rtol=1e-5, atol=1e-6)
        for g, w in zip(got, want))

    ok = clean_ok and caught and numerically_safe
    report["scenarios"].append({
        "name": name, "fault_site": site, "seed": seed,
        "clean_selfcheck_ok": clean_ok,
        "corruption_caught": caught,
        "fallback_numerically_safe": numerically_safe,
        "ok": ok,
    })
    print(f"[chaos-verify] {name}: clean={clean_ok} caught={caught} "  # noqa: T201
          f"safe={numerically_safe} -> {'OK' if ok else 'FAIL'}")
    return ok


def run_device_loss(out: Path, seed: int) -> int:
    """Seeded device-loss chaos: run ``bench.py --hermetic`` with the
    worker killed at a random config index, assert the sweep completes
    with a record for EVERY CPU-safe config and failover accounting on
    the victim. Runs the bench as a subprocess (its own architecture:
    the parent stays jax-free, each config in its own child)."""
    import random
    import subprocess

    repo_root = Path(__file__).resolve().parents[2]
    bench_py = repo_root / "bench.py"
    # the import is cheap (no jax in bench's parent) and keeps the
    # config list in ONE place
    sys.path.insert(0, str(repo_root))
    import bench as _bench
    cpu_safe = list(_bench.CPU_SAFE_CONFIGS)
    victim = random.Random(seed).choice(cpu_safe)

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["TL_TPU_TRACE"] = "1"
    env["TL_TPU_TRACE_DIR"] = str(out / "trace")
    # the bench children's flight-recorder dumps (the victim's device
    # loss is a dump trigger) land in the artifact dir CI uploads
    env["TL_TPU_FLIGHT_DIR"] = str(out / "flight")
    print(f"[chaos-device-loss] seed={seed}: killing the device inside "
          f"config {victim!r} of the hermetic sweep")  # noqa: T201

    proc = subprocess.run(
        [sys.executable, str(bench_py), "--hermetic", "--quick",
         "--device-loss-at", victim],
        capture_output=True, text=True, env=env, timeout=1800)
    records = {}
    for line in proc.stdout.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if rec.get("config") and "geomean_vs_baseline" not in rec:
            records[rec["config"]] = rec

    missing = [n for n in cpu_safe
               if n not in records or "error" in records[n]]
    vic = records.get(victim, {})
    flight_audit = _audit_flight_dumps(out / "flight")
    checks = {
        "rc_zero": proc.returncode == 0,
        "all_configs_produced_records": not missing,
        "victim_failed_over": vic.get("backend_failovers", 0) >= 1,
        "victim_on_fallback_backend":
            bool(vic.get("backends_used"))
            and vic.get("backend_health", {}).get(
                "tpu-pallas", {}).get("healthy") is False,
        # the victim's device loss is a flight-dump trigger; the black
        # box must exist in the uploaded artifact dir, atomically
        "flight_dumped_and_atomic": flight_audit["dumps"] >= 1
        and flight_audit["atomic"],
    }
    ok = all(checks.values())
    report = {"mode": "device-loss", "seed": seed, "victim": victim,
              "bench_rc": proc.returncode, "checks": checks,
              "missing_or_failed_configs": missing,
              "flight": flight_audit,
              "records": records}
    (out / "device_loss_report.json").write_text(
        json.dumps(report, indent=2))
    (out / "bench_stdout.jsonl").write_text(proc.stdout)
    (out / "bench_stderr.txt").write_text(proc.stderr)
    for name, rec in sorted(records.items()):
        print(f"[chaos-device-loss] {name}: backends_used="  # noqa: T201
              f"{rec.get('backends_used')} "
              f"failovers={rec.get('backend_failovers')}")
    for k, v in checks.items():
        print(f"[chaos-device-loss] {k}: "  # noqa: T201
              f"{'OK' if v else 'FAIL'}")
    print(f"[chaos-device-loss] {'PASS' if ok else 'FAIL'}; artifacts "  # noqa: T201
          f"in {out}/")
    return 0 if ok else 1


def _reset_serving_state() -> None:
    """Per-seed reset of the process-global serving/observability
    state: the serve soaks' accounting checks compare ABSOLUTE counters
    against per-run request outcomes, so a multi-seed invocation
    (``--seeds 7,13,42``) must start every seed from a clean slate."""
    from tilelang_mesh_tpu import observability as obs
    from tilelang_mesh_tpu.resilience.retry import global_breaker
    from tilelang_mesh_tpu.serving import (reset_gauges,
                                           reset_prefix_cache)
    obs.reset()
    reset_gauges()
    reset_prefix_cache()
    global_breaker().reset()
    try:
        from tilelang_mesh_tpu.codegen import backends as _backends
        if _backends._REGISTRY is not None:
            _backends._REGISTRY.reset()
    except Exception:
        pass


def _serve_accounting(eng, counters) -> tuple:
    """The counters-vs-outcomes-vs-``serve.e2e.latency``-histograms
    agreement predicate BOTH serve soaks gate on — one definition so
    the ``serve-smoke`` and ``mesh-serve-smoke`` CI gates can never
    silently test different accounting contracts. Returns
    ``(e2e_by_outcome, acct_ok)``."""
    from tilelang_mesh_tpu.observability import histogram as _hist
    outcomes = eng.outcomes()
    e2e_by_outcome: dict = {}
    for (name, labels), h in _hist.histograms():
        if name == "serve.e2e.latency":
            oc = dict(labels).get("outcome", "?")
            e2e_by_outcome[oc] = e2e_by_outcome.get(oc, 0) + h.count
    acct_ok = (
        counters["completed"] == outcomes["result"]
        and counters["deadline_exceeded"] == outcomes["deadline_exceeded"]
        and counters["failed"] == outcomes["failed"]
        and counters["canceled"] == outcomes["canceled"]
        and counters["shed_total"] == outcomes["shed"]
        and sum(e2e_by_outcome.values()) == len(eng.requests)
        and all(e2e_by_outcome.get(k, 0) == v
                for k, v in outcomes.items() if k != "pending"))
    return e2e_by_outcome, acct_ok


def _audit_flight_dumps(flight_dir: Path, trace_ids=None) -> dict:
    """Audit one soak's flight-recorder dumps (tl-scope,
    docs/observability.md): every dump must parse as JSONL with a
    versioned header, no torn tmp files may remain (the atomic-write
    contract), and — when ``trace_ids`` is given — at least one
    device-loss dump must name victim batch trace ids that all belong
    to the run's requests."""
    dumps = sorted(flight_dir.glob("flight_*.jsonl")) \
        if flight_dir.is_dir() else []
    torn = sorted(p.name for p in flight_dir.glob("*.tmp.*")) \
        if flight_dir.is_dir() else []
    parsed = []
    parse_ok = True
    for p in dumps:
        try:
            lines = [json.loads(ln) for ln in
                     p.read_text().splitlines() if ln.strip()]
            head = lines[0]
            assert head.get("type") == "flight" and head.get("schema")
            parsed.append(head)
        except Exception:  # noqa: BLE001 — a torn dump is the finding
            parse_ok = False
    device_loss_ok = True
    if trace_ids is not None:
        victims = [h for h in parsed
                   if h.get("reason") == "step_failure"
                   and h.get("attrs", {}).get("kind") == "device_loss"
                   and h.get("attrs", {}).get("batch_trace_ids")]
        device_loss_ok = bool(victims) and all(
            set(h["attrs"]["batch_trace_ids"]) <= set(trace_ids)
            for h in victims)
    return {"dumps": len(dumps), "files": [p.name for p in dumps],
            "reasons": sorted({h.get("reason", "?") for h in parsed}),
            "torn_tmp_files": torn,
            "atomic": parse_ok and not torn,
            "device_loss_dump_ok": device_loss_ok}


def _find_skew_dumps(flight_dir: Path, shard: str) -> list:
    """The tl-mesh-scope skew flight dumps naming ``shard`` as the slow
    core (header reason ``mesh_skew``, ``attrs.shard``)."""
    hits = []
    dumps = sorted(flight_dir.glob("flight_*.jsonl")) \
        if flight_dir.is_dir() else []
    for p in dumps:
        try:
            head = json.loads(p.read_text().splitlines()[0])
        except Exception:  # noqa: BLE001 — torn dumps fail atomicity
            continue       # elsewhere, not this scan
        if head.get("reason") == "mesh_skew" \
                and head.get("attrs", {}).get("shard") == shard:
            hits.append(p)
    return hits


def run_serve(out: Path, seed: int, n_requests: int) -> int:
    """Seeded serving-engine chaos soak (the CI ``serve-smoke`` job and
    the ISSUE 8 acceptance gate): ``n_requests`` requests with a
    deadline mix submitted in arrival waves, ``serve.*`` faults armed,
    the device killed once mid-batch (``device.dispatch``), and a drain
    wave at the end. Asserts the engine's whole failure contract:

    - every request reaches a terminal outcome (no drops, no hangs);
    - no deadlined request retires later than deadline + grace + one
      step bound (the zero-hang guarantee, measured per request);
    - KV slabs balance to zero (allocs == frees, no leaked owners);
    - the shed/deadline accounting in the counters and the e2e
      histogram agree with the per-request outcomes;
    - tl-scope (docs/observability.md), PROVED AT DEFAULTS — flight
      recorder on, ``TL_TPU_TRACE`` off: every terminal request's
      causal span chain closes (100% causally complete), and the
      injected mid-batch device loss produced an atomic
      flight-recorder dump naming the victim batch's member trace ids.
    """
    import random

    import numpy as np  # noqa: F401  (engine results are np arrays)

    # tl-scope runs this soak at DEFAULTS: the flight recorder and the
    # per-request causal chains must carry the post-mortem WITHOUT
    # TL_TPU_TRACE (the old always-on-trace soak could never prove
    # that); an operator can still export a full trace by arming the
    # env themselves
    import tilelang_mesh_tpu  # noqa: F401  (package init before serving)
    from tilelang_mesh_tpu import observability as obs
    from tilelang_mesh_tpu.observability import flight as _flight
    from tilelang_mesh_tpu.observability import histogram as _hist
    from tilelang_mesh_tpu.resilience import inject
    from tilelang_mesh_tpu.serving import (FlashDecodeWorkload,
                                           PagedKVAllocator,
                                           ServingEngine)

    # sandbox the prefix-cache disk tier with the other artifacts (it
    # must never land in $HOME under a CI soak)
    os.environ["TL_TPU_SERVE_PREFIX_DIR"] = str(out / "prefix")
    _reset_serving_state()
    _flight.configure(dump_dir=out / "flight")
    rng = random.Random(seed)
    alloc = PagedKVAllocator(n_pages=512, page_size=8, heads=2,
                             head_dim=64)
    wl = FlashDecodeWorkload(alloc, batch_buckets=(8,),
                             page_buckets=(2, 4))
    import time as _time
    eng = ServingEngine(wl, name="chaos-soak")
    t_warm0 = _time.perf_counter()
    warmed = eng.warmup()
    warm_s = _time.perf_counter() - t_warm0

    def make_request():
        ctx = rng.choice((16, 24, 32))
        steps = rng.choice((1, 1, 2, 3))
        roll = rng.random()
        if roll < 0.60:
            deadline = None
        elif roll < 0.80:
            deadline = 2000.0          # generous
        elif roll < 0.95:
            deadline = rng.uniform(30.0, 120.0)   # tight but feasible
        else:
            deadline = 0.0             # hopeless: shed at admission
        return dict(context_tokens=ctx, new_tokens=steps,
                    deadline_ms=deadline, seed=rng.randrange(1 << 30))

    drain_wave = max(4, n_requests // 25)
    main_wave = n_requests - drain_wave
    print(f"[chaos-serve] seed={seed}: {n_requests} requests "  # noqa: T201
          f"({drain_wave} after drain), {warmed} bucket kernels warmed "
          f"in {warm_s:.1f}s, serve.* + device.dispatch faults armed")
    t0 = _time.perf_counter()
    if n_requests < 20:
        print(f"[chaos-serve] --requests {n_requests} is below the soak "
              f"minimum (20): the kill/stall/drain phases need room to "
              f"fire", file=sys.stderr)  # noqa: T201
        return 2
    kill_at = rng.randrange(main_wave // 4, main_wave // 2)
    with inject("serve.step", p=0.03, seed=seed, kind="transient"), \
            inject("serve.kv", p=0.005, seed=seed + 1, kind="transient"), \
            inject("serve.admit", p=0.02, seed=seed + 2,
                   kind="transient"):
        submitted = 0
        killed = stalled = False
        while submitted < main_wave:
            wave = min(rng.randrange(8, 33), main_wave - submitted)
            for _ in range(wave):
                eng.submit(**make_request())
            submitted += wave
            if not killed and submitted >= kill_at:
                # the device dies mid-batch at a seeded point of the
                # sweep: the scheduler must quarantine the batch, fail
                # over, and re-admit its unexpired requests
                killed = True
                with inject("device.dispatch", kind="unreachable",
                            times=1):
                    eng.step()
            if not stalled and submitted >= main_wave // 2:
                # seeded arrival stall: a wave of tight-deadline
                # requests admitted onto a live queue, then the driver
                # pauses past their deadlines (a GC pause / upstream
                # hiccup) — the expiry sweep must retire them as
                # deadline_exceeded, never strand them. The deadline is
                # picked RELATIVE to the observed p50 so admission's
                # feasibility gate admits them on any machine speed,
                # and the pause is sized past deadline + grace so they
                # are in-flight-expired, not shed at admit.
                stalled = True
                from tilelang_mesh_tpu.serving.admission import \
                    observed_step_ms
                for _ in range(40):
                    if eng.queue_depth == 0:
                        break
                    eng.step()
                p50_ms = max(observed_step_ms(0.50, default_ms=5.0), 1.0)
                # feasibility is re-judged per submit against the queue
                # the wave itself builds: budget for all 12 ahead of
                # the last one, doubled for headroom
                stall_deadline_ms = max(
                    40.0, p50_ms * (eng.queue_depth + 12 + 2) * 2.0)
                for _ in range(12):
                    eng.submit(context_tokens=16, new_tokens=1,
                               deadline_ms=stall_deadline_ms,
                               seed=rng.randrange(1 << 30))
                _time.sleep((stall_deadline_ms + eng.grace_ms) / 1e3
                            + 0.05)
            for _ in range(rng.randrange(1, 4)):
                eng.step()
        eng.drain()
        for _ in range(drain_wave):
            eng.submit(**make_request())
        eng.run()
    wall_s = _time.perf_counter() - t0

    # -- the contract checks -------------------------------------------
    grace_s = eng.grace_ms / 1e3
    step_h = _hist.get_histogram("kernel.latency", kernel="serve.step",
                                 source="serving")
    max_step_s = (step_h.max if step_h and step_h.count else 0.1)
    non_terminal = [r.req_id for r in eng.requests if not r.is_terminal]
    late = [r.req_id for r in eng.requests
            if r.deadline is not None and r.terminal_t is not None
            and r.terminal_t - r.deadline > grace_s + max_step_s + 0.25]
    leaks = alloc.leak_check()
    outcomes = eng.outcomes()
    counters = obs.metrics_summary()["serving"]
    e2e_by_outcome, acct_ok = _serve_accounting(eng, counters)
    kv_ok = (not leaks and alloc.in_use == 0
             and alloc.alloc_count == alloc.free_count)
    # tl-scope gates (docs/observability.md): causal completeness of
    # EVERY terminal request's span chain, and an atomic flight dump
    # for the injected device loss naming the victim batch's members
    incomplete = [r.req_id for r in eng.requests
                  if r.is_terminal and not r.trace.complete]
    trace_ids = {r.trace_id for r in eng.requests}
    flight_audit = _audit_flight_dumps(out / "flight", trace_ids)
    checks = {
        "all_terminal": not non_terminal,
        "zero_hangs_past_deadline_grace": not late,
        "kv_slabs_balance_zero": kv_ok,
        "accounting_matches_histograms": acct_ok,
        "engine_completed_some_work": outcomes["result"] > 0,
        "deadline_path_exercised": outcomes["deadline_exceeded"] > 0,
        "chaos_actually_fired": counters["retries"] > 0
        and counters["failovers"] >= 1,
        "causal_chains_complete": not incomplete,
        "device_loss_flight_dump_names_victims":
            flight_audit["device_loss_dump_ok"],
        "flight_dumps_atomic": flight_audit["atomic"],
    }
    ok = all(checks.values())

    report = {
        "mode": "serve", "seed": seed, "requests": n_requests,
        "wall_s": round(wall_s, 3), "warmup_s": round(warm_s, 3),
        "warmed_kernels": warmed,
        "outcomes": outcomes,
        "shed_by_reason": counters["shed"],
        "retries": counters["retries"],
        "failovers": counters["failovers"],
        "steps": eng.stats()["steps"],
        "kv": alloc.stats(),
        "kv_leaks": {str(k): v for k, v in leaks.items()},
        "e2e_by_outcome": e2e_by_outcome,
        "non_terminal_requests": non_terminal,
        "late_requests": late,
        "causally_incomplete_requests": incomplete,
        "flight": flight_audit,
        "checks": checks, "ok": ok,
    }
    trace_path = out / "serve_trace.jsonl"
    obs.write_jsonl(str(trace_path))
    (out / "serve_report.json").write_text(json.dumps(report, indent=2))
    from ..tools.analyzer import format_serve_report
    summary = format_serve_report(obs.read_jsonl(str(trace_path)))
    (out / "serve_report.txt").write_text(summary + "\n")
    print(summary)  # noqa: T201
    for k, v in checks.items():
        print(f"[chaos-serve] {k}: {'OK' if v else 'FAIL'}")  # noqa: T201
    print(f"[chaos-serve] outcomes={outcomes} in {wall_s:.1f}s -> "  # noqa: T201
          f"{'PASS' if ok else 'FAIL'}; artifacts in {out}/")
    return 0 if ok else 1


def _build_scope_kernel():
    """A tiny 2x2 ``T.comm`` all_reduce mesh program compiled through
    the normal pipeline — the serve-mesh soak dispatches it through
    ``MeshKernel.__call__`` so tl-mesh-scope's ledger/timing path is
    exercised by a REAL scoped dispatch, not a synthetic feed."""
    import numpy as np

    import tilelang_mesh_tpu as tilelang
    from tilelang_mesh_tpu import language as T
    from tilelang_mesh_tpu.parallel import mesh_config

    rows = cols = 2
    n, m = 8, 32
    mesh_t = (rows, cols)
    shard = T.MeshShardingPolicy(cross_mesh_dim=0)
    with mesh_config(rows, cols):
        @T.prim_func
        def scope_probe(A: T.MeshTensor((rows * cols * n, m), shard,
                                        mesh_t, "float32"),
                        B: T.MeshTensor((rows * cols * n, 1), shard,
                                        mesh_t, "float32")):
            with T.Kernel(1) as bx:
                x = T.alloc_fragment((n, m), "float32")
                o = T.alloc_fragment((n, 1), "float32")
                T.copy(A, x)
                T.comm.all_reduce(x, o, "sum", "all", dim=1)
                T.copy(o, B)
        kern = tilelang.compile(scope_probe,
                                target=f"cpu-mesh[{rows}x{cols}]")
    arg = np.ones((rows * cols * n, m), np.float32)
    return kern, arg


def _scrape_mesh_endpoint() -> Optional[dict]:
    """Mid-soak ``/mesh`` scrape through a real HTTP round-trip on an
    ephemeral-port telemetry server: the endpoint must answer with a
    schema-versioned snapshot WHILE the storm is running. Returns the
    parsed payload, or None when the scrape failed (the caller's check
    turns that into a soak failure)."""
    import urllib.request

    from tilelang_mesh_tpu.observability.server import start_server
    srv = None
    try:
        srv = start_server(port=0)
        with urllib.request.urlopen(srv.url + "/mesh", timeout=10) as r:
            return json.loads(r.read().decode())
    except Exception as e:  # noqa: BLE001 — report, let the check gate
        print(f"[chaos-serve-mesh] /mesh scrape failed: "  # noqa: T201
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return None
    finally:
        if srv is not None:
            srv.stop()


def run_serve_mesh(out: Path, seed: int, n_requests: int) -> int:
    """Elastic mesh-serving chaos soak (the CI ``mesh-serve-smoke``
    gate): a seeded request storm through a ``MeshDecodeWorkload``
    sharded over the 2x2 host device mesh, with a mesh SLICE killed
    mid-step (``serve.shard`` armed ``kind=unreachable``) and low-rate
    transient step faults underneath. Asserts the elastic contract —
    losing a slice degrades capacity, never correctness:

    - every request reaches a terminal outcome (no drops, no hangs);
    - at least one reshard walked the layout ladder down, and the
      final layout differs from the starting rung;
    - KV slabs balance to zero globally (allocs == frees across BOTH
      the pre- and post-migration allocators, no leaked owners);
    - KV byte-conservation across the migration: every ``serve.reshard``
      event's migrated bytes equal pages x page-bytes, and the
      ``serve.kv.migrated_*`` counters agree (the checksummed
      ``restore()`` already hard-verified the bytes in flight);
    - the outcome accounting in the counters matches the
      ``serve.e2e.latency`` histograms.

    tl-mesh-scope rides the same soak (``TL_TPU_MESH_SCOPE=1``): a
    small ``T.comm`` mesh kernel dispatches through the storm so the
    per-link ICI ledger populates (conservation gate: ledger bytes ==
    static wire bytes x dispatches), the ``comm.collective`` fault site
    is armed inside sampled dispatches (injected faults must appear
    *attributed* in the ledger surfaces), a synthetic 3x-slow shard
    must fire exactly one skew episode with a flight dump naming the
    core, and a mid-run ``/mesh`` scrape must answer.
    """
    import random

    os.environ["TL_TPU_TRACE"] = "1"
    os.environ["TL_TPU_MESH_SCOPE"] = "1"
    os.environ.setdefault("TL_TPU_RUNTIME_SAMPLE", "1")
    # APPEND the host-device flag to any ambient XLA_FLAGS (a bare
    # setdefault would be a no-op under e.g. XLA_FLAGS=--xla_cpu_...,
    # leaving 1 CPU device and killing the 2x2 mesh build)
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import tilelang_mesh_tpu  # noqa: F401  (package init before serving)
    from tilelang_mesh_tpu import observability as obs
    from tilelang_mesh_tpu.observability import flight as _flight
    from tilelang_mesh_tpu.observability import histogram as _hist
    from tilelang_mesh_tpu.resilience import inject
    from tilelang_mesh_tpu.serving import (MeshDecodeWorkload,
                                           PagedKVAllocator,
                                           ServingEngine)

    os.environ["TL_TPU_SERVE_PREFIX_DIR"] = str(out / "prefix")
    _reset_serving_state()
    _flight.configure(dump_dir=out / "flight")
    rng = random.Random(seed)
    alloc = PagedKVAllocator(n_pages=512, page_size=8, heads=2,
                             head_dim=64)
    wl = MeshDecodeWorkload(alloc, batch_buckets=(8,),
                            page_buckets=(2, 4))
    import time as _time
    eng = ServingEngine(wl, name="mesh-soak")
    t_warm0 = _time.perf_counter()
    warmed = eng.warmup()
    warm_s = _time.perf_counter() - t_warm0
    first_layout = wl.layout.name

    # tl-mesh-scope: the decode workload drives its own jitted spmd, so
    # a real MeshKernel.__call__ path must dispatch alongside it to
    # populate the per-link ledger. Warm it BEFORE arming any
    # comm.collective clause: the warm call traces _apply_comm and
    # builds+caches the sampled microbench, so once faults arm, only
    # the scope's host-side attribution visit can consume the budget.
    from tilelang_mesh_tpu.observability import meshscope as _meshscope
    mesh_kern, mesh_arg = _build_scope_kernel()
    mesh_kern(mesh_arg)
    mesh_dispatches = 1

    if n_requests < 20:
        print(f"[chaos-serve-mesh] --requests {n_requests} is below the "
              f"soak minimum (20): the kill/drain phases need room to "
              f"fire", file=sys.stderr)  # noqa: T201
        return 2

    def make_request():
        ctx = rng.choice((16, 24, 32))
        steps = rng.choice((1, 1, 2, 3))
        deadline = None if rng.random() < 0.8 else 2000.0
        return dict(context_tokens=ctx, new_tokens=steps,
                    deadline_ms=deadline, seed=rng.randrange(1 << 30))

    drain_wave = max(4, n_requests // 25)
    main_wave = n_requests - drain_wave
    kill_at = rng.randrange(main_wave // 4, main_wave // 2)
    print(f"[chaos-serve-mesh] seed={seed}: {n_requests} requests "  # noqa: T201
          f"({drain_wave} after drain) on layout {first_layout}, "
          f"{warmed} bucket kernels warmed in {warm_s:.1f}s; slice "
          f"kill at ~request {kill_at}")
    t0 = _time.perf_counter()
    mesh_scrape: Optional[dict] = None
    comm_faults_armed = 0
    with inject("serve.step", p=0.02, seed=seed, kind="transient"):
        submitted = 0
        killed = False
        while submitted < main_wave:
            wave = min(rng.randrange(8, 33), main_wave - submitted)
            for _ in range(wave):
                eng.submit(**make_request())
            submitted += wave
            if not killed and submitted >= kill_at:
                # the mesh slice dies mid-step at a seeded point: the
                # engine must snapshot the surviving KV, quarantine,
                # walk one ladder rung down, migrate, and re-admit
                killed = True
                with inject("serve.shard", kind="unreachable", times=1):
                    eng.step()
                # ... and the observability layer must survive the
                # failure path it exists for: arm the comm.collective
                # site INSIDE meshscope-sampled dispatches — the scope
                # must attribute both faults, not die or drop them
                with inject("comm.collective", p=1.0, seed=seed,
                            kind="transient", times=2):
                    mesh_kern(mesh_arg)
                    mesh_kern(mesh_arg)
                mesh_dispatches += 2
                comm_faults_armed = 2
                mesh_scrape = _scrape_mesh_endpoint()
            for _ in range(rng.randrange(1, 4)):
                eng.step()
            # the scoped mesh kernel rides the storm cadence
            mesh_kern(mesh_arg)
            mesh_dispatches += 1
            wl.probe_shards()       # real sweeps feed the skew baseline
        eng.drain()
        for _ in range(drain_wave):
            eng.submit(**make_request())
        eng.run()
    # synthetic straggler: one shard pinned at 3x the sweep median long
    # enough to clear warmup+sustain — the detector must fire EXACTLY
    # one edge-triggered episode and flight-dump the core's name
    from tilelang_mesh_tpu.env import env as _env
    # enough sweeps for the EWMA to converge onto the 3x shard and its
    # MAD band to decay below the firing threshold even when the real
    # probe sweeps above already seeded a healthy baseline
    n_sweeps = 8 * (int(_env.TL_TPU_MESH_SKEW_WARMUP)
                    + int(_env.TL_TPU_MESH_SKEW_SUSTAIN))
    for _ in range(n_sweeps):
        _meshscope.observe_shards(
            {"x0y0": 1e-3, "x0y1": 1e-3, "x1y0": 1e-3, "x1y1": 3e-3},
            probe="chaos.synthetic")
    wall_s = _time.perf_counter() - t0

    # -- the elastic contract checks -----------------------------------
    cur = eng.workload.allocator       # post-migration allocator
    leaks = cur.leak_check()
    outcomes = eng.outcomes()
    counters = obs.metrics_summary()["serving"]
    non_terminal = [r.req_id for r in eng.requests if not r.is_terminal]
    e2e_by_outcome, acct_ok = _serve_accounting(eng, counters)
    # byte conservation: 2 pools x H x page_size x D x itemsize per page
    page_bytes = 2 * cur.heads * cur.page_size * cur.head_dim \
        * cur.dtype.itemsize
    resh_events = [e.get("attrs", {})
                   for e in obs.get_tracer().events()
                   if e.get("type") == "event"
                   and e.get("name") == "serve.reshard"]
    mig_pages = counters["kv_pages_migrated"]
    conserve_ok = (
        resh_events != []
        and all(ev.get("bytes") == ev.get("pages", 0) * page_bytes
                for ev in resh_events)
        and mig_pages == sum(ev.get("pages", 0) for ev in resh_events))
    kv_ok = (not leaks and cur.in_use == 0
             and counters["kv_pages_allocated"]
             == counters["kv_pages_freed"])
    incomplete = [r.req_id for r in eng.requests
                  if r.is_terminal and not r.trace.complete]
    flight_audit = _audit_flight_dumps(out / "flight")
    # -- the tl-mesh-scope contract ------------------------------------
    mesh_snap = _meshscope.mesh_snapshot()
    mesh_cons = mesh_snap.get("conservation") or {}
    mesh_skew = mesh_snap.get("skew") or {}
    skew_hits = [a for a in (mesh_skew.get("active") or [])
                 if a.get("shard") == "x1y1"]
    skew_dumps = _find_skew_dumps(out / "flight", shard="x1y1")
    mesh_checks = {
        # ledger bytes == static post-opt wire bytes x dispatch count,
        # with the ledger actually populated by the storm's dispatches
        "mesh_ledger_conserved": bool(mesh_cons.get("ok"))
        and mesh_cons.get("ledger_bytes", 0) > 0
        and (mesh_cons.get("kernels", {}).get("scope_probe", {})
             .get("dispatches") == mesh_dispatches),
        # both armed comm.collective faults landed attributed to the
        # collective they hit — the scope survived its failure path
        "mesh_faults_attributed":
            mesh_snap.get("faults", {}).get("injected", 0)
            == comm_faults_armed,
        # the synthetic 3x shard fired EXACTLY one edge-triggered
        # episode, and its flight dump names the core
        "mesh_skew_episode_exactly_once":
            len(skew_hits) == 1 and skew_hits[0].get("episodes") == 1,
        "mesh_skew_flight_dump_names_core": len(skew_dumps) >= 1,
        "mesh_endpoint_scraped_midrun": mesh_scrape is not None
        and mesh_scrape.get("schema") == _meshscope.MESH_SCHEMA
        and bool(mesh_scrape.get("dispatches")),
    }
    checks = {
        **mesh_checks,
        "all_terminal": not non_terminal,
        "kv_slabs_balance_zero": kv_ok,
        "resharded_down_the_ladder": counters["reshards"] >= 1
        and wl.layout.name != first_layout,
        "kv_bytes_conserved_across_migration": conserve_ok,
        "accounting_matches_histograms": acct_ok,
        "engine_completed_some_work": outcomes["result"] > 0,
        "causal_chains_complete": not incomplete,
        # the slice kill surfaced to the scheduler, so its black box
        # must exist and every dump must have committed atomically
        "flight_dumped_and_atomic": flight_audit["dumps"] >= 1
        and flight_audit["atomic"],
    }
    ok = all(checks.values())

    report = {
        "mode": "serve-mesh", "seed": seed, "requests": n_requests,
        "wall_s": round(wall_s, 3), "warmup_s": round(warm_s, 3),
        "warmed_kernels": warmed,
        # the full tl-mesh-scope snapshot: `analyzer mesh
        # serve_mesh_report.json` renders this section directly
        "mesh": mesh_snap,
        "mesh_dispatches": mesh_dispatches,
        "mesh_skew_dumps": [str(p) for p in skew_dumps],
        "first_layout": first_layout,
        "final_layout": wl.layout.name,
        "ladder": [r.name for r in wl.ladder],
        "reshards": counters["reshards"],
        "reshard_events": resh_events,
        "kv_pages_migrated": mig_pages,
        "outcomes": outcomes,
        "shed_by_reason": counters["shed"],
        "retries": counters["retries"],
        "steps": eng.stats()["steps"],
        "kv": cur.stats(),
        "kv_leaks": {str(k): v for k, v in leaks.items()},
        "e2e_by_outcome": e2e_by_outcome,
        "non_terminal_requests": non_terminal,
        "causally_incomplete_requests": incomplete,
        "flight": flight_audit,
        "checks": checks, "ok": ok,
    }
    trace_path = out / "serve_mesh_trace.jsonl"
    obs.write_jsonl(str(trace_path))
    (out / "serve_mesh_report.json").write_text(
        json.dumps(report, indent=2))
    from ..tools.analyzer import format_serve_report
    summary = format_serve_report(obs.read_jsonl(str(trace_path)))
    (out / "serve_mesh_report.txt").write_text(summary + "\n")
    print(summary)  # noqa: T201
    for k, v in checks.items():
        print(f"[chaos-serve-mesh] {k}: {'OK' if v else 'FAIL'}")  # noqa: T201
    print(f"[chaos-serve-mesh] layout {first_layout} -> "  # noqa: T201
          f"{wl.layout.name}, outcomes={outcomes} in {wall_s:.1f}s -> "
          f"{'PASS' if ok else 'FAIL'}; artifacts in {out}/")
    return 0 if ok else 1


def run_serve_lifecycle(out: Path, seed: int, n_requests: int) -> int:
    """Full-lifecycle serving chaos soak (the CI ``serve-lifecycle``
    gate; docs/serving.md "Full-lifecycle serving"): seeded MIXED
    traffic — shared-system-prompt requests (prefix-cache hits),
    long-prompt requests spanning many prefill chunks, short
    decode-heavy requests, streaming clients, and cancellations fired
    mid-prefill AND mid-decode — with ``serve.step``/``serve.kv``
    transient faults armed underneath. Asserts the lifecycle contract:

    - every request reaches a terminal outcome (the five-outcome
      vocabulary, ``canceled`` included) and the counters/histogram
      accounting agrees;
    - KV slabs balance to zero — cancellation mid-prefill and
      mid-decode must free every page (``leak_check()``);
    - at least one prefix-cache HIT with bytes saved (the shared
      system prompt was prefilled once, not per request);
    - prefill chunks ran interleaved with decode batches (a decode
      batch completed while some prompt was still mid-prefill), and
      the decode step p99 stayed within the budget — chunked prefill
      must not stall decode;
    - TTFT was recorded and every terminal request's causal chain is
      complete (the ``prefill.chunk`` spans ride the same chain).
    """
    import random

    import tilelang_mesh_tpu  # noqa: F401  (package init before serving)
    from tilelang_mesh_tpu import observability as obs
    from tilelang_mesh_tpu.observability import flight as _flight
    from tilelang_mesh_tpu.observability import histogram as _hist
    from tilelang_mesh_tpu.resilience import inject
    from tilelang_mesh_tpu.serving import (FlashDecodeWorkload,
                                           PagedKVAllocator,
                                           ServingEngine,
                                           reset_prefix_cache)

    # small chunk so the long prompts genuinely span many schedulable
    # units (overridable by the operator)
    os.environ.setdefault("TL_TPU_SERVE_PREFILL_CHUNK", "16")
    # the decode-p99 acceptance budget: TL_TPU_SERVE_P99_BUDGET_MS when
    # the operator set a POSITIVE one, else a CI-calibrated CPU ceiling
    # (0 is the documented "admission gate off" value, not a 0ms budget)
    try:
        budget_ms = float(os.environ.get("TL_TPU_SERVE_P99_BUDGET_MS")
                          or 0.0)
    except ValueError:
        budget_ms = 0.0
    if budget_ms <= 0:
        budget_ms = 250.0
    # per-run prefix-cache tier (fresh dir per seed: the >=1-hit gate
    # must prove THIS run shared a prefill, not inherit one)
    os.environ["TL_TPU_SERVE_PREFIX_DIR"] = str(out / "prefix")
    reset_prefix_cache()
    _reset_serving_state()
    _flight.configure(dump_dir=out / "flight")

    rng = random.Random(seed)
    alloc = PagedKVAllocator(n_pages=768, page_size=8, heads=2,
                             head_dim=64)
    ps = alloc.page_size
    wl = FlashDecodeWorkload(alloc, batch_buckets=(8,),
                             page_buckets=(2, 4))
    import time as _time
    eng = ServingEngine(wl, name="lifecycle-soak")
    t_warm0 = _time.perf_counter()
    warmed = eng.warmup()
    warm_s = _time.perf_counter() - t_warm0

    if n_requests < 20:
        print(f"[chaos-serve-lifecycle] --requests {n_requests} is "
              f"below the soak minimum (20)", file=sys.stderr)  # noqa: T201
        return 2

    # two shared system prompts, whole-page (6 pages = 48 tokens each)
    shared = [[rng.randrange(1 << 20) for _ in range(6 * ps)]
              for _ in range(2)]

    def make_request():
        roll = rng.random()
        kw = dict(seed=rng.randrange(1 << 30),
                  temperature=rng.choice((0.0, 0.0, 0.8)),
                  top_p=rng.choice((1.0, 0.9)))
        if roll < 0.45:
            # shared system prompt + unique user suffix
            prompt = list(rng.choice(shared)) \
                + [rng.randrange(1 << 20)
                   for _ in range(rng.randrange(0, 2 * ps))]
            kw.update(context_tokens=len(prompt), prompt_tokens=prompt,
                      new_tokens=rng.choice((1, 2)))
        elif roll < 0.60:
            # long prompt: many prefill chunks, decode must interleave
            kw.update(context_tokens=rng.choice((96, 128, 160)),
                      new_tokens=1)
        else:
            # short decode-heavy request
            kw.update(context_tokens=rng.choice((16, 24, 32)),
                      new_tokens=rng.choice((1, 2, 3)))
        if rng.random() < 0.15:
            kw.update(deadline_ms=2000.0)
        return kw

    print(f"[chaos-serve-lifecycle] seed={seed}: {n_requests} mixed "  # noqa: T201
          f"requests, {warmed} bucket kernels warmed in {warm_s:.1f}s, "
          f"chunk={os.environ['TL_TPU_SERVE_PREFILL_CHUNK']} tokens, "
          f"p99 budget {budget_ms:g}ms")
    t0 = _time.perf_counter()
    interleaved = False
    canceled_mid_prefill = 0
    stream_tokens = 0
    with inject("serve.step", p=0.02, seed=seed, kind="transient"), \
            inject("serve.kv", p=0.003, seed=seed + 1, kind="transient"):
        # seed the prefix cache: one pure-shared-prompt request per
        # prompt completes BEFORE the storm (the fleet's first tenant)
        for prompt in shared:
            eng.submit(context_tokens=len(prompt), prompt_tokens=prompt,
                       new_tokens=1, seed=rng.randrange(1 << 30))
        eng.run()
        # two streaming clients: one consumed to completion, one
        # closed after the first token (client disconnect -> cancel)
        stream = eng.stream(context_tokens=len(shared[0]),
                            prompt_tokens=list(shared[0]), new_tokens=3,
                            seed=rng.randrange(1 << 30))
        stream_tokens += sum(1 for _ in stream)
        dropper = eng.stream(context_tokens=32, new_tokens=4,
                             seed=rng.randrange(1 << 30))
        for _ in dropper:
            break                    # disconnect after the first token
        submitted = 0
        live = []
        while submitted < n_requests:
            wave = min(rng.randrange(6, 25), n_requests - submitted)
            for _ in range(wave):
                r = eng.submit(**make_request())
                if not r.is_terminal:
                    live.append(r)
            submitted += wave
            # deterministic mid-prefill cancel: pick a live request
            # still filling its prompt and cancel it RIGHT NOW — its
            # partial pages must free (leak_check gates)
            victims = [r for r in live
                       if not r.is_terminal and r.needs_prefill]
            if victims and canceled_mid_prefill < 5:
                v = rng.choice(victims)
                if eng.cancel(v):
                    canceled_mid_prefill += 1
            # random mid-decode cancels (~8% of a wave)
            for r in list(live):
                if not r.is_terminal and r.steps_done > 0 \
                        and rng.random() < 0.08:
                    eng.cancel(r)
            for _ in range(rng.randrange(1, 4)):
                before_batches = obs.metrics_summary()[
                    "serving"]["batches"]
                mid_prefill = any(not r.is_terminal and r.needs_prefill
                                  for r in eng.requests)
                eng.step()
                after_batches = obs.metrics_summary()[
                    "serving"]["batches"]
                if mid_prefill and after_batches > before_batches:
                    # a decode batch completed while a prompt was
                    # still mid-prefill: the interleave is real
                    interleaved = True
            live = [r for r in live if not r.is_terminal]
        eng.drain()
        eng.run()
    wall_s = _time.perf_counter() - t0

    # -- the lifecycle contract checks ---------------------------------
    leaks = alloc.leak_check()
    outcomes = eng.outcomes()
    counters = obs.metrics_summary()["serving"]
    e2e_by_outcome, acct_ok = _serve_accounting(eng, counters)
    kv_ok = (not leaks and alloc.in_use == 0
             and alloc.alloc_count == alloc.free_count)
    non_terminal = [r.req_id for r in eng.requests if not r.is_terminal]
    incomplete = [r.req_id for r in eng.requests
                  if r.is_terminal and not r.trace.complete]
    step_h = _hist.get_histogram("kernel.latency", kernel="serve.step",
                                 source="serving")
    p99_ms = (step_h.quantile(0.99) * 1e3
              if step_h and step_h.count else None)
    ttft_h = _hist.get_histogram("serve.ttft")
    pc = counters["prefix_cache"]
    checks = {
        "all_terminal": not non_terminal,
        "kv_slabs_balance_zero": kv_ok,
        "accounting_matches_histograms": acct_ok,
        "engine_completed_some_work": outcomes["result"] > 0,
        "prefix_cache_hit": pc["hits"] >= 1 and pc["bytes_saved"] > 0,
        "prefill_chunks_ran": counters["prefill_chunks"] > 0,
        "prefill_interleaved_with_decode": interleaved,
        "decode_p99_within_budget": p99_ms is not None
        and p99_ms <= budget_ms,
        "cancellation_exercised": outcomes["canceled"] >= 1
        and canceled_mid_prefill >= 1,
        "streaming_yielded_tokens": stream_tokens >= 1,
        "ttft_recorded": bool(ttft_h and ttft_h.count),
        "causal_chains_complete": not incomplete,
    }
    ok = all(checks.values())

    report = {
        "mode": "serve-lifecycle", "seed": seed,
        "requests": len(eng.requests),
        "wall_s": round(wall_s, 3), "warmup_s": round(warm_s, 3),
        "outcomes": outcomes,
        "shed_by_reason": counters["shed"],
        "canceled_mid_prefill": canceled_mid_prefill,
        "stream_tokens": stream_tokens,
        "prefill_chunks": counters["prefill_chunks"],
        "prefill_tokens": counters["prefill_tokens"],
        "prefix_cache": pc,
        "decode_p99_ms": round(p99_ms, 3) if p99_ms else None,
        "decode_p99_budget_ms": budget_ms,
        "ttft": counters["ttft"],
        "kv": alloc.stats(),
        "kv_leaks": {str(k): v for k, v in leaks.items()},
        "e2e_by_outcome": e2e_by_outcome,
        "non_terminal_requests": non_terminal,
        "causally_incomplete_requests": incomplete,
        "checks": checks, "ok": ok,
    }
    trace_path = out / "serve_lifecycle_trace.jsonl"
    obs.write_jsonl(str(trace_path))
    (out / "serve_lifecycle_report.json").write_text(
        json.dumps(report, indent=2))
    from ..tools.analyzer import format_serve_report
    summary = format_serve_report(obs.read_jsonl(str(trace_path)))
    (out / "serve_lifecycle_report.txt").write_text(summary + "\n")
    print(summary)  # noqa: T201
    for k, v in checks.items():
        print(f"[chaos-serve-lifecycle] {k}: "  # noqa: T201
              f"{'OK' if v else 'FAIL'}")
    print(f"[chaos-serve-lifecycle] outcomes={outcomes} "  # noqa: T201
          f"prefix={pc['hits']} hit(s)/{pc['bytes_saved']}B saved, "
          f"p99={report['decode_p99_ms']}ms in {wall_s:.1f}s -> "
          f"{'PASS' if ok else 'FAIL'}; artifacts in {out}/")
    return 0 if ok else 1


def run_fleet(out: Path, seed: int, n_requests: int) -> int:
    """Fleet chaos soak (the CI ``fleet-chaos`` gate; docs/serving.md
    "Fleet serving & failover"): a seeded multi-tenant storm through a
    supervised 3-engine ``Fleet`` with low-rate ``serve.step`` faults
    underneath, streaming clients opened BEFORE one engine is killed
    mid-stream (``serve.engine`` armed ``kind=unreachable``), and a
    post-readmission wave proving the victim serves live traffic
    again. Asserts the fleet robustness contract:

    - every request reaches a terminal outcome with ZERO lost: no
      unroutable sheds, no failover-lost requests (healthy peers
      adopted every victim);
    - the killed engine is ejected within the kill step, its breaker
      stays open until the half-open probe passes, and it is
      re-admitted AND receives new dispatches before the soak ends;
    - at least one failover re-dispatch restored WARM from the shared
      prefix cache (whole-page shared prompt, no cold re-prefill);
    - every ``TokenStream`` opened before the kill yields its full
      token budget (tokens ride the request, not the engine);
    - KV slabs balance to zero on every surviving engine (the victim
      freed its slabs at export);
    - the counters / ``serve.e2e.latency`` histograms / per-request
      outcomes agree (the shared ``_serve_accounting`` predicate,
      fleet-wide), and every terminal request's causal chain closes;
    - one atomic ``engine_failover`` flight dump names the victim and
      re-routed trace ids that all belong to this run;
    - the per-engine fleet step p99 stays within
      ``TL_TPU_FLEET_P99_BUDGET_MS`` (falling back to
      ``TL_TPU_SERVE_P99_BUDGET_MS``, else the CI CPU ceiling).
    """
    import random

    os.environ["TL_TPU_TRACE"] = "1"
    import tilelang_mesh_tpu  # noqa: F401  (package init before serving)
    from tilelang_mesh_tpu import observability as obs
    from tilelang_mesh_tpu.observability import flight as _flight
    from tilelang_mesh_tpu.observability import histogram as _hist
    from tilelang_mesh_tpu.resilience import inject
    from tilelang_mesh_tpu.serving import (Fleet, FlashDecodeWorkload,
                                           PagedKVAllocator,
                                           reset_prefix_cache)

    # the fleet p99 acceptance budget: TL_TPU_FLEET_P99_BUDGET_MS when
    # the operator set a POSITIVE one, TL_TPU_SERVE_P99_BUDGET_MS next,
    # else the CI-calibrated CPU ceiling
    budget_ms = 0.0
    for var in ("TL_TPU_FLEET_P99_BUDGET_MS", "TL_TPU_SERVE_P99_BUDGET_MS"):
        try:
            budget_ms = float(os.environ.get(var) or 0.0)
        except ValueError:
            budget_ms = 0.0
        if budget_ms > 0:
            break
    if budget_ms <= 0:
        budget_ms = 250.0
    # per-run shared prefix tier: the warm-restore gate must prove THIS
    # run's failover re-warmed from pages THIS run inserted
    os.environ["TL_TPU_SERVE_PREFIX_DIR"] = str(out / "prefix")
    reset_prefix_cache()
    _reset_serving_state()
    _flight.configure(dump_dir=out / "flight")

    rng = random.Random(seed)
    tenants = ("acme", "globex", "initech")

    def workload_factory():
        alloc = PagedKVAllocator(n_pages=512, page_size=8, heads=2,
                                 head_dim=64)
        return FlashDecodeWorkload(alloc, batch_buckets=(8,),
                                   page_buckets=(2, 4))

    import time as _time
    fleet = Fleet(workload_factory, n_engines=3, name="fleet-soak")
    t_warm0 = _time.perf_counter()
    warmed = fleet.warmup()
    warm_s = _time.perf_counter() - t_warm0
    ps = 8

    if n_requests < 20:
        print(f"[chaos-fleet] --requests {n_requests} is below the soak "
              f"minimum (20): the kill/readmit/drain phases need room "
              f"to fire", file=sys.stderr)  # noqa: T201
        return 2

    # two shared whole-page system prompts: their pages land in the
    # fleet-wide prefix cache, so victims holding them restore WARM on
    # the adopting engine
    shared = [[rng.randrange(1 << 20) for _ in range(4 * ps)]
              for _ in range(2)]

    def make_request():
        kw = dict(seed=rng.randrange(1 << 30),
                  tenant=rng.choice(tenants))
        if rng.random() < 0.45:
            prompt = list(rng.choice(shared))
            kw.update(context_tokens=len(prompt), prompt_tokens=prompt,
                      new_tokens=rng.choice((1, 2, 3)))
        else:
            kw.update(context_tokens=rng.choice((16, 24, 32)),
                      new_tokens=rng.choice((1, 2)))
        if rng.random() < 0.15:
            kw.update(deadline_ms=2000.0)
        return kw

    drain_wave = max(4, n_requests // 25)
    post_wave = min(24, max(8, n_requests // 20))
    n_streams = 3
    burst = 12
    main_wave = n_requests - drain_wave - post_wave - n_streams - burst
    phase1 = max(main_wave // 2, 1)
    print(f"[chaos-fleet] seed={seed}: {n_requests} requests over "  # noqa: T201
          f"{len(fleet.slots)} engines ({n_streams} streaming, "
          f"{post_wave} post-readmit, {drain_wave} after drain), "
          f"{warmed} bucket kernels warmed in {warm_s:.1f}s; one engine "
          f"killed mid-stream, p99 budget {budget_ms:g}ms")
    t0 = _time.perf_counter()
    with inject("serve.step", p=0.02, seed=seed, kind="transient"):
        # seed the shared prefix cache: one pure-shared-prompt request
        # per prompt completes before the storm
        for prompt in shared:
            fleet.submit(context_tokens=len(prompt),
                         prompt_tokens=prompt, new_tokens=1,
                         seed=rng.randrange(1 << 30), tenant="acme")
        fleet.run()

        # storm phase 1
        submitted = 0
        while submitted < phase1:
            wave = min(rng.randrange(6, 25), phase1 - submitted)
            for _ in range(wave):
                fleet.submit(**make_request())
            submitted += wave
            for _ in range(rng.randrange(1, 4)):
                fleet.step()

        # pre-kill burst: shared whole-page-prompt work queued on EVERY
        # engine (no pumping in between), so the victim dies holding
        # live requests whose prefix restores warm on the adopter
        for _ in range(burst):
            prompt = list(rng.choice(shared))
            fleet.submit(context_tokens=len(prompt),
                         prompt_tokens=prompt,
                         new_tokens=rng.choice((2, 3, 4)),
                         seed=rng.randrange(1 << 30),
                         tenant=rng.choice(tenants))
        # streaming clients on the shared prompt, opened BEFORE the
        # kill so the kill lands mid-stream; consumed after it — the
        # tokens ride the request, failover included
        streams = [fleet.stream(context_tokens=len(shared[0]),
                                prompt_tokens=list(shared[0]),
                                new_tokens=3,
                                seed=rng.randrange(1 << 30),
                                tenant=rng.choice(tenants))
                   for _ in range(n_streams)]

        # the kill: the first live engine pumped dies inside this ONE
        # fleet step; ejection + failover must complete within it
        live_before = {s.name for s in fleet.slots if s.state == "live"}
        with inject("serve.engine", kind="unreachable", times=1):
            fleet.step()
        ejected = [s.name for s in fleet.slots if s.state != "live"]
        victim = ejected[0] if ejected else None
        ejected_within_kill_step = (len(ejected) == 1
                                    and victim in live_before)

        # storm phase 2 rides through the failover + restart window
        while submitted < main_wave:
            wave = min(rng.randrange(6, 25), main_wave - submitted)
            for _ in range(wave):
                fleet.submit(**make_request())
            submitted += wave
            for _ in range(rng.randrange(1, 4)):
                fleet.step()

        readmitted = fleet.await_readmission(timeout_s=30.0)

        # post-readmission wave: the victim must receive NEW dispatches
        disp_before = obs.metrics_summary()["fleet"]["dispatch"] \
            if victim else {}
        for _ in range(post_wave):
            fleet.submit(**make_request())
        fleet.run()
        disp_after = obs.metrics_summary()["fleet"]["dispatch"] \
            if victim else {}
        victim_served = bool(victim) and (
            disp_after.get(victim, 0) > disp_before.get(victim, 0))

        # the streams opened before the kill keep yielding (their
        # requests may have failed over mid-stream)
        stream_tokens = [sum(1 for _ in s) for s in streams]

        fleet.drain()
        for _ in range(drain_wave):
            fleet.submit(**make_request())
        fleet.run()
    wall_s = _time.perf_counter() - t0

    # -- the fleet contract checks -------------------------------------
    leaks = {e: leak for e, leak in fleet.leak_check().items() if leak}
    in_use = sum(s.engine.workload.allocator.in_use
                 for s in fleet.slots if s.engine is not None)
    outcomes = fleet.outcomes()
    summary = obs.metrics_summary()
    counters = summary["serving"]
    fleet_sec = summary["fleet"] or {}
    e2e_by_outcome, acct_ok = _serve_accounting(fleet, counters)
    non_terminal = [r.req_id for r in fleet.requests
                    if not r.is_terminal]
    incomplete = [r.req_id for r in fleet.requests
                  if r.is_terminal and not r.trace.complete]
    # per-engine fleet step p99 (the exact-label fleet.step.latency
    # series the router also reads)
    p99s = {}
    for (hname, labels), h in _hist.histograms():
        if hname == "fleet.step.latency" and h.count:
            p99s[dict(labels).get("engine", "?")] = h.quantile(0.99) * 1e3
    worst_p99 = max(p99s.values()) if p99s else None
    # the failover black box must name the victim and re-routed ids
    trace_ids = {r.trace_id for r in fleet.requests}
    flight_audit = _audit_flight_dumps(out / "flight")
    failover_heads = []
    for fname in flight_audit["files"]:
        try:
            head = json.loads(
                (out / "flight" / fname).read_text().splitlines()[0])
        except Exception:  # noqa: BLE001 — atomicity gated separately
            continue
        if head.get("reason") == "engine_failover":
            failover_heads.append(head)
    dump_ok = bool(failover_heads) and any(
        h.get("attrs", {}).get("victim") == victim
        and h.get("attrs", {}).get("redispatched_trace_ids")
        and set(h["attrs"]["redispatched_trace_ids"]) <= trace_ids
        for h in failover_heads)
    tenants_seen = set(counters.get("tenants", {}))
    checks = {
        "all_terminal": not non_terminal,
        "zero_lost": (not non_terminal
                      and fleet_sec.get("shed_unroutable", 0) == 0),
        "kv_slabs_balance_zero": not leaks and in_use == 0,
        "engine_killed_and_failed_over": fleet.failovers >= 1
        and victim is not None,
        "ejected_within_kill_step": ejected_within_kill_step,
        "warm_restore_redispatch": fleet_sec.get("warm_restores",
                                                 0) >= 1,
        "victim_readmitted": readmitted
        and all(s.state == "live" for s in fleet.slots)
        and fleet_sec.get("readmits", {}).get(victim, 0) >= 1,
        "victim_served_after_readmit": victim_served,
        "streams_survived_failover": all(
            n == 3 for n in stream_tokens),
        "per_tenant_accounting": set(tenants) <= tenants_seen,
        "accounting_matches_histograms": acct_ok,
        "causal_chains_complete": not incomplete,
        "failover_flight_dump_names_victims": dump_ok,
        "flight_dumps_atomic": flight_audit["atomic"],
        "fleet_p99_within_budget": worst_p99 is not None
        and worst_p99 <= budget_ms,
    }
    ok = all(checks.values())

    report = {
        "mode": "fleet", "seed": seed, "requests": len(fleet.requests),
        "engines": [s.name for s in fleet.slots],
        "victim": victim,
        "wall_s": round(wall_s, 3), "warmup_s": round(warm_s, 3),
        "warmed_kernels": warmed,
        "outcomes": outcomes,
        "shed_by_reason": counters["shed"],
        "tenants": counters.get("tenants", {}),
        "fleet": fleet_sec,
        "stream_tokens": stream_tokens,
        "step_p99_ms": {e: round(v, 3) for e, v in sorted(p99s.items())},
        "step_p99_budget_ms": budget_ms,
        "kv_leaks": {e: leak for e, leak in leaks.items()},
        "e2e_by_outcome": e2e_by_outcome,
        "non_terminal_requests": non_terminal,
        "causally_incomplete_requests": incomplete,
        "flight": flight_audit,
        "checks": checks, "ok": ok,
    }
    trace_path = out / "fleet_trace.jsonl"
    obs.write_jsonl(str(trace_path))
    (out / "fleet_report.json").write_text(json.dumps(report, indent=2))
    from ..tools.analyzer import format_fleet_report, format_serve_report
    records = obs.read_jsonl(str(trace_path))
    summary_txt = (format_fleet_report(records) + "\n\n"
                   + format_serve_report(records))
    (out / "fleet_report.txt").write_text(summary_txt + "\n")
    print(summary_txt)  # noqa: T201
    for k, v in checks.items():
        print(f"[chaos-fleet] {k}: {'OK' if v else 'FAIL'}")  # noqa: T201
    print(f"[chaos-fleet] victim={victim} outcomes={outcomes} "  # noqa: T201
          f"warm={fleet_sec.get('warm_restores', 0)} in {wall_s:.1f}s "
          f"-> {'PASS' if ok else 'FAIL'}; artifacts in {out}/")
    return 0 if ok else 1


def run_fleet_proc(out: Path, seed: int, n_requests: int) -> int:
    """Process-isolated fleet chaos soak (the CI ``fleet-proc-chaos``
    gate; docs/serving.md "Process isolation & crash containment"):
    the multi-tenant storm through a 3-worker ``Fleet`` running
    ``TL_TPU_FLEET_ISOLATION=proc`` — every slot a real subprocess
    behind the checksummed frame protocol — with REAL deaths instead
    of injected Python exceptions: one worker SIGKILLed mid-stream,
    a second SIGKILLed mid-prefill, and one torn IPC frame injected
    once the fleet is whole again. Asserts the SIGKILL-proof zero-loss
    contract:

    - every request reaches a terminal outcome with ZERO lost (the
      supervisor's shadow requests survive both SIGKILLs and the torn
      frame, and healthy peers adopt every victim);
    - both SIGKILLed workers eject within the kill step, restart with
      a NEW pid, and re-admit after their end-to-end probes — and the
      first victim receives fresh dispatches afterwards;
    - at least one failover re-dispatch restores WARM from the disk
      prefix tier (the tier written by a process that is now dead);
    - every ``TokenStream`` opened before the first kill yields its
      full token budget across the SIGKILL;
    - the torn frame classifies ``deterministic`` (``fleet.ipc.errors``)
      and is non-fatal to the supervisor: the slot ejects, restarts,
      and the storm continues;
    - each ``engine_failover`` flight dump names the dead PID, exit
      signal, and re-routed trace ids all belonging to this run, and
      every dump is atomic;
    - counters / e2e histograms / per-request outcomes agree
      fleet-wide (the supervisor re-records worker-side accounting),
      causal chains close, KV slabs balance to zero, and the per-slot
      fleet step p99 stays within budget.
    """
    import functools
    import random
    import signal as _sig

    os.environ["TL_TPU_TRACE"] = "1"
    import tilelang_mesh_tpu  # noqa: F401  (package init before serving)
    from tilelang_mesh_tpu import observability as obs
    from tilelang_mesh_tpu.observability import flight as _flight
    from tilelang_mesh_tpu.observability import histogram as _hist
    from tilelang_mesh_tpu.resilience import inject
    from tilelang_mesh_tpu.serving import (Fleet,
                                           default_workload_factory,
                                           reset_prefix_cache)

    budget_ms = 0.0
    for var in ("TL_TPU_FLEET_P99_BUDGET_MS", "TL_TPU_SERVE_P99_BUDGET_MS"):
        try:
            budget_ms = float(os.environ.get(var) or 0.0)
        except ValueError:
            budget_ms = 0.0
        if budget_ms > 0:
            break
    if budget_ms <= 0:
        budget_ms = 400.0   # CI CPU ceiling + IPC round-trip headroom
    # the disk prefix tier is the CROSS-PROCESS transport here: workers
    # publish to it after every step, adopters restore warm from it
    os.environ["TL_TPU_SERVE_PREFIX_DIR"] = str(out / "prefix")
    reset_prefix_cache()
    _reset_serving_state()
    _flight.configure(dump_dir=out / "flight")

    rng = random.Random(seed)
    tenants = ("acme", "globex", "initech")
    # module-level factory + partial: closures cannot cross the
    # multiprocessing spawn boundary
    factory = functools.partial(default_workload_factory, n_pages=512,
                                page_size=8, heads=2, head_dim=64,
                                batch_buckets=(8,), page_buckets=(2, 4))

    import time as _time
    t_spawn0 = _time.perf_counter()
    fleet = Fleet(factory, n_engines=3, isolation="proc",
                  name="fleet-proc-soak")
    spawn_s = _time.perf_counter() - t_spawn0
    first_pids = {s.name: s.engine.pid for s in fleet.slots}
    t_warm0 = _time.perf_counter()
    warmed = fleet.warmup()
    warm_s = _time.perf_counter() - t_warm0
    ps = 8

    if n_requests < 20:
        print(f"[chaos-fleet-proc] --requests {n_requests} is below the "
              f"soak minimum (20): the kill/readmit/drain phases need "
              f"room to fire", file=sys.stderr)  # noqa: T201
        return 2

    shared = [[rng.randrange(1 << 20) for _ in range(4 * ps)]
              for _ in range(2)]

    def make_request():
        kw = dict(seed=rng.randrange(1 << 30),
                  tenant=rng.choice(tenants))
        if rng.random() < 0.45:
            prompt = list(rng.choice(shared))
            kw.update(context_tokens=len(prompt), prompt_tokens=prompt,
                      new_tokens=rng.choice((1, 2, 3)))
        else:
            kw.update(context_tokens=rng.choice((16, 24, 32)),
                      new_tokens=rng.choice((1, 2)))
        if rng.random() < 0.15:
            kw.update(deadline_ms=4000.0)
        return kw

    drain_wave = max(4, n_requests // 25)
    post_wave = min(24, max(8, n_requests // 20))
    n_streams = 3
    burst = 12
    prefill_burst = 8
    main_wave = (n_requests - drain_wave - post_wave - n_streams
                 - burst - prefill_burst)
    phase1 = max(main_wave // 2, 1)
    print(f"[chaos-fleet-proc] seed={seed}: {n_requests} requests over "  # noqa: T201
          f"{len(fleet.slots)} subprocess workers "
          f"(pids {sorted(first_pids.values())}, spawned in "
          f"{spawn_s:.1f}s, {warmed} kernels warmed in {warm_s:.1f}s); "
          f"SIGKILL mid-stream + mid-prefill, one torn frame, p99 "
          f"budget {budget_ms:g}ms")
    t0 = _time.perf_counter()

    def slot_holding(req):
        for s in fleet.slots:
            if s.engine is not None and req in s.engine.requests:
                return s
        return None

    # seed the shared prefix tier (a worker process writes it; that
    # worker may be dead by the time the pages restore)
    for prompt in shared:
        fleet.submit(context_tokens=len(prompt), prompt_tokens=prompt,
                     new_tokens=1, seed=rng.randrange(1 << 30),
                     tenant="acme")
    fleet.run()

    # storm phase 1
    submitted = 0
    while submitted < phase1:
        wave = min(rng.randrange(6, 25), phase1 - submitted)
        for _ in range(wave):
            fleet.submit(**make_request())
        submitted += wave
        for _ in range(rng.randrange(1, 4)):
            fleet.step()

    # pre-kill burst + streams, then a couple of pumps so the streams
    # are genuinely mid-flight when the SIGKILL lands
    for _ in range(burst):
        prompt = list(rng.choice(shared))
        fleet.submit(context_tokens=len(prompt), prompt_tokens=prompt,
                     new_tokens=rng.choice((2, 3, 4)),
                     seed=rng.randrange(1 << 30),
                     tenant=rng.choice(tenants))
    streams = [fleet.stream(context_tokens=len(shared[0]),
                            prompt_tokens=list(shared[0]),
                            new_tokens=3, seed=rng.randrange(1 << 30),
                            tenant=rng.choice(tenants))
               for _ in range(n_streams)]
    fleet.step()

    # SIGKILL #1: the worker holding the first stream, killed for real
    v1 = (slot_holding(streams[0].request)
          or next(s for s in fleet.slots if s.state == "live"))
    pid1 = v1.engine.pid
    live_before = {s.name for s in fleet.slots if s.state == "live"}
    os.kill(pid1, _sig.SIGKILL)
    fleet.step()
    eject1_ok = v1.state != "live" and v1.name in live_before

    # SIGKILL #2: queue whole-page-prompt prefill work WITHOUT pumping,
    # then kill a second worker holding some of it mid-prefill
    for _ in range(prefill_burst):
        prompt = list(rng.choice(shared))
        fleet.submit(context_tokens=len(prompt), prompt_tokens=prompt,
                     new_tokens=rng.choice((1, 2)),
                     seed=rng.randrange(1 << 30),
                     tenant=rng.choice(tenants))
    v2 = next((s for s in fleet.slots
               if s.state == "live" and s is not v1
               and s.engine is not None and s.engine.queue_depth > 0),
              None) or next(s for s in fleet.slots
                            if s.state == "live" and s is not v1)
    pid2 = v2.engine.pid
    live_before2 = {s.name for s in fleet.slots if s.state == "live"}
    os.kill(pid2, _sig.SIGKILL)
    fleet.step()
    eject2_ok = v2.state != "live" and v2.name in live_before2

    # back to a whole fleet before the torn frame (a torn frame while
    # two slots are still down could leave zero adopters — the zero-
    # loss gate needs a healthy peer to exist, as in any real topology)
    readmitted_mid = fleet.await_readmission(timeout_s=90.0)

    # storm phase 2 with ONE torn frame armed: some RPC in this phase
    # gets a flipped byte; the slot ejects (deterministic FrameError),
    # restarts, and the storm rides through it
    with inject("fleet.ipc", kind="torn", times=1) as torn_spec:
        while submitted < main_wave:
            wave = min(rng.randrange(6, 25), main_wave - submitted)
            for _ in range(wave):
                fleet.submit(**make_request())
            submitted += wave
            for _ in range(rng.randrange(1, 4)):
                fleet.step()
        torn_fired = torn_spec._fired >= 1

    readmitted = fleet.await_readmission(timeout_s=90.0)

    # post-readmission wave: victim #1 must receive NEW dispatches
    # through its restarted process. Steps are interleaved so queue
    # depths and latency windows stay live; the horizon extends
    # (bounded) because the router legitimately favors the LAST-reset
    # slot (the torn-frame victim, empty latency window) until its
    # window refills — the gate still demands an ORGANIC re-dispatch
    # to the SIGKILL victim, never a forced one
    disp_before = obs.metrics_summary()["fleet"]["dispatch"]
    for _ in range(post_wave):
        fleet.submit(**make_request())
        fleet.step()
    fleet.run()
    extra = 0
    while (obs.metrics_summary()["fleet"]["dispatch"]
           .get(v1.name, 0) <= disp_before.get(v1.name, 0)
           and extra < 3 * post_wave):
        fleet.submit(**make_request())
        fleet.step()
        extra += 1
    fleet.run()
    disp_after = obs.metrics_summary()["fleet"]["dispatch"]
    victim_served = (disp_after.get(v1.name, 0)
                     > disp_before.get(v1.name, 0))

    # the streams opened before SIGKILL #1 keep yielding
    stream_tokens = [sum(1 for _ in s) for s in streams]

    fleet.drain()
    for _ in range(drain_wave):
        fleet.submit(**make_request())
    fleet.run()
    wall_s = _time.perf_counter() - t0

    # -- the fleet-proc contract checks --------------------------------
    new_pids = {s.name: (s.engine.pid if s.engine is not None else None)
                for s in fleet.slots}
    leaks = {e: leak for e, leak in fleet.leak_check().items() if leak}
    in_use = sum(s.engine.workload.allocator.in_use
                 for s in fleet.slots if s.engine is not None)
    outcomes = fleet.outcomes()
    summary = obs.metrics_summary()
    counters = summary["serving"]
    counters_all = summary.get("counters", {})
    fleet_sec = summary["fleet"] or {}
    e2e_by_outcome, acct_ok = _serve_accounting(fleet, counters)
    non_terminal = [r.req_id for r in fleet.requests
                    if not r.is_terminal]
    incomplete = [r.req_id for r in fleet.requests
                  if r.is_terminal and not r.trace.complete]
    p99s = {}
    for (hname, labels), h in _hist.histograms():
        if hname == "fleet.step.latency" and h.count:
            p99s[dict(labels).get("engine", "?")] = h.quantile(0.99) * 1e3
    worst_p99 = max(p99s.values()) if p99s else None
    trace_ids = {r.trace_id for r in fleet.requests}
    flight_audit = _audit_flight_dumps(out / "flight")
    failover_heads = []
    for fname in flight_audit["files"]:
        try:
            head = json.loads(
                (out / "flight" / fname).read_text().splitlines()[0])
        except Exception:  # noqa: BLE001 — atomicity gated separately
            continue
        if head.get("reason") == "engine_failover":
            failover_heads.append(head)

    def dump_names_dead_pid(pid, victim_name):
        return any(
            h.get("attrs", {}).get("victim") == victim_name
            and h.get("attrs", {}).get("pid") == pid
            and h.get("attrs", {}).get("signal") == int(_sig.SIGKILL)
            and set(h["attrs"].get("redispatched_trace_ids") or [])
            <= trace_ids
            for h in failover_heads)

    ipc_tx = any(k.startswith("fleet.ipc.tx") for k in counters_all)
    torn_classified = any(
        k.startswith("fleet.ipc.errors") and "kind=deterministic" in k
        for k in counters_all)
    tenants_seen = set(counters.get("tenants", {}))
    checks = {
        "all_terminal": not non_terminal,
        "zero_lost": (not non_terminal
                      and fleet_sec.get("shed_unroutable", 0) == 0),
        "kv_slabs_balance_zero": not leaks and in_use == 0,
        "sigkilled_workers_failed_over": fleet.failovers >= 2
        and v1.name != v2.name,
        "ejected_within_kill_step": eject1_ok and eject2_ok,
        "warm_restore_redispatch": fleet_sec.get("warm_restores",
                                                 0) >= 1,
        "torn_frame_ejected_and_recovered": torn_fired
        and fleet.failovers >= 3 and torn_classified,
        "victims_restarted_new_pid": all(
            new_pids.get(v.name) not in (None, first_pids[v.name])
            for v in (v1, v2)),
        "victims_readmitted_after_probe": readmitted and readmitted_mid
        and all(s.state == "live" for s in fleet.slots)
        and all(fleet_sec.get("readmits", {}).get(v.name, 0) >= 1
                for v in (v1, v2)),
        "victim_served_after_readmit": victim_served,
        "streams_survived_sigkill": all(n == 3 for n in stream_tokens),
        "ipc_counters_present": ipc_tx,
        "per_tenant_accounting": set(tenants) <= tenants_seen,
        "accounting_matches_histograms": acct_ok,
        "causal_chains_complete": not incomplete,
        "failover_flight_dump_names_dead_pid":
        dump_names_dead_pid(pid1, v1.name)
        and dump_names_dead_pid(pid2, v2.name),
        "flight_dumps_atomic": flight_audit["atomic"],
        "fleet_p99_within_budget": worst_p99 is not None
        and worst_p99 <= budget_ms,
    }
    ok = all(checks.values())

    report = {
        "mode": "fleet-proc", "seed": seed,
        "requests": len(fleet.requests),
        "engines": [s.name for s in fleet.slots],
        "isolation": "proc",
        "victims": {v1.name: pid1, v2.name: pid2},
        "first_pids": first_pids, "final_pids": new_pids,
        "spawn_s": round(spawn_s, 3),
        "post_wave_dispatch": {"before": disp_before,
                               "after": disp_after},
        "wall_s": round(wall_s, 3), "warmup_s": round(warm_s, 3),
        "warmed_kernels": warmed,
        "outcomes": outcomes,
        "shed_by_reason": counters["shed"],
        "tenants": counters.get("tenants", {}),
        "fleet": fleet_sec,
        "ipc": {k: v for k, v in sorted(counters_all.items())
                if k.startswith("fleet.ipc.")
                or k.startswith("fleet.worker.")},
        "stream_tokens": stream_tokens,
        "step_p99_ms": {e: round(v, 3) for e, v in sorted(p99s.items())},
        "step_p99_budget_ms": budget_ms,
        "kv_leaks": {e: leak for e, leak in leaks.items()},
        "e2e_by_outcome": e2e_by_outcome,
        "non_terminal_requests": non_terminal,
        "causally_incomplete_requests": incomplete,
        "flight": flight_audit,
        "checks": checks, "ok": ok,
    }
    trace_path = out / "fleet_proc_trace.jsonl"
    obs.write_jsonl(str(trace_path))
    (out / "fleet_proc_report.json").write_text(
        json.dumps(report, indent=2))
    from ..tools.analyzer import format_fleet_report, format_serve_report
    records = obs.read_jsonl(str(trace_path))
    summary_txt = (format_fleet_report(records) + "\n\n"
                   + format_serve_report(records))
    (out / "fleet_proc_report.txt").write_text(summary_txt + "\n")
    print(summary_txt)  # noqa: T201
    for k, v in checks.items():
        print(f"[chaos-fleet-proc] {k}: {'OK' if v else 'FAIL'}")  # noqa: T201
    print(f"[chaos-fleet-proc] victims={{{v1.name}: {pid1}, "  # noqa: T201
          f"{v2.name}: {pid2}}} outcomes={outcomes} "
          f"warm={fleet_sec.get('warm_restores', 0)} in {wall_s:.1f}s "
          f"-> {'PASS' if ok else 'FAIL'}; artifacts in {out}/")
    fleet.shutdown(graceful=True)
    return 0 if ok else 1


def run_verify(out: Path, seed: int) -> int:
    """The default mode: seeded corruption on the comm interpret paths,
    the differential selfcheck must catch every scenario."""
    os.environ["TL_TPU_TRACE"] = "1"
    os.environ["TL_TPU_SELFCHECK"] = "1"
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")

    from tilelang_mesh_tpu import observability as obs

    obs.reset()      # per-seed clean slate (multi-seed invocations)
    report = {"seed": seed, "scenarios": []}
    ok = True
    for i, (name, prog, cfg, site) in enumerate(_programs()):
        ok = _run_one(name, prog, cfg, site, seed + i, report) and ok
    report["ok"] = ok

    trace_path = out / "chaos_trace.jsonl"
    obs.write_jsonl(str(trace_path))
    (out / "chaos_report.json").write_text(json.dumps(report, indent=2))

    from ..tools.analyzer import format_verify_report
    summary = format_verify_report(obs.read_jsonl(str(trace_path)))
    (out / "chaos_report.txt").write_text(summary + "\n")
    print(summary)  # noqa: T201
    print(f"[chaos-verify] {'PASS' if ok else 'FAIL'}; artifacts in "  # noqa: T201
          f"{out}/")
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tilelang_mesh_tpu.verify.chaos",
        description="Seeded chaos run proving the mesh guardrails catch "
                    "corrupted collective schedules (docs/robustness.md).")
    ap.add_argument("--out", default="chaos_report",
                    help="directory for the trace + report artifacts")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--seeds", type=str, default=None,
                    help="comma-separated seed list (e.g. 7,13,42): runs "
                         "the selected mode once per seed — artifacts in "
                         "<out>/seed<N> when more than one — and exits "
                         "with the worst run's code. Overrides --seed.")
    ap.add_argument("--device-loss", action="store_true",
                    help="device-loss mode: kill the worker at a seeded "
                         "random config index of a bench.py --hermetic "
                         "sweep and assert the failover tier still "
                         "produces a record per CPU-safe config")
    ap.add_argument("--serve", action="store_true",
                    help="serving-engine soak: seeded request storm with "
                         "serve.* faults armed and the device killed "
                         "mid-batch; asserts every request reaches a "
                         "terminal outcome with zero KV-slab leaks "
                         "(docs/serving.md)")
    ap.add_argument("--serve-mesh", action="store_true",
                    help="elastic mesh-serving soak: the storm through a "
                         "MeshDecodeWorkload sharded over the 2x2 host "
                         "mesh, a mesh slice killed mid-step; asserts "
                         "100%% terminal outcomes, a recorded reshard "
                         "down the layout ladder, zero KV leaks, and "
                         "byte-conservation across the KV migration "
                         "(docs/serving.md)")
    ap.add_argument("--serve-lifecycle", action="store_true",
                    help="full-lifecycle serving soak: mixed shared-"
                         "prompt / long-prompt / decode / streaming / "
                         "cancel traffic with chunked prefill "
                         "interleaved; asserts 100%% terminal outcomes, "
                         "zero KV leaks, >= 1 prefix-cache hit, and "
                         "decode p99 within budget (docs/serving.md)")
    ap.add_argument("--fleet", action="store_true",
                    help="fleet soak: a multi-tenant storm through a "
                         "supervised 3-engine Fleet with one engine "
                         "killed mid-stream (serve.engine armed "
                         "unreachable); asserts zero lost requests, "
                         "100%% terminal outcomes, >= 1 warm prefix "
                         "restore on failover, victim re-admitted and "
                         "serving again, streams yielding across the "
                         "kill, and fleet p99 within budget "
                         "(docs/serving.md)")
    ap.add_argument("--fleet-proc", action="store_true",
                    help="process-isolated fleet soak: the storm "
                         "through a 3-subprocess-worker Fleet "
                         "(TL_TPU_FLEET_ISOLATION=proc) with one "
                         "worker SIGKILLed mid-stream, a second "
                         "mid-prefill, and a torn IPC frame armed; "
                         "asserts zero lost requests, victims "
                         "restarted under new pids and re-admitted, "
                         ">= 1 warm restore from the disk prefix "
                         "tier, streams yielding across the SIGKILL, "
                         "and flight dumps naming the dead pids "
                         "(docs/serving.md)")
    ap.add_argument("--requests", type=int, default=500,
                    help="request count for --serve / --serve-mesh / "
                         "--serve-lifecycle / --fleet / --fleet-proc "
                         "(default 500)")
    args = ap.parse_args(argv)

    try:
        seeds = ([int(s) for s in args.seeds.split(",") if s.strip()]
                 if args.seeds else [args.seed])
    except ValueError:
        ap.error(f"--seeds must be a comma list of integers, got "
                 f"{args.seeds!r}")
    if not seeds:
        ap.error("--seeds parsed to an empty list")
    out = Path(args.out)

    def per_seed(runner) -> int:
        rc = 0
        for s in seeds:
            d = out if len(seeds) == 1 else out / f"seed{s}"
            d.mkdir(parents=True, exist_ok=True)
            rc = max(rc, runner(d, s))
        return rc

    if args.device_loss:
        return per_seed(run_device_loss)
    if args.serve:
        return per_seed(lambda d, s: run_serve(d, s, args.requests))
    if args.serve_mesh:
        return per_seed(lambda d, s: run_serve_mesh(d, s, args.requests))
    if args.serve_lifecycle:
        return per_seed(lambda d, s: run_serve_lifecycle(d, s,
                                                         args.requests))
    if args.fleet:
        return per_seed(lambda d, s: run_fleet(d, s, args.requests))
    if args.fleet_proc:
        return per_seed(lambda d, s: run_fleet_proc(d, s,
                                                    args.requests))
    return per_seed(run_verify)


if __name__ == "__main__":
    sys.exit(main())
