"""Static mesh schedule verifier.

An independent correctness net over the segment list `lower_mesh`
is about to compile — run AFTER ``transform/comm_opt.py`` has rewritten
it, so a miscompiling rewrite (or a corrupted schedule from any other
source) is caught before it becomes a silently-wrong compiled program.
"Independent" is load-bearing: the verifier re-derives payload identity,
data dependence, and wire-byte totals from the IR itself rather than
trusting the optimizer's own bookkeeping, the same way the pre-lower
semantic checks (analysis/checkers.py) re-derive loop legality instead
of trusting the tracer.

Checks, per the four failure classes a rewritten collective schedule
can introduce:

1. **SPMD deadlock freedom** — every core must execute the same
   collective sequence: no collective may hide inside a compute
   segment (where per-core control flow could skip it), a barrier may
   not synchronize only a subset of the mesh's cores, and every member
   of a fused op must agree on kind and mesh axis (a direction-mixed
   fused op would have different cores waiting on different axes).
2. **Races** — members batched into one simultaneous ``CommFused`` op
   must be pairwise data-independent (no member reads or overwrites
   what another member writes), and a ``CommChunked`` overlap window —
   the region between the chunked collective and the consumer segment
   that reads it — must not contain a write to the in-flight buffer.
3. **Payload/slot agreement** — members sharing a fused payload *slot*
   must move byte-identical regions (same buffer, window, dtype,
   semantics), and no collective's payload region may alias its
   destination region (the NoC schedule would read bytes it is
   concurrently overwriting).
4. **Wire-byte conservation** — the bytes the final op sequence moves,
   re-derived from ``comm_cost``, must equal both the per-record
   ``attrs["collectives"]`` accounting and the optimizer's own
   ``post_wire_bytes`` claim; a mismatch means a rewrite lost or
   invented payload.

``TL_TPU_VERIFY`` (or pass config ``tl.tpu.verify``) selects the mode:
``1``/``on`` (default) raises :class:`MeshVerifyError` on violations and
records warnings in ``plan_desc``; ``strict`` escalates warnings to
errors; ``0``/``off`` disables the pass. Every run lands in the tracer
(``verify.*`` counters, ``verify.warning``/``verify.error`` events) and
``metrics_summary()["verify"]``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence, Set, Tuple

from ..ir import (CommAllGather, CommAllReduce, CommBarrier, CommBroadcast,
                  CommChunked, CommFence, CommFused, CommPut, CommStmt,
                  Region, walk)
from ..observability import tracer as _trace
from ..resilience.errors import DeterministicError

__all__ = ["MeshVerifyError", "VerifyReport", "verify_mode",
           "verify_schedule"]

MODES = ("off", "on", "strict")


class MeshVerifyError(DeterministicError):
    """A rewritten mesh schedule failed static verification. Subclasses
    ``DeterministicError``: retrying the same compile cannot help, and
    the circuit breaker should learn the signature."""


@dataclass
class VerifyReport:
    """Outcome of one verifier run over a final segment list."""
    mode: str
    checked: int = 0                  # collectives examined
    errors: List[str] = field(default_factory=list)
    warnings: List[str] = field(default_factory=list)

    def attrs_record(self) -> dict:
        """JSON-safe record for CompiledArtifact.attrs['verify']."""
        return {"mode": self.mode, "checked": self.checked,
                "warnings": list(self.warnings)}


def verify_mode(pass_cfg: Optional[dict] = None) -> str:
    """Active verifier mode: ``tl.tpu.verify`` pass config when present,
    else ``TL_TPU_VERIFY``. Unknown tokens raise — a typo'd mode must
    not silently disable the safety net."""
    raw: Any = None
    if pass_cfg:
        raw = pass_cfg.get("tl.tpu.verify")
    if raw is None:
        from ..env import env
        raw = env.TL_TPU_VERIFY
    raw = str(raw).strip().lower()
    if raw in ("1", "on", "true", "yes", ""):
        return "on"
    if raw in ("0", "off", "false", "no", "none"):
        return "off"
    if raw == "strict":
        return "strict"
    raise ValueError(
        f"unknown TL_TPU_VERIFY mode {raw!r}; valid values are 0/off, "
        f"1/on, strict")


# ---------------------------------------------------------------------------
# independent payload identity (deliberately NOT comm_opt's _slot_key:
# the net re-derives what two ops move from the IR regions themselves)
# ---------------------------------------------------------------------------


def _region_id(r: Region) -> tuple:
    return (r.buffer.uid, tuple(str(b) for b in r.base),
            tuple(str(s) for s in r.shape), r.dtype)


def _payload_identity(c: CommStmt) -> Optional[tuple]:
    """What one collective moves over the wire: payload region identity
    plus the semantics that change its bytes. Two ops may share a fused
    payload slot only when these agree exactly."""
    if isinstance(c, CommBroadcast):
        return ("broadcast", _region_id(c.src), c.size, c.src_core)
    if isinstance(c, CommAllGather):
        return ("all_gather", _region_id(c.send), c.size)
    if isinstance(c, CommAllReduce):
        return ("all_reduce", _region_id(c.buffer), c.reduce_type, c.dim)
    if isinstance(c, CommPut):
        return ("put", _region_id(c.src), c.size, c.src_core, c.dst_core)
    return None


def _alias_pairs(c: CommStmt) -> List[Tuple[Region, Region, str]]:
    """(payload region, destination region) pairs that must not share a
    buffer: the schedule would read payload bytes it is concurrently
    overwriting. The all_reduce accumulate read (clear=False) is not a
    pair — reading the destination is its semantics."""
    if isinstance(c, CommBroadcast):
        return [(c.src, c.dst, "src/dst")]
    if isinstance(c, CommPut):
        return [(c.src, c.dst, "src/dst")]
    if isinstance(c, CommAllGather):
        return [(c.send, c.recv, "send/recv")]
    if isinstance(c, CommAllReduce):
        return [(c.buffer, c.out, "buffer/out")]
    return []


def _leaf_ops(c: CommStmt) -> List[CommStmt]:
    if isinstance(c, CommFused):
        return list(c.ops)
    if isinstance(c, CommChunked):
        return [c.op]
    return [c]


def _chunk_extent(c: CommStmt) -> Optional[int]:
    """Leading-axis extent the overlap rewrite splits, or None when this
    op kind cannot be chunked at all."""
    from ..transform.comm_opt import PSUMMABLE
    if isinstance(c, CommAllGather):
        shape = c.send.static_shape()
        return shape[0] if shape else None
    if isinstance(c, CommAllReduce) and c.reduce_type in PSUMMABLE:
        shape = c.out.static_shape()
        return shape[0] if shape else None
    return None


# ---------------------------------------------------------------------------
# the checks
# ---------------------------------------------------------------------------


def _check_uniformity(c: CommStmt, i: int, n_cores: int, desc, rep):
    if isinstance(c, CommBarrier) and c.group is not None:
        cores = set(c.group)
        if cores != set(range(n_cores)):
            rep.errors.append(
                f"[{i}] subset barrier: {desc(c)} synchronizes only "
                f"cores {sorted(cores)} of {n_cores} — cores outside "
                f"the group deadlock waiting for a barrier they never "
                f"reach")
    if isinstance(c, CommFused):
        head = c.ops[0]
        for j, m in enumerate(c.ops[1:], start=1):
            if type(m) is not type(head):
                rep.errors.append(
                    f"[{i}] mixed-kind fused op: member[{j}] {desc(m)} "
                    f"is a {type(m).__name__} inside a fused "
                    f"{type(head).__name__} batch")
            elif getattr(m, "direction", 2) != getattr(head, "direction",
                                                       2):
                rep.errors.append(
                    f"[{i}] mixed-axis fused op: member[{j}] {desc(m)} "
                    f"runs on a different mesh axis than {desc(head)} — "
                    f"cores would wait on different collective "
                    f"sequences")


def _check_alias(c: CommStmt, i: int, desc, rep):
    for leaf in _leaf_ops(c):
        for payload, dst, what in _alias_pairs(leaf):
            if payload.buffer.uid == dst.buffer.uid:
                rep.errors.append(
                    f"[{i}] payload/recv alias: {desc(leaf)} {what} "
                    f"regions share buffer {payload.buffer.name!r} — "
                    f"the schedule would read payload bytes it is "
                    f"concurrently overwriting")


def _check_fused(c: CommFused, i: int, desc, rw_of, rep):
    if len(c.ops) != len(c.slots):
        rep.errors.append(
            f"[{i}] malformed fused op: {len(c.ops)} members but "
            f"{len(c.slots)} slot assignments")
        return
    # slot agreement: members sharing a slot must move identical bytes
    by_slot: dict = {}
    for j, (m, s) in enumerate(zip(c.ops, c.slots)):
        ident = _payload_identity(m)
        prev = by_slot.get(s)
        if prev is None:
            by_slot[s] = (j, ident)
        elif prev[1] != ident:
            rep.errors.append(
                f"[{i}] mismatched fused slot {s}: member[{j}] "
                f"{desc(m)} does not move the same payload as "
                f"member[{prev[0]}] {desc(c.ops[prev[0]])} — fanning "
                f"one wire transfer out to both would corrupt one "
                f"destination")
    # data independence: fusion executes members as ONE simultaneous op
    seen_reads: Set[int] = set()
    seen_writes: Set[int] = set()
    for j, m in enumerate(c.ops):
        reads, writes = rw_of(m)
        if j and ((reads & seen_writes) or (writes & seen_writes)
                  or (writes & seen_reads)):
            rep.errors.append(
                f"[{i}] race inside fused op: member[{j}] {desc(m)} "
                f"touches a buffer another member writes — batching "
                f"reorders them into one simultaneous op")
        seen_reads |= reads
        seen_writes |= writes


def _check_chunked(c: CommChunked, i: int, segments, seg_rw, gp_uids,
                   desc, rw_of, rep):
    inner = c.op
    if c.chunks < 2:
        rep.errors.append(
            f"[{i}] degenerate chunking: {desc(inner)} split into "
            f"{c.chunks} chunk(s)")
    extent = _chunk_extent(inner)
    if extent is None:
        rep.errors.append(
            f"[{i}] unchunkable collective: {desc(inner)} cannot be "
            f"split on a leading axis")
        return
    if extent % c.chunks != 0:
        rep.errors.append(
            f"[{i}] dropped chunk: {desc(inner)} leading extent "
            f"{extent} is not divisible into {c.chunks} chunks — "
            f"{extent % c.chunks} trailing row(s) would never cross "
            f"the wire")
    _, writes = rw_of(inner)
    # the overlap window: everything between the chunked transfer and
    # the consumer that reads it races against the in-flight chunks
    consumer = None
    for j in range(i + 1, len(segments)):
        jkind, jpayload = segments[j]
        reads_j, writes_j = seg_rw[j]
        hit_w = writes & writes_j
        hit_r = writes & reads_j
        if jkind == "compute":
            if hit_r:
                consumer = j
                break
            if hit_w:
                rep.errors.append(
                    f"[{i}] comm/compute race: segment [{j}] overwrites "
                    f"the result of {desc(inner)} while its pipelined "
                    f"chunks may still be in flight")
                break
        else:
            if hit_w:
                rep.errors.append(
                    f"[{i}] write-write race: collective [{j}] "
                    f"{desc(jpayload)} overwrites the in-flight result "
                    f"of chunked {desc(inner)}")
                break
            if hit_r:
                rep.warnings.append(
                    f"[{i}] chunked {desc(inner)} feeds collective "
                    f"[{j}], not a compute segment — nothing overlaps "
                    f"the pipelined chunks")
                consumer = j
                break
    if consumer is None and not (writes & gp_uids) and not rep.errors:
        rep.warnings.append(
            f"[{i}] chunked {desc(inner)} has no consumer — the "
            f"overlap rewrite buys nothing here")


def _check_emit_meta(c: CommStmt, i: int, cost_fn, desc, rep):
    """Defense in depth: the payload bytes the frontend recorded at
    emission must agree with the bytes the lowering will move."""
    for leaf in _leaf_ops(c):
        meta = getattr(leaf, "emit_meta", None)
        if not meta or not meta.get("payload_bytes"):
            continue
        _, per_hop = cost_fn(leaf)
        if per_hop and meta["payload_bytes"] != per_hop:
            rep.warnings.append(
                f"[{i}] payload accounting drift: {desc(leaf)} was "
                f"emitted as {meta['payload_bytes']}B but lowers to "
                f"{per_hop}B per hop")


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def verify_schedule(segments: Sequence[Tuple[str, Any]],
                    seg_rw: Sequence[Tuple[set, set]],
                    global_out_uids: Set[int],
                    nrow: int, ncol: int,
                    mode: str = "on",
                    collective_recs: Optional[List[dict]] = None,
                    comm_opt_rec: Optional[dict] = None,
                    kernel: str = "?") -> VerifyReport:
    """Verify the FINAL (post-comm_opt) segment list of one mesh
    program. Raises :class:`MeshVerifyError` naming every offending op
    when a check fails (warnings too, in ``strict`` mode); returns the
    report otherwise so the caller can record findings in plan_desc."""
    from ..parallel.lowering import _comm_buffers, _comm_desc, comm_cost
    if mode not in MODES:
        raise ValueError(f"unknown verify mode {mode!r}")
    rep = VerifyReport(mode=mode)
    if mode == "off":
        return rep
    n_cores = nrow * ncol

    def desc(c: CommStmt) -> str:
        return _comm_desc(c, nrow, ncol)

    def rw_of(c: CommStmt) -> Tuple[Set[int], Set[int]]:
        r, w = _comm_buffers(c)
        return ({x.buffer.uid for x in r}, {x.buffer.uid for x in w})

    def cost_fn(c: CommStmt):
        return comm_cost(c, nrow, ncol)

    recomputed_wire = 0
    for i, (kind, payload) in enumerate(segments):
        if kind == "compute":
            # uniformity: a collective nested in per-core compute would
            # be reachable by only the cores whose control flow hits it
            for s in payload:
                walk(s, lambda x: rep.errors.append(
                    f"[{i}] collective {desc(x)} embedded inside a "
                    f"compute segment — per-core control flow could "
                    f"skip it on a subset of the mesh")
                    if isinstance(x, CommStmt) else None)
            continue
        c = payload
        rep.checked += 1
        _check_uniformity(c, i, n_cores, desc, rep)
        if isinstance(c, (CommBarrier, CommFence)):
            continue
        _check_alias(c, i, desc, rep)
        _check_emit_meta(c, i, cost_fn, desc, rep)
        if isinstance(c, CommFused):
            _check_fused(c, i, desc, rw_of, rep)
        if isinstance(c, CommChunked):
            _check_chunked(c, i, segments, seg_rw, global_out_uids,
                           desc, rw_of, rep)
        hops, per_hop = cost_fn(c)
        recomputed_wire += hops * per_hop

    # wire-byte conservation: the independent re-derivation must match
    # both accounting surfaces
    if collective_recs is not None:
        accounted = sum(r.get("wire_bytes", 0) for r in collective_recs)
        if accounted != recomputed_wire:
            rep.errors.append(
                f"wire-byte conservation: attrs['collectives'] accounts "
                f"{accounted}B but the op sequence moves "
                f"{recomputed_wire}B")
    if comm_opt_rec is not None:
        claimed = comm_opt_rec.get("post_wire_bytes", 0)
        if claimed != recomputed_wire:
            rep.errors.append(
                f"wire-byte conservation: comm_opt claims "
                f"{claimed}B post-optimization but the op sequence "
                f"moves {recomputed_wire}B")
        if comm_opt_rec.get("rewrites") and \
                claimed > comm_opt_rec.get("pre_wire_bytes", claimed):
            rep.warnings.append(
                f"comm_opt increased wire bytes: "
                f"{comm_opt_rec.get('pre_wire_bytes')}B -> {claimed}B")

    _trace.inc("verify.schedules")
    _trace.inc("verify.collectives_checked", rep.checked)
    for w in rep.warnings:
        _trace.inc("verify.warnings")
        _trace.event("verify.warning", "verify", kernel=kernel, finding=w)
    if mode == "strict" and rep.warnings:
        rep.errors.extend(f"(strict) {w}" for w in rep.warnings)
    if rep.errors:
        _trace.inc("verify.errors", len(rep.errors))
        for e in rep.errors:
            _trace.event("verify.error", "verify", kernel=kernel,
                         finding=e)
        from ..observability import flight as _flight
        _flight.dump("mesh_verify_error", kernel=kernel,
                     errors=list(rep.errors))
        raise MeshVerifyError(
            f"{kernel}: mesh schedule verification failed "
            f"({len(rep.errors)} violation(s)):\n  - " +
            "\n  - ".join(rep.errors), site="verify.schedule")
    return rep
