"""Mesh collective verifier & runtime guardrails.

An independent correctness net around the mesh layer, wired in at three
points (see docs/robustness.md, "Schedule verification & guardrails"):

- ``schedule`` — the static schedule verifier run inside
  ``parallel/lowering.lower_mesh`` after ``transform/comm_opt.py``:
  SPMD deadlock freedom, fused-slot agreement, overlap races,
  payload/recv aliasing, and wire-byte conservation. ``TL_TPU_VERIFY``
  (default on; ``strict`` escalates warnings) — hard
  :class:`MeshVerifyError` on violation.
- ``runtime`` — opt-in dispatch guards: the differential self-check
  (``TL_TPU_SELFCHECK=1``: optimized vs ``TL_TPU_COMM_OPT=0`` outputs on
  first call), the NaN/Inf sanitizer (``TL_TPU_SANITIZE=1``), and the
  per-collective watchdog (``TL_TPU_COMM_TIMEOUT_MS``).
- ``chaos`` — the seeded chaos-verify driver CI runs: arms faults on
  the comm interpret paths and asserts the guardrails catch them
  (``python -m tilelang_mesh_tpu.verify.chaos``).

Everything reports through ``verify.*`` tracer counters/events,
``metrics_summary()["verify"]``, and the ``analyzer verify`` subcommand.
"""

from .runtime import (GuardState, NumericError, SelfCheckDivergence,
                      check_flags, check_host_outputs, compare_outputs,
                      guard_state, sanitize_enabled, tolerance_for,
                      watchdog_call)
from .schedule import (MeshVerifyError, VerifyReport, verify_mode,
                       verify_schedule)

__all__ = [
    "MeshVerifyError", "VerifyReport", "verify_mode", "verify_schedule",
    "NumericError", "SelfCheckDivergence", "GuardState", "guard_state",
    "sanitize_enabled", "tolerance_for", "compare_outputs",
    "check_host_outputs", "check_flags", "watchdog_call",
]
