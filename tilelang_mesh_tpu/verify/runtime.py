"""Runtime guardrails for mesh kernels: differential self-check,
numeric sanitizer, and the collective watchdog.

Three opt-in nets around the dispatch path (all default-off, all
zero-cost when off — the dispatch fast path is one env read per knob,
the same contract as ``TL_TPU_RUNTIME_METRICS``):

- **Self-check** (``TL_TPU_SELFCHECK=1``): the FIRST call of each
  comm-opt-rewritten mesh kernel also runs through the
  ``TL_TPU_COMM_OPT=0`` schedule and compares outputs within dtype
  tolerance. Divergence is a deterministic :class:`SelfCheckDivergence`;
  under ``TL_TPU_FALLBACK=interp`` (the default) the kernel degrades to
  the unoptimized schedule and returns its (trustworthy) result instead
  of raising.
- **Sanitizer** (``TL_TPU_SANITIZE=1``): NaN/Inf checks on every
  floating collective payload and kernel output. Mesh kernels lazily
  build a sanitized variant of their SPMD program whose per-payload
  finite flags ride back as one extra (replicated) output; plain
  kernels check their outputs host-side. Violations raise
  :class:`NumericError` naming the poisoned payload.
- **Watchdog** (``TL_TPU_COMM_TIMEOUT_MS=N``): a mesh dispatch that
  exceeds ``N x n_collectives`` ms is classified as a timeout
  ``TLError``, trips the shared circuit breaker, and degrades to the
  unoptimized schedule (a hung rewritten collective must not wedge the
  serving process). The wedged device call cannot be interrupted, so
  its worker thread is abandoned — uniquely named, like the
  autotuner's timed-out trial workers.

All three report through ``verify.*`` counters/events,
``metrics_summary()["verify"]``, and ``analyzer verify``.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

from ..env import env
from ..observability import tracer as _trace
from ..resilience.errors import DeterministicError, TLTimeoutError

__all__ = ["NumericError", "SelfCheckDivergence", "GuardState",
           "guard_state", "sanitize_enabled", "sanitize_mode",
           "parse_sanitize_raw", "note_elided", "tolerance_for",
           "compare_outputs", "check_host_outputs", "check_flags",
           "watchdog_call"]

logger = logging.getLogger("tilelang_mesh_tpu.verify")


class NumericError(DeterministicError):
    """The sanitizer found a NaN/Inf on a collective payload or kernel
    output."""


class SelfCheckDivergence(DeterministicError):
    """The optimized schedule's outputs diverged from the
    ``TL_TPU_COMM_OPT=0`` reference beyond dtype tolerance."""


class GuardState:
    """Snapshot of the enabled guards for one dispatch. Only allocated
    when at least one guard is on — the disabled path returns the
    module-level ``None`` so tests can assert zero allocation.
    ``sanitize`` carries the MODE (``"on"``/``"auto"``/``False``) so
    the dispatch paths can elide statically-proven checks in auto."""

    __slots__ = ("selfcheck", "sanitize", "timeout_ms")

    def __init__(self, selfcheck: bool, sanitize, timeout_ms: float):
        self.selfcheck = selfcheck
        self.sanitize = sanitize
        self.timeout_ms = timeout_ms


def parse_sanitize_raw(raw: Optional[str]) -> str:
    """The ONE ``TL_TPU_SANITIZE`` grammar: ``off``/``on``/``auto``
    from a raw env value (None = unset = off); a typo raises instead of
    silently disabling the guard (the lint_mode/verify_mode contract).
    Shared with the fast-dispatch flag cache (jit/dispatch.py), which
    parses its own env snapshot."""
    if raw is None:
        return "off"
    raw = raw.strip().lower()
    if raw in ("", "0", "off", "false", "none", "no"):
        return "off"
    if raw in ("1", "on", "true", "yes"):
        return "on"
    if raw == "auto":
        return "auto"
    raise ValueError(
        f"unknown TL_TPU_SANITIZE mode {raw!r}; valid values are 0/off "
        f"(default), 1/on, auto")


def sanitize_mode() -> str:
    """The resolved ``TL_TPU_SANITIZE`` mode: ``off`` (default) /
    ``on`` / ``auto``. ``auto`` skips the runtime NaN/Inf pass for
    payloads and outputs the tl-num analysis proved finite
    (``attrs["numerics"]``, analysis/numerics.py) and checks only the
    unproven rest."""
    return parse_sanitize_raw(str(env.TL_TPU_SANITIZE))


def guard_state() -> Optional[GuardState]:
    """The enabled runtime guards, or None when everything is off (the
    common case: short-circuiting env reads, no allocation)."""
    sc = env.TL_TPU_SELFCHECK
    sz = sanitize_mode()
    to = env.TL_TPU_COMM_TIMEOUT_MS
    if not (sc or sz != "off" or to > 0):
        return None
    return GuardState(sc, False if sz == "off" else sz, to)


def sanitize_enabled() -> bool:
    return sanitize_mode() != "off"


def note_elided(kernel: str, n: int = 1) -> None:
    """Count a statically-proven check the auto mode skipped — the
    observable half of the elision contract (docs/robustness.md)."""
    _trace.inc("sanitize.elided", value=n, kernel=kernel)


# ---------------------------------------------------------------------------
# numeric comparison
# ---------------------------------------------------------------------------

_TOLERANCES = {
    "float64": (1e-9, 1e-12),
    "float32": (1e-5, 1e-6),
    "bfloat16": (2e-2, 1e-2),
    "float16": (1e-3, 1e-3),
}


def tolerance_for(dtype: str) -> Tuple[float, float]:
    """(rtol, atol) for one dtype; integers compare exactly."""
    return _TOLERANCES.get(str(dtype), (0.0, 0.0))


def compare_outputs(got: Sequence, want: Sequence,
                    names: Sequence[str],
                    tol_floor: Optional[Tuple[float, float]] = None
                    ) -> List[str]:
    """Compare two output tuples leaf-by-leaf within dtype tolerance;
    returns a description per diverging leaf (empty = equivalent).

    ``tol_floor`` raises the floating-point tolerance floor — the
    tile-opt dtype-narrowing selfcheck compares an internally-bf16
    kernel against its full-precision twin, so the float outputs carry
    the NARROWED dtype's rounding even though their own dtype is f32.
    Integer outputs still compare exactly (narrowing proofs for ints are
    range containment — no rounding exists to forgive)."""
    import numpy as np
    divs: List[str] = []
    for g, w, name in zip(got, want, names):
        ga, wa = np.asarray(g), np.asarray(w)
        if ga.shape != wa.shape:
            divs.append(f"{name}: shape {ga.shape} vs {wa.shape}")
            continue
        rtol, atol = tolerance_for(str(wa.dtype))
        if tol_floor is not None and (rtol or atol
                                      or wa.dtype.kind == "f"):
            rtol = max(rtol, tol_floor[0])
            atol = max(atol, tol_floor[1])
        gf = ga.astype(np.float64) if ga.dtype != np.float64 else ga
        wf = wa.astype(np.float64) if wa.dtype != np.float64 else wa
        with np.errstate(invalid="ignore"):
            ok = np.isclose(gf, wf, rtol=rtol, atol=atol, equal_nan=True)
        if not ok.all():
            bad = int((~ok).sum())
            idx = tuple(int(x[0]) for x in np.nonzero(~ok))
            divs.append(
                f"{name}: {bad}/{ok.size} element(s) beyond "
                f"rtol={rtol}/atol={atol}, first at {idx} "
                f"(got {gf[idx]!r}, want {wf[idx]!r})")
    return divs


# ---------------------------------------------------------------------------
# sanitizer
# ---------------------------------------------------------------------------


def is_float_dtype(dtype: str) -> bool:
    return str(dtype).startswith(("float", "bfloat"))


def check_flags(flags, checks: Sequence[str], kernel: str) -> None:
    """Validate the bad-element counts a sanitized SPMD program returned
    (one per registered check, in registration order)."""
    import numpy as np
    vals = np.asarray(flags)
    for bad, what in zip(vals, checks):
        if int(bad) > 0:
            _trace.inc("verify.sanitize.violations")
            _trace.event("verify.sanitize_violation", "verify",
                         kernel=kernel, check=what)
            raise NumericError(
                f"{kernel}: NaN/Inf detected on {what} "
                f"(TL_TPU_SANITIZE=1)", site="comm.sanitize")


def check_host_outputs(results: Sequence, names: Sequence[str],
                       kernel: str) -> None:
    """Host-side NaN/Inf check over a kernel's output leaves (the
    non-mesh path: no SPMD program to instrument)."""
    import jax.numpy as jnp
    for r, name in zip(results, names):
        if not is_float_dtype(str(getattr(r, "dtype", ""))):
            continue
        if bool(jnp.isfinite(r).all()):
            continue
        _trace.inc("verify.sanitize.violations")
        _trace.event("verify.sanitize_violation", "verify", kernel=kernel,
                     check=f"output {name}")
        raise NumericError(
            f"{kernel}: NaN/Inf detected on output {name!r} "
            f"(TL_TPU_SANITIZE=1)", site="comm.sanitize")


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------

_watchdog_seq = itertools.count()


def watchdog_call(fn: Callable, timeout_ms: float, n_collectives: int,
                  kernel: str):
    """Run ``fn()`` (a device dispatch) under the collective watchdog:
    the budget is ``timeout_ms`` per collective. On expiry the worker is
    abandoned (a wedged ICI transfer cannot be interrupted in-process)
    and a timeout ``TLError`` is raised for the caller to classify.

    The budget is enforced on the dispatch's measured wall time, not
    only on the queue wait: a dispatch whose result lands but took
    longer than the budget is still classified as a timeout. A caller
    with a budget has already missed it either way, and relying on the
    queue wait alone made the verdict depend on thread scheduling — a
    fast warm dispatch could finish before this thread ever reached
    ``q.get``, silently passing a budget it had blown (the
    test_watchdog_exempts_first_call_compile flake when the process was
    warm). The clock runs INSIDE the worker, around ``fn()`` itself, so
    thread-spawn and wakeup latency on a loaded host never count
    against a tight collective budget."""
    import queue
    import jax

    budget_s = timeout_ms * max(1, n_collectives) / 1e3
    q: "queue.Queue" = queue.Queue(maxsize=1)

    def _worker():
        try:
            t0 = time.monotonic()
            val = jax.block_until_ready(fn())
            q.put((True, val, time.monotonic() - t0))
        except BaseException as e:  # noqa: BLE001 — relayed to caller
            q.put((False, e, 0.0))

    t = threading.Thread(target=_worker, daemon=True,
                         name=f"tl-comm-watchdog-{next(_watchdog_seq)}")
    t.start()
    try:
        ok, val, elapsed_s = q.get(timeout=budget_s)
    except queue.Empty:
        raise TLTimeoutError(
            f"{kernel}: mesh dispatch exceeded the collective watchdog "
            f"budget ({timeout_ms}ms x {max(1, n_collectives)} "
            f"collectives = {budget_s * 1e3:.0f}ms); worker {t.name} "
            f"abandoned", site="comm.watchdog") from None
    if not ok:
        raise val
    if elapsed_s > budget_s:
        raise TLTimeoutError(
            f"{kernel}: mesh dispatch completed but took "
            f"{elapsed_s * 1e3:.3f}ms, past the collective watchdog "
            f"budget ({timeout_ms}ms x {max(1, n_collectives)} "
            f"collectives = {budget_s * 1e3:.3f}ms)",
            site="comm.watchdog")
    return val
