"""Per-kernel dispatch plans: the host-side fast path.

The reference compiles a dedicated C host wrapper per kernel so a
steady-state dispatch costs one function call; our pre-plan
``JITKernel.__call__`` re-ran the whole marshalling gauntlet per
invocation — per-arg ``to_jax``, a Python shape/dtype loop with two
tuple constructions and a ``str(dtype)`` per param, two inline
``import jax`` statements, and several env reads. The AXI4MLIR line of
work (PAPERS.md) shows a specialized host driver is worth integer
factors on small kernels; this module is that driver for the XLA
runtime (ROADMAP item 5; docs/host_dispatch.md).

A :class:`DispatchPlan` is compiled ONCE per ``JITKernel._build`` and
holds everything a warm call needs precomputed:

- the single-tuple **shape/dtype fingerprint** — one tuple comparison
  replaces the per-param loop; a mismatch falls into the original
  ``_check_shapes`` so the error text is byte-identical;
- per-call **flag cache**: the raw values of the env vars that shape a
  dispatch (fast-path switch, donation, runtime metrics, sanitizer,
  fault spec) are snapshotted and the derived flags re-armed only when
  a raw value changes — a flipped ``TL_TPU_SANITIZE=1`` mid-process
  still takes effect on the next call, but a steady-state call pays
  tuple-of-getenv + one equality instead of N descriptor reads;
- the **monomorphic warm-path closure** state (``func``): the failover
  machinery (PR 6) swaps it atomically via :meth:`rearm`, so device
  loss recovery keeps working through the fast path;
- **buffer donation** (``TL_TPU_DONATE``, default on): warm calls whose
  ``inout`` inputs are all jax arrays dispatch through a lazily-built
  ``jax.jit(raw_call, donate_argnums=...)`` so XLA may alias the input
  buffer into the output. Callers passing numpy/torch need copy-back
  and never donate; ``TL_TPU_DONATE=0`` restores the exact pre-plan
  dispatch;
- host-overhead instrumentation: sampled calls (when
  ``TL_TPU_RUNTIME_METRICS=1``) record their Python marshalling time
  into the ``dispatch.overhead`` histogram (labelled by path), the
  split the ``dispatch_overhead_smoke`` bench and the perf gate read.

Legacy escape hatches: ``TL_TPU_FAST_DISPATCH=0`` and the
reference-style all-params calling convention route through
``JITKernel._legacy_call`` (the pre-plan body), which records into the
same histogram under ``path=legacy``.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Optional, Tuple

from ..observability import runtime as _runtime
from ..observability import sol as _sol
from ..utils.tensor import copy_back, to_jax
from ..verify import runtime as _verify_rt

__all__ = ["DispatchPlan", "ENV_KEYS"]

# the env vars whose RAW values the plan snapshots per call; order is
# load-bearing only for the snapshot tuple comparison
ENV_KEYS = ("TL_TPU_FAST_DISPATCH", "TL_TPU_DONATE",
            "TL_TPU_RUNTIME_METRICS", "TL_TPU_SANITIZE", "TL_TPU_FAULTS",
            "TL_TPU_SOL")

_TRUE = ("1", "true", "yes", "on")
_getenv = os.environ.get


def _flag(raw: Optional[str], default: bool) -> bool:
    if raw is None:
        return default
    return raw.lower() in _TRUE


def _sanitize_mode(raw: Optional[str]):
    """False / "on" / "auto" from the raw env-snapshot value — ONE
    grammar (verify.runtime.parse_sanitize_raw); a typo raises on the
    first call after the flip, never silently disables the guard."""
    mode = _verify_rt.parse_sanitize_raw(raw)
    return False if mode == "off" else mode


class DispatchPlan:
    """Precompiled per-kernel dispatch state; see the module docstring.
    Built by ``JITKernel._build`` after params are known, re-armed by
    the backend failover / degradation paths via :meth:`rearm`."""

    __slots__ = (
        "kernel", "name", "n_in", "n_all", "expected_fp", "inout_results",
        "donate_argnums", "out_names", "jax", "jax_array",
        "_env_snap", "fast_on", "donate_on", "metrics_on", "sanitize_on",
        "sol_on", "_donate_cache", "unproven_out", "proven_out_count",
    )

    def __init__(self, kernel):
        import jax
        import jax.numpy as jnp
        art = kernel.artifact
        self.kernel = kernel
        self.name = art.name
        self.n_in = len(kernel._in_params)
        self.n_all = len(art.params)
        # one tuple: ((shape, np.dtype), ...) per input param — jax
        # arrays expose .shape as a tuple and .dtype as np.dtype, so
        # the warm check is a single structural equality
        self.expected_fp = tuple(
            (tuple(int(s) for s in p.shape), jnp.dtype(p.dtype))
            for p in kernel._in_params)
        self.inout_results = tuple(kernel._inout_results)
        # positions (within the jax_ins tuple == in_params order) of
        # donation-eligible params: inout inputs aliasable into outputs
        self.donate_argnums = tuple(
            i for i, p in enumerate(kernel._in_params)
            if p.role == "inout")
        self.out_names = tuple(p.name for p in kernel._out_params)
        # tl-num finiteness proofs (attrs["numerics"], analysis/
        # numerics.py): under TL_TPU_SANITIZE=auto only the UNPROVEN
        # float outputs are checked at run time; a missing record (lint
        # off, pre-proof artifact) proves nothing and auto degrades to
        # checking every float output
        proofs = (art.attrs.get("numerics") or {}).get("outputs") or {}
        float_outs = [(i, p.name) for i, p in enumerate(kernel._out_params)
                      if _verify_rt.is_float_dtype(p.dtype)]
        self.unproven_out = tuple(
            (i, n) for i, n in float_outs if not proofs.get(n, False))
        self.proven_out_count = len(float_outs) - len(self.unproven_out)
        self.jax = jax
        self.jax_array = jax.Array
        self._donate_cache: Optional[Callable] = None
        self._env_snap: Tuple = ()
        self._refresh(tuple(map(_getenv, ENV_KEYS)))

    # -- flag cache ----------------------------------------------------
    def _refresh(self, snap: Tuple) -> None:
        """Re-derive the per-call flags from a fresh raw-env snapshot
        (runs only when a watched env var actually changed)."""
        self._env_snap = snap
        fast, donate, metrics, sanitize, _, sol = snap
        self.fast_on = _flag(fast, True)
        self.donate_on = _flag(donate, True) and bool(self.donate_argnums)
        self.sol_on = _flag(sol, False)
        # the SoL profiler rides the sampled timing path, so turning it
        # on alone turns sampling on (same cadence as the runtime ring)
        self.metrics_on = _flag(metrics, False) or self.sol_on
        self.sanitize_on = _sanitize_mode(sanitize)

    # -- failover / rebuild interplay ---------------------------------
    def rearm(self) -> None:
        """The kernel's dispatch callable changed (backend failover,
        interpreter degradation, terminal-tier rebuild): drop the
        donation variant so the next donated call re-jits against the
        NEW raw_call. The plain path needs nothing — the closure reads
        ``kernel.func`` through one attribute load, and that swap is a
        single atomic store."""
        self._donate_cache = None

    def donating(self) -> Callable:
        """The donation variant of the dispatch callable:
        ``jax.jit(raw_call, donate_argnums=...)`` (+ the same host pin
        the serving backend applied), built lazily on the first
        donation-eligible warm call and invalidated by :meth:`rearm`."""
        fn = self._donate_cache
        if fn is None:
            jax = self.jax
            jfn = jax.jit(self.kernel._raw_call,
                          donate_argnums=self.donate_argnums)
            if getattr(self.kernel, "_pin_host", False):
                try:
                    cpu0 = jax.devices("cpu")[0]
                except Exception:
                    cpu0 = None
                if cpu0 is not None:
                    inner = jfn

                    def jfn(*a, _inner=inner, _dev=cpu0, _jax=jax):
                        with _jax.default_device(_dev):
                            return _inner(*a)
            self._donate_cache = fn = jfn
        return fn

    def run_sanitizer(self, results, mode=None) -> None:
        """The mode-aware output NaN/Inf pass: ``on`` scans every float
        output; ``auto`` scans only the outputs the tl-num analysis
        could NOT prove finite and counts the skipped proven ones in
        the ``sanitize.elided`` counter. An unproven output is NEVER
        skipped."""
        if mode is None:
            mode = self.sanitize_on
        if mode == "auto":
            if self.unproven_out:
                _verify_rt.check_host_outputs(
                    [results[i] for i, _n in self.unproven_out],
                    [n for _i, n in self.unproven_out],
                    kernel=self.name)
            if self.proven_out_count:
                _verify_rt.note_elided(self.name, self.proven_out_count)
            return
        _verify_rt.check_host_outputs(results, self.out_names,
                                      kernel=self.name)

    # -- the call ------------------------------------------------------
    def execute(self, args: tuple):
        """One ``JITKernel.__call__``. The warm steady state runs:
        env-snapshot compare, single-tuple fingerprint check, optional
        fault hook, jitted dispatch, tuple-normalize, return — no
        imports, no per-param loop, no descriptor reads."""
        kernel = self.kernel
        snap = tuple(map(_getenv, ENV_KEYS))
        if snap != self._env_snap:
            self._refresh(snap)
        if not self.fast_on or len(args) != self.n_in:
            # legacy marshalling loop: TL_TPU_FAST_DISPATCH=0, the
            # reference-style all-params convention, and arity errors
            # (the legacy path raises the identical TypeError)
            return kernel._legacy_call(args)
        timed = self.metrics_on and kernel._warmed and \
            _runtime.should_sample(self.name)
        t0 = time.perf_counter() if timed else 0.0
        all_jax = True
        jax_ins = []
        for a in args:
            if isinstance(a, self.jax_array):
                jax_ins.append(a)
            else:
                all_jax = False
                jax_ins.append(to_jax(a))
        if tuple((a.shape, a.dtype) for a in jax_ins) != self.expected_fp:
            # raises the same per-param ValueError the slow path did; a
            # benign representation difference falls through and runs
            kernel._check_shapes(jax_ins)
        donate = self.donate_on and kernel._warmed and \
            (all_jax or all(isinstance(args[i], self.jax_array)
                            for i in self.donate_argnums))
        if timed:
            t1 = time.perf_counter()
            result = kernel._dispatch(jax_ins, donate=donate)
            t2 = time.perf_counter()
        else:
            result = kernel._dispatch(jax_ins, donate=donate)
        results = result if isinstance(result, tuple) else (result,)
        if self.sanitize_on:
            self.run_sanitizer(results)
        if timed:
            # host overhead = marshalling before + bookkeeping after
            # the jitted dispatch, recorded BEFORE the device sync so
            # it never includes device time — and BEFORE the copy-back
            # loop, mirroring the legacy recorder exactly so the
            # fast/legacy histogram rows measure the same window. The
            # e2e latency then blocks the full pytree and spans
            # dispatch-to-sync (t1 onward), the same window the pre-PR
            # recorder measured.
            t3 = time.perf_counter()
            host_s = (t1 - t0) + (t3 - t2)
            _runtime.record_overhead(self.name, host_s, path="fast")
            self.jax.block_until_ready(results)
            e2e_s = time.perf_counter() - t1
            _runtime.record(self.name, e2e_s)
            if self.sol_on:
                _sol.note_dispatch(kernel, e2e_s, host_s, name=self.name)
        delivered = 0
        if not all_jax and self.inout_results:
            for oi, ii in self.inout_results:
                a = args[ii]
                if not isinstance(a, self.jax_array):
                    copy_back(a, results[oi])
                    delivered += 1
        if delivered and delivered == len(results):
            return None
        return results[0] if len(results) == 1 else results
