"""JITKernel: the executable kernel object.

Reference: /root/reference/tilelang/jit/kernel.py (JITKernel:31). The
reference compiles CUDA source with nvcc and marshals torch tensors through
a generated C host wrapper; here the artifact is generated Pallas source,
executed via exec() and wrapped in jax.jit — XLA is the runtime. The adapter
role (ctypes/cython/nvrtc) collapses into arg marshalling (utils/tensor.py
to_jax) because jax.Array IS the device handle.
"""

from __future__ import annotations

import logging
import time
from typing import Optional, Sequence

from ..engine.param import CompiledArtifact
from ..env import env
from ..observability import runtime as _runtime
from ..observability import sol as _sol
from ..observability import tracer as _trace
from ..resilience import faults as _faults
from ..resilience.errors import TLError, classify
from ..verify import runtime as _verify_rt
from ..utils.target import target_is_interpret
from ..utils.tensor import TensorSupplyType, copy_back, to_jax

logger = logging.getLogger("tilelang_mesh_tpu.jit")


def _recoverable(exc: BaseException) -> bool:
    """Is this an error the fallback machinery (interpreter degrade or
    backend failover) can help with? Delegates to the taxonomy's
    ``classify()`` so device-loss and compile-failure recovery share
    one predicate: ``device_loss`` (a dispatch-time PJRT disconnect,
    "worker unreachable" — previously misread as deterministic and
    never recovered) is always recoverable by failover; beyond that,
    only compile-shaped failures — XLA/Mosaic compile errors
    (jax/jaxlib-raised), Mosaic unsupported ops (NotImplementedError),
    and taxonomy errors — can be fixed by the interpreter. Builtin
    Python errors from user code (a data-dependent ValueError, a bad
    operand TypeError) and transient I/O pressure are not: the former
    are user errors, the latter belong to the retry machinery, and
    degrading on either would silently pin good inputs to the slow
    interpreter forever."""
    if classify(exc) == "device_loss":
        return True
    if isinstance(exc, (TLError, NotImplementedError)):
        return True
    mod = type(exc).__module__ or ""
    return mod.startswith(("jax", "jaxlib"))


# back-compat spelling (pre-registry tests import this name)
_compile_shaped = _recoverable


class JITKernel:
    def __init__(self, artifact: CompiledArtifact,
                 out_idx: Optional[Sequence[int]] = None,
                 verbose: bool = False):
        self.artifact = artifact
        self.out_idx = out_idx
        self.verbose = verbose
        self._build()

    # ------------------------------------------------------------------
    def _build(self):
        art = self.artifact
        modname = f"<tl_tpu:{art.name}>"
        ns: dict = {}
        with _trace.span("jit.exec_source", "jit", kernel=art.name,
                         source_bytes=len(art.kernel_source)):
            code = compile(art.kernel_source, modname, "exec")
            exec(code, ns)
            self._ns = ns
            self._interpret = target_is_interpret(art.target)
            self._degraded = False
            self._warmed = False   # set after the first successful call
            from ..codegen import backends as _backends
            self._registry = _backends.registry()
            self._chain = self._registry.chain_for(art.target)
            self._backend = None
            try:
                _faults.maybe_fail("jit.compile", kernel=art.name)
                self._select_and_build()
            except Exception as e:  # noqa: BLE001 — degrade or re-raise
                self._degrade(e, during="build")
        self._in_params = art.in_params
        self._out_params = art.out_params
        self._in_positions = [i for i, p in enumerate(art.params)
                              if p.role in ("in", "inout")]
        self._out_positions = [i for i, p in enumerate(art.params)
                               if p.role == "out"]
        # result index -> input index, for in-place (inout) params: the
        # reference mutates these in place, so non-jax inputs get the
        # result copied back (kernel.py __call__).
        self._inout_results = [
            (oi, self._in_params.index(p))
            for oi, p in enumerate(self._out_params) if p.role == "inout"]
        # jax is already loaded (the exec'd kernel source imports it);
        # caching the module here keeps every per-call import out of the
        # dispatch path
        import jax
        self._jax = jax
        # the precompiled dispatch plan: fingerprint, flag cache,
        # donation variant, overhead instrumentation (jit/dispatch.py)
        from .dispatch import DispatchPlan
        self._plan = DispatchPlan(self)
        # tile-opt differential selfcheck (TL_TPU_SELFCHECK=1, verify/):
        # armed only for kernels the optimizer actually rewrote; the
        # first call also runs the TL_TPU_TILE_OPT=0 lowering and
        # compares outputs within dtype tolerance. One boolean on the
        # warm path once disarmed.
        self._selfcheck_done = not (
            env.TL_TPU_SELFCHECK and self.artifact.attrs.get("tile_opt"))

    def _select_and_build(self) -> None:
        """Build on the first capable+healthy entry of the backend chain
        (codegen/backends.py). A single-entry chain skips the health
        probe entirely — there is nothing to choose, and the happy path
        must not pay a device round-trip per cold build. A chain whose
        head probes unhealthy (dead TPU worker at BUILD time) fails
        over immediately with a ``backend.failover`` event instead of
        wedging on the first dispatch."""
        from ..resilience.errors import DeviceLossError
        chain = self._chain
        backend = chain[0]
        if len(chain) > 1 and not self._registry.is_available(backend.name):
            h = self._registry.health(backend.name)
            err = DeviceLossError(h.error or "backend unhealthy",
                                  site="device.probe", backend=backend.name)
            nxt = self._registry.next_healthy(chain, backend.name)
            if nxt is not None and env.TL_TPU_FALLBACK != "none":
                self._registry.note_failover(
                    frm=backend.name, to=nxt.name,
                    kernel=self.artifact.name, during="build", error=err)
                logger.warning(
                    "kernel %s: backend %s is unhealthy (%s); building on "
                    "%s instead", self.artifact.name, backend.name,
                    h.error, nxt.name)
                backend = nxt
        self._backend = backend
        pin = backend is not chain[0] and backend.is_host \
            and not chain[0].is_host
        _trace.inc("backend.build", backend=backend.name)
        self._pin_host = pin
        self._raw_call, self.func = backend.build_plain(self._ns,
                                                        pin_host=pin)
        plan = getattr(self, "_plan", None)
        if plan is not None:
            plan.rearm()

    def _degrade(self, exc: BaseException, during: str) -> None:
        """Graceful degradation (``TL_TPU_FALLBACK=interp``, default on):
        when building or first-compiling the Pallas kernel fails, fall
        back to the reference interpreter execution path with a
        once-per-kernel warning and a ``degraded`` trace event instead of
        raising. ``TL_TPU_FALLBACK=none`` restores fail-fast."""
        if env.TL_TPU_FALLBACK != "interp" or self._degraded:
            raise exc
        self._degraded = True
        _trace.inc("resilience.degraded")
        _trace.event("degraded", "resilience", kernel=self.artifact.name,
                     during=during, error=f"{type(exc).__name__}: {exc}")
        logger.warning(
            "kernel %s failed to %s (%s: %s); degrading to the reference "
            "interpreter (TL_TPU_FALLBACK=interp)", self.artifact.name,
            "build" if during == "build" else "compile", type(exc).__name__,
            exc)
        self._backend = self._registry.get("host-interpret")
        _trace.inc("backend.build", backend=self._backend.name)
        self._pin_host = False
        self._raw_call = self._ns["build"](interpret=True)
        import jax
        self.func = jax.jit(self._raw_call)
        plan = getattr(self, "_plan", None)
        if plan is not None:
            plan.rearm()

    # ------------------------------------------------------------------
    def __call__(self, *args, stream=None, **kwargs):
        # one attribute load + the plan's precompiled fast path
        # (jit/dispatch.py). TL_TPU_FAST_DISPATCH=0 and the
        # reference-style all-params convention route to _legacy_call.
        if not self._selfcheck_done:
            return self._selfcheck_first_call(args)
        return self._plan.execute(args)

    def _selfcheck_first_call(self, args):
        """Differential check of a tile-opt-rewritten kernel's first
        call (TL_TPU_SELFCHECK=1): the same prim_func is re-lowered
        with ``tl.tpu.tile_opt=0`` (a distinct cache entry — the pass
        set is part of the key), the REFERENCE runs first on copies of
        the inputs (donation/in-place semantics may consume the
        originals), and divergence beyond dtype tolerance raises
        :class:`~..verify.SelfCheckDivergence` naming the leaves. A
        kernel loaded from the disk cache has no traced prim_func to
        re-lower and records ``verify.selfcheck.skipped`` instead."""
        import numpy as np
        pf = getattr(self, "prim_func", None)
        if pf is None:
            self._selfcheck_done = True
            _trace.inc("verify.selfcheck.skipped")
            return self._plan.execute(args)
        from ..verify.runtime import SelfCheckDivergence, compare_outputs
        cfg = dict(getattr(self, "_lower_cfg", None) or {})
        cfg["tl.tpu.tile_opt"] = "0"
        from ..cache.kernel_cache import cached
        ref = cached(pf, target=self.artifact.target,
                     out_idx=self.out_idx, pass_configs=cfg)
        ref_args = []
        for a in args:
            try:
                ref_args.append(np.array(a))
            except Exception:   # noqa: BLE001 — e.g. bf16 torch
                # an uncopyable input must NOT be aliased into the
                # reference run (inout/donation semantics could consume
                # it before the optimized run sees it) — skip the check
                self._selfcheck_done = True
                _trace.inc("verify.selfcheck.skipped")
                return self._plan.execute(args)
        want = ref(*ref_args)
        got = self._plan.execute(args)
        # disarm only once the differential actually ran: an exception
        # above (transient ref-compile failure, I/O fault) propagates
        # with the check still ARMED, so the caller's retry is verified
        # instead of silently running the rewritten kernel unchecked
        self._selfcheck_done = True
        _trace.inc("verify.selfcheck.runs")
        if want is None or got is None:
            _trace.inc("verify.selfcheck.skipped")
            return got
        got_t = got if isinstance(got, tuple) else (got,)
        want_t = want if isinstance(want, tuple) else (want,)
        names = [p.name for p in self._out_params]
        # a dtype-narrowed kernel rounds through the narrower dtype
        # internally, so its f32 outputs legitimately differ from the
        # =0 reference by that dtype's tolerance — raise the float
        # comparison floor to the widest narrowing target's band.
        # Integer outputs stay exact (range proofs don't round).
        tol_floor = None
        from ..verify.runtime import tolerance_for
        rec0 = self.artifact.attrs.get("tile_opt") or {}
        for proof in (rec0.get("narrow") or {}).get("proofs") or []:
            t = tolerance_for(str(proof.get("to")))
            if t != (0.0, 0.0):
                tol_floor = (max(t[0], (tol_floor or (0, 0))[0]),
                             max(t[1], (tol_floor or (0, 0))[1]))
        divs = compare_outputs(got_t, want_t, names, tol_floor=tol_floor)
        if divs:
            _trace.inc("verify.selfcheck.divergence")
            rec = self.artifact.attrs.get("tile_opt") or {}
            from ..observability import flight as _flight
            _flight.dump("selfcheck_divergence",
                         kernel=self.artifact.name, divergence=list(divs))
            raise SelfCheckDivergence(
                f"{self.artifact.name}: tile-opt selfcheck divergence vs "
                f"the TL_TPU_TILE_OPT=0 lowering "
                f"(rewrites: {rec.get('rewrites')}):\n  - "
                + "\n  - ".join(divs))
        _trace.inc("verify.selfcheck.ok")
        return got

    def _legacy_call(self, args):
        """The pre-plan marshalling loop, byte-for-byte semantics: the
        ``TL_TPU_FAST_DISPATCH=0`` escape hatch and the reference-style
        ``kernel(a, b, c)`` all-params convention (caller-provided
        output buffers + copy-back) run here. Sampled calls record
        their host overhead under ``path=legacy`` so the
        dispatch_overhead_smoke bench can compare the two paths."""
        _jax = self._jax
        n_in, n_all = len(self._in_params), len(self.artifact.params)
        outs_provided = None
        if len(args) == n_in:
            ins = list(args)
        elif len(args) == n_all:
            ins = [args[i] for i in self._in_positions]
            outs_provided = [args[i] for i in self._out_positions]
        else:
            raise TypeError(
                f"{self.artifact.name}: expected {n_in} input tensors "
                f"(or all {n_all} params, reference-style), got {len(args)}")
        # opt-in runtime recording (TL_TPU_RUNTIME_METRICS=1): sampled
        # calls pay a device sync for an honest end-to-end latency and
        # land in the shared kernel.latency histogram + ring buffer.
        # Warm calls only — the first call's XLA/Mosaic compile time is
        # already tracked by the jit compile spans, and folding seconds
        # of compile into a ~ms dispatch digest would wreck p99/max.
        # Disabled (default): ONE cached env read, no allocation.
        _rt_t0 = 0.0
        if self._warmed and _runtime.runtime_enabled() and \
                _runtime.should_sample(self.artifact.name):
            _rt_t0 = time.perf_counter()
        jax_ins = [to_jax(a) for a in ins]
        self._check_shapes(jax_ins)
        # _rt_td marks the end of marshalling: the overhead window is
        # (_rt_t0.._rt_td) + the post-dispatch bookkeeping, and the e2e
        # latency spans _rt_td onward (dispatch-to-sync — the same
        # window the pre-PR recorder measured, so historical
        # kernel.latency digests stay comparable)
        _rt_td = time.perf_counter() if _rt_t0 else 0.0
        result = self._dispatch(jax_ins)
        _post_t0 = time.perf_counter() if _rt_t0 else 0.0
        results = result if isinstance(result, tuple) else (result,)
        # opt-in numeric sanitizer (TL_TPU_SANITIZE, verify/runtime.py):
        # NaN/Inf on any float output raises a deterministic
        # NumericError; =auto skips outputs the tl-num analysis proved
        # finite (the plan holds the precomputed unproven subset).
        # Disabled (default): one cached env read.
        if _verify_rt.sanitize_enabled():
            self._plan.run_sanitizer(results,
                                     mode=_verify_rt.sanitize_mode())
        if _rt_t0:
            _rt_host = (_rt_td - _rt_t0) + (time.perf_counter() - _post_t0)
            _runtime.record_overhead(self.artifact.name, _rt_host,
                                     path="legacy")
            # block on the FULL result pytree: a multi-output kernel's
            # latency must include every sibling, not just the first leaf
            _jax.block_until_ready(results)
            _rt_e2e = time.perf_counter() - _rt_td
            _runtime.record(self.artifact.name, _rt_e2e)
            _sol.note_dispatch(self, _rt_e2e, _rt_host,
                               name=self.artifact.name)
        delivered = set()
        for oi, ii in self._inout_results:
            if not isinstance(ins[ii], _jax.Array):
                copy_back(ins[ii], results[oi])
                delivered.add(oi)
        if outs_provided:
            out_indices = [oi for oi, p in enumerate(self._out_params)
                           if p.role == "out"]
            for oi, dst in zip(out_indices, outs_provided):
                if not isinstance(dst, _jax.Array):
                    copy_back(dst, results[oi])
                    delivered.add(oi)
        # reference-style in-place call: only when EVERY result reached
        # the caller through a copy-back may the return value be dropped
        if delivered and len(delivered) == len(results):
            return None
        return results[0] if len(results) == 1 else results

    def _dispatch(self, jax_ins, donate: bool = False):
        """One guarded dispatch. Warm calls catch device-loss errors
        (classify() == "device_loss": PJRT disconnects, DEADLINE_EXCEEDED,
        "unreachable" — or an injected ``device.dispatch`` fault), mark
        the backend unhealthy in the registry, and re-lower on the next
        entry of the failover chain; every other warm error is a runtime
        fault that must propagate. The first call is where XLA/Mosaic
        actually compiles, so it additionally keeps the compile-shaped
        interpreter degrade (``TL_TPU_FALLBACK=interp``). With
        ``donate`` (fast path, jax-array inout inputs, TL_TPU_DONATE
        on) the dispatch runs the plan's donating jit variant instead of
        ``self.func``; a donation-eligible call that loses its device
        still walks the failover chain, though the donated buffers may
        already be invalid — the retry then surfaces the honest
        RuntimeError instead of silently double-spending them."""
        fn = self._plan.donating() if donate else self.func
        if self._warmed:
            try:
                _faults.maybe_fail("device.dispatch",
                                   kernel=self.artifact.name)
                return fn(*jax_ins)
            except Exception as e:  # noqa: BLE001 — classified below
                if classify(e) != "device_loss":
                    raise
                return self._failover_dispatch(e, jax_ins,
                                               during="dispatch")
        try:
            _faults.maybe_fail("device.dispatch", kernel=self.artifact.name)
            result = fn(*jax_ins)
        except Exception as e:  # noqa: BLE001 — degrade or re-raise
            if classify(e) == "device_loss":
                result = self._failover_dispatch(e, jax_ins,
                                                 during="compile")
            elif self._degraded or self._interpret or not _recoverable(e):
                raise
            else:
                self._degrade(e, during="compile")
                result = self.func(*jax_ins)
        self._warmed = True
        return result

    def _failover_dispatch(self, exc: BaseException, jax_ins,
                           during: str):
        """The device under this kernel died mid-flight: mark the
        backend unhealthy (feeding the shared circuit breaker), walk
        down the ``TL_TPU_BACKENDS`` chain re-lowering on each healthy
        entry until one completes the dispatch, and emit a
        degraded-class ``backend.failover`` event per hop.
        ``TL_TPU_FALLBACK=none`` (or a spent/single-entry chain)
        re-raises — an operator who disabled fallback gets fail-fast."""
        reg = self._registry
        while True:
            cur = self._backend.name if self._backend is not None \
                else self._chain[0].name
            nxt = reg.next_healthy(self._chain, cur)
            if nxt is None or env.TL_TPU_FALLBACK == "none":
                # spent chain (or fallback disabled): re-raise WITHOUT
                # poisoning the tier in the shared registry — a terminal
                # host tier cannot really be dead, and caching it
                # unhealthy would block sibling kernels' legitimate
                # failovers for the probe TTL
                raise exc
            reg.mark_unhealthy(cur, exc)
            reg.note_failover(frm=cur, to=nxt.name,
                              kernel=self.artifact.name, during=during,
                              error=exc)
            logger.warning(
                "kernel %s lost backend %s during %s (%s: %s); "
                "re-lowering on %s", self.artifact.name, cur, during,
                type(exc).__name__, exc, nxt.name)
            pin = nxt.is_host and not self._chain[0].is_host
            self._backend = nxt
            _trace.inc("backend.build", backend=nxt.name)
            self._pin_host = pin
            self._raw_call, self.func = nxt.build_plain(self._ns,
                                                        pin_host=pin)
            # the dispatch plan's monomorphic closure reads self.func;
            # drop its donation variant so the next donated call re-jits
            # against the NEW backend's raw_call (atomic swap: one store)
            self._plan.rearm()
            try:
                _faults.maybe_fail("device.dispatch",
                                   kernel=self.artifact.name)
                result = self.func(*jax_ins)
                self._warmed = True
                return result
            except Exception as e:  # noqa: BLE001 — classified below
                if classify(e) != "device_loss":
                    raise
                exc = e

    @property
    def backend(self) -> Optional[str]:
        """The name of the registry backend currently serving dispatches
        (None only if the build itself failed before selection)."""
        return self._backend.name if self._backend is not None else None

    def _check_shapes(self, jax_ins):
        for a, p in zip(jax_ins, self._in_params):
            if tuple(a.shape) != tuple(p.shape):
                raise ValueError(
                    f"{self.artifact.name}: param {p.name} expects shape "
                    f"{tuple(p.shape)}, got {tuple(a.shape)}")
            if str(a.dtype) != p.dtype:
                raise ValueError(
                    f"{self.artifact.name}: param {p.name} expects dtype "
                    f"{p.dtype}, got {a.dtype}")

    # -- introspection (reference kernel.py:423-734) -------------------------
    def get_kernel_source(self) -> str:
        """The generated Pallas/Python source (the 'CUDA source' analog)."""
        return self.artifact.kernel_source

    def get_ir_script(self) -> str:
        return self.artifact.ir_script

    def get_plan(self) -> str:
        return self.artifact.plan_desc

    def get_jaxpr(self) -> str:
        """The traced jaxpr — the closest analog of show_ptx."""
        import jax
        ins = self._example_inputs()
        return str(jax.make_jaxpr(self._raw_call)(*ins))

    def get_lowered_hlo(self) -> str:
        """Pre-optimization StableHLO text of the jitted wrapper."""
        return self._lowered().as_text()

    def _lowered(self):
        if getattr(self, "_lowered_cache", None) is None:
            self._lowered_cache = self.func.lower(*self._example_inputs())
        return self._lowered_cache

    def _compiled(self):
        if getattr(self, "_compiled_cache", None) is None:
            self._compiled_cache = self._lowered().compile()
        return self._compiled_cache

    # -- Mosaic/TPU-level artifacts (reference show_ptx/show_sass,
    #    kernel.py:657-734) --------------------------------------------------
    def get_mosaic(self) -> str:
        """The Mosaic MLIR module(s) the kernel actually runs on the TPU —
        the artifact-level analog of the reference's show_ptx. Extracted
        from the tpu_custom_call payload (base64 MLIR bytecode) of the
        lowered module; pre-Mosaic HLO (get_lowered_hlo) stops above this
        level and is useless for perf debugging the kernel body."""
        mods = self._mosaic_modules()
        if not mods:
            raise NotImplementedError(
                "no Mosaic module in the lowered program: the kernel is "
                "running in interpret mode (CPU) or contains no "
                "pallas_call; compile for a real TPU target to inspect "
                "Mosaic IR")
        return "\n".join(f"// ==== mosaic module {i}: @{name} ====\n{text}"
                         for i, (name, text) in enumerate(mods))

    def _mosaic_modules(self):
        import base64
        import json
        from jax._src.lib.mlir import ir
        mod = self._lowered().compiler_ir()
        calls = []

        def walk(op):
            for r in op.regions:
                for b in r.blocks:
                    for o in b.operations:
                        if "custom_call" in o.operation.name:
                            calls.append(o)
                        walk(o.operation)
        walk(mod.operation)
        out = []
        for o in calls:
            attrs = o.attributes
            cfg = None
            for key in ("mhlo.backend_config", "backend_config"):
                if key in attrs:
                    cfg = ir.StringAttr(attrs[key]).value
                    break
            if not cfg:
                continue
            try:
                body = json.loads(cfg)["custom_call_config"]["body"]
            except (ValueError, KeyError, TypeError):
                continue
            ctx = ir.Context()
            ctx.allow_unregistered_dialects = True
            m = ir.Module.parse(base64.b64decode(body), ctx)
            name = "kernel"
            try:
                name = ir.StringAttr(
                    m.operation.attributes["sym_name"]).value
            except (KeyError, ValueError):
                pass
            out.append((name, str(m)))
        return out

    def get_lowered(self, level: str = "mosaic") -> str:
        """The lowered artifact at the requested level — the accessor the
        reference exposes as show_ptx/show_sass:
        'mosaic' (device kernel MLIR), 'optimized_hlo' (post-optimization
        scheduled HLO; compiles), or 'stablehlo' (pre-optimization — the
        same artifact as get_lowered_hlo())."""
        if level == "mosaic":
            return self.get_mosaic()
        if level == "optimized_hlo":
            return self.get_compiled_hlo()
        if level == "stablehlo":
            return self.get_lowered_hlo()
        raise ValueError(f"unknown level {level!r} "
                         "(mosaic | optimized_hlo | stablehlo)")

    def show_mosaic(self) -> None:
        print(self.get_mosaic())  # noqa: T201 — reference show_ptx parity

    def show_hlo(self) -> None:
        print(self.get_compiled_hlo())  # noqa: T201

    def get_compiled_hlo(self) -> str:
        """Post-optimization, scheduled HLO with chosen layouts (e.g.
        f32[8,128]{1,0:T(8,128)}) — what XLA actually executes around the
        Mosaic kernel. Requires a real backend (compiles the kernel)."""
        return self._compiled().as_text()

    def get_memory_analysis(self):
        """XLA's CompiledMemoryStats for the compiled kernel (generated
        code size, argument/output/temp bytes)."""
        return self._compiled().memory_analysis()

    def get_cost_analysis(self) -> dict:
        """XLA's cost analysis (FLOPs, bytes accessed) for the compiled
        kernel."""
        return dict(self._compiled().cost_analysis() or {})

    def _example_inputs(self):
        import jax
        import jax.numpy as jnp
        return [jax.ShapeDtypeStruct(tuple(p.shape), jnp.dtype(p.dtype))
                for p in self._in_params]

    # -- profiler ------------------------------------------------------------
    def get_profiler(self,
                     tensor_supply_type: TensorSupplyType =
                     TensorSupplyType.Auto):
        from ..profiler import Profiler
        return Profiler(self, tensor_supply_type)

    @property
    def params(self):
        return self.artifact.params

    @property
    def out_params(self):
        return self._out_params

    def __repr__(self):
        return (f"JITKernel({self.artifact.name}, target="
                f"{self.artifact.target}, grid={self.artifact.grid})")
