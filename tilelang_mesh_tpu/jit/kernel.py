"""JITKernel: the executable kernel object.

Reference: /root/reference/tilelang/jit/kernel.py (JITKernel:31). The
reference compiles CUDA source with nvcc and marshals torch tensors through
a generated C host wrapper; here the artifact is generated Pallas source,
executed via exec() and wrapped in jax.jit — XLA is the runtime. The adapter
role (ctypes/cython/nvrtc) collapses into arg marshalling (utils/tensor.py
to_jax) because jax.Array IS the device handle.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional, Sequence

from ..engine.param import CompiledArtifact
from ..utils.target import target_is_interpret, target_is_mesh
from ..utils.tensor import TensorSupplyType, copy_back, to_jax


class JITKernel:
    def __init__(self, artifact: CompiledArtifact,
                 out_idx: Optional[Sequence[int]] = None,
                 verbose: bool = False):
        self.artifact = artifact
        self.out_idx = out_idx
        self.verbose = verbose
        self._build()

    # ------------------------------------------------------------------
    def _build(self):
        art = self.artifact
        modname = f"<tl_tpu:{art.name}>"
        ns: dict = {}
        code = compile(art.kernel_source, modname, "exec")
        exec(code, ns)
        interpret = target_is_interpret(art.target)
        self._raw_call: Callable = ns["build"](interpret=interpret)
        import jax
        self.func = jax.jit(self._raw_call)
        self._in_params = art.in_params
        self._out_params = art.out_params
        self._in_positions = [i for i, p in enumerate(art.params)
                              if p.role in ("in", "inout")]
        self._out_positions = [i for i, p in enumerate(art.params)
                               if p.role == "out"]
        # result index -> input index, for in-place (inout) params: the
        # reference mutates these in place, so non-jax inputs get the
        # result copied back (kernel.py __call__).
        self._inout_results = [
            (oi, self._in_params.index(p))
            for oi, p in enumerate(self._out_params) if p.role == "inout"]

    # ------------------------------------------------------------------
    def __call__(self, *args, stream=None, **kwargs):
        n_in, n_all = len(self._in_params), len(self.artifact.params)
        outs_provided = None
        if len(args) == n_in:
            ins = list(args)
        elif len(args) == n_all:
            ins = [args[i] for i in self._in_positions]
            outs_provided = [args[i] for i in self._out_positions]
        else:
            raise TypeError(
                f"{self.artifact.name}: expected {n_in} input tensors "
                f"(or all {n_all} params, reference-style), got {len(args)}")
        jax_ins = [to_jax(a) for a in ins]
        self._check_shapes(jax_ins)
        result = self.func(*jax_ins)
        results = result if isinstance(result, tuple) else (result,)
        import jax as _jax
        delivered = set()
        for oi, ii in self._inout_results:
            if not isinstance(ins[ii], _jax.Array):
                copy_back(ins[ii], results[oi])
                delivered.add(oi)
        if outs_provided:
            out_indices = [oi for oi, p in enumerate(self._out_params)
                           if p.role == "out"]
            for oi, dst in zip(out_indices, outs_provided):
                if not isinstance(dst, _jax.Array):
                    copy_back(dst, results[oi])
                    delivered.add(oi)
        # reference-style in-place call: only when EVERY result reached
        # the caller through a copy-back may the return value be dropped
        if delivered and len(delivered) == len(results):
            return None
        return results[0] if len(results) == 1 else results

    def _check_shapes(self, jax_ins):
        for a, p in zip(jax_ins, self._in_params):
            if tuple(a.shape) != tuple(p.shape):
                raise ValueError(
                    f"{self.artifact.name}: param {p.name} expects shape "
                    f"{tuple(p.shape)}, got {tuple(a.shape)}")
            if str(a.dtype) != p.dtype:
                raise ValueError(
                    f"{self.artifact.name}: param {p.name} expects dtype "
                    f"{p.dtype}, got {a.dtype}")

    # -- introspection (reference kernel.py:423-734) -------------------------
    def get_kernel_source(self) -> str:
        """The generated Pallas/Python source (the 'CUDA source' analog)."""
        return self.artifact.kernel_source

    def get_ir_script(self) -> str:
        return self.artifact.ir_script

    def get_plan(self) -> str:
        return self.artifact.plan_desc

    def get_jaxpr(self) -> str:
        """The traced jaxpr — the closest analog of show_ptx."""
        import jax
        ins = self._example_inputs()
        return str(jax.make_jaxpr(self._raw_call)(*ins))

    def get_lowered_hlo(self) -> str:
        """StableHLO text of the whole kernel (the SASS analog)."""
        ins = self._example_inputs()
        return self.func.lower(*ins).as_text()

    def _example_inputs(self):
        import jax
        import jax.numpy as jnp
        return [jax.ShapeDtypeStruct(tuple(p.shape), jnp.dtype(p.dtype))
                for p in self._in_params]

    # -- profiler ------------------------------------------------------------
    def get_profiler(self,
                     tensor_supply_type: TensorSupplyType =
                     TensorSupplyType.Auto):
        from ..profiler import Profiler
        return Profiler(self, tensor_supply_type)

    @property
    def params(self):
        return self.artifact.params

    @property
    def out_params(self):
        return self._out_params

    def __repr__(self):
        return (f"JITKernel({self.artifact.name}, target="
                f"{self.artifact.target}, grid={self.artifact.grid})")
