"""@tilelang.jit / compile / par_compile / lazy_jit.

Reference: /root/reference/tilelang/jit/__init__.py (compile:48,
par_compile:122, JITImpl:190, jit:456, lazy_jit:547). Same call-site shapes:

    @tilelang.jit                      # decorate a kernel *factory*
    def matmul(M, N, K, bm, bn, bk):
        @T.prim_func
        def kernel(...): ...
        return kernel
    k = matmul(1024, 1024, 1024, 128, 128, 32)   # -> JITKernel

    @tilelang.lazy_jit                 # shapes inferred per call site
    def kern(A: T.Tensor((M, K), "bfloat16"), ...): ...   # M, K = T.dynamic
"""

from __future__ import annotations

import functools
import inspect
import threading
import weakref
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, List, Optional, Sequence

from ..cache.kernel_cache import cached
from ..env import env
from ..language.builder import PrimFuncObj, trace_prim_func
from ..observability import tracer as _trace
from .kernel import JITKernel


def compile(func, out_idx: Optional[Sequence[int]] = None,  # noqa: A001
            execution_backend: str = "auto", target: str = "auto",
            verbose: bool = False, pass_configs: Optional[dict] = None,
            compile_flags=None) -> JITKernel:
    """Compile a traced prim_func into an executable kernel.

    `execution_backend` / `compile_flags` are accepted for reference parity;
    XLA is the only execution backend on TPU.
    """
    if not isinstance(func, PrimFuncObj):
        raise TypeError("tilelang.compile expects a @T.prim_func")
    with _trace.span("jit.compile", "jit",
                     kernel=getattr(func, "name", "?"), target=target):
        k = cached(func, target=target, out_idx=out_idx,
                   pass_configs=pass_configs, verbose=verbose)
    # keep the traced IR reachable from the kernel: the carver's
    # IR-derived autotuning (carver/node.py) re-analyzes it
    k.prim_func = func
    return k


def par_compile(funcs: Sequence[PrimFuncObj], num_workers: Optional[int] = None,
                ignore_error: bool = False, **kwargs) -> List[Any]:
    """Compile a batch of kernels on a thread pool (reference par_compile:122;
    used by the autotuner to overlap trace/plan/codegen work)."""
    num_workers = num_workers or env.TL_TPU_NUM_COMPILE_THREADS

    def one(f):
        try:
            return compile(f, **kwargs)
        except Exception:
            if ignore_error:
                return None
            raise

    with ThreadPoolExecutor(max_workers=num_workers) as pool:
        return list(pool.map(one, funcs))


# every live factory cache, so device-loss recovery (bench failover,
# codegen/backends.py) can force kernels to re-select a backend: a
# cached JITKernel pins the jitted callable of the backend it was built
# on, and clearing the kernel cache alone cannot reach it
_FACTORY_IMPLS: "weakref.WeakSet" = weakref.WeakSet()


def clear_factory_caches() -> int:
    """Empty every ``@tilelang.jit`` / ``@tilelang.lazy_jit`` callsite
    cache (returns how many cached kernels were dropped), plus every
    ``functools.lru_cache`` on package modules — the ops-level kernel
    factories (``ops/gemm.matmul_kernel`` etc.) and device-sniffing
    caches (``utils.target.tpu_available``) memoize kernels/verdicts
    that pin a possibly-dead backend. Combined with ``clear_cache()``
    this forces the next factory call to rebuild its kernel through the
    backend registry's chain walk — the recovery step after a backend
    was marked unhealthy."""
    import sys
    n = 0
    for impl in list(_FACTORY_IMPLS):
        n += len(impl._kernels)
        impl._kernels.clear()
    for modname, mod in list(sys.modules.items()):
        if not modname.startswith("tilelang_mesh_tpu") or mod is None:
            continue
        for attr in list(vars(mod).values()):
            if callable(attr) and hasattr(attr, "cache_clear") \
                    and hasattr(attr, "cache_info"):
                n += attr.cache_info().currsize
                attr.cache_clear()
    return n


class JITImpl:
    """Per-callsite kernel factory cache (reference JITImpl:190)."""

    def __init__(self, fn: Callable, out_idx=None, target: str = "auto",
                 verbose: bool = False, pass_configs: Optional[dict] = None,
                 **_ignored):
        functools.update_wrapper(self, fn)
        self.fn = fn
        self.out_idx = out_idx
        self.target = target
        self.verbose = verbose
        self.pass_configs = pass_configs
        self._kernels = {}
        _FACTORY_IMPLS.add(self)

    def _key(self, args, kwargs):
        return (tuple(args), tuple(sorted(kwargs.items())))

    def __call__(self, *args, **kwargs):
        key = self._key(args, kwargs)
        k = self._kernels.get(key)
        if k is None:
            # hit AND miss gated together on tracing: counting misses
            # alone would read as a 0% hit rate in untraced runs, and
            # the hit side is the per-dispatch hot path that must not
            # touch the tracer's lock when tracing is off
            if _trace.trace_enabled():
                _trace.inc("jit.callsite.miss")
            with _trace.span("jit.callsite_compile", "jit",
                             factory=getattr(self.fn, "__name__", "?")):
                pf = self.fn(*args, **kwargs)
                if isinstance(pf, JITKernel):
                    k = pf
                elif isinstance(pf, PrimFuncObj):
                    k = compile(pf, out_idx=self.out_idx, target=self.target,
                                verbose=self.verbose,
                                pass_configs=self.pass_configs)
                else:
                    raise TypeError(
                        f"@tilelang.jit factory must return a @T.prim_func, "
                        f"got {type(pf)}")
            self._kernels[key] = k
        elif _trace.trace_enabled():
            _trace.inc("jit.callsite.hit")
        return k


def jit(fn: Optional[Callable] = None, *, out_idx=None, target: str = "auto",
        execution_backend: str = "auto", verbose: bool = False,
        pass_configs: Optional[dict] = None, debug_root_path: Optional[str] = None,
        compile_flags=None):
    """Decorator over a kernel factory (reference jit:456)."""

    def wrap(f):
        if isinstance(f, PrimFuncObj):
            return compile(f, out_idx=out_idx, target=target,
                           verbose=verbose, pass_configs=pass_configs)
        return JITImpl(f, out_idx=out_idx, target=target, verbose=verbose,
                       pass_configs=pass_configs)

    if fn is not None:
        return wrap(fn)
    return wrap


# ---------------------------------------------------------------------------
# lazy_jit: per-shape specialization (reference lazy_jit:547)
# ---------------------------------------------------------------------------


def _solve_dims(annot_shape, actual_shape, binding: dict, pname: str):
    from ..ir import Var, as_int
    if len(annot_shape) != len(actual_shape):
        raise ValueError(
            f"lazy_jit: param {pname} rank mismatch: annotation rank "
            f"{len(annot_shape)} vs tensor rank {len(actual_shape)}")
    for dim, actual in zip(annot_shape, actual_shape):
        if isinstance(dim, Var):
            prev = binding.get(id(dim))
            if prev is None:
                binding[id(dim)] = (dim, int(actual))
            elif prev[1] != actual:
                raise ValueError(
                    f"lazy_jit: dim {dim.name} bound to both {prev[1]} and "
                    f"{actual}")
        else:
            c = as_int(dim)
            if c is not None and c != actual:
                raise ValueError(
                    f"lazy_jit: param {pname} expects dim {c}, got {actual}")


def _subst_shape(shape, env_map):
    from ..ir import Var, as_int, convert
    out = []
    for dim in shape:
        if isinstance(dim, Var):
            if id(dim) not in env_map:
                raise ValueError(f"lazy_jit: unbound symbolic dim {dim.name}")
            out.append(env_map[id(dim)])
        else:
            v = as_int(dim)
            if v is None:
                raise ValueError("lazy_jit: arithmetic symbolic dims are not "
                                 "supported yet; use bare T.dynamic dims")
            out.append(v)
    return tuple(out)


_LAZY_BIND_LOCK = threading.Lock()


class LazyJITImpl:
    def __init__(self, fn: Callable, dynamic_bucket: Optional[int] = None,
                 **jit_kwargs):
        functools.update_wrapper(self, fn)
        self.fn = fn
        self.jit_kwargs = jit_kwargs
        # Bucketed symbolic dims (the TPU answer to the reference's
        # T.dynamic compile-once kernels, tilelang/language/symbolics.py):
        # XLA requires static shapes, so a dyn dim is rounded UP to the
        # next multiple of `dynamic_bucket`, inputs are zero-padded and
        # dyn output dims sliced back — ONE compiled kernel then serves
        # every length in the bucket instead of one kernel per length.
        # Zero padding is an identity for GEMM/elementwise/reduce-sum
        # kernels; kernels with normalizing semantics (softmax, mean)
        # must take the true length as an explicit scalar operand and
        # mask, like the varlen/blocksparse kernels do.
        if dynamic_bucket is not None:
            if not isinstance(dynamic_bucket, int) or dynamic_bucket <= 0:
                raise ValueError(
                    f"lazy_jit: dynamic_bucket must be a positive int, "
                    f"got {dynamic_bucket!r}")
            if jit_kwargs.get("out_idx") is None:
                raise ValueError(
                    "lazy_jit(dynamic_bucket=...) requires out_idx: the "
                    "wrapper must own the output buffers to slice their "
                    "padded dyn dims back")
        self.dynamic_bucket = dynamic_bucket
        self._kernels = {}
        _FACTORY_IMPLS.add(self)

    def __call__(self, *tensors):
        from ..language.annot import TensorAnnot
        sig = inspect.signature(self.fn)
        names = list(sig.parameters)
        annots = [sig.parameters[n].annotation for n in names]
        out_idx = self.jit_kwargs.get("out_idx")
        if out_idx is not None:
            # outputs are allocated by the kernel: the caller passes inputs
            # only, and dims are solved from them (reference lazy_jit
            # shape-from-tensor path, tilelang/jit/__init__.py:547)
            idxs = [out_idx] if isinstance(out_idx, int) else list(out_idx)
            for i in idxs:
                if not -len(names) <= i < len(names):
                    raise IndexError(
                        f"out_idx {i} out of range for {len(names)} kernel "
                        f"params")
            outs = {i % len(names) for i in idxs}
            in_pos = [i for i in range(len(names)) if i not in outs]
        else:
            in_pos = list(range(len(names)))
        if len(tensors) != len(in_pos):
            raise TypeError(f"lazy_jit kernel takes {len(in_pos)} input "
                            f"tensors, got {len(tensors)}")
        binding: dict = {}
        for i, t in zip(in_pos, tensors):
            if isinstance(annots[i], TensorAnnot):
                _solve_dims(annots[i].shape, t.shape, binding, names[i])
        true_vals = {k: v for k, (_, v) in binding.items()}
        if self.dynamic_bucket:
            b = self.dynamic_bucket
            binding = {k: (var, -(-val // b) * b)
                       for k, (var, val) in binding.items()}
            if _trace.trace_enabled():   # dispatch hot path: build the
                # dims payload only when it will be recorded. A list
                # keyed by (name, uid), not a name-keyed dict: two dyn
                # Vars sharing a name must not collapse to one entry
                # (the same collision shape_key below avoids via uid)
                _trace.event(
                    "jit.lazy_bucket", "jit", bucket=b,
                    dims=[{"dim": var.name, "uid": var.uid,
                           "true": true_vals[k], "padded": val}
                          for k, (var, val) in binding.items()])
        env_map = {k: v for k, (_, v) in binding.items()}
        # Key by the Var's unique uid, not its name: two distinct dyn vars
        # sharing a name would otherwise collide after sorting and silently
        # return the wrong cached specialization (round-1 advisor finding).
        shape_key = tuple(sorted((v.uid, val)
                                 for v, val in binding.values()))
        kernel = self._kernels.get(shape_key)
        if _trace.trace_enabled():
            # hit/miss gated TOGETHER (a miss-only count reads as a 0%
            # hit rate untraced), and the hit side is the dispatch hot
            # path that must not take the tracer lock when tracing is off
            _trace.inc("jit.lazy.hit" if kernel is not None else
                       "jit.lazy.miss")
        if kernel is None:
            # re-trace with concrete shapes substituted into annotations
            concrete = []
            for pname, annot in zip(names, annots):
                if isinstance(annot, TensorAnnot):
                    concrete.append(TensorAnnot(
                        _subst_shape(annot.shape, env_map), annot.dtype))
                else:
                    concrete.append(annot)
            fn = self.fn
            # Var._bound is process-global mutable state: serialize all
            # lazy_jit specializations so a concurrent trace (par_compile
            # runs a ThreadPoolExecutor in this module) can never fold
            # against another call-site's shape
            with _trace.span("jit.lazy_specialize", "jit",
                             factory=getattr(fn, "__name__", "?"),
                             shapes={v.name: val
                                     for v, val in binding.values()}), \
                    _LAZY_BIND_LOCK:
                orig = dict(fn.__annotations__)
                try:
                    for n, a in zip(names, concrete):
                        fn.__annotations__[n] = a
                    # bind dyn Vars so body uses (grid extents, bounds
                    # checks) fold to this call-site's concrete shape;
                    # compile must run inside the binding scope too —
                    # exprs traced un-foldable (e.g. tail guards `i < M`)
                    # still hold the Var and only resolve while its
                    # binding is live
                    for var, val in binding.values():
                        var._bound = val
                    pf = trace_prim_func(fn)
                    kernel = compile(pf, **self.jit_kwargs)
                finally:
                    fn.__annotations__.update(orig)
                    for var, _ in binding.values():
                        var._bound = None
            self._kernels[shape_key] = kernel
        if not self.dynamic_bucket:
            return kernel(*tensors)
        return self._call_padded(kernel, tensors, in_pos, names, annots,
                                 binding, true_vals)

    def _call_padded(self, kernel, tensors, in_pos, names, annots,
                     binding, true_vals):
        """Bucketed call: zero-pad every input's dyn dims to the bucketed
        capacity, run the (bucket-shaped) kernel, slice dyn output dims
        back to their true extents."""
        import jax.numpy as jnp

        from ..ir import Var
        from ..language.annot import TensorAnnot

        padded = []
        for i, t in zip(in_pos, tensors):
            annot = annots[i]
            if isinstance(annot, TensorAnnot):
                t = jnp.asarray(t)
                pads = []
                needs = False
                for dim, actual in zip(annot.shape, t.shape):
                    if isinstance(dim, Var) and id(dim) in binding:
                        cap = binding[id(dim)][1]
                        pads.append((0, cap - int(actual)))
                        needs = needs or cap != int(actual)
                    else:
                        pads.append((0, 0))
                if needs:
                    t = jnp.pad(t, pads)
            padded.append(t)
        out_params = kernel.out_params
        if any(p.role == "inout" for p in out_params):
            bad = [p.name for p in out_params if p.role == "inout"]
            raise NotImplementedError(
                f"lazy_jit(dynamic_bucket=...) does not support in-place "
                f"(inout) params ({', '.join(bad)}): the padded-shape "
                f"result cannot be copied back into the caller's unpadded "
                f"buffer; write to a separate output tensor instead")
        result = kernel(*padded)
        results = result if isinstance(result, tuple) else (result,)
        # results follow the kernel's out_params order; map each back to
        # its signature annotation by name to find its dyn dims
        pos_of = {n: i for i, n in enumerate(names)}
        sliced = []
        for r, p in zip(results, out_params):
            annot = annots[pos_of[p.name]]
            if isinstance(annot, TensorAnnot):
                idx = []
                for dim, actual in zip(annot.shape, r.shape):
                    if isinstance(dim, Var) and id(dim) in true_vals:
                        idx.append(slice(0, true_vals[id(dim)]))
                    else:
                        idx.append(slice(None))
                r = r[tuple(idx)]
            sliced.append(r)
        return sliced[0] if len(sliced) == 1 else tuple(sliced)


def lazy_jit(fn: Optional[Callable] = None, *, out_idx=None,
             target: str = "auto", verbose: bool = False,
             pass_configs: Optional[dict] = None,
             dynamic_bucket: Optional[int] = None, **_ignored):
    def wrap(f):
        return LazyJITImpl(f, dynamic_bucket=dynamic_bucket,
                           out_idx=out_idx, target=target,
                           verbose=verbose, pass_configs=pass_configs)
    if fn is not None:
        return wrap(fn)
    return wrap
