"""@tilelang.jit / compile / par_compile / lazy_jit.

Reference: /root/reference/tilelang/jit/__init__.py (compile:48,
par_compile:122, JITImpl:190, jit:456, lazy_jit:547). Same call-site shapes:

    @tilelang.jit                      # decorate a kernel *factory*
    def matmul(M, N, K, bm, bn, bk):
        @T.prim_func
        def kernel(...): ...
        return kernel
    k = matmul(1024, 1024, 1024, 128, 128, 32)   # -> JITKernel

    @tilelang.lazy_jit                 # shapes inferred per call site
    def kern(A: T.Tensor((M, K), "bfloat16"), ...): ...   # M, K = T.dynamic
"""

from __future__ import annotations

import functools
import inspect
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, List, Optional, Sequence

from ..cache.kernel_cache import cached
from ..env import env
from ..language.builder import PrimFuncObj, trace_prim_func
from .kernel import JITKernel


def compile(func, out_idx: Optional[Sequence[int]] = None,  # noqa: A001
            execution_backend: str = "auto", target: str = "auto",
            verbose: bool = False, pass_configs: Optional[dict] = None,
            compile_flags=None) -> JITKernel:
    """Compile a traced prim_func into an executable kernel.

    `execution_backend` / `compile_flags` are accepted for reference parity;
    XLA is the only execution backend on TPU.
    """
    if not isinstance(func, PrimFuncObj):
        raise TypeError("tilelang.compile expects a @T.prim_func")
    return cached(func, target=target, out_idx=out_idx,
                  pass_configs=pass_configs, verbose=verbose)


def par_compile(funcs: Sequence[PrimFuncObj], num_workers: Optional[int] = None,
                ignore_error: bool = False, **kwargs) -> List[Any]:
    """Compile a batch of kernels on a thread pool (reference par_compile:122;
    used by the autotuner to overlap trace/plan/codegen work)."""
    num_workers = num_workers or env.TL_TPU_NUM_COMPILE_THREADS

    def one(f):
        try:
            return compile(f, **kwargs)
        except Exception:
            if ignore_error:
                return None
            raise

    with ThreadPoolExecutor(max_workers=num_workers) as pool:
        return list(pool.map(one, funcs))


class JITImpl:
    """Per-callsite kernel factory cache (reference JITImpl:190)."""

    def __init__(self, fn: Callable, out_idx=None, target: str = "auto",
                 verbose: bool = False, pass_configs: Optional[dict] = None,
                 **_ignored):
        functools.update_wrapper(self, fn)
        self.fn = fn
        self.out_idx = out_idx
        self.target = target
        self.verbose = verbose
        self.pass_configs = pass_configs
        self._kernels = {}

    def _key(self, args, kwargs):
        return (tuple(args), tuple(sorted(kwargs.items())))

    def __call__(self, *args, **kwargs):
        key = self._key(args, kwargs)
        k = self._kernels.get(key)
        if k is None:
            pf = self.fn(*args, **kwargs)
            if isinstance(pf, JITKernel):
                k = pf
            elif isinstance(pf, PrimFuncObj):
                k = compile(pf, out_idx=self.out_idx, target=self.target,
                            verbose=self.verbose,
                            pass_configs=self.pass_configs)
            else:
                raise TypeError(
                    f"@tilelang.jit factory must return a @T.prim_func, got "
                    f"{type(pf)}")
            self._kernels[key] = k
        return k


def jit(fn: Optional[Callable] = None, *, out_idx=None, target: str = "auto",
        execution_backend: str = "auto", verbose: bool = False,
        pass_configs: Optional[dict] = None, debug_root_path: Optional[str] = None,
        compile_flags=None):
    """Decorator over a kernel factory (reference jit:456)."""

    def wrap(f):
        if isinstance(f, PrimFuncObj):
            return compile(f, out_idx=out_idx, target=target,
                           verbose=verbose, pass_configs=pass_configs)
        return JITImpl(f, out_idx=out_idx, target=target, verbose=verbose,
                       pass_configs=pass_configs)

    if fn is not None:
        return wrap(fn)
    return wrap


# ---------------------------------------------------------------------------
# lazy_jit: per-shape specialization (reference lazy_jit:547)
# ---------------------------------------------------------------------------


def _solve_dims(annot_shape, actual_shape, binding: dict, pname: str):
    from ..ir import Var, as_int
    if len(annot_shape) != len(actual_shape):
        raise ValueError(
            f"lazy_jit: param {pname} rank mismatch: annotation rank "
            f"{len(annot_shape)} vs tensor rank {len(actual_shape)}")
    for dim, actual in zip(annot_shape, actual_shape):
        if isinstance(dim, Var):
            prev = binding.get(id(dim))
            if prev is None:
                binding[id(dim)] = (dim, int(actual))
            elif prev[1] != actual:
                raise ValueError(
                    f"lazy_jit: dim {dim.name} bound to both {prev[1]} and "
                    f"{actual}")
        else:
            c = as_int(dim)
            if c is not None and c != actual:
                raise ValueError(
                    f"lazy_jit: param {pname} expects dim {c}, got {actual}")


def _subst_shape(shape, env_map):
    from ..ir import Var, as_int, convert
    out = []
    for dim in shape:
        if isinstance(dim, Var):
            if id(dim) not in env_map:
                raise ValueError(f"lazy_jit: unbound symbolic dim {dim.name}")
            out.append(env_map[id(dim)])
        else:
            v = as_int(dim)
            if v is None:
                raise ValueError("lazy_jit: arithmetic symbolic dims are not "
                                 "supported yet; use bare T.dynamic dims")
            out.append(v)
    return tuple(out)


_LAZY_BIND_LOCK = threading.Lock()


class LazyJITImpl:
    def __init__(self, fn: Callable, **jit_kwargs):
        functools.update_wrapper(self, fn)
        self.fn = fn
        self.jit_kwargs = jit_kwargs
        self._kernels = {}

    def __call__(self, *tensors):
        from ..language.annot import TensorAnnot
        sig = inspect.signature(self.fn)
        names = list(sig.parameters)
        annots = [sig.parameters[n].annotation for n in names]
        out_idx = self.jit_kwargs.get("out_idx")
        if out_idx is not None:
            # outputs are allocated by the kernel: the caller passes inputs
            # only, and dims are solved from them (reference lazy_jit
            # shape-from-tensor path, tilelang/jit/__init__.py:547)
            idxs = [out_idx] if isinstance(out_idx, int) else list(out_idx)
            for i in idxs:
                if not -len(names) <= i < len(names):
                    raise IndexError(
                        f"out_idx {i} out of range for {len(names)} kernel "
                        f"params")
            outs = {i % len(names) for i in idxs}
            in_pos = [i for i in range(len(names)) if i not in outs]
        else:
            in_pos = list(range(len(names)))
        if len(tensors) != len(in_pos):
            raise TypeError(f"lazy_jit kernel takes {len(in_pos)} input "
                            f"tensors, got {len(tensors)}")
        binding: dict = {}
        for i, t in zip(in_pos, tensors):
            if isinstance(annots[i], TensorAnnot):
                _solve_dims(annots[i].shape, t.shape, binding, names[i])
        env_map = {k: v for k, (_, v) in binding.items()}
        # Key by the Var's unique uid, not its name: two distinct dyn vars
        # sharing a name would otherwise collide after sorting and silently
        # return the wrong cached specialization (round-1 advisor finding).
        shape_key = tuple(sorted((v.uid, val)
                                 for v, val in binding.values()))
        kernel = self._kernels.get(shape_key)
        if kernel is None:
            # re-trace with concrete shapes substituted into annotations
            concrete = []
            for pname, annot in zip(names, annots):
                if isinstance(annot, TensorAnnot):
                    concrete.append(TensorAnnot(
                        _subst_shape(annot.shape, env_map), annot.dtype))
                else:
                    concrete.append(annot)
            fn = self.fn
            # Var._bound is process-global mutable state: serialize all
            # lazy_jit specializations so a concurrent trace (par_compile
            # runs a ThreadPoolExecutor in this module) can never fold
            # against another call-site's shape
            with _LAZY_BIND_LOCK:
                orig = dict(fn.__annotations__)
                try:
                    for n, a in zip(names, concrete):
                        fn.__annotations__[n] = a
                    # bind dyn Vars so body uses (grid extents, bounds
                    # checks) fold to this call-site's concrete shape;
                    # compile must run inside the binding scope too —
                    # exprs traced un-foldable (e.g. tail guards `i < M`)
                    # still hold the Var and only resolve while its
                    # binding is live
                    for var, val in binding.values():
                        var._bound = val
                    pf = trace_prim_func(fn)
                    kernel = compile(pf, **self.jit_kwargs)
                finally:
                    fn.__annotations__.update(orig)
                    for var, _ in binding.values():
                        var._bound = None
            self._kernels[shape_key] = kernel
        return kernel(*tensors)


def lazy_jit(fn: Optional[Callable] = None, *, out_idx=None,
             target: str = "auto", verbose: bool = False,
             pass_configs: Optional[dict] = None, **_ignored):
    def wrap(f):
        return LazyJITImpl(f, out_idx=out_idx, target=target,
                           verbose=verbose, pass_configs=pass_configs)
    if fn is not None:
        return wrap(fn)
    return wrap
