"""Retry with jittered exponential backoff + per-signature circuit breaker.

Policy knobs come from env (``TL_TPU_RETRY_MAX`` / ``TL_TPU_RETRY_BASE_MS``
/ ``TL_TPU_RETRY_MAX_MS`` / ``TL_TPU_BREAKER_THRESHOLD``) so an operator
can harden or loosen a serving process without a code change. Decisions
key on the error taxonomy (errors.classify):

- transient    — retried up to ``max_attempts`` total attempts
- timeout      — retried at most once (a wedged compile usually wedges
                 again; one retry covers scheduler hiccups)
- device_loss  — retried like a transient: the kernel-level backend
                 failover (codegen/backends.py) swaps the dead backend
                 underneath the retry, so the next attempt runs on a
                 live one instead of burning the budget on a dead worker
- deterministic — never retried, and its signature is fed to the circuit
                 breaker: after ``threshold`` occurrences the breaker
                 opens and callers (the autotuner sweep) fast-fail
                 matching work instead of burning the timeout budget on
                 a failure mode that is already understood.

Every retry emits a ``resilience.retry`` tracer event + counter; every
breaker trip emits ``resilience.breaker_open``.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from ..env import env
from ..observability import tracer as _trace
from .errors import classify, error_signature

__all__ = ["RetryPolicy", "CircuitBreaker", "retry_call", "global_breaker"]

logger = logging.getLogger("tilelang_mesh_tpu.resilience")


@dataclass
class RetryPolicy:
    """Jittered exponential backoff: delay(n) = min(base * 2^n, cap),
    scaled by a uniform jitter in [1-jitter, 1] so synchronized workers
    (autotune thread pool, multi-process cache writers) decorrelate."""

    max_attempts: int = 3          # total attempts, including the first
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    jitter: float = 0.5
    rng: random.Random = field(default_factory=lambda: random.Random(0),
                               repr=False)

    @classmethod
    def from_env(cls) -> "RetryPolicy":
        return cls(max_attempts=max(1, env.TL_TPU_RETRY_MAX),
                   base_delay_s=env.TL_TPU_RETRY_BASE_MS / 1e3,
                   max_delay_s=env.TL_TPU_RETRY_MAX_MS / 1e3)

    def delay_s(self, attempt: int) -> float:
        raw = min(self.base_delay_s * (2 ** attempt), self.max_delay_s)
        return raw * (1.0 - self.jitter * self.rng.random())


class CircuitBreaker:
    """Per-failure-signature breaker. ``record_failure`` counts identical
    failures; at ``threshold`` the signature's circuit opens and
    ``is_open`` reports it until ``reset``. Thread-safe — the autotuner's
    trial threads share one instance."""

    def __init__(self, threshold: Optional[int] = None):
        self.threshold = threshold if threshold is not None \
            else max(1, env.TL_TPU_BREAKER_THRESHOLD)
        self._lock = threading.Lock()
        self._failures: Dict[str, int] = {}

    def record_failure(self, signature: str) -> bool:
        """Count one failure; returns True the moment this signature's
        circuit opens (exactly once, so callers can log/trace the trip)."""
        with self._lock:
            n = self._failures.get(signature, 0) + 1
            self._failures[signature] = n
        if n == self.threshold:
            _trace.inc("resilience.breaker_open")
            _trace.event("resilience.breaker_open", "resilience",
                         signature=signature, failures=n)
            logger.warning("circuit breaker OPEN for %r after %d identical "
                           "failures", signature, n)
            return True
        return False

    def is_open(self, signature: str) -> bool:
        with self._lock:
            return self._failures.get(signature, 0) >= self.threshold

    def reset(self, signature: Optional[str] = None) -> None:
        with self._lock:
            if signature is None:
                self._failures.clear()
            else:
                self._failures.pop(signature, None)


_GLOBAL_BREAKER: Optional[CircuitBreaker] = None
_GLOBAL_LOCK = threading.Lock()


def global_breaker() -> CircuitBreaker:
    """The process-wide breaker shared by autotune sweeps and compile
    retries, so repeated deterministic failures are recognized across
    call sites."""
    global _GLOBAL_BREAKER
    with _GLOBAL_LOCK:
        if _GLOBAL_BREAKER is None:
            _GLOBAL_BREAKER = CircuitBreaker()
        return _GLOBAL_BREAKER


def retry_call(fn: Callable, *, site: str, policy: Optional[RetryPolicy] = None,
               breaker: Optional[CircuitBreaker] = None,
               sleep: Callable[[float], None] = time.sleep):
    """Call ``fn()`` under the retry policy. Deterministic failures
    propagate immediately (after feeding the breaker); transients retry
    with backoff; timeouts retry once. Returns fn's value or raises the
    last error."""
    policy = policy or RetryPolicy.from_env()
    attempt = 0
    while True:
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — classified below
            kind = classify(e)
            sig = error_signature(e)
            # only deterministic failures feed the breaker: transients are
            # exactly what retry exists to absorb, and counting them would
            # open the circuit on the flakiness it is meant to ride out
            if breaker is not None and kind == "deterministic":
                breaker.record_failure(sig)
            retryable = (kind in ("transient", "device_loss") and
                         attempt + 1 < policy.max_attempts) or \
                        (kind == "timeout" and attempt == 0 and
                         policy.max_attempts > 1)
            if not retryable or (breaker is not None and breaker.is_open(sig)):
                raise
            d = policy.delay_s(attempt)
            attempt += 1
            _trace.inc("resilience.retry", site=site, kind=kind)
            _trace.event("resilience.retry", "resilience", site=site,
                         kind=kind, attempt=attempt, delay_s=round(d, 4),
                         error=f"{type(e).__name__}: {e}")
            logger.info("retrying %s after %s (attempt %d/%d, %.0f ms)",
                        site, type(e).__name__, attempt + 1,
                        policy.max_attempts, d * 1e3)
            sleep(d)
