"""Structured error taxonomy for the resilience subsystem.

Every fault the pipeline can recover from is classified into one of three
kinds, and the retry/fallback machinery keys its decisions on that kind:

- ``transient``     — worth retrying (flaky I/O, injected chaos, OOM-ish
                      resource pressure that clears). Retried with jittered
                      exponential backoff up to ``TL_TPU_RETRY_MAX`` times.
- ``timeout``       — the operation wedged past its wall-clock budget.
                      Retried at most once (a wedged XLA compile usually
                      wedges again); counted separately so sweeps can report
                      "slow" distinctly from "broken".
- ``deterministic`` — retrying cannot help (type errors, semantic-check
                      failures, codegen bugs). Never retried; repeated
                      occurrences of the same signature trip the circuit
                      breaker so sweeps stop burning time on them.
- ``device_loss``   — the execution backend itself died (TPU worker
                      unreachable, PJRT disconnect, DEADLINE_EXCEEDED
                      mid-dispatch). Retrying the SAME backend cannot
                      help, but the work is salvageable: the backend
                      registry (codegen/backends.py) marks the backend
                      unhealthy and the kernel re-lowers on the next
                      entry of the ``TL_TPU_BACKENDS`` failover chain.

``TLError`` subclasses carry ``site`` (the fault-site name, e.g.
``autotune.trial``) and ``phase`` (the pipeline phase, e.g. ``lower.plan``)
so a failure deep in a worker thread is still attributable in logs and
traces. Foreign exceptions are mapped by ``classify()``.
"""

from __future__ import annotations

import concurrent.futures
from typing import Optional

__all__ = [
    "TLError", "TransientError", "DeterministicError", "TLTimeoutError",
    "DeviceLossError", "InjectedFault", "classify", "error_signature",
    "is_device_loss",
]


class TLError(Exception):
    """Base of the structured error hierarchy. Carries enough context
    (kind / site / phase) that the retry machinery and the tracer never
    have to parse messages."""

    kind = "deterministic"

    def __init__(self, message: str, *, site: Optional[str] = None,
                 phase: Optional[str] = None):
        super().__init__(message)
        self.site = site
        self.phase = phase

    def __str__(self):
        base = super().__str__()
        ctx = ", ".join(f"{k}={v}" for k, v in
                        (("site", self.site), ("phase", self.phase)) if v)
        return f"{base} [{ctx}]" if ctx else base


class TransientError(TLError):
    """A failure that is expected to clear on retry."""
    kind = "transient"


class DeterministicError(TLError):
    """A failure retrying cannot fix; trips the circuit breaker."""
    kind = "deterministic"


class DeviceLossError(TLError):
    """The execution backend died under the operation (worker
    unreachable, PJRT disconnect). Not retried on the same backend;
    handled by backend failover (codegen/backends.py)."""
    kind = "device_loss"

    def __init__(self, message: str, *, site: Optional[str] = None,
                 phase: Optional[str] = None,
                 backend: Optional[str] = None):
        super().__init__(message, site=site, phase=phase)
        self.backend = backend


class TLTimeoutError(TLError, concurrent.futures.TimeoutError):
    """An operation exceeded its wall-clock budget. Also a
    ``concurrent.futures.TimeoutError`` so pre-taxonomy callers (and the
    reference tuner idiom) keep catching it."""
    kind = "timeout"


class InjectedFault(TransientError):
    """Raised by the fault-injection registry. Subtyped per spec ``kind``
    via ``as_kind()`` so injected faults flow through the exact same
    classification path as organic ones."""

    @staticmethod
    def as_kind(kind: str, site: str) -> TLError:
        msg = f"injected fault at {site}"
        if kind == "timeout":
            return TLTimeoutError(msg, site=site)
        if kind == "deterministic":
            return DeterministicError(msg, site=site)
        if kind == "oserror":
            return _InjectedOSError(msg)
        if kind == "unreachable":
            return DeviceLossError(f"injected device loss at {site}: "
                                   f"worker unreachable", site=site)
        return InjectedFault(msg, site=site)


class _InjectedOSError(OSError):
    """An injected I/O failure — a plain OSError so the cache's organic
    OSError handling is what gets exercised."""


# exception types that are transient regardless of message: I/O pressure
# and wedged-worker timeouts
_TRANSIENT_TYPES = (OSError, IOError, ConnectionError, MemoryError)
_TIMEOUT_TYPES = (concurrent.futures.TimeoutError, TimeoutError)

# message signatures of a dying execution backend, as XLA/jax surface
# them: gRPC deadline expiry, a tunnel/PJRT worker going away, and the
# PJRT client's own disconnect wording. Matched case-insensitively on
# FOREIGN exceptions only (TLErrors self-classify). Deliberately
# NARROW multi-word phrases: a bare "unreachable" would match a
# compiler's "unreachable code reached" and a bare "pjrt" would match
# "PJRT plugin does not support X" — deterministic errors that must
# never mark a healthy backend dead.
_DEVICE_LOSS_MARKERS = (
    "deadline_exceeded",
    "deadline exceeded",
    "worker unreachable",
    "failed to connect",
    "connection reset",
    "socket closed",
    "device lost",
    "device is lost",
    "pjrt client is dead",
    "pjrt plugin exited",
    "tpu initialization failed",
    "backend 'tpu' failed to initialize",
    "unavailable: ",      # absl::UnavailableError prefix
)


def is_device_loss(exc: BaseException) -> bool:
    """Does this exception look like the execution backend itself died
    (as opposed to the program on it being wrong)?"""
    if isinstance(exc, DeviceLossError):
        return True
    if isinstance(exc, TLError):
        return False
    msg = str(exc).lower()
    return any(m in msg for m in _DEVICE_LOSS_MARKERS)


def classify(exc: BaseException) -> str:
    """Map any exception to ``transient`` / ``timeout`` /
    ``deterministic`` / ``device_loss``. TLErrors self-classify; foreign
    exceptions fall back to message signatures (device loss) then
    type-based rules (I/O errors are transient, everything else —
    TypeError, ValueError, codegen failures — is deterministic)."""
    if isinstance(exc, TLError):
        return exc.kind
    if is_device_loss(exc):
        return "device_loss"
    if isinstance(exc, _TIMEOUT_TYPES):
        return "timeout"
    if isinstance(exc, _TRANSIENT_TYPES):
        return "transient"
    return "deterministic"


def error_signature(exc: BaseException, limit: int = 80) -> str:
    """A stable signature for circuit-breaker bucketing: exception type
    plus the head of its message (long messages often embed addresses or
    shapes that would defeat bucketing)."""
    return f"{type(exc).__name__}:{str(exc)[:limit]}"
