"""Structured error taxonomy for the resilience subsystem.

Every fault the pipeline can recover from is classified into one of three
kinds, and the retry/fallback machinery keys its decisions on that kind:

- ``transient``     — worth retrying (flaky I/O, injected chaos, OOM-ish
                      resource pressure that clears). Retried with jittered
                      exponential backoff up to ``TL_TPU_RETRY_MAX`` times.
- ``timeout``       — the operation wedged past its wall-clock budget.
                      Retried at most once (a wedged XLA compile usually
                      wedges again); counted separately so sweeps can report
                      "slow" distinctly from "broken".
- ``deterministic`` — retrying cannot help (type errors, semantic-check
                      failures, codegen bugs). Never retried; repeated
                      occurrences of the same signature trip the circuit
                      breaker so sweeps stop burning time on them.

``TLError`` subclasses carry ``site`` (the fault-site name, e.g.
``autotune.trial``) and ``phase`` (the pipeline phase, e.g. ``lower.plan``)
so a failure deep in a worker thread is still attributable in logs and
traces. Foreign exceptions are mapped by ``classify()``.
"""

from __future__ import annotations

import concurrent.futures
from typing import Optional

__all__ = [
    "TLError", "TransientError", "DeterministicError", "TLTimeoutError",
    "InjectedFault", "classify", "error_signature",
]


class TLError(Exception):
    """Base of the structured error hierarchy. Carries enough context
    (kind / site / phase) that the retry machinery and the tracer never
    have to parse messages."""

    kind = "deterministic"

    def __init__(self, message: str, *, site: Optional[str] = None,
                 phase: Optional[str] = None):
        super().__init__(message)
        self.site = site
        self.phase = phase

    def __str__(self):
        base = super().__str__()
        ctx = ", ".join(f"{k}={v}" for k, v in
                        (("site", self.site), ("phase", self.phase)) if v)
        return f"{base} [{ctx}]" if ctx else base


class TransientError(TLError):
    """A failure that is expected to clear on retry."""
    kind = "transient"


class DeterministicError(TLError):
    """A failure retrying cannot fix; trips the circuit breaker."""
    kind = "deterministic"


class TLTimeoutError(TLError, concurrent.futures.TimeoutError):
    """An operation exceeded its wall-clock budget. Also a
    ``concurrent.futures.TimeoutError`` so pre-taxonomy callers (and the
    reference tuner idiom) keep catching it."""
    kind = "timeout"


class InjectedFault(TransientError):
    """Raised by the fault-injection registry. Subtyped per spec ``kind``
    via ``as_kind()`` so injected faults flow through the exact same
    classification path as organic ones."""

    @staticmethod
    def as_kind(kind: str, site: str) -> TLError:
        msg = f"injected fault at {site}"
        if kind == "timeout":
            return TLTimeoutError(msg, site=site)
        if kind == "deterministic":
            return DeterministicError(msg, site=site)
        if kind == "oserror":
            return _InjectedOSError(msg)
        return InjectedFault(msg, site=site)


class _InjectedOSError(OSError):
    """An injected I/O failure — a plain OSError so the cache's organic
    OSError handling is what gets exercised."""


# exception types that are transient regardless of message: I/O pressure
# and wedged-worker timeouts
_TRANSIENT_TYPES = (OSError, IOError, ConnectionError, MemoryError)
_TIMEOUT_TYPES = (concurrent.futures.TimeoutError, TimeoutError)


def classify(exc: BaseException) -> str:
    """Map any exception to ``transient`` / ``timeout`` /
    ``deterministic``. TLErrors self-classify; foreign exceptions fall
    back to type-based rules (I/O errors are transient, everything else —
    TypeError, ValueError, codegen failures — is deterministic)."""
    if isinstance(exc, TLError):
        return exc.kind
    if isinstance(exc, _TIMEOUT_TYPES):
        return "timeout"
    if isinstance(exc, _TRANSIENT_TYPES):
        return "transient"
    return "deterministic"


def error_signature(exc: BaseException, limit: int = 80) -> str:
    """A stable signature for circuit-breaker bucketing: exception type
    plus the head of its message (long messages often embed addresses or
    shapes that would defeat bucketing)."""
    return f"{type(exc).__name__}:{str(exc)[:limit]}"
