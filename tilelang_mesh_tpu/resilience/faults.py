"""Deterministic fault injection.

The pipeline's recovery paths (retry, quarantine, interpreter fallback)
are only trustworthy if they can be exercised on demand. This module puts
a named *fault site* at every place the pipeline touches something that
can fail in production — disk, XLA, worker threads — and arms them from a
spec string so a chaos run is one env var away:

    TL_TPU_FAULTS="cache.disk.write:p=0.3:seed=7;autotune.trial:p=0.5:kind=transient"

Grammar (``;``-separated clauses, ``:``-separated fields)::

    site[:p=<float>][:seed=<int>][:kind=<kind>][:times=<int>]

- ``site``  — a fault-site name or fnmatch glob (``lower.*`` arms every
  lowering phase). Known sites: see ``FAULT_SITES``.
- ``p``     — per-visit injection probability (default 1.0).
- ``seed``  — seeds the clause's private RNG, so a chaos run replays
  byte-for-byte (default 0). The RNG advances once per matching visit.
- ``kind``  — ``transient`` (default) / ``timeout`` / ``deterministic`` /
  ``oserror`` / ``corrupt`` / ``unreachable``. All but ``corrupt`` raise
  the matching exception from the errors taxonomy (``unreachable`` raises
  :class:`~.errors.DeviceLossError`, simulating the TPU worker dying at
  ``device.probe`` / ``device.dispatch`` so the backend-failover tier is
  deterministically testable); ``corrupt`` is site-specific: at
  ``cache.disk.write`` the site simulates a torn write (the artifact
  lands truncated, exercising checksum + quarantine on load), and at
  ``comm.chunk``/``comm.fused`` the collective interpret path silently
  poisons its wire payload (a compiled-in miscompile, exercising the
  ``TL_TPU_SELFCHECK`` divergence net — parallel/lowering.py).
  ``torn`` / ``delay`` / ``kill`` are ``fleet.ipc``-specific
  (serving/worker.py): flip a byte in the next IPC frame, stall the
  round-trip past the watchdog, or SIGKILL the worker process.
- ``times`` — inject at most N times, then the clause goes inert.

Tests use the ``inject(...)`` context manager instead of the env var.
Every injection emits a ``fault.injected`` tracer event and increments
the ``fault.injected{site=...}`` counter; with ``TL_TPU_FAULTS`` unset
and no active ``inject()`` scope, ``maybe_fail`` is a two-branch no-op.
"""

from __future__ import annotations

import contextlib
import fnmatch
import logging
import random
import threading
from typing import List, Optional, Tuple

from ..env import env
from ..observability import tracer as _trace
from .errors import InjectedFault

__all__ = ["FAULT_SITES", "FaultSpec", "maybe_fail", "inject",
           "parse_fault_spec", "active_specs", "CorruptionRequest",
           "IPCFaultRequest"]

logger = logging.getLogger("tilelang_mesh_tpu.resilience")

# every armable site, in pipeline order — docs and the analyzer key on
# these names; globs in specs match against them
FAULT_SITES = (
    "cache.disk.read",
    "cache.disk.write",
    "lower.canonicalize",
    "lower.checks",
    "lower.plan",
    "lower.codegen",
    "lower.artifact",
    "autotune.trial",
    "jit.compile",
    "comm.collective",
    "comm.chunk",
    "comm.fused",
    "device.probe",
    "device.dispatch",
    "serve.admit",
    "serve.step",
    "serve.kv",
    "serve.shard",
    "serve.engine",
    "fleet.ipc",
)

_KINDS = ("transient", "timeout", "deterministic", "oserror", "corrupt",
          "unreachable", "torn", "delay", "kill")


class CorruptionRequest(Exception):
    """Raised for ``kind=corrupt`` clauses; the site catches it and
    corrupts its own artifact instead of failing. ``cache.disk.write``
    persists a deliberately torn artifact (the on-disk damage a crash
    mid-write would leave); ``comm.chunk``/``comm.fused`` poison the
    collective's wire payload at trace time (a silent miscompile for
    the selfcheck to catch)."""

    def __init__(self, site: str):
        super().__init__(f"injected torn write at {site}")
        self.site = site


class IPCFaultRequest(Exception):
    """Raised for ``kind=torn`` / ``delay`` / ``kill`` clauses — the
    ``fleet.ipc`` site (serving/worker.py) catches it and damages its
    own transport instead of failing: ``torn`` flips a byte inside the
    next frame (the checksum catches it on decode), ``delay`` stalls
    the round-trip past the step watchdog, ``kill`` SIGKILLs the
    worker process mid-RPC (real process death, not a Python
    exception)."""

    def __init__(self, site: str, mode: str):
        super().__init__(f"injected ipc fault ({mode}) at {site}")
        self.site = site
        self.mode = mode


class FaultSpec:
    """One armed clause: a site pattern plus its private, seeded RNG."""

    __slots__ = ("pattern", "p", "seed", "kind", "times", "_rng", "_fired")

    def __init__(self, pattern: str, p: float = 1.0, seed: int = 0,
                 kind: str = "transient", times: Optional[int] = None):
        if kind not in _KINDS:
            raise ValueError(
                f"TL_TPU_FAULTS: unknown kind {kind!r} (one of {_KINDS})")
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"TL_TPU_FAULTS: p={p} outside [0, 1]")
        self.pattern = pattern
        self.p = p
        self.seed = seed
        self.kind = kind
        self.times = times
        self._rng = random.Random(seed)
        self._fired = 0

    def matches(self, site: str) -> bool:
        return fnmatch.fnmatchcase(site, self.pattern)

    def should_fire(self) -> bool:
        """Advance the clause RNG once; decide. The draw happens on every
        matching visit (even when ``times`` is exhausted is checked first)
        so the injection sequence depends only on the visit order."""
        if self.times is not None and self._fired >= self.times:
            return False
        if self._rng.random() >= self.p:
            return False
        self._fired += 1
        return True

    def __repr__(self):
        return (f"FaultSpec({self.pattern!r}, p={self.p}, seed={self.seed}, "
                f"kind={self.kind!r}, times={self.times})")


def parse_fault_spec(raw: str) -> List[FaultSpec]:
    """Parse a ``TL_TPU_FAULTS`` string into clauses. Raises ValueError
    on malformed input — a silently mis-parsed chaos spec would report a
    falsely green run."""
    specs: List[FaultSpec] = []
    for clause in raw.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        fields = clause.split(":")
        site = fields[0].strip()
        if not site:
            raise ValueError(f"TL_TPU_FAULTS: empty site in {clause!r}")
        kwargs: dict = {}
        for f in fields[1:]:
            if "=" not in f:
                raise ValueError(
                    f"TL_TPU_FAULTS: field {f!r} in {clause!r} is not "
                    f"key=value")
            k, v = f.split("=", 1)
            k = k.strip()
            v = v.strip()
            try:
                if k == "p":
                    kwargs["p"] = float(v)
                elif k == "seed":
                    kwargs["seed"] = int(v)
                elif k == "times":
                    kwargs["times"] = int(v)
            except ValueError:
                raise ValueError(
                    f"TL_TPU_FAULTS: {k}={v!r} in {clause!r} is not a "
                    f"number") from None
            if k in ("p", "seed", "times"):
                continue
            if k == "kind":
                kwargs["kind"] = v
            else:
                raise ValueError(
                    f"TL_TPU_FAULTS: unknown field {k!r} in {clause!r} "
                    f"(p / seed / kind / times)")
        specs.append(FaultSpec(site, **kwargs))
    return specs


# parsed-spec cache keyed by the raw env string, so a monkeypatched env
# takes effect on the next visit while the steady state parses once.
# Clause RNG state lives in the cached FaultSpec objects: re-parsing on
# every call would reset the sequence and break determinism.
_env_lock = threading.Lock()
_env_cache: Tuple[Optional[str], List[FaultSpec]] = (None, [])

# programmatic injections (tests): a process-global stack so faults reach
# worker threads (autotune trials, par_compile) too
_overrides: List[FaultSpec] = []


def _env_specs() -> List[FaultSpec]:
    global _env_cache
    raw = env.TL_TPU_FAULTS
    if not raw:
        return []
    with _env_lock:
        if _env_cache[0] != raw:
            _env_cache = (raw, parse_fault_spec(raw))
        return _env_cache[1]


def active_specs() -> List[FaultSpec]:
    """Every clause currently armed (env + inject() scopes)."""
    return _env_specs() + list(_overrides)


def corrupt_armed(site: str) -> bool:
    """Is a ``kind=corrupt`` clause armed for this site? A read-only
    probe: neither the seeded coin nor the ``times=`` budget advances.
    Sites that exist at BOTH a bookkeeping point and the point that can
    actually corrupt an artifact use this to leave the whole clause
    budget to the corrupting visit (``comm.collective``: lowering-time
    accounting vs the trace-time payload poison)."""
    if not _overrides and not env.TL_TPU_FAULTS:
        return False
    return any(spec.kind == "corrupt" and spec.matches(site)
               for spec in active_specs())


def maybe_fail(site: str, **ctx) -> None:
    """The hook each fault site calls. No-op unless a clause matches and
    its seeded coin lands; then records the injection and raises the
    clause's error kind."""
    if not _overrides and not env.TL_TPU_FAULTS:
        return
    for spec in active_specs():
        if not spec.matches(site) or not spec.should_fire():
            continue
        _trace.inc("fault.injected", site=site)
        _trace.event("fault.injected", "resilience", site=site,
                     kind=spec.kind, pattern=spec.pattern, **ctx)
        logger.debug("fault injected at %s (kind=%s, pattern=%s)",
                     site, spec.kind, spec.pattern)
        if spec.kind == "corrupt":
            raise CorruptionRequest(site)
        if spec.kind in ("torn", "delay", "kill"):
            raise IPCFaultRequest(site, spec.kind)
        raise InjectedFault.as_kind(spec.kind, site)


@contextlib.contextmanager
def inject(site: str, p: float = 1.0, seed: int = 0,
           kind: str = "transient", times: Optional[int] = None):
    """Arm one clause for the duration of a ``with`` block (tests)::

        with inject("autotune.trial", p=0.5, seed=3, times=2):
            tuned(1024, 1024)

    Process-global (worker threads see it); yields the FaultSpec so the
    test can assert on ``spec._fired``.
    """
    spec = FaultSpec(site, p=p, seed=seed, kind=kind, times=times)
    _overrides.append(spec)
    try:
        yield spec
    finally:
        _overrides.remove(spec)
