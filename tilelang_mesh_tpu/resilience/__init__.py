"""Resilience subsystem: fault injection, retry/backoff, circuit breaking.

The compile/tune path is a long-running service in production: a torn
cache write, a wedged XLA compile, or a flaky autotune trial must degrade
the run, not corrupt or abort it. This package provides the three
building blocks the rest of the pipeline leans on:

- ``faults``  — deterministic fault injection: named sites armed by
  ``TL_TPU_FAULTS`` (or ``inject()`` in tests), seeded per clause so a
  chaos run replays exactly (see docs/robustness.md for the grammar)
- ``errors``  — the ``TLError`` taxonomy (transient / timeout /
  deterministic) + ``classify()`` for foreign exceptions
- ``retry``   — jittered exponential backoff (``retry_call``) and a
  per-failure-signature ``CircuitBreaker``

Consumers: ``cache/kernel_cache.py`` (atomic writes, checksum verify,
quarantine, per-key locks), ``autotuner/`` (trial classification, retry,
sweep journal), ``jit/kernel.py`` (interpreter fallback under
``TL_TPU_FALLBACK=interp``), ``engine/lower.py`` + ``parallel/lowering.py``
(per-phase fault sites). Everything is observable: injections, retries,
breaker trips, quarantines, and degradations all land in the tracer.
"""

from .errors import (DeterministicError, DeviceLossError, InjectedFault,
                     TLError, TLTimeoutError, TransientError, classify,
                     error_signature, is_device_loss)
from .faults import (FAULT_SITES, CorruptionRequest, FaultSpec,
                     active_specs, inject, maybe_fail, parse_fault_spec)
from .retry import CircuitBreaker, RetryPolicy, global_breaker, retry_call

__all__ = [
    "TLError", "TransientError", "DeterministicError", "TLTimeoutError",
    "DeviceLossError", "InjectedFault", "classify", "error_signature",
    "is_device_loss",
    "FAULT_SITES", "FaultSpec", "CorruptionRequest", "maybe_fail", "inject",
    "parse_fault_spec", "active_specs",
    "RetryPolicy", "CircuitBreaker", "retry_call", "global_breaker",
]
