from .transformer import (ModelConfig, init_params, forward, loss_fn,
                          make_train_step, make_sharded_train_step,
                          param_specs)
