"""Flagship model: LLaMA-style decoder built on the tile-kernel library.

The reference is a kernel framework whose examples compose into model
components (flash_attention, fusedmoe, norm — SURVEY §2.4); this module is
the corresponding model tier: a functional transformer whose attention runs
the framework's FlashAttention tile kernel, with a megatron-style
tensor+data-parallel training step expressed through ``shard_map`` over a
("dp", "tp") mesh — attention heads and MLP hidden sharded on tp (activation
psums ride ICI), batch on dp (gradient psums).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    vocab: int = 256
    d_model: int = 128
    n_layers: int = 2
    n_heads: int = 8
    d_ff: int = 384
    max_seq: int = 128
    dtype: Any = jnp.float32
    rope_theta: float = 10000.0
    use_flash: bool = True   # tile kernel vs jnp reference (tiny-shape runs)

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


# ---------------------------------------------------------------------------
# params
# ---------------------------------------------------------------------------


def init_params(rng: jax.Array, cfg: ModelConfig) -> Dict:
    k = jax.random.split(rng, 2 + cfg.n_layers)
    d, f, hd = cfg.d_model, cfg.d_ff, cfg.head_dim

    def dense(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale
                ).astype(cfg.dtype)

    layers = []
    for i in range(cfg.n_layers):
        lk = jax.random.split(k[2 + i], 7)
        layers.append({
            "attn_norm": jnp.ones((d,), cfg.dtype),
            "wq": dense(lk[0], (d, d), d ** -0.5),
            "wk": dense(lk[1], (d, d), d ** -0.5),
            "wv": dense(lk[2], (d, d), d ** -0.5),
            "wo": dense(lk[3], (d, d), d ** -0.5),
            "mlp_norm": jnp.ones((d,), cfg.dtype),
            "w_gate": dense(lk[4], (d, f), d ** -0.5),
            "w_up": dense(lk[5], (d, f), d ** -0.5),
            "w_down": dense(lk[6], (f, d), f ** -0.5),
        })
    return {
        "embed": dense(k[0], (cfg.vocab, d), 1.0),
        "final_norm": jnp.ones((d,), cfg.dtype),
        "layers": layers,
    }


def param_specs(cfg: ModelConfig):
    """PartitionSpec tree for the ("dp","tp") mesh: heads + mlp hidden on
    tp, everything else replicated."""
    from jax.sharding import PartitionSpec as P
    layer = {
        "attn_norm": P(),
        "wq": P(None, "tp"), "wk": P(None, "tp"), "wv": P(None, "tp"),
        "wo": P("tp", None),
        "mlp_norm": P(),
        "w_gate": P(None, "tp"), "w_up": P(None, "tp"),
        "w_down": P("tp", None),
    }
    return {
        "embed": P(),
        "final_norm": P(),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
    }


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _rms_norm(x, w, eps=1e-6):
    v = jnp.mean(jnp.square(x.astype(jnp.float32)), -1, keepdims=True)
    return (x * jax.lax.rsqrt(v + eps)).astype(x.dtype) * w


def _rope(x, theta: float):
    # x: (B, H, S, hd)
    hd = x.shape[-1]
    S = x.shape[2]
    half = hd // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    t = jnp.arange(S, dtype=jnp.float32)
    ang = jnp.einsum("s,f->sf", t, freqs)
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[..., :half], x[..., half:]
    xr1 = x1 * cos - x2 * sin
    xr2 = x2 * cos + x1 * sin
    return jnp.concatenate([xr1, xr2], -1).astype(x.dtype)


def _attention(x, lp, cfg: ModelConfig, n_heads_local: int,
               tp_axis: Optional[str]):
    B, S, _ = x.shape
    hd = cfg.head_dim
    h = _rms_norm(x, lp["attn_norm"])

    def proj(w):
        y = jnp.einsum("bsd,dk->bsk", h, w)
        return y.reshape(B, S, n_heads_local, hd).transpose(0, 2, 1, 3)

    q, k, v = proj(lp["wq"]), proj(lp["wk"]), proj(lp["wv"])
    q, k = _rope(q, cfg.rope_theta), _rope(k, cfg.rope_theta)

    if cfg.use_flash:
        from ..ops.flash_attention import flash_attention
        o = flash_attention(q, k, v, causal=True,
                            block_M=min(128, S), block_N=min(128, S))
    else:
        from ..ops.flash_attention import _reference_attention
        o = _reference_attention(q, k, v, True, 1.0 / math.sqrt(hd))

    o = o.transpose(0, 2, 1, 3).reshape(B, S, n_heads_local * hd)
    o = jnp.einsum("bsk,kd->bsd", o, lp["wo"])
    if tp_axis is not None:
        o = jax.lax.psum(o, tp_axis)
    return x + o.astype(x.dtype)


def _mlp(x, lp, tp_axis: Optional[str]):
    h = _rms_norm(x, lp["mlp_norm"])
    g = jax.nn.silu(jnp.einsum("bsd,df->bsf", h, lp["w_gate"]))
    u = jnp.einsum("bsd,df->bsf", h, lp["w_up"])
    y = jnp.einsum("bsf,fd->bsd", g * u, lp["w_down"])
    if tp_axis is not None:
        y = jax.lax.psum(y, tp_axis)
    return x + y.astype(x.dtype)


def forward(params: Dict, tokens: jax.Array, cfg: ModelConfig,
            tp_axis: Optional[str] = None) -> jax.Array:
    """tokens (B, S) int32 -> logits (B, S, vocab). Works on full params
    (tp_axis=None) or tp-sharded params inside shard_map."""
    x = params["embed"][tokens].astype(cfg.dtype)
    n_heads_local = params["layers"][0]["wq"].shape[1] // cfg.head_dim
    for lp in params["layers"]:
        x = _attention(x, lp, cfg, n_heads_local, tp_axis)
        x = _mlp(x, lp, tp_axis)
    x = _rms_norm(x, params["final_norm"])
    return jnp.einsum("bsd,vd->bsv", x.astype(jnp.float32),
                      params["embed"].astype(jnp.float32))


def loss_fn(params, tokens, cfg: ModelConfig,
            tp_axis: Optional[str] = None):
    """Next-token cross entropy (mean over local batch)."""
    logits = forward(params, tokens[:, :-1], cfg, tp_axis)
    targets = tokens[:, 1:]
    logz = jax.scipy.special.logsumexp(logits, -1)
    gold = jnp.take_along_axis(logits, targets[..., None], -1)[..., 0]
    return jnp.mean(logz - gold)


# ---------------------------------------------------------------------------
# training steps
# ---------------------------------------------------------------------------


def make_train_step(cfg: ModelConfig, lr: float = 3e-4):
    """Single-device training step (adamw via optax)."""
    import optax
    opt = optax.adamw(lr)

    def init(params):
        return opt.init(params)

    @jax.jit
    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, tokens, cfg))(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return init, step


def make_sharded_train_step(cfg: ModelConfig, mesh, lr: float = 3e-4):
    """Megatron-style dp x tp training step under shard_map.

    Forward: tp-sharded attention heads / mlp hidden with activation psums
    over "tp". Backward: grads psum over "dp"; grads of replicated params
    additionally psum over "tp" (the transpose collective of using a
    replicated activation against a tp-sharded weight).
    """
    import optax
    from jax.sharding import PartitionSpec as P

    opt = optax.adamw(lr)
    pspecs = param_specs(cfg)

    def _is_replicated(spec) -> bool:
        return all(s is None for s in spec)

    def local_step(params, opt_state, tokens):
        from ..parallel.device_mesh import axis_size_compat
        dp = axis_size_compat("dp")

        def local_loss(p):
            return loss_fn(p, tokens, cfg, tp_axis="tp")

        loss, grads = jax.value_and_grad(local_loss)(params)
        loss = jax.lax.pmean(loss, "dp")
        grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
        grads = jax.tree.map(
            lambda g, s: jax.lax.psum(g, "tp") if _is_replicated(s) else g,
            grads, pspecs)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, jax.lax.pmean(loss, "tp")

    def init(params):
        return opt.init(params)

    def make(params, opt_state):
        data_spec = P("dp")
        pspec_tree = pspecs
        # optimizer-state leaves mirror param paths (mu/nu subtrees); match
        # each state leaf to its param's spec by key-path suffix
        from jax.tree_util import keystr, tree_flatten_with_path
        from jax.tree_util import tree_map_with_path
        param_paths = [(keystr(kp), spec) for kp, spec in
                       tree_flatten_with_path(pspec_tree)[0]]

        def state_spec(kp, leaf):
            ks = keystr(kp)
            for ppath, spec in param_paths:
                if ks.endswith(ppath):
                    return spec
            return P()

        ospec_tree = tree_map_with_path(state_spec, opt_state)
        from ..parallel.device_mesh import shard_map_compat
        f = shard_map_compat(
            local_step, mesh=mesh,
            in_specs=(pspec_tree, ospec_tree, data_spec),
            out_specs=(pspec_tree, ospec_tree, P()))
        return jax.jit(f)

    return init, make
