"""Grouped-query attention: Hq query heads share Hkv < Hq KV heads
(reference examples/flash_attention GQA variants).

The KV head for query head h is h // (Hq // Hkv): the planner lowers that
`//` into the K/V BlockSpec index maps directly, so every query-head grid
step fetches its group's KV tiles through the same pipelined path as MHA.
"""

import functools
import math

import tilelang_mesh_tpu.language as T
from ..jit import compile as _tl_compile
from .flash_attention import _always


@functools.lru_cache(maxsize=None)
def gqa_fwd_kernel(B, Hq, Hkv, Sq, Sk, D, block_M, block_N, causal,
                   sm_scale, dtype, num_stages=2):
    assert Hq % Hkv == 0
    group = Hq // Hkv
    scale = sm_scale * 1.44269504

    @T.prim_func
    def gqa_fwd(Q: T.Tensor((B, Hq, Sq, D), dtype),
                K: T.Tensor((B, Hkv, Sk, D), dtype),
                V: T.Tensor((B, Hkv, Sk, D), dtype),
                O: T.Tensor((B, Hq, Sq, D), dtype)):
        with T.Kernel(T.ceildiv(Sq, block_M), Hq, B) as (bx, by, bz):
            Q_s = T.alloc_shared((block_M, D), dtype)
            K_s = T.alloc_shared((block_N, D), dtype)
            V_s = T.alloc_shared((block_N, D), dtype)
            S = T.alloc_fragment((block_M, block_N), "float32")
            P = T.alloc_fragment((block_M, block_N), dtype)
            acc = T.alloc_fragment((block_M, D), "float32")
            m_prev = T.alloc_fragment((block_M,), "float32")
            m_new = T.alloc_fragment((block_M,), "float32")
            m_cur = T.alloc_fragment((block_M,), "float32")
            l = T.alloc_fragment((block_M,), "float32")
            l_cur = T.alloc_fragment((block_M,), "float32")

            T.copy(Q[bz, by, bx * block_M, 0], Q_s)
            T.fill(acc, 0)
            T.fill(l, 0)
            T.fill(m_prev, -T.infinity("float32"))

            for kb in T.Pipelined(T.ceildiv(Sk, block_N),
                                  num_stages=num_stages):
                with T.If(kb * block_N <= bx * block_M + (block_M - 1)) \
                        if causal else _always():
                    T.copy(K[bz, by // group, kb * block_N, 0], K_s)
                    T.copy(V[bz, by // group, kb * block_N, 0], V_s)
                    T.gemm(Q_s, K_s, S, transpose_B=True, clear_accum=True)
                    if causal:
                        for i, j in T.Parallel(block_M, block_N):
                            S[i, j] = T.if_then_else(
                                bx * block_M + i >= kb * block_N + j,
                                S[i, j] * scale, -T.infinity("float32"))
                    else:
                        for i, j in T.Parallel(block_M, block_N):
                            S[i, j] = S[i, j] * scale
                    T.reduce_max(S, m_cur, dim=1)
                    for i in T.Parallel(block_M):
                        m_new[i] = T.max(m_prev[i], m_cur[i])
                    for i, j in T.Parallel(block_M, block_N):
                        S[i, j] = T.exp2(S[i, j] - m_new[i])
                    T.reduce_sum(S, l_cur, dim=1)
                    for i in T.Parallel(block_M):
                        l[i] = l[i] * T.exp2(m_prev[i] - m_new[i]) + l_cur[i]
                    for i, j in T.Parallel(block_M, D):
                        acc[i, j] = acc[i, j] * T.exp2(m_prev[i] - m_new[i])
                    T.copy(S, P)
                    T.gemm(P, V_s, acc)
                    for i in T.Parallel(block_M):
                        m_prev[i] = m_new[i]

            for i, j in T.Parallel(block_M, D):
                acc[i, j] = acc[i, j] / l[i]
            T.copy(acc, O[bz, by, bx * block_M, 0])

    return _tl_compile(gqa_fwd)


def gqa_attention(q, k, v, causal=False, sm_scale=None, block_M=128,
                  block_N=128):
    """q (B, Hq, Sq, D); k/v (B, Hkv, Sk, D) with Hkv | Hq."""
    B, Hq, Sq, D = q.shape
    Hkv, Sk = k.shape[1], k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    kern = gqa_fwd_kernel(B, Hq, Hkv, Sq, Sk, D, min(block_M, Sq),
                          min(block_N, Sk), bool(causal), float(sm_scale),
                          str(q.dtype))
    return kern(q, k, v)
