"""Grouped-query attention: Hq query heads share Hkv < Hq KV heads
(reference examples/flash_attention GQA variants).

The KV head for query head h is h // (Hq // Hkv): the planner lowers that
`//` into the K/V BlockSpec index maps directly, so every query-head grid
step fetches its group's KV tiles through the same pipelined path as MHA.
"""

import functools
import math

import tilelang_mesh_tpu.language as T
from ..jit import compile as _tl_compile
from ._online_softmax import (alloc_softmax_state, init_softmax_state,
                              online_softmax_update)
from .flash_attention import (_always, _prescale_q,
                              _scaled_masked_scores)


@functools.lru_cache(maxsize=None)
def gqa_fwd_kernel(B, Hq, Hkv, Sq, Sk, D, block_M, block_N, causal,
                   sm_scale, dtype, num_stages=2):
    assert Hq % Hkv == 0
    group = Hq // Hkv
    scale = sm_scale * 1.44269504

    @T.prim_func
    def gqa_fwd(Q: T.Tensor((B, Hq, Sq, D), dtype),
                K: T.Tensor((B, Hkv, Sk, D), dtype),
                V: T.Tensor((B, Hkv, Sk, D), dtype),
                O: T.Tensor((B, Hq, Sq, D), dtype)):
        with T.Kernel(T.ceildiv(Sq, block_M), Hq, B) as (bx, by, bz):
            Q_s = T.alloc_shared((block_M, D), dtype)
            K_s = T.alloc_shared((block_N, D), dtype)
            V_s = T.alloc_shared((block_N, D), dtype)
            st = alloc_softmax_state(block_M, block_N, D, dtype)

            T.copy(Q[bz, by, bx * block_M, 0], Q_s)
            Q_f = _prescale_q(Q_s, scale, block_M, D, dtype)
            init_softmax_state(st)

            for kb in T.Pipelined(T.ceildiv(Sk, block_N),
                                  num_stages=num_stages):
                with T.If(kb * block_N <= bx * block_M + (block_M - 1)) \
                        if causal else _always():
                    T.copy(K[bz, by // group, kb * block_N, 0], K_s)
                    T.copy(V[bz, by // group, kb * block_N, 0], V_s)
                    _scaled_masked_scores(st, Q_f, K_s, causal, bx,
                                          kb, block_M, block_N)
                    online_softmax_update(st, V_s, block_M, block_N, D)

            acc, l = st["acc"], st["l"]
            for i, j in T.Parallel(block_M, D):
                # clamped divide (the dsa/nsa idiom): 0/0 = NaN on a
                # fully-underflowed row — tl-num TL009
                acc[i, j] = acc[i, j] / T.max(l[i], 1e-30)
            T.copy(acc, O[bz, by, bx * block_M, 0])

    return _tl_compile(gqa_fwd)


@functools.lru_cache(maxsize=None)
def gqa_fwd_partial_kernel(B, Hq, Hkv, Sq, Sk, D, block_M, block_N, causal,
                           sm_scale, dtype, num_stages=2):
    """Same online-softmax loop but emits the UNNORMALIZED accumulator and
    per-row (m, l) stats in the exp2 domain — what the backward kernels
    (ops/gqa_bwd.py) need to rebuild the softmax from L = m + log2(l)."""
    assert Hq % Hkv == 0
    group = Hq // Hkv
    scale = sm_scale * 1.44269504

    @T.prim_func
    def gqa_fwd_partial(Q: T.Tensor((B, Hq, Sq, D), dtype),
                        K: T.Tensor((B, Hkv, Sk, D), dtype),
                        V: T.Tensor((B, Hkv, Sk, D), dtype),
                        O: T.Tensor((B, Hq, Sq, D), "float32"),
                        M: T.Tensor((B, Hq, Sq), "float32"),
                        L: T.Tensor((B, Hq, Sq), "float32")):
        with T.Kernel(T.ceildiv(Sq, block_M), Hq, B) as (bx, by, bz):
            Q_s = T.alloc_shared((block_M, D), dtype)
            K_s = T.alloc_shared((block_N, D), dtype)
            V_s = T.alloc_shared((block_N, D), dtype)
            st = alloc_softmax_state(block_M, block_N, D, dtype)

            T.copy(Q[bz, by, bx * block_M, 0], Q_s)
            Q_f = _prescale_q(Q_s, scale, block_M, D, dtype)
            init_softmax_state(st)

            for kb in T.Pipelined(T.ceildiv(Sk, block_N),
                                  num_stages=num_stages):
                with T.If(kb * block_N <= bx * block_M + (block_M - 1)) \
                        if causal else _always():
                    T.copy(K[bz, by // group, kb * block_N, 0], K_s)
                    T.copy(V[bz, by // group, kb * block_N, 0], V_s)
                    _scaled_masked_scores(st, Q_f, K_s, causal, bx,
                                          kb, block_M, block_N)
                    online_softmax_update(st, V_s, block_M, block_N, D)

            T.copy(st["acc"], O[bz, by, bx * block_M, 0])
            T.copy(st["m_prev"], M[bz, by, bx * block_M])
            T.copy(st["l"], L[bz, by, bx * block_M])

    return _tl_compile(gqa_fwd_partial)


def gqa_attention(q, k, v, causal=False, sm_scale=None, block_M=128,
                  block_N=128, backward: str = "kernel"):
    """Differentiable grouped-query attention on the tile kernels.

    q (B, Hq, Sq, D); k/v (B, Hkv, Sk, D) with Hkv | Hq.

    backward="kernel" (default): forward under AD runs the partial kernel
    (saving m, l) and the backward runs the group-accumulating dKdV / dQ
    tile kernels (ops/gqa_bwd.py, cf. reference example_gqa_bwd.py).
    backward="reference": jax AD through the dense reference (debugging
    fallback).
    """
    from .flash_attention import _make_attention_vjp

    B, Hq, Sq, D = q.shape
    Hkv, Sk = k.shape[1], k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    bm, bn = min(block_M, Sq), min(block_N, Sk)
    kern = gqa_fwd_kernel(B, Hq, Hkv, Sq, Sk, D, bm, bn, bool(causal),
                          float(sm_scale), str(q.dtype))

    def _partial(q, k, v):
        pk = gqa_fwd_partial_kernel(B, Hq, Hkv, Sq, Sk, D, bm, bn,
                                    bool(causal), float(sm_scale),
                                    str(q.dtype))
        return pk(q, k, v)

    def _bwd(q, k, v, o, lse2, g):
        from .gqa_bwd import gqa_attention_bwd
        return gqa_attention_bwd(q, k, v, o, lse2, g, causal, sm_scale,
                                 bm, bn)

    fa = _make_attention_vjp(
        kern, _partial, _bwd,
        lambda q, k, v: _reference_gqa(q, k, v, causal, sm_scale),
        backward)
    return fa(q, k, v)


def _reference_gqa(q, k, v, causal, sm_scale):
    """Dense GQA reference (jax AD-able)."""
    import jax.numpy as jnp
    group = q.shape[1] // k.shape[1]
    k = jnp.repeat(k, group, axis=1)
    v = jnp.repeat(v, group, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        Sq, Sk = q.shape[2], k.shape[2]
        mask = jnp.tril(jnp.ones((Sq, Sk), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jnp.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)
