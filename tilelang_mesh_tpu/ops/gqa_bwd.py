"""Grouped-query attention backward as tile kernels.

Behavioral equivalent of the reference's
examples/flash_attention/example_gqa_bwd.py:1 — dK/dV for a KV head
accumulate contributions from every query head in its group, softmax is
recomputed from the forward log-sum-exp.

TPU re-design (no atomics, cf. ops/flash_attention_bwd.py): the dKdV
kernel grids over (KV blocks, KV heads, batch) so each dK/dV output block
is written exactly once; the query-head group and the Q-block sweep are
folded into ONE pipelined axis (t -> (head_in_group, q_block)) so Mosaic
overlaps the Q/dO/L/Delta fetches of the whole group — where the
reference accumulates per-warp partials and reduces through shared
memory/TMA, here the group reduction is just more steps on the pipelined
axis feeding the same VMEM accumulator. The dQ kernel is the MHA dQ with
the KV head taken as query_head // group.
"""

import functools

import tilelang_mesh_tpu.language as T
from ..jit import compile as _tl_compile
from .flash_attention import _always

_LOG2E = 1.44269504


@functools.lru_cache(maxsize=None)
def gqa_bwd_dkdv_kernel(B, Hq, Hkv, Sq, Sk, D, block_M, block_N, causal,
                        sm_scale, dtype, num_stages=2):
    assert Hq % Hkv == 0
    group = Hq // Hkv
    scale2 = sm_scale * _LOG2E
    nQ = -(-Sq // block_M)

    @T.prim_func
    def dkdv(Q: T.Tensor((B, Hq, Sq, D), dtype),
             K: T.Tensor((B, Hkv, Sk, D), dtype),
             V: T.Tensor((B, Hkv, Sk, D), dtype),
             dO: T.Tensor((B, Hq, Sq, D), dtype),
             L: T.Tensor((B, Hq, Sq), "float32"),
             Delta: T.Tensor((B, Hq, Sq), "float32"),
             dK: T.Tensor((B, Hkv, Sk, D), "float32"),
             dV: T.Tensor((B, Hkv, Sk, D), "float32")):
        with T.Kernel(T.ceildiv(Sk, block_N), Hkv, B) as (bx, by, bz):
            K_s = T.alloc_shared((block_N, D), dtype)
            V_s = T.alloc_shared((block_N, D), dtype)
            Q_s = T.alloc_shared((block_M, D), dtype)
            dO_s = T.alloc_shared((block_M, D), dtype)
            L_s = T.alloc_shared((block_M,), "float32")
            De_s = T.alloc_shared((block_M,), "float32")
            S = T.alloc_fragment((block_M, block_N), "float32")
            P = T.alloc_fragment((block_M, block_N), dtype)
            dP = T.alloc_fragment((block_M, block_N), "float32")
            dS = T.alloc_fragment((block_M, block_N), dtype)
            dK_a = T.alloc_fragment((block_N, D), "float32")
            dV_a = T.alloc_fragment((block_N, D), "float32")

            T.copy(K[bz, by, bx * block_N, 0], K_s)
            T.copy(V[bz, by, bx * block_N, 0], V_s)
            T.fill(dK_a, 0)
            T.fill(dV_a, 0)

            # one pipelined axis sweeping (head-in-group, q-block):
            # t // nQ selects the query head, t % nQ the Q block
            # (group == 1, the MHA case, keeps the plain indices)
            for t in T.Pipelined(group * nQ, num_stages=num_stages):
                hq = by if group == 1 else by * group + t // nQ
                qb = t if group == 1 else t % nQ
                with T.If(qb * block_M + (block_M - 1)
                          >= bx * block_N) if causal else _always():
                    T.copy(Q[bz, hq, qb * block_M, 0], Q_s)
                    T.copy(dO[bz, hq, qb * block_M, 0], dO_s)
                    T.copy(L[bz, hq, qb * block_M], L_s)
                    T.copy(Delta[bz, hq, qb * block_M], De_s)
                    T.gemm(Q_s, K_s, S, transpose_B=True, clear_accum=True)
                    if causal:
                        for i, j in T.Parallel(block_M, block_N):
                            S[i, j] = T.if_then_else(
                                qb * block_M + i >= bx * block_N + j,
                                T.exp2(S[i, j] * scale2 - L_s[i]), 0.0)
                    else:
                        for i, j in T.Parallel(block_M, block_N):
                            S[i, j] = T.exp2(S[i, j] * scale2 - L_s[i])
                    T.copy(S, P)
                    # dV += P^T dO  (accumulates across the whole group)
                    T.gemm(P, dO_s, dV_a, transpose_A=True)
                    # dP = dO V^T
                    T.gemm(dO_s, V_s, dP, transpose_B=True,
                           clear_accum=True)
                    for i, j in T.Parallel(block_M, block_N):
                        dS[i, j] = S[i, j] * (dP[i, j] - De_s[i]) * sm_scale
                    # dK += dS^T Q
                    T.gemm(dS, Q_s, dK_a, transpose_A=True)

            T.copy(dK_a, dK[bz, by, bx * block_N, 0])
            T.copy(dV_a, dV[bz, by, bx * block_N, 0])

    return _tl_compile(dkdv)


@functools.lru_cache(maxsize=None)
def gqa_bwd_dq_kernel(B, Hq, Hkv, Sq, Sk, D, block_M, block_N, causal,
                      sm_scale, dtype, num_stages=2):
    assert Hq % Hkv == 0
    group = Hq // Hkv
    scale2 = sm_scale * _LOG2E

    @T.prim_func
    def dq(Q: T.Tensor((B, Hq, Sq, D), dtype),
           K: T.Tensor((B, Hkv, Sk, D), dtype),
           V: T.Tensor((B, Hkv, Sk, D), dtype),
           dO: T.Tensor((B, Hq, Sq, D), dtype),
           L: T.Tensor((B, Hq, Sq), "float32"),
           Delta: T.Tensor((B, Hq, Sq), "float32"),
           dQ: T.Tensor((B, Hq, Sq, D), "float32")):
        with T.Kernel(T.ceildiv(Sq, block_M), Hq, B) as (bx, by, bz):
            Q_s = T.alloc_shared((block_M, D), dtype)
            dO_s = T.alloc_shared((block_M, D), dtype)
            L_s = T.alloc_shared((block_M,), "float32")
            De_s = T.alloc_shared((block_M,), "float32")
            K_s = T.alloc_shared((block_N, D), dtype)
            V_s = T.alloc_shared((block_N, D), dtype)
            S = T.alloc_fragment((block_M, block_N), "float32")
            dP = T.alloc_fragment((block_M, block_N), "float32")
            dS = T.alloc_fragment((block_M, block_N), dtype)
            dQ_a = T.alloc_fragment((block_M, D), "float32")

            T.copy(Q[bz, by, bx * block_M, 0], Q_s)
            T.copy(dO[bz, by, bx * block_M, 0], dO_s)
            T.copy(L[bz, by, bx * block_M], L_s)
            T.copy(Delta[bz, by, bx * block_M], De_s)
            T.fill(dQ_a, 0)

            hk = by if group == 1 else by // group
            for kb in T.Pipelined(T.ceildiv(Sk, block_N),
                                  num_stages=num_stages):
                with T.If(kb * block_N <= bx * block_M + (block_M - 1)) \
                        if causal else _always():
                    T.copy(K[bz, hk, kb * block_N, 0], K_s)
                    T.copy(V[bz, hk, kb * block_N, 0], V_s)
                    T.gemm(Q_s, K_s, S, transpose_B=True, clear_accum=True)
                    if causal:
                        for i, j in T.Parallel(block_M, block_N):
                            S[i, j] = T.if_then_else(
                                bx * block_M + i >= kb * block_N + j,
                                T.exp2(S[i, j] * scale2 - L_s[i]), 0.0)
                    else:
                        for i, j in T.Parallel(block_M, block_N):
                            S[i, j] = T.exp2(S[i, j] * scale2 - L_s[i])
                    T.gemm(dO_s, V_s, dP, transpose_B=True,
                           clear_accum=True)
                    for i, j in T.Parallel(block_M, block_N):
                        dS[i, j] = S[i, j] * (dP[i, j] - De_s[i]) * sm_scale
                    T.gemm(dS, K_s, dQ_a)

            T.copy(dQ_a, dQ[bz, by, bx * block_M, 0])

    return _tl_compile(dq)


def gqa_attention_bwd(q, k, v, o, lse2, g, causal, sm_scale, block_M=128,
                      block_N=128, delta=None):
    """lse2 = m + log2(l) from the forward partial kernel (exp2 domain).
    `delta` (= sum(g*o, -1), f32) may be passed by callers that already
    computed it (attention_sink's dsink closed form shares it)."""
    import jax.numpy as jnp
    B, Hq, Sq, D = q.shape
    Hkv, Sk = k.shape[1], k.shape[2]
    if delta is None:
        delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), -1)
    bm, bn = min(block_M, Sq), min(block_N, Sk)
    dkdv = gqa_bwd_dkdv_kernel(B, Hq, Hkv, Sq, Sk, D, bm, bn, bool(causal),
                               float(sm_scale), str(q.dtype))
    dqk = gqa_bwd_dq_kernel(B, Hq, Hkv, Sq, Sk, D, bm, bn, bool(causal),
                            float(sm_scale), str(q.dtype))
    dk, dv = dkdv(q, k, v, g, lse2, delta)
    dq_ = dqk(q, k, v, g, lse2, delta)
    return (dq_.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))
