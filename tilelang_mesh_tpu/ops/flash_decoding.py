"""Flash decoding: single-token attention against a long KV cache with
split-KV parallel reduction (BASELINE config #4).

Behavioral equivalent of /root/reference/examples/flash_decoding/: the KV
cache is split into chunks processed in parallel grid steps; each split
emits an unnormalized partial (o, m, l) and a tiny XLA epilogue combines
them — the split axis is a *parallel* Pallas grid dimension, so Mosaic
overlaps chunk DMA freely. Paged KV has two strategies: gather pages to
contiguous form at the XLA level then run the pipelined kernel
(`flash_decode_paged`), or walk an H-major page pool IN-KERNEL at
table-driven DMA offsets with no gather pass
(`flash_decode_paged_pool`); the bench measures both per chip.
"""

import functools
import math

import tilelang_mesh_tpu.language as T
from ..jit import compile as _tl_compile

_LOG2E = 1.44269504


@functools.lru_cache(maxsize=None)
def decode_kernel(B, H, S, D, n_split, block_N, sm_scale, dtype,
                  num_stages=2):
    chunk = S // n_split
    scale = sm_scale * _LOG2E

    # Stats layouts keep every grid-var index off the lane (minor) axis:
    # Mosaic only allows dynamic lane offsets that are 128-aligned, while
    # dynamic sublane offsets are unrestricted — so the head index rides
    # the sublane axis and the lane axis is D (Op) or a unit dim (Mp/Lp).
    @T.prim_func
    def dec(Q: T.Tensor((B, H, 1, D), dtype),
            K: T.Tensor((B, H, S, D), dtype),
            V: T.Tensor((B, H, S, D), dtype),
            Op: T.Tensor((B, n_split, H, D), "float32"),
            Mp: T.Tensor((B, n_split, H, 1), "float32"),
            Lp: T.Tensor((B, n_split, H, 1), "float32")):
        # by (head) is the kernel's FIRST axis and therefore the
        # innermost grid dim: the Op/Mp/Lp output blocks are indexed by
        # (bz, bs) only, so their widened head-axis revisits must be
        # consecutive grid steps for Pallas's output-revisit semantics
        with T.Kernel(H, n_split, B) as (by, bs, bz):
            Q_s = T.alloc_shared((1, D), dtype)
            K_s = T.alloc_shared((block_N, D), dtype)
            V_s = T.alloc_shared((block_N, D), dtype)
            S_f = T.alloc_fragment((1, block_N), "float32")
            P_f = T.alloc_fragment((1, block_N), dtype)
            acc = T.alloc_fragment((1, D), "float32")
            m_prev = T.alloc_fragment((1,), "float32")
            m_new = T.alloc_fragment((1,), "float32")
            m_cur = T.alloc_fragment((1,), "float32")
            l = T.alloc_fragment((1,), "float32")
            l_cur = T.alloc_fragment((1,), "float32")

            T.copy(Q[bz, by, 0, 0], Q_s)
            T.fill(acc, 0)
            T.fill(l, 0)
            T.fill(m_prev, -T.infinity("float32"))

            for kb in T.Pipelined(T.ceildiv(chunk, block_N),
                                  num_stages=num_stages):
                T.copy(K[bz, by, bs * chunk + kb * block_N, 0], K_s)
                T.copy(V[bz, by, bs * chunk + kb * block_N, 0], V_s)
                T.gemm(Q_s, K_s, S_f, transpose_B=True, clear_accum=True)
                for i, j in T.Parallel(1, block_N):
                    S_f[i, j] = S_f[i, j] * scale
                T.reduce_max(S_f, m_cur, dim=1)
                for i in T.Parallel(1):
                    m_new[i] = T.max(m_prev[i], m_cur[i])
                for i, j in T.Parallel(1, block_N):
                    S_f[i, j] = T.exp2(S_f[i, j] - m_new[i])
                T.reduce_sum(S_f, l_cur, dim=1)
                for i in T.Parallel(1):
                    l[i] = l[i] * T.exp2(m_prev[i] - m_new[i]) + l_cur[i]
                for i, j in T.Parallel(1, D):
                    acc[i, j] = acc[i, j] * T.exp2(m_prev[i] - m_new[i])
                T.copy(S_f, P_f)
                T.gemm(P_f, V_s, acc)
                for i in T.Parallel(1):
                    m_prev[i] = m_new[i]

            T.copy(acc, Op[bz, bs, by, 0])
            T.copy(m_prev, Mp[bz, bs, by, 0])
            T.copy(l, Lp[bz, bs, by, 0])

    return _tl_compile(dec)


def flash_decode(q, k, v, sm_scale=None, n_split=None, block_N=128):
    """q (B, H, 1, D); k/v (B, H, S, D) -> (B, H, 1, D)."""
    import jax.numpy as jnp

    B, H, _, D = q.shape
    S = k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    if n_split is None:
        n_split = max(1, min(8, S // max(block_N, 1)))
    while S % n_split or (S // n_split) % min(block_N, S // n_split):
        n_split -= 1
    block_N = min(block_N, S // n_split)

    kern = decode_kernel(B, H, S, D, n_split, block_N, float(sm_scale),
                         str(q.dtype))
    op, mp, lp = kern(q, k, v)
    return _combine_splits(q, op, mp, lp)


@functools.lru_cache(maxsize=None)
def paged_decode_kernel(B, H, PP, PS, D, n_split, rows, sm_scale, dtype):
    """In-kernel page walking: KP/VP are an H-MAJOR page pool
    (H, n_pages*page_size, D); each split's programs DMA their pages
    directly at table-driven offsets (the same data-dependent gather as
    ops/nsa.py), so no XLA-level page materialization pass touches HBM.
    Emits the split partials the shared combine epilogue merges."""
    pps = PP // n_split        # pages per split
    scale = sm_scale * _LOG2E

    @T.prim_func
    def pdec(Q: T.Tensor((B, H, 1, D), dtype),
             KP: T.Tensor((H, rows, D), dtype),
             VP: T.Tensor((H, rows, D), dtype),
             Tab: T.Tensor((B, PP), "int32"),
             Op: T.Tensor((B, n_split, H, D), "float32"),
             Mp: T.Tensor((B, n_split, H, 1), "float32"),
             Lp: T.Tensor((B, n_split, H, 1), "float32")):
        # head axis innermost (cf. decode_kernel's layout note)
        with T.Kernel(H, n_split, B) as (by, bs, bz):
            Q_s = T.alloc_shared((1, D), dtype)
            K_s = T.alloc_shared((PS, D), dtype)
            V_s = T.alloc_shared((PS, D), dtype)
            tab = T.alloc_shared((PP,), "int32")
            S_f = T.alloc_fragment((1, PS), "float32")
            P_f = T.alloc_fragment((1, PS), dtype)
            acc = T.alloc_fragment((1, D), "float32")
            m_prev = T.alloc_fragment((1,), "float32")
            m_new = T.alloc_fragment((1,), "float32")
            m_cur = T.alloc_fragment((1,), "float32")
            l = T.alloc_fragment((1,), "float32")
            l_cur = T.alloc_fragment((1,), "float32")

            T.copy(Q[bz, by, 0, 0], Q_s)
            T.copy(Tab[bz, 0], tab)
            T.fill(acc, 0)
            T.fill(l, 0)
            T.fill(m_prev, -T.infinity("float32"))

            for p in T.serial(pps):
                off = tab[bs * pps + p] * PS
                T.copy(KP[by, off, 0], K_s)
                T.copy(VP[by, off, 0], V_s)
                T.gemm(Q_s, K_s, S_f, transpose_B=True, clear_accum=True)
                for i, j in T.Parallel(1, PS):
                    S_f[i, j] = S_f[i, j] * scale
                T.reduce_max(S_f, m_cur, dim=1)
                for i in T.Parallel(1):
                    m_new[i] = T.max(m_prev[i], m_cur[i])
                for i, j in T.Parallel(1, PS):
                    S_f[i, j] = T.exp2(S_f[i, j] - m_new[i])
                T.reduce_sum(S_f, l_cur, dim=1)
                for i in T.Parallel(1):
                    l[i] = l[i] * T.exp2(m_prev[i] - m_new[i]) + l_cur[i]
                for i, j in T.Parallel(1, D):
                    acc[i, j] = acc[i, j] * T.exp2(m_prev[i] - m_new[i])
                T.copy(S_f, P_f)
                T.gemm(P_f, V_s, acc)
                for i in T.Parallel(1):
                    m_prev[i] = m_new[i]

            T.copy(acc, Op[bz, bs, by, 0])
            T.copy(m_prev, Mp[bz, bs, by, 0])
            T.copy(l, Lp[bz, bs, by, 0])

    return _tl_compile(pdec)


def _combine_splits(q, op, mp, lp):
    """Merge per-split (o, m, l) partials in the exp2 domain (shared by
    flash_decode and the paged walk)."""
    import jax.numpy as jnp
    mp = mp[..., 0]                                         # (B,ns,H)
    lp = lp[..., 0]
    m_max = jnp.max(mp, axis=1, keepdims=True)              # (B,1,H)
    alpha = jnp.exp2(mp - m_max)                            # (B,ns,H)
    l_tot = jnp.sum(lp * alpha, axis=1)[..., None]          # (B,H,1)
    o = jnp.sum(op * alpha[..., None], axis=1)              # (B,H,D)
    return (o / l_tot)[:, :, None, :].astype(q.dtype)


def pages_to_hmajor(pages):
    """(n_pages, page_size, H, D) -> the H-major pool layout
    (H, n_pages*page_size, D) that in-kernel page walking wants. A
    serving system maintains the pool in this layout persistently; this
    one-time transform exists for interop and tests."""
    import jax.numpy as jnp
    n_pages, ps, H, D = pages.shape
    return jnp.transpose(pages, (2, 0, 1, 3)).reshape(H, n_pages * ps, D)


def flash_decode_paged(q, kv_pages, v_pages, page_table, sm_scale=None,
                       block_N=128, n_split=None):
    """Paged KV decode, GATHER strategy: pages (n_pages, page_size, H,
    D) + page_table (B, pages_per_seq) gathered to contiguous KV at the
    XLA level, then the pipelined split-KV kernel (block_N tiling
    honored). The alternative is `flash_decode_paged_pool`, which walks
    an H-major pool in-kernel with no gather pass — the bench measures
    both and keeps the faster on the target chip."""
    import jax.numpy as jnp

    B = page_table.shape[0]
    n_pages, page_size, H, D = kv_pages.shape
    k = jnp.take(kv_pages, page_table, axis=0)   # (B, pp, ps, H, D)
    v = jnp.take(v_pages, page_table, axis=0)
    S = page_table.shape[1] * page_size
    k = k.reshape(B, S, H, D).transpose(0, 2, 1, 3)
    v = v.reshape(B, S, H, D).transpose(0, 2, 1, 3)
    return flash_decode(q, k, v, sm_scale=sm_scale, block_N=block_N,
                        n_split=n_split)


def flash_decode_paged_pool(q, kp, vp, page_table, page_size,
                            sm_scale=None, n_split=None):
    """In-kernel page walk over an H-major pool (H, rows, D)."""
    B, H, _, D = q.shape
    PP = page_table.shape[1]
    rows = kp.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    if n_split is None:
        n_split = max(1, min(8, PP))
    while PP % n_split:
        n_split -= 1
    import jax.numpy as jnp
    kern = paged_decode_kernel(B, H, PP, int(page_size), D, n_split,
                               rows, float(sm_scale), str(q.dtype))
    op, mp, lp = kern(q, kp, vp, jnp.asarray(page_table, jnp.int32))
    return _combine_splits(q, op, mp, lp)
