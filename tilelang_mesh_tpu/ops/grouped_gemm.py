"""Grouped (batched-expert) GEMM: out[e] = X[e] @ W[e].

Behavioral equivalent of /root/reference/examples/grouped_gemm/ and the
compute core of fusedmoe. TPU design: the expert index is an extra parallel
Pallas grid dimension — every expert's tiles ride the same pipelined K loop,
so Mosaic interleaves DMA across experts instead of launching per-expert
kernels.
"""

import functools

import tilelang_mesh_tpu.language as T
from ..jit import compile as _tl_compile


@functools.lru_cache(maxsize=None)
def grouped_gemm_kernel(E, M, N, K, block_M=128, block_N=128, block_K=128,
                        in_dtype="bfloat16", accum_dtype="float32",
                        out_dtype=None, num_stages=2):
    out_dtype = out_dtype or in_dtype

    @T.prim_func
    def ggemm(X: T.Tensor((E, M, K), in_dtype),
              W: T.Tensor((E, K, N), in_dtype),
              O: T.Tensor((E, M, N), out_dtype)):
        with T.Kernel(T.ceildiv(N, block_N), T.ceildiv(M, block_M), E) \
                as (bx, by, be):
            X_s = T.alloc_shared((block_M, block_K), in_dtype)
            W_s = T.alloc_shared((block_K, block_N), in_dtype)
            O_l = T.alloc_fragment((block_M, block_N), accum_dtype)
            T.clear(O_l)
            for ko in T.Pipelined(T.ceildiv(K, block_K),
                                  num_stages=num_stages):
                T.copy(X[be, by * block_M, ko * block_K], X_s)
                T.copy(W[be, ko * block_K, bx * block_N], W_s)
                T.gemm(X_s, W_s, O_l)
            T.copy(O_l, O[be, by * block_M, bx * block_N])

    return _tl_compile(ggemm)


def grouped_matmul(x, w, block_M=128, block_N=128, block_K=128,
                   num_stages=2):
    """x (E, M, K) @ w (E, K, N) -> (E, M, N)."""
    E, M, K = x.shape
    N = w.shape[-1]
    k = grouped_gemm_kernel(E, M, N, K, min(block_M, M), min(block_N, N),
                            min(block_K, K), in_dtype=str(x.dtype),
                            num_stages=num_stages)
    return k(x, w)


# ---------------------------------------------------------------------------
# Varlen (ragged) grouped GEMM — MoE token-sorted layout
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def varlen_grouped_gemm_kernel(rows_pad, TB, E, K, N, block_M, block_N,
                               block_K, in_dtype, trans_b=False):
    """Ragged grouped GEMM (reference examples/grouped_gemm/
    example_grouped_gemm_fwd.py): A holds all groups' rows concatenated;
    each m-block's (expert, row-start) comes from host-precomputed int32
    metadata (the group sizes are static, so the search the reference does
    in-kernel folds to a table lookup). The output is written to a
    block-padded layout so every store is a full BlockSpec tile; the host
    wrapper drops pad rows.
    """
    b_shape = (E, N, K) if trans_b else (E, K, N)

    @T.prim_func
    def vggemm(A: T.Tensor((rows_pad, K), in_dtype),  # padded rows
               B: T.Tensor(b_shape, in_dtype),
               BlkExp: T.Tensor((TB,), "int32"),
               BlkRow: T.Tensor((TB,), "int32"),
               C: T.Tensor((TB * block_M, N), "float32")):
        with T.Kernel(TB, T.ceildiv(N, block_N)) as (bx, by):
            A_s = T.alloc_shared((block_M, block_K), in_dtype)
            B_s = T.alloc_shared((block_N, block_K) if trans_b else
                                 (block_K, block_N), in_dtype)
            acc = T.alloc_fragment((block_M, block_N), "float32")
            T.clear(acc)
            # the per-block metadata is read straight out of the SMEM-
            # resident tables (planner smem promotion): staging it through
            # an alloc_var would make the tables region-used and force an
            # illegal (1,)-block VMEM residency on real TPUs
            for ko in T.Pipelined(T.ceildiv(K, block_K), num_stages=2):
                T.copy(A[BlkRow[bx], ko * block_K], A_s)
                if trans_b:
                    T.copy(B[BlkExp[bx], by * block_N, ko * block_K], B_s)
                    T.gemm(A_s, B_s, acc, transpose_B=True)
                else:
                    T.copy(B[BlkExp[bx], ko * block_K, by * block_N], B_s)
                    T.gemm(A_s, B_s, acc)
            T.copy(acc, C[bx * block_M, by * block_N])

    return _tl_compile(vggemm)


def _varlen_meta(sizes, block_M):
    """block -> (expert, row_start) tables + padded gather indices."""
    import numpy as np
    offs, row_of_block, exp_of_block, out_rows = [0], [], [], []
    for s in sizes:
        offs.append(offs[-1] + int(s))
    pad_base = 0
    for e, s in enumerate(sizes):
        nb = -(-int(s) // block_M) if s else 0
        for b in range(nb):
            exp_of_block.append(e)
            row_of_block.append(offs[e] + b * block_M)
        out_rows.extend(range(pad_base, pad_base + int(s)))
        pad_base += nb * block_M
    return (np.asarray(exp_of_block, np.int32),
            np.asarray(row_of_block, np.int32),
            np.asarray(out_rows, np.int64))


def varlen_grouped_matmul(a, b, sizes, block_M=128, block_N=128,
                          block_K=128, trans_b=False):
    """a (sum(sizes), K) x b (E, K, N) -> (sum(sizes), N), group g of rows
    multiplying b[g]. `sizes` must be a static python sequence."""
    import jax.numpy as jnp
    import numpy as np
    sizes = tuple(int(s) for s in sizes)
    E = b.shape[0]
    K = a.shape[1]
    N = b.shape[1] if trans_b else b.shape[2]
    if len(sizes) != E:
        raise ValueError(f"len(sizes) ({len(sizes)}) != groups in b ({E})")
    if sum(sizes) != a.shape[0]:
        raise ValueError(f"sum(sizes) ({sum(sizes)}) != rows of a "
                         f"({a.shape[0]})")
    block_K = min(block_K, K)
    block_N = min(block_N, N)
    exp_blk, row_blk, out_rows = _varlen_meta(sizes, block_M)
    TB = len(exp_blk)
    # pad A so the last block of each group can read block_M full rows
    a_pad = jnp.concatenate(
        [a, jnp.zeros((block_M, K), a.dtype)], axis=0)
    kern = varlen_grouped_gemm_kernel(a_pad.shape[0], TB, E, K, N,
                                      block_M, block_N,
                                      block_K, str(a.dtype), trans_b)
    c_pad = kern(a_pad, b, exp_blk, row_blk)
    return c_pad[jnp.asarray(out_rows)]


def varlen_grouped_matmul_reference(a, b, sizes, trans_b=False):
    import jax.numpy as jnp
    import jax
    out, off = [], 0
    for e, s in enumerate(sizes):
        w = b[e].T if trans_b else b[e]
        # highest precision: on TPU the default f32 dot is a single bf16
        # MXU pass, which would make this "reference" less exact than the
        # tile kernel it validates
        out.append(jnp.matmul(a[off:off + s].astype(jnp.float32),
                              w.astype(jnp.float32),
                              precision=jax.lax.Precision.HIGHEST))
        off += s
    return jnp.concatenate(out, axis=0)
