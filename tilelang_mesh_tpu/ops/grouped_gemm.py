"""Grouped (batched-expert) GEMM: out[e] = X[e] @ W[e].

Behavioral equivalent of /root/reference/examples/grouped_gemm/ and the
compute core of fusedmoe. TPU design: the expert index is an extra parallel
Pallas grid dimension — every expert's tiles ride the same pipelined K loop,
so Mosaic interleaves DMA across experts instead of launching per-expert
kernels.
"""

import functools

import tilelang_mesh_tpu.language as T
from ..jit import compile as _tl_compile


@functools.lru_cache(maxsize=None)
def grouped_gemm_kernel(E, M, N, K, block_M=128, block_N=128, block_K=128,
                        in_dtype="bfloat16", accum_dtype="float32",
                        out_dtype=None, num_stages=2):
    out_dtype = out_dtype or in_dtype

    @T.prim_func
    def ggemm(X: T.Tensor((E, M, K), in_dtype),
              W: T.Tensor((E, K, N), in_dtype),
              O: T.Tensor((E, M, N), out_dtype)):
        with T.Kernel(T.ceildiv(N, block_N), T.ceildiv(M, block_M), E) \
                as (bx, by, be):
            X_s = T.alloc_shared((block_M, block_K), in_dtype)
            W_s = T.alloc_shared((block_K, block_N), in_dtype)
            O_l = T.alloc_fragment((block_M, block_N), accum_dtype)
            T.clear(O_l)
            for ko in T.Pipelined(T.ceildiv(K, block_K),
                                  num_stages=num_stages):
                T.copy(X[be, by * block_M, ko * block_K], X_s)
                T.copy(W[be, ko * block_K, bx * block_N], W_s)
                T.gemm(X_s, W_s, O_l)
            T.copy(O_l, O[be, by * block_M, bx * block_N])

    return _tl_compile(ggemm)


def grouped_matmul(x, w, block_M=128, block_N=128, block_K=128):
    """x (E, M, K) @ w (E, K, N) -> (E, M, N)."""
    E, M, K = x.shape
    N = w.shape[-1]
    k = grouped_gemm_kernel(E, M, N, K, min(block_M, M), min(block_N, N),
                            min(block_K, K), in_dtype=str(x.dtype))
    return k(x, w)
