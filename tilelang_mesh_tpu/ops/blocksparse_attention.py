"""Block-sparse attention: a per-(query-block, key-block) mask skips whole
tiles (reference examples/blocksparse_attention).

The block mask rides a (1,1) BlockSpec indexed by the query-block and
KV-block grid axes; a masked tile's entire body is predicated out, so
skipped blocks cost neither MXU flops nor VPU work (their tile fetches are
still scheduled by the pipeline — acceptable on TPU where the fetch
overlaps compute).
"""

import functools
import math

import tilelang_mesh_tpu.language as T
from ..jit import compile as _tl_compile
from ._online_softmax import (alloc_softmax_state, init_softmax_state,
                              online_softmax_update)


@functools.lru_cache(maxsize=None)
def blocksparse_mha_kernel(B, H, Sq, Sk, D, block_M, block_N, sm_scale,
                           dtype, num_stages=2, causal=False):
    scale = sm_scale * 1.44269504

    @T.prim_func
    def bs_mha(Q: T.Tensor((B, H, Sq, D), dtype),
               K: T.Tensor((B, H, Sk, D), dtype),
               V: T.Tensor((B, H, Sk, D), dtype),
               BlockMask: T.Tensor((B, H, Sq // block_M, Sk // block_N),
                                   "int32"),
               O: T.Tensor((B, H, Sq, D), dtype)):
        with T.Kernel(T.ceildiv(Sq, block_M), H, B) as (bx, by, bz):
            Q_s = T.alloc_shared((block_M, D), dtype)
            K_s = T.alloc_shared((block_N, D), dtype)
            V_s = T.alloc_shared((block_N, D), dtype)
            st = alloc_softmax_state(block_M, block_N, D, dtype)
            S = st["S"]

            T.copy(Q[bz, by, bx * block_M, 0], Q_s)
            init_softmax_state(st)

            for kb in T.Pipelined(T.ceildiv(Sk, block_N),
                                  num_stages=num_stages):
                live = BlockMask[bz, by, bx, kb] != 0
                if causal:
                    live = live & (kb * block_N <=
                                   bx * block_M + (block_M - 1))
                with T.If(live):
                    T.copy(K[bz, by, kb * block_N, 0], K_s)
                    T.copy(V[bz, by, kb * block_N, 0], V_s)
                    T.gemm(Q_s, K_s, S, transpose_B=True, clear_accum=True)
                    if causal:
                        for i, j in T.Parallel(block_M, block_N):
                            S[i, j] = T.if_then_else(
                                bx * block_M + i >= kb * block_N + j,
                                S[i, j] * scale, -T.infinity("float32"))
                    else:
                        for i, j in T.Parallel(block_M, block_N):
                            S[i, j] = S[i, j] * scale
                    online_softmax_update(st, V_s, block_M, block_N, D)

            # rows whose every block is masked produce l == 0 -> emit zeros
            acc, l = st["acc"], st["l"]
            for i, j in T.Parallel(block_M, D):
                acc[i, j] = T.if_then_else(l[i] > 0.0, acc[i, j] / l[i], 0.0)
            T.copy(acc, O[bz, by, bx * block_M, 0])

    return _tl_compile(bs_mha)


def blocksparse_attention(q, k, v, block_mask, sm_scale=None, block_M=128,
                          block_N=128, causal=False):
    """block_mask (B, H, Sq//block_M, Sk//block_N) nonzero = attend;
    causal=True additionally applies the elementwise causal mask (the
    seer-attention configuration)."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    block_M = min(block_M, Sq)
    block_N = min(block_N, Sk)
    if Sq % block_M or Sk % block_N:
        raise ValueError(
            f"blocksparse_attention needs Sq % block_M == 0 and "
            f"Sk % block_N == 0, got Sq={Sq}, Sk={Sk}, block_M={block_M}, "
            f"block_N={block_N}")
    expect = (B, H, Sq // block_M, Sk // block_N)
    if tuple(block_mask.shape) != expect:
        raise ValueError(f"block_mask shape {tuple(block_mask.shape)} does "
                         f"not match grid {expect}")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    kern = blocksparse_mha_kernel(B, H, Sq, Sk, D, block_M, block_N,
                                  float(sm_scale), str(q.dtype),
                                  causal=bool(causal))
    return kern(q, k, v, block_mask)


def blocksparse_reference(q, k, v, block_mask, block_M, block_N,
                          sm_scale=None, causal=False):
    import jax.numpy as jnp
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    dense = jnp.repeat(jnp.repeat(block_mask != 0, block_M, 2), block_N, 3)
    if causal:
        dense = dense & jnp.tril(jnp.ones((Sq, Sk), bool))
    s = jnp.where(dense, s, -jnp.inf)
    m = jnp.max(s, -1, keepdims=True)
    p = jnp.where(jnp.isfinite(m), jnp.exp(s - m), 0.0)
    denom = p.sum(-1, keepdims=True)
    p = jnp.where(denom > 0, p / denom, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)
