"""Attention with per-head sink logits (gpt-oss / StreamingLLM style).

Behavioral equivalent of the reference's examples/attention_sink
(example_mha_sink_fwd_bhsd.py, example_gqa_sink_fwd_bhsd_wgmma_pipelined.py):
standard blockwise online-softmax attention where each head owns a learnable
"sink" logit that joins the softmax denominator without contributing a
value — after the KV loop the running sum picks up exp(sink - m).

TPU design notes: identical pipelined KV loop as ops/flash_attention.py
(MXU GEMMs, VPU stat updates, Mosaic double-buffered K/V tiles); the sink
contribution is one extra VPU vector op after the loop. Optional sliding
window masks at block granularity so fully-outside KV tiles are skipped via
the same predicated-execution path causal masking uses.
"""

import functools
import math
from typing import Optional

import tilelang_mesh_tpu.language as T
from ..jit import compile as _tl_compile
from ._online_softmax import (alloc_softmax_state, init_softmax_state,
                              online_softmax_update)
from .flash_attention import _always

_LOG2E = 1.44269504


@functools.lru_cache(maxsize=None)
def sink_fwd_kernel(B, Hq, Hkv, Sq, Sk, D, block_M, block_N, causal,
                    window, sm_scale, dtype, num_stages=2):
    """window <= 0 means no sliding window. Sinks are float32 (Hq,)."""
    assert Hq % Hkv == 0
    group = Hq // Hkv
    scale = sm_scale * _LOG2E

    def _block_live(kb, bx):
        """Trace-time predicate: this KV block intersects some query row's
        visible range."""
        conds = []
        if causal:
            conds.append(kb * block_N <= bx * block_M + (block_M - 1))
        if window > 0:
            # newest visible key for the oldest query row in the tile
            conds.append(kb * block_N + (block_N - 1) >=
                         bx * block_M - (window - 1))
        if not conds:
            return None
        c = conds[0]
        for extra in conds[1:]:
            c = c & extra
        return c

    @T.prim_func
    def sink_fwd(Q: T.Tensor((B, Hq, Sq, D), dtype),
                 K: T.Tensor((B, Hkv, Sk, D), dtype),
                 V: T.Tensor((B, Hkv, Sk, D), dtype),
                 Sinks: T.Tensor((Hq,), "float32"),
                 O: T.Tensor((B, Hq, Sq, D), dtype)):
        with T.Kernel(T.ceildiv(Sq, block_M), Hq, B) as (bx, by, bz):
            Q_s = T.alloc_shared((block_M, D), dtype)
            K_s = T.alloc_shared((block_N, D), dtype)
            V_s = T.alloc_shared((block_N, D), dtype)
            sink = T.alloc_shared((1,), "float32")
            st = alloc_softmax_state(block_M, block_N, D, dtype)
            S = st["S"]

            T.copy(Q[bz, by, bx * block_M, 0], Q_s)
            T.copy(Sinks[by], sink)
            init_softmax_state(st)

            for kb in T.Pipelined(T.ceildiv(Sk, block_N),
                                  num_stages=num_stages):
                live = _block_live(kb, bx)
                with T.If(live) if live is not None else _always():
                    T.copy(K[bz, by // group, kb * block_N, 0], K_s)
                    T.copy(V[bz, by // group, kb * block_N, 0], V_s)
                    T.gemm(Q_s, K_s, S, transpose_B=True, clear_accum=True)
                    for i, j in T.Parallel(block_M, block_N):
                        qi = bx * block_M + i
                        kj = kb * block_N + j
                        vis = (qi >= kj) if causal else (kj < Sk)
                        if window > 0:
                            vis = vis & (kj > qi - window)
                        S[i, j] = T.if_then_else(
                            vis, S[i, j] * scale, -T.infinity("float32"))
                    online_softmax_update(st, V_s, block_M, block_N, D)

            # the sink joins the denominator as one extra (value-less) logit
            # (cf. reference example_mha_sink_fwd_bhsd.py:177)
            acc, l, m_prev = st["acc"], st["l"], st["m_prev"]
            for i in T.Parallel(block_M):
                l[i] = l[i] + T.exp2(sink[0] * _LOG2E - m_prev[i])
            for i, j in T.Parallel(block_M, D):
                # clamped divide (the dsa/nsa idiom) — tl-num TL009
                acc[i, j] = acc[i, j] / T.max(l[i], 1e-30)
            T.copy(acc, O[bz, by, bx * block_M, 0])

    return _tl_compile(sink_fwd)


def attention_sink(q, k, v, sinks, causal: bool = True,
                   window_size: Optional[int] = None,
                   sm_scale: Optional[float] = None,
                   block_M: int = 128, block_N: int = 128,
                   num_stages: int = 2, backward: Optional[str] = None):
    """Sink attention: q (B, Hq, Sq, D); k/v (B, Hkv, Sk, D), Hkv | Hq;
    sinks (Hq,) float32 per-head sink logits. window_size=None disables the
    sliding window (full causal/dense attention + sink).

    backward="kernel" (reference example_mha_sink_bwd_bhsd.py /
    example_gqa_sink_bwd_bhsd.py behavior; requires window_size=None):
    differentiable in q, k, v AND sinks. The sink only shifts the
    softmax normalizer, so the sink-less GQA partial's (acc, m, l) plus
    one XLA fold — l' = l + exp2(sink·log2e − m) — yields exactly the
    lse the standard dKdV/dQ recompute kernels (ops/gqa_bwd.py) need,
    and d(sink) is the closed form −Σ p_sink·delta."""
    B, Hq, Sq, D = q.shape
    Hkv, Sk = k.shape[1], k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    window = 0 if window_size is None else int(window_size)
    block_M, block_N = min(block_M, Sq), min(block_N, Sk)
    if Sq % block_M or Sk % block_N:
        raise ValueError(
            f"attention_sink needs Sq % block_M == 0 and Sk % block_N == 0 "
            f"(got Sq={Sq}, Sk={Sk}, block_M={block_M}, block_N={block_N})")
    import jax.numpy as jnp
    if backward is None:
        kern = sink_fwd_kernel(B, Hq, Hkv, Sq, Sk, D, block_M,
                               block_N, bool(causal), window,
                               float(sm_scale), str(q.dtype), num_stages)
        return kern(q, k, v, jnp.asarray(sinks, jnp.float32))

    if backward != "kernel":
        raise ValueError(f"backward must be None or 'kernel', "
                         f"got {backward!r}")
    if window:
        raise ValueError(
            "attention_sink backward requires window_size=None (the "
            "dKdV/dQ recompute kernels carry no window mask)")
    import jax
    from .gqa import gqa_fwd_partial_kernel

    def _fwd_stats(q, k, v, sinks):
        pk = gqa_fwd_partial_kernel(B, Hq, Hkv, Sq, Sk, D, block_M,
                                    block_N, bool(causal),
                                    float(sm_scale), str(q.dtype),
                                    num_stages)
        acc, m, l = pk(q, k, v)                         # sink-less stats
        sk_col = (jnp.asarray(sinks, jnp.float32)
                  .reshape(1, Hq, 1) * _LOG2E)
        l_sink = l + jnp.exp2(sk_col - m)               # sink joins denom
        o = (acc / l_sink[..., None]).astype(q.dtype)
        lse2 = m + jnp.log2(l_sink)
        return o, lse2, sk_col

    @jax.custom_vjp
    def fa(q, k, v, sinks):
        # non-differentiated primal: the fused one-pass kernel (the
        # partial + XLA fold runs only under AD, in fwd below)
        kern = sink_fwd_kernel(B, Hq, Hkv, Sq, Sk, D, block_M, block_N,
                               bool(causal), 0, float(sm_scale),
                               str(q.dtype), num_stages)
        return kern(q, k, v, sinks)

    def fwd(q, k, v, sinks):
        o, lse2, sk_col = _fwd_stats(q, k, v, sinks)
        return o, (q, k, v, o, lse2, sk_col)

    def bwd(res, g):
        from .gqa_bwd import gqa_attention_bwd
        q, k, v, o, lse2, sk_col = res
        # dsink: sink has no value column, so d(o)/d(sink) = -p_sink o
        # per row => dsink_h = -sum_{b,t} p_sink * (g . o). delta is
        # computed once here and shared with the recompute kernels.
        delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                        -1)                             # (B, Hq, Sq)
        dq, dk, dv = gqa_attention_bwd(q, k, v, o, lse2, g, causal,
                                       sm_scale, block_M, block_N,
                                       delta=delta)
        p_sink = jnp.exp2(sk_col - lse2)
        dsink = -jnp.sum(p_sink * delta, axis=(0, 2))   # (Hq,)
        return dq, dk, dv, dsink.astype(jnp.float32)

    fa.defvjp(fwd, bwd)
    return fa(q, k, v, jnp.asarray(sinks, jnp.float32))


def attention_sink_reference(q, k, v, sinks, causal=True, window_size=None,
                             sm_scale=None):
    """Dense reference (matches the reference's torch ref_program):
    softmax over [scores, sink] where the sink column carries no value."""
    import jax.numpy as jnp

    B, Hq, Sq, D = q.shape
    Hkv, Sk = k.shape[1], k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    group = Hq // Hkv
    kf = jnp.repeat(k.astype(jnp.float32), group, axis=1)
    vf = jnp.repeat(v.astype(jnp.float32), group, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32), kf) * sm_scale
    qi = jnp.arange(Sq)[:, None]
    kj = jnp.arange(Sk)[None, :]
    mask = jnp.ones((Sq, Sk), bool)
    if causal:
        mask = mask & (qi >= kj)
    if window_size is not None:
        mask = mask & (kj > qi - window_size)
    s = jnp.where(mask, s, -jnp.inf)
    sink = jnp.asarray(sinks, jnp.float32).reshape(1, Hq, 1, 1)
    m = jnp.maximum(s.max(-1, keepdims=True), sink)
    p = jnp.exp(s - m)
    denom = p.sum(-1, keepdims=True) + jnp.exp(sink - m)
    return jnp.einsum("bhqk,bhkd->bhqd", p / denom, vf).astype(q.dtype)
