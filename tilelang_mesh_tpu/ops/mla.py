"""DeepSeek MLA (multi-head latent attention) decode kernel
(BASELINE config #4).

Behavioral equivalent of /root/reference/examples/deepseek_mla/: queries are
absorbed into the latent space, so all heads attend over one shared latent
KV cache ``ckv (B, S, dc)`` plus a small rope channel ``kpe (B, S, dr)``.
TPU design: heads ride the *sublane* axis of one score tile (H, block_N) —
one MXU gemm per chunk for the latent part and one for rope — with split-KV
parallel reduction like flash decoding.
"""

import functools
import math

import tilelang_mesh_tpu.language as T
from ..jit import compile as _tl_compile

_LOG2E = 1.44269504


@functools.lru_cache(maxsize=None)
def mla_decode_kernel(B, H, S, dc, dr, n_split, block_N, sm_scale, dtype,
                      num_stages=2):
    chunk = S // n_split
    scale = sm_scale * _LOG2E

    @T.prim_func
    def mla(Qc: T.Tensor((B, H, dc), dtype),
            Qr: T.Tensor((B, H, dr), dtype),
            CKV: T.Tensor((B, S, dc), dtype),
            KPE: T.Tensor((B, S, dr), dtype),
            Op: T.Tensor((B, n_split, H, dc), "float32"),
            Mp: T.Tensor((B, n_split, H), "float32"),
            Lp: T.Tensor((B, n_split, H), "float32")):
        with T.Kernel(n_split, B) as (bs, bz):
            Qc_s = T.alloc_shared((H, dc), dtype)
            Qr_s = T.alloc_shared((H, dr), dtype)
            C_s = T.alloc_shared((block_N, dc), dtype)
            R_s = T.alloc_shared((block_N, dr), dtype)
            S_f = T.alloc_fragment((H, block_N), "float32")
            P_f = T.alloc_fragment((H, block_N), dtype)
            acc = T.alloc_fragment((H, dc), "float32")
            m_prev = T.alloc_fragment((H,), "float32")
            m_new = T.alloc_fragment((H,), "float32")
            m_cur = T.alloc_fragment((H,), "float32")
            l = T.alloc_fragment((H,), "float32")
            l_cur = T.alloc_fragment((H,), "float32")

            T.copy(Qc[bz, 0, 0], Qc_s)
            T.copy(Qr[bz, 0, 0], Qr_s)
            T.fill(acc, 0)
            T.fill(l, 0)
            T.fill(m_prev, -T.infinity("float32"))

            for kb in T.Pipelined(T.ceildiv(chunk, block_N),
                                  num_stages=num_stages):
                T.copy(CKV[bz, bs * chunk + kb * block_N, 0], C_s)
                T.copy(KPE[bz, bs * chunk + kb * block_N, 0], R_s)
                # scores: latent + rope parts, both on the MXU
                T.gemm(Qc_s, C_s, S_f, transpose_B=True, clear_accum=True)
                T.gemm(Qr_s, R_s, S_f, transpose_B=True)
                for i, j in T.Parallel(H, block_N):
                    S_f[i, j] = S_f[i, j] * scale
                T.reduce_max(S_f, m_cur, dim=1)
                for i in T.Parallel(H):
                    m_new[i] = T.max(m_prev[i], m_cur[i])
                for i, j in T.Parallel(H, block_N):
                    S_f[i, j] = T.exp2(S_f[i, j] - m_new[i])
                T.reduce_sum(S_f, l_cur, dim=1)
                for i in T.Parallel(H):
                    l[i] = l[i] * T.exp2(m_prev[i] - m_new[i]) + l_cur[i]
                for i, j in T.Parallel(H, dc):
                    acc[i, j] = acc[i, j] * T.exp2(m_prev[i] - m_new[i])
                T.copy(S_f, P_f)
                T.gemm(P_f, C_s, acc)
                for i in T.Parallel(H):
                    m_prev[i] = m_new[i]

            T.copy(acc, Op[bz, bs, 0, 0])
            T.copy(m_prev, Mp[bz, bs, 0])
            T.copy(l, Lp[bz, bs, 0])

    return _tl_compile(mla)


def mla_decode(q_latent, q_rope, ckv, kpe, sm_scale=None, n_split=None,
               block_N=128):
    """q_latent (B, H, dc); q_rope (B, H, dr); ckv (B, S, dc);
    kpe (B, S, dr) -> attention output in latent space (B, H, dc)."""
    import jax.numpy as jnp

    B, H, dc = q_latent.shape
    dr = q_rope.shape[-1]
    S = ckv.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(dc + dr)
    if n_split is None:
        n_split = max(1, min(8, S // max(block_N, 1)))
    while S % n_split or (S // n_split) % min(block_N, S // n_split):
        n_split -= 1
    block_N = min(block_N, S // n_split)

    kern = mla_decode_kernel(B, H, S, dc, dr, n_split, block_N,
                             float(sm_scale), str(q_latent.dtype))
    op, mp, lp = kern(q_latent, q_rope, ckv, kpe)
    m_max = jnp.max(mp, axis=1, keepdims=True)            # (B,1,H)
    alpha = jnp.exp2(mp - m_max)                          # (B,ns,H)
    l_tot = jnp.sum(lp * alpha, axis=1)                   # (B,H)
    o = jnp.sum(op * alpha[..., None], axis=1)            # (B,H,dc)
    return (o / l_tot[..., None]).astype(q_latent.dtype)


def mla_decode_reference(q_latent, q_rope, ckv, kpe, sm_scale=None):
    import jax
    import jax.numpy as jnp
    B, H, dc = q_latent.shape
    dr = q_rope.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(dc + dr)
    s = (jnp.einsum("bhc,bsc->bhs", q_latent.astype(jnp.float32),
                    ckv.astype(jnp.float32)) +
         jnp.einsum("bhr,bsr->bhs", q_rope.astype(jnp.float32),
                    kpe.astype(jnp.float32))) * sm_scale
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhs,bsc->bhc", p,
                      ckv.astype(jnp.float32)).astype(q_latent.dtype)
