"""DeepSeek V3.2 sparse attention (DSA): lightning indexer, top-k token
selector, sparse MLA forward, and the differentiable wrapper used for
sparse fine-tuning.

Behavioral mirror of the reference's examples/deepseek_v32
(fp8_lighting_indexer.py, topk_selector.py, sparse_mla_fwd.py) and
examples/dsa_sparse_finetune (dsa.py, sparse_mla_bwd.py):

  1. indexer:   logits[b,t,j] = sum_h w[b,t,h] * relu(qI[b,t,h,:]·kI[b,j,:])
  2. selector:  per (b, t) causal top-k token ids from the logits
  3. sparse MLA fwd: each query token attends only its top-k tokens of the
     shared latent KV (dim + tail rope dims); returns (O, LSE)
  4. sparse_mla: custom-vjp wrapper — forward runs the gather kernel, the
     backward recomputes through an XLA take_along_axis gather (the
     reference writes sparse_mla_bwd.py as a second gather kernel; on TPU
     the XLA gather path is the pragmatic bwd at finetune scale).

TPU design notes: the per-token KV gather is a serial in-kernel DMA loop at
data-dependent offsets (the NSA block-gather pattern at token granularity);
scores/softmax run in the exp2 domain on the MXU/VPU.
"""

import functools
import math

import tilelang_mesh_tpu.language as T
from ..jit import compile as _tl_compile
from ._online_softmax import (alloc_softmax_state, init_softmax_state,
                              online_softmax_update)

_LOG2E = 1.44269504


# ---------------------------------------------------------------------------
# 1. lightning indexer
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def lightning_indexer_kernel(B, S, Skv, HI, DI, block_T, q_offset, dtype):
    """Index logits with causal mask: (B, S, Skv) f32.

    QI (B, S, HI, DI), KI (B, Skv, DI), W (B, S, HI) f32. Query t sits at
    absolute position q_offset + t in the KV timeline (q_offset = Skv - S
    when the S queries are the tail of an Skv-long cache).
    Reference: deepseek_v32/fp8_lighting_indexer.py
    mqa_attn_return_logits_kernel (relu(q·k) head-reduced by weights).
    """
    @T.prim_func
    def indexer(QI: T.Tensor((B, S, HI, DI), dtype),
                KI: T.Tensor((B, Skv, DI), dtype),
                W: T.Tensor((B, S, HI), "float32"),
                L: T.Tensor((B, S, Skv), "float32")):
        with T.Kernel(T.ceildiv(S, block_T), B) as (bt, bz):
            k_s = T.alloc_shared((Skv, DI), dtype)
            q_s = T.alloc_shared((block_T, DI), dtype)
            w_s = T.alloc_shared((block_T, HI), "float32")
            s_f = T.alloc_fragment((block_T, Skv), "float32")
            out = T.alloc_fragment((block_T, Skv), "float32")
            T.copy(KI[bz, 0, 0], k_s)
            T.copy(W[bz, bt * block_T, 0], w_s)
            T.fill(out, 0)
            for h in range(HI):
                T.copy(QI[bz, bt * block_T:(bt + 1) * block_T, h, 0:DI],
                       q_s)
                T.gemm(q_s, k_s, s_f, transpose_B=True, clear_accum=True)
                for i, j in T.Parallel(block_T, Skv):
                    out[i, j] = out[i, j] + T.max(s_f[i, j], 0) * w_s[i, h]
            # causal mask: key j visible when j <= q_offset + t
            for i, j in T.Parallel(block_T, Skv):
                out[i, j] = T.if_then_else(
                    j <= q_offset + bt * block_T + i, out[i, j],
                    -T.infinity("float32"))
            T.copy(out, L[bz, bt * block_T, 0])

    return _tl_compile(indexer)


def lightning_indexer(q_index, k_index, weights, block_T=64,
                      q_offset=None):
    """q_index (B, S, HI, DI), k_index (B, Skv, DI), weights (B, S, HI).

    q_offset: absolute position of query 0 in the KV timeline; defaults to
    Skv - S (queries are the cache tail)."""
    B, S, HI, DI = q_index.shape
    Skv = k_index.shape[1]
    if q_offset is None:
        q_offset = Skv - S
    block_T = min(block_T, S)
    while S % block_T:
        block_T //= 2
    kern = lightning_indexer_kernel(B, S, Skv, HI, DI, block_T,
                                    int(q_offset), str(q_index.dtype))
    return kern(q_index, k_index, weights)


# ---------------------------------------------------------------------------
# 2. top-k token selector
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def topk_selector_kernel(B, S, Skv, topk, block_T):
    """Per-row top-k indices (iterative argmax-and-mask, reference
    deepseek_v32/topk_selector.py). Masked (-inf) entries select index -1
    when fewer than topk keys are visible."""
    @T.prim_func
    def select(L: T.Tensor((B, S, Skv), "float32"),
               I: T.Tensor((B, S, topk), "int32")):
        with T.Kernel(T.ceildiv(S, block_T), B) as (bt, bz):
            frag = T.alloc_fragment((block_T, Skv), "float32")
            mx = T.alloc_fragment((block_T,), "float32")
            emx = T.alloc_fragment((block_T, Skv), "int32")
            mi = T.alloc_fragment((block_T,), "int32")
            idx = T.alloc_fragment((block_T, topk), "int32")
            T.copy(L[bz, bt * block_T, 0], frag)
            for k in range(topk):
                T.reduce_max(frag, mx, dim=1, clear=True)
                for i, j in T.Parallel(block_T, Skv):
                    emx[i, j] = T.if_then_else(
                        (mx[i] == frag[i, j]) & (mx[i] > -1e30),
                        -j, -(Skv + 1))
                T.reduce_max(emx, mi, dim=1, clear=True)
                for i, j in T.Parallel(block_T, Skv):
                    frag[i, j] = T.if_then_else(
                        mi[i] == -j, -T.infinity("float32"), frag[i, j])
                for i in T.Parallel(block_T):
                    idx[i, k] = T.if_then_else(mi[i] == -(Skv + 1),
                                               -1, -mi[i])
            T.copy(idx, I[bz, bt * block_T, 0])

    return _tl_compile(select)


def topk_selector(logits, topk, block_T=64):
    B, S, Skv = logits.shape
    block_T = min(block_T, S)
    while S % block_T:
        block_T //= 2
    kern = topk_selector_kernel(B, S, Skv, topk, block_T)
    return kern(logits)


# ---------------------------------------------------------------------------
# 3. sparse MLA forward
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def sparse_mla_fwd_kernel(B, S, Skv, H, D, DT, topk, BI, q_offset,
                          sm_scale, dtype):
    """Per-token gathered MLA attention.

    Q (B, S, H, D+DT); KV (B, Skv, D+DT) shared latent (kv_group=1);
    Indices (B, S, topk) int32 (-1 = invalid); O (B, S, H, D);
    Lse (B, S, H) f32 (natural-log domain).
    Reference: deepseek_v32/sparse_mla_fwd.py.
    """
    scale = sm_scale * _LOG2E
    n_blk = topk // BI

    @T.prim_func
    def mla_fwd(Q: T.Tensor((B, S, H, D + DT), dtype),
                KV: T.Tensor((B, Skv, D + DT), dtype),
                Ind: T.Tensor((B, S, topk), "int32"),
                O: T.Tensor((B, S, H, D), dtype),
                Lse: T.Tensor((B, S, H), "float32")):
        with T.Kernel(S, B) as (t, bz):
            Q_s = T.alloc_shared((H, D + DT), dtype)
            KV_s = T.alloc_shared((BI, D + DT), dtype)
            Idx = T.alloc_shared((topk,), "int32")
            st = alloc_softmax_state(H, BI, D, dtype)
            S_f, acc, l = st["S"], st["acc"], st["l"]
            out = T.alloc_fragment((H, D), "float32")
            lse = T.alloc_fragment((H,), "float32")

            T.copy(Q[bz, t, 0, 0], Q_s)
            T.copy(Ind[bz, t, 0], Idx)
            init_softmax_state(st)
            for ib in T.serial(n_blk):
                # zero the tile: rows of invalid (-1) indices must hold 0s,
                # not scratch garbage — P@V multiplies them by 0 and
                # 0 * garbage-NaN would poison the accumulator
                T.fill(KV_s, 0)
                # token-granular gather: one DMA per selected KV row
                for r in T.serial(BI):
                    with T.If(Idx[ib * BI + r] >= 0):
                        T.copy(KV[bz, Idx[ib * BI + r], 0],
                               KV_s[r, 0:D + DT])
                T.gemm(Q_s, KV_s, S_f, transpose_B=True, clear_accum=True)
                for i, j in T.Parallel(H, BI):
                    S_f[i, j] = T.if_then_else(
                        (Idx[ib * BI + j] >= 0) &
                        (Idx[ib * BI + j] <= q_offset + t),
                        S_f[i, j] * scale, -T.infinity("float32"))
                online_softmax_update(st, KV_s[0:BI, 0:D], H, BI, D)
            for i, j in T.Parallel(H, D):
                out[i, j] = acc[i, j] / T.max(l[i], 1e-30)
            for i in T.Parallel(H):
                # back to natural log: lse = m + log2(l) all over log2e
                lse[i] = (st["m_prev"][i] + T.log2(T.max(l[i], 1e-30))) \
                    / _LOG2E
            T.copy(out, O[bz, t, 0, 0])
            T.copy(lse, Lse[bz, t, 0])

    return _tl_compile(mla_fwd)


def _tail_split(Dfull, tail_dim):
    if tail_dim is None:
        if Dfull % 128 == 0:
            raise ValueError(
                f"q feature dim {Dfull} is a multiple of 128: pass "
                "tail_dim explicitly (the default heuristic — tail 64 when "
                "D+tail is not 128-aligned — cannot infer the rope split)")
        tail_dim = 64
    if not 0 <= tail_dim < Dfull:
        raise ValueError(f"tail_dim {tail_dim} out of range for feature "
                         f"dim {Dfull}")
    return Dfull - tail_dim, tail_dim


def sparse_mla_fwd(q, kv, indices, sm_scale=None, block_I=64,
                   tail_dim=None, q_offset=None):
    """q (B, S, H, D+DT) with D = kv latent dim, DT = rope tail; kv
    (B, Skv, D+DT); indices (B, S, topk). q_offset: absolute position of
    query 0 in the KV timeline (default Skv - S). Returns
    (o (B,S,H,D), lse)."""
    B, S, H, Dfull = q.shape
    Skv = kv.shape[1]
    topk = indices.shape[-1]
    D, DT = _tail_split(Dfull, tail_dim)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(Dfull)
    if q_offset is None:
        q_offset = Skv - S
    BI = min(block_I, topk)
    if topk % BI:
        raise ValueError(f"topk ({topk}) must be a multiple of block_I "
                         f"({BI})")
    kern = sparse_mla_fwd_kernel(B, S, Skv, H, D, DT, topk, BI,
                                 int(q_offset), float(sm_scale),
                                 str(q.dtype))
    return kern(q, kv, indices)


def sparse_mla_reference(q, kv, indices, sm_scale=None, tail_dim=None,
                         q_offset=None):
    """Dense gather emulation (reference ref_sparse_mla_fwd_interface)."""
    import jax.numpy as jnp
    B, S, H, Dfull = q.shape
    D, DT = _tail_split(Dfull, tail_dim)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(Dfull)
    if q_offset is None:
        q_offset = kv.shape[1] - S
    topk = indices.shape[-1]
    safe = jnp.maximum(indices, 0)
    g = jnp.take_along_axis(kv[:, None, :, :],
                            safe[:, :, :, None].repeat(Dfull, -1), axis=2)
    # g: (B, S, topk, Dfull)
    scores = jnp.einsum("bshd,bskd->bshk", q.astype(jnp.float32),
                        g.astype(jnp.float32)) * sm_scale
    t_ids = jnp.arange(S)[None, :, None]
    valid = (indices >= 0) & (indices <= q_offset + t_ids)
    scores = jnp.where(valid[:, :, None, :], scores, -jnp.inf)
    m = scores.max(axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    l = p.sum(axis=-1, keepdims=True)
    o = jnp.einsum("bshk,bskd->bshd", p / jnp.maximum(l, 1e-30),
                   g[..., :D].astype(jnp.float32))
    lse = (m[..., 0] + jnp.log(jnp.maximum(l[..., 0], 1e-30)))
    return o.astype(q.dtype), lse


# ---------------------------------------------------------------------------
# 4. differentiable sparse MLA (dsa_sparse_finetune)
# ---------------------------------------------------------------------------

def make_sparse_mla(sm_scale=None, block_I=64, tail_dim=None):
    """Returns a differentiable sparse_mla(q, kv, indices) -> o.

    Forward runs the gather kernel; backward recomputes through the XLA
    gather (reference dsa_sparse_finetune/sparse_mla_bwd.py writes this as
    a second tile kernel; the XLA path is equivalent math at finetune
    scale and lets jax.grad flow into q and kv)."""
    import jax

    @jax.custom_vjp
    def sparse_mla(q, kv, indices):
        o, _ = sparse_mla_fwd(q, kv, indices, sm_scale=sm_scale,
                              block_I=block_I, tail_dim=tail_dim)
        return o

    def fwd(q, kv, indices):
        o, lse = sparse_mla_fwd(q, kv, indices, sm_scale=sm_scale,
                                block_I=block_I, tail_dim=tail_dim)
        return o, (q, kv, indices)

    def bwd(res, do):
        q, kv, indices = res

        def ref(qq, kk):
            o, _ = sparse_mla_reference(qq, kk, indices, sm_scale=sm_scale,
                                        tail_dim=tail_dim)
            return o

        _, vjp = jax.vjp(ref, q, kv)
        dq, dkv = vjp(do)
        return dq, dkv, None

    sparse_mla.defvjp(fwd, bwd)
    return sparse_mla
