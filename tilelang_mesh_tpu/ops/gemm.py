"""Ready-made GEMM ops built on the tile DSL.

The analog of the reference's benchmark/matmul kernels
(/root/reference/benchmark/matmul/benchmark_matmul.py) exposed as plain jax
callables with carver-driven tile selection.
"""


import functools
from typing import Optional

import tilelang_mesh_tpu.language as T
from ..jit import compile as _tl_compile
from ..carver import MatmulTemplate


@functools.lru_cache(maxsize=None)
def matmul_kernel(M, N, K, block_M=None, block_N=None, block_K=None,
                  in_dtype="bfloat16", out_dtype=None, accum_dtype="float32",
                  trans_A=False, trans_B=False, relu=False, num_stages=2):
    out_dtype = out_dtype or in_dtype
    if block_M is None:
        hints = MatmulTemplate(M, N, K, in_dtype, accum_dtype).hints(1)
        cfg = hints[0].config if hints else {"block_M": 128, "block_N": 128,
                                             "block_K": 128}
        block_M, block_N, block_K = (cfg["block_M"], cfg["block_N"],
                                     cfg["block_K"])
    a_shape = (K, M) if trans_A else (M, K)
    b_shape = (N, K) if trans_B else (K, N)
    a_tile = (block_K, block_M) if trans_A else (block_M, block_K)
    b_tile = (block_N, block_K) if trans_B else (block_K, block_N)

    @T.prim_func
    def gemm(A: T.Tensor(a_shape, in_dtype),
             B: T.Tensor(b_shape, in_dtype),
             C: T.Tensor((M, N), out_dtype)):
        with T.Kernel(T.ceildiv(N, block_N), T.ceildiv(M, block_M)) \
                as (bx, by):
            A_s = T.alloc_shared(a_tile, in_dtype)
            B_s = T.alloc_shared(b_tile, in_dtype)
            C_l = T.alloc_fragment((block_M, block_N), accum_dtype)
            T.clear(C_l)
            for ko in T.Pipelined(T.ceildiv(K, block_K),
                                  num_stages=num_stages):
                if trans_A:
                    T.copy(A[ko * block_K, by * block_M], A_s)
                else:
                    T.copy(A[by * block_M, ko * block_K], A_s)
                if trans_B:
                    T.copy(B[bx * block_N, ko * block_K], B_s)
                else:
                    T.copy(B[ko * block_K, bx * block_N], B_s)
                T.gemm(A_s, B_s, C_l, transpose_A=trans_A,
                       transpose_B=trans_B)
            if relu:
                for i, j in T.Parallel(block_M, block_N):
                    C_l[i, j] = T.max(C_l[i, j], 0)
            T.copy(C_l, C[by * block_M, bx * block_N])

    return _tl_compile(gemm)


def matmul(a, b, trans_A: bool = False, trans_B: bool = False,
           out_dtype: Optional[str] = None, relu: bool = False,
           block_M=None, block_N=None, block_K=None):
    """C = op(A) @ op(B) through the tile pipeline."""
    M = a.shape[1] if trans_A else a.shape[0]
    K = a.shape[0] if trans_A else a.shape[1]
    N = b.shape[0] if trans_B else b.shape[1]
    k = matmul_kernel(M, N, K, block_M, block_N, block_K,
                      in_dtype=str(a.dtype), out_dtype=out_dtype,
                      trans_A=trans_A, trans_B=trans_B, relu=relu)
    return k(a, b)
