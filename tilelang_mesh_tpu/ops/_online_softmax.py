"""Shared online-softmax building blocks for the attention kernel family.

Every blockwise-attention kernel (flash fwd, partial fwd, block-sparse, GQA,
flash-decode) performs the same per-KV-block update on its running
(max, sum, accumulator) statistics; this module is the single home for that
update so numerics fixes apply everywhere at once (cf. the reference's
shared softmax macros across examples/flash_attention/*).

All statistics live in the exp2 domain: callers pre-scale scores by
``sm_scale * log2(e)`` and use ``exp2`` throughout, which replaces every
transcendental with the VPU's native exp2.
"""

import tilelang_mesh_tpu.language as T


def alloc_softmax_state(block_M, block_N, D, p_dtype):
    """Allocate the standard statistic/scratch buffers: returns a dict with
    S (scores f32), P (probs, kernel dtype), acc (f32), and the five per-row
    stat vectors."""
    return dict(
        S=T.alloc_fragment((block_M, block_N), "float32"),
        P=T.alloc_fragment((block_M, block_N), p_dtype),
        acc=T.alloc_fragment((block_M, D), "float32"),
        m_prev=T.alloc_fragment((block_M,), "float32"),
        m_new=T.alloc_fragment((block_M,), "float32"),
        m_cur=T.alloc_fragment((block_M,), "float32"),
        l=T.alloc_fragment((block_M,), "float32"),
        l_cur=T.alloc_fragment((block_M,), "float32"),
    )


def init_softmax_state(st):
    T.fill(st["acc"], 0)
    T.fill(st["l"], 0)
    T.fill(st["m_prev"], -T.infinity("float32"))


def online_softmax_update(st, V_s, block_M, block_N, D):
    """One online-softmax step over the scores in st['S'] (already scaled to
    the exp2 domain and masked): rescale running stats, accumulate P @ V.

    Emits (at trace time) the canonical update:
        m_new = max(m_prev, rowmax(S)); S = exp2(S - m_new)
        l = l * exp2(m_prev - m_new) + rowsum(S)
        acc = acc * exp2(m_prev - m_new) + S @ V
    """
    S, P, acc = st["S"], st["P"], st["acc"]
    m_prev, m_new, m_cur = st["m_prev"], st["m_new"], st["m_cur"]
    l, l_cur = st["l"], st["l_cur"]
    T.reduce_max(S, m_cur, dim=1)
    for i in T.Parallel(block_M):
        # -1e30 floor keeps fully-masked rows finite (exp2(-inf - -inf)
        # would be NaN); a no-op whenever any key is visible
        m_new[i] = T.max(m_prev[i], T.max(m_cur[i], -1e30))
    for i, j in T.Parallel(block_M, block_N):
        # one pass: exp2 into the f32 stats buffer AND the gemm-dtype
        # P (fusing the cast saves a full re-read of S per KV block —
        # flash is VPU-bound, cf. benchmark/RESULTS.md bound analysis)
        S[i, j] = T.exp2(S[i, j] - m_new[i])
        P[i, j] = S[i, j]
    T.reduce_sum(S, l_cur, dim=1)
    for i in T.Parallel(block_M):
        l[i] = l[i] * T.exp2(m_prev[i] - m_new[i]) + l_cur[i]
    for i, j in T.Parallel(block_M, D):
        acc[i, j] = acc[i, j] * T.exp2(m_prev[i] - m_new[i])
    T.gemm(P, V_s, acc)
    for i in T.Parallel(block_M):
        m_prev[i] = m_new[i]
