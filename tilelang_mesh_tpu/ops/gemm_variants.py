"""GEMM scheduling variants: split-K, stream-K, GEMV, block-sparse GEMM.

Behavioral equivalents of the reference's scheduling examples
(/root/reference/examples/gemm_splitk/example_tilelang_gemm_splitk.py,
gemm_streamk/example_tilelang_gemm_streamk.py, gemv/example_gemv.py,
blocksparse_gemm/example_blocksparse_gemm.py) re-designed for TPU:

* split-K: the reference accumulates partials with ``T.atomic_add`` into C.
  TPU has no global-memory atomics, so each split writes its partial tile and
  a tiny XLA epilogue sums over the split axis (same pattern the flash-decode
  split-KV kernel uses).
* stream-K: the reference balances (tile, k-chunk) work units over persistent
  CTAs with an atomic fixup. Here the host plans contiguous work segments
  (tile, k0, k_len) that exactly load-balance the flat iteration space, the
  kernel runs one grid step per segment with a *dynamic-extent* K loop and
  dynamic-offset DMA (tile ids live in scalar descriptors), and the fixup is
  an XLA ``segment_sum`` over segment partials.
* GEMV: one MXU gemm row per N-block; A rides a (1, bk) block so the whole
  reduction stays on the MXU rather than scalar lanes.
* block-sparse GEMM: a (M/bm, N/bn) mask predicates whole output tiles, like
  the block-sparse attention kernel predicates KV tiles.
"""

import functools
import math

import tilelang_mesh_tpu.language as T
from ..jit import compile as _tl_compile


# ---------------------------------------------------------------------------
# split-K
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def splitk_kernel(M, N, K, n_split, block_M, block_N, block_K, in_dtype,
                  num_stages=2):
    split_len = K // n_split

    @T.prim_func
    def gemm_splitk(A: T.Tensor((M, K), in_dtype),
                    B: T.Tensor((K, N), in_dtype),
                    Cp: T.Tensor((n_split, M, N), "float32")):
        with T.Kernel(n_split, T.ceildiv(N, block_N),
                      T.ceildiv(M, block_M)) as (bs, bx, by):
            A_s = T.alloc_shared((block_M, block_K), in_dtype)
            B_s = T.alloc_shared((block_K, block_N), in_dtype)
            C_l = T.alloc_fragment((block_M, block_N), "float32")
            T.clear(C_l)
            for ko in T.Pipelined(T.ceildiv(split_len, block_K),
                                  num_stages=num_stages):
                T.copy(A[by * block_M, bs * split_len + ko * block_K], A_s)
                T.copy(B[bs * split_len + ko * block_K, bx * block_N], B_s)
                T.gemm(A_s, B_s, C_l)
            T.copy(C_l, Cp[bs, by * block_M, bx * block_N])

    return _tl_compile(gemm_splitk)


def matmul_splitk(a, b, n_split=4, block_M=128, block_N=128, block_K=128,
                  out_dtype=None):
    """C = A @ B with the K reduction split over ``n_split`` parallel grid
    steps; partials are combined by XLA (reference uses atomic_add)."""
    import jax.numpy as jnp

    M, K = a.shape
    N = b.shape[1]
    # Mosaic lane rule: A/B's K-axis block must be a multiple of 128 (or
    # the whole axis), so splits are only taken at 128-aligned chunk
    # sizes; otherwise fall back to a single full-K chunk.
    while n_split > 1 and (K % n_split or (K // n_split) % 128):
        n_split -= 1
    split_len = K // n_split
    if split_len % 128 == 0:
        block_K = max(128, min(block_K, split_len) // 128 * 128)
        while split_len % block_K:
            block_K -= 128
    else:
        block_K = split_len  # full-axis block (always legal)
    kern = splitk_kernel(M, N, K, n_split, block_M, block_N, block_K,
                         str(a.dtype))
    cp = kern(a, b)
    return jnp.sum(cp, axis=0).astype(out_dtype or a.dtype)


# ---------------------------------------------------------------------------
# stream-K
# ---------------------------------------------------------------------------

def _streamk_segments(n_tiles, k_iters, n_programs):
    """Balance the flat (tile, k-chunk) iteration space over programs;
    split each program's contiguous range at tile boundaries. Native
    scheduler (src/tltpu_core.cc tl_streamk_partition) with the python
    mirror as fallback."""
    from ..layout import native as lnat
    from ..layout import python_impl as lpy
    segs = lnat.streamk_partition(n_tiles, k_iters, n_programs)
    if segs is None:
        segs = lpy.streamk_partition(n_tiles, k_iters, n_programs)
    return segs


@functools.lru_cache(maxsize=None)
def streamk_kernel(M, N, K, n_seg, block_M, block_N, block_K, in_dtype):
    @T.prim_func
    def gemm_streamk(A: T.Tensor((M, K), in_dtype),
                     B: T.Tensor((K, N), in_dtype),
                     TileM: T.Tensor((n_seg,), "int32"),
                     TileN: T.Tensor((n_seg,), "int32"),
                     KStart: T.Tensor((n_seg,), "int32"),
                     KLen: T.Tensor((n_seg,), "int32"),
                     Part: T.Tensor((n_seg, block_M, block_N), "float32")):
        with T.Kernel(n_seg) as sid:
            A_s = T.alloc_shared((block_M, block_K), in_dtype)
            B_s = T.alloc_shared((block_K, block_N), in_dtype)
            acc = T.alloc_fragment((block_M, block_N), "float32")
            tm = T.alloc_var("int32")
            tn = T.alloc_var("int32")
            k0 = T.alloc_var("int32")
            kl = T.alloc_var("int32")
            tm[0] = TileM[sid]
            tn[0] = TileN[sid]
            k0[0] = KStart[sid]
            kl[0] = KLen[sid]
            T.clear(acc)
            for i in T.serial(kl[0]):
                T.copy(A[tm[0] * block_M, (k0[0] + i) * block_K], A_s)
                T.copy(B[(k0[0] + i) * block_K, tn[0] * block_N], B_s)
                T.gemm(A_s, B_s, acc)
            T.copy(acc, Part[sid, 0, 0])

    return _tl_compile(gemm_streamk)


def matmul_streamk(a, b, n_programs=8, block_M=128, block_N=128, block_K=128,
                   out_dtype=None):
    """Stream-K GEMM: host-balanced (tile, k-range) segments, one grid step
    per segment, XLA segment-sum fixup across segments of the same tile."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    M, K = a.shape
    N = b.shape[1]
    assert M % block_M == 0 and N % block_N == 0 and K % block_K == 0
    nM, nN = M // block_M, N // block_N
    k_iters = K // block_K
    segs = _streamk_segments(nM * nN, k_iters, n_programs)
    n_seg = len(segs)
    tiles = np.array([s[0] for s in segs], np.int32)
    tile_m = jnp.asarray(tiles // nN, jnp.int32)
    tile_n = jnp.asarray(tiles % nN, jnp.int32)
    k_start = jnp.asarray([s[1] for s in segs], jnp.int32)
    k_len = jnp.asarray([s[2] for s in segs], jnp.int32)

    kern = streamk_kernel(M, N, K, n_seg, block_M, block_N, block_K,
                          str(a.dtype))
    part = kern(a, b, tile_m, tile_n, k_start, k_len)
    fixed = jax.ops.segment_sum(part, jnp.asarray(tiles), num_segments=nM * nN)
    c = fixed.reshape(nM, nN, block_M, block_N).transpose(0, 2, 1, 3)
    return c.reshape(M, N).astype(out_dtype or a.dtype)


# ---------------------------------------------------------------------------
# GEMV
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def gemv_kernel(N, K, block_N, block_K, in_dtype, out_dtype,
                num_stages=2):
    @T.prim_func
    def gemv(A: T.Tensor((1, K), in_dtype),
             B: T.Tensor((N, K), in_dtype),
             C: T.Tensor((1, N), out_dtype)):
        with T.Kernel(T.ceildiv(N, block_N)) as bx:
            A_s = T.alloc_shared((1, block_K), in_dtype)
            B_s = T.alloc_shared((block_N, block_K), in_dtype)
            acc = T.alloc_fragment((1, block_N), "float32")
            T.clear(acc)
            for ko in T.Pipelined(T.ceildiv(K, block_K),
                                  num_stages=num_stages):
                T.copy(A[0, ko * block_K], A_s)
                T.copy(B[bx * block_N, ko * block_K], B_s)
                T.gemm(A_s, B_s, acc, transpose_B=True)
            T.copy(acc, C[0, bx * block_N])

    return _tl_compile(gemv)


def gemv(a, b, out_dtype=None, block_N=128, block_K=512):
    """c = B @ a with a (K,), B (N, K) -> (N,)  (reference example_gemv.py
    computes A @ B.T with the same operand layout)."""
    K, = a.shape
    N = b.shape[0]
    block_K = min(block_K, K)
    kern = gemv_kernel(N, K, block_N, block_K, str(a.dtype),
                       out_dtype or str(a.dtype))
    return kern(a.reshape(1, K), b)[0]


# ---------------------------------------------------------------------------
# block-sparse GEMM
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def blocksparse_gemm_kernel(M, N, K, block_M, block_N, block_K, in_dtype,
                            out_dtype, num_stages=2):
    @T.prim_func
    def bs_gemm(A: T.Tensor((M, K), in_dtype),
                B: T.Tensor((K, N), in_dtype),
                BlockMask: T.Tensor((M // block_M, N // block_N), "int32"),
                C: T.Tensor((M, N), out_dtype)):
        with T.Kernel(T.ceildiv(N, block_N),
                      T.ceildiv(M, block_M)) as (bx, by):
            A_s = T.alloc_shared((block_M, block_K), in_dtype)
            B_s = T.alloc_shared((block_K, block_N), in_dtype)
            C_l = T.alloc_fragment((block_M, block_N), "float32")
            T.clear(C_l)
            for ko in T.Pipelined(T.ceildiv(K, block_K),
                                  num_stages=num_stages):
                with T.If(BlockMask[by, bx] != 0):
                    T.copy(A[by * block_M, ko * block_K], A_s)
                    T.copy(B[ko * block_K, bx * block_N], B_s)
                    T.gemm(A_s, B_s, C_l)
            T.copy(C_l, C[by * block_M, bx * block_N])

    return _tl_compile(bs_gemm)


def blocksparse_matmul(a, b, block_mask, block_M=128, block_N=128,
                       block_K=128, out_dtype=None):
    """C tiles where block_mask (M/bm, N/bn) is nonzero; zeros elsewhere."""
    M, K = a.shape
    N = b.shape[1]
    kern = blocksparse_gemm_kernel(M, N, K, block_M, block_N,
                                   min(block_K, K), str(a.dtype),
                                   out_dtype or str(a.dtype))
    return kern(a, b, block_mask)
