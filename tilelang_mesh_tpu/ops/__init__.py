"""Kernel library: ready-made jax-callable ops built on the tile DSL.

The analog of the reference's examples/ capability surface packaged as a
library (SURVEY §2.4): GEMM variants, FlashAttention, normalization, etc.
"""

from .gemm import matmul, matmul_kernel
from .flash_attention import (flash_attention, mha_fwd_kernel,
                              flash_attention_partial)
from .flash_attention_bwd import flash_attention_bwd
from .flash_attention_varlen import flash_attention_varlen
from .flash_decoding import flash_decode, flash_decode_paged
from .mla import mla_decode, mla_decode_reference
from .dequant_gemm import (dequant_matmul, dequant_gemm_kernel,
                           w4a8_matmul, quantize_w4_per_channel)
from .gqa import gqa_attention
from .linear_attention import linear_attention, retention
from .mamba2 import mamba2_chunk_scan, mamba2_reference
from .blocksparse_attention import blocksparse_attention
from .grouped_gemm import grouped_matmul, grouped_gemm_kernel
from .gemm_variants import (matmul_splitk, matmul_streamk, gemv,
                            blocksparse_matmul)
from .attention_sink import attention_sink, attention_sink_reference
from .nsa import nsa_attention_varlen, nsa_attention, nsa_decode, nsa_reference
from .seer_attention import seer_attention, seer_block_mask, seer_reference
from .minference import vertical_slash_sparse_attention, vs_sparse_reference
from .gdn import gdn_chunk_fwd, gdn_reference
from .dsa import lightning_indexer, topk_selector, sparse_mla_fwd
from .softmax import softmax, softmax_kernel
