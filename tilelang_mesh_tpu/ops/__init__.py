"""Kernel library: ready-made jax-callable ops built on the tile DSL.

The analog of the reference's examples/ capability surface packaged as a
library (SURVEY §2.4): GEMM variants, FlashAttention, normalization, etc.
"""

from .gemm import matmul, matmul_kernel
from .flash_attention import flash_attention, mha_fwd_kernel
from .flash_decoding import flash_decode, flash_decode_paged
from .mla import mla_decode, mla_decode_reference
from .dequant_gemm import dequant_matmul, dequant_gemm_kernel
