"""Chunked (causal) linear attention.

Behavioral equivalent of /root/reference/examples/linear_attention/ (chunked
recurrent form): within a chunk the causal product is quadratic on the MXU;
across chunks a (D_k, D_v) state carries the prefix sum. The chunk loop is a
serial in-kernel loop (true recurrence), so K/V/Q chunk fetches use explicit
DMA — the fallback path of the planner — while all three matmuls per chunk
hit the MXU.

    o_t = q_t · sum_{s<=t} k_s^T v_s   (optionally feature-mapped q, k)
"""

import functools

import tilelang_mesh_tpu.language as T
from ..jit import compile as _tl_compile


@functools.lru_cache(maxsize=None)
def linear_attention_kernel(B, H, S, DK, DV, chunk, dtype="float32",
                            accum_dtype="float32"):
    NC = S // chunk

    @T.prim_func
    def lin_attn(Q: T.Tensor((B, H, S, DK), dtype),
                 K: T.Tensor((B, H, S, DK), dtype),
                 V: T.Tensor((B, H, S, DV), dtype),
                 O: T.Tensor((B, H, S, DV), dtype)):
        with T.Kernel(H, B) as (by, bz):
            Q_s = T.alloc_shared((chunk, DK), dtype)
            K_s = T.alloc_shared((chunk, DK), dtype)
            V_s = T.alloc_shared((chunk, DV), dtype)
            state = T.alloc_fragment((DK, DV), accum_dtype)
            attn = T.alloc_fragment((chunk, chunk), accum_dtype)
            attn_c = T.alloc_fragment((chunk, chunk), dtype)
            out = T.alloc_fragment((chunk, DV), accum_dtype)
            out_c = T.alloc_fragment((chunk, DV), dtype)
            T.fill(state, 0)
            for c in T.serial(NC):
                T.copy(Q[bz, by, c * chunk, 0], Q_s)
                T.copy(K[bz, by, c * chunk, 0], K_s)
                T.copy(V[bz, by, c * chunk, 0], V_s)
                # inter-chunk: q @ carried state
                T.gemm(Q_s, state, out, clear_accum=True)
                # intra-chunk: causal-masked quadratic part
                T.gemm(Q_s, K_s, attn, transpose_B=True, clear_accum=True)
                for i, j in T.Parallel(chunk, chunk):
                    attn[i, j] = T.if_then_else(i >= j, attn[i, j], 0.0)
                T.copy(attn, attn_c)
                T.gemm(attn_c, V_s, out)
                # state += k^T v
                T.gemm(K_s, V_s, state, transpose_A=True)
                T.copy(out, out_c)
                T.copy(out_c, O[bz, by, c * chunk, 0])

    return _tl_compile(lin_attn)


def linear_attention(q, k, v, chunk=128, backward=None):
    """Causal linear attention o_t = q_t @ sum_{s<=t} k_s^T v_s.

    backward="kernel" (reference examples/linear_attention/
    example_linear_attn_bwd.py behavior): the three gradients are the
    SAME forward kernel with rearranged / time-flipped operands —
        dQ_t = dO_t Σ_{s<=t} v_s k_s^T   = LA(dO, v, k)
        dK_s = v_s  Σ_{t>=s} dO_t q_t^T  = flip(LA(flip v, flip dO, flip q))
        dV_s = k_s  Σ_{t>=s} q_t dO_t^T  = flip(LA(flip k, flip q, flip dO))
    (suffix sums = prefix sums on the reversed sequence; the causal
    diagonal is inclusive both ways)."""
    B, H, S, DK = q.shape
    DV = v.shape[-1]
    chunk = min(chunk, S)
    while S % chunk:
        chunk //= 2
    kern = linear_attention_kernel(B, H, S, DK, DV, chunk, str(q.dtype))
    if backward is None:
        return kern(q, k, v)
    if backward != "kernel":
        raise ValueError(f"backward must be None or 'kernel', "
                         f"got {backward!r}")
    import jax
    import jax.numpy as jnp

    kern_t = linear_attention_kernel(B, H, S, DV, DK, chunk,
                                     str(q.dtype))  # output dim DK

    @jax.custom_vjp
    def fa(q, k, v):
        return kern(q, k, v)

    def fwd(q, k, v):
        return fa(q, k, v), (q, k, v)

    def bwd(res, g):
        q, k, v = res
        g = g.astype(q.dtype)

        def flip(x):
            return jnp.flip(x, axis=2)

        dq = kern_t(g, v, k)
        dk = flip(kern_t(flip(v), flip(g), flip(q)))
        dv = flip(kern(flip(k), flip(q), flip(g)))
        return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)

    fa.defvjp(fwd, bwd)
    return fa(q, k, v)


def linear_attention_reference(q, k, v):
    import jax.numpy as jnp
    s = jnp.einsum("bhsd,bhtd->bhst", q.astype(jnp.float32),
                   k.astype(jnp.float32))
    S = q.shape[2]
    mask = jnp.tril(jnp.ones((S, S), bool))
    s = jnp.where(mask, s, 0.0)
    return jnp.einsum("bhst,bhtv->bhsv", s,
                      v.astype(jnp.float32)).astype(q.dtype)


@functools.lru_cache(maxsize=None)
def retention_kernel(B, H, S, DK, DV, chunk, dtype="float32"):
    """Retention (RetNet) forward: linear attention with per-head
    exponential decay gamma (reference examples/linear_attention/
    example_retention_fwd.py). Chunked form: intra-chunk decay matrix
    gamma^(i-j), inter-chunk state decayed by gamma^chunk."""
    NC = S // chunk

    @T.prim_func
    def retention(Q: T.Tensor((B, H, S, DK), dtype),
                  K: T.Tensor((B, H, S, DK), dtype),
                  V: T.Tensor((B, H, S, DV), dtype),
                  Gamma: T.Tensor((H,), "float32"),
                  O: T.Tensor((B, H, S, DV), dtype)):
        with T.Kernel(H, B) as (by, bz):
            Q_s = T.alloc_shared((chunk, DK), dtype)
            K_s = T.alloc_shared((chunk, DK), dtype)
            Kd_s = T.alloc_shared((chunk, DK), dtype)
            V_s = T.alloc_shared((chunk, DV), dtype)
            g_s = T.alloc_shared((1,), "float32")
            state = T.alloc_fragment((DK, DV), "float32")
            attn = T.alloc_fragment((chunk, chunk), "float32")
            attn_c = T.alloc_fragment((chunk, chunk), dtype)
            out = T.alloc_fragment((chunk, DV), "float32")
            out_c = T.alloc_fragment((chunk, DV), dtype)
            T.copy(Gamma[by], g_s)
            T.fill(state, 0)
            for c in T.serial(NC):
                T.copy(Q[bz, by, c * chunk, 0], Q_s)
                T.copy(K[bz, by, c * chunk, 0], K_s)
                T.copy(V[bz, by, c * chunk, 0], V_s)
                # inter-chunk: gamma^(i+1) * q_i @ state
                T.gemm(Q_s, state, out, clear_accum=True)
                for i, j in T.Parallel(chunk, DV):
                    out[i, j] = out[i, j] * T.exp2(
                        T.log2(g_s[0]) * (i + 1))
                # intra-chunk: gamma^(i-j) causal mask
                T.gemm(Q_s, K_s, attn, transpose_B=True, clear_accum=True)
                for i, j in T.Parallel(chunk, chunk):
                    attn[i, j] = T.if_then_else(
                        i >= j,
                        attn[i, j] * T.exp2(T.log2(g_s[0]) * (i - j)), 0.0)
                T.copy(attn, attn_c)
                T.gemm(attn_c, V_s, out)
                # state = gamma^chunk * state + (gamma^(chunk-1-j) k_j)^T v_j
                for i, j in T.Parallel(chunk, DK):
                    Kd_s[i, j] = K_s[i, j] * T.exp2(
                        T.log2(g_s[0]) * (chunk - 1 - i))
                for i, j in T.Parallel(DK, DV):
                    state[i, j] = state[i, j] * T.exp2(
                        T.log2(g_s[0]) * chunk)
                T.gemm(Kd_s, V_s, state, transpose_A=True)
                T.copy(out, out_c)
                T.copy(out_c, O[bz, by, c * chunk, 0])

    return _tl_compile(retention)


def retention(q, k, v, gamma, chunk=64):
    """RetNet retention: o_t = sum_{s<=t} gamma^(t-s) (q_t.k_s) v_s."""
    import numpy as np
    B, H, S, DK = q.shape
    DV = v.shape[-1]
    chunk = min(chunk, S)
    while S % chunk:
        chunk //= 2
    kern = retention_kernel(B, H, S, DK, DV, chunk, str(q.dtype))
    return kern(q, k, v, np.asarray(gamma, np.float32))


def retention_reference(q, k, v, gamma):
    import jax.numpy as jnp
    S = q.shape[2]
    s = jnp.einsum("bhsd,bhtd->bhst", q.astype(jnp.float32),
                   k.astype(jnp.float32))
    t_i = jnp.arange(S)[:, None]
    t_j = jnp.arange(S)[None, :]
    decay = jnp.where(t_i >= t_j,
                      jnp.asarray(gamma, jnp.float32)[:, None, None]
                      ** (t_i - t_j), 0.0)
    return jnp.einsum("bhst,bhtv->bhsv", s * decay[None],
                      v.astype(jnp.float32)).astype(q.dtype)
