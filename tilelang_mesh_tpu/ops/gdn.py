"""Gated DeltaNet (GDN) chunked forward.

Behavioral equivalent of the reference's examples/gdn family
(example_chunk_delta_h.py, example_wy_fast.py, example_chunk_o.py,
example_chunk_scaled_dot_kkt.py, example_cumsum.py): the gated delta rule

    h_t = a_t * h_{t-1} + k_t ⊗ beta_t (v_t - (a_t h_{t-1})^T k_t),
    o_t = scale * q_t^T h_t,            a_t = exp(g_t),

evaluated chunk-parallel via the WY representation: per chunk, the strictly
lower triangular system T = (I + A)^{-1} with
A[i,j] = beta_i (k_i·k_j) exp(gc_i - gc_j) turns the sequential rank-1
updates into three MXU GEMMs + one triangular solve, and a lax.scan carries
the (K, V) state across chunks — the TPU-idiomatic replacement for the
reference's per-piece CUDA kernels (intra-chunk math is batched onto the
MXU; the only sequential dimension is the chunk axis).
"""

import math
from typing import Optional

import jax
import jax.numpy as jnp


def gdn_chunk_fwd(q, k, v, g, beta, chunk_size: int = 64,
                  scale: Optional[float] = None,
                  initial_state=None, output_final_state: bool = False):
    """q/k (B, H, T, K); v (B, H, T, V); g (B, H, T) log-decay;
    beta (B, H, T) write strengths. T % chunk_size == 0."""
    B, H, T, K = q.shape
    V = v.shape[-1]
    C = chunk_size
    if T % C:
        raise ValueError(f"T={T} must be divisible by chunk_size={C}")
    if scale is None:
        scale = 1.0 / math.sqrt(K)
    N = T // C

    qf = q.astype(jnp.float32).reshape(B, H, N, C, K)
    kf = k.astype(jnp.float32).reshape(B, H, N, C, K)
    vf = v.astype(jnp.float32).reshape(B, H, N, C, V)
    gf = g.astype(jnp.float32).reshape(B, H, N, C)
    bf = beta.astype(jnp.float32).reshape(B, H, N, C)

    gc = jnp.cumsum(gf, axis=-1)                     # within-chunk cumdecay
    # A[i,j] = beta_i (k_i.k_j) exp(gc_i - gc_j), strictly lower
    kk = jnp.einsum("bhnik,bhnjk->bhnij", kf, kf)
    decay = jnp.exp(gc[..., :, None] - gc[..., None, :])
    tril_s = jnp.tril(jnp.ones((C, C), bool), -1)
    A = jnp.where(tril_s, bf[..., :, None] * kk * decay, 0.0)

    # T_mat = (I + A)^{-1}: unit lower-triangular solve against I
    # (unit_diagonal ignores A's zero diagonal, so no eye-add needed)
    eye = jnp.eye(C, dtype=jnp.float32)
    T_mat = jax.scipy.linalg.solve_triangular(
        A, jnp.broadcast_to(eye, A.shape), lower=True, unit_diagonal=True)

    # WY factors: w_i (state-eating keys), u_i (injected values)
    w = jnp.einsum("bhnij,bhnjk->bhnik",
                   T_mat, bf[..., None] * jnp.exp(gc)[..., None] * kf)
    u = jnp.einsum("bhnij,bhnjv->bhniv", T_mat, bf[..., None] * vf)

    # intra-chunk attention weights (q_i.k_j) exp(gc_i - gc_j), j <= i
    qk = jnp.einsum("bhnik,bhnjk->bhnij", qf, kf)
    attn = jnp.where(jnp.tril(jnp.ones((C, C), bool)), qk * decay, 0.0)

    g_tot = gc[..., -1]                              # full-chunk decay
    k_out = jnp.exp(g_tot[..., None] - gc)[..., None] * kf

    h0 = jnp.zeros((B, H, K, V), jnp.float32) if initial_state is None \
        else initial_state.astype(jnp.float32)

    def step(h, inp):
        qc, wc, uc, att, koc, gcc, gt = inp
        v_new = uc - jnp.einsum("bhik,bhkv->bhiv", wc, h)
        o_c = (jnp.einsum("bhik,bhkv->bhiv",
                          jnp.exp(gcc)[..., None] * qc, h) +
               jnp.einsum("bhij,bhjv->bhiv", att, v_new)) * scale
        h_next = (jnp.exp(gt)[..., None, None] * h +
                  jnp.einsum("bhik,bhiv->bhkv", koc, v_new))
        return h_next, o_c

    xs = tuple(jnp.moveaxis(x, 2, 0)
               for x in (qf, w, u, attn, k_out, gc, g_tot))
    h_final, o = jax.lax.scan(step, h0, xs)
    o = jnp.moveaxis(o, 0, 2).reshape(B, H, T, V).astype(q.dtype)
    if output_final_state:
        return o, h_final
    return o


def gdn_reference(q, k, v, g, beta, scale: Optional[float] = None,
                  initial_state=None, output_final_state: bool = False):
    """Sequential gated delta rule (ground truth, cf. fla's
    fused_recurrent_gated_delta_rule semantics)."""
    import numpy as np

    B, H, T, K = q.shape
    V = v.shape[-1]
    if scale is None:
        scale = 1.0 / math.sqrt(K)
    qf = np.asarray(q, np.float32)
    kf = np.asarray(k, np.float32)
    vf = np.asarray(v, np.float32)
    gf = np.asarray(g, np.float32)
    bf = np.asarray(beta, np.float32)
    h = np.zeros((B, H, K, V), np.float32) if initial_state is None \
        else np.asarray(initial_state, np.float32).copy()
    o = np.zeros((B, H, T, V), np.float32)
    for t in range(T):
        h = h * np.exp(gf[:, :, t])[..., None, None]
        kv = np.einsum("bhkv,bhk->bhv", h, kf[:, :, t])
        v_new = bf[:, :, t][..., None] * (vf[:, :, t] - kv)
        h = h + np.einsum("bhk,bhv->bhkv", kf[:, :, t], v_new)
        o[:, :, t] = scale * np.einsum("bhkv,bhk->bhv", h, qf[:, :, t])
    out = jnp.asarray(o, q.dtype)
    if output_final_state:
        return out, jnp.asarray(h)
    return out
